# Test/verify entry points (the reference's build-scripts plane,
# paddle/scripts/travis/, as make targets).
#
#   make test    — fast tier: every test not marked `slow`; < 5 min on the
#                  virtual 8-device CPU mesh.  This is the default CI gate.
#   make verify  — the full suite, then a bench smoke (one metric) and the
#                  8-device multichip dry-run compile.
#   make bench   — the full benchmark set (one JSON line per metric).

PY ?= python
CPU_ENV = XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu

.PHONY: test verify bench test-all

test:
	$(CPU_ENV) $(PY) -m pytest tests/ -q -m "not slow" --durations=20

test-all:
	$(CPU_ENV) $(PY) -m pytest tests/ -q

verify: test-all
	$(CPU_ENV) $(PY) -c "import bench; print(bench.bench_allreduce_virtual8())"
	$(CPU_ENV) $(PY) -c "import bench; print(bench.bench_scaling_virtual8())"
	$(CPU_ENV) $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

bench:
	$(PY) bench.py
