# Test/verify entry points (the reference's build-scripts plane,
# paddle/scripts/travis/, as make targets).
#
#   make lint    — static analysis: AST self-lint over paddle_tpu + bench.py
#                  (analysis/ast_rules), graph-lint over every shipped
#                  demo config (tests/configs/), the T106 buffer-
#                  donation audit over the step builders (incl. the
#                  whole-pass epoch program), the C-rules lock-
#                  discipline lint over the threaded planes
#                  (analysis/concurrency_lint), and the N-rules
#                  precision-flow lint (analysis/numerics_lint) in
#                  four legs: package probes at f32, the demo-config
#                  corpus at f32, the flagship corpus at bf16, and the
#                  package probes at bf16 — the last leg is the pragma-
#                  hygiene pass (every `# num:` pragma must be justified
#                  AND still suppressing something, package-wide).
#                  Fixes + justified pragmas keep all four at zero.
#                  Zero findings = pass.
#   make test    — fast tier: lint, then every test not marked `slow`;
#                  < 6 min on the virtual 8-device CPU mesh.  The CI gate.
#   make verify  — the full suite, then the decode-speed gate (beam-5
#                  nmt_generate + spec-decode/prefix-cache A/B under the
#                  bench regression guard — any >5%-worse-than-history
#                  metric fails the target), a bench smoke (one metric),
#                  the AOT-cache warm-boot record (cold/warm compile
#                  counts + wall time, dispatches-per-epoch) and the
#                  8-device multichip dry-run compile.
#   make bench   — the full benchmark set (one JSON line per metric).
#   make tier1-check / tier1-update — diff (or re-snapshot) the tier-1
#                  failing-test SET against tests/tier1_failures_baseline.txt
#                  (scripts/tier1_failset.py), so CI catches a newly broken
#                  test even when another fix keeps the count unchanged.
#                  tier1-check also verifies the multi-process e2e files
#                  stay slow-marked (--slow-guard) — they must never creep
#                  into the fast tier.
#   make chaos   — the fault-injection drills: the single-process subset
#                  (NaN-inject, torn checkpoint, subprocess kill -9 +
#                  --resume), the elastic kill-one-of-N scenarios
#                  (tests/test_elastic_e2e.py: 4 worker processes, one
#                  SIGKILLed mid-pass holding a shard lease — leases
#                  requeue, params stay bit-for-bit), the master-
#                  failover drill (tests/test_master_failover_e2e.py:
#                  kill -9 the LEADER mid-pass under a 4-worker fleet —
#                  the standby takes over warm from the journal, zero
#                  recomputed tasks, bit-for-bit params), the serving
#                  drills (tests/test_serving_e2e.py: open-loop load +
#                  poisoned-request rejection + slow-client isolation,
#                  lock-sanitizer armed), the production-gate fleet
#                  scenarios (tests/test_scenarios_e2e.py: kill a worker
#                  AND bounce the master under LIVE train+serve traffic;
#                  SIGTERM graceful drain of `paddle-tpu serve`), and the
#                  hostile-network drills (tests/test_netem_e2e.py: a
#                  worker partitioned mid-pass rejoins bit-for-bit, and
#                  the leader<->standby asymmetric-partition split-brain
#                  ends with exactly one fenced leader, zero tasks lost,
#                  a clean surviving journal), and the decode-speed
#                  drills (tests/test_decode_speed_e2e.py: shared-prefix
#                  open-loop load over the COW cache, speculative decode
#                  under load, cancel-mid-speculation page drain), plus
#                  the chaos-composition fuzzer batch (paddle-tpu fuzz:
#                  25 seeded compositions over the fault vocabulary must
#                  run invariant-clean, and a planted-bug canary must be
#                  detected, ddmin-shrunk to a spec, and replayed).
#   make scenarios — the fast production-gate scenario subset
#                  (robustness/scenarios.py via `paddle-tpu scenario
#                  --all-fast`), sanitizer-armed: overload shed-not-
#                  collapse, burst arrivals, chaos-under-load recovery,
#                  mixed train+serve.  Runs as the last step of `make
#                  test`, so the fast tier reports the SLO gates too.
#   make serve-bench — the serving-plane headline (bench_serving).
#   make trace-demo — the obs-plane acceptance drill: run the fast
#                  mixed_train_serve scenario with span tracing armed
#                  (`paddle-tpu scenario mixed_train_serve --trace`) and
#                  assert ONE merged, schema-valid Chrome-trace timeline
#                  lands, correlating spans from >= 2 processes and >= 3
#                  planes (serving request lifecycle, trainer step,
#                  master RPC) — tests/test_obs_e2e.py.

PY ?= python
CPU_ENV = XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu

.PHONY: test verify bench test-all lint tier1-check tier1-update chaos serve-bench scenarios trace-demo

lint:
	$(CPU_ENV) $(PY) -m paddle_tpu lint --extra bench.py
	$(CPU_ENV) $(PY) -m paddle_tpu lint \
		$(foreach c,$(wildcard tests/configs/*.py),--config $(c))
	$(CPU_ENV) $(PY) -m paddle_tpu lint --donation
	$(CPU_ENV) $(PY) -m paddle_tpu lint --concurrency
	$(CPU_ENV) $(PY) -m paddle_tpu lint --protocol
	$(CPU_ENV) $(PY) -m paddle_tpu lint --numerics
	$(CPU_ENV) $(PY) -m paddle_tpu lint --numerics \
		$(foreach c,$(wildcard tests/configs/*.py),--config $(c))
	$(CPU_ENV) $(PY) -m paddle_tpu lint --numerics --compute-dtype bfloat16 \
		$(foreach c,$(wildcard tests/configs/*.py),--config $(c))
	$(CPU_ENV) $(PY) -m paddle_tpu lint --numerics --compute-dtype bfloat16

test: lint
	$(CPU_ENV) $(PY) -m pytest tests/ -q -m "not slow" --durations=20
	$(MAKE) scenarios

# the fast production-gate scenario subset, SANITIZER-ARMED (each measured
# window doubles as a runtime lock-order drill on the scheduler's new
# shed/cancel/drain paths); one JSON metrics line per scenario
scenarios:
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m paddle_tpu scenario --all-fast

tier1-check:
	$(CPU_ENV) $(PY) scripts/tier1_failset.py --slow-guard
	$(CPU_ENV) $(PY) scripts/tier1_failset.py --check

tier1-update:
	$(CPU_ENV) $(PY) scripts/tier1_failset.py --update

# chaos drills run SANITIZER-ARMED: every lock constructed through the
# analysis/lock_sanitizer factories is instrumented, so each failover /
# kill-one-of-N fleet drill doubles as a runtime lock-order race detector
# (a cycle raises DeadlockReport and fails the drill)
# the single-process drills also arm the NUMERICS sanitizer: the
# nan_batch drill's flight-recorder postmortem must name the first
# non-finite-producing eqn (analysis/num_sanitizer.py), not just skip
chaos:
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 PADDLE_TPU_NUM_SANITIZER=1 $(PY) -m pytest tests/test_chaos_e2e.py tests/test_robustness.py tests/test_num_sanitizer.py -q
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_elastic_e2e.py -q
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_master_failover_e2e.py -q
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_serving_e2e.py -q
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_scenarios_e2e.py -q
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_netem_e2e.py -q
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_decode_speed_e2e.py -q
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_fleet_serving_e2e.py -q
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_explore_e2e.py -q
	# interleaving explorer batch: seeded (replayable) schedules over the
	# real router/master/HA planes must come back clean...
	$(CPU_ENV) $(PY) -m paddle_tpu explore --model router --schedules 200 --seed 0 --dfs-depth 3
	$(CPU_ENV) $(PY) -m paddle_tpu explore --model ha --schedules 200 --seed 0 --dfs-depth 4
	$(CPU_ENV) $(PY) -m paddle_tpu explore --model master --schedules 60 --seed 0
	# ...and the planted-bug canary proves the harness can still see:
	# detect (exit 1) -> shrunk spec on disk -> replay reproduces (exit 0)
	$(CPU_ENV) $(PY) -m paddle_tpu explore --model router --schedules 200 \
		--seed 7 --max-events 12 --plant double_serve \
		--out /tmp/paddle_tpu_canary.spec.json; test $$? -eq 1
	$(CPU_ENV) $(PY) -m paddle_tpu explore --replay /tmp/paddle_tpu_canary.spec.json
	# chaos-composition fuzzer (robustness/fuzz.py): the record/replay +
	# fuzz CLI drills, then a seeded 25-composition batch over the real
	# engine/scheduler must come back clean...
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_fuzz_e2e.py -q
	$(CPU_ENV) $(PY) -m paddle_tpu fuzz --count 25 --seed 0
	# ...and the planted-bug canary proves the fuzzer can still see:
	# detect (exit 1) -> ddmin-shrunk spec on disk -> replay reproduces
	$(CPU_ENV) $(PY) -m paddle_tpu fuzz --count 25 --seed 7 \
		--plant ledger_skew \
		--out /tmp/paddle_tpu_fuzz_canary.spec.json; test $$? -eq 1
	$(CPU_ENV) $(PY) -m paddle_tpu fuzz --replay /tmp/paddle_tpu_fuzz_canary.spec.json
	$(MAKE) trace-demo

# the obs-plane acceptance drill (sanitizer-armed: the traced scenario
# doubles as a lock-order drill on the instrumented scheduler/master paths)
trace-demo:
	$(CPU_ENV) PADDLE_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_obs_e2e.py -q

# the serving-plane headline under the bench regression guard: continuous
# batching + block-paged decode cache vs the one-shot path, open-loop load
# (sustained req/s, p50/p99 per-token latency; bench.bench_serving)
serve-bench:
	$(CPU_ENV) $(PY) -c "import bench, json; \
		[print(json.dumps(r)) for r in bench.bench_serving()]"

test-all:
	$(CPU_ENV) $(PY) -m pytest tests/ -q

verify: test-all
	$(CPU_ENV) $(PY) -c "import bench; bench.run_gated('nmt_generate', 'decode_speed')"
	$(CPU_ENV) $(PY) -c "import bench; print(bench.bench_allreduce_virtual8())"
	$(CPU_ENV) $(PY) -c "import bench; print(bench.bench_scaling_virtual8())"
	$(CPU_ENV) $(PY) -c "import bench; [print(r) for r in bench.bench_quantized()]"
	$(CPU_ENV) $(PY) -c "import bench; [print(r) for r in bench.bench_aot_warm_boot()]"
	$(CPU_ENV) $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

bench:
	$(PY) bench.py
