"""Benchmark driver — emits the BASELINE.json metric set, one JSON line per
metric (the first line is the headline ResNet-50 number the driver parses):

   1. resnet50_train_images_per_sec_per_chip — bf16 mixed-precision training
   2. nmt_tokens_per_sec                     — seq2seq-NMT attention GRU fwd+bwd
                                               through the FUSED decoder core,
                                               batch-size x bucketing sweep
                                               (headline = bs 128, bucketing ON,
                                               valid target tokens/s)
   2b. nmt_generate_tokens_per_sec           — jitted beam-5 decode (fused
                                               attention-GRU step), tokens/s +
                                               ms/sentence
   3. allreduce_bw_gbps                      — psum bandwidth over the mesh
   4. allreduce_psum_8dev_gbps               — value-verified 8-dev virtual-mesh psum
   5. transformer_base_tokens_per_sec        — Transformer-base MT train step
   6. transformer_long_ctx_tokens_per_sec    — seq 1024, Pallas flash attention
   7. transformer_xl_ctx_tokens_per_sec      — seq 4096 (dense attention cannot)
   8. lstm_textcls_ms_per_batch              — 2xLSTM text cls (benchmark/paddle/rnn)
                                               + bucketing on/off A/B sub-metric
   9. alexnet_ms_per_batch                   — reference alexnet.py config, unmodified
  10. googlenet_ms_per_batch                 — reference googlenet.py config, unmodified
  11. smallnet_ms_per_batch                  — reference smallnet_mnist_cifar.py config
  12. resnet50_pipeline_images_per_sec       — ResNet-50 through the real data
                                               plane, FIRST epoch (H2D-bound:
                                               inline vs async vs data-echo feed,
                                               scored against the measured serial
                                               ceiling)
  12b. resnet50_pipeline_feed_path_images_per_sec — first epoch, unique
                                               images, no echo: the feed-path
                                               regression tripwire
  12c. resnet50_pipeline_cached_epoch_images_per_sec — epochs >= 2 through the
                                               device-resident pass cache
                                               (reader/pass_cache.py): zero H2D,
                                               scored against the compute-path
                                               number
  13. scaling_virtual8_correctness_only      — n=1 vs n=8 virtual-CPU dp step
                                               time (correctness-grade)

Training metrics carry step_ms + achieved TFLOP/s + MFU (fraction of the
chip's bf16 peak) from XLA's own cost analysis.  Every metric also carries
best_prior/regressed_vs_best guard fields diffed against the committed
BENCH_r*.json round history (>5% worse than the best prior round flags),
and a REGRESSION_GUARD summary line closes the run.

Methodology: every step consumes a different pre-staged device batch (cycled)
and a fresh PRNG key, and timing syncs via a host fetch of the cost scalar —
jax.block_until_ready returns early on the experimental axon backend, and a
device->host read is a true execution barrier everywhere.

Targets (vs_baseline denominators): ResNet-50 1400 img/s = 0.8x per-chip A100
(A100 ~1750 img/s mixed precision, widely reported).  NMT 40k tokens/s = 0.8x
an A100 estimate (~50k tok/s for GNMT-class attention RNN; MLPerf GNMT V100
~20k scaled by the A100/V100 ratio).  Allreduce 100 GB/s (single-chip it
degenerates to an on-device pass-through — see the devices field).
"""

from __future__ import annotations

import json
import math
import os
import time

import numpy as np

# 8 virtual CPU devices alongside the real chip so the multi-device psum
# path is exercised every bench run (allreduce_psum_8dev metric); must be
# set before jax initializes its backends.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

TARGET_IMG_S = 1400.0  # 0.8x per-chip A100 ResNet-50 throughput (north star)
TARGET_NMT_TOK_S = 40000.0  # 0.8x per-chip A100 attention-RNN NMT estimate
TARGET_ALLREDUCE_GBPS = 100.0
# 0.8x per-chip A100 Transformer-base estimate (~55k tok/s training with
# seq 64-128 class batches in mixed precision)
TARGET_TRANSFORMER_TOK_S = 44000.0


def _sync(metrics) -> float:
    return float(metrics["cost"])


# bf16 peak TFLOP/s per chip by device kind (public specs) — for the MFU
# fields (reference prints hierarchical timer tables per log period,
# paddle/utils/Stat.h:230; here each metric carries achieved TFLOP/s and
# %-of-peak so "14% MFU" is said out loud in the bench output itself)
_PEAK_TFLOPS = (
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0), ("v6", 918.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 46.0),
)


def _peak_tflops() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAK_TFLOPS:
        if key in kind:
            return peak
    return 197.0  # assume v5e-class when unknown


def _aot(jitted, *args):
    """AOT-compile the step once and return (runner, flops-per-execution
    from XLA's own cost analysis).  The runner IS the compiled executable —
    benches must call it for their timed loop, otherwise the traced jit
    path compiles the identical program a second time (measured: the
    dispatch cache is not populated by lower().compile()).  Must run BEFORE
    the first call: the step donates its buffers."""
    try:
        compiled = jitted.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = float(ca.get("flops", 0.0))
        return compiled, (f if f > 0 else None)
    except Exception:
        return jitted, None


def _mfu_fields(flops, sec_per_iter: float) -> dict:
    """{"tflops": achieved, "mfu": fraction-of-peak} — empty when XLA gave
    no cost analysis."""
    if not flops or sec_per_iter <= 0:
        return {}
    tflops = flops / sec_per_iter / 1e12
    return {
        "tflops": round(tflops, 2),
        "mfu": round(tflops / _peak_tflops(), 4),
    }


def _measure_steps(
    cnet, opt, params, state, opt_state, batches,
    k: int = 8, iters_multi: int = 5, iters_single: int = 10,
):
    """Time the jitted train step two ways and return
    (ms_multi, ms_single, flops_per_step).

    ms_multi — K steps per dispatch (make_multi_train_step lax.scan): the
    HEADLINE.  Every dispatch crosses the host boundary once, and on this
    bench environment's tunneled device that costs ~6 ms flat — for fast
    steps the single-dispatch loop measures the transport, not the chip
    (r4 VERDICT weak #4/#5).  A production loop gets the same amortization
    from async dispatch keeping the device queue full.

    ms_single — one step per dispatch, reported alongside so the dispatch
    overhead stays visible instead of silently folded away."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.trainer.step import (
        make_multi_train_step,
        make_train_step,
    )

    key = jax.random.PRNGKey(1)
    single = make_train_step(cnet, opt, mesh=None)
    single, flops = _aot(single, params, state, opt_state, batches[0], key)
    params, state, opt_state, m = single(
        params, state, opt_state, batches[0], key
    )
    _sync(m)
    t0 = time.perf_counter()
    for i in range(iters_single):
        params, state, opt_state, m = single(
            params, state, opt_state, batches[i % len(batches)],
            jax.random.PRNGKey(i),
        )
    _sync(m)
    ms_single = (time.perf_counter() - t0) / iters_single * 1e3

    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[batches[i % len(batches)] for i in range(k)],
    )
    multi = make_multi_train_step(cnet, opt, k, mesh=None)
    multi, _ = _aot(multi, params, state, opt_state, stacked, key)
    params, state, opt_state, m = multi(
        params, state, opt_state, stacked, key
    )
    _sync(m)
    t0 = time.perf_counter()
    for i in range(iters_multi):
        params, state, opt_state, m = multi(
            params, state, opt_state, stacked, jax.random.PRNGKey(i)
        )
    _sync(m)
    ms_multi = (time.perf_counter() - t0) / (iters_multi * k) * 1e3
    return ms_multi, ms_single, flops


def _time_multi(cnet, opt, batches, k: int = 8, iters: int = 3,
                init_seed: int = 0):
    """AOT-compile + time ONE batch shape multi-dispatch (k steps/dispatch);
    returns (ms_per_step, flops_per_step).  Fresh params per call: the step
    donates its buffers, so shape groups can't share a params pytree."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.trainer.step import make_multi_train_step

    params, state = cnet.init(jax.random.PRNGKey(init_seed))
    opt_state = opt.init(params)
    key = jax.random.PRNGKey(1)
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[batches[i % len(batches)] for i in range(k)],
    )
    multi = make_multi_train_step(cnet, opt, k, mesh=None)
    multi, flops_k = _aot(multi, params, state, opt_state, stacked, key)
    params, state, opt_state, m = multi(params, state, opt_state, stacked, key)
    _sync(m)
    t0 = time.perf_counter()
    for i in range(iters):
        params, state, opt_state, m = multi(
            params, state, opt_state, stacked, jax.random.PRNGKey(i)
        )
    _sync(m)
    ms = (time.perf_counter() - t0) / (iters * k) * 1e3
    return ms, (flops_k / k if flops_k else None)


def _bucket_ab_arm(cnet, opt, host_batches, tok_counts, k: int = 8,
                   iters: int = 3):
    """Time one arm of a bucketing on/off A/B over an epoch of host batches.

    Batches are grouped by device shape (batch_shape_key — one group = one
    jit executable = one ladder bucket); each group is AOT-compiled and
    timed multi-dispatch on up to 4 staged batches.  The arm's tokens/sec
    is the epoch-weighted aggregate: sum(valid tokens) over sum(batches x
    that shape's ms/step) — i.e. what a full epoch at these shape
    frequencies sustains, not a best-bucket cherry-pick.  Returns
    (tokens_per_sec, flops_per_sec or None, per-shape table)."""
    import jax

    from paddle_tpu.core.batch import batch_shape_key

    groups: dict = {}
    for hb, tk in zip(host_batches, tok_counts):
        groups.setdefault(batch_shape_key(hb), []).append((hb, tk))
    total_s = 0.0
    total_tok = 0
    total_flops = 0.0
    flops_ok = True
    table = []
    for key_, items in sorted(groups.items(), key=lambda kv: -len(kv[1])):
        dev = [
            jax.tree_util.tree_map(jax.device_put, hb) for hb, _ in items[:4]
        ]
        ms, flops = _time_multi(cnet, opt, dev, k=k, iters=iters)
        n = len(items)
        total_s += n * ms / 1e3
        total_tok += sum(t for _, t in items)
        if flops:
            total_flops += flops * n
        else:
            flops_ok = False
        # label the group by its first sequence slot's (B, T)
        bt = next(
            (s for _, s, _ in key_ if len(s) >= 2), key_[0][1]
        )
        table.append({"shape": "x".join(map(str, bt)), "batches": n,
                      "step_ms": round(ms, 2)})
    tok_s = total_tok / total_s if total_s else 0.0
    return tok_s, (total_flops / total_s if flops_ok and total_s else None), table


def _bucketing_ab(cnet, opt, samples, dtypes, batch_size: int, budget: int,
                  tok_fn, cache_name: str, k: int = 8, iters: int = 3):
    """Both arms of a bucketing on/off A/B over ONE sample corpus.

    off — paddle.batch order through a plain DataFeeder (pad to per-batch
    max; with a full-size batch that concentrates at the corpus max).
    on — reader.bucketing token-budget packing + DataFeeder(ladder=...)
    canonical shapes, with every on-arm batch observed by a
    CompileShapeCache so the bounded-recompile claim is in the output.

    Returns (tok_on, tok_off, flops_per_sec_on, detail-dict)."""
    from paddle_tpu.core.batch import DEFAULT_LADDER
    from paddle_tpu.core.compiler import CompileShapeCache
    from paddle_tpu.reader import bucketing as bkt
    from paddle_tpu.reader.feeder import DataFeeder

    feeder_off = DataFeeder(dtypes)
    off_raw = [
        samples[i : i + batch_size]
        for i in range(0, len(samples) - batch_size + 1, batch_size)
    ]
    tok_off, _, off_table = _bucket_ab_arm(
        cnet, opt, [feeder_off(b) for b in off_raw],
        [tok_fn(b) for b in off_raw], k=k, iters=iters,
    )
    on_raw = list(
        bkt.token_budget_batch(
            lambda: iter(samples), token_budget=budget, drop_last=True
        )()
    )
    feeder_on = DataFeeder(dtypes, ladder=DEFAULT_LADDER)
    on_host = [feeder_on(b) for b in on_raw]
    cache = CompileShapeCache(cache_name)
    for hb in on_host:
        cache.observe(hb)
    tok_on, fl_on, on_table = _bucket_ab_arm(
        cnet, opt, on_host, [tok_fn(b) for b in on_raw], k=k, iters=iters,
    )
    detail = {
        "on_tokens_per_sec": round(tok_on, 2),
        "off_tokens_per_sec": round(tok_off, 2),
        "speedup": round(tok_on / tok_off, 3) if tok_off else None,
        "compile_cache": {
            **cache.summary(), "ladder_rungs": len(DEFAULT_LADDER),
        },
        "shapes_on": on_table,
        "shapes_off": off_table,
    }
    return tok_on, tok_off, fl_on, detail


def _pass_cache_epoch_ms(cnet, opt, batches, k: int = 8, iters: int = 2,
                         seed: int = 0):
    """Cached-epoch arm for the image benches: seal the staged device
    batches into a PassCache (reader/pass_cache.py, the TPU-native
    CACHE_PASS_IN_MEM) and time multi-dispatch replay of the stacked cached
    pass — the repeat-epoch regime where the feed is HBM-resident, zero
    H2D.  k steps per dispatch are drawn from consecutive cached epochs
    (seed-reproducible shuffle), stacked once on device before the clock.
    Fresh params per call (the step donates its buffers).  Returns
    (ms_per_batch, cache summary)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.reader.pass_cache import PassCache
    from paddle_tpu.trainer.step import make_multi_train_step

    cache = PassCache(seed=seed)
    for b in batches:
        cache.observe(b)
    cache.seal()
    stream = cache.stream()
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[next(stream) for _ in range(k)]
    )
    params, state = cnet.init(jax.random.PRNGKey(seed))
    opt_state = opt.init(params)
    key = jax.random.PRNGKey(1)
    multi = make_multi_train_step(cnet, opt, k, mesh=None)
    multi, _ = _aot(multi, params, state, opt_state, stacked, key)
    params, state, opt_state, m = multi(params, state, opt_state, stacked, key)
    _sync(m)
    t0 = time.perf_counter()
    for i in range(iters):
        params, state, opt_state, m = multi(
            params, state, opt_state, stacked, jax.random.PRNGKey(i)
        )
    _sync(m)
    return (time.perf_counter() - t0) / (iters * k) * 1e3, cache.summary()


def _rate_mfu_fields(flops_per_sec) -> dict:
    """MFU fields from an aggregate FLOP/s rate (the A/B arms time several
    shapes; _mfu_fields wants a single per-step pairing)."""
    if not flops_per_sec:
        return {}
    tflops = flops_per_sec / 1e12
    return {
        "tflops": round(tflops, 2),
        "mfu": round(tflops / _peak_tflops(), 4),
    }


def bench_resnet() -> dict:
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.models.resnet import resnet_cost

    reset_auto_names()
    batch_size, img_size = 128, 224

    cost, _ = resnet_cost(depth=50, class_num=1000, img_size=img_size)
    net = CompiledNetwork(Topology([cost]), compute_dtype=jnp.bfloat16)
    params, state = net.init(jax.random.PRNGKey(0))
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)

    rng = np.random.RandomState(0)
    batches = [
        {
            "image": SeqTensor(
                jax.device_put(
                    rng.randn(batch_size, img_size * img_size * 3).astype(np.float32)
                )
            ),
            "label": SeqTensor(
                jax.device_put(rng.randint(0, 1000, size=batch_size).astype(np.int32))
            ),
        }
        for _ in range(4)
    ]
    ms, ms_single, flops = _measure_steps(
        net, opt, params, state, opt.init(params), batches, k=4,
        iters_multi=8, iters_single=16,
    )
    img_per_sec = batch_size / (ms / 1e3)
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / TARGET_IMG_S, 4),
        "step_ms": round(ms, 2),
        "steps_per_dispatch": 4,
        "single_dispatch_ms": round(ms_single, 2),
        "feed": "pre-staged device batches (feed excluded by design)",
        **_mfu_fields(flops, ms / 1e3),
        "binds": "profiled (jax.profiler): 45 of 50 ms in conv fusions "
        "(backward convs dominate, NHWC throughout, copies <3 ms); the "
        "205 MB stage-1 activations put residual/relu ops at HBM roofline "
        "(~0.9 ms each).  Batch 256 measured the same MFU — conv time is "
        "XLA's ceiling at these shapes, not a layout or fusion artifact",
    }


def bench_nmt() -> dict:
    """Seq2seq NMT with attention (BASELINE configs #3) over a VARIABLE-
    length corpus: a batch-size × bucketing sweep in one process.

    The decoder scan now runs the FUSED attention-GRU core (ops/rnn.py
    _attgru_core via the recurrent_group pattern match): 2 chained
    [B,H]-class GEMMs + the attention matvec per step instead of the
    6-GEMM per-layer chain (the expand+fc state projection alone was
    [B*S, H] redundant rows every step).  A latency-bound step scales
    near-free with batch, so the sweep times bs 64/128/256 with the
    token budget scaled to each (budget = bs x rung(max_len)).

    off — pad-to-max feed (paddle.batch order, per-batch max padding).
    on — reader.bucketing token-budget packing + DataFeeder(ladder=...)
    canonical shapes + scan early-exit past each bucket's true max.

    tokens/sec counts VALID target tokens in both arms.  Headline = the
    bs-128 bucketing-on number (r05-comparable); the compile cache must
    stay bounded by the ladder (no per-batch recompiles).

    Roofline (B=128, T=50, S=50, H=P=512, E=1024, v5e):
      * removed outright: the unfused expand+fc state projection ran a
        [B*S,H]x[H,P] GEMM per step = 3.36 GFLOP (S=50x redundant — every
        row repeats the same [B,H] product); fused it is 0.1 GFLOP inside
        the shared a1 GEMM.  Over 50 steps fwd+bwd that is ~0.4 TFLOP of
        pure waste gone, ~2 ms at peak before counting launch overhead.
      * remaining in-scan chain per step (fwd): a1 [128,512]x[512,1536]
        (0.2 GF) -> score matvec (7 MF) -> ctx reduce (13 MF) -> ctx GEMM
        [128,1024]x[1024,1536] (0.4 GF) -> candidate [128,512]x[512,512]
        (67 MF) ≈ 0.7 GFLOP = ~3.5 us of MXU at peak, but FIVE dependent
        kernels deep; at ~2-4 us latency per small-GEMM link the chain
        floor is ~10-20 us/step fwd (similar bwd) -> ~1.5-4 ms for the
        whole scan, irreducible without batching more rows per step.
        That is why the batch sweep exists: latency-bound steps scale
        near-free with B until the GEMMs hit the MXU roofline.
      * out-of-scan (hoisted) work now dominates FLOPs: vocab head +
        softmax-CE ~590 GFLOP fwd+bwd per batch at high MFU."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.batch import ladder_len
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.data_types import integer_value_sequence
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.models.seq2seq import seq2seq_cost

    reset_auto_names()
    max_len, min_len = 50, 8
    head_bs = 128
    src_vocab = trg_vocab = 30000

    cost, _ = seq2seq_cost(src_vocab, trg_vocab, word_dim=512, hidden_dim=512)
    net = CompiledNetwork(Topology([cost]), compute_dtype=jnp.bfloat16)
    opt = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9)

    # short-skewed sentence lengths (WMT-like); every arm sees THIS corpus
    rng = np.random.RandomState(0)
    n_samples = 4096
    lens = (
        min_len
        + np.floor((max_len - min_len + 1) * rng.beta(2.0, 3.0, n_samples))
    ).astype(int)
    samples = [
        tuple(
            [int(t) for t in rng.randint(1, src_vocab, size=int(l))]
            for _ in range(3)
        )
        for l in lens
    ]
    dtypes = [
        ("src_word", integer_value_sequence(src_vocab)),
        ("trg_word", integer_value_sequence(trg_vocab)),
        ("trg_next", integer_value_sequence(trg_vocab)),
    ]
    valid_tok = lambda b: sum(len(s[2]) for s in b)  # target tokens

    sweep = []
    head = None
    for bs in (64, 128, 256):
        budget = bs * ladder_len(max_len)
        iters = 3 if bs == head_bs else 2
        tok_on, tok_off, fl_on, ab = _bucketing_ab(
            net, opt, samples, dtypes, bs, budget, valid_tok,
            cache_name=f"nmt_bench_bs{bs}", k=8, iters=iters,
        )
        sweep.append({
            "batch_size": bs,
            "on_tokens_per_sec": round(tok_on, 2),
            "off_tokens_per_sec": round(tok_off, 2),
            "speedup": round(tok_on / tok_off, 3) if tok_off else None,
        })
        if bs == head_bs:
            head = (tok_on, fl_on, ab)
    tok_on, fl_on, ab = head

    return {
        "metric": "nmt_tokens_per_sec",
        "value": round(tok_on, 2),
        "unit": "valid target tokens/sec",
        "bucketing": "on",
        "batch_size": head_bs,
        "vs_baseline": round(tok_on / TARGET_NMT_TOK_S, 4),
        "batch_sweep": sweep,
        "ab": {
            **ab,
            "corpus": f"{n_samples} pairs, len {min_len}-{max_len} "
            "beta(2,3)-skewed",
        },
        "steps_per_dispatch": 8,
        "binds": "decoder scan = the FUSED attention-GRU core "
        "(recurrent_group pattern-match -> ops/rnn._attgru_core, the "
        "hl_cuda_lstm.cu fused-timestep discipline): per step one "
        "[B,H]x[H,P+2H] state GEMM (attention projection + GRU gates "
        "share h_prev), score matvec + context reduce, one "
        "[B,E]x[E,3H] context GEMM, one [B,H]x[H,H] candidate GEMM; "
        "target-side input projection + vocab head + softmax-CE all run "
        "once on the stacked sequence outside the scan; backward defers "
        "every weight grad to post-scan einsums.  Bucketing packs each "
        "step to a ~constant valid-token budget and the scan early-exits "
        "dead steps; batch sweep probes the latency-bound regime",
        **_rate_mfu_fields(fl_on),
    }


def bench_nmt_generate() -> dict:
    """Generation-side NMT throughput: jitted beam-5 decode over the same
    attention-GRU model, through the golden-tested Seq2SeqGenerator path
    with the fused decoder step (reference flagship inference path:
    RecurrentGradientMachine.cpp:964 generateSequence, :1393 beamSearch —
    run host-side there, on-device lax.scan here)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost

    reset_auto_names()
    src_vocab = trg_vocab = 30000
    b, beam, max_len, src_len = 64, 5, 32, 40
    cost, _ = seq2seq_cost(src_vocab, trg_vocab, word_dim=512, hidden_dim=512)
    params = paddle.parameters.create(cost, seed=0)
    gen = Seq2SeqGenerator(
        params, src_vocab, trg_vocab, word_dim=512, hidden_dim=512,
        bos_id=0, eos_id=1, max_length=max_len, beam_size=beam,
    )
    rng = np.random.RandomState(0)
    batch = {
        "src_word": SeqTensor(
            jax.device_put(
                rng.randint(2, src_vocab, size=(b, src_len)).astype(np.int32)
            ),
            jax.device_put(np.full((b,), src_len, np.int32)),
        )
    }
    # weights ride as an ARGUMENT, not a trace-time closure constant
    # (analysis.trace_lint T102: closure-captured params can't be donated
    # and re-ship with every compile)
    gp = params.params
    fn = jax.jit(lambda p, bt: gen.generate(bt, params=p))
    fn, flops = _aot(fn, gp, batch)
    seqs, scores = fn(gp, batch)
    float(np.asarray(scores)[0, 0])  # device sync
    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        seqs, scores = fn(gp, batch)
    float(np.asarray(scores)[0, 0])
    dt = (time.perf_counter() - t0) / iters
    # emitted top-beam tokens (eos-terminated) per second
    top = np.asarray(seqs)[:, 0, :]
    eos_pos = np.where(top == 1, np.arange(top.shape[1])[None, :], max_len)
    out_lens = eos_pos.min(axis=1)
    n_tok = int(out_lens.sum()) or b * max_len
    return {
        "metric": "nmt_generate_tokens_per_sec",
        "value": round(n_tok / dt, 2),
        "unit": "top-beam tokens/sec",
        "ms_per_sentence": round(dt / b * 1e3, 3),
        "batch": b,
        "beam": beam,
        "max_length": max_len,
        "decode_steps_per_sec": round(max_len / dt, 2),
        "binds": "a beam step is the SAME dependent chain as a training "
        "forward step at B*beam rows (fused attention-GRU step + vocab "
        "head + top-k) — latency-bound, so throughput scales with "
        "batch*beam, not with the MXU; untrained weights, fixed-shape "
        "decode (no early stop), which lower-bounds tokens/s",
        **_mfu_fields(flops, dt),
    }


def bench_serving() -> list:
    """Serving-plane headline (ROADMAP item 1): continuous batching +
    block-paged decode cache (paddle_tpu/serving/) vs the one-shot
    Seq2SeqGenerator path, under OPEN-LOOP load (reader/loadgen.py — the
    Gemma-on-TPU serving methodology, arXiv:2605.25645: arrivals follow a
    fixed Poisson clock, queueing shows up in latency, not offered rate).

    Three arms:
      * one-shot EAGER — the pre-serving inference surface (per-request
        ``generate_greedy``, retraced per call): the path this subsystem
        replaces, and the acceptance baseline;
      * one-shot JIT — per-request whole-decode jitted at B=1 (the
        strongest single-request baseline, only reachable through the new
        engine's reference path);
      * serving — open-loop load through the continuous-batching
        scheduler at ~90% of its saturation capacity.

    Asserted in-run: sustained req/s >= 2x the one-shot path at no-worse
    p99 per-token latency, outputs bit-identical per request, ZERO
    compiles inside the measured window (the prewarmed ladder bound)."""
    import paddle_tpu as paddle
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen
    from paddle_tpu.serving import Request, ServingEngine, ServingScheduler

    reset_auto_names()
    # container-sized flagship shape: on the 2-core CPU host every decode
    # arm is equal-flops compute-bound (no HBM-bandwidth win to share), so
    # the dims stay small enough that dispatch amortization — the part of
    # the architecture the container CAN measure — is visible
    vocab, word_dim, hidden, max_new = 1000, 128, 128, 24
    n_requests, max_slots, k_steps = 64, 16, 8
    cost, _ = seq2seq_cost(vocab, vocab, word_dim=word_dim, hidden_dim=hidden)
    params = paddle.parameters.create(cost, seed=0)
    gen = Seq2SeqGenerator(
        params, vocab, vocab, word_dim=word_dim, hidden_dim=hidden,
        bos_id=0, eos_id=1, max_length=max_new,
    )
    engine = ServingEngine(
        gen, max_slots=max_slots, hbm_budget_mb=16, max_new_tokens=max_new,
        block_steps=k_steps,
    )
    rng = np.random.RandomState(0)
    srcs = [
        rng.randint(2, vocab, size=rng.randint(4, 31)).tolist()
        for _ in range(n_requests)
    ]

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    # -- arm 1: the EAGER one-shot path (what inference looked like before
    # this subsystem: per-request generate_greedy, retraced per call) -----
    from paddle_tpu.reader.feeder import DataFeeder
    from paddle_tpu.core.batch import DEFAULT_LADDER

    feeder = DataFeeder(
        gen._enc_net.topology.data_types(), ladder=DEFAULT_LADDER,
        min_seq_len=1,
    )
    eager_tpot = []
    t0 = time.perf_counter()
    for s in srcs[:8]:  # 8 requests suffice: each pays a full retrace
        r0 = time.perf_counter()
        toks, lens = gen.generate_greedy(
            feeder([(s,)]), max_new_tokens=max_new
        )
        n = int(np.asarray(lens)[0])
        eager_tpot.append((time.perf_counter() - r0) / max(n, 1))
    eager_rps = 8 / (time.perf_counter() - t0)

    # -- arm 2: the JIT one-shot baseline (B=1 whole-decode executable per
    # source rung; doubles as the bit-identity goldens) -------------------
    for s in (min(srcs, key=len), max(srcs, key=len)):
        engine.reference_decode(s, max_new)  # compile both rungs
    refs, jit_tpot = [], []
    t0 = time.perf_counter()
    for s in srcs:
        r0 = time.perf_counter()
        toks = engine.reference_decode(s, max_new)
        jit_tpot.append((time.perf_counter() - r0) / max(len(toks), 1))
        refs.append(toks)
    jit_rps = n_requests / (time.perf_counter() - t0)

    # -- arm 3: serving.  Deterministic ladder prewarm (the `paddle-tpu
    # cache warm` discipline) realizes every (slot-rung, page-rung) decode
    # variant and every (group-rung, source-rung) prefill variant, then a
    # saturation wave measures capacity, then the MEASURED open-loop run
    # offers ~90% of that capacity — stable queue, honest p99 -------------
    for gsz in (1, 2, 4, 8, 16):
        for src_len in (5, 20):  # 1-page and 2-page rungs
            engine.admit([Request([2] * src_len) for _ in range(gsz)])
            while engine.n_live:
                engine.step()

    def run_serving(reqs, offered_rps=None, seed=2):
        with ServingScheduler(engine) as sched:
            t1 = time.perf_counter()
            if offered_rps is None:  # saturation: all at once
                for r in reqs:
                    sched.submit(r)
            else:
                OpenLoopLoadGen(
                    offered_rps, len(reqs), lambda i: reqs[i], seed=seed
                ).run(sched.submit)
            for r in reqs:
                if not r.wait(300):
                    raise RuntimeError(f"unserved request {r.req_id}")
            return time.perf_counter() - t1

    capacity_rps = n_requests / run_serving([Request(s) for s in srcs])
    traces_before = dict(engine.trace_counts)
    offered = 0.9 * capacity_rps
    reqs = [Request(s) for s in srcs]
    wall = run_serving(reqs, offered)
    assert engine.trace_counts == traces_before, (
        "continuous batching recompiled mid-run: "
        f"{traces_before} -> {engine.trace_counts}"
    )

    bit_identical = all(
        r.error is None and r.tokens == ref for r, ref in zip(reqs, refs)
    )
    assert bit_identical, "serving decode diverged from the one-shot path"
    # ladder bound: decode variants <= |slot rungs| x |page rungs realized|
    assert engine.trace_counts["decode"] <= 10, engine.summary()

    tpots = sorted(
        (r.t_done - r.t_admit) / max(len(r.tokens), 1) for r in reqs
    )
    queue_waits = sorted(r.t_admit - r.t_submit for r in reqs)
    sustained = n_requests / wall
    p99_serving, p99_eager = pct(tpots, 0.99), pct(sorted(eager_tpot), 0.99)
    meets_2x = (
        sustained >= 2.0 * eager_rps and p99_serving <= p99_eager * 1.05
    )
    assert meets_2x, (
        f"serving gate: {sustained / eager_rps:.2f}x req/s vs one-shot, "
        f"p99 tpot {p99_serving * 1e3:.2f} vs {p99_eager * 1e3:.2f} ms"
    )
    n_tokens = sum(len(r.tokens) for r in reqs)
    return [
        {
            "metric": "serving_req_per_sec",
            "value": round(sustained, 2),
            "unit": "sustained req/s (open-loop)",
            "oneshot_req_per_sec": round(eager_rps, 2),
            "oneshot_jit_req_per_sec": round(jit_rps, 2),
            "speedup_vs_oneshot": round(sustained / eager_rps, 2),
            "speedup_vs_oneshot_jit": round(sustained / jit_rps, 2),
            "offered_req_per_sec": round(offered, 2),
            "capacity_req_per_sec": round(capacity_rps, 2),
            "n_requests": n_requests,
            "max_slots": max_slots,
            "decode_block_steps": k_steps,
            "tokens_per_sec": round(n_tokens / wall, 1),
            "p50_token_ms": round(pct(tpots, 0.5) * 1e3, 3),
            "p99_token_ms": round(p99_serving * 1e3, 3),
            "oneshot_p99_token_ms": round(p99_eager * 1e3, 3),
            "oneshot_jit_p99_token_ms": round(
                pct(sorted(jit_tpot), 0.99) * 1e3, 3
            ),
            "p99_queue_wait_ms": round(pct(queue_waits, 0.99) * 1e3, 3),
            "decode_compiles": engine.trace_counts["decode"],
            "prefill_compiles": engine.trace_counts["prefill"],
            "bit_identical_to_oneshot": bit_identical,
            "meets_2x_at_equal_p99": meets_2x,
            "pages": engine.pages.summary(),
            "binds": "per-token p50/p99 = (done - admit)/tokens per "
            "request; sustained = completed/(first submit -> last done) "
            "under a Poisson arrival clock at 0.9x saturation capacity.  "
            "The 2x gate scores against the pre-serving EAGER one-shot "
            "path; the B=1 whole-decode JIT arm is reported alongside — "
            "on this 2-core CPU every arm is equal-flops compute-bound, "
            "so batched decode only amortizes dispatch (~parity with the "
            "jit arm); on TPU the B=1 decode GEMV is HBM-bound and "
            "in-flight batching is the multiplier (arXiv:2604.15464)",
        },
        {
            "metric": "serving_p99_token_ms",
            "value": round(p99_serving * 1e3, 3),
            "unit": "ms",
            "p50_token_ms": round(pct(tpots, 0.5) * 1e3, 3),
            "oneshot_p99_token_ms": round(p99_eager * 1e3, 3),
        },
    ]


def bench_fleet_serving() -> list:
    """Fleet-router tier (ISSUE 18): sustained req/s through the SLO-aware
    affinity router (paddle_tpu/serving/router.py) over 1 -> 2 -> 4 REAL
    ``paddle-tpu serve --register`` engine subprocesses, each with BLAS
    pinned to one thread (the _fleet_env discipline).

    CORRECTNESS-GRADE curve on this 2-core container: 4 single-threaded
    engines contend for 2 cores, so the N-scaling number measures routing
    overhead + contention, not the fleet's throughput multiplier — on a
    pod slice each engine owns its chips and the curve is the capacity
    knob.  What the container CAN gate:

      * every request served through every fleet size (disjoint ledger
        sums to offered, zero double-serves);
      * N-INVARIANCE — output tokens bit-identical across 1/2/4-engine
        fleets and therefore to single-engine serving (same seeded
        params, continuous batching already bit-stable per bench_serving);
      * the affinity A/B — duplicate-heavy traffic (PrefixMixer
        dup_frac) with COW prefix caches armed: prefix-cache hit rate
        with affinity routing ON must be >= OFF (affinity concentrates a
        session's repeats on the engine whose cache holds the blocks;
        least-loaded spread pays one cold miss PER ENGINE per prompt)."""
    import signal as _signal
    import subprocess as _subprocess

    from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer
    from paddle_tpu.robustness.scenarios import (
        _V, _prewarm_fleet, _spawn_engine, _wait_engines,
    )
    from paddle_tpu.serving import FleetClient, Request, Router

    n_requests, max_new = 32, 8
    rng = np.random.RandomState(0)
    srcs = [
        rng.randint(2, _V, size=rng.randint(4, 25)).tolist()
        for _ in range(n_requests)
    ]

    def run_fleet(n_engines, *, affinity=True, mixer=None, tag="",
                  n=n_requests, rate=None):
        router = Router(
            address=("127.0.0.1", 0), lease_timeout_s=5.0,
            stats_poll_s=0.1, affinity=affinity,
        )
        procs = []
        extra = ("--prefix-cache",) if mixer is not None else ()
        try:
            procs = [
                _spawn_engine(f"{tag}e{i}", router.address, 0, extra=extra)
                for i in range(n_engines)
            ]
            _wait_engines(router, n_engines, procs=procs)
            _prewarm_fleet(router)
            time.sleep(0.3)  # one poll period: post-prewarm counters land
            base = {
                e: dict(h["stats"])
                for e, h in router.fleet_stats()["engines"].items()
            }
            if mixer is None:
                reqs = [
                    Request(list(s), max_new, req_id=f"{tag}-{i}")
                    for i, s in enumerate(srcs)
                ]
            else:
                reqs = [
                    Request(
                        mixer.source(i), max_new, req_id=f"{tag}-{i}",
                        session_id=mixer.session_of(i),
                    )
                    for i in range(n)
                ]
            fc = FleetClient(router.address)
            t1 = time.perf_counter()
            try:
                if rate is None:  # saturation: all at once
                    for r in reqs:
                        fc.submit(r)
                else:  # open-loop: spaced arrivals (repeats find PARKED
                    # pages — a duplicate concurrent with its first
                    # occurrence misses by construction)
                    OpenLoopLoadGen(
                        rate, len(reqs), lambda i: reqs[i], seed=3
                    ).run(fc.submit)
                for r in reqs:
                    if not r.wait(300):
                        raise RuntimeError(f"unserved request {r.req_id}")
            finally:
                fc.close()
            wall = time.perf_counter() - t1
            bad = [r for r in reqs if r.status != "served"]
            assert not bad, (
                f"fleet n={n_engines}: {len(bad)} requests not served: "
                f"{[(r.req_id, r.status, r.error) for r in bad[:3]]}"
            )
            time.sleep(0.3)  # final poll: cumulative cache counters land
            fleet = router.fleet_stats()
            hits = misses = 0
            for e, h in fleet["engines"].items():
                s, b = h["stats"], base.get(e, {})
                hits += int(s.get("prefix_cache_hits", 0)) - int(
                    b.get("prefix_cache_hits", 0)
                )
                misses += int(s.get("prefix_cache_misses", 0)) - int(
                    b.get("prefix_cache_misses", 0)
                )
            ledger = fleet["ledger"]
            assert ledger["served"] >= n_requests and sum(
                ledger.values()
            ) == ledger["served"], f"fleet ledger not disjoint: {ledger}"
            return {
                "rps": n_requests / wall,
                "tokens": [list(r.tokens) for r in reqs],
                "hits": hits,
                "misses": misses,
            }
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(_signal.SIGTERM)
            for p in procs:
                try:
                    p.communicate(timeout=90)
                except _subprocess.TimeoutExpired:
                    p.kill()
                    p.communicate()
            router.close()

    curve, tokens_by_n = {}, {}
    for n in (1, 2, 4):
        out = run_fleet(n, tag=f"n{n}")
        curve[str(n)] = round(out["rps"], 2)
        tokens_by_n[n] = out["tokens"]
    n_invariant = tokens_by_n[1] == tokens_by_n[2] == tokens_by_n[4]
    assert n_invariant, "fleet outputs diverged across engine counts"

    ab = {}
    for affinity in (True, False):
        mixer = PrefixMixer(
            _V, pool_size=4, prefix_frac=1.0, prefix_tokens=10,
            tail_tokens=6, dup_frac=0.6, seed=7, sessions=4,
        )
        out = run_fleet(
            2, affinity=affinity, mixer=mixer, tag=f"aff{int(affinity)}",
            n=48, rate=10.0,
        )
        tot = out["hits"] + out["misses"]
        ab[affinity] = {
            "hit_frac": round(out["hits"] / max(tot, 1), 4),
            "hits": out["hits"],
            "misses": out["misses"],
        }
    affinity_wins = ab[True]["hit_frac"] >= ab[False]["hit_frac"]
    assert affinity_wins, (
        f"affinity routing lost the prefix-hit A/B: ON {ab[True]} "
        f"vs OFF {ab[False]}"
    )
    return [
        {
            "metric": "fleet_serving_req_per_sec",
            "value": curve["2"],
            "unit": "sustained req/s through the affinity router, 2 "
            "engines (correctness-grade on this 2-core container)",
            "curve_req_per_sec": curve,
            "n_requests": n_requests,
            "n_invariance_bit_identical": bool(n_invariant),
            "binds": "engines are separate processes, BLAS pinned to 1 "
            "thread each; on 2 cores the 1->2->4 curve measures router "
            "dispatch + core contention (correctness-grade), on a pod "
            "slice it is the capacity knob.  Gated here: all served, "
            "disjoint ledger, outputs bit-identical across fleet sizes "
            "(= identical to single-engine serving)",
        },
        {
            "metric": "fleet_affinity_prefix_hit_frac",
            "value": ab[True]["hit_frac"],
            "unit": "prefix-cache hit fraction, affinity ON "
            "(duplicate-heavy traffic: PrefixMixer dup_frac=0.6)",
            "affinity_off_hit_frac": ab[False]["hit_frac"],
            "ab": {"on": ab[True], "off": ab[False]},
            "gate_affinity_improves_hit_rate": bool(affinity_wins),
        },
    ]


def bench_decode_speed() -> list:
    """Decode raw speed (PR 17): the tentpole pair A/B-measured on the
    container-sized NMT flagship shape.

    * speculative decoding — n-gram draft + verify-K in ONE dispatch vs
      the plain greedy block-decode loop, SAME requests: tokens/s both
      arms, accept rate, and outputs asserted BIT-IDENTICAL (rejection
      falls back to the true argmax chain);
    * COW prefix sharing — PrefixMixer traffic (pooled prefixes + exact
      duplicates) through the threaded scheduler under open-loop load:
      hit rate, shared-block peak, and served p99 per-token latency
      gated against the PR-12 SLO (<= 1.05x the one-shot eager p99,
      the bench_serving discipline) with sharing ON."""
    import paddle_tpu as paddle
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
    from paddle_tpu.reader.feeder import DataFeeder
    from paddle_tpu.core.batch import DEFAULT_LADDER
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer
    from paddle_tpu.serving import Request, ServingEngine, ServingScheduler

    reset_auto_names()
    vocab, word_dim, hidden, max_new = 1000, 128, 128, 24
    n_requests, max_slots, k_steps = 32, 16, 8
    cost, _ = seq2seq_cost(vocab, vocab, word_dim=word_dim, hidden_dim=hidden)
    params = paddle.parameters.create(cost, seed=0)
    gen = Seq2SeqGenerator(
        params, vocab, vocab, word_dim=word_dim, hidden_dim=hidden,
        bos_id=0, eos_id=1, max_length=max_new,
    )
    rng = np.random.RandomState(1)
    srcs = [
        rng.randint(2, vocab, size=rng.randint(4, 31)).tolist()
        for _ in range(n_requests)
    ]

    def make_engine(**kw):
        return ServingEngine(
            gen, max_slots=max_slots, hbm_budget_mb=16,
            max_new_tokens=max_new, block_steps=k_steps, **kw,
        )

    def prewarm(eng):
        for gsz in (1, 2, 4, 8, 16):
            for src_len in (5, 20):  # 1-page and 2-page rungs
                eng.admit([Request([2] * src_len) for _ in range(gsz)])
                while eng.n_live:
                    eng.step()

    def run_engine(eng, reqs):
        pending = list(reqs)
        t0 = time.perf_counter()
        while pending or eng.n_live or eng.n_prefilling:
            if pending:
                admitted = eng.admit(pending)
                pending = pending[len(admitted):]
            eng.step()
        return time.perf_counter() - t0

    # -- A/B: greedy block decode vs speculative verify-K -----------------
    greedy = make_engine(spec_decode=False)
    refs = [greedy.reference_decode(s, max_new) for s in srcs]
    prewarm(greedy)
    g_reqs = [Request(s) for s in srcs]
    g_wall = run_engine(greedy, g_reqs)
    g_tokens = sum(len(r.tokens) for r in g_reqs)
    assert all(r.tokens == ref for r, ref in zip(g_reqs, refs))

    spec = make_engine(spec_decode=True)
    prewarm(spec)
    s_reqs = [Request(s) for s in srcs]
    s_wall = run_engine(spec, s_reqs)
    s_tokens = sum(len(r.tokens) for r in s_reqs)
    # the acceptance bit: speculation NEVER changes a token
    spec_identical = all(r.tokens == ref for r, ref in zip(s_reqs, refs))
    assert spec_identical, "speculative decode diverged from greedy"

    # -- COW prefix sharing under open-loop load --------------------------
    mixer = PrefixMixer(
        vocab, pool_size=4, prefix_frac=0.6, prefix_tokens=16,
        tail_tokens=10, dup_frac=0.5, seed=4,
    )
    p_srcs = [mixer.source(i) for i in range(n_requests)]
    shared = make_engine(prefix_cache=True)
    p_refs = [shared.reference_decode(s, max_new) for s in p_srcs]
    prewarm(shared)
    # the prewarm wave's duplicate prompts hit the cache too — zero the
    # counters so the reported rate covers ONLY the measured traffic
    shared.prefix_hits = shared.prefix_misses = 0

    # one-shot EAGER p99 (the pre-serving path, retraced per call): the
    # PR-12 SLO reference the served p99 is gated against
    feeder = DataFeeder(
        gen._enc_net.topology.data_types(), ladder=DEFAULT_LADDER,
        min_seq_len=1,
    )
    eager_tpot = []
    for s in p_srcs[:6]:
        r0 = time.perf_counter()
        _, lens = gen.generate_greedy(feeder([(s,)]), max_new_tokens=max_new)
        n = int(np.asarray(lens)[0])
        eager_tpot.append((time.perf_counter() - r0) / max(n, 1))

    peak_shared = [0]

    def on_done(_r):
        # sampled at each completion, while other same-prefix requests
        # are still live over the shared mapping
        peak_shared[0] = max(peak_shared[0], shared.pages.n_shared)

    p_reqs = [Request(s, callback=on_done) for s in p_srcs]
    with ServingScheduler(shared) as sched:
        t1 = time.perf_counter()
        # offered fast enough that same-prefix requests OVERLAP in
        # flight (the condition under which sharing holds one copy);
        # queue wait is excluded from the tpot gate (t_admit-based)
        OpenLoopLoadGen(
            100.0, len(p_reqs), lambda i: p_reqs[i], seed=4
        ).run(sched.submit)
        for r in p_reqs:
            if not r.wait(300):
                raise RuntimeError(f"unserved request {r.req_id}")
        p_wall = time.perf_counter() - t1
    assert all(
        r.error is None and r.tokens == ref
        for r, ref in zip(p_reqs, p_refs)
    ), "prefix-shared decode diverged from the one-shot path"
    assert shared.prefix_hits > 0, "the duplicate-heavy mix never hit"
    assert shared.pages.n_used == 0, shared.pages.summary()

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    tpots = sorted(
        (r.t_done - r.t_admit) / max(len(r.tokens), 1) for r in p_reqs
    )
    p99_shared = pct(tpots, 0.99)
    p99_eager = pct(sorted(eager_tpot), 0.99)
    slo_ok = p99_shared <= p99_eager * 1.05
    assert slo_ok, (
        f"prefix sharing blew the PR-12 p99 SLO: "
        f"{p99_shared * 1e3:.2f} vs {p99_eager * 1e3:.2f} ms eager"
    )
    hit_rate = shared.prefix_hits / max(
        shared.prefix_hits + shared.prefix_misses, 1
    )
    return [
        {
            "metric": "spec_decode_tokens_per_sec",
            "value": round(s_tokens / s_wall, 1),
            "unit": "tokens/sec",
            "greedy_tokens_per_sec": round(g_tokens / g_wall, 1),
            "speedup_vs_greedy": round(
                (s_tokens / s_wall) / (g_tokens / g_wall), 3
            ),
            "accept_rate": round(spec.spec_accept_rate(), 4),
            "drafted": spec.spec_proposed,
            "accepted": spec.spec_accepted,
            "spec_ngram": spec.spec_ngram,
            "verify_block_steps": k_steps,
            "bit_identical_to_greedy": spec_identical,
            "n_requests": n_requests,
            "binds": "same requests through the same engine shape, spec "
            "ON vs OFF; the verify program hoists all K draft embeddings "
            "into one batched GEMM, and a rejected draft costs nothing "
            "but the unconsumed tail of its dispatch (the emitted tokens "
            "are the true argmax chain either way).  On this CPU host "
            "both arms are compute-bound, so the ratio isolates the "
            "dispatch/hoist arithmetic, not an HBM win.  Note the greedy "
            "arm's block loop ALREADY emits K exact tokens per dispatch "
            "on this recurrent decoder (the amortization speculation buys "
            "architectures whose step can't scan), so spec trades emitted "
            "tokens for draft verification here — the guard pins that "
            "trade from getting worse, not a speedup claim",
        },
        {
            "metric": "spec_accept_rate",
            "value": round(spec.spec_accept_rate(), 4),
            "unit": "fraction of drafted tokens confirmed",
            "drafted": spec.spec_proposed,
            "accepted": spec.spec_accepted,
            "spec_ngram": spec.spec_ngram,
        },
        {
            "metric": "prefix_cache_hit_rate",
            "value": round(hit_rate, 4),
            "unit": "fraction of admissions mapping warmed blocks",
            "hits": shared.prefix_hits,
            "misses": shared.prefix_misses,
            "entries": shared.prefix_cache_len,
            "peak_pages_shared": peak_shared[0],
            "pages_retained": shared.pages.n_retained,
            "tokens_per_sec": round(
                sum(len(r.tokens) for r in p_reqs) / p_wall, 1
            ),
            "p99_token_ms": round(p99_shared * 1e3, 3),
            "eager_p99_token_ms": round(p99_eager * 1e3, 3),
            "meets_p99_slo": slo_ok,
            "bit_identical_to_oneshot": True,
            "binds": "PrefixMixer open-loop mix (pool 4, prefix_frac "
            "0.6, dup_frac 0.5): every duplicate prompt admits with ZERO "
            "prefill dispatches over refcount-shared blocks; p99 "
            "per-token latency gated <= 1.05x the one-shot eager path "
            "(the PR-12 SLO discipline) with sharing ON",
        },
    ]


def run_gated(*names) -> None:
    """Run named bench arms under the regression guard (the `make verify`
    legs): each ``bench_<name>()`` result gets best_prior/regressed fields
    against the committed BENCH_r*.json history, a REGRESSION_GUARD line
    sums them up, and any regression (or non-finite metric) exits
    nonzero — the same discipline `make bench` applies to the full set."""
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    prior = load_prior_bench(repo_dir)
    results = []
    for name in names:
        rs = globals()["bench_" + name]()
        for r in rs if isinstance(rs, list) else [rs]:
            r.update(regression_fields(
                r.get("metric", ""), r.get("value"), r.get("unit"), prior
            ))
            results.append(r)
            print(json.dumps(r), flush=True)
    guard = build_guard(results)
    print(json.dumps(guard), flush=True)
    if guard["regressed"] or guard["non_finite"]:
        raise SystemExit(
            "bench regression vs committed history: "
            + json.dumps(guard["regressed"] + guard["non_finite"])
        )


def bench_scenarios() -> list:
    """Production-gate scenario record (ROADMAP item 5): the scenario
    harness (robustness/scenarios.py) run under the bench regression
    guard.  Three fast scenarios, gates ASSERTED in-run:

      * overload — the shed-not-collapse gate: at 2x the measured
        saturation rate, goodput (completed within the SLO) must hold
        >= 80% of the saturation-rate goodput AND the p99 of served
        requests must stay inside the SLO (deadline-aware shedding
        degrades to the feasible subset; pre-SLO FCFS collapses here);
      * nan_request_under_load — a poisoned request fired mid-traffic:
        exactly one victim, recovery-time-after-fault reported;
      * mixed_train_serve — train + serve concurrently in one process:
        training stays bit-identical to the solo run.
      * partition_under_load — the hostile-network gate (ISSUE 15): a
        real-RPC training loop rides a corrupt frame (codec reject
        counter asserted > 0) and a mid-pass link partition while the
        serving plane takes live deadline traffic; recovery-time-after-
        partition is the committed metric, params bit-identical to an
        unfaulted reference leg, surviving journal lints clean.
      * trace_replay_drift — the scenario-realism gate (ISSUE 20): a
        recorded two-class overload window replays bit-identically from
        its .ptt trace; replay-vs-live p99/goodput drift bounded, per-
        class admission sheds the batch class first in both windows.

    Committed round artifacts: SCENARIO_r12.json (overload/chaos/mixed),
    SCENARIO_r15.json (+ partition_under_load) and SCENARIO_r20.json
    (+ trace_replay_drift); load_prior_bench reads SCENARIO_r*.json into
    the same best_prior history BENCH_r*.json feeds."""
    from paddle_tpu.robustness import scenarios

    ov = scenarios.scenario_overload()
    assert ov["passed"], (
        "shed-not-collapse gate failed: "
        f"goodput 2x/1x {ov['goodput_2x_over_1x']} "
        f"(gate_goodput={ov['gate_goodput_2x_ge_80pct']}, "
        f"gate_p99={ov['gate_p99_within_slo']})"
    )
    nan = scenarios.scenario_chaos_under_load(point="nan_request")
    assert nan["passed"], f"nan_request_under_load failed: {nan}"
    mixed = scenarios.scenario_mixed_train_serve()
    assert mixed["passed"], f"mixed_train_serve failed: {mixed}"
    part = scenarios.scenario_partition_under_load()
    assert part["passed"], f"partition_under_load failed: {part}"
    assert part["recovery_after_partition_ms"] < 10_000, part
    trd = scenarios.scenario_trace_replay_drift()
    assert trd["passed"], f"trace_replay_drift failed: {trd}"
    return [
        {
            "metric": "scenario_goodput_2x_frac",
            "value": ov["goodput_2x_over_1x"],
            "unit": "goodput@2x-saturation / goodput@saturation "
            "(completed-within-SLO rate; gate >= 0.8)",
            "slo_ms": ov["slo_ms"],
            "saturation_rps": ov["saturation_rps"],
            "statuses_2x": ov["at_2x"]["statuses"],
            "statuses_1x": ov["at_1x"]["statuses"],
            "p99_ms_2x_served": ov["at_2x"]["p99_ms"],
            "gate_goodput_2x_ge_80pct": ov["gate_goodput_2x_ge_80pct"],
            "gate_p99_within_slo": ov["gate_p99_within_slo"],
            "binds": "open-loop Poisson arrivals with per-request "
            "deadlines = SLO; saturation derived as slots/mean-service "
            "from an all-at-once wave; shed = deadline-infeasible at "
            "admission (EWMA queue-wait predictor), timeout = canceled "
            "mid-decode at deadline (slot+pages freed)",
        },
        {
            "metric": "scenario_served_p99_ms_at_saturation",
            "value": ov["at_1x"]["p99_ms"],
            "unit": "ms end-to-end at 1x saturation (cpu container)",
            "p50_ms": ov["at_1x"]["p50_ms"],
            "p95_ms": ov["at_1x"]["p95_ms"],
        },
        {
            "metric": "scenario_chaos_recovery_ms",
            "value": nan["recovery_after_fault_ms"],
            "unit": "ms fault-to-next-completion under live load "
            "(nan_request mid-traffic)",
            "n_chaos_victims": nan["n_chaos_victims"],
            "goodput_frac": nan["goodput_frac"],
        },
        {
            "metric": "scenario_mixed_train_serve_goodput",
            "value": mixed["goodput_frac"],
            "unit": "fraction of requests completed within SLO while a "
            "training loop shares the process",
            "train_bit_identical_to_solo":
                mixed["train_bit_identical_to_solo"],
            "train_steps_per_s_solo": mixed["train_steps_per_s_solo"],
            "train_steps_per_s_mixed": mixed["train_steps_per_s_mixed"],
        },
        {
            "metric": "scenario_partition_recovery_ms",
            "value": part["recovery_after_partition_ms"],
            "unit": "ms partition-onset to next successful task ack "
            "under live mixed train+serve traffic (gate < 10s; "
            "correctness gates: codec reject counter > 0, params "
            "bit-identical, journal clean)",
            "partition_secs": part["partition_secs"],
            "chaos_point": part["chaos_point"],
            "wire_server_rejected_frames":
                part["wire"].get("server_rejected_frames"),
            "train_params_bit_identical":
                part["train_params_bit_identical"],
            "serve_goodput_frac": part["goodput_frac"],
            "binds": "netem fault transport over the master_wire codec: "
            "net_corrupt flips one client frame (CRC rejects, bounded "
            "retry rides it), net_partition severs the client link for "
            f"{part['partition_secs']}s mid-pass; the worker's RPC "
            "retry window absorbs it and the serving plane keeps its "
            "SLO throughout",
        },
        {
            "metric": "scenario_trace_replay_goodput",
            "value": trd["replay"]["goodput_frac"],
            "unit": "fraction of REPLAYED requests completed within SLO "
            "on a recorded 2x-saturation two-class window (drift vs the "
            "live window gated <= 0.35 in-run)",
            "slo_ms": trd["slo_ms"],
            "trace_records": trd["trace_records"],
            "live_goodput_frac": trd["live"]["goodput_frac"],
            "goodput_drift": round(abs(trd["replay"]["goodput_frac"]
                                       - trd["live"]["goodput_frac"]), 4),
            "gate_offer_bit_identical": trd["gate_offer_bit_identical"],
            "gate_goodput_drift": trd["gate_goodput_drift"],
            "p0_goodput_live":
                trd["live"]["classes"]["p0"]["goodput_frac"],
            "p0_goodput_replay":
                trd["replay"]["classes"]["p0"]["goodput_frac"],
            "p2_goodput_live":
                trd["live"]["classes"]["p2"]["goodput_frac"],
            "p2_goodput_replay":
                trd["replay"]["classes"]["p2"]["goodput_frac"],
            "gate_high_class_goodput": trd["gate_high_class_goodput"],
            "gate_low_class_sheds_first":
                trd["gate_low_class_sheds_first"],
            "binds": "record a PrefixMixer two-class (p0 interactive / "
            "p2 batch) 2x-saturation window to a .ptt request-lifecycle "
            "trace while serving it live, then replay the trace against "
            "a fresh scheduler: the replayed offer is bit-identical "
            "(prompts, sessions, classes, deadlines, order), per-class "
            "admission (class_shed_slack {0:0.7, 2:1.5}) must shed the "
            "batch class first in BOTH windows",
        },
        {
            "metric": "scenario_trace_replay_p99_ms",
            "value": trd["replay"]["p99_ms"],
            "unit": "ms end-to-end p99 of served requests in the "
            "REPLAYED window (drift vs live gated <= 3x + 250ms in-run; "
            "cpu container)",
            "live_p99_ms": trd["live"]["p99_ms"],
            "gate_p99_drift": trd["gate_p99_drift"],
        },
    ]


def bench_tracing_overhead() -> list:
    """Obs-plane overhead gate (ISSUE 13): the span tracer's ring recorder
    (paddle_tpu/obs) must cost <= 3% throughput with the flight recorder
    ARMED, on both instrumented hot paths — ASSERTED in-run:

      * the LSTM flagship training step driven through the REAL
        ``SGD.train`` loop (feed span on the stage path, train_step span
        per dispatch, block_fetch span on the host sync — exactly the
        production instrumentation, not a synthetic emit loop);
      * the serving saturation arm: an all-at-once request wave through
        the fully-instrumented ``ServingScheduler`` (submit/queued/admit
        instants, decode_step spans, delivery spans, terminal ledger
        instants per request).

    Methodology for a noisy 2-core container: R alternating
    recorder-off / recorder-on reps per arm, scored on the MIN wall of
    each arm (the noise floor), so a scheduler hiccup in one rep cannot
    fake a 3% regression.  The committed round artifact is OBS_r13.json
    (load_prior_bench reads OBS_r*.json into the same best_prior
    history)."""
    from paddle_tpu import obs
    from paddle_tpu.utils import flags as _flags

    results = []

    # -- arm 1: LSTM flagship step through SGD.train ----------------------
    # the rnn-benchmark idiom (embedding -> simple_lstm -> last_seq -> fc
    # softmax) built via the DSL — the staged reference config needs the
    # /root/reference mount this container lacks, and the overhead gate
    # measures the INSTRUMENTED LOOP, not the model zoo
    import paddle_tpu as paddle
    from paddle_tpu.core.topology import reset_auto_names

    batch_size, seq_len, n_batches, reps = 64, 32, 8, 6
    vocab, emb_dim, hidden = 10000, 128, 128
    reset_auto_names()
    words = paddle.layer.data(
        "word", paddle.data_type.integer_value_sequence(vocab)
    )
    emb = paddle.layer.embedding(input=words, size=emb_dim)
    lstm = paddle.layer.networks.simple_lstm(input=emb, size=hidden)
    last = paddle.layer.last_seq(input=lstm)
    pred = paddle.layer.fc(
        last, size=2, act=paddle.activation.Softmax()
    )
    label = paddle.layer.data("label", paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=pred, label=label)
    trainer = paddle.trainer.SGD(
        cost=cost,
        parameters=paddle.parameters.create(cost, seed=0),
        update_equation=paddle.optimizer.Adam(learning_rate=1e-3),
    )
    rng = np.random.RandomState(0)
    row_batches = [
        [
            (rng.randint(2, vocab, size=seq_len).tolist(), int(i % 2))
            for i in range(batch_size)
        ]
        for _ in range(n_batches)
    ]

    def one_pass():
        t0 = time.perf_counter()
        trainer.train(
            reader=lambda: iter(row_batches), num_passes=1,
            async_load_data=False,
        )
        return time.perf_counter() - t0

    one_pass()  # compile warmup (outside every measured rep)
    walls = {False: [], True: []}
    for rep in range(reps):
        # the arm ORDER flips each rep: a monotonic machine drift (turbo
        # ramp, background load) otherwise favors whichever arm always
        # samples first and fakes a systematic overhead
        for armed in ((False, True) if rep % 2 == 0 else (True, False)):
            obs.tracer.set_recording(armed)
            obs.tracer.reset()
            walls[armed].append(one_pass())
    obs.tracer.set_recording(bool(_flags.get_flag("flight_recorder")))
    off_ms = min(walls[False]) / n_batches * 1e3
    on_ms = min(walls[True]) / n_batches * 1e3
    train_overhead_pct = (on_ms - off_ms) / off_ms * 100.0
    assert train_overhead_pct <= 3.0, (
        f"tracing overhead gate (train): {train_overhead_pct:.2f}% > 3% "
        f"({off_ms:.2f} -> {on_ms:.2f} ms/batch)"
    )
    results.append({
        "metric": "tracing_overhead_lstm_step_ms",
        "value": round(on_ms, 3),
        "unit": "ms/batch, recorder ARMED (LSTM-128 flagship-idiom step "
        "via SGD.train)",
        "recorder_off_ms": round(off_ms, 3),
        "overhead_pct": round(train_overhead_pct, 3),
        "gate_overhead_le_3pct": True,
        "reps": reps,
        "binds": "per-step cost = 2 spans + 1 feed span (~1-2 us each, "
        "one short lock hold into a bounded deque) against a "
        "multi-ms jitted dispatch — min-of-reps over alternating "
        "off/on passes",
    })

    # -- arm 2: serving saturation wave -----------------------------------
    from paddle_tpu.robustness.scenarios import make_serving_engine
    from paddle_tpu.serving import Request, ServingScheduler

    # production-shaped dispatch amortization (serving_decode_block_steps'
    # K-tokens-per-dispatch default): the gate measures the instrumented
    # scheduler at the dispatch granularity serving actually runs, not the
    # scenario harness's K=1 worst case
    engine = make_serving_engine(seed=0, max_slots=4, block_steps=4)
    n_requests = 48
    rng = np.random.RandomState(0)
    srcs = [
        rng.randint(2, 60, size=rng.randint(3, 24)).tolist()
        for _ in range(n_requests)
    ]

    def one_wave():
        reqs = [Request(s) for s in srcs]
        with ServingScheduler(engine) as sched:
            t0 = time.perf_counter()
            for r in reqs:
                sched.submit(r)
            for r in reqs:
                if not r.wait(300):
                    raise RuntimeError(f"unserved {r.req_id}")
            wall = time.perf_counter() - t0
        assert all(r.status == "served" for r in reqs)
        return wall

    one_wave()  # warmup (prewarmed engine; first wave pays queue ramp)
    walls = {False: [], True: []}
    for rep in range(reps):
        for armed in ((False, True) if rep % 2 == 0 else (True, False)):
            obs.tracer.set_recording(armed)
            obs.tracer.reset()
            walls[armed].append(one_wave())
    obs.tracer.set_recording(bool(_flags.get_flag("flight_recorder")))
    off_s, on_s = min(walls[False]), min(walls[True])
    serve_overhead_pct = (on_s - off_s) / off_s * 100.0
    assert serve_overhead_pct <= 3.0, (
        f"tracing overhead gate (serving): {serve_overhead_pct:.2f}% > 3% "
        f"({off_s * 1e3:.1f} -> {on_s * 1e3:.1f} ms/wave)"
    )
    results.append({
        "metric": "tracing_overhead_serving_wave_ms",
        "value": round(on_s * 1e3, 3),
        "unit": f"ms to serve a {n_requests}-request saturation wave, "
        "recorder ARMED",
        "recorder_off_ms": round(off_s * 1e3, 3),
        "overhead_pct": round(serve_overhead_pct, 3),
        "gate_overhead_le_3pct": True,
        "req_per_sec_armed": round(n_requests / on_s, 2),
        "reps": reps,
        "binds": "~6 instants + 2 spans per request lifecycle against "
        "multi-ms decode dispatches; min-of-reps over alternating "
        "off/on waves through the instrumented scheduler",
    })
    return results


def bench_resnet_pipeline() -> list:
    """ResNet-50 fed through the REAL IO plane: recordio file -> native
    threaded Prefetcher -> host decode/batching -> uint8 device transfer ->
    on-device normalize -> train step, with jax async dispatch overlapping
    host feed and device compute.  This is the number that regresses when
    the recordio/prefetch/transfer path does (the all-device-resident bench
    above cannot).

    Three metrics (the VERDICT-prescribed split): the FIRST epoch is
    H2D-bound and scores against the measured serial ceiling (inline /
    async / data-echo arms; plus a no-echo feed-path tripwire metric that
    regresses when the recordio/prefetch/transfer path does); every LATER
    epoch feeds from the device-resident pass cache (reader/pass_cache.py —
    the TPU-native CACHE_PASS_IN_MEM) with zero H2D traffic and scores
    against the compute-path number."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp()
    try:
        return _bench_resnet_pipeline_body(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_resnet_pipeline_body(tmp: str) -> list:
    import os

    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.io import recordio
    from paddle_tpu.models.resnet import resnet_cost
    from paddle_tpu.trainer.step import make_train_step

    reset_auto_names()
    batch_size, img_size, n_rec = 128, 224, 512
    rng = np.random.RandomState(0)
    path = os.path.join(tmp, "train.rio")
    # uint8 HWC pixels + label byte per record (imagenet-pipe-like payload)
    recordio.write_records(
        path,
        (
            rng.randint(0, 256, size=img_size * img_size * 3, dtype=np.uint8)
            .tobytes() + bytes([rng.randint(100)])
            for _ in range(n_rec)
        ),
        max_chunk_records=64,
    )

    cost, _ = resnet_cost(depth=50, class_num=1000, img_size=img_size)
    topo = Topology([cost])
    # Host->device bandwidth is the scarce resource (especially through the
    # axon tunnel this bench runs over): ship the raw uint8 pixels (4x
    # smaller than f32) and cast+normalize INSIDE the jitted step via the
    # data layer's wire-dtype attrs (compiler._feed_transform — XLA fuses
    # the cast+scale into the first conv's input read).  The pass cache
    # below therefore holds the pass at ~1 byte/px, exactly the uint8 wire
    # form the HBM sizing rule is stated for.
    img_conf = topo.layers["image"]
    img_conf.attrs["feed_dtype"] = "uint8"
    img_conf.attrs["feed_scale"] = 1.0 / 255.0
    net = CompiledNetwork(topo, compute_dtype=jnp.bfloat16)
    params, state = net.init(jax.random.PRNGKey(0))
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    opt_state = opt.init(params)
    step = make_train_step(net, opt, mesh=None)

    # Isolated host->device bandwidth (device idle), best of 3 — the
    # environment's transfer capability when nothing else runs.  The axon
    # tunnel backend SERIALIZES transfers with compute (a put issued while
    # the stream is busy completes only after the queued compute drains),
    # so the honest per-environment ceiling for an interleaved pipeline is
    # serial: batch transfer at isolated bw + one step, back to back.
    probe = np.zeros(16 << 20, np.uint8)
    jax.device_put(probe[: 1 << 20]).block_until_ready()  # warm the path
    h2d_bytes_per_s = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_put(probe).block_until_ready()
        h2d_bytes_per_s = max(
            h2d_bytes_per_s, probe.nbytes / (time.perf_counter() - t0)
        )
    batch_bytes = batch_size * (img_size * img_size * 3 + 4)

    def raw_batches():
        """(uint8 pixels [B, HWC], int32 labels [B]) host batches, forever."""
        while True:
            pf = recordio.Prefetcher([path])
            try:
                imgs, labels = [], []
                while True:
                    rec = pf.next()
                    if rec is None:
                        break
                    imgs.append(np.frombuffer(rec[:-1], np.uint8))
                    labels.append(rec[-1])
                    if len(imgs) == batch_size:
                        yield np.stack(imgs), np.asarray(labels, np.int32)
                        imgs, labels = [], []
            finally:
                pf.close()

    def stage(pair):
        """Background-thread half of the feed: issue the H2D transfers so
        they overlap the main thread's step dispatch/compute.  Pixels stay
        uint8 across the wire AND in the staged batch — the step's fused
        feed transform casts+normalizes on device."""
        u8, labels = pair
        return {
            "image": SeqTensor(jax.device_put(u8)),
            "label": SeqTensor(jax.device_put(labels)),
        }

    from paddle_tpu.reader.prefetch import DevicePrefetcher

    m = None
    src = raw_batches()
    warm = stage(next(src))
    for _ in range(4):  # warm compile + caches
        params, state, opt_state, m = step(
            params, state, opt_state, warm, jax.random.PRNGKey(0)
        )
    _sync(m)

    # pure step time on an already-staged batch (same run, same weather):
    # isolates the compute term of the serial ceiling
    t0 = time.perf_counter()
    for i in range(8):
        params, state, opt_state, m = step(
            params, state, opt_state, warm, jax.random.PRNGKey(i)
        )
    _sync(m)
    step_s = (time.perf_counter() - t0) / 8

    iters = 24

    # ---- A/B: the same recordio -> stage -> step loop, fed two ways ----
    # (a) inline: stage on the main thread, then step (the pre-r03 path)
    t0 = time.perf_counter()
    for i in range(iters):
        params, state, opt_state, m = step(
            params, state, opt_state, stage(next(src)), jax.random.PRNGKey(i)
        )
    _sync(m)
    sync_dt = time.perf_counter() - t0
    sync_img_s = batch_size * iters / sync_dt

    # (b) async: background worker stages batch i+1 (decode + device_put)
    # while the device runs step i (double-buffered)
    it = DevicePrefetcher(src, stage, depth=2)
    next(it)  # fill the double buffer before the clock starts
    it.wait_s = 0.0
    t0 = time.perf_counter()
    for i in range(iters):
        params, state, opt_state, m = step(
            params, state, opt_state, next(it), jax.random.PRNGKey(i)
        )
    _sync(m)
    async_dt = time.perf_counter() - t0
    feed_wait_s = it.wait_s
    it.close()
    async_img_s = batch_size * iters / async_dt

    dt = min(sync_dt, async_dt)
    # what the interleaved transfers actually sustained; only meaningful
    # when transfers visibly serialize with compute (non-transfer time is a
    # sizeable share of the wall) — on hardware that overlaps copies this
    # residual is ~0 and the figure would be noise
    xfer_s = dt - iters * step_s
    interleaved_mb_s = (
        iters * batch_bytes / xfer_s / 1e6 if xfer_s > 0.2 * dt else None
    )
    serial_ceiling_img_s = batch_size / (batch_bytes / h2d_bytes_per_s + step_s)

    # (c) data echo: train each transferred batch echo_factor times, so the
    # H2D-bound first epoch amortizes every transfer (pass_cache.capture's
    # echo path; img/s counts trained samples, the data-echo accounting)
    echo_factor, echo_iters = 2, 12
    t0 = time.perf_counter()
    for i in range(echo_iters):
        b = stage(next(src))
        for e in range(echo_factor):
            params, state, opt_state, m = step(
                params, state, opt_state, b, jax.random.PRNGKey(i * 7 + e)
            )
    _sync(m)
    echo_dt = time.perf_counter() - t0
    echo_img_s = batch_size * echo_iters * echo_factor / echo_dt

    # ---- cached epochs: device-resident pass cache (zero H2D) -----------
    from paddle_tpu.reader.pass_cache import PassCache
    from paddle_tpu.trainer.step import make_multi_train_step

    n_pass_batches = n_rec // batch_size  # 4 = the whole recordio pass
    cache = PassCache(seed=0)
    for _ in range(n_pass_batches):
        cache.observe(stage(next(src)))
    cache.seal()

    # stepwise replay — the exact SGD cached-epoch path, one dispatch per
    # step (pays the environment's per-dispatch cost each step).  One
    # warmup step + host-fetch sync first: the capture loop's device_puts
    # are async, and an unsynced clock would bill their in-flight H2D to a
    # metric whose whole claim is zero H2D.
    params, state, opt_state, m = step(
        params, state, opt_state, next(iter(cache.epoch(0))),
        jax.random.PRNGKey(99),
    )
    _sync(m)
    stepwise_epochs = 3
    t0 = time.perf_counter()
    for p in range(stepwise_epochs):
        for i, b in enumerate(cache.epoch(p)):
            params, state, opt_state, m = step(
                params, state, opt_state, b, jax.random.PRNGKey(p * 31 + i)
            )
    _sync(m)
    stepwise_dt = time.perf_counter() - t0
    stepwise_img_s = (
        batch_size * n_pass_batches * stepwise_epochs / stepwise_dt
    )

    # multi-dispatch replay — one dispatch per cached epoch (lax.scan over
    # the stacked pass), the production regime where async dispatch keeps
    # the device queue full; stacked once on device (a jnp.stack per leaf,
    # still zero H2D), timed over several epochs
    stacked = cache.stacked_pass(0)
    multi = make_multi_train_step(net, opt, n_pass_batches, mesh=None)
    multi, _ = _aot(multi, params, state, opt_state, stacked, jax.random.PRNGKey(0))
    params, state, opt_state, m = multi(
        params, state, opt_state, stacked, jax.random.PRNGKey(0)
    )
    _sync(m)
    cached_epochs = 6
    t0 = time.perf_counter()
    for p in range(cached_epochs):
        params, state, opt_state, m = multi(
            params, state, opt_state, stacked, jax.random.PRNGKey(p)
        )
    _sync(m)
    cached_dt = time.perf_counter() - t0
    cached_img_s = batch_size * n_pass_batches * cached_epochs / cached_dt
    compute_img_s = batch_size / step_s

    feed_path_img_s = max(sync_img_s, async_img_s)  # unique images, no echo
    img_per_sec = max(feed_path_img_s, echo_img_s)
    first = {
        "metric": "resnet50_pipeline_images_per_sec",
        "value": round(img_per_sec, 2),
        "unit": "images/sec (first epoch, H2D-bound)",
        "vs_baseline": round(img_per_sec / TARGET_IMG_S, 4),
        "sync_img_s": round(sync_img_s, 2),
        "async_img_s": round(async_img_s, 2),
        "echo2_img_s": round(echo_img_s, 2),
        "serial_ceiling_img_s": round(serial_ceiling_img_s, 1),
        "vs_serial_ceiling": round(img_per_sec / serial_ceiling_img_s, 3),
        "note": (
            "ACCOUNTING CHANGE r06: the headline may be the data-echo arm "
            "(trained samples/s, each image counted echo_factor times); "
            "pre-r06 rounds were no-echo — the comparable no-echo series "
            "is resnet50_pipeline_feed_path_images_per_sec.  "
            f"FIRST epoch, three arms: inline feed {sync_img_s:.0f} img/s, "
            f"background double-buffered feeder {async_img_s:.0f} img/s "
            f"(feed wait {feed_wait_s:.1f}s of {async_dt:.1f}s wall), "
            f"data-echo x{echo_factor} {echo_img_s:.0f} trained-img/s "
            "(each transferred batch trains twice — pass_cache echo_factor); "
            "headline = the fastest arm, scored against the SERIAL ceiling "
            f"~{serial_ceiling_img_s:.0f} img/s (echo can beat it: it "
            "amortizes the transfer term)."
            + (
                "  Environment-bound: the axon tunnel backend serializes "
                "H2D with compute — isolated transfer "
                f"{h2d_bytes_per_s / 1e6:.0f} MB/s but only "
                f"{interleaved_mb_s:.0f} MB/s once interleaved with steps "
                f"({step_s * 1e3:.0f} ms/step pure); on hardware with "
                "normal async copy engines the same code overlaps transfer "
                "with compute."
                if interleaved_mb_s is not None
                else "  Transfers fully overlapped compute this run."
            )
            + " Epochs >= 2 feed from the device-resident pass cache — see "
            "resnet50_pipeline_cached_epoch_images_per_sec"
        ),
    }
    # echo counts each image echo_factor times, so the headline above can
    # stay healthy while the recordio/prefetch/transfer path rots — this
    # metric is the feed-path regression tripwire (unique images through
    # the real feed, no echo), guarded on its own history
    feed_metric = {
        "metric": "resnet50_pipeline_feed_path_images_per_sec",
        "value": round(feed_path_img_s, 2),
        "unit": "images/sec (first epoch, unique images, no echo)",
        "vs_baseline": round(feed_path_img_s / TARGET_IMG_S, 4),
        "sync_img_s": round(sync_img_s, 2),
        "async_img_s": round(async_img_s, 2),
        "vs_serial_ceiling": round(feed_path_img_s / serial_ceiling_img_s, 3),
        "note": "max(inline, async double-buffer) over the recordio -> "
        "stage -> uint8 H2D -> step loop; THE number that regresses when "
        "the feed path does (the echo-inclusive headline cannot — echoed "
        "steps are compute-bound)",
    }
    cached_metric = {
        "metric": "resnet50_pipeline_cached_epoch_images_per_sec",
        "value": round(cached_img_s, 2),
        "unit": "images/sec (epochs >= 2, device-resident pass cache)",
        "vs_baseline": round(cached_img_s / TARGET_IMG_S, 4),
        "compute_path_img_s": round(compute_img_s, 2),
        "vs_compute_path": round(cached_img_s / compute_img_s, 3),
        "stepwise_img_s": round(stepwise_img_s, 2),
        "cache": cache.summary(),
        "note": (
            "epochs >= 2 replay the decoded pass from HBM "
            f"({cache.nbytes / 1e6:.0f} MB uint8 wire form, normalize "
            "fused in the step) — zero H2D, no per-batch Python.  "
            f"Headline = one dispatch per cached epoch (lax.scan over the "
            f"stacked pass, {n_pass_batches} steps/dispatch) vs the pure "
            f"compute path {compute_img_s:.0f} img/s; stepwise replay "
            f"(one dispatch per step, the literal SGD loop) sustains "
            f"{stepwise_img_s:.0f} img/s through the tunnel's per-dispatch "
            "cost.  The reference's CACHE_PASS_IN_MEM "
            "(PyDataProvider2.cpp:69) kept the pass in host RAM; the wire "
            "being the TPU bottleneck, this cache keeps it in HBM"
        ),
    }
    return [first, feed_metric, cached_metric]


def _bench_transformer_ctx(
    metric: str, batch_size: int, seq_len: int, iters: int,
    use_pallas: bool, extra: dict | None = None,
) -> dict:
    """Shared Transformer-base training harness: one jitted step over
    padded [B, seq_len] batches, optionally through the Pallas flash
    attention kernel (the long-context path); AOT-compiled once, timed via
    host-fetch sync, MFU from XLA cost analysis."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.models.transformer import transformer_cost
    from paddle_tpu.utils.flags import set_flag

    reset_auto_names()
    vocab = 32000
    d_model, n_heads, n_layers, d_ff = 512, 8, 6, 2048

    set_flag("use_pallas_attention", use_pallas)
    try:
        cost, _ = transformer_cost(
            vocab, vocab, d_model, n_heads, n_layers, d_ff
        )
        net = CompiledNetwork(Topology([cost]), compute_dtype=jnp.bfloat16)
        params, state = net.init(jax.random.PRNGKey(0))
        opt = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9)
        opt_state = opt.init(params)

        rng = np.random.RandomState(0)
        lens = jnp.full((batch_size,), seq_len, jnp.int32)

        def mk():
            def ids():
                return jax.device_put(
                    rng.randint(1, vocab, size=(batch_size, seq_len)).astype(
                        np.int32
                    )
                )

            return {
                "src_word": SeqTensor(ids(), lens),
                "trg_word": SeqTensor(ids(), lens),
                "trg_next": SeqTensor(ids(), lens),
            }

        batches = [mk() for _ in range(2 if seq_len >= 1024 else 4)]
        k = 4 if seq_len >= 1024 else 8
        ms, ms_single, flops = _measure_steps(
            cnet=net, opt=opt, params=params, state=state,
            opt_state=opt_state, batches=batches, k=k,
            iters_multi=max(2, iters // k), iters_single=min(iters, 8),
        )
    finally:
        set_flag("use_pallas_attention", False)

    tok_per_sec = batch_size * seq_len / (ms / 1e3)
    flops_src = "xla"
    if use_pallas and flops:
        # XLA's cost analysis counts NOTHING inside a pallas_call custom
        # kernel, so with flash attention on, the dominant FLOPs of a
        # long-context step vanish from the report (r04's xl-ctx "MFU 0.14"
        # undercounted by ~2x).  Add the kernels' analytic count:
        # fwd = 4·B·h·T²·dh (qk + pv), flash bwd ≈ 2.5x fwd (5 block
        # matmuls + s recompute); causal self-attention skips half the
        # blocks.  Layers: 6 encoder self (full) + 6 decoder self (causal)
        # + 6 cross (full).
        unit = (
            14.0 * batch_size * n_heads * (d_model // n_heads)
            * seq_len * seq_len
        )
        # n_layers encoder self (full) + n_layers decoder self (causal,
        # half the blocks) + n_layers cross (full)
        flops = flops + unit * (n_layers + n_layers * 0.5 + n_layers)
        flops_src = "xla+analytic_flash"
    return {
        "metric": metric,
        "value": round(tok_per_sec, 2),
        "unit": "tokens/sec",
        # all context lengths share the short-seq class target: long context
        # should stay at or above it on TPU, not get a discount
        "vs_baseline": round(tok_per_sec / TARGET_TRANSFORMER_TOK_S, 4),
        "step_ms": round(ms, 2),
        "steps_per_dispatch": k,
        "single_dispatch_ms": round(ms_single, 2),
        "flops_src": flops_src,
        **(extra or {}),
        **_mfu_fields(flops, ms / 1e3),
    }


def bench_transformer() -> dict:
    """Transformer-base MT train step (BASELINE configs #5), seq 64.
    batch 128 saturates the chip (64 left the MXU ~20% idle on pure
    dispatch granularity; throughput is the metric)."""
    return _bench_transformer_ctx(
        "transformer_base_tokens_per_sec", batch_size=128, seq_len=64,
        iters=20, use_pallas=False,
        extra={
            "binds": "profiled (jax.profiler, per-HLO): GEMM fusions ~21 ms of "
            "36 (near the 15.5 ms MXU floor for small-K/N=512 tiles), attention "
            "bwd layout-change copies ~8 ms (XLA materializes [B,h,T,dh] "
            "relayouts; einsum respellings and a VMEM Pallas kernel both "
            "measured slower), head CE ~2x its 4.1 ms floor"
        },
    )


def bench_transformer_long_context() -> dict:
    """Long-context training (seq 1024) with the Pallas flash-attention
    kernel on — the memory-bound regime where the fused online-softmax
    kernel avoids materializing [T, T] score matrices."""
    return _bench_transformer_ctx(
        "transformer_long_ctx_tokens_per_sec", batch_size=8, seq_len=1024,
        iters=10, use_pallas=True, extra={"seq_len": 1024},
    )


def bench_transformer_xl_context() -> dict:
    """Sequence 4096 training — the regime the Pallas flash kernel EXISTS
    for: a dense [T, T] score matrix at T=4096 is 128 MB per head per
    direction (f32) and the dense path OOMs/thrashes, while the streaming
    kernel holds O(T*dh)."""
    return _bench_transformer_ctx(
        "transformer_xl_ctx_tokens_per_sec", batch_size=2, seq_len=4096,
        iters=6, use_pallas=True, extra={"seq_len": 4096},
    )


def bench_lstm_textcls() -> dict:
    """Train the reference's OWN rnn benchmark config unmodified
    (benchmark/paddle/rnn/rnn.py: embedding 128 -> lstm_num x
    simple_lstm(hidden_size) -> last_seq -> fc softmax, via v1_compat +
    the config's provider.py).  Data: imdb.train.pkl synthesized in the
    provider's exact pickle schema (zero-egress stand-in for the IMDB
    download; vocab 30k, seq 100 padded, batch 128).  Reference K40m:
    261 ms/batch (benchmark/README.md:121-127, hidden 512 / bs 128);
    vs_baseline = reference_ms / our_ms."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.v1_compat import (
        make_optimizer,
        make_provider_reader,
        parse_config,
    )

    batch_size, seq_len, ref_ms = 128, 100, 261.0
    from paddle_tpu.testing import stage_reference_rnn_benchmark

    d = tempfile.mkdtemp(prefix="rnn_bench_")
    try:
        stage_reference_rnn_benchmark(d, n=512, seq_len=seq_len)

        cwd = os.getcwd()
        os.chdir(d)  # rnn.py probes imdb.train.pkl relative to cwd
        try:
            p = parse_config(
                os.path.join(d, "rnn.py"),
                f"hidden_size=512,lstm_num=2,batch_size={batch_size}",
            )
        finally:
            os.chdir(cwd)
        net = CompiledNetwork(p.topology, compute_dtype=jnp.bfloat16)
        params, state = net.init(jax.random.PRNGKey(0))
        opt = make_optimizer(p.settings)

        from paddle_tpu.reader.feeder import DataFeeder

        reader = make_provider_reader(p, d, train=True)
        feeder = DataFeeder(p.topology.data_types())
        it = reader()
        rows = [next(it) for _ in range(batch_size * 4)]
        batches = [
            jax.tree_util.tree_map(
                jax.device_put,
                feeder(rows[i * batch_size : (i + 1) * batch_size]),
            )
            for i in range(4)
        ]
    finally:
        shutil.rmtree(d, ignore_errors=True)
    # K=64 steps per dispatch: at ~4.5 ms/step the tunnel's ~6 ms flat
    # dispatch cost is 0.75 ms/step at K=8 — exactly the r05 gap between
    # the bench's 5.2 ms and the profiled 4.5 ms pure-device step (the
    # profile amortized dispatch, the bench didn't).  r06 K retune 32->64
    # bounds the amortized overhead at ~0.1 ms/step so the metric lands on
    # the profiled 4.5 ms core (VERDICT #9 closeout: target <= 4.6 ms).
    ms, ms_single, flops = _measure_steps(
        net, opt, params, state, opt.init(params), batches, k=64,
        iters_multi=2,
    )

    # ---- bucketing on/off A/B on a variable-length corpus ----------------
    # (headline above keeps the reference's fixed seq-100 shape for K40m
    # comparability; real IMDB reviews are variable-length, so the A/B
    # measures what bucketing buys on the same model.)  Rows follow the
    # provider's slot order (token ids, label); lengths are 10..100
    # beta(2,3)-skewed like the staged variable-length pkl.
    from paddle_tpu.core.batch import ladder_len

    rngv = np.random.RandomState(1)
    lens_v = (
        10 + np.floor(91 * rngv.beta(2.0, 3.0, size=2048))
    ).astype(int)
    rows_v = [
        ([int(t) for t in rngv.randint(2, 30000, size=int(l))], int(l % 2))
        for l in lens_v
    ]
    tok_on, tok_off, _, ab = _bucketing_ab(
        net, opt, rows_v, p.topology.data_types(), batch_size,
        batch_size * ladder_len(seq_len), lambda b: sum(len(r[0]) for r in b),
        cache_name="lstm_bench", k=8, iters=2,
    )

    return {
        "metric": "lstm_textcls_ms_per_batch",
        "value": round(ms, 2),
        "unit": "ms/batch",
        "vs_baseline": round(ref_ms / ms, 4),
        "steps_per_dispatch": 64,
        "single_dispatch_ms": round(ms_single, 2),
        "bucketing_ab": {
            **ab,
            "corpus": "2048 reviews, len 10-100 beta(2,3)-skewed (headline "
            "stays fixed seq-100 for K40m comparability)",
        },
        **_mfu_fields(flops, ms / 1e3),
        "binds": "scan-sequential recurrent GEMMs ([128,512]x[512,2048] per "
        "step, 200 dependent steps) — MXU-latency-bound, not HBM; "
        "custom-VJP cells (ops/rnn.py _lstm_core) keep backward to one "
        "GEMM/step with the weight grad as one post-scan einsum; "
        "single-dispatch adds ~6 ms tunnel cost",
    }


def _bench_reference_image_config(
    config_name: str, config_args: str, metric: str, ref_ms: float,
    batch_size: int, img_pixels: int, num_class: int, iters: int = 20,
    k: int = 8, note: str = "", ab_f32_feed: bool = False,
    _inner: bool = False,
) -> dict:
    """Train the reference's OWN benchmark config file (benchmark/paddle/
    image/*.py, parsed unmodified by v1_compat.parse_config) and report
    ms/batch against the published K40m number (benchmark/README.md tables;
    vs_baseline = reference_ms / our_ms).

    Every bench also reports the cached-epoch mode (`cached_epoch_ms_per_
    batch`): the same batches replayed through the device-resident
    PassCache, the repeat-epoch regime with zero H2D.  ``ab_f32_feed=True``
    additionally re-measures with BENCH_IMG_F32_FEED semantics (float32
    wire, no on-device normalize epilogue) in the same run — the committed
    bisect lever for feed-epilogue regressions."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.v1_compat import make_optimizer, parse_config

    p = parse_config(
        f"/root/reference/benchmark/paddle/image/{config_name}.py", config_args
    )
    net = CompiledNetwork(p.topology, compute_dtype=jnp.bfloat16)
    params, state = net.init(jax.random.PRNGKey(0))
    opt = make_optimizer(p.settings)
    opt_state = opt.init(params)

    rng = np.random.RandomState(0)
    # Feed through the REAL converter with the provider-resolved slot types
    # (PyDataProvider2 runtime input_types): rows follow data-layer
    # declaration order; the image slot is the one whose declared size
    # matches the pixel count, the label slot feeds as an integer id.
    from paddle_tpu.core.data_types import SlotKind
    from paddle_tpu.reader.feeder import DataFeeder

    dtypes = p.topology.data_types()  # raises if provider types unresolved
    assert any(t.kind == SlotKind.INDEX for _, t in dtypes), (
        f"{config_name}: label slot did not resolve to an index type"
    )
    assert any(
        t.kind == SlotKind.DENSE and t.dim == img_pixels for _, t in dtypes
    ), f"{config_name}: no dense slot resolved to the {img_pixels}-pixel image"

    # Narrow-dtype feed, on by default for the image benches: pixels cross
    # host->device as uint8 (1/4 the bytes) and the jitted step casts +
    # normalizes on device (compiler._feed_transform; the reference never
    # ships float32 pixels either — mnist_bin_part stores raw bytes).  The
    # parsed config's data layer gets the transform attrs injected here,
    # exactly what data_layer(feed_dtype="uint8", ...) declares first-class.
    img_names = [
        name for name, conf in p.topology.data_layers().items()
        if conf.input_type is not None
        and conf.input_type.kind == SlotKind.DENSE
        and conf.input_type.dim == img_pixels
    ]
    # A/B lever for feed-epilogue suspicion (see bench_googlenet): setting
    # BENCH_IMG_F32_FEED=1 ships float32 pixels and drops the on-device
    # cast+scale+shift epilogue, isolating whether the normalize fusion
    # costs step time on a given XLA version.
    f32_feed = bool(os.environ.get("BENCH_IMG_F32_FEED"))
    if not f32_feed:
        for n in img_names:
            c = p.topology.layers[n]
            c.attrs["feed_dtype"] = "uint8"
            c.attrs["feed_scale"] = 1.0 / 255.0
            c.attrs["feed_shift"] = -0.5
    feeder = DataFeeder(
        dtypes,
        feed_dtypes=({} if f32_feed else {n: np.uint8 for n in img_names}),
    )

    def row():
        out = []
        for name, t in dtypes:
            if t.kind == SlotKind.DENSE and name in img_names:
                out.append(rng.randint(0, 256, t.dim, dtype=np.uint8))
            elif t.kind == SlotKind.DENSE:
                out.append(rng.randn(t.dim).astype(np.float32))
            else:
                out.append(int(rng.randint(num_class)))
        return tuple(out)

    t_feed = time.perf_counter()
    host_batches = [
        feeder([row() for _ in range(batch_size)]) for _ in range(4)
    ]
    feed_ms = (time.perf_counter() - t_feed) / 4 * 1e3  # host feed per batch
    batches = [
        jax.tree_util.tree_map(jax.device_put, hb) for hb in host_batches
    ]
    ms, ms_single, flops = _measure_steps(
        net, opt, params, state, opt_state, batches, k=k,
        iters_multi=max(2, iters // k), iters_single=min(iters, 10),
    )
    result = {
        "metric": metric,
        "value": round(ms, 2),
        "unit": "ms/batch",
        "vs_baseline": round(ref_ms / ms, 4),
        "host_feed_ms_per_batch": round(feed_ms, 2),
        "steps_per_dispatch": k,
        "single_dispatch_ms": round(ms_single, 2),
        "feed": "f32 (BENCH_IMG_F32_FEED)" if f32_feed else "uint8 wire",
        "binds": (note + "  " if note else "")
        + "uint8 wire feed + on-device normalize; conv fusions "
        "(XLA) dominate the step",
        **_mfu_fields(flops, ms / 1e3),
    }
    if _inner:
        return result
    # cached-epoch mode: the same staged batches through the device-resident
    # pass cache (repeat-epoch regime, zero H2D)
    cached_ms, cache_sum = _pass_cache_epoch_ms(net, opt, batches, k=k)
    result["cached_epoch_ms_per_batch"] = round(cached_ms, 2)
    result["pass_cache"] = cache_sum
    if ab_f32_feed and not f32_feed:
        # in-run feed-epilogue bisect: re-parse + re-measure with float32
        # wire (no uint8 cast+scale+shift epilogue) and record the verdict
        os.environ["BENCH_IMG_F32_FEED"] = "1"
        try:
            alt = _bench_reference_image_config(
                config_name, config_args, metric, ref_ms,
                batch_size=batch_size, img_pixels=img_pixels,
                num_class=num_class, iters=iters, k=k, _inner=True,
            )
        finally:
            os.environ.pop("BENCH_IMG_F32_FEED", None)
        f32_ms = alt["value"]
        delta_pct = (ms - f32_ms) / f32_ms * 100.0
        result["f32_feed_ab"] = {
            "uint8_ms": round(ms, 2),
            "f32_ms": round(f32_ms, 2),
            "uint8_minus_f32_pct": round(delta_pct, 2),
            "cause": (
                f"uint8 normalize epilogue costs {ms - f32_ms:.1f} ms of "
                "the step — the r04->r05 regression lives in the feed "
                "epilogue fusion"
                if delta_pct > 3.0
                else "normalize epilogue exonerated (uint8 within 3% of "
                "f32 wire) — the r04->r05 delta is XLA scheduling "
                "variance on the inception concat graph, not the feed"
            ),
        }
    return result


def bench_alexnet() -> dict:
    """Reference benchmark/paddle/image/alexnet.py unmodified; K40m bs=128:
    334 ms/batch (benchmark/README.md:34-39)."""
    return _bench_reference_image_config(
        "alexnet", "batch_size=128", "alexnet_ms_per_batch", 334.0,
        batch_size=128, img_pixels=227 * 227 * 3, num_class=1000,
    )


def bench_googlenet() -> dict:
    """Reference benchmark/paddle/image/googlenet.py unmodified; K40m
    bs=128: 1149 ms/batch (benchmark/README.md:44-51).  The r04->r05
    29.1->31.5 ms regression's bisect lever now runs IN-RUN
    (ab_f32_feed=True): both wire forms are measured every round and the
    f32_feed_ab.cause field carries the one-line verdict."""
    return _bench_reference_image_config(
        "googlenet", "batch_size=128", "googlenet_ms_per_batch", 1149.0,
        batch_size=128, img_pixels=224 * 224 * 3, num_class=1000,
        ab_f32_feed=True,
        note="r04->r05 regressed 29.1->31.5 ms while alexnet (same "
        "harness, same feed path) improved 18.8->17.5 the same round — "
        "historic spread is 30.1 (r02) / 29.1 (r04); the f32_feed_ab "
        "field bisects it in-run (uint8 normalize epilogue vs XLA "
        "scheduling variance on the inception concat graph) and the "
        "regression guard pins every metric against best-prior.",
    )


def bench_smallnet() -> dict:
    """Reference benchmark/paddle/image/smallnet_mnist_cifar.py unmodified;
    K40m bs=64: 10.46 ms/batch (benchmark/README.md:53-60).  K=128 steps
    per dispatch (r06 retune, 64->128): at ~1 ms of device work per step
    the tunnel's ~6 ms dispatch cost was ~40% of the K=8 headline (r05 MFU
    0.0099) and still ~0.1 ms/step at K=64; K=128 bounds it at ~0.05
    ms/step so the metric measures the chip (VERDICT #9 closeout: MFU
    target >= 0.02)."""
    return _bench_reference_image_config(
        "smallnet_mnist_cifar", "batch_size=64", "smallnet_ms_per_batch",
        10.46, batch_size=64, img_pixels=32 * 32 * 3, num_class=10,
        iters=128, k=128,
    )


def _allreduce_body(devices, words: int, chain: int, iters: int):
    """Chained shard_map psum over the given devices; returns (GB/s, n) and
    verifies the reduction VALUE (each element must equal n^(chain+1) times
    the chained scale factor — a wrong collective shape or a dropped shard
    shows up as a numeric mismatch, not just a slow run)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.parallel.mesh import DATA_AXIS

    n = len(devices)
    mesh = Mesh(np.array(devices), (DATA_AXIS,))
    x = jnp.ones((words,), jnp.float32)

    def many(v):
        def body(c, _):
            r = jax.lax.psum(c, DATA_AXIS)
            # scale keeps the n=1 identity psum from folding; pcast re-marks
            # the replicated sum as device-varying so the carry type is stable
            return jax.lax.pcast(r * (1.0 + 1e-7), DATA_AXIS, to="varying"), None

        c, _ = jax.lax.scan(body, v, None, length=chain)
        return jax.lax.psum(c, DATA_AXIS)

    from paddle_tpu.parallel.mesh import shard_map as _shard_map

    f = jax.jit(
        _shard_map(many, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P())
    )
    y = f(x)
    got = float(y[0])
    want = float(n) ** (chain + 1) * (1.0 + 1e-7) ** chain
    assert abs(got - want) <= 1e-3 * want, (
        f"psum over {n} devices produced {got}, want {want}"
    )
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(x)
    float(y[0])
    dt = time.perf_counter() - t0
    return words * 4 * chain * iters / dt / 1e9, n


def bench_allreduce() -> dict:
    """Gradient-allreduce bandwidth over the mesh data axis — the path that
    replaces the reference pserver push/pull (ParameterServer2 addGradient /
    sendBackParameter).  Multi-device: true ICI AllReduce via shard_map psum;
    single chip (the bench environment): degenerates to an on-device
    pass-through, reported with devices=1."""
    import jax

    gbps, n = _allreduce_body(
        jax.devices(), words=32 * 1024 * 1024, chain=10, iters=10
    )
    return {
        "metric": "allreduce_bw_gbps",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "devices": n,
        "vs_baseline": round(gbps / TARGET_ALLREDUCE_GBPS, 4),
    }


def bench_allreduce_virtual8() -> dict:
    """The real multi-device AllReduce path on 8 virtual CPU devices (the
    single-chip metric above degenerates to an on-device copy): shard_map
    psum across an 8-way mesh with value verification, tracked round over
    round for scaling/regression — the loopback-cluster discipline of the
    reference (MultiGradientMachine.h:44-120 thread-ring, tested via
    in-process multi-port pservers).  The GB/s figure measures CPU
    emulation, not ICI: the metric name carries `correctness_only` so it is
    never read against the hardware-bandwidth baseline."""
    import jax

    cpus = jax.devices("cpu")[:8]
    gbps, n = _allreduce_body(cpus, words=4 * 1024 * 1024, chain=4, iters=5)
    return {
        "metric": "allreduce_psum_8dev_correctness_only_gbps",
        "value": round(gbps, 2),
        "unit": "GB/s (cpu-emulated; correctness gate, not a bandwidth claim)",
        "devices": n,
        "backend": "cpu-virtual",
        "vs_baseline": None,
    }


def bench_scaling_virtual8() -> dict:
    """Virtual-mesh weak-scaling record (VERDICT #10): the SAME dp train
    step (fixed global batch) timed on a 1-device vs an 8-device virtual
    CPU mesh — the loopback discipline of the reference's published 4-GPU
    table (benchmark/README.md:76-97, 3.85x at bs 512), minus the hardware.
    CPU emulation makes the speedup figure correctness-grade, not a scaling
    claim (the metric name says so, like allreduce_psum_8dev_correctness_
    only_gbps); what it guards is that the sharded step RUNS, SCALES the
    shard math correctly (first-step cost parity n=1 vs n=8) and never
    silently degenerates to a replicated loop."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.parallel.mesh import make_mesh, shard_batch
    from paddle_tpu.trainer.step import make_train_step

    cpus = jax.devices("cpu")[:8]
    # bench.py pins --xla_force_host_platform_device_count=8 before jax
    # initializes, so 8 virtual devices exist from the documented entry
    # points; degrade to whatever is there if imported into an
    # already-initialized process (the allreduce bench's discipline)
    n_hi = max(len(cpus), 1)
    rng = np.random.RandomState(0)
    d_in, d_h, classes, b = 256, 512, 16, 256
    xs = rng.randn(b, d_in).astype(np.float32)
    ys = rng.randint(0, classes, size=b).astype(np.int32)

    times, costs = {}, {}
    for n in (1, n_hi):
        reset_auto_names()
        x = paddle.layer.data("x", paddle.data_type.dense_vector(d_in))
        h = paddle.layer.fc(x, size=d_h, act=paddle.activation.Relu())
        h = paddle.layer.fc(h, size=d_h, act=paddle.activation.Relu())
        pred = paddle.layer.fc(h, size=classes, act=paddle.activation.Softmax())
        y = paddle.layer.data("y", paddle.data_type.integer_value(classes))
        cost = paddle.layer.classification_cost(input=pred, label=y)
        mesh = make_mesh(data=n, model=1, devices=cpus[:n])
        net = CompiledNetwork(Topology([cost]))
        params, state = net.init(jax.random.PRNGKey(0))
        # hand the cpu-mesh jit host arrays so placement follows its
        # in_shardings (init lands on the default backend, which may be the
        # real chip)
        params = jax.tree_util.tree_map(np.asarray, params)
        state = jax.tree_util.tree_map(np.asarray, state)
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        opt_state = jax.tree_util.tree_map(np.asarray, opt.init(params))
        step = make_train_step(net, opt, mesh)
        batch = shard_batch({"x": SeqTensor(xs), "y": SeqTensor(ys)}, mesh)
        params, state, opt_state, m = step(
            params, state, opt_state, batch, jax.random.PRNGKey(1)
        )
        costs[n] = float(m["cost"])
        iters = 20
        t0 = time.perf_counter()
        for i in range(iters):
            params, state, opt_state, m = step(
                params, state, opt_state, batch, jax.random.PRNGKey(i)
            )
        _sync(m)
        times[n] = (time.perf_counter() - t0) / iters * 1e3
    cost_delta = abs(costs[1] - costs[n_hi])
    assert cost_delta <= 1e-4 * max(1.0, abs(costs[1])), (
        f"dp shard math diverged: n=1 cost {costs[1]} vs n={n_hi} {costs[n_hi]}"
    )
    return {
        "metric": "scaling_virtual8_correctness_only",
        "value": round(times[1] / times[n_hi], 3),
        "unit": f"x n1/n{n_hi} step-time ratio (cpu-emulated; correctness "
        "gate, not a scaling claim)",
        "step_ms_n1": round(times[1], 2),
        f"step_ms_n{n_hi}": round(times[n_hi], 2),
        "global_batch": b,
        "cost_delta": float(f"{cost_delta:.3e}"),
        "devices": n_hi,
        "backend": "cpu-virtual",
        "vs_baseline": None,
    }


def bench_elastic_scaling() -> dict:
    """1→N multi-PROCESS scaling-efficiency curve over the elastic cluster
    plane (ROADMAP item 3, the MULTICHIP_r06 record): N real worker
    processes lease data-shard tasks from an HA master, contribute
    deterministic per-task gradients, fence + reduce per pass, and write
    sharded checkpoints.  Workers run the numpy model so the curve measures
    task compute + lease/RPC/fence coordination, not interpreter boot
    (per-worker work-phase timestamps bound the span).  CPU processes on an
    oversubscribed container make the absolute speedup correctness-grade;
    what the guard holds is that the protocol round-trips at N>=4 with
    per-N parameter equality (the N-invariance of the task-ordered
    reduction)."""
    import subprocess
    import sys
    import tempfile

    from paddle_tpu.io import recordio
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.master_ha import HAMaster

    base = tempfile.mkdtemp(prefix="elastic-bench-")
    rng = np.random.RandomState(0)
    dim, hidden, n_rec, passes = 256, 512, 16384, 2
    w_true = rng.randn(dim).astype(np.float32)
    data = os.path.join(base, "data.rio")
    recordio.write_records(
        data,
        (
            np.concatenate(
                [x := rng.randn(dim).astype(np.float32),
                 [np.float32(np.tanh(x @ w_true))]]
            ).astype(np.float32).tobytes()
            for _ in range(n_rec)
        ),
        max_chunk_records=64,
    )  # 256 chunks -> 32 tasks/pass at 8 chunks/task

    def run_fleet(n: int):
        d = os.path.join(base, f"n{n}")
        ck = os.path.join(d, "ck")
        ha = HAMaster(
            os.path.join(d, "ha"), [data], owner_id="bench-driver",
            lease_timeout=5.0, chunks_per_task=8, timeout_s=60.0,
            worker_timeout_s=5.0, auto_rotate=False,
            snapshot_min_interval_s=0.5,
        )
        ha.start()
        assert ha.wait_leader(30)
        # one BLAS thread per worker: otherwise a single process already
        # saturates every core and the process-scaling curve measures
        # oversubscription, not the cluster plane
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", OMP_NUM_THREADS="1",
            OPENBLAS_NUM_THREADS="1", MKL_NUM_THREADS="1",
        )
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.trainer.elastic",
                 "--dir", os.path.join(d, "ha"), "--worker-id", f"w{i}",
                 "--num-passes", str(passes), "--model", "numpy",
                 "--model-arg", f"dim={dim}",
                 "--model-arg", f"hidden={hidden}",
                 "--model-arg", "lr=0.01",
                 "--min-workers", str(n),
                 "--checkpoint-dir", ck,
                 "--stats-out", os.path.join(d, f"stats{i}.json")],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            for i in range(n)
        ]
        rcs = [p.wait() for p in procs]
        ha.stop()
        assert all(rc == 0 for rc in rcs), f"n={n}: worker rcs {rcs}"
        stats = []
        for i in range(n):
            with open(os.path.join(d, f"stats{i}.json")) as f:
                stats.append(json.load(f))
        span = max(s["t_work1"] for s in stats) - min(
            s["t_work0"] for s in stats
        )
        from paddle_tpu.trainer.elastic import NumpyLinearModel

        mgr = CheckpointManager(ck)
        restored = mgr.restore_latest(
            NumpyLinearModel(dim, hidden=hidden, seed=0).state()
        )
        assert restored is not None, f"n={n}: no committed manifest"
        return {
            "span_s": span,
            "records_per_s": n_rec * passes / max(span, 1e-9),
            "tasks": sum(s["tasks_done"] for s in stats),
            "params": restored[1],
        }

    curve = {}
    ref_params = None
    for n in (1, 2, 4):
        r = run_fleet(n)
        if ref_params is None:
            ref_params = r["params"]
        else:
            assert np.array_equal(ref_params["w"], r["params"]["w"]), (
                f"n={n}: reduction is not N-invariant"
            )
        curve[n] = {
            "span_s": round(r["span_s"], 3),
            "records_per_s": round(r["records_per_s"], 1),
        }
    speedup = curve[4]["records_per_s"] / curve[1]["records_per_s"]
    cores = os.cpu_count() or 1
    return {
        "metric": "elastic_scaling_4proc_correctness_only",
        "value": round(speedup, 3),
        "unit": "x n4/n1 records/s (cpu multi-process; correctness gate + "
        "N-invariance proof, not a scaling claim)",
        "efficiency_4proc": round(speedup / min(4, cores), 3),
        "host_cores": cores,
        "curve": curve,
        "n_records": n_rec,
        "passes": passes,
        "backend": "cpu-multiprocess",
        "vs_baseline": None,
    }


def bench_quantized() -> list:
    """Quantized-collectives round (ISSUE 16, the EQuARX recipe,
    arXiv:2506.17615): block-scaled int8 gradient traffic on BOTH result
    planes plus int8 weight-only serving, each as an explicit f32-vs-
    quantized A/B with its reduction gate asserted in-run.

    * quantized_allreduce_virtual8 — the REAL dp train step (flag off vs
      on) on the 8-device virtual mesh: per-step gradient wire bytes drop
      >= 3x by block-scale arithmetic (1 byte/elt + 4/block vs 4), the
      10-step cost trajectory stays within 5%, and the step still runs in
      the same order (cpu emulation makes the time ratio correctness-
      grade, like every *_virtual8 metric);
    * elastic_quantized_wire_bytes — a 2-worker fleet A/B over the REAL
      RPC plane, gated on the measured per-pass master_wire byte counters
      (wire_bytes_per_pass in the worker summaries), not arithmetic;
    * serving_int8_weights — resident decode-weight bytes >= 3x down,
      slots-per-GB up, dequantization drift inside the
      serving_int8_drift_budget flag."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.ops import quantize as bsq
    from paddle_tpu.parallel.mesh import make_mesh, shard_batch
    from paddle_tpu.trainer.step import make_train_step
    from paddle_tpu.utils import flags as _flags

    results = []

    # -- arm 1: in-graph quantized allreduce A/B --------------------------
    cpus = jax.devices("cpu")[:8]
    n = max(len(cpus), 1)
    rng = np.random.RandomState(0)
    d_in, d_h, classes, b = 256, 512, 16, 256
    xs = rng.randn(b, d_in).astype(np.float32)
    ys = rng.randint(0, classes, size=b).astype(np.int32)
    mesh = make_mesh(data=n, model=1, devices=cpus[:n])

    def build_arm(quantized):
        reset_auto_names()
        x = paddle.layer.data("x", paddle.data_type.dense_vector(d_in))
        h = paddle.layer.fc(x, size=d_h, act=paddle.activation.Relu())
        pred = paddle.layer.fc(h, size=classes,
                               act=paddle.activation.Softmax())
        y = paddle.layer.data("y", paddle.data_type.integer_value(classes))
        cost = paddle.layer.classification_cost(input=pred, label=y)
        net = CompiledNetwork(Topology([cost]))
        params, state = net.init(jax.random.PRNGKey(0))
        params = jax.tree_util.tree_map(np.asarray, params)
        state = jax.tree_util.tree_map(np.asarray, state)
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        opt_state = jax.tree_util.tree_map(np.asarray, opt.init(params))
        step = make_train_step(net, opt, mesh, quantized=quantized)
        batch = shard_batch({"x": SeqTensor(xs), "y": SeqTensor(ys)}, mesh)
        return step, params, state, opt_state, batch

    arm = {}
    for quantized in (False, True):
        step, params, state, opt_state, batch = build_arm(quantized)
        costs = []
        for i in range(10):  # fixed batch: trajectory A/B, warm after i=0
            params, state, opt_state, m = step(
                params, state, opt_state, batch, jax.random.PRNGKey(i)
            )
            costs.append(_sync(m))
        iters = 20
        t0 = time.perf_counter()
        for i in range(iters):
            params, state, opt_state, m = step(
                params, state, opt_state, batch, jax.random.PRNGKey(i)
            )
        _sync(m)
        arm[quantized] = {
            "costs": costs,
            "ms": (time.perf_counter() - t0) / iters * 1e3,
            "params": params,
        }
    cost_rel = abs(arm[True]["costs"][-1] - arm[False]["costs"][-1]) / max(
        abs(arm[False]["costs"][-1]), 1e-9
    )
    assert cost_rel <= 0.05, (
        f"quantized trajectory diverged: {arm[False]['costs'][-1]} vs "
        f"{arm[True]['costs'][-1]}"
    )
    # gradient wire bytes by block-scale arithmetic over the REAL grad tree
    block = int(_flags.get_flag("quantize_block_size"))
    f32_bytes = q_bytes = 0
    for leaf in jax.tree_util.tree_leaves(arm[False]["params"]):
        sz = int(np.asarray(leaf).size)
        f32_bytes += 4 * sz
        q_bytes += sz + 4 * ((sz + block - 1) // block)
    wire_reduction = f32_bytes / q_bytes
    assert wire_reduction >= 3.0, f"allreduce wire reduction {wire_reduction}"
    results.append({
        "metric": "quantized_allreduce_virtual8_wire_reduction",
        "value": round(wire_reduction, 3),
        "unit": "x grad wire bytes f32/int8 (block-scale arithmetic over "
        "the live grad tree; >= 3x gate asserted)",
        "grad_bytes_f32": f32_bytes,
        "grad_bytes_int8": q_bytes,
        "block": block,
        "step_ms_f32": round(arm[False]["ms"], 2),
        "step_ms_int8": round(arm[True]["ms"], 2),
        "final_cost_rel_delta": float(f"{cost_rel:.3e}"),
        "devices": n,
        "backend": "cpu-virtual",
        "vs_baseline": None,
    })

    # -- arm 2: elastic fleet wire bytes, measured ------------------------
    import subprocess
    import sys
    import tempfile

    from paddle_tpu.io import recordio
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.master_ha import HAMaster
    from paddle_tpu.trainer.elastic import NumpyLinearModel

    base = tempfile.mkdtemp(prefix="quant-bench-")
    dim, hidden, n_rec, passes, n_workers = 256, 512, 4096, 2, 2
    w_true = np.random.RandomState(0).randn(dim).astype(np.float32)
    data = os.path.join(base, "data.rio")
    rng = np.random.RandomState(1)
    recordio.write_records(
        data,
        (
            np.concatenate(
                [x := rng.randn(dim).astype(np.float32),
                 [np.float32(np.tanh(x @ w_true))]]
            ).astype(np.float32).tobytes()
            for _ in range(n_rec)
        ),
        max_chunk_records=64,
    )

    def run_fleet(quantized: bool):
        d = os.path.join(base, "q" if quantized else "f")
        ha = HAMaster(
            os.path.join(d, "ha"), [data], owner_id="bench-driver",
            lease_timeout=5.0, chunks_per_task=8, timeout_s=60.0,
            worker_timeout_s=5.0, auto_rotate=False,
            snapshot_min_interval_s=0.5,
        )
        ha.start()
        assert ha.wait_leader(30)
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", OMP_NUM_THREADS="1",
            OPENBLAS_NUM_THREADS="1", MKL_NUM_THREADS="1",
        )
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.trainer.elastic",
                 "--dir", os.path.join(d, "ha"), "--worker-id", f"w{i}",
                 "--num-passes", str(passes), "--model", "numpy",
                 "--model-arg", f"dim={dim}",
                 "--model-arg", f"hidden={hidden}",
                 "--model-arg", "lr=0.01",
                 "--min-workers", str(n_workers),
                 "--checkpoint-dir", os.path.join(d, "ck"),
                 "--stats-out", os.path.join(d, f"stats{i}.json")]
                + (["--quantized-grads"] if quantized else []),
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            for i in range(n_workers)
        ]
        rcs = [p.wait() for p in procs]
        ha.stop()
        assert all(rc == 0 for rc in rcs), f"worker rcs {rcs}"
        stats = []
        for i in range(n_workers):
            with open(os.path.join(d, f"stats{i}.json")) as f:
                stats.append(json.load(f))
        mgr = CheckpointManager(os.path.join(d, "ck"))
        restored = mgr.restore_latest(
            NumpyLinearModel(dim, hidden=hidden, seed=0).state()
        )
        assert restored is not None
        wire_pp = [w for s in stats for w in s["wire_bytes_per_pass"]]
        return {
            "wire_bytes_per_pass": float(np.mean(wire_pp)),
            "grad_payload_bytes": sum(s["grad_payload_bytes"]
                                      for s in stats),
            "quantized": all(s["quantized_grads"] for s in stats),
            "params": restored[1],
        }

    f32_fleet = run_fleet(False)
    q_fleet = run_fleet(True)
    assert q_fleet["quantized"] and not f32_fleet["quantized"]
    wire_ratio = (
        f32_fleet["wire_bytes_per_pass"] / q_fleet["wire_bytes_per_pass"]
    )
    payload_ratio = (
        f32_fleet["grad_payload_bytes"] / q_fleet["grad_payload_bytes"]
    )
    assert wire_ratio >= 3.0, (
        f"elastic wire-bytes-per-pass reduction {wire_ratio:.2f}x < 3x "
        f"({f32_fleet['wire_bytes_per_pass']:.0f} -> "
        f"{q_fleet['wire_bytes_per_pass']:.0f})"
    )
    # both arms learned the same regression target (quantization error is
    # a small perturbation, not a different trajectory)
    wf, wq = f32_fleet["params"]["w"], q_fleet["params"]["w"]
    w_rel = float(
        np.linalg.norm(wf - wq) / max(np.linalg.norm(wf), 1e-9)
    )
    assert w_rel < 0.05, f"fleet params diverged: rel {w_rel}"
    results.append({
        "metric": "elastic_quantized_wire_bytes_reduction",
        "value": round(wire_ratio, 3),
        "unit": "x measured wire bytes/pass f32/int8 (master_wire "
        "counters, 2-worker fleet; >= 3x gate asserted)",
        "wire_bytes_per_pass_f32": round(f32_fleet["wire_bytes_per_pass"]),
        "wire_bytes_per_pass_int8": round(q_fleet["wire_bytes_per_pass"]),
        "grad_payload_reduction": round(payload_ratio, 3),
        "param_rel_delta": float(f"{w_rel:.3e}"),
        "workers": n_workers,
        "passes": passes,
        "backend": "cpu-multiprocess",
        "vs_baseline": None,
    })

    # -- arm 3: serving int8 weight-only ----------------------------------
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
    from paddle_tpu.serving import ServingEngine

    V, E, H, MAXLEN = 256, 48, 64, 16

    def build_engine(int8):
        reset_auto_names()
        cost, _ = seq2seq_cost(V, V, word_dim=E, hidden_dim=H)
        params = paddle.parameters.create(cost, seed=7)
        gen = Seq2SeqGenerator(
            params, V, V, word_dim=E, hidden_dim=H,
            bos_id=0, eos_id=1, max_length=MAXLEN,
        )
        return ServingEngine(gen, max_slots=8, hbm_budget_mb=4,
                             max_new_tokens=MAXLEN, int8_weights=int8)

    f32_eng = build_engine(False)
    q_eng = build_engine(True)
    weight_ratio = f32_eng.weight_bytes / q_eng.weight_bytes
    drift = q_eng.weight_drift()
    budget = float(_flags.get_flag("serving_int8_drift_budget"))
    assert weight_ratio >= 3.0, f"weight bytes ratio {weight_ratio}"
    assert 0.0 < drift < budget, (drift, budget)
    slots_f32 = f32_eng.slots_per_gb(16)
    slots_q = q_eng.slots_per_gb(16)
    assert slots_q > slots_f32
    srcs = [np.random.RandomState(3).randint(2, V, size=8).tolist()
            for _ in range(4)]
    outs_q = [q_eng.reference_decode(s, MAXLEN) for s in srcs]
    assert all(len(o) > 0 for o in outs_q)
    results.append({
        "metric": "serving_int8_weight_bytes_reduction",
        "value": round(weight_ratio, 3),
        "unit": "x resident decode-weight bytes f32/int8 (>= 3x gate "
        "asserted; drift gated against serving_int8_drift_budget)",
        "weight_bytes_f32": int(f32_eng.weight_bytes),
        "weight_bytes_int8": int(q_eng.weight_bytes),
        "slots_per_gb_f32": round(slots_f32, 1),
        "slots_per_gb_int8": round(slots_q, 1),
        "weight_drift": float(f"{drift:.3e}"),
        "drift_budget": budget,
        "vs_baseline": None,
    })
    return results


def bench_master_failover() -> dict:
    import shutil
    import tempfile

    base = tempfile.mkdtemp(prefix="failover-bench-")
    try:
        return _bench_master_failover_in(base)
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _bench_master_failover_in(base: str) -> dict:
    """Recovery-time-after-fault for the cluster plane (ROADMAP item 5's
    first entry, the MULTICHIP_r07 record; metric vocabulary from the
    Gemma serving comparison, arXiv:2605.25645): kill -9 the LEADER master
    mid-pass under a live 4-worker fleet and measure the warm takeover.

    The leader journals every transition (master_journal.py) and a hot
    standby tails snapshot + journal into a live replica; the ``kill_
    master`` chaos point SIGKILLs the leader inside ``task_finished``
    BEFORE the transition executes.  Reported: takeover time from the
    observed leader death to the standby serving (includes lease-staleness
    detection — the honest recovery span), journal records replayed, and
    recomputed tasks, which the bench ASSERTS to be zero: every task of
    every pass is computed exactly once fleet-wide despite the bounce."""
    import subprocess
    import sys

    from paddle_tpu.io import recordio
    from paddle_tpu.master_ha import HAMaster, discover_endpoint

    rng = np.random.RandomState(0)
    dim, n_rec, passes, n_workers = 64, 2048, 2, 4
    w_true = rng.randn(dim).astype(np.float32)
    data = os.path.join(base, "data.rio")
    recordio.write_records(
        data,
        (
            np.concatenate(
                [x := rng.randn(dim).astype(np.float32),
                 [np.float32(np.tanh(x @ w_true))]]
            ).astype(np.float32).tobytes()
            for _ in range(n_rec)
        ),
        max_chunk_records=16,
    )  # 128 chunks -> 16 tasks/pass at 8 chunks/task
    tasks_per_pass = 16
    hadir = os.path.join(base, "ha")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", OMP_NUM_THREADS="1",
        OPENBLAS_NUM_THREADS="1", MKL_NUM_THREADS="1",
    )
    lease_timeout = 6.0  # wide: a loaded box must not pre-empt the drill
    leader = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "master",
         "--dir", hadir, "--patterns", data,
         "--chunks-per-task", "8", "--timeout-s", "60",
         "--worker-timeout-s", "15",
         "--lease-timeout", str(lease_timeout),
         "--chaos", "kill_master@10"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    standby = HAMaster(
        hadir, [data], owner_id="bench-standby", chunks_per_task=8,
        timeout_s=60.0, worker_timeout_s=15.0, auto_rotate=False,
        lease_timeout=lease_timeout,
    )
    procs = []
    try:
        deadline = time.time() + 60
        while discover_endpoint(hadir) is None:
            assert leader.poll() is None, "leader master died on boot"
            assert time.time() < deadline, "no leader endpoint"
            time.sleep(0.05)
        standby.start()
        while standby._replica is None:  # warm takeover or bust
            assert time.time() < deadline, "standby never built a replica"
            time.sleep(0.05)
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.trainer.elastic",
                 "--dir", hadir, "--worker-id", f"w{i}",
                 "--num-passes", str(passes), "--model", "numpy",
                 "--model-arg", f"dim={dim}", "--model-arg", "lr=0.05",
                 "--min-workers", str(n_workers),
                 "--stats-out", os.path.join(base, f"stats{i}.json")],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            for i in range(n_workers)
        ]
        while leader.poll() is None:  # the chaos point fires mid-pass 0
            assert time.time() < deadline, "kill_master chaos never fired"
            time.sleep(0.005)
        t_kill = time.time()
        rcs = [p.wait(timeout=300) for p in procs]
        assert all(rc == 0 for rc in rcs), f"worker rcs {rcs}"
        assert standby.is_leader.is_set(), "standby never took over"
        takeover = dict(standby.last_takeover)
        master_stats = standby.service.stats()
    finally:
        standby.stop()
        if leader.poll() is None:
            leader.kill()
        leader.wait()
    stats = []
    for i in range(n_workers):
        with open(os.path.join(base, f"stats{i}.json")) as f:
            stats.append(json.load(f))
    total_acks = sum(s["tasks_done"] for s in stats)
    recomputed = total_acks - tasks_per_pass * passes
    assert recomputed == 0, (
        f"{recomputed} task(s) recomputed across the failover"
    )
    assert master_stats["fail_events"] == 0
    recovery_s = takeover["t_leader"] - t_kill
    return {
        "metric": "master_failover_recovery_ms",
        "value": round(recovery_s * 1000.0, 1),
        "unit": "ms kill-9-to-serving (lease detection + campaign + journal "
        "replay; warm standby, cpu container)",
        "takeover_replay_s": round(takeover["takeover_s"], 4),
        "replayed_records": takeover["replayed_records"],
        "recomputed_tasks": recomputed,
        "warm": takeover["warm"],
        "lease_timeout_s": lease_timeout,
        "n_workers": n_workers,
        "tasks_per_pass": tasks_per_pass,
        "passes": passes,
        "fail_events": master_stats["fail_events"],
        "backend": "cpu-multiprocess",
        "vs_baseline": None,
    }


# ---------------------------------------------------------------------------
# Regression guard — diff every metric against the best committed prior
# round (the reference keeps its whole perf table as one versioned artifact,
# benchmark/README.md; here every BENCH_r*.json in the repo is the history)
# ---------------------------------------------------------------------------

def bench_aot_warm_boot() -> list:
    """Dispatch-elimination record (core/aot_cache.py + the whole-pass
    epoch program): two guarded metrics.

    ``aot_warm_boot_compile_ms`` — a fresh process prewarms a flagship MLP
    config's train step through ``paddle-tpu cache warm`` twice against one
    cache dir: run 1 is the cold boot (full XLA compiles, serialized to
    disk), run 2 the warm boot (deserialize only).  The value is the warm
    run's compile-path wall time; the record asserts zero compiles on the
    warm boot and carries the cold/warm ratio (acceptance: warm <= 0.5x
    cold, or the labeled no-serialization shim path on jax builds without
    executable serialization).

    ``whole_pass_dispatches_per_epoch`` — cached epochs >= 2 under
    ``whole_pass_program`` run as ONE lax.scan dispatch; the in-process A/B
    counts host dispatches per cached epoch and times the stepwise replay
    against the epoch program on the same sealed pass."""
    import shutil
    import subprocess
    import sys
    import tempfile

    import jax

    results = []
    tmp = tempfile.mkdtemp(prefix="aot_bench_")
    try:
        with open(os.path.join(tmp, "conf.py"), "w") as f:
            f.write(
                "from paddle.trainer_config_helpers import *\n"
                "define_py_data_sources2(train_list='t', test_list=None,\n"
                "                        module='prov', obj='process')\n"
                "settings(batch_size=32, learning_rate=1e-3,\n"
                "         learning_method=AdamOptimizer())\n"
                "img = data_layer(name='pixel', size=784)\n"
                "h1 = fc_layer(input=img, size=128, act=ReluActivation())\n"
                "h2 = fc_layer(input=h1, size=64, act=ReluActivation())\n"
                "pred = fc_layer(input=h2, size=10,\n"
                "                act=SoftmaxActivation())\n"
                "lbl = data_layer(name='label', size=10)\n"
                "outputs(classification_cost(input=pred, label=lbl))\n"
            )
        with open(os.path.join(tmp, "prov.py"), "w") as f:
            f.write(
                "from paddle.trainer.PyDataProvider2 import *\n"
                "@provider(input_types=[dense_vector(784),\n"
                "                       integer_value(10)],\n"
                "          should_shuffle=False)\n"
                "def process(settings, f):\n"
                "    for i in range(100):\n"  # 32x3 + a 4-row tail: 2 rungs
                "        yield [0.01 * (i % 7)] * 784, i % 10\n"
            )
        with open(os.path.join(tmp, "t"), "w") as f:
            f.write("dummy\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.abspath(__file__))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        env.setdefault("JAX_PLATFORMS", "cpu")

        def boot():
            r = subprocess.run(
                [sys.executable, "-m", "paddle_tpu", "cache", "warm",
                 "--dir", os.path.join(tmp, "cache"),
                 "--config", os.path.join(tmp, "conf.py")],
                capture_output=True, text=True, env=env, timeout=600,
            )
            assert r.returncode == 0, r.stderr[-2000:]
            return json.loads(r.stdout.strip().splitlines()[-1])

        cold = boot()
        warm = boot()
        from paddle_tpu.core.aot_cache import serialization_available

        shim = not serialization_available()
        ratio = warm["warm_s"] / max(cold["warm_s"], 1e-9)
        note = (
            "no executable serialization in this jax build: warm boot == "
            "cold boot (shim no-op parity path; counters stay zero)"
            if shim else
            f"warm boot deserialized {warm['loads']} executable(s) with "
            f"{warm['compiles']} compiles vs {cold['compiles']} cold "
            f"compiles ({cold['warm_s']:.2f}s -> {warm['warm_s']:.2f}s)"
        )
        results.append({
            "metric": "aot_warm_boot_compile_ms",
            "value": round(warm["warm_s"] * 1e3, 1),
            "unit": "ms",
            "cold_compile_ms": round(cold["warm_s"] * 1e3, 1),
            "warm_vs_cold_ratio": round(ratio, 4),
            "meets_0p5x": bool(shim or ratio <= 0.5),
            "cold_compiles": cold["compiles"],
            "warm_compiles": warm["compiles"],
            "warm_loads": warm["loads"],
            "shapes": cold["shapes"],
            "serialization_shim": shim,
            "note": note,
        })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # -- whole-pass epoch program: dispatches + ms per cached epoch -------
    import paddle_tpu as paddle
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.utils.flags import reset_flags, set_flag
    from paddle_tpu.utils.timers import global_stats

    def _model():
        reset_auto_names()
        x = paddle.layer.data("x", paddle.data_type.dense_vector(64))
        h = paddle.layer.fc(x, size=128, act=paddle.activation.Relu())
        pred = paddle.layer.fc(h, size=10, act=paddle.activation.Softmax())
        y = paddle.layer.data("y", paddle.data_type.integer_value(10))
        return paddle.layer.classification_cost(input=pred, label=y)

    rng = np.random.RandomState(0)
    samples = [
        (rng.randn(64).astype(np.float32), int(rng.randint(10)))
        for _ in range(512)
    ]

    def run(whole_pass: bool, passes: int = 4):
        reset_flags()
        global_stats.reset()
        set_flag("cache_pass_in_mem", True)
        if whole_pass:
            set_flag("whole_pass_program", True)
        cost = _model()
        params = paddle.parameters.create(cost, seed=0)
        tr = paddle.trainer.SGD(
            cost=cost, parameters=params, seed=0,
            update_equation=paddle.optimizer.Adam(learning_rate=1e-3),
        )

        def reader():
            yield from samples

        t_by_pass = {}
        t0 = time.perf_counter()

        def handler(ev):
            nonlocal t0
            if isinstance(ev, paddle.event.EndPass):
                t_by_pass[ev.pass_id] = time.perf_counter() - t0
                t0 = time.perf_counter()

        tr.train(reader=paddle.batch(reader, 32), num_passes=passes,
                 event_handler=handler, async_load_data=False)
        disp = global_stats.count("epoch_program/dispatches")
        reset_flags()
        # cached epochs only (pass 1 streams + captures in both arms)
        cached_ms = [v * 1e3 for p, v in sorted(t_by_pass.items()) if p >= 1]
        return cached_ms, disp, tr._pass_cache.n_batches

    step_ms, _, n_batches = run(False)
    prog_ms, dispatches, _ = run(True)
    cached_epochs = len(prog_ms)
    results.append({
        "metric": "whole_pass_dispatches_per_epoch",
        "value": round(dispatches / max(cached_epochs, 1), 2),
        "unit": "dispatches/epoch",
        "stepwise_dispatches_per_epoch": n_batches,
        "stepwise_cached_epoch_ms": round(float(np.median(step_ms)), 2),
        "program_cached_epoch_ms": round(float(np.median(prog_ms)), 2),
        "cached_epochs_timed": cached_epochs,
        "note": "cached epochs >= 2 under whole_pass_program run as one "
        "lax.scan dispatch over the stacked pass cache (bit-exact vs "
        "stepwise, tests/test_epoch_program.py); stepwise pays one host "
        "dispatch per batch",
    })
    return results


REGRESSION_TOLERANCE = 0.05  # >5% worse than best prior = flagged


def load_prior_bench(repo_dir: str) -> dict:
    """{metric: [(round, value), ...]} harvested from the committed
    BENCH_r*.json round artifacts.  Tolerates every historic schema: r05+
    store the compact ALL line under parsed.results; earlier rounds only
    kept the stdout tail — scrape its per-metric JSON lines."""
    import glob
    import re

    prior: dict = {}
    paths = sorted(glob.glob(os.path.join(repo_dir, "BENCH_r*.json")))
    # scenario-gate rounds ride the same guard (SCENARIO_r12.json+), and
    # the obs-plane overhead rounds (OBS_r13.json+)
    paths += sorted(glob.glob(os.path.join(repo_dir, "SCENARIO_r*.json")))
    paths += sorted(glob.glob(os.path.join(repo_dir, "OBS_r*.json")))
    for path in paths:
        rnd = os.path.basename(path).split("_", 1)[1][:-len(".json")]
        try:
            with open(path) as f:
                d = json.load(f)
        except Exception:
            continue
        found: dict = {}
        p = d.get("parsed")
        if isinstance(p, dict) and isinstance(p.get("results"), list):
            for r in p["results"]:
                if isinstance(r, dict) and isinstance(
                    r.get("value"), (int, float)
                ):
                    found[r.get("metric")] = float(r["value"])
        elif isinstance(p, dict) and isinstance(p.get("value"), (int, float)):
            found[p.get("metric")] = float(p["value"])
        for m, v in re.findall(
            r'"metric": "([a-z0-9_]+)", "value": ([0-9.eE+-]+)',
            d.get("tail", ""),
        ):
            try:
                found.setdefault(m, float(v))
            except ValueError:
                pass
        for m, v in found.items():
            if m:
                prior.setdefault(m, []).append((rnd, v))
    return prior


def regression_fields(metric: str, value, unit, prior: dict) -> dict:
    """best_prior / regressed_vs_best fields for one fresh result.  Lower
    is better for ms metrics, higher for every rate; correctness-only
    metrics (cpu-emulated bandwidth) are exempt — their value is noise.

    A NON-FINITE value is a hard regression regardless of history: NaN
    compares false against every threshold, so before this guard a bench
    that started emitting NaN sailed through `delta > tolerance` as
    "not regressed" — the exact silent-pass the numerics plane exists to
    kill."""
    if isinstance(value, (int, float)) and not math.isfinite(value):
        return {"regressed_vs_best": True, "non_finite": True}
    hist = prior.get(metric)
    if not hist or not isinstance(value, (int, float)) or value <= 0:
        return {}
    if "correctness_only" in metric:
        return {}
    lower_better = "ms" in (unit or "") or metric.endswith("ms_per_batch")
    if lower_better:
        best_round, best = min(hist, key=lambda rv: rv[1])
        delta = (value - best) / best
    else:
        best_round, best = max(hist, key=lambda rv: rv[1])
        delta = (best - value) / best
    return {
        "best_prior": best,
        "best_prior_round": best_round,
        "delta_vs_best_pct": round(delta * 100.0, 2),
        "regressed_vs_best": bool(delta > REGRESSION_TOLERANCE),
    }


def build_guard(results: list) -> dict:
    """The REGRESSION_GUARD summary line.  Non-finite metrics report in
    their own `non_finite` list (hard regressions with no best_prior to
    compare against) so a NaN bench is unmissable in the tail."""
    regressed = [
        {
            "metric": r["metric"],
            "value": r.get("value"),
            "best_prior": r.get("best_prior"),
            "best_prior_round": r.get("best_prior_round"),
            "delta_vs_best_pct": r.get("delta_vs_best_pct"),
        }
        for r in results
        if r.get("regressed_vs_best") and not r.get("non_finite")
    ]
    non_finite = [
        {"metric": r["metric"], "value": repr(r.get("value"))}
        for r in results
        if r.get("non_finite")
    ]
    return {
        "metric": "REGRESSION_GUARD",
        "checked": sum(1 for r in results if "regressed_vs_best" in r),
        "tolerance_pct": REGRESSION_TOLERANCE * 100.0,
        "regressed": regressed,
        "non_finite": non_finite,
    }


def main() -> None:
    """One JSON line per metric as each finishes (live progress), the full
    set mirrored to bench_results.json, and — LAST — one compact JSON line
    with every metric.  The driver keeps only the tail of stdout (r04 lost
    the resnet/nmt headlines to a 2000-char tail), so the final line alone
    must carry the whole table, like the reference keeps its entire
    benchmark table in one artifact (benchmark/README.md).  Every metric
    carries best_prior/regressed_vs_best guard fields against the committed
    BENCH_r*.json history; a REGRESSION_GUARD line sums them up."""
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    prior = load_prior_bench(repo_dir)
    results = []
    for fn in (bench_resnet, bench_nmt, bench_nmt_generate, bench_serving,
               bench_decode_speed, bench_fleet_serving,
               bench_scenarios, bench_tracing_overhead,
               bench_allreduce,
               bench_allreduce_virtual8, bench_scaling_virtual8,
               bench_elastic_scaling, bench_quantized,
               bench_master_failover,
               bench_aot_warm_boot,
               bench_transformer,
               bench_transformer_long_context, bench_transformer_xl_context,
               bench_lstm_textcls,
               bench_alexnet, bench_googlenet, bench_smallnet,
               bench_resnet_pipeline):
        try:
            rs = fn()
        except Exception as e:  # keep later metrics alive if one fails
            rs = {"metric": fn.__name__, "error": repr(e)[:500]}
        # a bench may emit several guarded metrics (the pipeline's
        # first-epoch / cached-epoch split)
        for r in rs if isinstance(rs, list) else [rs]:
            r.update(
                regression_fields(
                    r.get("metric", ""), r.get("value"), r.get("unit"), prior
                )
            )
            results.append(r)
            print(json.dumps(r), flush=True)
    results.append(build_guard(results))
    print(json.dumps(results[-1]), flush=True)
    with open(os.path.join(repo_dir, "bench_results.json"), "w") as f:
        json.dump(results, f, indent=1)
    # the tail-proof summary must fit inside the driver's 2000-char tail:
    # headline fields only (full detail lives above and in
    # bench_results.json)
    compact = []
    for r in results:
        if r.get("metric") == "REGRESSION_GUARD":
            compact.append({
                "metric": "REGRESSION_GUARD",
                "regressed": [g["metric"] for g in r["regressed"]],
                # the tail is often the only surviving output — a NaN
                # bench must be visible HERE, not only in the full log
                "non_finite": [g["metric"] for g in r.get("non_finite", ())],
            })
            continue
        c = {"metric": r.get("metric")}
        for k in ("value", "vs_baseline", "mfu", "error"):
            if r.get(k) is not None:
                c[k] = r[k]
        if r.get("regressed_vs_best"):
            c["regressed_vs_best"] = True
        compact.append(c)
    print(json.dumps({"metric": "ALL", "results": compact},
                     separators=(",", ":")), flush=True)


if __name__ == "__main__":
    main()
