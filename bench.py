"""Benchmark driver: ResNet-50 training throughput (images/sec/chip) on the
ambient accelerator — the BASELINE.json headline metric.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline compares against the reference's 4×K40m AlexNet-era numbers only
indirectly; the north-star target is 0.8× A100 ≈ ~1400 img/s/chip for
ResNet-50 bf16 (A100 ~1750 img/s reported widely); we report the ratio vs
that target.
"""

from __future__ import annotations

import json
import time

import numpy as np

TARGET_IMG_S = 1400.0  # 0.8x per-chip A100 ResNet-50 throughput (north star)


def main() -> None:
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.models.resnet import resnet_cost
    from paddle_tpu.trainer.step import make_train_step

    reset_auto_names()
    batch_size = 64
    img_size = 224

    cost, _ = resnet_cost(depth=50, class_num=1000, img_size=img_size)
    topo = Topology([cost])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    opt_state = opt.init(params)
    step = make_train_step(net, opt, mesh=None)

    rng = np.random.RandomState(0)
    from paddle_tpu.core.batch import SeqTensor

    batch = {
        "image": SeqTensor(
            jax.device_put(
                rng.randn(batch_size, img_size * img_size * 3).astype(np.float32)
            )
        ),
        "label": SeqTensor(
            jax.device_put(rng.randint(0, 1000, size=batch_size).astype(np.int32))
        ),
    }
    key = jax.random.PRNGKey(1)

    # warmup / compile.  NB: sync via host fetch of the cost scalar —
    # jax.block_until_ready returns early on the experimental axon backend,
    # and a device->host read is a true execution barrier everywhere.
    params, state, opt_state, metrics = step(params, state, opt_state, batch, key)
    float(metrics["cost"])

    iters = 40
    t0 = time.perf_counter()
    for _ in range(iters):
        params, state, opt_state, metrics = step(params, state, opt_state, batch, key)
    float(metrics["cost"])
    dt = time.perf_counter() - t0

    img_per_sec = batch_size * iters / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": round(img_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(img_per_sec / TARGET_IMG_S, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
