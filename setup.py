"""Packaging for paddle_tpu (the reference ships cmake + a python sdist;
here one setuptools config installs the pure-python package plus the native
recordio source, which paddle_tpu.io.recordio compiles on demand with the
host compiler)."""

import os

from setuptools import find_packages, setup

HERE = os.path.dirname(os.path.abspath(__file__))


def readme() -> str:
    path = os.path.join(HERE, "README.md")
    return open(path).read() if os.path.exists(path) else ""


setup(
    name="paddle-tpu",
    version="0.1.0",
    description="TPU-native deep-learning framework with the PaddlePaddle v1/v2 API surface",
    long_description=readme(),
    long_description_content_type="text/markdown",
    packages=find_packages(include=["paddle_tpu", "paddle_tpu.*"]),
    # native recordio source ships inside the package; compiled lazily at
    # first use (paddle_tpu/io/recordio.py), with a pure-python fallback
    package_data={"paddle_tpu": ["native/recordio.cc", "native/protodata.cc"]},
    include_package_data=True,
    # the reference's `paddle` shell wrapper (submit_local.sh.in) — here a
    # console script: `paddle-tpu train --config=... --save_dir=...`
    entry_points={
        "console_scripts": ["paddle-tpu=paddle_tpu.cli:main"],
    },
    python_requires=">=3.11",  # BaseException.add_note in the error path
    install_requires=[
        "jax",
        "numpy",
    ],
    extras_require={
        "test": ["pytest", "chex"],
    },
)
