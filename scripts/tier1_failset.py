#!/usr/bin/env python
"""Tier-1 failure-SET snapshot — CI compares which tests fail, not how many.

The tier-1 gate (ROADMAP.md) historically compared failure *counts* against
the seed baseline, which lets a PR trade one fixed test for one newly broken
test invisibly.  This script snapshots the exact set of failing test ids to
``tests/tier1_failures_baseline.txt`` and diffs the current run against it:

  python scripts/tier1_failset.py --check --from-log /tmp/_t1.log
      parse an existing ``pytest -q`` log (fast; no re-run) and fail (exit
      1) on any test failing that is not in the committed baseline.  Tests
      that now PASS are reported as improvements (exit 0) with a reminder
      to re-snapshot.

  python scripts/tier1_failset.py --check
      run the tier-1 suite itself first (the ROADMAP command), then diff.

  python scripts/tier1_failset.py --update [--from-log ...]
      rewrite the baseline from the run/log.

  python scripts/tier1_failset.py --slow-guard
      verify that the multi-process e2e files (SLOW_ONLY_FILES) collect
      ZERO tests under the tier-1 ``-m "not slow"`` filter — a forgotten
      slow mark would drag multi-process process-spawning runs into the
      fast tier and break its time budget.

Log format: the ``FAILED <nodeid>[ - msg]`` / ``ERROR <nodeid>`` lines of
pytest's short test summary (printed by default, including under ``-q``).
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tests", "tier1_failures_baseline.txt")

# the ROADMAP.md tier-1 command, minus the pipefail/dots accounting
TIER1_CMD = [
    sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
    "--continue-on-collection-errors", "-p", "no:cacheprovider",
    "-p", "no:xdist", "-p", "no:randomly",
]

_LINE = re.compile(r"^(FAILED|ERROR)\s+(.+)$")

# test files whose EVERY test must stay out of the tier-1 fast tier (they
# spawn fleets of python processes); enforced by --slow-guard in CI
SLOW_ONLY_FILES = [
    "tests/test_elastic_e2e.py",
    "tests/test_master_failover_e2e.py",
    "tests/test_serving_e2e.py",
    "tests/test_scenarios_e2e.py",
    "tests/test_obs_e2e.py",
    "tests/test_netem_e2e.py",
    "tests/test_quantized_e2e.py",
    "tests/test_decode_speed_e2e.py",
    "tests/test_fleet_serving_e2e.py",
    "tests/test_explore_e2e.py",
    "tests/test_fuzz_e2e.py",
]


def _strip_message(rest: str) -> str:
    """Node id without pytest's appended ' - <message>'.  Parametrized ids
    may themselves contain ' - ' inside their [...] part, so cut at the
    first ' - ' OUTSIDE brackets, not the first one anywhere."""
    depth = 0
    for i, c in enumerate(rest):
        if c == "[":
            depth += 1
        elif c == "]":
            depth = max(depth - 1, 0)
        elif depth == 0 and rest.startswith(" - ", i):
            return rest[:i]
    return rest


def parse_failures(text: str) -> set:
    """Failing node ids from pytest's SHORT TEST SUMMARY section only —
    captured test output can legitimately contain lines starting with
    'ERROR ...' (log records), so everything before the summary marker is
    ignored.  Falls back to the whole text when the marker is absent
    (truncated log)."""
    lines = text.splitlines()
    for i, line in enumerate(lines):
        if "short test summary info" in line:
            lines = lines[i + 1:]
            break
    out = set()
    for line in lines:
        m = _LINE.match(line.strip())
        if m:
            out.add(_strip_message(m.group(2)).strip().rstrip(":"))
    return out


def run_tier1() -> str:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    with tempfile.NamedTemporaryFile("w+", suffix=".log", delete=False) as f:
        proc = subprocess.run(
            TIER1_CMD, cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        f.write(proc.stdout)
        print(f"(tier-1 log: {f.name})")
    tail = "\n".join(proc.stdout.splitlines()[-3:])
    print(tail)
    return proc.stdout


def load_baseline() -> set:
    if not os.path.exists(BASELINE):
        return set()
    with open(BASELINE) as f:
        return {
            ln.strip() for ln in f
            if ln.strip() and not ln.startswith("#")
        }


def slow_guard() -> int:
    """Exit 1 when any SLOW_ONLY_FILES test would run in the fast tier."""
    missing = [
        f for f in SLOW_ONLY_FILES if not os.path.exists(os.path.join(REPO, f))
    ]
    if missing:
        print(f"SLOW-GUARD FAIL: guarded file(s) do not exist: {missing}")
        return 1
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", *SLOW_ONLY_FILES],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    # pytest exit 0 = collected-and-deselected, 5 = nothing collected; any
    # other code (collection error, usage error) means the guard verified
    # NOTHING and must fail rather than pass vacuously
    if proc.returncode not in (0, 5):
        print(
            f"SLOW-GUARD FAIL: pytest collection exited "
            f"{proc.returncode}:\n{proc.stdout[-2000:]}"
        )
        return 1
    collected = [
        ln for ln in proc.stdout.splitlines()
        if "::" in ln and not ln.startswith(("=", "<"))
    ]
    if collected:
        print(
            f"SLOW-GUARD FAIL: {len(collected)} multi-process e2e test(s) "
            "would run in the tier-1 fast tier (missing slow mark):"
        )
        for t in collected:
            print(f"  - {t}")
        return 1
    print(
        f"slow-guard ok: {', '.join(SLOW_ONLY_FILES)} fully excluded from "
        "tier-1"
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="diff the failure set against the baseline")
    mode.add_argument("--update", action="store_true",
                      help="rewrite the baseline from this run/log")
    mode.add_argument("--slow-guard", action="store_true",
                      help="verify multi-process e2e files stay slow-marked")
    ap.add_argument("--from-log", default=None,
                    help="parse this pytest log instead of running the suite")
    args = ap.parse_args()

    if args.slow_guard:
        return slow_guard()

    if args.from_log:
        with open(args.from_log) as f:
            text = f.read()
    else:
        text = run_tier1()
    failures = parse_failures(text)

    if args.update:
        with open(BASELINE, "w") as f:
            f.write(
                "# Tier-1 failing-test baseline (the SET CI diffs against,\n"
                "# scripts/tier1_failset.py).  One pytest node id per line;\n"
                "# update with: python scripts/tier1_failset.py --update "
                "[--from-log L]\n"
            )
            for t in sorted(failures):
                f.write(t + "\n")
        print(f"baseline updated: {len(failures)} failing test(s) -> {BASELINE}")
        return 0

    baseline = load_baseline()
    new = sorted(failures - baseline)
    fixed = sorted(baseline - failures)
    print(
        f"tier-1 failure set: {len(failures)} failing, baseline "
        f"{len(baseline)}"
    )
    if fixed:
        print(f"\n{len(fixed)} baseline failure(s) now PASS (improvement):")
        for t in fixed:
            print(f"  + {t}")
        print("  (re-snapshot with --update to lock these in)")
    if new:
        print(f"\n{len(new)} NEW failure(s) not in the baseline (REGRESSION):")
        for t in new:
            print(f"  - {t}")
        return 1
    print("no new failures vs baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
