"""Hostile-network drills (fast tier): the netem fault transport against a
REAL Server/Client pair — duplicated/reordered delivery vs the idempotent
ack plane (seq-correlated replies, stale discard), corrupt-frame storms vs
codec rejection (accept loop survives, counter > 0, damaged payloads never
deserialize), HAClient failover under corrupt + duplicated-response
delivery with no zombie connection leak, and partition ride-through.

The slow, multi-process partition/split-brain drills live in
tests/test_netem_e2e.py (`make chaos`)."""

import os
import socket
import time

import numpy as np
import pytest

from paddle_tpu import master_wire as wire
from paddle_tpu.io import recordio
from paddle_tpu.master import (
    Client,
    MasterTimeoutError,
    MasterTransportError,
    Server,
    Service,
)
from paddle_tpu.robustness import chaos, netem


@pytest.fixture(autouse=True)
def _clean_netem(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_NETEM_PARTITION_SECS", "0.5")
    monkeypatch.setenv("PADDLE_TPU_NETEM_DELAY_MS", "30")
    chaos.disarm()
    netem.reset()
    wire.counters.reset()
    yield
    chaos.disarm()
    netem.reset()
    wire.counters.reset()


def _dataset(tmp_path, n=16):
    data = os.path.join(str(tmp_path), "data.rio")
    recordio.write_records(
        data, iter([b"r%d" % i for i in range(n)]), max_chunk_records=2
    )
    return data


def _payload(x=1.0):
    return {"grads": {"w": np.full(4, x, np.float32)}, "cost": float(x),
            "rows": 4}


def test_maybe_wrap_is_zero_cost_unarmed():
    sentinel = object()
    assert netem.maybe_wrap(sentinel, role="client") is sentinel
    chaos.arm("net_drop@999")
    wrapped = netem.maybe_wrap(sentinel, role="client")
    assert isinstance(wrapped, netem.FaultyConnection)


def test_role_gating(monkeypatch):
    chaos.arm("net_drop@999")
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "server")
    sentinel = object()
    assert netem.maybe_wrap(sentinel, role="client") is sentinel
    assert isinstance(
        netem.maybe_wrap(sentinel, role="server"), netem.FaultyConnection
    )


def test_duplicated_request_acks_exactly_once(tmp_path, monkeypatch):
    """net_dup duplicates EVERY client frame: the server must execute the
    duplicate ack as an idempotent dedupe (one done task, one stored
    result) and the client must discard the duplicate reply by seq."""
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "client")
    data = _dataset(tmp_path)
    svc = Service(chunks_per_task=2, auto_rotate=False)
    srv = Server(svc)
    chaos.arm("net_dup")
    try:
        c = Client(srv.address, call_timeout_s=5.0)
        c.set_dataset([data])
        c.register_worker("w0")
        got = c.get_task("w0")
        assert c.task_finished(
            got["task"]["task_id"], got["epoch"], _payload(), got["pass_id"]
        )
        time.sleep(0.2)  # let the duplicate's reply land in the buffer
        assert c.n_tasks() == 4  # the stale reply was discarded, not
        #                          credited to this call
        assert len(svc.done) == 1
        assert len(svc.results[0]) == 1  # stored exactly once
        assert wire.counters.snapshot().get("stale_replies_discarded", 0) >= 1
        c.close()
    finally:
        srv.close()


def test_reordered_delivery_rides_idempotent_ack(tmp_path, monkeypatch):
    """net_reorder holds an ack frame back and releases it AFTER the
    retry that follows the timeout: the server sees ack, then stale
    duplicate — dedupe keeps exactly one completion, the late reply is
    discarded by seq."""
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "client")
    data = _dataset(tmp_path)
    svc = Service(chunks_per_task=2, auto_rotate=False)
    srv = Server(svc)
    try:
        c = Client(srv.address, call_timeout_s=0.5, reconnect_tries=2,
                   reconnect_backoff=0.05)
        c.set_dataset([data])
        c.register_worker("w0")
        got = c.get_task("w0")
        tid, ep, pid = got["task"]["task_id"], got["epoch"], got["pass_id"]
        chaos.arm("net_reorder@1")  # the NEXT egress frame is held back
        acked = False
        for _ in range(4):  # the at-least-once retry loop a worker runs
            try:
                acked = c.task_finished(tid, ep, _payload(), pid)
                break
            except (MasterTimeoutError, MasterTransportError):
                continue
        assert acked
        time.sleep(0.2)
        assert c.n_tasks() == 4
        assert len(svc.done) == 1 and len(svc.results[0]) == 1
        c.close()
    finally:
        srv.close()


def test_corrupt_frame_storm_server_survives(tmp_path):
    """Garbage at every layer: raw unauthenticated TCP spray, then
    authenticated-but-alien frames, then CRC-broken frames — the accept
    loop survives all of it, the reject counter counts, a damaged payload
    never deserializes (by CRC construction), and a well-behaved client
    is served throughout."""
    from multiprocessing.connection import Client as ConnClient

    data = _dataset(tmp_path)
    svc = Service(chunks_per_task=2, auto_rotate=False)
    srv = Server(svc)
    try:
        # (1) unauthenticated garbage: dies in the auth handshake,
        # per-client, accept loop keeps going (the Listener's backlog is
        # tiny and each bad handshake briefly occupies the accept thread,
        # so a refused connect just means "busy" — retry like a client)
        sprayed = 0
        for i in range(8):
            s = None
            for attempt in range(100):
                try:
                    s = socket.create_connection(srv.address, timeout=2)
                    break
                except OSError:
                    time.sleep(0.02)
            if s is None:
                continue  # accept thread busy chewing earlier garbage
            try:
                s.sendall(os.urandom(64))
            finally:
                s.close()
            sprayed += 1
        assert sprayed >= 4
        # (2) authenticated garbage frames: not even wire-framed
        conn = ConnClient(tuple(srv.address), authkey=b"paddle-tpu")
        rng = np.random.RandomState(0)
        for i in range(6):
            conn.send_bytes(rng.bytes(32))
        # (3) CRC-broken real frames
        frame = bytearray(wire.encode_frame(
            wire.encode_payload(("n_tasks", (), {"seq": 1}))
        ))
        frame[-1] ^= 0xFF
        conn.send_bytes(bytes(frame))
        # (4) a validly-encoded but structurally alien message
        conn.send_bytes(wire.encode_frame(wire.encode_payload(42)))
        deadline = time.time() + 5
        while (wire.counters.snapshot().get("server_rejected_frames", 0) < 8
               and time.time() < deadline):
            time.sleep(0.02)
        assert wire.counters.snapshot()["server_rejected_frames"] >= 8
        conn.close()
        # the storm never crashed the accept loop: a clean client works
        c = Client(srv.address, call_timeout_s=5.0)
        assert c.set_dataset([data]) == 4
        assert c.stats()["wire"]["server_rejected_frames"] >= 8
        c.close()
    finally:
        srv.close()


def test_corrupt_request_rides_client_retry(tmp_path, monkeypatch):
    """A frame corrupted in flight surfaces server-side as a structured
    wire-reject; the client's bounded retry re-sends the call whole and
    succeeds — nothing ever deserialized the damaged bytes."""
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "client")
    data = _dataset(tmp_path)
    svc = Service(chunks_per_task=2, auto_rotate=False)
    srv = Server(svc)
    chaos.arm("net_corrupt@2")
    try:
        c = Client(srv.address, call_timeout_s=5.0)
        assert c.set_dataset([data]) == 4  # frame 1
        assert c.n_tasks() == 4            # frame 2: corrupted -> retried
        snap = wire.counters.snapshot()
        assert snap.get("server_rejected_frames", 0) >= 1
        assert netem.counters.snapshot().get("corrupted", 0) == 1
        c.close()
    finally:
        srv.close()


def test_partition_rides_bounded_retry(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "client")
    data = _dataset(tmp_path)
    svc = Service(chunks_per_task=2, auto_rotate=False)
    srv = Server(svc)
    chaos.arm("net_partition@2")
    try:
        c = Client(srv.address, call_timeout_s=0.3, reconnect_tries=3,
                   reconnect_backoff=0.05)
        assert c.set_dataset([data]) == 4  # msg 1
        t0 = time.time()
        n = None
        while n is None and time.time() - t0 < 10:
            try:
                n = c.n_tasks()  # msg 2 fires the partition
            except (ConnectionError, OSError):
                time.sleep(0.05)
        assert n == 4
        assert time.time() - t0 >= 0.4  # genuinely waited the link out
        assert netem.last_partition_start() > 0
        c.close()
    finally:
        srv.close()


def test_delay_and_drop_points(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "client")
    data = _dataset(tmp_path)
    svc = Service(chunks_per_task=2, auto_rotate=False)
    srv = Server(svc)
    try:
        chaos.arm("net_delay@1")
        c = Client(srv.address, call_timeout_s=5.0)
        t0 = time.time()
        assert c.set_dataset([data]) == 4
        assert time.time() - t0 >= 0.025
        assert netem.counters.snapshot().get("delayed", 0) == 1
        chaos.arm("net_drop@1")  # re-arm resets consultation counts
        c2 = Client(srv.address, call_timeout_s=0.3)
        n = None
        for _ in range(5):
            try:
                n = c2.n_tasks()  # 1st frame dropped -> deadline -> retry
                break
            except (MasterTimeoutError, MasterTransportError):
                continue
        assert n == 4
        assert netem.counters.snapshot().get("dropped", 0) == 1
        c2.close()
        c.close()
    finally:
        srv.close()


def test_partition_expires_lease_requeue_and_zombie_ack(tmp_path, monkeypatch):
    """A worker partitioned while HOLDING a shard lease: the lease
    expires into the failure/requeue discipline, a survivor recomputes,
    and the partitioned worker's eventual late ack is rejected as a
    zombie (epoch guard) — the surviving recomputation's bits win."""
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "client")
    monkeypatch.setenv("PADDLE_TPU_NETEM_PARTITION_SECS", "1.0")
    data = _dataset(tmp_path)
    svc = Service(chunks_per_task=2, auto_rotate=False, timeout_s=0.4)
    srv = Server(svc)
    svc.set_dataset([data])  # in-process setup: no wire messages burned
    chaos.arm("net_partition@2")
    try:
        c = Client(srv.address, call_timeout_s=0.3, reconnect_tries=2,
                   reconnect_backoff=0.05)
        got = c.get_task("wA")  # msg 1: lease granted to the victim
        tid, ep, pid = got["task"]["task_id"], got["epoch"], got["pass_id"]
        with pytest.raises((MasterTimeoutError, MasterTransportError)):
            # msg 2 fires the partition: the ack never arrives
            c.task_finished(tid, ep, _payload(1.0), pid)
        time.sleep(0.5)  # the held lease expires behind the partition
        # in-process survivors lease until one reaches the REQUEUED task
        # (the failure discipline appends it behind the untouched todo;
        # distinct ids because get_task re-serves a worker's held lease)
        for i in range(8):
            got2 = svc.get_task(f"wB{i}")
            if got2["task"]["task_id"] == tid:
                break
        assert got2["task"]["task_id"] == tid
        assert got2["epoch"] == ep + 1  # the failure discipline bumped it
        assert svc.stats()["fail_events"] == 1
        assert svc.task_finished(tid, got2["epoch"], _payload(2.0), pid)
        time.sleep(0.8)  # partition heals
        # the victim's retried ack is a ZOMBIE: stale epoch, rejected
        assert c.task_finished(tid, ep, _payload(1.0), pid) is False
        assert svc.results[0][tid]["grads"]["w"][0] == np.float32(2.0)
        c.close()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# HAClient failover under hostile delivery (the satellite drill)
# ---------------------------------------------------------------------------


@pytest.fixture()
def ha_master(tmp_path):
    from paddle_tpu.master_ha import HAMaster

    data = _dataset(tmp_path)
    ha = HAMaster(
        os.path.join(str(tmp_path), "ha"), [data], owner_id="m0",
        lease_timeout=5.0, chunks_per_task=2, auto_rotate=False,
    )
    ha.start()
    assert ha.wait_leader(30)
    yield ha
    ha.stop()


def test_haclient_rides_corrupt_frames_no_conn_leak(ha_master, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "client")
    from paddle_tpu.master_ha import HAClient

    chaos.arm("net_corrupt@3")
    hc = HAClient(ha_master.dir, timeout=20.0, call_timeout_s=2.0)
    try:
        for i in range(6):  # one of these frames corrupts mid-flight
            assert "pass_id" in hc.stats()
        snap = wire.counters.snapshot()
        assert snap.get("server_rejected_frames", 0) >= 1
        # no zombie connections: the reject/retry cycle closed what it
        # dropped (<= the live client conn + one still-draining handler)
        deadline = time.time() + 5
        while len(ha_master.server._conns) > 2 and time.time() < deadline:
            time.sleep(0.05)
        assert len(ha_master.server._conns) <= 2
    finally:
        hc.close()


def test_haclient_rides_duplicated_responses(ha_master, monkeypatch):
    """net_dup on the SERVER role duplicates every REPLY: the client must
    discard the duplicates by seq — every call still returns the right
    answer, and the bounded-retry window never trips."""
    monkeypatch.setenv("PADDLE_TPU_NETEM_ROLE", "server")
    from paddle_tpu.master_ha import HAClient

    chaos.arm("net_dup")
    # fresh connections AFTER arming so the server side wraps them
    hc = HAClient(ha_master.dir, timeout=20.0, call_timeout_s=2.0)
    try:
        assert hc.register_worker("w0")["pass_id"] == 0
        got = hc.get_task("w0")
        assert hc.task_finished(
            got["task"]["task_id"], got["epoch"], _payload(), got["pass_id"]
        )
        assert hc.stats()["n_done"] == 1
        assert wire.counters.snapshot().get("stale_replies_discarded", 0) >= 1
        assert len(ha_master.service.results[0]) == 1
    finally:
        hc.close()
