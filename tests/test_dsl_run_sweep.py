"""Every reference DSL-suite config EXECUTES — one jitted forward with
random batches, finite outputs.

The reference's own suite (trainer_config_helpers/tests/configs/
file_list.sh, driven by test_config_parser.py) only checks the configs
PARSE to stable protostrs; the golden-serialize test here mirrors that.
This sweep goes further: each config builds a CompiledNetwork, gets a
random batch shaped by per-config slot-type hints (the DSL fixtures carry
no data declarations, so sequence-ness is knowledge about the net), and
runs forward under train=True.  A config that stops executing — a layer
lowering regression, a shape contract break — fails here even if its
serialized form is unchanged.
"""

import os

import jax
import numpy as np
import pytest

import paddle_tpu.core.data_types as dt
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.v1_compat import parse_config

from layer_grad_util import rand_batch_for

DSL = "/root/reference/python/paddle/trainer_config_helpers/tests/configs"

# the reference's own list (file_list.sh `configs=`)
FILE_LIST = [
    "test_repeat_layer", "test_fc", "layer_activations", "projections",
    "test_print_layer", "test_sequence_pooling", "test_lstmemory_layer",
    "test_grumemory_layer", "last_first_seq", "test_expand_layer",
    "test_ntm_layers", "test_hsigmoid", "img_layers", "img_trans_layers",
    "util_layers", "simple_rnn_layers", "unused_layers", "test_cost_layers",
    "test_rnn_group", "shared_fc", "shared_lstm", "shared_gru",
    "test_cost_layers_with_weight", "test_spp_layer", "test_bilinear_interp",
    "test_maxout", "test_bi_grumemory", "math_ops",
    "test_seq_concat_reshape", "test_pad", "test_smooth_l1",
    "test_multiplex_layer", "test_prelu_layer", "test_row_conv",
    "test_detection_output_layer", "test_multibox_loss_layer",
    "test_recursive_topology", "test_gated_unit_layer", "test_clip_layer",
    "test_row_l2_norm_layer",
]

# configs that cannot run as plain forward passes, with the reason stated
SKIP = {
    "test_detection_output_layer":
        "needs structured ground-truth boxes; executed end-to-end by "
        "tests/test_detection.py",
    "test_multibox_loss_layer":
        "needs structured ground-truth boxes; executed end-to-end by "
        "tests/test_detection.py",
    "test_sequence_pooling":
        "one slot feeds BOTH stride pooling (defined on plain sequences) "
        "and TO_SEQUENCE pooling (needs nested input) — unrunnable on any "
        "single input type even in the reference (its suite only parses "
        "these); both modes execute in tests/test_layer_grad.py and "
        "tests/test_nested_seq.py",
    "test_expand_layer":
        "one slot feeds FROM_NO_SEQUENCE (non-seq input) and FROM_SEQUENCE "
        "(seq input over a nested pattern) expansion simultaneously — same "
        "parse-only conflict; both modes execute in tests/test_nested_seq.py",
    "last_first_seq":
        "one slot feeds stride selection (plain sequences only) and "
        "TO_SEQUENCE aggregation (nested input) simultaneously — parse-only "
        "conflict; both execute in tests/test_layer_grad.py and "
        "tests/test_nested_seq.py",
    "projections":
        "m2 += table_projection(input=m1) indexes an embedding table with a "
        "DENSE intermediate — undefined at runtime in the reference too "
        "(TableProjection requires an ids argument); every projection kind "
        "executes in tests/test_mixed.py",
    "test_rnn_group":
        "feeds a whole subsequence plus a flat memory into one fc inside a "
        "non-nested group — frame-count mismatch in the reference's fc too "
        "(gserver FC CHECKs equal row counts); the shipped nested-group "
        "form executes in tests/test_nested_seq.py and "
        "tests/test_generation_golden.py",
}

# slot-type hints: the DSL fixtures declare bare data_layer sizes; which
# slots are sequences (or labels) is net knowledge the reference encodes in
# its C++ test drivers
H = {
    "last_first_seq": {"data": dt.dense_vector_sub_sequence(30)},
    "projections": {"test": dt.integer_value_sequence(100)},
    "simple_rnn_layers": {"data": dt.dense_vector_sequence(200)},
    "test_bi_grumemory": {"data": dt.dense_vector_sequence(120)},
    "test_grumemory_layer": {"data": dt.dense_vector_sequence(120)},
    "test_lstmemory_layer": {"data": dt.dense_vector_sequence(128)},
    "test_row_conv": {"data": dt.dense_vector_sequence(2560)},
    "test_seq_concat_reshape": {
        "data1": dt.dense_vector_sequence(30),
        "data2": dt.dense_vector_sequence(30),
    },
    "shared_gru": {
        "data_a": dt.dense_vector_sequence(100),
        "data_b": dt.dense_vector_sequence(100),
        "label": dt.integer_value(10),
    },
    "shared_lstm": {
        "data_a": dt.dense_vector_sequence(100),
        "data_b": dt.dense_vector_sequence(100),
        "label": dt.integer_value(10),
    },
    "shared_fc": {"label": dt.integer_value(10)},
    "test_rnn_group": {
        "seq_input": dt.dense_vector_sequence(100),
        "sub_seq_input": dt.dense_vector_sub_sequence(100),
    },
    "test_cost_layers": {
        "input": dt.dense_vector_sequence(200),
        "labels": dt.integer_value_sequence(200),
        "crf_label": dt.integer_value_sequence(4),
        "probs": dt.dense_vector(10),
        "xe-label": dt.integer_value(10),
        "left": dt.dense_vector(1),
        "right": dt.dense_vector(1),
        "label": dt.integer_value(2),
        "list_feature": dt.dense_vector_sequence(100),
        "list_scores": dt.dense_vector_sequence(1),
        "huber_probs": dt.dense_vector(1),
        "huber_label": dt.integer_value(2),
    },
    "test_cost_layers_with_weight": {
        "label": dt.integer_value(10),
        "weight": dt.dense_vector(1),
        "multi_class_label": dt.integer_value(500),
    },
    "test_hsigmoid": {"label": dt.integer_value(10)},
}

# per-config batch adjustments where plain random values are mathematically
# out of domain (the reference layer would produce the same NaNs)
def _ntm_fix(batch):
    # power_layer computes a ** w: a negative base with a fractional
    # exponent is NaN in the reference's PowerLayer too — feed positives
    import jax.numpy as jnp

    from paddle_tpu.core.batch import SeqTensor

    out = dict(batch)
    out["a"] = SeqTensor(jnp.abs(batch["a"].data) + 0.1)
    out["w"] = SeqTensor(jnp.abs(batch["w"].data))
    return out


BATCH_FIX = {"test_ntm_layers": _ntm_fix}


def _hinted(parsed, name):
    hints = H.get(name, {})
    for lname, itype in hints.items():
        conf = parsed.topology.layers.get(lname)
        if conf is None:
            raise AssertionError(
                f"{name}: hint for unknown data layer {lname!r}; layers: "
                f"{list(parsed.topology.data_layers())}"
            )
        object.__setattr__(conf, "input_type", itype)
        conf.attrs.pop("_v1_size_only", None)
    return parsed


@pytest.mark.parametrize("name", FILE_LIST)
def test_dsl_config_executes(name):
    if name in SKIP:
        pytest.skip(SKIP[name])
    parsed = _hinted(parse_config(os.path.join(DSL, name + ".py")), name)
    net = CompiledNetwork(parsed.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = rand_batch_for(parsed.topology, batch_size=2, max_len=4)
    if name in BATCH_FIX:
        batch = BATCH_FIX[name](batch)
    if net.has_dynamic_widths:  # e.g. test_fc's trans -> fc
        params, _ = net.resolve_dynamic_widths(params, batch)
    outs, _ = net.apply(
        params, batch, state=state, train=True, rng=jax.random.PRNGKey(1)
    )
    for oname in parsed.topology.output_names:
        v = outs[oname]
        arr = v.data if hasattr(v, "data") else v
        assert np.all(np.isfinite(np.asarray(arr, np.float32))), (
            f"{name}: output {oname} not finite"
        )
