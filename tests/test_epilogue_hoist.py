"""recurrent_group epilogue hoisting (layers/recurrent_group.py
_split_epilogue): the rowwise suffix of a step graph runs once on the
stacked sequence instead of per scan step.  These tests pin (a) the
partition itself, (b) exact numerics vs the unhoisted path, and (c) the
group-level @logits exposure that lets cross_entropy fuse."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
import importlib

rg = importlib.import_module("paddle_tpu.layers.recurrent_group")

L = paddle.layer
A = paddle.activation


def _group_cost(vocab=37):
    """A decoder-shaped group: GRU-ish recurrence + per-step vocab fc."""
    paddle.init(seed=5)
    x = L.data("x", paddle.data_type.integer_value_sequence(vocab))
    emb = L.embedding(x, size=12)

    def step(e_t):
        state = L.memory("st", 10)
        h = L.fc([e_t, state], size=10, act=A.Tanh(), name="st")
        return L.fc(h, size=vocab, act=A.Softmax(), name="head")

    dec = L.recurrent_group(step, input=[emb], name="dec_group")
    lab = L.data("y", paddle.data_type.integer_value_sequence(vocab))
    return L.classification_cost(input=dec, label=lab)


def _batch(vocab=37, b=3, t=6):
    rng = np.random.RandomState(0)
    lens = jnp.asarray([6, 4, 2], jnp.int32)
    return {
        "x": SeqTensor(
            jnp.asarray(rng.randint(0, vocab, size=(b, t)), jnp.int32), lens
        ),
        "y": SeqTensor(
            jnp.asarray(rng.randint(0, vocab, size=(b, t)), jnp.int32), lens
        ),
    }


def test_partition_hoists_head_only():
    reset_auto_names()
    cost = _group_cost()
    topo = Topology([cost])
    gconf = next(
        c for c in topo.layers.values() if c.type == "recurrent_group"
    )
    sub = gconf.attrs["_sub_topology"]
    epi, frontier = rg._split_epilogue(
        sub, gconf.attrs["_memories"], gconf.attrs["_output"], set()
    )
    assert epi == {"head"}
    # the head reads exactly the recurrent state from the loop
    assert frontier == ("st",)


def test_hoisted_numerics_match_unhoisted(monkeypatch):
    reset_auto_names()
    cost = _group_cost()
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    batch = _batch()

    def cost_and_grads():
        def loss(p):
            # net.cost returns (cost, aux); take the scalar
            return net.cost(p, batch, state=state, rng=None, train=True)[0]

        return jax.value_and_grad(loss)(params)

    v_hoisted, g_hoisted = cost_and_grads()
    monkeypatch.setattr(
        rg, "_split_epilogue", lambda *a, **k: (None, (a[2],))
    )
    v_plain, g_plain = cost_and_grads()
    np.testing.assert_allclose(v_hoisted, v_plain, rtol=1e-5)
    flat_h = jax.tree_util.tree_leaves(g_hoisted)
    flat_p = jax.tree_util.tree_leaves(g_plain)
    for a, b in zip(flat_h, flat_p):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_group_exposes_fused_ce_logits():
    reset_auto_names()
    cost = _group_cost()
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    outs, _ = net.apply(params, _batch(), state=state, train=True)
    lg = outs.get("dec_group@logits")
    assert lg is not None, "hoisted softmax must expose group-level logits"
    assert lg.data.shape == outs["dec_group"].data.shape
    # logits really are the pre-softmax values of the group output
    np.testing.assert_allclose(
        np.asarray(jax.nn.softmax(lg.data[..., :], axis=-1))[0, 0],
        np.asarray(outs["dec_group"].data)[0, 0],
        atol=1e-5,
    )


def test_memory_dependent_head_stays_in_loop():
    """A suffix that feeds a memory cannot hoist."""
    reset_auto_names()
    paddle.init(seed=6)
    x = L.data("x", paddle.data_type.integer_value_sequence(11))
    emb = L.embedding(x, size=8)

    def step(e_t):
        state = L.memory("looped", 11)
        h = L.fc([e_t, state], size=8, act=A.Tanh(), name="h")
        out = L.fc(h, size=11, act=A.Softmax(), name="looped")
        return out

    dec = L.recurrent_group(step, input=[emb], name="g2")
    topo = Topology([dec])
    gconf = next(
        c for c in topo.layers.values() if c.type == "recurrent_group"
    )
    epi, frontier = rg._split_epilogue(
        gconf.attrs["_sub_topology"], gconf.attrs["_memories"],
        gconf.attrs["_output"], set(),
    )
    assert epi is None and frontier == (gconf.attrs["_output"],)


def test_diamond_with_loop_resident_consumer():
    """p feeds both a hoistable suffix AND a loop-resident (dropout)
    layer: p must stay in the loop — a hoisted p would leave the loop
    consumer reading a never-computed output."""
    reset_auto_names()
    paddle.init(seed=7)
    x = L.data("x", paddle.data_type.integer_value_sequence(13))
    emb = L.embedding(x, size=8)

    def step(e_t):
        state = L.memory("s", 6)
        h = L.fc([e_t, state], size=6, act=A.Tanh(), name="s")
        p = L.fc(h, size=6, act=A.Tanh(), name="p")
        q = L.fc(p, size=6, act=A.Tanh(), name="q",
                 layer_attr=paddle.attr.ExtraAttr(drop_rate=0.5))
        return L.addto([p, q], act=A.Identity(), name="out",
                       bias_attr=False)

    dec = L.recurrent_group(step, input=[emb], name="g3")
    net = CompiledNetwork(Topology([dec]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {
        "x": SeqTensor(
            jnp.asarray(rng.randint(0, 13, size=(2, 4)), jnp.int32),
            jnp.asarray([4, 2], jnp.int32),
        )
    }
    outs, _ = net.apply(
        params, batch, state=state, train=True, rng=jax.random.PRNGKey(1)
    )
    assert outs["g3"].data.shape == (2, 4, 6)


def test_seq_valued_frontier_disables_hoisting():
    """A loop layer emitting a per-step SEQUENCE (expand over a static
    seq) cannot be time-flattened: the abstract probe must disable
    hoisting and the nested output must match the unhoisted semantics."""
    reset_auto_names()
    paddle.init(seed=8)
    x = L.data("x", paddle.data_type.integer_value_sequence(13))
    emb = L.embedding(x, size=8)
    static = L.fc(emb, size=5, act=A.Tanh(), name="stat")

    from paddle_tpu.layers.recurrent_group import StaticInput

    def step(e_t, stat_seq):
        state = L.memory("s2", 5)
        h = L.fc([e_t, state], size=5, act=A.Tanh(), name="s2")
        ex = L.expand(h, stat_seq, name="ex")
        return L.fc(ex, size=5, act=A.Tanh(), name="head2")

    dec = L.recurrent_group(
        step, input=[emb, StaticInput(static, is_seq=True)], name="g4"
    )
    net = CompiledNetwork(Topology([dec]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {
        "x": SeqTensor(
            jnp.asarray(rng.randint(0, 13, size=(2, 4)), jnp.int32),
            jnp.asarray([4, 3], jnp.int32),
        )
    }
    outs, _ = net.apply(params, batch, state=state, train=True)
    # nested [B, S, T, D] output, exactly as without hoisting
    assert outs["g4"].data.ndim == 4
