"""recurrent_group epilogue hoisting (layers/recurrent_group.py
_split_epilogue): the rowwise suffix of a step graph runs once on the
stacked sequence instead of per scan step.  These tests pin (a) the
partition itself, (b) exact numerics vs the unhoisted path, and (c) the
group-level @logits exposure that lets cross_entropy fuse."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
import importlib

rg = importlib.import_module("paddle_tpu.layers.recurrent_group")

L = paddle.layer
A = paddle.activation


def _group_cost(vocab=37):
    """A decoder-shaped group: GRU-ish recurrence + per-step vocab fc."""
    paddle.init(seed=5)
    x = L.data("x", paddle.data_type.integer_value_sequence(vocab))
    emb = L.embedding(x, size=12)

    def step(e_t):
        state = L.memory("st", 10)
        h = L.fc([e_t, state], size=10, act=A.Tanh(), name="st")
        return L.fc(h, size=vocab, act=A.Softmax(), name="head")

    dec = L.recurrent_group(step, input=[emb], name="dec_group")
    lab = L.data("y", paddle.data_type.integer_value_sequence(vocab))
    return L.classification_cost(input=dec, label=lab)


def _batch(vocab=37, b=3, t=6):
    rng = np.random.RandomState(0)
    lens = jnp.asarray([6, 4, 2], jnp.int32)
    return {
        "x": SeqTensor(
            jnp.asarray(rng.randint(0, vocab, size=(b, t)), jnp.int32), lens
        ),
        "y": SeqTensor(
            jnp.asarray(rng.randint(0, vocab, size=(b, t)), jnp.int32), lens
        ),
    }


def test_partition_hoists_head_only():
    reset_auto_names()
    cost = _group_cost()
    topo = Topology([cost])
    gconf = next(
        c for c in topo.layers.values() if c.type == "recurrent_group"
    )
    sub = gconf.attrs["_sub_topology"]
    epi, frontier = rg._split_epilogue(
        sub, gconf.attrs["_memories"], gconf.attrs["_output"], set()
    )
    assert epi == {"head"}
    # the head reads exactly the recurrent state from the loop
    assert frontier == ("st",)


def test_hoisted_numerics_match_unhoisted(monkeypatch):
    reset_auto_names()
    cost = _group_cost()
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    batch = _batch()

    def cost_and_grads():
        def loss(p):
            # net.cost returns (cost, aux); take the scalar
            return net.cost(p, batch, state=state, rng=None, train=True)[0]

        return jax.value_and_grad(loss)(params)

    v_hoisted, g_hoisted = cost_and_grads()
    monkeypatch.setattr(
        rg, "_split_epilogue", lambda *a, **k: (None, (a[2],))
    )
    v_plain, g_plain = cost_and_grads()
    np.testing.assert_allclose(v_hoisted, v_plain, rtol=1e-5)
    flat_h = jax.tree_util.tree_leaves(g_hoisted)
    flat_p = jax.tree_util.tree_leaves(g_plain)
    for a, b in zip(flat_h, flat_p):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_group_exposes_fused_ce_logits():
    reset_auto_names()
    cost = _group_cost()
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    outs, _ = net.apply(params, _batch(), state=state, train=True)
    lg = outs.get("dec_group@logits")
    assert lg is not None, "hoisted softmax must expose group-level logits"
    assert lg.data.shape == outs["dec_group"].data.shape
    # logits really are the pre-softmax values of the group output
    np.testing.assert_allclose(
        np.asarray(jax.nn.softmax(lg.data[..., :], axis=-1))[0, 0],
        np.asarray(outs["dec_group"].data)[0, 0],
        atol=1e-5,
    )


def test_memory_dependent_head_stays_in_loop():
    """A suffix that feeds a memory cannot hoist."""
    reset_auto_names()
    paddle.init(seed=6)
    x = L.data("x", paddle.data_type.integer_value_sequence(11))
    emb = L.embedding(x, size=8)

    def step(e_t):
        state = L.memory("looped", 11)
        h = L.fc([e_t, state], size=8, act=A.Tanh(), name="h")
        out = L.fc(h, size=11, act=A.Softmax(), name="looped")
        return out

    dec = L.recurrent_group(step, input=[emb], name="g2")
    topo = Topology([dec])
    gconf = next(
        c for c in topo.layers.values() if c.type == "recurrent_group"
    )
    epi, frontier = rg._split_epilogue(
        gconf.attrs["_sub_topology"], gconf.attrs["_memories"],
        gconf.attrs["_output"], set(),
    )
    assert epi is None and frontier == (gconf.attrs["_output"],)


def test_diamond_with_loop_resident_consumer():
    """p feeds both a hoistable suffix AND a loop-resident (dropout)
    layer: p must stay in the loop — a hoisted p would leave the loop
    consumer reading a never-computed output."""
    reset_auto_names()
    paddle.init(seed=7)
    x = L.data("x", paddle.data_type.integer_value_sequence(13))
    emb = L.embedding(x, size=8)

    def step(e_t):
        state = L.memory("s", 6)
        h = L.fc([e_t, state], size=6, act=A.Tanh(), name="s")
        p = L.fc(h, size=6, act=A.Tanh(), name="p")
        q = L.fc(p, size=6, act=A.Tanh(), name="q",
                 layer_attr=paddle.attr.ExtraAttr(drop_rate=0.5))
        return L.addto([p, q], act=A.Identity(), name="out",
                       bias_attr=False)

    dec = L.recurrent_group(step, input=[emb], name="g3")
    net = CompiledNetwork(Topology([dec]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {
        "x": SeqTensor(
            jnp.asarray(rng.randint(0, 13, size=(2, 4)), jnp.int32),
            jnp.asarray([4, 2], jnp.int32),
        )
    }
    outs, _ = net.apply(
        params, batch, state=state, train=True, rng=jax.random.PRNGKey(1)
    )
    assert outs["g3"].data.shape == (2, 4, 6)


def test_seq_valued_frontier_disables_hoisting():
    """A loop layer emitting a per-step SEQUENCE (expand over a static
    seq) cannot be time-flattened: the abstract probe must disable
    hoisting and the nested output must match the unhoisted semantics."""
    reset_auto_names()
    paddle.init(seed=8)
    x = L.data("x", paddle.data_type.integer_value_sequence(13))
    emb = L.embedding(x, size=8)
    static = L.fc(emb, size=5, act=A.Tanh(), name="stat")

    from paddle_tpu.layers.recurrent_group import StaticInput

    def step(e_t, stat_seq):
        state = L.memory("s2", 5)
        h = L.fc([e_t, state], size=5, act=A.Tanh(), name="s2")
        ex = L.expand(h, stat_seq, name="ex")
        return L.fc(ex, size=5, act=A.Tanh(), name="head2")

    dec = L.recurrent_group(
        step, input=[emb, StaticInput(static, is_seq=True)], name="g4"
    )
    net = CompiledNetwork(Topology([dec]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {
        "x": SeqTensor(
            jnp.asarray(rng.randint(0, 13, size=(2, 4)), jnp.int32),
            jnp.asarray([4, 3], jnp.int32),
        )
    }
    outs, _ = net.apply(params, batch, state=state, train=True)
    # nested [B, S, T, D] output, exactly as without hoisting
    assert outs["g4"].data.ndim == 4


def test_prologue_hoists_input_projection():
    """An in-step projection fed only by the scanned input (the
    sequence_layer_group.conf pattern: Layer(fc) over the step input
    before the recurrence) must land in the prologue set; the recurrent
    fc must not."""
    reset_auto_names()
    paddle.init(seed=9)
    x = L.data("x", paddle.data_type.integer_value_sequence(17))
    emb = L.embedding(x, size=9)

    def step(e_t):
        proj = L.fc(e_t, size=6, act=A.Identity(), name="in_proj")
        state = L.memory("rec", 6)
        return L.fc([proj, state], size=6, act=A.Tanh(), name="rec")

    g = L.recurrent_group(step, input=[emb], name="gg")
    topo = Topology([g])
    gconf = next(
        c for c in topo.layers.values() if c.type == "recurrent_group"
    )
    sub = gconf.attrs["_sub_topology"]
    pro = rg._split_prologue(
        sub, gconf.attrs["_scan_placeholders"],
        gconf.attrs["_static_placeholders"], set(),
    )
    assert any(sub.layers[n].name == "in_proj" for n in pro), pro
    assert all(sub.layers[n].name != "rec" for n in pro), pro


def test_prologue_numerics_match_unhoisted(monkeypatch):
    reset_auto_names()
    paddle.init(seed=10)
    x = L.data("x", paddle.data_type.integer_value_sequence(17))
    emb = L.embedding(x, size=12)
    g = paddle.networks.gru_group(emb, size=4, name="gg2")
    pool = L.last_seq(input=g)
    out = L.fc(pool, size=3, act=A.Softmax())
    lab = L.data("y", paddle.data_type.integer_value(3))
    cost = L.classification_cost(input=out, label=lab)
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {
        "x": SeqTensor(
            jnp.asarray(rng.randint(0, 17, size=(3, 5)), jnp.int32),
            jnp.asarray([5, 3, 1], jnp.int32),
        ),
        "y": SeqTensor(jnp.asarray(rng.randint(0, 3, size=3), jnp.int32)),
    }

    def cg():
        def loss(p):
            return net.cost(p, batch, state=state, rng=None, train=True)[0]

        return jax.value_and_grad(loss)(params)

    v_h, g_h = cg()
    monkeypatch.setattr(rg, "_split_prologue", lambda *a, **k: set())
    v_p, g_p = cg()
    np.testing.assert_allclose(v_h, v_p, rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_h), jax.tree_util.tree_leaves(g_p)
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_epilogue_reads_step_input_directly():
    """A readout consuming the scanned input alongside the recurrent
    state: the placeholder is preset from the already-flattened xs (never
    re-stacked by the scan) and numerics hold."""
    reset_auto_names()
    paddle.init(seed=13)
    x = L.data("x", paddle.data_type.integer_value_sequence(19))
    emb = L.embedding(x, size=7)

    def step(e_t):
        state = L.memory("r5", 7)
        h = L.fc([e_t, state], size=7, act=A.Tanh(), name="r5")
        return L.fc([h, e_t], size=5, act=A.Softmax(), name="head5")

    g = L.recurrent_group(step, input=[emb], name="g5")
    net = CompiledNetwork(Topology([g]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {
        "x": SeqTensor(
            jnp.asarray(rng.randint(0, 19, size=(2, 4)), jnp.int32),
            jnp.asarray([4, 2], jnp.int32),
        )
    }
    outs, _ = net.apply(params, batch, state=state, train=True)
    assert outs["g5"].data.shape == (2, 4, 5)
    # hoisting actually engaged (head5 in the epilogue)
    gconf = net.topology.layers["g5"]
    epi, frontier = rg._split_epilogue(
        gconf.attrs["_sub_topology"], gconf.attrs["_memories"],
        gconf.attrs["_output"], set(),
    )
    assert epi == {"head5"}
    assert "g5@in0" in frontier
