"""CRF / CTC layer tests (reference: paddle/gserver/tests/test_CRFLayerGrad.cpp,
test_LinearChainCRF.cpp, test_CTCLayer.cpp, test_WarpCTCLayer.cpp).

Goldens: brute-force enumeration for the CRF (tiny label spaces), and
torch.nn.functional.ctc_loss (CPU) for CTC — the same role WarpCTC plays as
the alternative implementation in the reference's test_WarpCTCLayer.cpp.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names

from layer_grad_util import check_layer_grad

L = paddle.layer


@pytest.fixture(autouse=True)
def _reset_names():
    reset_auto_names()
    yield


def _crf_net(n=3):
    emis = L.data("emis", paddle.data_type.dense_vector_sequence(n))
    lab = L.data("lab", paddle.data_type.integer_value_sequence(n))
    cost = L.crf(emis, lab, size=n)
    topo = Topology([cost])
    return cost, topo, CompiledNetwork(topo)


def _brute_force_nll(x, y, lengths, w):
    """Enumerate all label paths; x: [B,T,N] np, w: [(N+2),N]."""
    a, b, trans = w[0], w[1], w[2:]
    out = []
    for i in range(x.shape[0]):
        T = int(lengths[i])
        n = x.shape[2]

        def path_score(path):
            s = a[path[0]] + b[path[-1]] + sum(x[i, t, path[t]] for t in range(T))
            s += sum(trans[path[t - 1], path[t]] for t in range(1, T))
            return s

        scores = [path_score(p) for p in itertools.product(range(n), repeat=T)]
        logz = np.logaddexp.reduce(scores)
        gold = path_score([int(v) for v in y[i, :T]])
        out.append(logz - gold)
    return np.array(out)


def test_crf_matches_brute_force():
    n = 3
    cost, topo, net = _crf_net(n)
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    B, T = 3, 4
    x = rng.randn(B, T, n).astype(np.float32)
    lengths = np.array([4, 2, 3], np.int32)
    y = rng.randint(0, n, size=(B, T)).astype(np.int32)
    batch = {
        "emis": SeqTensor(jnp.asarray(x), jnp.asarray(lengths)),
        "lab": SeqTensor(jnp.asarray(y), jnp.asarray(lengths)),
    }
    outs, _ = net.apply(params, batch, state=state)
    got = np.asarray(outs[cost.name].data)[:, 0]
    w = np.asarray(params[cost.name]["w"])
    expect = _brute_force_nll(x, y, lengths, w)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)


def test_crf_grad():
    n = 3
    cost, _, _ = _crf_net(n)
    check_layer_grad(cost, batch_size=3, max_len=4)


def test_crf_decoding_matches_brute_force():
    n = 3
    emis = L.data("emis", paddle.data_type.dense_vector_sequence(n))
    dec = L.crf_decoding(emis, size=n)
    topo = Topology([dec])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(2))
    rng = np.random.RandomState(3)
    B, T = 4, 5
    x = rng.randn(B, T, n).astype(np.float32)
    lengths = np.array([5, 1, 3, 4], np.int32)
    batch = {"emis": SeqTensor(jnp.asarray(x), jnp.asarray(lengths))}
    outs, _ = net.apply(params, batch, state=state)
    got = np.asarray(outs[dec.name].data)

    w = np.asarray(params[dec.name]["w"])
    a, b, trans = w[0], w[1], w[2:]
    for i in range(B):
        T_i = int(lengths[i])

        def path_score(path):
            s = a[path[0]] + b[path[-1]] + sum(x[i, t, path[t]] for t in range(T_i))
            s += sum(trans[path[t - 1], path[t]] for t in range(1, T_i))
            return s

        best = max(
            itertools.product(range(n), repeat=T_i), key=path_score
        )
        np.testing.assert_array_equal(got[i, :T_i], np.array(best))
        assert not got[i, T_i:].any()


def test_crf_decoding_with_label_mismatch_output():
    n = 3
    emis = L.data("emis", paddle.data_type.dense_vector_sequence(n))
    lab = L.data("lab", paddle.data_type.integer_value_sequence(n))
    dec = L.crf_decoding(emis, size=n, label=lab)
    topo = Topology([dec])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(2))
    rng = np.random.RandomState(3)
    B, T = 2, 4
    lengths = np.array([4, 3], np.int32)
    batch = {
        "emis": SeqTensor(jnp.asarray(rng.randn(B, T, n).astype(np.float32)),
                          jnp.asarray(lengths)),
        "lab": SeqTensor(jnp.asarray(rng.randint(0, n, (B, T)), jnp.int32),
                         jnp.asarray(lengths)),
    }
    outs, _ = net.apply(params, batch, state=state)
    err = np.asarray(outs[dec.name].data)
    assert err.shape == (B, T)
    assert set(np.unique(err)).issubset({0.0, 1.0})


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------


def _ctc_batch(B, T, C, Lmax, seed=0):
    rng = np.random.RandomState(seed)
    logits = rng.randn(B, T, C).astype(np.float32)
    in_len = rng.randint(Lmax + 1, T + 1, size=B).astype(np.int32)
    lab_len = rng.randint(1, Lmax + 1, size=B).astype(np.int32)
    # labels in 1..C-1 (0 is the blank in warp_ctc convention)
    labels = rng.randint(1, C, size=(B, Lmax)).astype(np.int32)
    return logits, in_len, labels, lab_len


def test_ctc_matches_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    B, T, C, Lmax = 4, 8, 5, 3
    logits, in_len, labels, lab_len = _ctc_batch(B, T, C, Lmax)

    probs = L.data("probs", paddle.data_type.dense_vector_sequence(C))
    lab = L.data("lab", paddle.data_type.integer_value_sequence(C))
    cost = L.warp_ctc(probs, lab, size=C, blank=0)
    topo = Topology([cost])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {
        "probs": SeqTensor(jnp.asarray(logits), jnp.asarray(in_len)),
        "lab": SeqTensor(jnp.asarray(labels), jnp.asarray(lab_len)),
    }
    outs, _ = net.apply(params, batch, state=state)
    got = np.asarray(outs[cost.name].data)[:, 0]

    lp = F.log_softmax(torch.tensor(logits), dim=-1).transpose(0, 1)  # [T,B,C]
    expect = F.ctc_loss(
        lp,
        torch.tensor(labels),
        torch.tensor(in_len),
        torch.tensor(lab_len),
        blank=0,
        reduction="none",
    ).numpy()
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)


def test_ctc_empty_label():
    """Empty target: NLL must be -log P(all blank) exactly (regression: the
    s_eff==1 case double-counted the final alpha)."""
    B, T, C = 1, 2, 3
    logits = np.zeros((B, T, C), np.float32)  # uniform: p(blank)=1/3 each step
    probs = L.data("probs", paddle.data_type.dense_vector_sequence(C))
    lab = L.data("lab", paddle.data_type.integer_value_sequence(C))
    cost = L.warp_ctc(probs, lab, size=C, blank=0)
    topo = Topology([cost])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {
        "probs": SeqTensor(jnp.asarray(logits), jnp.asarray([T], jnp.int32)),
        "lab": SeqTensor(jnp.zeros((B, 2), jnp.int32), jnp.asarray([0], jnp.int32)),
    }
    outs, _ = net.apply(params, batch, state=state)
    np.testing.assert_allclose(
        float(outs[cost.name].data[0, 0]), 2 * np.log(3.0), rtol=1e-5
    )


def test_ctc_grad():
    B, T, C, Lmax = 3, 6, 4, 2
    logits, in_len, labels, lab_len = _ctc_batch(B, T, C, Lmax, seed=7)
    probs = L.data("probs", paddle.data_type.dense_vector_sequence(C))
    lab = L.data("lab", paddle.data_type.integer_value_sequence(C))
    cost = L.warp_ctc(probs, lab, size=C, blank=0)
    batch = {
        "probs": SeqTensor(jnp.asarray(logits), jnp.asarray(in_len)),
        "lab": SeqTensor(jnp.asarray(labels), jnp.asarray(lab_len)),
    }
    check_layer_grad(cost, batch=batch)

