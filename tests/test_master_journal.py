"""The master's durable state plane: CRC-framed journal + snapshot
compaction + replay (master_journal.py, master.Service journal=True).

The contracts under test are the ISSUE-7 satellite list verbatim: a torn
final record is tolerated (prefix-consistent replay), a CRC-corrupt
complete record stops replay at the good prefix and is flagged by the
lint, compaction is equivalence-preserving (replay(snapshot + journal) ==
live state), replay is idempotent under double delivery, an unknown
record type is a HARD error everywhere, and a fenced (deposed) leader can
never append again."""

import json
import os

import numpy as np
import pytest

from paddle_tpu import master as master_mod
from paddle_tpu import master_journal as mj
from paddle_tpu.io import recordio


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _write(path, n=80, chunk=10):
    recordio.write_records(
        path, (f"{i}".encode() for i in range(n)), max_chunk_records=chunk,
    )


def _make_service(tmp_path, clock=None, **kw):
    """Journaled 4-task service over a deterministic dataset."""
    data = str(tmp_path / "d.rio")
    if not os.path.exists(data):
        _write(data)
    kw.setdefault("chunks_per_task", 2)
    kw.setdefault("auto_rotate", False)
    kw.setdefault("journal", True)
    kw.setdefault("journal_fsync", False)  # unit tests grind records
    svc = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"),
        clock=clock or _FakeClock(), **kw,
    )
    svc.set_dataset([data])
    return svc


def _journal_path(tmp_path):
    snap = json.load(open(tmp_path / "snap.json"))
    assert snap.get("journal_file"), "snapshot is not journal-anchored"
    return str(tmp_path / snap["journal_file"])


def _tree_equal(a, b):
    if isinstance(a, dict) or isinstance(b, dict):
        return (
            isinstance(a, dict) and isinstance(b, dict)
            and a.keys() == b.keys()
            and all(_tree_equal(a[k], b[k]) for k in a)
        )
    return np.array_equal(np.asarray(a), np.asarray(b))


def _fingerprint(svc):
    """Everything the queue/cluster plane knows, minus runtime deadlines
    (which recovery deliberately refreshes)."""
    with svc._lock:
        return {
            "pass_id": svc.pass_id,
            "todo": sorted((t.task_id, t.epoch) for t in svc.todo),
            "pending": sorted(
                (tid, ent[0].epoch, ent[2])
                for tid, ent in svc.pending.items()
            ),
            "done": sorted((t.task_id, t.epoch) for t in svc.done),
            "discarded": sorted(t.task_id for t in svc.discarded),
            "fail_events": svc.fail_events,
            "workers": sorted(svc.workers),
            "pass_done": dict(svc._pass_done),
            "fences": {
                fid: (sorted(f["arrived"]), f["released"])
                for fid, f in svc.fences.items()
            },
        }


def _results_equal(a, b):
    sa = {p: dict(a.results.get(p, {})) for p in a.results}
    sb = {p: dict(b.results.get(p, {})) for p in b.results}
    if sa.keys() != sb.keys():
        return False
    for p in sa:
        if sa[p].keys() != sb[p].keys():
            return False
        for tid in sa[p]:
            if not _tree_equal(sa[p][tid], sb[p][tid]):
                return False
    return True


def _workload(svc):
    """A representative mid-pass history touching every record type the
    live plane emits: leases, finishes with numpy result payloads, a
    failure, a graceful return, registry join/leave, fence arrivals and a
    release, one full rotation, and a pass-1 lease."""
    svc.register_worker("w0")
    svc.register_worker("w1")
    svc.register_worker("w2")
    served = {}
    for w in ("w0", "w1", "w2"):
        got = svc.get_task(w)
        served[w] = (got["task"]["task_id"], got["epoch"])
    # w1's task fails once (epoch walk), w2 hands its back gracefully
    svc.task_failed(*served["w1"])
    svc.task_returned(*served["w2"])
    svc.deregister_worker("w2")
    # drain pass 0 on w0/w1 with per-task result payloads
    svc.task_finished(
        *served["w0"],
        {"g": np.arange(4, dtype=np.float32) + served["w0"][0], "rows": 10},
    )
    while True:
        got = svc.get_task("w0")
        if got in (None, "wait"):
            break
        svc.task_finished(
            got["task"]["task_id"], got["epoch"],
            {"g": np.arange(4, dtype=np.float32) + got["task"]["task_id"],
             "rows": 10},
        )
    svc.fence_arrive("pass-0", "w0", {"ckpt": True})
    svc.fence_arrive("pass-0", "w1", {"ckpt": False})
    assert svc.fence_status("pass-0")["released"]
    svc.start_new_pass(1)
    got = svc.get_task("w0")  # one warm mid-pass-1 lease
    assert got not in (None, "wait")


# ---------------------------------------------------------------------------
# framing: torn tail, CRC corruption, unknown types, sequence order
# ---------------------------------------------------------------------------

def test_frame_roundtrip(tmp_path):
    p = str(tmp_path / "j.log")
    w = mj.JournalWriter(p, fsync=False)
    recs = [{"t": "join", "worker": f"w{i}", "blob": b"x" * i}
            for i in range(20)]
    for i, r in enumerate(recs):
        w.append(i + 1, r)
    w.close()
    got, info = read = mj.read_records(p)
    assert not info["torn"] and not info["corrupt"]
    assert info["end_offset"] == os.path.getsize(p)
    assert [s for s, _ in got] == list(range(1, 21))
    assert [r for _, r in got] == recs
    # offset resume: re-read from the middle yields the tail only
    mid_off = None
    off = 0
    for i, (s, r) in enumerate(got):
        if i == 10:
            mid_off = off
        off += len(mj.encode_frame(s, r))
    tail, info2 = mj.read_records(p, offset=mid_off)
    assert [s for s, _ in tail] == list(range(11, 21))
    # the resume contract a tailer stands on: end_offset is ABSOLUTE, so
    # feeding it back as the next offset neither regresses (re-reads) nor
    # lands mid-frame (fake corruption) — frames here are variable-size
    # on purpose
    assert info2["end_offset"] == os.path.getsize(p)
    again, info3 = mj.read_records(p, offset=info2["end_offset"])
    assert again == [] and not info3["corrupt"]
    assert info3["end_offset"] == os.path.getsize(p)


def test_torn_final_record_is_tolerated(tmp_path):
    p = str(tmp_path / "j.log")
    w = mj.JournalWriter(p, fsync=False)
    for i in range(3):
        w.append(i + 1, {"t": "join", "worker": f"w{i}"})
    w.close()
    os.truncate(p, os.path.getsize(p) - 3)  # crash mid-append
    got, info = mj.read_records(p)
    assert [s for s, _ in got] == [1, 2]
    assert info["torn"] and not info["corrupt"]
    findings = mj.verify_journal(p)
    assert [f["rule"] for f in findings] == ["J004"]
    assert findings[0]["severity"] == "warning"


def test_crc_corrupt_mid_record_stops_at_prefix(tmp_path):
    p = str(tmp_path / "j.log")
    w = mj.JournalWriter(p, fsync=False)
    offs = []
    for i in range(3):
        offs.append(w.tell())
        w.append(i + 1, {"t": "join", "worker": f"w{i}"})
    w.close()
    # flip one payload byte of the COMPLETE middle record
    with open(p, "r+b") as f:
        f.seek(offs[1] + 20)
        b = f.read(1)
        f.seek(offs[1] + 20)
        f.write(bytes([b[0] ^ 0xFF]))
    got, info = mj.read_records(p)
    assert [s for s, _ in got] == [1]  # replay stops at the good prefix
    assert info["corrupt"]
    rules = [f["rule"] for f in mj.verify_journal(p)]
    assert "J001" in rules


def test_unknown_record_type_is_hard_error(tmp_path):
    p = str(tmp_path / "j.log")
    w = mj.JournalWriter(p, fsync=False)
    w.append(1, {"t": "join", "worker": "w0"})
    w.append(2, {"t": "frobnicate", "x": 1})  # version skew / corruption
    w.close()
    findings = mj.verify_journal(p)
    assert any(
        f["rule"] == "J002" and f["severity"] == "error" for f in findings
    )
    svc = _make_service(tmp_path)
    with pytest.raises(mj.JournalError):
        svc.apply_record(svc._seq + 1, {"t": "frobnicate", "x": 1})


def test_non_monotonic_sequence_flagged(tmp_path):
    p = str(tmp_path / "j.log")
    w = mj.JournalWriter(p, fsync=False)
    w.append(5, {"t": "join", "worker": "a"})
    w.append(3, {"t": "join", "worker": "b"})
    w.close()
    assert any(f["rule"] == "J003" for f in mj.verify_journal(p))


def test_cli_lint_journal(tmp_path, capsys):
    from paddle_tpu.cli import cmd_lint

    p = str(tmp_path / "j.log")
    w = mj.JournalWriter(p, fsync=False)
    for i in range(4):
        w.append(i + 1, {"t": "join", "worker": f"w{i}"})
    w.close()
    assert cmd_lint(["--journal", p]) == 0
    assert "no diagnostics" in capsys.readouterr().out
    w = mj.JournalWriter(p, fsync=False, fresh=False)
    w.append(9, {"t": "martian"})
    w.close()
    assert cmd_lint(["--journal", p]) == 1
    assert "J002" in capsys.readouterr().out


def test_every_journaled_record_type_is_known_and_applicable():
    """Emission <-> registration <-> replay coverage, now owned by the
    protocol lint (rule P502 in analysis/protocol_lint.py) instead of a
    hand-rolled AST walk here: the package must carry zero P502 findings,
    and — so this assertion can't rot into a vacuous pass — a seeded
    typo'd emission must make P502 fire through the same entry point."""
    from paddle_tpu.analysis import format_diagnostics
    from paddle_tpu.analysis.protocol_lint import (
        PROTOCOL_FILES,
        lint_protocol_sources,
    )

    pkg = os.path.dirname(master_mod.__file__)
    srcs = {
        rel: open(os.path.join(pkg, rel), encoding="utf-8").read()
        for rel in PROTOCOL_FILES
    }
    p502 = [d for d in lint_protocol_sources(srcs) if d.rule == "P502"]
    assert p502 == [], format_diagnostics(p502)

    mutated = dict(srcs)
    mutated["master.py"] = srcs["master.py"].replace(
        '{"t": "rotate", "from": from_pass}',
        '{"t": "rotateX", "from": from_pass}', 1)
    assert mutated["master.py"] != srcs["master.py"]
    assert any(d.rule == "P502"
               for d in lint_protocol_sources(mutated))


# ---------------------------------------------------------------------------
# service-level: recovery equivalence, compaction, idempotence, fencing
# ---------------------------------------------------------------------------

def test_recovery_replays_to_live_state(tmp_path):
    svc = _make_service(tmp_path)
    _workload(svc)
    live_fp = _fingerprint(svc)
    svc.fence()  # deposed: the recovering leader owns the files now
    twin = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"),
        chunks_per_task=2, auto_rotate=False, journal=True,
        journal_fsync=False, clock=_FakeClock(),
    )
    assert twin.replayed_records > 0
    assert _fingerprint(twin) == live_fp
    assert _results_equal(twin, svc)


def test_compaction_equivalence(tmp_path):
    """Force several mid-workload compactions: the snapshot absorbs the
    journal, result payloads are re-emitted into the fresh generation, and
    replay(snapshot + journal) still equals the live state — with exactly
    one generation left on disk."""
    svc = _make_service(tmp_path, journal_compact_every=3)
    _workload(svc)
    assert svc._journal_gen > 1  # compaction actually happened
    live_fp = _fingerprint(svc)
    svc.fence()
    import glob
    gens = glob.glob(str(tmp_path / "master_journal-*.log"))
    assert len(gens) == 1  # older generations swept
    twin = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"),
        chunks_per_task=2, auto_rotate=False, journal=True,
        journal_fsync=False, clock=_FakeClock(),
    )
    assert _fingerprint(twin) == live_fp
    assert _results_equal(twin, svc)


def test_replay_is_idempotent_under_double_delivery(tmp_path):
    svc = _make_service(tmp_path)
    _workload(svc)
    records, info = mj.read_records(_journal_path(tmp_path))
    assert records and not info["torn"] and not info["corrupt"]
    svc.fence()
    twin = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"),
        chunks_per_task=2, auto_rotate=False, journal=True,
        journal_fsync=False, clock=_FakeClock(),
    )
    fp = _fingerprint(twin)
    # a tailing standby re-reading the same records must change nothing
    assert all(not twin.apply_record(s, r) for s, r in records)
    assert _fingerprint(twin) == fp


def test_torn_tail_recovery_applies_the_prefix(tmp_path):
    svc = _make_service(tmp_path)
    got = svc.get_task("w0")
    tid, epoch = got["task"]["task_id"], got["epoch"]
    jpath = _journal_path(tmp_path)
    before_last = os.path.getsize(jpath)
    fp_before_last = _fingerprint(svc)
    svc.task_finished(tid, epoch, {"g": np.ones(2, np.float32)})
    svc.fence()
    os.truncate(jpath, before_last + 7)  # crash mid-append of the finish
    twin = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"),
        chunks_per_task=2, auto_rotate=False, journal=True,
        journal_fsync=False, clock=_FakeClock(),
    )
    # the torn finish never happened; the lease survives warm, so the
    # worker's retried ack (at-least-once) completes it without recompute
    assert _fingerprint(twin) == fp_before_last
    assert twin.task_finished(tid, epoch, {"g": np.ones(2, np.float32)})


def test_failover_keeps_results_and_warm_leases_zero_recompute(tmp_path):
    """The tentpole contract in miniature: finished tasks keep their
    result payloads across a failover, in-flight leases stay warm (the
    retried ack is absorbed), and requeue_unresulted finds NOTHING to
    recompute."""
    svc = _make_service(tmp_path)
    svc.register_worker("w0")
    svc.register_worker("w1")
    done = {}
    for _ in range(2):
        got = svc.get_task("w0")
        payload = {
            "g": np.full(3, got["task"]["task_id"], np.float32), "rows": 10,
        }
        svc.task_finished(got["task"]["task_id"], got["epoch"], payload)
        done[got["task"]["task_id"]] = payload
    inflight = svc.get_task("w1")
    svc.fence()  # kill -9 the leader
    twin = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"),
        chunks_per_task=2, auto_rotate=False, journal=True,
        journal_fsync=False, clock=_FakeClock(),
    )
    assert twin.requeue_unresulted() == 0  # nothing to recompute
    res = twin.pass_results(0)["results"]
    assert res.keys() == done.keys()
    assert all(_tree_equal(res[t], done[t]) for t in done)
    # w1 never heard the old leader's reply: the re-served lease is the
    # SAME task, and the retried ack lands
    tid, epoch = inflight["task"]["task_id"], inflight["epoch"]
    reserved = twin.get_task("w1")
    assert reserved["task"]["task_id"] == tid and reserved["epoch"] == epoch
    assert twin.task_finished(tid, epoch, {"g": np.zeros(3, np.float32)})


def test_fenced_leader_cannot_append(tmp_path):
    svc = _make_service(tmp_path)
    got = svc.get_task("w0")
    jpath = _journal_path(tmp_path)
    size = os.path.getsize(jpath)
    svc.fence()
    # the deposed leader still mutates its own memory, but the shared
    # journal and snapshot never see it
    svc.task_finished(got["task"]["task_id"], got["epoch"], {"g": [1.0]})
    svc.register_worker("zombie")
    assert os.path.getsize(jpath) == size


def test_legacy_snapshot_upgrade_boot(tmp_path):
    """A journal=False master's snapshot (v1, no journal_file) boots a
    journaled successor: pending requeues (legacy semantics — the lease
    records never existed), then the plane is journal-anchored."""
    data = str(tmp_path / "d.rio")
    _write(data)
    old = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), chunks_per_task=2,
        auto_rotate=False, snapshot_min_interval_s=0.0, journal=False,
    )
    old.set_dataset([data])
    got = old.get_task("w0")
    old.task_finished(got["task"]["task_id"], got["epoch"])
    old.get_task("w0")  # leave one pending
    old.fence()
    new = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), chunks_per_task=2,
        auto_rotate=False, journal=True, journal_fsync=False,
    )
    assert len(new.pending) == 0  # legacy pending went back to todo
    assert new.n_tasks() == 4
    assert json.load(open(tmp_path / "snap.json")).get("journal_file")


def test_deposed_leader_compaction_fences_instead_of_truncating(tmp_path):
    """The compaction-side fence: a deposed-but-not-yet-fenced leader that
    reaches its compaction threshold must NOT rewrite the shared plane —
    the published snapshot references the NEW leader's generation, so the
    zombie fences itself instead of truncating the live journal /
    replacing the snapshot / sweeping the other generations."""
    a = _make_service(tmp_path)
    got = a.get_task("w0")
    a.task_finished(got["task"]["task_id"], got["epoch"], {"r": 1})

    # the new leader recovers from the shared plane and re-anchors it
    # into its own generation (exactly what boot/promote do)
    b = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), journal=True,
        journal_fsync=False, chunks_per_task=2, auto_rotate=False,
        clock=_FakeClock(),
    )
    b_file = mj.journal_filename(b._journal_gen)
    b_size = os.path.getsize(tmp_path / b_file)

    # the zombie hits its compaction threshold
    a._compact()
    assert a.snapshot_path is None  # fenced: never writes shared files again
    assert a._journal_writer is None
    # ...and B's plane is untouched: snapshot still references B's
    # generation, B's journal bytes intact, B can still append
    snap = json.load(open(tmp_path / "snap.json"))
    assert snap["journal_file"] == b_file
    assert os.path.getsize(tmp_path / b_file) == b_size
    got_b = b.get_task("w1")
    assert got_b is not None
    assert mj.verify_journal(str(tmp_path / b_file)) == []


def test_midlife_generation_collision_fences(tmp_path):
    """If the target generation file already exists at a MID-LIFE
    compaction (a racing new leader created it in the check-to-create
    window), the exclusive create fails and the leader fences — only a
    freshly-acquired lease (boot/promote) may reclaim such a file."""
    svc = _make_service(tmp_path)
    racer = tmp_path / mj.journal_filename(svc._journal_gen + 1)
    racer.write_bytes(b"")  # the racing leader's freshly-created file
    svc._compact()
    assert svc.snapshot_path is None  # fenced
    assert racer.read_bytes() == b""  # never touched the racer's file


def test_boot_reclaims_unpublished_crash_orphan(tmp_path):
    """A compaction that died between writing the new generation and
    publishing the snapshot leaves an orphan file one generation above
    the published one.  The next boot (which holds the fresh lease) must
    reclaim it — not fence on the collision, not recover garbage."""
    a = _make_service(tmp_path)
    got = a.get_task("w0")
    a.task_finished(got["task"]["task_id"], got["epoch"], {"r": 7})
    fp = _fingerprint(a)
    orphan = tmp_path / mj.journal_filename(a._journal_gen + 1)
    orphan.write_bytes(b"half-written garbage")  # crashed mid-compaction
    a.fence()

    b = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), journal=True,
        journal_fsync=False, chunks_per_task=2, auto_rotate=False,
        clock=_FakeClock(),
    )
    assert _fingerprint(b) == fp  # recovered the real state...
    snap = json.load(open(tmp_path / "snap.json"))
    assert snap["journal_file"] == mj.journal_filename(b._journal_gen)
    assert mj.verify_journal(
        str(tmp_path / snap["journal_file"])
    ) == []  # ...and owns a clean reclaimed generation


def test_failed_compaction_rolls_back_and_retries(tmp_path):
    """A transient disk failure mid-compaction (ENOSPC, EIO) must not
    desync the generation counter: a dangling bump would make the NEXT
    compaction see the published snapshot as someone else's and silently
    self-fence this HEALTHY leader — acks would keep flowing while the
    journal silently stopped.  Instead the failed attempt rolls back,
    appends keep landing durably in the old generation, and a later
    compaction succeeds and publishes the new one."""
    svc = _make_service(tmp_path)
    gen0 = svc._journal_gen
    got = svc.get_task("w0")
    svc.task_finished(got["task"]["task_id"], got["epoch"], {"r": 1})

    real = svc._write_snapshot
    calls = {"n": 0}

    def failing(*a, **kw):
        calls["n"] += 1
        raise OSError(28, "No space left on device")

    svc._write_snapshot = failing
    svc._compact()
    svc._write_snapshot = real

    assert calls["n"] == 1
    assert svc.snapshot_path is not None  # NOT fenced
    assert svc._journal_writer is not None  # still appending durably
    assert svc._journal_gen == gen0  # generation rolled back
    # the partial new generation was removed: the retry's O_EXCL create
    # must not collide with our own failed attempt
    assert not os.path.exists(tmp_path / mj.journal_filename(gen0 + 1))

    # transitions keep landing in the old generation...
    got2 = svc.get_task("w1")
    svc.task_finished(got2["task"]["task_id"], got2["epoch"], {"r": 2})
    fp = _fingerprint(svc)

    # ...and the retried compaction publishes the next generation cleanly
    svc._compact()
    snap = json.load(open(tmp_path / "snap.json"))
    assert snap["journal_file"] == mj.journal_filename(gen0 + 1)
    assert mj.verify_journal(str(tmp_path / snap["journal_file"])) == []

    svc.fence()
    b = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), journal=True,
        journal_fsync=False, chunks_per_task=2, auto_rotate=False,
        clock=_FakeClock(),
    )
    assert _fingerprint(b) == fp  # nothing was lost along the way


def test_promote_reclaims_plane_over_zombie_last_gasp_publish(tmp_path):
    """A deposed leader waking in the lease-gap window can publish one
    last compaction AFTER the standby tailed its final record.  The
    lease-holding promotion must RECLAIM the plane — adopt the zombie's
    generation as base and re-anchor above it — not silently fence
    itself: a self-fenced fresh leader would serve the whole fleet with
    journal and snapshot OFF, and the next failover would lose the
    entire leadership's state."""
    a = _make_service(tmp_path)
    got = a.get_task("w0")
    a.task_finished(got["task"]["task_id"], got["epoch"], {"r": 3})
    fp = _fingerprint(a)

    # the replica a standby's _standby_tick would have built from the
    # shared plane (snapshot + journal tail)
    snap_state = json.load(open(tmp_path / "snap.json"))
    jf = snap_state["journal_file"]
    replica = master_mod.Service(
        snapshot_path=None, journal=False, chunks_per_task=2,
        auto_rotate=False, clock=_FakeClock(),
    )
    replica.load_state(snap_state, warm=True)
    for seq, rec in mj.read_records(str(tmp_path / jf))[0]:
        replica.apply_record(seq, rec)
    replica._journal_gen = mj.parse_generation(jf)

    # ...then the zombie (deposed but not yet fenced) publishes one last
    # compaction before the replica promotes
    a._compact()
    zombie_file = json.load(open(tmp_path / "snap.json"))["journal_file"]
    assert zombie_file != jf

    replica.promote(str(tmp_path / "snap.json"), journal_fsync=False)
    assert replica.snapshot_path is not None  # NOT fenced
    assert replica._journal_writer is not None  # journaling is ON
    assert _fingerprint(replica) == fp
    published = json.load(open(tmp_path / "snap.json"))["journal_file"]
    assert published == mj.journal_filename(replica._journal_gen)
    assert mj.parse_generation(published) > mj.parse_generation(zombie_file)
    # and the reclaimed plane is live: appends land in the new generation
    assert replica.get_task("w1") is not None
    assert mj.verify_journal(str(tmp_path / published)) == []


def test_stalled_zombie_compaction_cannot_publish_over_new_leader(tmp_path):
    """The O_EXCL fence alone cannot stop a leader that stalls INSIDE its
    compaction (slow fsync) past the lease: a new leader reclaims by
    skipping the contested generation name, so the zombie's exclusive
    create already succeeded.  The pre-publish ownership re-verify must
    catch it: the zombie wakes, sees the snapshot no longer references
    what it prechecked, fences itself, and never replaces the rightful
    leader's snapshot with stale state."""
    a = _make_service(tmp_path)
    got = a.get_task("w0")
    a.task_finished(got["task"]["task_id"], got["epoch"], {"r": 1})
    fp = _fingerprint(a)

    real_sync = mj.JournalWriter.sync
    state = {"fired": False}
    b_box = {}

    def stalling_sync(self):
        if not state["fired"]:
            state["fired"] = True
            # while A's compaction is parked on this fsync, its lease
            # expires and a new leader boots from the shared plane
            b_box["b"] = master_mod.Service(
                snapshot_path=str(tmp_path / "snap.json"), journal=True,
                journal_fsync=False, chunks_per_task=2, auto_rotate=False,
                clock=_FakeClock(),
            )
        return real_sync(self)

    mj.JournalWriter.sync = stalling_sync
    try:
        a._compact()  # the zombie's compaction, interleaved with B's boot
    finally:
        mj.JournalWriter.sync = real_sync

    b = b_box["b"]
    assert a.snapshot_path is None  # zombie fenced itself mid-compaction
    assert _fingerprint(b) == fp  # B recovered the full acked state
    snap = json.load(open(tmp_path / "snap.json"))
    assert snap["journal_file"] == mj.journal_filename(b._journal_gen)
    assert mj.verify_journal(
        str(tmp_path / snap["journal_file"])
    ) == []  # ...and the plane B owns is intact, not overwritten


def test_zombie_post_publish_sweep_cannot_delete_new_leaders_generation(
    tmp_path,
):
    """A zombie that stalls BETWEEN its snapshot publish and its
    old-generation sweep passes every pre-publish fence — its publish was
    legitimate when it happened.  If the sweep then removes "everything
    but my own file", it unlinks the live generation a reclaiming new
    leader anchored ABOVE it (reclaim adopts the published generation as
    its base), and every transition the new leader acks afterwards is
    invisible to recovery.  The sweep must only collect generations
    strictly below the sweeper's own."""
    a = _make_service(tmp_path)
    got = a.get_task("w0")
    a.task_finished(got["task"]["task_id"], got["epoch"], {"r": 1})

    real_write = a._write_snapshot
    b_box = {}

    def publish_then_stall(**kwargs):
        real_write(**kwargs)
        # parked right after its publish, A's lease expires; a new leader
        # boots from the shared plane and re-anchors ABOVE A's generation
        b_box["b"] = master_mod.Service(
            snapshot_path=str(tmp_path / "snap.json"), journal=True,
            journal_fsync=False, chunks_per_task=2, auto_rotate=False,
            clock=_FakeClock(),
        )

    a._write_snapshot = publish_then_stall
    try:
        a._compact()  # the zombie wakes and sweeps AFTER B re-anchored
    finally:
        del a._write_snapshot

    b = b_box["b"]
    bfile = tmp_path / mj.journal_filename(b._journal_gen)
    assert bfile.exists()  # the sweep did not unlink B's live generation
    snap = json.load(open(tmp_path / "snap.json"))
    assert snap["journal_file"] == bfile.name
    # B keeps acking durably: a cold recovery replays to B's live state
    got = b.get_task("w1")
    b.task_finished(got["task"]["task_id"], got["epoch"], {"r": 2})
    fp = _fingerprint(b)
    c = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), journal=True,
        journal_fsync=False, chunks_per_task=2, auto_rotate=False,
        clock=_FakeClock(),
    )
    assert _fingerprint(c) == fp
    assert _results_equal(c, b)
