"""Pallas flash-attention kernel: exactness (fwd + custom-VJP backward) vs
dense attention, via interpret mode on the CPU test mesh.  The real-TPU
lowering is exercised by the verify drives and the transformer bench."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_attention import (
    flash_attention,
    flash_attention_diff,
    supported,
)


def _dense(q, k, v, lengths=None, causal=False):
    b, t, h, dh = q.shape
    P = jax.lax.Precision.HIGHEST
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, precision=P) / math.sqrt(dh)
    if lengths is not None:
        s = jnp.where(
            (jnp.arange(t)[None, :] < lengths[:, None])[:, None, None, :],
            s, -jnp.inf,
        )
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -jnp.inf)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v, precision=P
    )


def _qkv(t=256, b=2, h=2, dh=64, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, dh), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense_interpret(causal):
    q, k, v = _qkv()
    lens = jnp.asarray([256, 173], jnp.int32)
    got = flash_attention(q, k, v, lengths=lens, causal=causal, interpret=True)
    want = _dense(q, k, v, lengths=lens, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense_interpret(causal):
    q, k, v = _qkv(t=128)
    lens = jnp.asarray([128, 90], jnp.int32)

    def loss_flash(q_, k_, v_):
        o = flash_attention_diff(q_, k_, v_, lens, causal, 128, 128, True)
        return jnp.sum(o**2)

    def loss_dense(q_, k_, v_):
        return jnp.sum(_dense(q_, k_, v_, lens, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)


def test_flash_padding_invariance_interpret():
    q, k, v = _qkv(t=128)
    lens = jnp.asarray([70, 128], jnp.int32)
    base = flash_attention(q, k, v, lengths=lens, interpret=True)
    k2 = k.at[0, 70:].set(50.0)
    v2 = v.at[0, 70:].set(-50.0)
    pert = flash_attention(q, k2, v2, lengths=lens, interpret=True)
    np.testing.assert_allclose(np.asarray(base), np.asarray(pert), atol=5e-5)


def test_supported_shapes():
    assert supported(256, 64)
    assert supported(128, 8)
    assert not supported(100, 64)  # T not a block multiple
    assert not supported(64, 64)  # too short to pay off
    assert not supported(256, 7)  # lane-hostile head dim
