"""GAN + VAE demos (v1_api_demo/{gan,vae} parity): both generative trainers
learn simple synthetic distributions."""

import numpy as np
import pytest

from paddle_tpu.models.gan import GANTrainer
from paddle_tpu.models.vae import VAETrainer


def test_gan_learns_gaussian():
    """G must move its output distribution onto N(3, 0.5)^2."""
    rng = np.random.RandomState(0)
    gan = GANTrainer(noise_dim=4, data_dim=2, hidden=32, seed=1)
    before = gan.sample(512, np.random.RandomState(99))
    for _ in range(400):
        real = (3.0 + 0.5 * rng.randn(64, 2)).astype(np.float32)
        gan.train_batch(real, rng)
    after = gan.sample(512, np.random.RandomState(99))
    # mean moved to ~3 on both dims; it started near 0
    assert np.abs(before.mean(0)).max() < 1.5
    np.testing.assert_allclose(after.mean(0), [3.0, 3.0], atol=0.6)
    assert 0.1 < after.std(0).mean() < 1.5  # not collapsed to a point mass


def test_gan_losses_are_finite_and_adversarial():
    rng = np.random.RandomState(2)
    gan = GANTrainer(noise_dim=3, data_dim=2, hidden=16, seed=3)
    d_losses, g_losses = [], []
    for _ in range(50):
        real = (1.0 + 0.2 * rng.randn(32, 2)).astype(np.float32)
        d, g = gan.train_batch(real, rng)
        d_losses.append(d)
        g_losses.append(g)
    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()
    # adversarial dynamics: D beats chance (2*ln2 ~ 1.386) at some point,
    # and G keeps pushing back (its loss stays bounded, no collapse to 0)
    assert min(d_losses) < 1.3
    assert max(g_losses) > 0.05


def test_vae_reconstructs_and_samples():
    rng = np.random.RandomState(0)
    centers = np.asarray([[2.0, 2.0, 2.0, 2.0], [-2.0, -2.0, -2.0, -2.0]])
    vae = VAETrainer(data_dim=4, latent_dim=2, hidden=32, lr=3e-3, seed=0)

    def batch(n=64):
        c = centers[rng.randint(2, size=n)]
        return (c + 0.2 * rng.randn(n, 4)).astype(np.float32)

    losses = [vae.train_batch(batch()) for _ in range(300)]
    assert np.mean(losses[-20:]) < 0.3 * np.mean(losses[:20])
    # reconstruction puts each point near its cluster center
    x = batch(128)
    rec = vae.reconstruct(x)
    assert np.mean(np.sum((rec - x) ** 2, axis=-1)) < 1.0
    # prior samples land near the data manifold (one of the two clusters)
    s = vae.sample(256)
    d = np.minimum(
        np.linalg.norm(s - centers[0], axis=-1),
        np.linalg.norm(s - centers[1], axis=-1),
    )
    assert np.median(d) < 2.0
