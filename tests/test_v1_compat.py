"""v1 config-file compatibility (north star: v1_api_demo configs run
unmodified).  parse_config mirrors python/paddle/trainer/config_parser.py:3669;
settings()/optimizer classes mirror trainer_config_helpers/optimizers.py."""

import io
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.v1_compat import make_optimizer, parse_config

REF = "/root/reference/v1_api_demo"
pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference demo configs not mounted"
)

HERE = os.path.dirname(__file__)


@pytest.fixture()
def dict_dir(tmp_path):
    """cwd with ./data/dict.txt — quick_start configs hardcode this path."""
    (tmp_path / "data").mkdir()
    (tmp_path / "data" / "dict.txt").write_text(
        "\n".join(f"w{i}\t{i}" for i in range(100))
    )
    old = os.getcwd()
    os.chdir(tmp_path)
    yield tmp_path
    os.chdir(old)


def test_light_mnist_builds_and_matches_golden():
    p = parse_config(f"{REF}/mnist/light_mnist.py")
    golden = open(os.path.join(HERE, "goldens", "v1_light_mnist.topo")).read()
    assert p.serialize() == golden
    # provider types resolved from mnist_provider's @provider declaration
    t = p.provider_input_types
    assert t["pixel"].dim == 784 and t["label"].dim == 10
    assert p.settings.batch_size == 50
    assert p.settings.learning_method.kind == "adam"


def test_light_mnist_predict_mode():
    p = parse_config(f"{REF}/mnist/light_mnist.py", "is_predict=1")
    # predict config skips the data sources and the cost layer
    assert p.data_sources is None
    assert all(
        p.topology.layers[n].type != "cross_entropy" for n in p.topology.order
    )


def test_vgg_16_mnist_builds():
    p = parse_config(f"{REF}/mnist/vgg_16_mnist.py")
    assert len(p.topology.order) > 30  # 4 conv groups with bn


def test_quick_start_lr_golden(dict_dir):
    p = parse_config(
        f"{REF}/quick_start/trainer_config.lr.py", "dict_file=data/dict.txt"
    )
    golden = open(os.path.join(HERE, "goldens", "v1_quick_start_lr.topo")).read()
    assert p.serialize() == golden
    assert p.provider_input_types["word"].dim == 100  # from the dict file
    assert p.settings.gradient_clipping_threshold == 25


@pytest.mark.parametrize(
    "cfg", ["lr", "emb", "cnn", "lstm", "bidi-lstm", "db-lstm", "resnet-lstm"]
)
def test_quick_start_configs_build(dict_dir, cfg):
    p = parse_config(f"{REF}/quick_start/trainer_config.{cfg}.py")
    assert len(p.topology.order) >= 4
    assert p.output_layers


def test_sequence_tagging_configs_build():
    p = parse_config(f"{REF}/sequence_tagging/linear_crf.py")
    assert any(p.topology.layers[n].type == "crf" for n in p.topology.order)
    assert len(p.evaluators) == 2  # sum + chunk evaluators recorded
    p2 = parse_config(f"{REF}/sequence_tagging/rnn_crf.py")
    assert any(p2.topology.layers[n].type == "crf" for n in p2.topology.order)


def test_traffic_prediction_config_builds():
    p = parse_config(f"{REF}/traffic_prediction/trainer_config.py")
    assert len(p.topology.order) > 50


def test_make_optimizer_mapping(dict_dir):
    p = parse_config(
        f"{REF}/quick_start/trainer_config.lr.py", "dict_file=data/dict.txt"
    )
    opt = make_optimizer(p.settings)
    import paddle_tpu.optimizer as O

    assert isinstance(opt, O.Adam)
    assert opt.learning_rate == pytest.approx(2e-3)
    assert opt.clip == 25
    assert isinstance(opt.regularization, O.L2Regularization)
    assert opt.regularization.rate == pytest.approx(8e-4)


def test_quick_start_lr_trains_end_to_end(dict_dir):
    """The north-star slice: a reference config + its reference data provider
    train through the v2 trainer with nothing modified."""
    p = parse_config(
        f"{REF}/quick_start/trainer_config.lr.py", "dict_file=data/dict.txt"
    )
    # synthesize a tiny dataset in the provider's expected format:
    # "<label>\t<word> <word> ..." with words from the dict
    rng = np.random.RandomState(0)
    train_file = dict_dir / "train.txt"
    lines = []
    for _ in range(600):
        label = rng.randint(2)
        base = 10 if label else 60
        words = [f"w{base + rng.randint(20)}" for _ in range(rng.randint(3, 8))]
        lines.append(f"{label}\t{' '.join(words)}")
    train_file.write_text("\n".join(lines))

    import importlib
    import sys

    sys.path.insert(0, f"{REF}/quick_start")
    try:
        provider_mod = importlib.import_module(p.data_sources.module)
    finally:
        sys.path.pop(0)
    word_dict = {f"w{i}": i for i in range(100)}
    reader = getattr(provider_mod, p.data_sources.obj)(
        str(train_file), dictionary=word_dict
    )

    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology,
        parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    costs = []
    trainer.train(
        reader=paddle.batch(reader, p.settings.batch_size),
        num_passes=10,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert np.mean(costs[-3:]) < 0.7 * np.mean(costs[:3]), costs


def test_positional_provider_types_pair_by_declaration_order(tmp_path):
    """Positional provider input_types must map to data layers in DECLARATION
    order even when graph-traversal order differs (label declared first but
    the cost graph visits pixel's subtree first)."""
    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_py_data_sources2(train_list='t', test_list=None,\n"
        "                        module='prov_mod', obj='process')\n"
        "settings(batch_size=4, learning_rate=1e-3,\n"
        "         learning_method=MomentumOptimizer())\n"
        "lbl = data_layer(name='label', size=10)\n"
        "img = data_layer(name='pixel', size=784)\n"
        "fc1 = fc_layer(input=img, size=10, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=fc1, label=lbl))\n"
    )
    (tmp_path / "prov_mod.py").write_text(
        "from paddle.trainer.PyDataProvider2 import *\n"
        "@provider(input_types=[integer_value(10), dense_vector(784)])\n"
        "def process(settings, f):\n"
        "    yield 0, [0.0] * 784\n"
    )
    p = parse_config(str(cfg))
    from paddle_tpu.core.data_types import SlotKind

    assert p.provider_input_types["label"].kind == SlotKind.INDEX
    assert p.provider_input_types["pixel"].kind == SlotKind.DENSE
    assert p.provider_input_types["pixel"].dim == 784


@pytest.mark.parametrize("mode", ["generator_training", "discriminator_training", "generator"])
def test_gan_configs_build(mode):
    p = parse_config(
        f"{REF}/gan/gan_conf.py",
        f"noise_dim=10,sample_dim=2,hidden_dim=16,mode={mode}",
    )
    assert len(p.topology.order) >= 4
    p2 = parse_config(
        f"{REF}/gan/gan_conf_image.py",
        f"noise_dim=100,sample_dim=28,c_dim=1,dataname=mnist_data,mode={mode}",
    )
    assert len(p2.topology.order) >= 6


def test_vae_config_builds_and_trains():
    """vae_conf.py exercises mixed_layer context blocks, layer_math, and
    LayerOutput arithmetic; the parsed topology must actually train."""
    p = parse_config(f"{REF}/vae/vae_conf.py")
    assert len(p.topology.order) > 15
    gen = parse_config(f"{REF}/vae/vae_conf.py", "is_generating=1")
    assert len(gen.topology.order) == 3

    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology, parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(64):
            yield (rng.rand(784).astype(np.float32),)

    costs = []
    trainer.train(
        reader=paddle.batch(reader, p.settings.batch_size), num_passes=3,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert np.isfinite(costs).all()
    assert np.mean(costs[-2:]) < np.mean(costs[:2])


def test_layer_math_and_mixed_context():
    from paddle_tpu.layers import layer_math
    import jax
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu import layers as L

    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(3))
    y = L.data("y", paddle.data_type.dense_vector(3))
    expr = layer_math.exp(x) * 0.5 + y - 1.0
    with L.mixed() as m:
        m += L.dotmul_projection(x)
    net = CompiledNetwork(Topology([expr, m]))
    params, state = net.init(jax.random.PRNGKey(0))
    xv = np.asarray([[0.0, 1.0, 2.0]], np.float32)
    yv = np.asarray([[10.0, 10.0, 10.0]], np.float32)
    outs, _ = net.apply(
        params, {"x": SeqTensor(xv), "y": SeqTensor(yv)}, state=state
    )
    np.testing.assert_allclose(
        np.asarray(outs[expr.name].data),
        np.exp(xv) * 0.5 + yv - 1.0,
        rtol=1e-5,
    )
    w = np.asarray(params[m.name]["p0_w"])
    np.testing.assert_allclose(np.asarray(outs[m.name].data), xv * w, rtol=1e-5)


@pytest.mark.parametrize("layer_num,n_layers", [(50, 123), (101, 242), (152, 361)])
def test_model_zoo_resnet_configs_build(layer_num, n_layers):
    """model_zoo/resnet/resnet.py (capital Settings/Inputs/Outputs config_parser
    face, default_momentum/decay_rate globals) builds at all bottleneck
    depths."""
    p = parse_config(
        f"{REF}/model_zoo/resnet/resnet.py", f"layer_num={layer_num},is_test=1"
    )
    assert len(p.topology.order) == n_layers
    assert p.output_layers  # resolved from Outputs(name, ...) strings
    assert p.settings.learning_method.kind == "momentum"
    import paddle_tpu.optimizer as O

    assert isinstance(make_optimizer(p.settings).regularization, O.L2Regularization)
