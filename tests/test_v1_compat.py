"""v1 config-file compatibility (north star: v1_api_demo configs run
unmodified).  parse_config mirrors python/paddle/trainer/config_parser.py:3669;
settings()/optimizer classes mirror trainer_config_helpers/optimizers.py."""

import io
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.v1_compat import make_optimizer, parse_config

REF = "/root/reference/v1_api_demo"
pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference demo configs not mounted"
)

HERE = os.path.dirname(__file__)


@pytest.fixture()
def dict_dir(tmp_path):
    """cwd with ./data/dict.txt — quick_start configs hardcode this path."""
    (tmp_path / "data").mkdir()
    (tmp_path / "data" / "dict.txt").write_text(
        "\n".join(f"w{i}\t{i}" for i in range(100))
    )
    old = os.getcwd()
    os.chdir(tmp_path)
    yield tmp_path
    os.chdir(old)


def test_light_mnist_builds_and_matches_golden():
    p = parse_config(f"{REF}/mnist/light_mnist.py")
    golden = open(os.path.join(HERE, "goldens", "v1_light_mnist.topo")).read()
    assert p.serialize() == golden
    # provider types resolved from mnist_provider's @provider declaration
    t = p.provider_input_types
    assert t["pixel"].dim == 784 and t["label"].dim == 10
    assert p.settings.batch_size == 50
    assert p.settings.learning_method.kind == "adam"


def test_light_mnist_predict_mode():
    p = parse_config(f"{REF}/mnist/light_mnist.py", "is_predict=1")
    # predict config skips the data sources and the cost layer
    assert p.data_sources is None
    assert all(
        p.topology.layers[n].type != "cross_entropy" for n in p.topology.order
    )


def test_vgg_16_mnist_builds():
    p = parse_config(f"{REF}/mnist/vgg_16_mnist.py")
    assert len(p.topology.order) > 30  # 4 conv groups with bn


def test_quick_start_lr_golden(dict_dir):
    p = parse_config(
        f"{REF}/quick_start/trainer_config.lr.py", "dict_file=data/dict.txt"
    )
    golden = open(os.path.join(HERE, "goldens", "v1_quick_start_lr.topo")).read()
    assert p.serialize() == golden
    assert p.provider_input_types["word"].dim == 100  # from the dict file
    assert p.settings.gradient_clipping_threshold == 25


@pytest.mark.parametrize(
    "cfg", ["lr", "emb", "cnn", "lstm", "bidi-lstm", "db-lstm", "resnet-lstm"]
)
def test_quick_start_configs_build(dict_dir, cfg):
    p = parse_config(f"{REF}/quick_start/trainer_config.{cfg}.py")
    assert len(p.topology.order) >= 4
    assert p.output_layers


def test_sequence_tagging_configs_build():
    p = parse_config(f"{REF}/sequence_tagging/linear_crf.py")
    assert any(p.topology.layers[n].type == "crf" for n in p.topology.order)
    assert len(p.evaluators) == 2  # sum + chunk evaluators recorded
    p2 = parse_config(f"{REF}/sequence_tagging/rnn_crf.py")
    assert any(p2.topology.layers[n].type == "crf" for n in p2.topology.order)


def test_traffic_prediction_config_builds():
    p = parse_config(f"{REF}/traffic_prediction/trainer_config.py")
    assert len(p.topology.order) > 50


def test_make_optimizer_mapping(dict_dir):
    p = parse_config(
        f"{REF}/quick_start/trainer_config.lr.py", "dict_file=data/dict.txt"
    )
    opt = make_optimizer(p.settings)
    import paddle_tpu.optimizer as O

    assert isinstance(opt, O.Adam)
    assert opt.learning_rate == pytest.approx(2e-3)
    assert opt.clip == 25
    assert isinstance(opt.regularization, O.L2Regularization)
    assert opt.regularization.rate == pytest.approx(8e-4)


def test_quick_start_lr_trains_end_to_end(dict_dir):
    """The north-star slice: a reference config + its reference data provider
    train through the v2 trainer with nothing modified."""
    p = parse_config(
        f"{REF}/quick_start/trainer_config.lr.py", "dict_file=data/dict.txt"
    )
    # synthesize a tiny dataset in the provider's expected format:
    # "<label>\t<word> <word> ..." with words from the dict
    rng = np.random.RandomState(0)
    train_file = dict_dir / "train.txt"
    lines = []
    for _ in range(600):
        label = rng.randint(2)
        base = 10 if label else 60
        words = [f"w{base + rng.randint(20)}" for _ in range(rng.randint(3, 8))]
        lines.append(f"{label}\t{' '.join(words)}")
    train_file.write_text("\n".join(lines))

    import importlib
    import sys

    sys.path.insert(0, f"{REF}/quick_start")
    try:
        provider_mod = importlib.import_module(p.data_sources.module)
    finally:
        sys.path.pop(0)
    word_dict = {f"w{i}": i for i in range(100)}
    reader = getattr(provider_mod, p.data_sources.obj)(
        str(train_file), dictionary=word_dict
    )

    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology,
        parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    costs = []
    trainer.train(
        reader=paddle.batch(reader, p.settings.batch_size),
        num_passes=10,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert np.mean(costs[-3:]) < 0.7 * np.mean(costs[:3]), costs


def test_positional_provider_types_pair_by_declaration_order(tmp_path):
    """Provider slot types that do not dim-check positionally against the
    feeding order (DFS from outputs — here [pixel, label], though label is
    declared first) are re-bound via the unique dim-consistent assignment:
    dense(784) can only be the 784-wide pixel layer."""
    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_py_data_sources2(train_list='t', test_list=None,\n"
        "                        module='prov_mod', obj='process')\n"
        "settings(batch_size=4, learning_rate=1e-3,\n"
        "         learning_method=MomentumOptimizer())\n"
        "lbl = data_layer(name='label', size=10)\n"
        "img = data_layer(name='pixel', size=784)\n"
        "fc1 = fc_layer(input=img, size=10, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=fc1, label=lbl))\n"
    )
    (tmp_path / "prov_mod.py").write_text(
        "from paddle.trainer.PyDataProvider2 import *\n"
        "@provider(input_types=[integer_value(10), dense_vector(784)])\n"
        "def process(settings, f):\n"
        "    yield 0, [0.0] * 784\n"
    )
    with pytest.warns(UserWarning, match="unique dim-consistent assignment"):
        p = parse_config(str(cfg))
    from paddle_tpu.core.data_types import SlotKind

    assert p.provider_input_types["label"].kind == SlotKind.INDEX
    assert p.provider_input_types["pixel"].kind == SlotKind.DENSE
    assert p.provider_input_types["pixel"].dim == 784
    # The permuted binding must come with the matching feeding map: provider
    # tuples stay in SLOT order (label first), so positional pairing against
    # the feeding order [pixel, label] would send the int label into the
    # pixel layer.  parse_config surfaces the permutation for the trainer.
    assert p.feeding == {"label": 0, "pixel": 1}
    import numpy as np

    from paddle_tpu.reader.feeder import DataFeeder

    feeder = DataFeeder(p.topology.data_types(), p.feeding)
    batch = feeder([(3, np.full(784, 0.5, np.float32))])
    assert batch["pixel"].data.shape == (1, 784)
    assert float(batch["pixel"].data[0, 0]) == 0.5
    assert int(batch["label"].data[0]) == 3


def test_label_first_config_feeds_in_dfs_order(tmp_path):
    """The googlenet regression (BENCH_r03): config declares label BEFORE
    input (benchmark/paddle/image/googlenet.py:146-147) while the provider's
    init_hook yields (image, label) — reference feeding order is DFS from
    the outputs (networks.py:1412 outputs() __dfs_travel__), so the dense
    image slot must bind to the image layer and an end-to-end feed + train
    step must run."""
    import jax
    import numpy as np

    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_py_data_sources2(train_list='t', test_list=None,\n"
        "                        module='prov_lf', obj='process')\n"
        "settings(batch_size=4, learning_rate=1e-3,\n"
        "         learning_method=MomentumOptimizer())\n"
        "lbl = data_layer(name='label', size=10)\n"
        "img = data_layer(name='input', size=48)\n"
        "fc1 = fc_layer(input=img, size=10, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=fc1, label=lbl))\n"
    )
    # init_hook-declared slots, image first — the googlenet provider.py shape
    (tmp_path / "prov_lf.py").write_text(
        "from paddle.trainer.PyDataProvider2 import *\n"
        "def hook(settings, **kw):\n"
        "    settings.slots = [dense_vector(48), integer_value(10)]\n"
        "@provider(init_hook=hook)\n"
        "def process(settings, f):\n"
        "    for i in range(8):\n"
        "        yield [0.1] * 48, i % 10\n"
    )
    p = parse_config(str(cfg))
    from paddle_tpu.core.data_types import SlotKind

    order = list(p.topology.data_layers())
    assert order == ["input", "label"], order
    dtypes = p.topology.data_types()
    assert dict(dtypes)["input"].kind == SlotKind.DENSE
    assert dict(dtypes)["input"].dim == 48
    assert dict(dtypes)["label"].kind == SlotKind.INDEX

    # end-to-end: feed rows in feeding order through the real converter and
    # take one train step (this is exactly what bench_googlenet does)
    import jax.numpy as jnp

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.reader.feeder import DataFeeder
    from paddle_tpu.trainer.step import make_train_step
    from paddle_tpu.v1_compat import make_optimizer

    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    opt = make_optimizer(p.settings)
    opt_state = opt.init(params)
    step = make_train_step(net, opt, mesh=None)
    feeder = DataFeeder(dtypes)
    rows = [(np.full(48, 0.1, np.float32), i % 10) for i in range(4)]
    params, state, opt_state, m = step(
        params, state, opt_state, feeder(rows), jax.random.PRNGKey(1)
    )
    assert np.isfinite(float(m["cost"]))


def test_first_sample_inference_binds_by_dim(tmp_path):
    """Introspection path (no declared types): a label-first config whose
    provider yields (image, label) must still resolve via the unique
    dim-consistent assignment, and int lists must infer as id sequences,
    never dense (even when len(list) == size)."""
    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_py_data_sources2(train_list='t', test_list=None,\n"
        "                        module='prov_inf', obj='process')\n"
        "settings(batch_size=4, learning_rate=1e-3)\n"
        "lbl = data_layer(name='label', size=7)\n"
        "img = data_layer(name='input', size=32)\n"
        "emb = embedding_layer(input=data_layer(name='ids', size=32), size=8)\n"
        "pooled = pooling_layer(input=emb, pooling_type=SumPooling())\n"
        "fc1 = fc_layer(input=[img, pooled], size=7, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=fc1, label=lbl))\n"
    )
    # no input_types, no hook types: first-sample introspection.  The ids
    # slot yields a 32-long int list — len == the ids layer size (32), the
    # ambiguity ADVICE flagged — and must still infer as a sequence.
    (tmp_path / "prov_inf.py").write_text(
        "from paddle.trainer.PyDataProvider2 import *\n"
        "@provider()\n"
        "def process(settings, f):\n"
        "    for i in range(8):\n"
        "        yield [0.1] * 32, [3] * 32, i % 7\n"
    )
    p = parse_config(str(cfg))
    from paddle_tpu.core.data_types import SeqLevel, SlotKind

    t = dict(p.topology.data_types())
    assert t["input"].kind == SlotKind.DENSE and t["input"].dim == 32
    assert t["ids"].kind == SlotKind.INDEX and t["ids"].seq == SeqLevel.SEQ
    assert t["label"].kind == SlotKind.INDEX and t["label"].seq == SeqLevel.NONE


def test_explicit_inputs_pins_feeding_order(tmp_path):
    """Capital-I Inputs(...) fixes the feeding order regardless of graph
    shape (reference config_parser.py:205-222)."""
    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=4, learning_rate=1e-3)\n"
        "lbl = data_layer(name='label', size=10)\n"
        "img = data_layer(name='pixel', size=16)\n"
        "Inputs('label', 'pixel')\n"
        "fc1 = fc_layer(input=img, size=10, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=fc1, label=lbl))\n"
    )
    p = parse_config(str(cfg))
    assert list(p.topology.data_layers()) == ["label", "pixel"]


@pytest.mark.parametrize("mode", ["generator_training", "discriminator_training", "generator"])
def test_gan_configs_build(mode):
    p = parse_config(
        f"{REF}/gan/gan_conf.py",
        f"noise_dim=10,sample_dim=2,hidden_dim=16,mode={mode}",
    )
    assert len(p.topology.order) >= 4
    p2 = parse_config(
        f"{REF}/gan/gan_conf_image.py",
        f"noise_dim=100,sample_dim=28,c_dim=1,dataname=mnist_data,mode={mode}",
    )
    assert len(p2.topology.order) >= 6


def test_vae_config_builds_and_trains():
    """vae_conf.py exercises mixed_layer context blocks, layer_math, and
    LayerOutput arithmetic; the parsed topology must actually train."""
    p = parse_config(f"{REF}/vae/vae_conf.py")
    assert len(p.topology.order) > 15
    gen = parse_config(f"{REF}/vae/vae_conf.py", "is_generating=1")
    assert len(gen.topology.order) == 3

    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology, parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(64):
            yield (rng.rand(784).astype(np.float32),)

    costs = []
    trainer.train(
        reader=paddle.batch(reader, p.settings.batch_size), num_passes=3,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert np.isfinite(costs).all()
    assert np.mean(costs[-2:]) < np.mean(costs[:2])


def test_layer_math_and_mixed_context():
    from paddle_tpu.layers import layer_math
    import jax
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu import layers as L

    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(3))
    y = L.data("y", paddle.data_type.dense_vector(3))
    expr = layer_math.exp(x) * 0.5 + y - 1.0
    with L.mixed() as m:
        m += L.dotmul_projection(x)
    net = CompiledNetwork(Topology([expr, m]))
    params, state = net.init(jax.random.PRNGKey(0))
    xv = np.asarray([[0.0, 1.0, 2.0]], np.float32)
    yv = np.asarray([[10.0, 10.0, 10.0]], np.float32)
    outs, _ = net.apply(
        params, {"x": SeqTensor(xv), "y": SeqTensor(yv)}, state=state
    )
    np.testing.assert_allclose(
        np.asarray(outs[expr.name].data),
        np.exp(xv) * 0.5 + yv - 1.0,
        rtol=1e-5,
    )
    w = np.asarray(params[m.name]["p0_w"])
    np.testing.assert_allclose(np.asarray(outs[m.name].data), xv * w, rtol=1e-5)


@pytest.mark.parametrize("layer_num,n_layers", [(50, 123), (101, 242), (152, 361)])
def test_model_zoo_resnet_configs_build(layer_num, n_layers):
    """model_zoo/resnet/resnet.py (capital Settings/Inputs/Outputs config_parser
    face, default_momentum/decay_rate globals) builds at all bottleneck
    depths."""
    p = parse_config(
        f"{REF}/model_zoo/resnet/resnet.py", f"layer_num={layer_num},is_test=1"
    )
    assert len(p.topology.order) == n_layers
    assert p.output_layers  # resolved from Outputs(name, ...) strings
    assert p.settings.learning_method.kind == "momentum"
    import paddle_tpu.optimizer as O

    assert isinstance(make_optimizer(p.settings).regularization, O.L2Regularization)


# ---------------------------------------------------------------------------
# reference trainer_config_helpers/tests/configs suite (golden-protostr
# configs of the reference DSL tests — file_list.sh).  Building each config
# unmodified is the parity bar; the reference's two non-configs (the
# stdin-driver script and the broken test_crop, both absent from
# file_list.sh) are excluded the same way the reference excludes them.
# ---------------------------------------------------------------------------

DSL_CONFIGS_DIR = (
    "/root/reference/python/paddle/trainer_config_helpers/tests/configs"
)
_DSL_EXCLUDED = {"test_config_parser_for_non_file_config.py", "test_crop.py"}


def _dsl_config_files():
    import glob

    return sorted(
        f
        for f in glob.glob(os.path.join(DSL_CONFIGS_DIR, "*.py"))
        if os.path.basename(f) not in _DSL_EXCLUDED
    )


@pytest.mark.parametrize(
    "cfg", _dsl_config_files(), ids=lambda f: os.path.basename(f)[:-3]
)
def test_reference_dsl_config_builds(cfg):
    p = parse_config(cfg)
    assert p.topology.order and p.output_layers
    # every built layer resolves to a registered implementation
    from paddle_tpu.layers.base import get_layer_impl

    for name in p.topology.order:
        get_layer_impl(p.topology.layers[name].type)


def test_parse_config_accepts_callable():
    """reference parse_config(configs_fn, '') form (the non-file-config
    driver, tests/configs/test_config_parser_for_non_file_config.py)."""
    from paddle_tpu.v1_compat import config_helpers as H

    def configs():
        d = H.data_layer(name="d", size=10)
        H.settings(batch_size=32, learning_rate=1e-3)
        H.outputs(H.fc_layer(input=d, size=4))

    p = parse_config(configs)
    assert p.settings.batch_size == 32 and len(p.output_layers) == 1


def test_shared_fc_and_groups_share_storage():
    """shared_fc.py / shared_lstm.py: named ParamAttrs share storage —
    per-key (fc w0/w1 + named bias) and across recurrent groups."""
    import jax

    p = parse_config(f"{DSL_CONFIGS_DIR}/shared_fc.py")
    from paddle_tpu.core.compiler import CompiledNetwork

    net = CompiledNetwork(p.topology)
    params, _ = net.init(jax.random.PRNGKey(0))
    pred = [n for n in p.topology.order if n.startswith("__fc_layer")]
    # the softmax fc keeps one stored weight; its second input's weight
    # shares it (intra-layer [p, p] list)
    soft = params[pred[-1]]
    assert "w0" in soft and "w1" not in soft
    # hidden_a owns fc_param/bias_param storage; hidden_b shares both
    ha, hb = params[pred[0]], params.get(pred[1], {})
    assert "w0" in ha and "b" in ha
    assert "w0" not in hb and "b" not in hb

    p2 = parse_config(f"{DSL_CONFIGS_DIR}/shared_lstm.py")
    net2 = CompiledNetwork(p2.topology)
    params2, _ = net2.init(jax.random.PRNGKey(0))
    groups = [
        n for n in p2.topology.order
        if p2.topology.layers[n].type == "recurrent_group"
    ]
    assert len(groups) == 2
    assert groups[0] in params2 and groups[1] not in params2  # shared subtree


def test_shared_lstm_forward_runs():
    """The lstmemory_group machinery (mixed recurrence + weightless
    lstm_step + @cell memory) produces finite outputs end to end."""
    import jax

    p = parse_config(f"{DSL_CONFIGS_DIR}/shared_lstm.py")
    from paddle_tpu.core.batch import SeqTensor, seq as mkseq
    from paddle_tpu.core.compiler import CompiledNetwork

    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    b, t = 3, 5
    lens = np.asarray([5, 3, 1], np.int32)
    batch = {
        "data_a": mkseq(rng.randn(b, t, 100).astype(np.float32), lens),
        "data_b": mkseq(rng.randn(b, t, 100).astype(np.float32), lens),
        "label": SeqTensor(rng.randint(0, 10, size=(b,)).astype(np.int32)),
    }
    outs, _ = net.apply(params, batch, state=state, train=False)
    cost = np.asarray(outs[p.output_layers[0]].data)
    assert np.isfinite(cost).all()


def test_stride_sequence_pooling_matches_numpy():
    """pooling_layer/first_seq/last_seq stride>0 (reference
    SequencePoolLayer stride): fixed windows -> shorter sequence."""
    import jax
    from paddle_tpu.core.batch import seq as mkseq
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu import layers as L
    from paddle_tpu import pooling as P

    reset_auto_names()
    din = paddle.layer.data("din", paddle.data_type.dense_vector_sequence(2))
    pooled = L.pooling(din, P.Sum(), stride=3)
    lastw = L.last_seq(input=din, stride=3)
    net = CompiledNetwork(Topology([pooled, lastw]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    x = rng.randn(2, 7, 2).astype(np.float32)
    lens = np.asarray([7, 4], np.int32)
    outs, _ = net.apply(
        params, {"din": mkseq(x, lens)}, state=state, train=False
    )
    got = outs[pooled.name]
    assert got.lengths is not None
    np.testing.assert_array_equal(np.asarray(got.lengths), [3, 2])
    # row 0: windows [0:3], [3:6], [6:7]
    np.testing.assert_allclose(
        np.asarray(got.data)[0, 0], x[0, 0:3].sum(0), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(got.data)[0, 2], x[0, 6:7].sum(0), rtol=1e-5
    )
    # row 1 (len 4): windows [0:3], [3:4]; window 2 masked to zero
    np.testing.assert_allclose(
        np.asarray(got.data)[1, 1], x[1, 3:4].sum(0), rtol=1e-5
    )
    np.testing.assert_allclose(np.asarray(got.data)[1, 2], 0.0, atol=1e-6)
    lw = np.asarray(outs[lastw.name].data)
    np.testing.assert_allclose(lw[0, 1], x[0, 5], rtol=1e-5)  # last of [3:6]
    np.testing.assert_allclose(lw[1, 1], x[1, 3], rtol=1e-5)  # last of [3:4]


def test_repeat_and_gated_unit_and_weighted_cost():
    import jax
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu import layers as L

    reset_auto_names()
    d = paddle.layer.data("d", paddle.data_type.dense_vector(3))
    row = L.repeat_layer(input=d, num_repeats=2, as_row_vector=True)
    col = L.repeat_layer(input=d, num_repeats=2, as_row_vector=False)
    glu = L.gated_unit_layer(input=d, size=4)
    lbl = paddle.layer.data("lbl", paddle.data_type.dense_vector(3))
    w = paddle.layer.data("w", paddle.data_type.dense_vector(1))
    cost = L.mse_cost(input=d, label=lbl, weight=w)
    net = CompiledNetwork(Topology([row, col, glu, cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    x = np.asarray([[1.0, 2.0, 3.0]], np.float32)
    batch = {
        "d": SeqTensor(x),
        "lbl": SeqTensor(np.zeros((1, 3), np.float32)),
        "w": SeqTensor(np.asarray([[0.5]], np.float32)),
    }
    outs, _ = net.apply(params, batch, state=state, train=False)
    np.testing.assert_allclose(
        np.asarray(outs[row.name].data), [[1, 2, 3, 1, 2, 3]], rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(outs[col.name].data), [[1, 1, 2, 2, 3, 3]], rtol=1e-6
    )
    assert np.asarray(outs[glu.name].data).shape == (1, 4)
    # weighted mse: weight * (0.5 * sum((x-0)^2))
    unweighted = 0.5 * float(np.sum(x**2))
    np.testing.assert_allclose(
        np.asarray(outs[cost.name].data), [[0.5 * unweighted]], rtol=1e-5
    )


_GOLDEN_DSL = [
    "projections", "test_cost_layers", "last_first_seq", "test_rnn_group",
    "img_layers", "test_sequence_pooling", "shared_lstm", "test_ntm_layers",
]


@pytest.mark.parametrize("name", _GOLDEN_DSL)
def test_reference_dsl_config_golden_serialize(name):
    """Golden-snapshot testing of the DSL compiler (reference protostr
    goldens, trainer_config_helpers/tests/configs/protostr): the built
    Topology's deterministic serialize() must not drift.  Regenerate a
    golden by deleting tests/goldens/dsl_<name>.topo and re-running."""
    p = parse_config(os.path.join(DSL_CONFIGS_DIR, name + ".py"))
    golden_path = os.path.join(HERE, "goldens", f"dsl_{name}.topo")
    if not os.path.exists(golden_path):  # pragma: no cover - regen path
        with open(golden_path, "w") as f:
            f.write(p.serialize())
    golden = open(golden_path).read()
    assert p.serialize() == golden


@pytest.mark.parametrize(
    "name,args,min_layers",
    [
        ("alexnet", "batch_size=128", 15),
        ("googlenet", "batch_size=128", 80),
        ("smallnet_mnist_cifar", "batch_size=64", 10),
    ],
)
def test_reference_benchmark_configs_build(name, args, min_layers):
    """The reference's own benchmark driver configs (benchmark/paddle/image)
    parse and build unmodified — bench.py trains these for the ms/batch
    comparison against benchmark/README.md's K40m tables."""
    p = parse_config(f"/root/reference/benchmark/paddle/image/{name}.py", args)
    assert len(p.topology.order) >= min_layers
    assert p.settings.learning_method.kind == "momentum"
    from paddle_tpu.core.compiler import CompiledNetwork

    CompiledNetwork(p.topology)  # every layer type resolves


def test_reference_rnn_benchmark_config_trains(tmp_path):
    """The reference's rnn benchmark config (benchmark/paddle/rnn/rnn.py)
    parses AND trains unmodified through its own provider.py: the pickle
    dataset is synthesized in the provider's exact schema (its py2-style
    `yield map(int, row), label` samples exercise the iterator
    materialization in data_provider).  bench.py times this same path at
    full size against benchmark/README.md:121-127."""
    import jax
    import numpy as np

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.testing import stage_reference_rnn_benchmark
    from paddle_tpu.trainer.step import make_train_step
    from paddle_tpu.v1_compat import (
        make_optimizer,
        make_provider_reader,
    )
    from paddle_tpu.reader.feeder import DataFeeder

    d = str(tmp_path)
    stage_reference_rnn_benchmark(d, n=12, seq_len=8, vocab=300)
    cwd = os.getcwd()
    os.chdir(d)
    try:
        p = parse_config(
            os.path.join(d, "rnn.py"),
            "hidden_size=16,lstm_num=1,batch_size=4,pad_seq=True",
        )
    finally:
        os.chdir(cwd)
    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    opt = make_optimizer(p.settings)
    opt_state = opt.init(params)
    reader = make_provider_reader(p, d, train=True)
    feeder = DataFeeder(p.topology.data_types())
    it = reader()
    rows = [next(it) for _ in range(4)]
    assert all(isinstance(r[0], (list, tuple)) for r in rows), (
        "provider map() fields must be materialized"
    )
    step = make_train_step(net, opt, mesh=None)
    batch = feeder(rows)
    c = None
    for i in range(3):
        params, state, opt_state, m = step(
            params, state, opt_state, batch, jax.random.PRNGKey(i)
        )
        c = float(m["cost"])
    assert np.isfinite(c)


# ---------------------------------------------------------------------------
# reference C++ test fixtures: gserver/tests/*.conf + trainer/tests/*.conf
# (raw config_parser face: Layer/Input/Memory/RecurrentLayerGroupBegin,
# TrainData/ProtoData, model_type, Evaluator) — all parse and every layer
# type resolves to a registered implementation.
# ---------------------------------------------------------------------------

_FIXTURE_DIRS = [
    "/root/reference/paddle/gserver/tests",
    "/root/reference/paddle/trainer/tests",
]


def _fixture_configs():
    import glob

    out = []
    for d in _FIXTURE_DIRS:
        out.extend(sorted(glob.glob(os.path.join(d, "*.conf"))))
    return out


def _parse_fixture(path, config_args=""):
    old = os.getcwd()
    os.chdir("/root/reference/paddle")  # fixtures open data files relatively
    try:
        return parse_config(path, config_args)
    finally:
        os.chdir(old)


@pytest.mark.parametrize(
    "cfg", _fixture_configs(), ids=lambda f: os.path.basename(f)[:-5]
)
def test_reference_cpp_fixture_config_builds(cfg):
    from paddle_tpu.layers.base import get_layer_impl

    p = _parse_fixture(cfg)
    assert p.topology.order and p.output_layers
    for n in p.topology.order:
        get_layer_impl(p.topology.layers[n].type)


def test_raw_face_chunking_crf_forward():
    """chunking.conf (raw Layer/Input/Evaluator face incl. crf sharing the
    'crfw' parameter with crf_decoding) builds AND runs a forward pass."""
    import jax
    from paddle_tpu.core.batch import SeqTensor, seq as mkseq
    from paddle_tpu.core.compiler import CompiledNetwork

    p = _parse_fixture("/root/reference/paddle/trainer/tests/chunking.conf")
    assert p.train_data is not None and p.train_data.kind == "proto"
    assert p.output_layers == ["crf"]
    assert len(p.evaluators) == 1  # the raw Evaluator("error", "sum") decl
    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    b, t = 2, 5
    lens = np.asarray([5, 3], np.int32)
    feats = rng.randn(b, t, 4339).astype(np.float32)
    batch = {
        "features": mkseq(feats, lens),
        "word": mkseq(rng.randint(0, 478, size=(b, t)).astype(np.int32), lens),
        "pos": mkseq(rng.randint(0, 45, size=(b, t)).astype(np.int32), lens),
        "chunk": mkseq(rng.randint(0, 23, size=(b, t)).astype(np.int32), lens),
    }
    outs, _ = net.apply(params, batch, state=state, train=False)
    cost = np.asarray(outs["crf"].data)
    assert cost.shape[0] == b and np.isfinite(cost).all()


def test_raw_face_recurrent_group_forward():
    """A raw RecurrentLayerGroupBegin/Memory/Layer(mixed)/End group computes
    the same function as the DSL recurrent_group it lowers to."""
    import jax
    from paddle_tpu.core.batch import seq as mkseq
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.v1_compat import config_helpers as H

    def configs():
        H.Layer(name="in", type="data", size=6)
        H.RecurrentLayerGroupBegin(
            "g_layer_group", in_links=["in"], out_links=["g"]
        )
        mem = H.Memory(name="g", size=6)
        H.Layer(
            name="g", type="mixed", size=6, active_type="tanh", bias=False,
            inputs=[
                H.IdentityProjection("in"),
                H.FullMatrixProjection(mem, parameter_name="rec_w"),
            ],
        )
        H.RecurrentLayerGroupEnd("g_layer_group")
        H.settings(batch_size=4, learning_rate=1e-3)
        H.Outputs("g")

    p = parse_config(configs)
    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 6).astype(np.float32)
    lens = np.asarray([4, 2], np.int32)
    outs, _ = net.apply(params, {"in": mkseq(x, lens)}, state=state, train=False)
    got = np.asarray(outs[p.output_layers[0]].data)
    # hand-rolled recurrence: h_t = tanh(x_t + h_{t-1} W)
    group_params = [v for v in params.values()][0]
    w = np.asarray(next(iter(group_params.values()))["p1_w"])
    h = np.zeros((2, 6), np.float32)
    for t in range(4):
        h = np.tanh(x[:, t] + h @ w)
        mask = (t < lens).astype(np.float32)[:, None]
        np.testing.assert_allclose(
            got[:, t] * mask, h * mask, rtol=1e-5, atol=1e-6
        )


def test_malformed_raw_group_does_not_poison_next_parse():
    """A config dying inside RecurrentLayerGroupBegin/End must leave no
    stale raw-group or trace state behind (parse_config resets it)."""
    from paddle_tpu.v1_compat import config_helpers as H

    def bad():
        H.Layer(name="in", type="data", size=4)
        H.RecurrentLayerGroupBegin("g_layer_group", in_links=["in"],
                                   out_links=["g"])
        H.Layer(name="g", type="no_such_type", size=4)

    with pytest.raises(KeyError):
        parse_config(bad)

    # the next parse is clean: a fresh group works, and memory() outside a
    # group is rejected again
    def good():
        H.Layer(name="in", type="data", size=4)
        H.RecurrentLayerGroupBegin("g2_layer_group", in_links=["in"],
                                   out_links=["g2"])
        mem = H.Memory(name="g2", size=4)
        H.Layer(name="g2", type="mixed", size=4, active_type="tanh",
                bias=False,
                inputs=[H.IdentityProjection("in"),
                        H.FullMatrixProjection(mem)])
        H.RecurrentLayerGroupEnd("g2_layer_group")
        H.settings(batch_size=4, learning_rate=1e-3)
        H.Outputs("g2")

    p = parse_config(good)
    # Outputs("g2") resolves the out_link alias to the group layer itself
    assert p.output_layers == ["g2_layer_group"]
    from paddle_tpu.layers import memory as dsl_memory

    with pytest.raises(AssertionError, match="inside a recurrent_group"):
        dsl_memory(name="x", size=3)


def test_multi_nn_ensemble_builds_and_trains(tmp_path):
    """model_type('multi_nn') (reference MultiNetwork.cpp, SubModelConfig
    ModelConfig.proto:579): two sub-networks with their own Inputs/Outputs
    compile into one program whose objective sums the sub-costs, and the
    ensemble trains end to end."""
    cfg = tmp_path / "multi.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=8, learning_rate=1e-2,\n"
        "         learning_method=AdamOptimizer())\n"
        "model_type('multi_nn')\n"
        "SubModelBegin('branch_a')\n"
        "xa = data_layer(name='xa', size=6)\n"
        "la = data_layer(name='la', size=2)\n"
        "fa = fc_layer(input=xa, size=2, act=SoftmaxActivation())\n"
        "ca = classification_cost(input=fa, label=la, name='cost_a')\n"
        "Inputs('xa', 'la')\n"
        "Outputs('cost_a')\n"
        "SubModelEnd('branch_a')\n"
        "SubModelBegin('branch_b')\n"
        "xb = data_layer(name='xb', size=4)\n"
        "lb = data_layer(name='lb', size=1)\n"
        "fb = fc_layer(input=xb, size=1, act=LinearActivation())\n"
        "cb = regression_cost(input=fb, label=lb, name='cost_b')\n"
        "Inputs('xb', 'lb')\n"
        "Outputs('cost_b')\n"
        "SubModelEnd('branch_b')\n"
    )
    p = parse_config(str(cfg))
    # feeding order: sub-model Inputs concatenated
    assert list(p.topology.data_layers()) == ["xa", "la", "xb", "lb"]
    assert p.output_layers[0] == "__multi_nn_cost__"
    assert "cost_a" in p.output_layers and "cost_b" in p.output_layers

    from paddle_tpu.core.data_types import (
        dense_vector, integer_value,
    )

    # the parse left slot types as declared placeholders (no provider):
    # feed via an explicit DataFeeder with the true types
    from paddle_tpu.reader.feeder import DataFeeder

    feeder = DataFeeder([
        ("xa", dense_vector(6)), ("la", integer_value(2)),
        ("xb", dense_vector(4)), ("lb", dense_vector(1)),
    ])
    rng = np.random.RandomState(0)

    def rows(n=8):
        out = []
        for _ in range(n):
            ya = rng.randint(2)
            xa = rng.randn(6).astype(np.float32) + 2.0 * ya
            xb = rng.randn(4).astype(np.float32)
            yb = np.asarray([xb.sum()], np.float32)
            out.append((xa, ya, xb, yb))
        return out

    import jax

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.trainer.step import make_train_step

    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    opt = make_optimizer(p.settings)
    opt_state = opt.init(params)
    step = make_train_step(net, opt, mesh=None)
    costs = []
    for i in range(60):
        params, state, opt_state, m = step(
            params, state, opt_state, feeder(rows()), jax.random.PRNGKey(i)
        )
        costs.append(float(m["cost"]))
    assert np.mean(costs[-5:]) < 0.5 * np.mean(costs[:5]), (
        costs[:5], costs[-5:],
    )


# ---------------------------------------------------------------------------
# demo configs EXECUTE (the run-sweep discipline of test_dsl_run_sweep.py
# applied to v1_api_demo): build + one jitted forward with hinted random
# batches.  quick_start-lr / gan / vae / mnist already TRAIN in other tests.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "cfg", ["lr", "emb", "cnn", "lstm", "bidi-lstm", "db-lstm", "resnet-lstm"]
)
def test_quick_start_configs_execute(dict_dir, cfg):
    import jax

    import paddle_tpu.core.data_types as dt
    from paddle_tpu.core.compiler import CompiledNetwork

    from layer_grad_util import rand_batch_for

    p = parse_config(f"{REF}/quick_start/trainer_config.{cfg}.py")
    for name, conf in list(p.topology.data_layers().items()):
        if conf.input_type is None or conf.attrs.get("_v1_size_only"):
            itype = (
                dt.integer_value(2) if name == "label"
                else dt.integer_value_sequence(max(conf.size, 2))
            )
            object.__setattr__(conf, "input_type", itype)
            conf.attrs.pop("_v1_size_only", None)
            conf.attrs.pop("_v1_unresolved", None)
    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = rand_batch_for(p.topology, batch_size=2, max_len=4)
    outs, _ = net.apply(
        params, batch, state=state, train=True, rng=jax.random.PRNGKey(1)
    )
    for oname in p.topology.output_names:
        arr = outs[oname].data
        assert np.all(np.isfinite(np.asarray(arr, np.float32))), (cfg, oname)


@pytest.mark.parametrize("cfg", ["linear_crf", "rnn_crf"])
def test_sequence_tagging_configs_execute(cfg):
    import jax

    import paddle_tpu.core.data_types as dt
    from paddle_tpu.core.compiler import CompiledNetwork

    from layer_grad_util import rand_batch_for

    p = parse_config(f"{REF}/sequence_tagging/{cfg}.py")
    hints = {
        "features": dt.sparse_binary_vector_sequence(76328),
        "word": dt.integer_value_sequence(6778),
        "pos": dt.integer_value_sequence(44),
        "chunk": dt.integer_value_sequence(24),
    }
    for name, conf in list(p.topology.data_layers().items()):
        if name in hints:
            object.__setattr__(conf, "input_type", hints[name])
            conf.attrs.pop("_v1_size_only", None)
            conf.attrs.pop("_v1_unresolved", None)
    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = rand_batch_for(p.topology, batch_size=2, max_len=4)
    outs, _ = net.apply(
        params, batch, state=state, train=True, rng=jax.random.PRNGKey(1)
    )
    for oname in p.topology.output_names:
        arr = outs[oname].data
        assert np.all(np.isfinite(np.asarray(arr, np.float32))), (cfg, oname)


def test_v2_toplevel_surface_complete():
    """Every name the reference exports from paddle.v2.__init__ (its
    __all__, python/paddle/v2/__init__.py:39-60) resolves on paddle_tpu —
    a user porting reference code must find the same module attributes."""
    import paddle_tpu as p

    want = [
        "optimizer", "layer", "activation", "parameters", "init",
        "trainer", "event", "data_type", "attr", "pooling", "dataset",
        "reader", "topology", "networks", "infer", "plot", "evaluator",
        "image", "master", "model",
    ]
    missing = [w for w in want if not hasattr(p, w)]
    assert not missing, missing
