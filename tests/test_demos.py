"""Demo-level end-to-end tests (VERDICT item 10): sequence_tagging NER with
sparse sharding on the mesh, quick_start-style text classification, the
cluster launcher, and packaging."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models.sequence_tagging import ner_crf_cost, synthetic_tag_reader
from paddle_tpu.evaluator import chunk_evaluator, classification_error_evaluator

VOCAB, LABELS = 60, 5


def _train_ner(mesh=None, sparse=True, passes=6, seed=3):
    reset_auto_names()
    cost, decode = ner_crf_cost(VOCAB, LABELS, sparse_update=sparse)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost,
        parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-2),
        extra_layers=[decode],
        mesh=mesh,
    )
    costs = []
    trainer.train(
        reader=paddle.batch(synthetic_tag_reader(VOCAB, LABELS, n=96, seed=seed), 16),
        num_passes=passes,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    return trainer, costs


def test_ner_crf_trains_locally():
    trainer, costs = _train_ner(mesh=None)
    assert np.mean(costs[-4:]) < 0.5 * np.mean(costs[:4]), (
        costs[:4], costs[-4:],
    )


def test_ner_crf_trains_sparse_sharded_on_mesh():
    """The sequence_tagging sparse path end-to-end on the virtual 8-device
    mesh: row-sharded embedding + data-parallel batch (the reference's
    sparse-remote-update pserver path, test_CompareSparse.cpp contract)."""
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(data=2, model=4)
    trainer, costs = _train_ner(mesh=mesh, sparse=True)
    assert np.mean(costs[-4:]) < 0.5 * np.mean(costs[:4])
    # sharded training must match the local run's trajectory closely
    _, local_costs = _train_ner(mesh=None, sparse=True)
    np.testing.assert_allclose(
        np.asarray(costs[:8]), np.asarray(local_costs[:8]), rtol=2e-2, atol=2e-2
    )


def test_ner_tagging_accuracy_via_decoding():
    trainer, _ = _train_ner(mesh=None, passes=10)
    # decode a fresh batch and measure tag accuracy
    reader = synthetic_tag_reader(VOCAB, LABELS, n=32, seed=11)
    batch = list(reader())
    feeder = trainer._make_feeder(None)
    fed = feeder(batch)
    outs, _ = trainer.network.apply(
        trainer.parameters.params, fed, state=trainer.parameters.state, train=False
    )
    dec = outs["crf_decode"]
    ids = np.asarray(dec.data)
    mask = np.asarray(dec.mask()) if dec.is_seq else np.ones_like(ids)
    want = np.asarray(fed["word"].data) % LABELS
    acc = (ids == want)[mask > 0].mean()
    assert acc > 0.9, acc


# ---------------------------------------------------------------------------
# launcher
# ---------------------------------------------------------------------------


def test_launcher_builds_env_and_commands():
    from paddle_tpu import launcher

    env = launcher.build_worker_env("h0:8476", 4, 2)
    assert env[launcher.ENV_COORD] == "h0:8476"
    assert env[launcher.ENV_NPROC] == "4"
    assert env[launcher.ENV_PROC_ID] == "2"
    cmds = launcher.build_commands(
        ["localhost", "worker1"], "h0:8476", "train.py", ["--lr", "0.1"],
        python="python3", workdir="/job",
    )
    assert cmds[0][0] == "env" and "train.py" in cmds[0]
    assert cmds[1][0] == "ssh" and cmds[1][1] == "worker1"
    assert "PADDLE_TPU_PROCESS_ID=1" in cmds[1][2]


def test_launcher_single_host_init_is_noop(monkeypatch):
    from paddle_tpu import launcher

    monkeypatch.delenv(launcher.ENV_COORD, raising=False)
    group = launcher.init_cluster()
    assert not group  # falsy ProcessGroup: no multi-process runtime
    assert group.backend == "single" and group.num_processes == 1


def test_launcher_multi_process_shim_records_membership(monkeypatch):
    """On the CPU dev container init_cluster forms the SHIM group (no
    jax.distributed runtime to join): membership is recorded and the
    cross-process reduction rides the master plane instead."""
    from paddle_tpu import launcher
    from paddle_tpu.parallel.mesh import current_process_group

    monkeypatch.setenv(launcher.ENV_COORD, "h0:8476")
    monkeypatch.setenv(launcher.ENV_NPROC, "4")
    monkeypatch.setenv(launcher.ENV_PROC_ID, "2")
    monkeypatch.delenv("PADDLE_TPU_DIST_BACKEND", raising=False)
    group = launcher.init_cluster()
    try:
        assert group  # truthy: multi-process membership formed
        assert group.backend == "shim"
        assert group.num_processes == 4 and group.process_id == 2
        assert current_process_group() is group
    finally:
        monkeypatch.delenv(launcher.ENV_COORD, raising=False)
        monkeypatch.delenv(launcher.ENV_NPROC, raising=False)
        monkeypatch.delenv(launcher.ENV_PROC_ID, raising=False)
        launcher.init_cluster()  # reset the module-global group


def test_launcher_forwards_dist_backend_choice(monkeypatch):
    """The operator's PADDLE_TPU_DIST_BACKEND choice must travel with the
    job: remote workers only see the inlined env fragment."""
    from paddle_tpu import launcher

    monkeypatch.setenv("PADDLE_TPU_DIST_BACKEND", "jax")
    assert launcher.build_worker_env("h0:1", 4, 2)[
        "PADDLE_TPU_DIST_BACKEND"
    ] == "jax"
    monkeypatch.delenv("PADDLE_TPU_DIST_BACKEND")
    assert "PADDLE_TPU_DIST_BACKEND" not in launcher.build_worker_env(
        "h0:1", 4, 2
    )


def test_launcher_extra_env_arms_one_worker(tmp_path):
    """extra_env reaches exactly the targeted process id — how a chaos
    drill arms kill_worker on worker k of N."""
    from paddle_tpu import launcher

    cmds = launcher.build_commands(
        ["localhost", "localhost", "localhost"], "h0:1", "train.py",
        extra_env={1: {"PADDLE_TPU_CHAOS": "kill_worker@2"}},
    )
    assert "PADDLE_TPU_CHAOS=kill_worker@2" in cmds[1]
    assert not any("PADDLE_TPU_CHAOS" in c for c in cmds[0])
    assert not any("PADDLE_TPU_CHAOS" in c for c in cmds[2])


def test_launcher_local_dry_run():
    from paddle_tpu import launcher

    rc = launcher.main([
        "--hosts", "localhost,localhost", "--coordinator", "127.0.0.1:9999",
        "--dry-run", "train.py",
    ])
    assert rc == 0


def test_launcher_runs_local_workers(tmp_path):
    """Two local workers actually spawn and see their process ids."""
    from paddle_tpu import launcher

    script = tmp_path / "worker.py"
    out = tmp_path / "out"
    script.write_text(
        "import os, sys\n"
        f"open(r'{out}' + os.environ['PADDLE_TPU_PROCESS_ID'], 'w')"
        ".write(os.environ['PADDLE_TPU_NUM_PROCESSES'])\n"
    )
    rc = launcher.launch(
        ["localhost", "localhost"], "127.0.0.1:9876", str(script)
    )
    assert rc == 0
    assert (tmp_path / "out0").read_text() == "2"
    assert (tmp_path / "out1").read_text() == "2"


# ---------------------------------------------------------------------------
# packaging
# ---------------------------------------------------------------------------


def test_setup_py_parses():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "setup.py", "--name", "--version"],
        cwd=repo, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "paddle-tpu" in r.stdout and "0.1.0" in r.stdout


def test_param_sharing_by_name():
    """Layers declaring the same ParamAttr name share one parameter slot
    (reference global parameter table), e.g. tied input/output embeddings."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology

    reset_auto_names()
    shared = paddle.attr.ParamAttr(name="tied_w")
    a = paddle.layer.data("a", paddle.data_type.integer_value_sequence(10))
    e1 = paddle.layer.embedding(a, size=4, param_attr=shared, name="emb1")
    e2 = paddle.layer.embedding(a, size=4, param_attr=shared, name="emb2")
    diff = paddle.layer.addto(
        [e1, paddle.layer.slope_intercept(e2, slope=-1.0)],
        act=paddle.activation.Abs(),
    )
    net = CompiledNetwork(Topology([diff]))
    params, state = net.init(jax.random.PRNGKey(0))
    assert "emb1" in params and "emb2" not in params  # one storage slot
    batch = {"a": SeqTensor(jnp.asarray([[1, 2, 3]], jnp.int32), jnp.asarray([3]))}
    outs, _ = net.apply(params, batch, state=state, train=False)
    np.testing.assert_allclose(np.asarray(outs[diff.name].data), 0.0, atol=1e-6)
