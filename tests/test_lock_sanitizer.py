"""Runtime lock-order sanitizer (analysis/lock_sanitizer.py): cycle
detection with both stacks, RLock reentrancy, StatSet held-time stats, and
the reader-teardown thread-leak contract the chaos drills rely on."""

import threading
import time

import pytest

from paddle_tpu.analysis import lock_sanitizer as ls
from paddle_tpu.utils.timers import StatSet, global_stats


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv(ls.ENV_FLAG, "1")
    ls.reset()
    yield
    ls.reset()


# ---------------------------------------------------------------------------
# arming
# ---------------------------------------------------------------------------


def test_disarmed_factories_return_plain_primitives(monkeypatch):
    monkeypatch.delenv(ls.ENV_FLAG, raising=False)
    assert not ls.sanitizer_enabled()
    lk = ls.make_lock("x")
    rlk = ls.make_rlock("x")
    assert not isinstance(lk, ls.SanitizedLock)
    assert not isinstance(rlk, ls.SanitizedLock)
    with lk:
        pass
    with rlk, rlk:  # reentrant
        pass


def test_armed_factories_instrument(armed):
    assert ls.sanitizer_enabled()
    lk = ls.make_lock("a")
    assert isinstance(lk, ls.SanitizedLock)
    with lk:
        assert ls.held_report()  # this thread shows up holding 'a'
    assert ls.held_report() == {}


# ---------------------------------------------------------------------------
# cycle detection
# ---------------------------------------------------------------------------


def test_abba_cycle_raises_deadlock_report_with_both_stacks(armed):
    a = ls.make_lock("A")
    b = ls.make_lock("B")

    def order_ab():
        with a:
            with b:
                pass

    order_ab()  # records A -> B
    with pytest.raises(ls.DeadlockReport) as ei:
        with b:
            with a:  # closes the cycle: report fires BEFORE blocking
                pass
    rep = ei.value
    assert rep.cycle[0] == "B" and set(rep.cycle) == {"A", "B"}
    # both acquisition stacks ride the report
    assert "order_ab" in rep.other_stack
    assert "test_abba_cycle" in rep.this_stack
    assert "A -> B" in str(rep) or "B -> A" in str(rep)


def test_cycle_detected_across_threads(armed):
    a = ls.make_lock("A")
    b = ls.make_lock("B")
    err = []

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()

    def t2():
        try:
            with b:
                with a:
                    pass
        except ls.DeadlockReport as e:
            err.append(e)

    th = threading.Thread(target=t2)
    th.start()
    th.join()
    assert len(err) == 1


def test_transitive_cycle_three_locks(armed):
    a, b, c = ls.make_lock("A"), ls.make_lock("B"), ls.make_lock("C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(ls.DeadlockReport) as ei:
        with c:
            with a:
                pass
    assert set(ei.value.cycle) == {"A", "B", "C"}


def test_consistent_order_never_reports(armed):
    a = ls.make_lock("A")
    b = ls.make_lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert ("A", "B") in ls.edges()
    assert ("B", "A") not in ls.edges()


def test_reentrant_rlock_is_not_an_ordering_event(armed):
    r = ls.make_rlock("R")
    b = ls.make_lock("B")
    with r:
        with b:
            with r:  # re-enter while holding B: must NOT record B -> R
                pass
    # only R -> B exists; no self-edge, no inversion
    assert set(ls.edges()) == {("R", "B")}
    # and a second nesting the same way is fine
    with r, b:
        pass


def test_release_misuse_still_raises(armed):
    lk = ls.make_lock("M")
    with pytest.raises(RuntimeError):
        lk.release()


def test_acquire_timeout_false_does_not_push(armed):
    lk = ls.make_lock("T")
    grabbed = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            grabbed.set()
            release.wait(5)

    th = threading.Thread(target=holder)
    th.start()
    grabbed.wait(5)
    assert lk.acquire(timeout=0.05) is False
    # the failed acquire left no residue on THIS thread (the holder thread
    # legitimately shows up until it releases)
    me = threading.current_thread().name
    assert me not in ls.held_report()
    release.set()
    th.join()
    assert ls.held_report() == {}


# ---------------------------------------------------------------------------
# held-time stats ride the StatSet plane
# ---------------------------------------------------------------------------


def test_held_time_observed_into_global_stats(armed):
    global_stats.reset()
    lk = ls.make_lock("statsy")
    with lk:
        time.sleep(0.01)
    summ = global_stats.summary()
    assert "lock_held/statsy" in summ
    assert summ["lock_held/statsy"]["count"] == 1
    assert summ["lock_held/statsy"]["max"] >= 0.01
    global_stats.reset()


# ---------------------------------------------------------------------------
# StatSet lock-consistency (the C-rule audit satellite): two threads
# hammering incr/observe/timer must never lose a count
# ---------------------------------------------------------------------------


def test_statset_two_thread_increment_stress():
    stats = StatSet()
    N = 5000

    def worker():
        for _ in range(N):
            stats.incr("hits")
            stats.observe("vals", 1.0)

    ts = [threading.Thread(target=worker) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert stats.count("hits") == 2 * N
    summ = stats.summary()
    assert summ["vals"]["count"] == 2 * N
    assert summ["vals"]["total"] == pytest.approx(2 * N)


# ---------------------------------------------------------------------------
# thread_report: the reader-teardown leak contract
# ---------------------------------------------------------------------------


def _wait_clear(timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not ls.thread_report():
            return []
        time.sleep(0.02)
    return ls.thread_report()


def test_buffered_reader_abandoned_early_leaks_no_thread():
    from paddle_tpu.reader.decorator import buffered

    def slow_reader():
        for i in range(10_000):
            yield i

    r = buffered(slow_reader, size=4)
    it = r()
    assert next(it) == 0
    it.close()  # abandon mid-stream: fill thread must stop and join
    assert _wait_clear() == []


def test_xmap_reader_abandoned_early_leaks_no_thread():
    from paddle_tpu.reader.decorator import xmap_readers

    def src():
        for i in range(10_000):
            yield i

    r = xmap_readers(lambda x: x * 2, src, process_num=3, buffer_size=2,
                     order=True)
    it = r()
    assert next(it) == 0
    it.close()
    assert _wait_clear() == []


def test_xmap_reader_full_drain_still_joins():
    from paddle_tpu.reader.decorator import xmap_readers

    def src():
        for i in range(50):
            yield i

    r = xmap_readers(lambda x: x + 1, src, process_num=2, buffer_size=4,
                     order=True)
    assert list(r()) == list(range(1, 51))
    assert _wait_clear() == []


def test_recordio_prefetcher_close_joins_workers(tmp_path):
    from paddle_tpu.io import recordio

    paths = []
    for i in range(3):
        p = str(tmp_path / f"f{i}.rio")
        recordio.write_records(
            p, [f"{i}-{j}".encode() for j in range(2000)],
            max_chunk_records=100,
        )
        paths.append(p)

    pf = recordio.Prefetcher(paths, n_threads=2, capacity=8)
    assert pf.next() is not None  # workers alive, queue tiny: they park
    pf.close()
    if getattr(pf, "_lib", None) is None:  # python backend spawns threads
        assert _wait_clear() == []
    # close is idempotent
    pf.close()


def test_device_prefetcher_close_joins():
    from paddle_tpu.reader.prefetch import DevicePrefetcher

    pf = DevicePrefetcher(iter(range(10_000)), depth=2)
    assert next(pf) == 0
    pf.close()
    assert _wait_clear() == []


def test_same_named_distinct_locks_do_not_crash(armed):
    # two instances of one class share a lock NAME (the Module.Class.attr
    # convention): nesting them must neither crash nor fabricate an edge
    a1 = ls.make_lock("Prefetcher._next_lock")
    a2 = ls.make_lock("Prefetcher._next_lock")
    with a1:
        with a2:
            pass
    with a2:
        with a1:
            pass
    assert ("Prefetcher._next_lock", "Prefetcher._next_lock") not in ls.edges()


def test_prefetcher_close_join_is_deadlined(tmp_path, monkeypatch):
    # a worker wedged in file i/o (never reaching a _stopped check) must
    # degrade to leaking one daemon thread, not hang close() forever
    import time as _time
    from paddle_tpu.io import recordio

    p = str(tmp_path / "f.rio")
    recordio.write_records(p, [b"x"] * 10)
    pf = recordio.Prefetcher([p], n_threads=1, capacity=4)
    if getattr(pf, "_lib", None) is not None:
        pf.close()
        return  # native backend: python join path not in play
    wedged = threading.Event()

    def hang():
        wedged.wait(30)

    pf._threads.append(threading.Thread(target=hang, daemon=True))
    pf._threads[-1].start()
    t0 = _time.monotonic()
    pf.close()
    assert _time.monotonic() - t0 < 10  # bounded, despite the wedged thread
    wedged.set()
