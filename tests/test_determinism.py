"""Cross-process determinism of parameter init (VERDICT r1 weak #3: Python's
salted str hash made the same seed give different parameters per process;
init now folds a crc32-based stable hash, layers/base.py stable_hash)."""

import json
import os
import subprocess
import sys

SNIPPET = """
import json
import jax
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names

reset_auto_names()
L = paddle.layer
x = L.data("x", paddle.data_type.dense_vector(8))
h = L.fc(x, size=16, act=paddle.activation.Tanh())
out = L.fc(h, size=4, act=paddle.activation.Softmax())
lab = L.data("lab", paddle.data_type.integer_value(4))
cost = L.classification_cost(input=out, label=lab)
net = CompiledNetwork(Topology([cost]))
params, _ = net.init(jax.random.PRNGKey(42))
leaves = jax.tree_util.tree_leaves(params)
print(json.dumps([float(np.asarray(l).sum()) for l in leaves]))
"""


def _run_once():
    env = dict(os.environ)
    out = subprocess.run(
        [sys.executable, "-c", SNIPPET],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_param_init_stable_across_processes():
    a = _run_once()
    b = _run_once()
    assert a == b, (a, b)
