"""Serving plane unit tests — block-paged cache, paged decode engine,
continuous-batching scheduler (paddle_tpu/serving/).

The load-bearing guarantees pinned here:

* decode through the page table is BIT-IDENTICAL per request to the
  one-shot ``Seq2SeqGenerator.generate_greedy`` path, under staggered
  admission/retirement and after preemption;
* compile counts stay bounded by the shape ladder (counter-asserted);
* the HBM budget refuses admission instead of OOMing, and freed pages
  re-admit the waiters;
* greedy early-exit / ``max_new_tokens`` are bit-identical to the full
  unroll truncated (the ops/beam contract the engine's step relies on).
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor, pad_batch_rows, slice_batch_rows
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
from paddle_tpu.ops.beam import greedy_search
from paddle_tpu.reader.loadgen import OpenLoopLoadGen
from paddle_tpu.serving import Request, ServingEngine, ServingScheduler
from paddle_tpu.serving.pages import BlockPagedCache

V, E, H = 20, 8, 12
BOS, EOS = 0, 1
MAXLEN = 8


@pytest.fixture(scope="module")
def small_gen():
    """Seeded (untrained) tiny NMT generator — argmax decode over random
    weights is deterministic, which is all bit-identity tests need."""
    reset_auto_names()
    cost, _ = seq2seq_cost(V, V, word_dim=E, hidden_dim=H)
    params = paddle.parameters.create(cost, seed=5)
    return Seq2SeqGenerator(
        params, V, V, word_dim=E, hidden_dim=H,
        bos_id=BOS, eos_id=EOS, max_length=MAXLEN,
    )


def make_engine(small_gen, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("hbm_budget_mb", 1)
    kw.setdefault("max_new_tokens", MAXLEN)
    return ServingEngine(small_gen, **kw)


def srcs_of(seed, lengths):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, V, size=n).tolist() for n in lengths]


# ---------------------------------------------------------------------------
# block-paged cache
# ---------------------------------------------------------------------------


def test_pages_budget_derives_capacity():
    c = BlockPagedCache(16, {"enc": 24, "ep": 12}, hbm_budget_bytes=16 * 36 * 4 * 10)
    assert c.bytes_per_block == 16 * 36 * 4
    assert c.n_blocks == 10
    assert c.scratch == 10 and c.pool_rows == 11
    assert c.pages_for_tokens(1) == 1
    assert c.pages_for_tokens(16) == 1
    assert c.pages_for_tokens(17) == 2


def test_pages_alloc_free_and_refusal():
    c = BlockPagedCache(16, {"x": 1}, n_blocks=4)
    a = c.alloc(3)
    assert a is not None and len(a) == 3 and c.n_free == 1
    assert c.alloc(2) is None  # refused, not partial
    assert c.n_free == 1
    c.free(a)
    assert c.n_free == 4
    b = c.alloc(4)
    assert sorted(b) == [0, 1, 2, 3]
    with pytest.raises(ValueError):
        c.free([7])  # foreign id
    c.free(b)
    with pytest.raises(ValueError):
        c.free([b[0]])  # double free


def test_pages_zero_capacity_budget_raises():
    with pytest.raises(ValueError):
        BlockPagedCache(16, {"x": 1024}, hbm_budget_bytes=10)


# ---------------------------------------------------------------------------
# engine: bit-identity under continuous batching
# ---------------------------------------------------------------------------


def test_engine_requires_fused_match(small_gen, monkeypatch):
    monkeypatch.setattr(small_gen, "_match", None)
    with pytest.raises(ValueError, match="fused attention-GRU"):
        make_engine(small_gen)


def test_engine_staggered_bit_identical(small_gen):
    eng = make_engine(small_gen)
    reqs = [Request(s) for s in srcs_of(0, (3, 5, 9, 2, 17, 4))]
    # continuous batching: admit mid-flight, retire mid-flight
    assert len(eng.admit(reqs[:2])) == 2
    fin = eng.step() + eng.step()
    eng.admit(reqs[2:4])
    for _ in range(40):
        if len(fin) >= 4:
            break
        fin += eng.step()
    eng.admit(reqs[4:])
    for _ in range(40):
        if not eng.n_live:
            break
        fin += eng.step()
    assert len(fin) == 6 and eng.n_live == 0
    for r in reqs:
        assert r.tokens == eng.reference_decode(r.src_ids, MAXLEN), r.req_id
    # every page and slot returned to the free pool
    assert eng.pages.n_free == eng.pages.n_blocks
    assert eng.n_free_slots == eng.max_slots


def test_engine_compile_bounded_by_ladder(small_gen):
    eng = make_engine(small_gen)
    # two full rounds over the same length/slot-count mix: round 2 must
    # add ZERO compiled variants — the continuous-batching contract
    for seed in (0, 1):
        reqs = [Request(s) for s in srcs_of(seed, (3, 5, 9, 2, 17, 4))]
        eng.admit(reqs[:4])
        done = []
        while len(done) < 6:
            done += eng.step()
            if eng.n_free_slots and len(done) + eng.n_live < 6:
                eng.admit(reqs[4:])
        if seed == 0:
            first = dict(eng.trace_counts)
            # realized rungs: slot counts {1..4} -> B rungs {1,2,4};
            # page counts {1,2} -> P rungs {1,2}; never one per shape mix
            assert first["decode"] <= 6
            assert first["prefill"] <= 4
    assert eng.trace_counts == first  # round 2: all cache hits
    assert len(eng._decode_table) == first["decode"]


def test_engine_admission_refused_until_pages_free(small_gen):
    # pool of 2 blocks: one 17-token request (2 pages) fills it
    blk = 16 * (2 * H + H) * 4
    eng = make_engine(small_gen, hbm_budget_mb=2 * blk / (1 << 20))
    assert eng.pages.n_blocks == 2
    big = Request(srcs_of(2, (17,))[0])
    small = Request(srcs_of(3, (4,))[0])
    assert eng.admit([big, small]) == [big]  # strict FCFS: small waits
    assert eng.admit([small]) == []
    while eng.n_live:
        eng.step()
    assert eng.admit([small]) == [small]
    while eng.n_live:
        eng.step()
    assert small.tokens == eng.reference_decode(small.src_ids, MAXLEN)


def test_engine_preemption_bit_identical(small_gen):
    # block_steps=1 so two steps leave every request genuinely mid-decode
    eng = make_engine(small_gen, block_steps=1)
    reqs = [Request(s) for s in srcs_of(1, (4, 6, 3))]
    eng.admit(reqs)
    fin = eng.step() + eng.step()
    victim = eng.preempt()
    assert victim is not None and victim._resume is not None
    assert victim._resume["tokens"] == victim._resume["tokens"]
    # pages came back; re-admission restores the saved GRU state
    eng.admit([victim])
    for _ in range(40):
        if not eng.n_live:
            break
        fin += eng.step()
    for r in reqs:
        assert r.tokens == eng.reference_decode(r.src_ids, MAXLEN), r.req_id


def test_engine_block_steps_bit_identical(small_gen):
    """K tokens per dispatch (odd K, forcing mid-block eos/cap crossings)
    must not change a single output token vs K=1 vs the one-shot path."""
    eng1 = make_engine(small_gen, block_steps=1)
    eng3 = make_engine(small_gen, block_steps=3)
    srcs = srcs_of(7, (3, 5, 9, 2, 17, 4, 6, 8))
    outs = {}
    for eng in (eng1, eng3):
        reqs = [Request(s) for s in srcs]
        eng.admit(reqs[:4])
        done = []
        for _ in range(100):
            if len(done) == len(reqs):
                break
            done += eng.step()
            if eng.n_free_slots:
                eng.admit(reqs[len(done) + eng.n_live:])
        outs[eng.block_steps] = [r.tokens for r in reqs]
    assert outs[1] == outs[3]
    for r_tokens, s in zip(outs[3], srcs):
        assert r_tokens == eng3.reference_decode(s, MAXLEN)


def test_engine_max_new_tokens_cap(small_gen):
    eng = make_engine(small_gen)
    r = Request(srcs_of(4, (6,))[0], max_new_tokens=2)
    eng.admit([r])
    fin = []
    for _ in range(10):
        if fin:
            break
        fin += eng.step()
    assert fin == [r]
    assert r.tokens == eng.reference_decode(r.src_ids, 2)
    assert len(r.tokens) <= 2


# ---------------------------------------------------------------------------
# scheduler (threaded) — fast smoke; chaos/load drills live in
# tests/test_serving_e2e.py (slow, `make chaos`)
# ---------------------------------------------------------------------------


def test_scheduler_serves_and_rejects(small_gen):
    eng = make_engine(small_gen)
    with ServingScheduler(eng) as sched:
        good = [sched.submit(Request(s)) for s in srcs_of(5, (3, 7, 2))]
        bad = [
            sched.submit(Request([])),  # empty
            sched.submit(Request([2, V + 5])),  # out of vocab
            sched.submit(Request([2, 3], max_new_tokens=0)),  # bad cap
            sched.submit(Request([2, 3], max_new_tokens="5")),  # non-numeric
            sched.submit(Request([2, 3], max_new_tokens=float("nan"))),
            sched.submit(Request([2, float("nan"), 3])),  # poisoned
            sched.submit(Request(list(range(2, 2 + 10_000)) * 2)),  # too long
        ]
        for r in good + bad:
            assert r.wait(60), r
        for r in good:
            assert r.error is None
            assert r.result() == eng.reference_decode(r.src_ids, MAXLEN)
            assert r.t_submit <= r.t_admit <= r.t_done
        for r in bad:
            assert r.error is not None
            with pytest.raises(RuntimeError):
                r.result()
    # closed: no thread leaks, further submits refused
    assert not [
        t for t in threading.enumerate() if t.name.startswith("paddle-serve")
    ]
    with pytest.raises(RuntimeError):
        sched.submit(Request([2, 3]))
    sched.close()  # idempotent


def test_scheduler_loop_crash_strands_no_client(small_gen, monkeypatch):
    """An engine bug must fail LOUDLY: every outstanding request finalizes
    with the crash error (wait() unblocks) and further submits raise —
    never a silently dead daemon thread with clients parked forever."""
    eng = make_engine(small_gen)
    monkeypatch.setattr(
        eng, "step", lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    )
    sched = ServingScheduler(eng)
    try:
        r = sched.submit(Request([2, 3, 4]))
        assert r.wait(30), "client stranded by a crashed step loop"
        assert r.error is not None and "crashed" in r.error
        for _ in range(200):  # the loop marks itself closed
            try:
                sched.submit(Request([2, 3]))
                threading.Event().wait(0.01)
            except RuntimeError:
                break
        else:
            pytest.fail("submit still accepted after loop crash")
    finally:
        sched.close()


def test_scheduler_callback_runs_off_step_thread(small_gen):
    eng = make_engine(small_gen)
    seen = []

    def cb(r):
        seen.append((r.req_id, threading.current_thread().name))

    with ServingScheduler(eng) as sched:
        r = sched.submit(Request([2, 3, 4], callback=cb))
        assert r.wait(60)
        # wait() unblocked by the STEP thread; the callback lands on the
        # delivery thread shortly after
        for _ in range(200):
            if seen:
                break
            threading.Event().wait(0.01)
    assert seen and seen[0][1] == "paddle-serve-deliver"


# ---------------------------------------------------------------------------
# SLO plane: deadlines, shedding, backpressure, cancel, drain
# ---------------------------------------------------------------------------


def test_generate_timeout_cancels_and_frees_pages(small_gen):
    """The orphaned-slot regression: a timed-out ``generate()`` must CANCEL
    its in-flight request — pages_in_use returns to 0 instead of the slot
    decoding to max_new_tokens for nobody."""
    import time as _time

    eng = make_engine(small_gen)
    sched = ServingScheduler(eng)
    try:
        with pytest.raises(TimeoutError):
            sched.generate([2, 3, 4], timeout=0.0)
        deadline = _time.perf_counter() + 10.0
        while _time.perf_counter() < deadline:
            if (eng.pages.n_used == 0 and eng.n_live == 0
                    and eng.n_free_slots == eng.max_slots):
                break
            threading.Event().wait(0.01)
        assert eng.pages.n_used == 0, eng.pages.summary()
        assert eng.n_live == 0 and eng.n_free_slots == eng.max_slots
        # the canceled request burned nothing and the plane still serves
        assert sched.generate([2, 3, 4], timeout=60.0) == (
            eng.reference_decode([2, 3, 4], MAXLEN)
        )
    finally:
        sched.close()


def test_cancel_by_req_id(small_gen):
    eng = make_engine(small_gen)
    with ServingScheduler(eng) as sched:
        r = sched.submit(Request(srcs_of(11, (6,))[0]))
        sched.cancel(r.req_id, reason="timeout: operator cancel")
        assert r.wait(10)
        assert r.status in ("timeout", "served")  # raced completion is fine
    assert eng.pages.n_used == 0


def test_queue_limit_backpressure_rejects_immediately(small_gen):
    eng = make_engine(small_gen)
    with ServingScheduler(eng, queue_limit=2) as sched:
        reqs = [sched.submit(Request(s)) for s in srcs_of(12, (5,) * 30)]
        for r in reqs:
            assert r.wait(60)
        statuses = [r.status for r in reqs]
        assert statuses.count("rejected") > 0
        assert set(statuses) <= {"served", "rejected"}
        for r in reqs:
            if r.status == "rejected":
                assert "queue full" in r.error
                assert r.tokens == []


def test_deadline_stamped_and_shed_statuses_disjoint(small_gen):
    """An effectively-zero deadline sheds everything the sweep sees; the
    ledger stays disjoint over served/shed/timeout."""
    eng = make_engine(small_gen)
    with ServingScheduler(eng) as sched:
        # calibrate the EWMA so the shed predictor is live
        sched.generate([2, 3, 4])
        reqs = [
            sched.submit(Request(s, deadline_s=1e-4))
            for s in srcs_of(13, (5,) * 12)
        ]
        for r in reqs:
            assert r.wait(60)
            assert r.t_deadline is not None
        assert all(r.status in ("shed", "timeout") for r in reqs), [
            r.status for r in reqs
        ]
        assert any(r.status == "shed" for r in reqs)
    assert eng.pages.n_used == 0


def test_scheduler_shed_verdict_uses_predictor(small_gen):
    """Deterministic predictor unit: with a calibrated EWMA, an infeasible
    deadline sheds and a generous one admits."""
    eng = make_engine(small_gen)
    sched = ServingScheduler(eng)
    sched.close()  # predictor methods are pure reads after close
    sched._rung_token_s = {4: 0.01}  # 10 ms/token at the full house
    sched._ewma_tokens = 8.0         # 80 ms expected service
    now = 1000.0
    tight = Request([2, 3], deadline_s=0.05)
    tight.t_submit, tight.t_deadline = now, now + 0.05
    verdict = sched._shed_verdict(tight, n_ahead=4, now=now)
    assert verdict is not None and verdict.startswith("shed:")
    wide = Request([2, 3], deadline_s=10.0)
    wide.t_submit, wide.t_deadline = now, now + 10.0
    assert sched._shed_verdict(wide, n_ahead=4, now=now) is None
    # uncalibrated predictor never sheds blind
    sched._rung_token_s = {}
    assert sched._shed_verdict(tight, n_ahead=100, now=now) is None


def test_drain_finishes_in_flight_and_refuses_new(small_gen):
    eng = make_engine(small_gen)
    sched = ServingScheduler(eng)
    reqs = [sched.submit(Request(s)) for s in srcs_of(14, (4,) * 6)]
    assert sched.drain(60.0) is True
    assert all(r.status == "served" for r in reqs)
    for r in reqs:
        assert r.result() == eng.reference_decode(r.src_ids, MAXLEN)
    with pytest.raises(RuntimeError):
        sched.submit(Request([2, 3]))
    assert not [
        t for t in threading.enumerate() if t.name.startswith("paddle-serve")
    ]


# ---------------------------------------------------------------------------
# chunked prefill (serving_prefill_chunk_tokens)
# ---------------------------------------------------------------------------


def test_chunked_prefill_bit_identical_interleaved(small_gen):
    """A long prompt prefilling in ladder-rung chunks, interleaved with
    short prompts decoding live, changes NOTHING in any request's output
    vs the one-shot path — and the chunk programs stay a bounded set."""
    eng = make_engine(small_gen, prefill_chunk_tokens=16, hbm_budget_mb=4)
    long_srcs = srcs_of(20, (40, 70))
    short_srcs = srcs_of(21, (3, 5))
    reqs = [Request(s) for s in long_srcs + short_srcs]
    eng.admit(reqs)
    assert eng.n_prefilling == 2 and eng.n_live == 2
    done = []
    for _ in range(300):
        done += eng.step()
        if len(done) == len(reqs):
            break
    assert len(done) == len(reqs)
    for r in reqs:
        assert r.tokens == eng.reference_decode(r.src_ids, MAXLEN), r.req_id
    # fw + bw + scatter + boot: exactly four traced chunk programs
    assert eng.trace_counts["prefill_chunk"] == 4, eng.trace_counts
    assert eng.pages.n_used == 0 and eng.n_free_slots == eng.max_slots
    # a second long round re-uses every chunk program (zero new traces)
    before = dict(eng.trace_counts)
    reqs2 = [Request(s) for s in srcs_of(22, (33, 50))]
    eng.admit(reqs2)
    while eng.n_live or eng.n_prefilling:
        eng.step()
    assert eng.trace_counts == before
    for r in reqs2:
        assert r.tokens == eng.reference_decode(r.src_ids, MAXLEN)


def test_chunked_prefill_through_scheduler(small_gen):
    eng = make_engine(small_gen, prefill_chunk_tokens=16, hbm_budget_mb=4)
    srcs = srcs_of(23, (40, 4, 25, 6))
    with ServingScheduler(eng) as sched:
        reqs = [sched.submit(Request(s)) for s in srcs]
        for r in reqs:
            assert r.wait(120), r
        for r in reqs:
            assert r.result() == eng.reference_decode(r.src_ids, MAXLEN)


def test_chunked_prefill_flag_validation(small_gen):
    with pytest.raises(ValueError, match="multiple"):
        make_engine(small_gen, prefill_chunk_tokens=24)  # not a blk multiple
    with pytest.raises(ValueError, match="divide"):
        make_engine(small_gen, prefill_chunk_tokens=48)  # 64-rung misfit


def test_chunked_prefill_cancel_mid_prefill(small_gen):
    eng = make_engine(small_gen, prefill_chunk_tokens=16, hbm_budget_mb=4)
    r = Request(srcs_of(24, (70,))[0])
    eng.admit([r])
    eng.step()  # one fw chunk in
    assert eng.n_prefilling == 1
    assert eng.cancel(r) is True
    assert eng.pages.n_used == 0 and eng.n_free_slots == eng.max_slots
    assert eng.cancel(r) is False  # idempotent miss


# ---------------------------------------------------------------------------
# greedy early-exit / max_new_tokens (ops/beam contract)
# ---------------------------------------------------------------------------


def _toy_step_fn(vocab=6, eos=1):
    """Deterministic step_fn: row b emits token (2+b+t) % vocab until step
    3+b, then eos — exercises per-row finish times."""

    def step_fn(ids, carry):
        t = carry["t"]
        b = ids.shape[0]
        row = jnp.arange(b, dtype=jnp.int32)
        tok = jnp.where(t < 3 + row, (2 + row + t) % vocab, eos)
        logp = jnp.full((b, vocab), -20.0).at[row, tok].set(0.0)
        return logp, {"t": t + 1}

    return step_fn, {"t": jnp.asarray(0, jnp.int32)}


def test_greedy_early_exit_bit_identical_toy():
    step_fn, carry = _toy_step_fn()
    full = greedy_search(step_fn, carry, 3, 0, 1, 12)
    early = greedy_search(step_fn, carry, 3, 0, 1, 12, early_exit=True)
    np.testing.assert_array_equal(np.asarray(full[0]), np.asarray(early[0]))
    np.testing.assert_array_equal(np.asarray(full[1]), np.asarray(early[1]))
    # truncation: capped run == full run's first k columns
    for k in (1, 4, 12, 99):
        capped = greedy_search(
            step_fn, carry, 3, 0, 1, 12, max_new_tokens=k, early_exit=True
        )
        kk = min(k, 12)
        np.testing.assert_array_equal(
            np.asarray(capped[0]), np.asarray(full[0])[:, :kk]
        )
        np.testing.assert_array_equal(
            np.asarray(capped[1]), np.minimum(np.asarray(full[1]), kk)
        )
    zero = greedy_search(step_fn, carry, 3, 0, 1, 12, max_new_tokens=0)
    assert np.asarray(zero[0]).shape == (3, 0)


def test_generate_greedy_early_exit_bit_identical(small_gen):
    from paddle_tpu.reader.feeder import DataFeeder

    feeder = DataFeeder(small_gen._enc_net.topology.data_types())
    batch = feeder([(s,) for s in srcs_of(6, (3, 5, 4))])
    full_t, full_l = small_gen.generate_greedy(batch, early_exit=False)
    early_t, early_l = small_gen.generate_greedy(batch)  # default early exit
    np.testing.assert_array_equal(np.asarray(full_t), np.asarray(early_t))
    np.testing.assert_array_equal(np.asarray(full_l), np.asarray(early_l))
    cap_t, cap_l = small_gen.generate_greedy(batch, max_new_tokens=3)
    np.testing.assert_array_equal(np.asarray(cap_t), np.asarray(full_t)[:, :3])
    np.testing.assert_array_equal(
        np.asarray(cap_l), np.minimum(np.asarray(full_l), 3)
    )


# ---------------------------------------------------------------------------
# batch-row canonicalization helpers (core/batch.py)
# ---------------------------------------------------------------------------


def test_pad_and_slice_batch_rows():
    b = {
        "x": SeqTensor(np.ones((3, 5, 2), np.float32),
                       np.asarray([5, 2, 4], np.int32)),
        "y": SeqTensor(np.ones((3, 7), np.float32)),
    }
    p = pad_batch_rows(b, 8)
    assert p["x"].data.shape == (8, 5, 2)
    assert p["y"].data.shape == (8, 7)
    # dead rows: zero data, length 1 (never 0 — mean-pool safe)
    assert p["x"].data[3:].sum() == 0
    assert list(np.asarray(p["x"].lengths)) == [5, 2, 4, 1, 1, 1, 1, 1]
    s = slice_batch_rows(p, 3)
    np.testing.assert_array_equal(np.asarray(s["x"].data), b["x"].data)
    np.testing.assert_array_equal(np.asarray(s["x"].lengths), b["x"].lengths)
    # already at the rung: no-op
    assert pad_batch_rows(b, 3)["x"] is b["x"]


# ---------------------------------------------------------------------------
# open-loop load generator
# ---------------------------------------------------------------------------


def test_loadgen_open_loop_arrivals_independent_of_completion():
    # virtual clock: sleep() advances it; submit() takes 0.4s of "service
    # time" — open loop means arrival TIMES still follow the schedule
    now = [0.0]

    def clock():
        return now[0]

    def sleep(s):
        now[0] += s

    gen = OpenLoopLoadGen(
        10.0, 5, lambda i: i, process="uniform", clock=clock, sleep=sleep
    )
    times = []

    def submit(i):
        times.append((i, clock()))
        now[0] += 0.4  # a slow server mustn't throttle the arrival clock
        return i

    gen.run(submit)
    assert [i for i, _ in times] == [0, 1, 2, 3, 4]
    # uniform at 10 req/s: scheduled arrivals at 0.1, 0.2, ...; service
    # time pushes the clock PAST later arrivals, which then fire with no
    # extra wait (the queueing shows up at the server, not the generator)
    assert times[0][1] == pytest.approx(0.1, abs=1e-6)
    assert times[1][1] == pytest.approx(0.5, abs=1e-6)


def test_loadgen_deterministic_schedule():
    a = OpenLoopLoadGen(5.0, 8, lambda i: i, seed=3).arrivals
    b = OpenLoopLoadGen(5.0, 8, lambda i: i, seed=3).arrivals
    c = OpenLoopLoadGen(5.0, 8, lambda i: i, seed=4).arrivals
    assert a == b != c
    with pytest.raises(ValueError):
        OpenLoopLoadGen(0.0, 1, lambda i: i)
    with pytest.raises(ValueError):
        OpenLoopLoadGen(1.0, 1, lambda i: i, process="bursty")


def test_loadgen_burst_process_mean_rate_and_burstiness():
    """Burst arrivals: seeded-deterministic, long-run mean close to the
    nominal rate, and gap dispersion strictly above the plain-Poisson
    floor (the bursts are real, not relabeled exponentials)."""
    n, rate = 4000, 20.0
    g1 = OpenLoopLoadGen(rate, n, lambda i: i, process="burst", seed=7)
    g2 = OpenLoopLoadGen(rate, n, lambda i: i, process="burst", seed=7)
    assert g1.arrivals == g2.arrivals
    mean_rate = n / g1.arrivals[-1]
    assert 0.7 * rate < mean_rate < 1.4 * rate, mean_rate
    gaps = np.diff([0.0] + g1.arrivals)
    pois = np.diff(
        [0.0] + OpenLoopLoadGen(rate, n, lambda i: i, seed=7).arrivals
    )
    # exponential gaps have CV ~= 1; a two-state modulated process is
    # overdispersed
    cv_burst = gaps.std() / gaps.mean()
    cv_pois = pois.std() / pois.mean()
    assert cv_burst > cv_pois * 1.1, (cv_burst, cv_pois)
    with pytest.raises(ValueError):
        OpenLoopLoadGen(1.0, 4, lambda i: i, process="burst",
                        burst_factor=5.0, burst_fraction=0.5)


def test_loadgen_stamps_deadlines_and_honors_stop():
    class Req:
        deadline_s = None

    now = [0.0]
    gen = OpenLoopLoadGen(
        10.0, 6, lambda i: Req(), process="uniform", deadline_s=1.5,
        clock=lambda: now[0], sleep=lambda s: now.__setitem__(0, now[0] + s),
    )
    seen = []
    out = gen.run(seen.append, stop=lambda: len(seen) >= 3)
    assert len(out) == len(seen) == 3  # stop truncated the schedule
    assert all(r.deadline_s == 1.5 for r in seen)


# ---------------------------------------------------------------------------
# per-class SLO admission (Request.priority, strict-priority-with-aging,
# per-class shed slack — the PR-20 service-class plane)
# ---------------------------------------------------------------------------


def test_parse_class_spec_grammar():
    from paddle_tpu.serving.scheduler import _parse_class_spec

    assert _parse_class_spec("0:0.25,2:1.5") == {0: 0.25, 2: 1.5}
    assert _parse_class_spec(" 1:2 ") == {1: 2.0}
    assert _parse_class_spec("") == {}
    assert _parse_class_spec(None) == {}


def test_request_priority_default_and_class_label():
    assert Request([2, 3]).priority == 1
    assert Request([2, 3]).class_label == "p1"
    assert Request([2, 3], priority=0).class_label == "p0"
    assert Request([2, 3], priority=7).class_label == "p7"


def test_eff_priority_aging_promotes(small_gen):
    eng = make_engine(small_gen)
    sched = ServingScheduler(eng, priority_aging_s=2.0)
    sched.close()
    r = Request([2, 3], priority=4)
    r.t_submit = 100.0
    assert sched._eff_priority(r, 100.0) == pytest.approx(4.0)
    # 4 seconds of wait at 2 s/level promote two levels
    assert sched._eff_priority(r, 104.0) == pytest.approx(2.0)
    # aging off: pure strict priority (starvation is explicit)
    sched.priority_aging_s = 0.0
    assert sched._eff_priority(r, 104.0) == pytest.approx(4.0)


def test_n_ahead_counts_the_priority_queue_not_the_backlog(small_gen):
    """A high-priority arrival is judged against ITS queue: waiting
    batch requests do not count ahead of it, but earlier same-class
    submits do (stable FIFO within a class)."""
    eng = make_engine(small_gen)
    sched = ServingScheduler(eng, priority_aging_s=0.0)
    sched.close()
    now = 50.0

    def req(prio, t):
        r = Request([2, 3], priority=prio)
        r.t_submit = t
        return r

    batch = [req(2, 10.0), req(2, 11.0), req(2, 12.0)]
    high = req(0, 13.0)
    # the admission loop judges a request against the OTHER waiters
    assert sched._n_ahead_of(high, batch, now) == 0
    assert sched._n_ahead_of(
        batch[0], [batch[1], batch[2], high], now) == 1  # just high
    assert sched._n_ahead_of(
        batch[2], [batch[0], batch[1], high], now) == 3


def test_class_shed_slack_sheds_batch_first(small_gen):
    """With a calibrated predictor and a borderline deadline, the batch
    class (slack > 1, sheds early) is refused while the interactive
    class (slack < 1, holds longer) admits — low classes shed FIRST at
    the same offered deadline, by construction."""
    eng = make_engine(small_gen)
    sched = ServingScheduler(eng, class_shed_slack={0: 0.25, 2: 4.0})
    sched.close()
    sched._rung_token_s = {4: 0.01}  # est service 0.08 s at full house
    sched._ewma_tokens = 8.0
    now = 1000.0

    def req(prio, deadline):
        r = Request([2, 3], priority=prio, deadline_s=deadline)
        r.t_submit, r.t_deadline = now, now + deadline
        return r

    # per-class shed floor = 0.08 * 1.5 * slack: p0 -> 0.03s, p2 -> 0.48s
    assert sched._shed_verdict(req(0, 0.2), n_ahead=0, now=now) is None
    v = sched._shed_verdict(req(2, 0.2), n_ahead=0, now=now)
    assert v is not None and v.startswith("shed:")
    # an unconfigured class falls back to slack 1.0 (0.12s floor)
    assert sched._shed_verdict(req(1, 0.2), n_ahead=0, now=now) is None


def test_priority_dequeue_order_end_to_end(small_gen):
    """Strict-priority dequeue through the REAL engine: with one slot
    occupied, a later-submitted interactive request is served before the
    earlier batch backlog; ties within a class stay FIFO."""
    eng = make_engine(small_gen, max_slots=1)
    sched = ServingScheduler(eng)
    order = []
    note = lambda r: order.append(r.req_id)  # noqa: E731
    blocker = sched.submit(Request(srcs_of(31, (4,))[0], req_id="blk"))
    lows = [
        Request(s, priority=5, req_id=f"low{i}", callback=note)
        for i, s in enumerate(srcs_of(32, (4, 4)))
    ]
    high = Request(srcs_of(33, (4,))[0], priority=0, req_id="hi",
                   callback=note)
    for r in lows:
        sched.submit(r)
    sched.submit(high)
    for r in [blocker, *lows, high]:
        assert r.wait(60.0), r
    sched.close()
    assert order == ["hi", "low0", "low1"]
    assert all(r.status == "served" for r in [blocker, *lows, high])


def test_finalize_counts_per_class_ledger(small_gen):
    from paddle_tpu.utils.timers import StatSet

    stats = StatSet()
    eng = make_engine(small_gen)
    sched = ServingScheduler(eng, stats=stats)
    a = sched.submit(Request(srcs_of(34, (4,))[0], priority=0))
    b = sched.submit(Request(srcs_of(35, (4,))[0]))
    assert a.wait(60.0) and b.wait(60.0)
    sched.close()
    s = stats.summary()
    # EVERY status lands in the class ledger, served included — the
    # class-labeled paddle_tpu_serving_requests_total series' source
    assert s["serving/class/p0/served"]["count"] == 1
    assert s["serving/class/p1/served"]["count"] == 1


def test_class_gauges_register_and_unregister(small_gen):
    from paddle_tpu.obs.metrics import _registry

    eng = make_engine(small_gen)
    sched = ServingScheduler(eng)
    # a blocker holds the slot so a priority-stamped waiter sits in the
    # queue long enough for the step loop to snapshot its class
    blk = sched.submit(Request(srcs_of(36, (4,))[0]))
    for _ in range(40):
        sched.submit(Request(srcs_of(37, (4,))[0], priority=3,
                             deadline_s=60.0))
        keys = set(_registry.snapshot())
        if any("paddle_tpu_serving_class_queue_depth" in k
               and 'class="p3"' in k for k in keys):
            break
        blk.wait(0.05)
    else:
        pytest.fail("per-class gauges never registered")
    sched.close()
    keys = set(_registry.snapshot())
    assert not any("paddle_tpu_serving_class_queue_depth" in k
                   and 'class="p3"' in k for k in keys), keys
    assert not any("paddle_tpu_serving_class_predicted_wait" in k
                   and 'class="p3"' in k for k in keys), keys
