"""Diagnostics: layer-name error context (CustomStackTrace.h:51 equivalent),
per-layer profiling (NeuralNetwork.cpp:247 per-layer timers), parameter
stats (TrainerInternal.cpp:83-110 show_parameter_stats_period)."""

import logging

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layers
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu.utils.debug import (
    format_layer_profile,
    format_parameter_stats,
    parameter_stats,
    profile_layers,
)


def _net():
    x = layers.data("x", paddle.data_type.dense_vector(4))
    h = layers.fc(x, size=8, act=paddle.activation.Tanh(), name="hidden")
    return x, layers.fc(h, size=3, act=paddle.activation.Softmax(), name="out")


def test_layer_error_carries_name_and_type():
    reset_auto_names()
    _, out = _net()
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(0))
    bad = {"x": SeqTensor(np.zeros((2, 7), np.float32))}  # wrong width
    with pytest.raises(Exception) as ei:
        net.apply(params, bad, state=state)
    notes = "\n".join(getattr(ei.value, "__notes__", []))
    assert "hidden" in notes and "type=fc" in notes, notes


def test_profile_layers_reports_every_layer():
    reset_auto_names()
    _, out = _net()
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {"x": SeqTensor(np.random.rand(4, 4).astype(np.float32))}
    rows = profile_layers(net, params, batch, state=state)
    names = [r[0] for r in rows]
    assert names == ["hidden", "out"]
    assert all(ms >= 0 for _, _, ms in rows)
    text = format_layer_profile(rows)
    assert "TOTAL" in text and "hidden" in text


def test_parameter_stats_values():
    params = {"fc": {"w": np.asarray([[1.0, -3.0], [2.0, 0.0]]), "b": np.zeros(2)}}
    stats = parameter_stats(params)
    assert stats["fc.w"]["min"] == -3.0 and stats["fc.w"]["max"] == 2.0
    assert stats["fc.w"]["avg"] == pytest.approx(0.0)
    assert stats["fc.w"]["abs_avg"] == pytest.approx(1.5)
    assert stats["fc.b"]["size"] == 2
    assert "fc.w" in format_parameter_stats(stats)


def test_show_parameter_stats_period_logs(caplog):
    reset_auto_names()
    x, out = _net()
    y = layers.data("y", paddle.data_type.integer_value(3))
    cost = layers.classification_cost(input=out, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(12):
            yield rng.rand(4).astype(np.float32), rng.randint(3)

    with caplog.at_level(logging.INFO, logger="paddle_tpu.trainer"):
        trainer.train(
            reader=paddle.batch(reader, 4),
            num_passes=1,
            show_parameter_stats_period=2,
        )
    text = caplog.text
    assert "parameter stats" in text and "hidden.w0" in text


def test_duplicate_layer_name_rejected():
    """Two structurally different layers under one name fail at Topology
    build (the reference's config_parser duplicate-name config_assert), not
    deep inside a traced matmul."""
    reset_auto_names()
    x = layers.data("dx", paddle.data_type.dense_vector(4))
    a = layers.fc(x, size=3, name="same")
    b = layers.fc(x, size=5, name="same")
    with pytest.raises(ValueError, match="share the name"):
        Topology([layers.addto([a, b])])


def test_unknown_activation_fails_fast():
    """A bad activation name dies at DSL build with the known names listed
    (reference ActivationFunction::create fatal), not at apply time."""
    reset_auto_names()
    x = layers.data("ax", paddle.data_type.dense_vector(4))
    with pytest.raises(KeyError, match="unknown activation"):
        layers.fc(x, size=3, act="frobnicate")


def test_lstmemory_wrong_input_size_fails_fast():
    """lstmemory demands a 4H pre-projection (reference LstmLayer::init
    CHECK_EQ on input size) and says so at build."""
    reset_auto_names()
    x = layers.data("lx", paddle.data_type.dense_vector_sequence(10))
    with pytest.raises(AssertionError, match="must be 4"):
        layers.lstmemory(x)


def test_wrong_dense_dim_fails_at_feed():
    """A sample narrower than the declared dense slot dies in the feeder's
    reshape, before any device work."""
    from paddle_tpu.reader.feeder import DataFeeder

    feeder = DataFeeder([("d", paddle.data_type.dense_vector(8))])
    with pytest.raises(ValueError, match="reshape"):
        feeder([(np.zeros(5, np.float32),)])
