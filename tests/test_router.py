"""Fleet-router fast units (paddle_tpu/serving/router.py) — the routing
POLICY in-process, no sockets, no engine subprocesses:

* least-predicted-wait dispatch (engine EWMA + router-side in-flight);
* affinity-key stability (block-chain hash, tail-invariant) + rendezvous
  minimal movement (a new engine steals only the keys it wins);
* drain-aware exclusion (a draining engine takes no new requests);
* lease-expiry removal (a silent engine is pruned, a heartbeat renews);
* the zero-double-serve ledger: duplicate submits AND duplicate result
  deliveries return the ORIGINAL record exactly once, and a journal-
  recovered router refuses ids its predecessor already settled.

Fake engines are injected through ``client_factory`` — the router dials
its data plane per request, so a dict of scripted callables stands in for
the whole fleet.  The socket path is covered by
tests/test_fleet_serving_e2e.py (slow, `make chaos`).
"""

import threading

import pytest

from paddle_tpu.serving.router import (
    Router,
    affinity_key,
    rendezvous_pick,
)


class FakeClock:
    def __init__(self, now=0.0):
        self.now = float(now)

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += float(dt)


class FakeEngineClient:
    """Scripted engine data-plane client: behavior keyed on the engine's
    fake address (the router never cares what is behind the dial)."""

    def __init__(self, book, address):
        self._book = book
        self._addr = (str(address[0]), int(address[1]))

    def serve(self, req_id, src_ids, max_new_tokens=None, deadline_s=None,
              beam_size=None, session_id=None, priority=None):
        self._book.setdefault("serves", []).append((self._addr, str(req_id)))
        fn = self._book.get("serve")
        if fn is not None:
            return fn(self._addr, req_id, src_ids)
        return {
            "req_id": str(req_id), "status": "served",
            "tokens": [7, 8, 9], "error": None,
            "engine": f"fake@{self._addr[1]}",
        }

    def stats(self):
        return dict(self._book.get("stats", {}).get(self._addr, {}))

    def drain(self, timeout_s):
        self._book.setdefault("drains", []).append(self._addr)
        return True

    def ping(self):
        return "pong"

    def close(self):
        pass


def make_router(book, clk, **kw):
    kw.setdefault("address", None)
    kw.setdefault("stats_poll_s", 3600.0)  # poll thread idles: units script
    kw.setdefault("lease_timeout_s", 2.0)  # h.stats directly
    kw.setdefault("sleep", lambda s: clk.advance(s))
    return Router(
        clock=clk,
        client_factory=lambda addr, timeout: FakeEngineClient(book, addr),
        **kw,
    )


def set_stats(router, engine_id, **st):
    with router._lock:
        router._engines[engine_id].stats = st


@pytest.fixture(autouse=True)
def _no_leaked_router_threads():
    before = {t for t in threading.enumerate()}
    yield
    leaked = [
        t for t in threading.enumerate()
        if t not in before and t.name.startswith("paddle-") and t.is_alive()
    ]
    assert not leaked, f"leaked router threads: {[t.name for t in leaked]}"


# -- routing policy ---------------------------------------------------------

def test_least_predicted_wait_choice():
    clk = FakeClock()
    r = make_router({}, clk, affinity=False)
    try:
        for i, e in enumerate(("a", "b", "c")):
            r.register_engine(e, "127.0.0.1", 9000 + i)
        set_stats(r, "a", predicted_wait_s=0.5, est_service_s=0.1,
                  max_slots=2)
        set_stats(r, "b", predicted_wait_s=0.05, est_service_s=0.1,
                  max_slots=2)
        set_stats(r, "c", predicted_wait_s=0.2, est_service_s=0.1,
                  max_slots=2)
        assert r.pick_engine() == "b"
        # router-side in-flight amortized over slots covers poll staleness:
        # 12 in flight on b -> 0.05 + 12*0.1/2 = 0.65 > c's 0.2, a's 0.5
        with r._lock:
            r._engines["b"].inflight = 12
        assert r.pick_engine() == "c"
        # exclusion (the re-route `tried` set) falls through to the next
        assert r.pick_engine(exclude=("c",)) == "a"
    finally:
        r.close()


def test_affinity_key_stability():
    blk = 16
    head = list(range(2, 2 + blk))  # one whole block
    k1 = affinity_key(head + [30, 31], None, blk)
    k2 = affinity_key(head + [40, 41, 42], None, blk)
    k3 = affinity_key(head + [30, 31], None, blk)
    # the key hashes WHOLE blocks only: same head-block => same key,
    # whatever the sub-block tail — exactly the prefix-cache share unit
    assert k1 == k2 == k3
    assert affinity_key([9] * blk + [1], None, blk) != k1
    # a session id overrides the content hash (conversation stickiness)
    assert affinity_key(head, "u1", blk) == "sess:u1"
    # sub-block prompts still key deterministically
    assert affinity_key([2, 3], None, blk) == affinity_key([2, 3], None, blk)


def test_rendezvous_minimal_movement():
    keys = [f"k{i}" for i in range(100)]
    old = ["e0", "e1", "e2"]
    before = {k: rendezvous_pick(k, old) for k in keys}
    # stable under permutation of the candidate list
    assert all(
        rendezvous_pick(k, ["e2", "e0", "e1"]) == before[k] for k in keys
    )
    after = {k: rendezvous_pick(k, old + ["e3"]) for k in keys}
    moved = [k for k in keys if after[k] != before[k]]
    # rendezvous hashing: every moved key moved TO the new engine, and
    # roughly 1/4 of the keyspace moved (not a full reshuffle)
    assert moved and all(after[k] == "e3" for k in moved)
    assert len(moved) < 50


def test_affinity_respects_slack():
    clk = FakeClock()
    r = make_router({}, clk, affinity=True, affinity_slack_s=0.25)
    try:
        r.register_engine("a", "127.0.0.1", 9000)
        r.register_engine("b", "127.0.0.1", 9001)
        key = "sess:pin"
        pref = rendezvous_pick(key, ["a", "b"])
        other = "b" if pref == "a" else "a"
        # within slack: affinity wins even when the other engine is idler
        set_stats(r, pref, predicted_wait_s=0.2, est_service_s=0.0,
                  max_slots=1)
        set_stats(r, other, predicted_wait_s=0.0, est_service_s=0.0,
                  max_slots=1)
        assert r.pick_engine(key) == pref
        # beyond slack: load wins over stickiness
        set_stats(r, pref, predicted_wait_s=10.0, est_service_s=0.0,
                  max_slots=1)
        assert r.pick_engine(key) == other
    finally:
        r.close()


def test_drain_aware_exclusion():
    clk = FakeClock()
    book = {}
    r = make_router(book, clk, affinity=False)
    try:
        r.register_engine("a", "127.0.0.1", 9000)
        r.register_engine("b", "127.0.0.1", 9001)
        with r._lock:
            r._engines["a"].draining = True
        assert r.pick_engine() == "b"
        assert r.pick_engine(exclude=("b",)) is None  # draining never picked
        # the full drain protocol: forwarded over the wire, then deregistered
        assert r.drain_engine("b") is True
        assert book["drains"] == [("127.0.0.1", 9001)]
        assert r.pick_engine() is None
    finally:
        r.close()


def test_lease_expiry_removal():
    clk = FakeClock()
    r = make_router({}, clk, lease_timeout_s=2.0)
    try:
        r.register_engine("a", "127.0.0.1", 9000)
        r.register_engine("b", "127.0.0.1", 9001)
        clk.advance(1.0)
        assert r.heartbeat("a") is True  # renews to t=3.0
        clk.advance(1.5)  # t=2.5: b's lease (t=2.0) expired, a's holds
        assert r.live_engines() == ["a"]
        # an expired engine's heartbeat is refused — it must re-register
        assert r.heartbeat("b") is False
        ack = r.register_engine("b", "127.0.0.1", 9001)
        assert "b" in ack["engines"] and r.live_engines() == ["a", "b"]
    finally:
        r.close()


# -- the zero-double-serve ledger -------------------------------------------

def test_zero_double_serve_on_duplicate_delivery():
    clk = FakeClock()
    book = {}
    r = make_router(book, clk, affinity=False)
    try:
        r.register_engine("a", "127.0.0.1", 9000)
        first = r.serve("r1", [2, 3, 4])
        assert first["status"] == "served" and first["tokens"] == [7, 8, 9]
        # an at-least-once client retry re-delivers the SAME req_id: the
        # ledger returns the original record, flagged, without a second
        # engine dispatch
        again = r.serve("r1", [2, 3, 4])
        assert again["duplicate"] is True
        assert again["tokens"] == [7, 8, 9] and again["status"] == "served"
        assert [rid for _, rid in book["serves"]] == ["r1"]
        ledger = r.fleet_stats()["ledger"]
        assert ledger["served"] == 1 and sum(ledger.values()) == 1
    finally:
        r.close()


def test_duplicate_result_delivery_discarded():
    clk = FakeClock()
    r = make_router({}, clk)
    try:
        one = r._finalize("rq", "served", tokens=[1, 2], engine="a")
        assert one["tokens"] == [1, 2] and "duplicate" not in one
        # a re-route race delivers a SECOND terminal result for the same
        # id: first writer wins, the late copy is counted and discarded
        two = r._finalize("rq", "served", tokens=[9, 9], engine="b")
        assert two["duplicate"] is True and two["tokens"] == [1, 2]
        assert two["engine"] == "a"
        assert r.duplicates_discarded == 1
        assert r.fleet_stats()["ledger"]["served"] == 1
    finally:
        r.close()


def test_journal_failover_refuses_double_serve(tmp_path):
    journal = str(tmp_path / "journal.jsonl")
    clk = FakeClock()
    book = {}
    r1 = make_router(book, clk, journal_path=journal)
    try:
        r1.register_engine("a", "127.0.0.1", 9000)
        assert r1.serve("r1", [2, 3])["status"] == "served"
    finally:
        r1.close()
    # HA failover: a fresh incarnation recovers the ledger from the journal
    r2 = make_router(book, clk, journal_path=journal)
    try:
        r2.register_engine("a", "127.0.0.1", 9000)
        dup = r2.serve("r1", [2, 3])
        assert dup["duplicate"] is True and dup["status"] == "served"
        assert "recovered" in dup["error"]
        # only the original pre-failover dispatch ever reached an engine
        assert [rid for _, rid in book["serves"]] == ["r1"]
        fresh = r2.serve("r2", [2, 3])
        assert fresh["status"] == "served" and "duplicate" not in fresh
    finally:
        r2.close()


def test_frontend_validation_rejects_before_network():
    clk = FakeClock()
    book = {}
    r = make_router(book, clk)
    try:
        r.register_engine("a", "127.0.0.1", 9000)
        bad = [
            r.serve("v1", "not-a-token-list"),
            r.serve("v2", [2, -5, 3]),
            r.serve("v3", [2, 3], max_new_tokens=0),
            r.serve("v4", [2, 3], deadline_s=-1.0),
            r.serve("v5", [2, 3], beam_size=0),
        ]
        assert all(b["status"] == "rejected" for b in bad)
        assert book.get("serves", []) == []  # no network hop was paid
        ledger = r.fleet_stats()["ledger"]
        assert ledger["rejected"] == 5 and sum(ledger.values()) == 5
    finally:
        r.close()


def test_autoscaler_hook_spawn_and_retire():
    clk = FakeClock(100.0)
    # the scale decisions, not the lease plane, are under test: a lease
    # long enough that the virtual-clock jumps never expire anyone
    r = make_router({}, clk, lease_timeout_s=1000.0)
    try:
        r.register_engine("a", "127.0.0.1", 9000)
        calls = []
        r.set_autoscaler(
            spawn=lambda router: calls.append("spawn"),
            retire=lambda router, e: calls.append(f"retire:{e}"),
            shed_rate_threshold=0.5, window_s=5.0, min_engines=1,
            max_engines=4, cooldown_s=1.0,
        )
        # sustained shed rate above threshold -> spawn
        with r._lock:
            r._shed_times.extend([clk.now - 0.5] * 4)
        assert r.maybe_autoscale() == "spawn"
        assert calls == ["spawn"]
        # cooldown gates a second action
        assert r.maybe_autoscale() is None
        # a quiet window with a fleet above min -> retire the idlest
        clk.advance(10.0)
        r.register_engine("b", "127.0.0.1", 9001)
        assert r.maybe_autoscale() == "retire"
        assert calls == ["spawn", "retire:a"]
    finally:
        r.close()
