"""Mixed-precision (bfloat16 compute, float32 masters) tests.

Reference analogue: the reference is float32-only; bf16 compute is the
TPU-native performance path (MXU native dtype).  These tests pin the mixed
contract: master params stay f32, gradients arrive f32, losses stay finite
and close to the f32 run, and recurrent_group scan carries keep a
consistent dtype.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import (
    CompiledNetwork,
    get_default_compute_dtype,
    set_default_compute_dtype,
)
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu.trainer.step import make_train_step

L = paddle.layer
A = paddle.activation


def _mlp_cost():
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(16))
    lab = L.data("lab", paddle.data_type.integer_value(4))
    h = L.fc(x, size=32, act=A.Relu())
    out = L.fc(h, size=4, act=A.Softmax())
    return L.classification_cost(input=out, label=lab)


def _batch(rng, n=8):
    return {
        "x": SeqTensor(jnp.asarray(rng.randn(n, 16), jnp.float32)),
        "lab": SeqTensor(jnp.asarray(rng.randint(0, 4, n), jnp.int32)),
    }


def test_masters_stay_f32_grads_f32():
    cost = _mlp_cost()
    net = CompiledNetwork(Topology([cost]), compute_dtype=jnp.bfloat16)
    params, state = net.init(jax.random.PRNGKey(0))
    for leaf in jax.tree_util.tree_leaves(params):
        assert leaf.dtype == jnp.float32
    batch = _batch(np.random.RandomState(0))
    (c, _), grads = jax.value_and_grad(
        lambda p: net.cost(p, batch, state=state, train=False), has_aux=True
    )(params)
    assert c.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(grads):
        assert leaf.dtype == jnp.float32, leaf.dtype
    assert np.isfinite(float(c))


def test_bf16_close_to_f32():
    cost = _mlp_cost()
    topo = Topology([cost])
    net32 = CompiledNetwork(topo)
    net16 = CompiledNetwork(topo, compute_dtype=jnp.bfloat16)
    params, state = net32.init(jax.random.PRNGKey(1))
    batch = _batch(np.random.RandomState(1))
    c32, _ = net32.cost(params, batch, state=state, train=False)
    c16, _ = net16.cost(params, batch, state=state, train=False)
    # bf16 has ~3 decimal digits; costs should agree to a few percent
    assert abs(float(c32) - float(c16)) < 0.05 * max(1.0, abs(float(c32)))


def test_bf16_training_converges():
    cost = _mlp_cost()
    net = CompiledNetwork(Topology([cost]), compute_dtype=jnp.bfloat16)
    params, state = net.init(jax.random.PRNGKey(2))
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    opt_state = opt.init(params)
    step = make_train_step(net, opt)
    batch = _batch(np.random.RandomState(2), n=16)
    costs = []
    for i in range(20):
        params, state, opt_state, m = step(
            params, state, opt_state, batch, jax.random.PRNGKey(i)
        )
        costs.append(float(m["cost"]))
    assert all(np.isfinite(costs))
    assert costs[-1] < 0.3 * costs[0], costs


def test_recurrent_group_bf16_carry():
    """Scan carries must hold the compute dtype (regression: f32 masks inside
    attention promoted the carry and broke lax.scan type agreement)."""
    reset_auto_names()
    vocab = 50
    src = L.data("w", paddle.data_type.integer_value_sequence(vocab))
    emb = L.embedding(src, size=16)

    def step_fn(x):
        prev = paddle.layer.memory("h", 16)
        nxt = L.fc([x, prev], size=16, act=A.Tanh(), name="h")
        return nxt

    rec = paddle.layer.recurrent_group(step=step_fn, input=emb)
    pooled = L.pooling(rec, pooling_type=paddle.pooling.Max())
    out = L.fc(pooled, size=4, act=A.Softmax())
    lab = L.data("lab", paddle.data_type.integer_value(4))
    cost = L.classification_cost(input=out, label=lab)

    net = CompiledNetwork(Topology([cost]), compute_dtype=jnp.bfloat16)
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    feeder = paddle.reader.DataFeeder(net.topology.data_types())
    batch = feeder(
        [([int(i) for i in rng.randint(0, vocab, 5)], int(rng.randint(4)))
         for _ in range(4)]
    )
    c, _ = net.cost(params, batch, state=state, train=False)
    assert np.isfinite(float(c))


def test_default_compute_dtype_global():
    prev = get_default_compute_dtype()
    try:
        set_default_compute_dtype("bfloat16")
        cost = _mlp_cost()
        net = CompiledNetwork(Topology([cost]))
        assert net.compute_dtype == jnp.dtype(jnp.bfloat16)
        set_default_compute_dtype(None)
        net2 = CompiledNetwork(Topology([_mlp_cost()]))
        assert net2.compute_dtype == jnp.dtype(jnp.float32)
    finally:
        set_default_compute_dtype(prev)


def test_init_compute_dtype_kwarg():
    prev = get_default_compute_dtype()
    try:
        paddle.init(seed=0, compute_dtype="bfloat16")
        assert get_default_compute_dtype() == jnp.dtype(jnp.bfloat16)
    finally:
        set_default_compute_dtype(prev)
