"""Seq2seq NMT with attention, end-to-end: train on a copy task, then
beam/greedy generation — the test_recurrent_machine_generation.cpp equivalent
(reference: paddle/trainer/tests/test_recurrent_machine_generation.cpp checks
beam-search output against a golden model dir)."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost

VOCAB = 16
BOS, EOS = 0, 1
# Readers yield (src, trg, trg_next); the topology's DFS feeding order
# visits trg_word first (the cost's decoder subtree) — map explicitly, the
# reference v2 feeding= contract (v2/trainer.py:107 train(feeding=...))
FEEDING = {"src_word": 0, "trg_word": 1, "trg_next": 2}


def copy_task_reader(n=512, seed=0):
    """src: random tokens [2, VOCAB); trg = copy of src.  Slots:
    (src_word, trg_word=bos+trg, trg_next=trg+eos)."""

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = rng.randint(2, 6)
            toks = rng.randint(2, VOCAB, size=ln).tolist()
            yield toks, [BOS] + toks, toks + [EOS]

    return reader


@pytest.fixture(scope="module")
def trained():
    reset_auto_names()
    paddle.init(seed=0)
    cost, dec = seq2seq_cost(VOCAB, VOCAB, word_dim=24, hidden_dim=32)
    params = paddle.parameters.create(cost, seed=3)
    trainer = paddle.trainer.SGD(
        cost=cost,
        parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01),
    )
    costs = []
    trainer.train(
        paddle.batch(copy_task_reader(), 64),
        num_passes=14,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration)
        else None,
        feeding=FEEDING,
    )
    return trainer, costs


def test_nmt_cost_decreases(trained):
    trainer, costs = trained
    assert costs[-1] < costs[0] * 0.25, f"{costs[0]} -> {costs[-1]}"


def _gen_batch(trainer, samples):
    feeder = paddle.reader.DataFeeder(trainer.topology.data_types(), FEEDING)
    return feeder(samples)


def test_greedy_generation_copies(trained):
    trainer, _ = trained
    gen = Seq2SeqGenerator(
        trainer.parameters, VOCAB, VOCAB, word_dim=24, hidden_dim=32,
        bos_id=BOS, eos_id=EOS, max_length=10,
    )
    samples = list(copy_task_reader(n=32, seed=99)())
    batch = _gen_batch(trainer, samples)
    toks, lengths = gen.generate_greedy(batch)
    toks, lengths = np.asarray(toks), np.asarray(lengths)
    correct = 0
    for i, (src, _, _) in enumerate(samples):
        out = toks[i, : lengths[i]].tolist()
        if out == src:
            correct += 1
    # the tiny model trained briefly won't be perfect; demand better than 40%
    assert correct / len(samples) > 0.4, f"copy accuracy {correct}/{len(samples)}"


def test_beam_search_generation(trained):
    trainer, _ = trained
    gen = Seq2SeqGenerator(
        trainer.parameters, VOCAB, VOCAB, word_dim=24, hidden_dim=32,
        bos_id=BOS, eos_id=EOS, max_length=10, beam_size=3,
    )
    samples = list(copy_task_reader(n=16, seed=7)())
    batch = _gen_batch(trainer, samples)
    seqs, scores = gen.generate(batch)
    seqs, scores = np.asarray(seqs), np.asarray(scores)
    assert seqs.shape == (16, 3, 10)
    # scores sorted best-first
    assert (np.diff(scores, axis=1) <= 1e-5).all()
    # beam-0 should be at least as good as greedy on average: compare
    # copy-accuracy of top beam vs greedy
    toks_g, lens_g = gen.generate_greedy(batch)
    toks_g = np.asarray(toks_g)
    top_match = greedy_match = 0
    for i, (src, _, _) in enumerate(samples):
        beam0 = seqs[i, 0]
        eos_pos = np.where(beam0 == EOS)[0]
        out = beam0[: eos_pos[0]].tolist() if len(eos_pos) else beam0.tolist()
        top_match += out == src
        lg = int(np.asarray(lens_g)[i])
        greedy_match += toks_g[i, :lg].tolist() == src
    assert top_match >= greedy_match - 1  # beam should not be much worse


# ---------------------------------------------------------------------------
# beam-search user hooks (reference BeamSearchControlCallbacks,
# RecurrentGradientMachine.h:70-120 + diy_beam_search_prob_so .cpp:27):
# candidate-adjust / drop / norm as restricted in-graph functions
def test_greedy_early_exit_matches_full_unroll(trained):
    """The generate_greedy max_new_tokens/EOS-early-exit contract on a
    TRAINED model (real per-row eos times): early exit and per-call caps
    are bit-identical to the full unroll truncated."""
    trainer, _ = trained
    gen = Seq2SeqGenerator(
        trainer.parameters, VOCAB, VOCAB, word_dim=24, hidden_dim=32,
        bos_id=BOS, eos_id=EOS, max_length=10,
    )
    samples = list(copy_task_reader(n=24, seed=13)())
    batch = _gen_batch(trainer, samples)
    full_t, full_l = gen.generate_greedy(batch, early_exit=False)
    early_t, early_l = gen.generate_greedy(batch)  # early exit is the default
    np.testing.assert_array_equal(np.asarray(full_t), np.asarray(early_t))
    np.testing.assert_array_equal(np.asarray(full_l), np.asarray(early_l))
    for cap in (1, 4, 10, 64):  # caps beyond max_length clamp to it
        cap_t, cap_l = gen.generate_greedy(batch, max_new_tokens=cap)
        k = min(cap, 10)
        np.testing.assert_array_equal(
            np.asarray(cap_t), np.asarray(full_t)[:, :k]
        )
        np.testing.assert_array_equal(
            np.asarray(cap_l), np.minimum(np.asarray(full_l), k)
        )


# ---------------------------------------------------------------------------


def _toy_step_fn(vocab, eos_id):
    """Deterministic toy LM: fixed preferences 1 > 2 > 3 > ... regardless of
    state, eos least preferred."""
    logits = np.full((vocab,), -10.0, np.float32)
    for k in range(1, vocab - 1):
        logits[k] = -0.5 * k
    logits[eos_id] = -9.0
    logp = np.log(np.exp(logits) / np.exp(logits).sum())

    def step_fn(ids, carry):
        return jnp.asarray(np.tile(logp, (ids.shape[0], 1))), carry

    return step_fn


def test_beam_candidate_adjust_hook_bans_token():
    from paddle_tpu.ops.beam import beam_search

    V, B, K, T, EOS_ = 6, 2, 3, 4, 5
    step_fn = _toy_step_fn(V, EOS_)
    seqs, _ = beam_search(step_fn, {}, B, K, V, bos_id=0, eos_id=EOS_, max_len=T)
    assert (np.asarray(seqs)[:, 0] == 1).all()  # unconstrained: best token

    def ban_1(logp, prefix, t):
        return logp.at[:, 1].set(-1e9)

    seqs2, _ = beam_search(
        step_fn, {}, B, K, V, bos_id=0, eos_id=EOS_, max_len=T,
        candidate_adjust_fn=ban_1,
    )
    s2 = np.asarray(seqs2)
    assert (s2 != 1).all()
    assert (s2[:, 0] == 2).all()  # next-best takes over


def test_beam_drop_hook_prunes_paths():
    from paddle_tpu.ops.beam import beam_search

    V, B, K, T, EOS_ = 6, 2, 3, 4, 5
    step_fn = _toy_step_fn(V, EOS_)

    def drop_12(seqs, ids, scores, t):
        return (ids == 1) | (ids == 2)  # drop any path extended with 1 or 2

    seqs, scores = beam_search(
        step_fn, {}, B, K, V, bos_id=0, eos_id=EOS_, max_len=T,
        drop_fn=drop_12,
    )
    s = np.asarray(seqs)
    # the surviving best path uses token 3 throughout
    assert (s[:, 0] == 3).all()
    assert (np.asarray(scores)[:, 0] > -1e8).all()


def test_beam_norm_hook_rescores_final_ranking():
    from paddle_tpu.ops.beam import beam_search

    V, B, K, T, EOS_ = 6, 1, 3, 4, 5
    step_fn = _toy_step_fn(V, EOS_)
    seqs, scores = beam_search(
        step_fn, {}, B, K, V, bos_id=0, eos_id=EOS_, max_len=T
    )
    base_top = np.asarray(seqs)[0, 0].copy()

    def invert(scores, seqs, lengths):
        return -scores  # pathological on purpose: rank inversion

    seqs2, scores2 = beam_search(
        step_fn, {}, B, K, V, bos_id=0, eos_id=EOS_, max_len=T,
        norm_fn=invert,
    )
    # the former best is now ranked last; scores still reported sorted
    assert (np.asarray(seqs2)[0, -1] == base_top).all()
    assert (np.diff(np.asarray(scores2), axis=1) <= 1e-6).all()


def test_beam_hooks_through_dsl_layer(trained):
    """Hooks plumb through the layers.beam_search DSL face: banning token 1
    via candidate_adjust_fn keeps it out of the generated ids entirely."""
    trainer, _ = trained
    gen = Seq2SeqGenerator(
        trainer.parameters, VOCAB, VOCAB, word_dim=24, hidden_dim=32,
        bos_id=BOS, eos_id=EOS, max_length=10, beam_size=3,
        candidate_adjust_fn=lambda logp, prefix, t: logp.at[:, 1].set(-1e9),
    )
    samples = list(copy_task_reader(n=8, seed=21)())
    batch = _gen_batch(trainer, samples)
    seqs, _ = gen.generate(batch)
    assert (np.asarray(seqs) != 1).all()
