"""Seq2seq NMT with attention, end-to-end: train on a copy task, then
beam/greedy generation — the test_recurrent_machine_generation.cpp equivalent
(reference: paddle/trainer/tests/test_recurrent_machine_generation.cpp checks
beam-search output against a golden model dir)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost

VOCAB = 16
BOS, EOS = 0, 1


def copy_task_reader(n=512, seed=0):
    """src: random tokens [2, VOCAB); trg = copy of src.  Slots:
    (src_word, trg_word=bos+trg, trg_next=trg+eos)."""

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = rng.randint(2, 6)
            toks = rng.randint(2, VOCAB, size=ln).tolist()
            yield toks, [BOS] + toks, toks + [EOS]

    return reader


@pytest.fixture(scope="module")
def trained():
    reset_auto_names()
    paddle.init(seed=0)
    cost, dec = seq2seq_cost(VOCAB, VOCAB, word_dim=24, hidden_dim=32)
    params = paddle.parameters.create(cost, seed=3)
    trainer = paddle.trainer.SGD(
        cost=cost,
        parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01),
    )
    costs = []
    trainer.train(
        paddle.batch(copy_task_reader(), 64),
        num_passes=14,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration)
        else None,
    )
    return trainer, costs


def test_nmt_cost_decreases(trained):
    trainer, costs = trained
    assert costs[-1] < costs[0] * 0.25, f"{costs[0]} -> {costs[-1]}"


def _gen_batch(trainer, samples):
    feeder = paddle.reader.DataFeeder(trainer.topology.data_types())
    return feeder(samples)


def test_greedy_generation_copies(trained):
    trainer, _ = trained
    gen = Seq2SeqGenerator(
        trainer.parameters, VOCAB, VOCAB, word_dim=24, hidden_dim=32,
        bos_id=BOS, eos_id=EOS, max_length=10,
    )
    samples = list(copy_task_reader(n=32, seed=99)())
    batch = _gen_batch(trainer, samples)
    toks, lengths = gen.generate_greedy(batch)
    toks, lengths = np.asarray(toks), np.asarray(lengths)
    correct = 0
    for i, (src, _, _) in enumerate(samples):
        out = toks[i, : lengths[i]].tolist()
        if out == src:
            correct += 1
    # the tiny model trained briefly won't be perfect; demand better than 40%
    assert correct / len(samples) > 0.4, f"copy accuracy {correct}/{len(samples)}"


def test_beam_search_generation(trained):
    trainer, _ = trained
    gen = Seq2SeqGenerator(
        trainer.parameters, VOCAB, VOCAB, word_dim=24, hidden_dim=32,
        bos_id=BOS, eos_id=EOS, max_length=10, beam_size=3,
    )
    samples = list(copy_task_reader(n=16, seed=7)())
    batch = _gen_batch(trainer, samples)
    seqs, scores = gen.generate(batch)
    seqs, scores = np.asarray(seqs), np.asarray(scores)
    assert seqs.shape == (16, 3, 10)
    # scores sorted best-first
    assert (np.diff(scores, axis=1) <= 1e-5).all()
    # beam-0 should be at least as good as greedy on average: compare
    # copy-accuracy of top beam vs greedy
    toks_g, lens_g = gen.generate_greedy(batch)
    toks_g = np.asarray(toks_g)
    top_match = greedy_match = 0
    for i, (src, _, _) in enumerate(samples):
        beam0 = seqs[i, 0]
        eos_pos = np.where(beam0 == EOS)[0]
        out = beam0[: eos_pos[0]].tolist() if len(eos_pos) else beam0.tolist()
        top_match += out == src
        lg = int(np.asarray(lens_g)[i])
        greedy_match += toks_g[i, :lg].tolist() == src
    assert top_match >= greedy_match - 1  # beam should not be much worse
