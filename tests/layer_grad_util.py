"""Gradient-check harness — the LayerGradUtil equivalent (reference:
paddle/gserver/tests/LayerGradUtil.h:33-60 testLayerGrad): build a micro-net
around a single layer, run numeric-vs-analytic gradient comparison through
the whole jitted forward, for both parameters and inputs.

jax.test_util.check_grads does central-difference comparison against VJPs.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.test_util import check_grads

from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import LayerOutput, Topology, reset_auto_names


def rand_batch_for(topology: Topology, batch_size: int = 4, max_len: int = 6, seed: int = 0):
    """Random dense batch for every data layer; index slots get valid ids."""
    rng = np.random.RandomState(seed)
    batch = {}
    for name, conf in topology.data_layers().items():
        it = conf.input_type
        if it is None:
            continue
        from paddle_tpu.core.data_types import SeqLevel, SlotKind

        if it.seq == SeqLevel.NONE:
            if it.kind == SlotKind.INDEX:
                batch[name] = SeqTensor(
                    jnp.asarray(rng.randint(0, it.dim, size=batch_size), jnp.int32)
                )
            else:
                batch[name] = SeqTensor(
                    jnp.asarray(rng.randn(batch_size, it.dim), jnp.float32)
                )
        elif it.seq == SeqLevel.SUB_SEQ:
            s_max = 3
            n_sub = jnp.asarray(
                rng.randint(1, s_max + 1, size=batch_size), jnp.int32
            )
            sub_len = jnp.asarray(
                rng.randint(1, max_len + 1, size=(batch_size, s_max)), jnp.int32
            )
            if it.kind == SlotKind.INDEX:
                data = jnp.asarray(
                    rng.randint(0, it.dim, size=(batch_size, s_max, max_len)),
                    jnp.int32,
                )
            else:
                data = jnp.asarray(
                    rng.randn(batch_size, s_max, max_len, it.dim), jnp.float32
                )
            batch[name] = SeqTensor(data, n_sub, sub_len)
        else:
            lengths = jnp.asarray(
                rng.randint(2, max_len + 1, size=batch_size), jnp.int32
            )
            if it.kind == SlotKind.INDEX:
                data = jnp.asarray(
                    rng.randint(0, it.dim, size=(batch_size, max_len)), jnp.int32
                )
            else:
                data = jnp.asarray(
                    rng.randn(batch_size, max_len, it.dim), jnp.float32
                )
            batch[name] = SeqTensor(data, lengths)
    return batch


def check_layer_grad(
    out_layer: LayerOutput,
    batch_size: int = 4,
    max_len: int = 6,
    seed: int = 0,
    atol: float = 5e-2,
    rtol: float = 5e-2,
    eps: float = 1e-3,
    check_inputs: bool = True,
    batch: Optional[Dict[str, SeqTensor]] = None,
):
    """Numeric-vs-analytic gradient of mean(output) wrt params (and dense
    inputs).  Scalar reduction mirrors testLayerGrad's implicit cost."""
    topo = Topology([out_layer])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(seed))
    if batch is None:
        batch = rand_batch_for(topo, batch_size, max_len, seed)

    def loss_from_params(p):
        outs, _ = net.apply(p, batch, state=state, train=False)
        o = outs[out_layer.name]
        data = o.masked_data() if o.is_seq else o.data
        return jnp.mean(jnp.square(data))  # square: exercise nonunit cotangent

    if jax.tree_util.tree_leaves(params):
        check_grads(
            loss_from_params, (params,), order=1, modes=["rev"],
            atol=atol, rtol=rtol, eps=eps,
        )

    if check_inputs:
        dense_slots = [
            n for n, t in batch.items()
            if jnp.issubdtype(t.data.dtype, jnp.floating)
        ]

        def loss_from_inputs(*dense_vals):
            b2 = dict(batch)
            for n, v in zip(dense_slots, dense_vals):
                b2[n] = SeqTensor(v, batch[n].lengths, batch[n].sub_lengths)
            outs, _ = net.apply(params, b2, state=state, train=False)
            o = outs[out_layer.name]
            data = o.masked_data() if o.is_seq else o.data
            return jnp.mean(jnp.square(data))

        if dense_slots:
            vals = tuple(batch[n].data for n in dense_slots)
            check_grads(
                loss_from_inputs, vals, order=1, modes=["rev"],
                atol=atol, rtol=rtol, eps=eps,
            )
