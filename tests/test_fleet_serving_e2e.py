"""Fleet-serving e2e drills (slow; `make chaos` runs them SANITIZER-ARMED).

Three tiers over the REAL wire (paddle_tpu/serving/router.py on
master.Server sockets):

* socket fleet with fake schedulers — Router + 2 ``EngineAgent`` data
  planes + ``FleetClient``, requests spread over both engines, outputs
  deterministic, and a duplicate submit over the wire returns the
  ORIGINAL tokens flagged ``duplicate`` (the at-least-once ack plane);
* the ``fleet_serving`` scenario — real ``paddle-tpu serve --register``
  engine subprocesses, SIGKILL one mid-window: lease-expiry re-route,
  bounded recovery, journal-audited zero double-serves;
* the ``fleet_rolling_restart`` scenario — drain+replace every engine
  under live traffic: clean drains, rc 0 exits, fleet never below N-1.

Real processes + wall-clock traffic, so the module is slow-marked
(scripts/tier1_failset.py --slow-guard pins that).
"""

import threading
import time

import pytest

from paddle_tpu import master
from paddle_tpu.robustness.scenarios import (
    run_fleet_rolling_restart,
    run_fleet_serving,
)
from paddle_tpu.serving import EngineAgent, FleetClient, Request, Router
from paddle_tpu.serving.router import ROUTER_METHODS

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _no_leaked_fleet_threads():
    before = set(threading.enumerate())
    yield
    deadline = time.time() + 10.0
    while time.time() < deadline:
        leaked = [
            t for t in threading.enumerate()
            if t not in before and t.name.startswith("paddle-")
            and t.is_alive()
        ]
        if not leaked:
            return
        time.sleep(0.1)
    raise AssertionError(f"leaked fleet threads: {[t.name for t in leaked]}")


class FakeScheduler:
    """Scheduler-shaped stub: finalizes every request instantly with a
    deterministic token echo — the wire/routing path is under test here,
    not decoding (the scenario tests below run real engines)."""

    def __init__(self):
        self.draining = False
        self.served = 0

    def submit(self, r):
        r.tokens = [len(r.src_ids), int(r.src_ids[0])]
        r.status = "served"
        r.error = None
        self.served += 1
        r._event.set()

    def cancel(self, r, reason=""):
        pass

    def export_stats(self):
        return {
            "queue_depth": 0, "pages_in_use": 0, "predicted_wait_s": 0.0,
            "est_service_s": 0.01, "max_slots": 4, "n_live": 0,
            "draining": self.draining,
        }

    def drain(self, timeout_s):
        self.draining = True
        return True


def test_socket_fleet_routes_and_dedups():
    router = Router(address=("127.0.0.1", 0), stats_poll_s=0.1,
                    lease_timeout_s=2.0)
    agents = []
    try:
        scheds = [FakeScheduler() for _ in range(2)]
        agents = [
            EngineAgent(s, f"eng{i}", router.address)
            for i, s in enumerate(scheds)
        ]
        for a in agents:
            assert a.registered.wait(10.0), "engine never registered"
        assert router.live_engines() == ["eng0", "eng1"]

        reqs = [Request([2 + i, 3, 4], 4, req_id=f"w{i}") for i in range(12)]
        fc = FleetClient(router.address)
        try:
            for r in reqs:
                fc.submit(r)
            for r in reqs:
                assert r.wait(30.0), f"request {r.req_id} never finalized"
        finally:
            fc.close()
        for i, r in enumerate(reqs):
            assert r.status == "served" and r.error is None
            assert r.tokens == [3, 2 + i]  # the fake's deterministic echo
        assert sum(s.served for s in scheds) == 12
        assert all(s.served > 0 for s in scheds), (
            "least-predicted-wait routing never spread across the fleet: "
            f"{[s.served for s in scheds]}"
        )

        # duplicate submit over the REAL wire: the ledger answers with the
        # original tokens, no second engine dispatch
        c = master.Client(router.address, methods=ROUTER_METHODS,
                          call_timeout_s=30.0)
        try:
            first = c.serve("dup1", [5, 6, 7], 4, None, None, None)
            again = c.serve("dup1", [5, 6, 7], 4, None, None, None)
        finally:
            c.close()
        assert first["status"] == "served" and "duplicate" not in first
        assert again["duplicate"] is True
        assert again["tokens"] == first["tokens"] == [3, 5]
        assert sum(s.served for s in scheds) == 13
        ledger = router.fleet_stats()["ledger"]
        assert ledger["served"] == 13 and sum(ledger.values()) == 13
    finally:
        for a in agents:
            a.close()
        router.close()


def test_fleet_serving_scenario_kill_one_engine(tmp_path):
    out = run_fleet_serving(
        str(tmp_path), n_engines=2, n_requests=24, rate_rps=6.0, seed=0,
    )
    assert out["passed"], out
    assert out["double_served"] == 0
    assert out["ledger_disjoint"] is True
    assert sum(out["statuses"].values()) == out["n_offered"]
    assert out["reroutes"] >= 0 and out["recovery_after_kill_s"] <= 11.0
    # only SLO-sanctioned failure modes may appear under the kill
    assert out["statuses"]["rejected"] == 0 and out["statuses"]["closed"] == 0


def test_fleet_rolling_restart_scenario(tmp_path):
    out = run_fleet_rolling_restart(
        str(tmp_path), n_engines=2, n_requests=16, rate_rps=4.0, seed=0,
    )
    assert out["passed"], out
    assert all(out["drains_clean"].values())
    assert all(rc == 0 for rc in out["retired_rcs"].values())
    assert out["min_live_engines"] >= 1
    assert out["double_served"] == 0
    assert out["statuses"]["rejected"] == 0 and out["statuses"]["closed"] == 0
