"""Scenario-harness unit tests (fast tier) — registry, SLO resolution,
status ledger helpers, CLI surface.  The scenarios themselves EXECUTE in
`make scenarios` (paddle-tpu scenario --all-fast, sanitizer-armed) and in
tests/test_scenarios_e2e.py (slow, `make chaos`) — running a full
measured window here would blow the fast tier's time budget."""

import pytest

from paddle_tpu.robustness import scenarios
from paddle_tpu.utils import flags as _flags


def test_new_flags_registered_with_defaults():
    assert _flags.get_flag("serving_default_deadline_s") == 0.0
    assert _flags.get_flag("serving_queue_limit") == 0
    assert _flags.get_flag("serving_prefill_chunk_tokens") == 0
    assert _flags.get_flag("scenario_slo_ms") == 0.0
    # the per-class SLO admission flags (PR 20)
    assert _flags.get_flag("serving_priority_aging_s") == 2.0
    assert _flags.get_flag("serving_class_deadline_s") == ""
    assert _flags.get_flag("serving_class_shed_slack") == ""


def test_registry_names_and_unknown():
    assert set(scenarios.FAST_SCENARIOS) == {
        "overload", "burst_overload", "nan_request_under_load",
        "slow_client_under_load", "mixed_train_serve",
        "partition_under_load", "trace_replay_drift",
    }
    assert set(scenarios.SLOW_SCENARIOS) == {
        "fleet_kill_worker", "fleet_kill_master",
        "fleet_serving", "fleet_rolling_restart",
    }
    with pytest.raises(KeyError):
        scenarios.run_scenario("frobnicate")


def test_resolve_slo_precedence():
    wave = {"p95_service_ms": 40.0, "mean_service_ms": 12.0}
    # explicit beats everything
    assert scenarios._resolve_slo_s(200.0, wave) == pytest.approx(0.2)
    # flag beats derivation
    _flags.set_flag("scenario_slo_ms", 120.0)
    try:
        assert scenarios._resolve_slo_s(None, wave) == pytest.approx(0.12)
    finally:
        _flags.reset_flags()
    # derived: 2.5x p95 service, floored at 50 ms
    assert scenarios._resolve_slo_s(None, wave) == pytest.approx(0.1)
    assert scenarios._resolve_slo_s(None, {"p95_service_ms": 1.0}) == 0.05


def test_status_counts_and_percentiles():
    class R:
        def __init__(self, status):
            self.status = status

    counts = scenarios._status_counts(
        [R("served"), R("served"), R("shed"), R("timeout")]
    )
    assert counts["served"] == 2 and counts["shed"] == 1
    assert counts["rejected"] == 0 and counts["timeout"] == 1
    assert scenarios._pct([], 0.5) is None
    assert scenarios._pct([3.0, 1.0, 2.0], 0.5) == 2.0
    assert scenarios._ms(None) is None
    assert scenarios._ms(0.0123) == 12.3


def test_chaos_scenario_rejects_calibration_clobbering_occurrence():
    with pytest.raises(ValueError, match="occurrence"):
        scenarios.scenario_chaos_under_load(occurrence=2)
    with pytest.raises(ValueError, match="serving chaos point"):
        scenarios.scenario_chaos_under_load(point="kill")


def test_fleet_chaos_rejects_unknown_fault(tmp_path):
    with pytest.raises(ValueError, match="unknown fleet fault"):
        scenarios.run_fleet_chaos(str(tmp_path), kill="kill_everything")


def test_cli_scenario_list_and_arg_validation(capsys):
    from paddle_tpu.cli import cmd_scenario

    assert cmd_scenario(["--list"]) == 0
    out = capsys.readouterr().out
    for name in ("overload", "fleet_kill_master", "mixed_train_serve"):
        assert name in out
    assert cmd_scenario([]) == 2  # no names given
