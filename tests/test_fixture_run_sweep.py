"""Every gserver/trainer .conf fixture EXECUTES — one jitted forward with
random batches (the raw-face sibling of tests/test_dsl_run_sweep.py).

The reference runs a handful of these through its C++ integration binaries
(test_TrainerOnePass, test_RecurrentGradientMachine, test_NetworkCompare);
the rest exist as parse fixtures.  Here every one of them must BUILD and
RUN a forward pass; the few that cannot carry documented skip reasons
pointing at the test that covers their real execution path.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.v1_compat import parse_config

from layer_grad_util import rand_batch_for

DIRS = [
    "/root/reference/paddle/gserver/tests",
    "/root/reference/paddle/trainer/tests",
]

FIXTURES = sorted(
    f for d in DIRS for f in glob.glob(os.path.join(d, "*.conf"))
)

SKIP = {
    "chunking.conf":
        "CRF chunking needs sequence-typed feature slots; the checked-in "
        "data_bin_part header resolves them as flat ranking rows (the LTR "
        "regime those slots actually train under in "
        "test_protodata.py::test_trainer_big_vocab_ltr_configs_train_on_data_bin_part)",
    "sample_trainer_config_compare_sparse.conf":
        "declares word_dim=999 against 1.45M-id data — the hard-error "
        "contract is pinned by test_protodata.py::"
        "test_compare_sparse_conf_mismatched_dims_is_a_hard_error",
    "sample_trainer_config_rnn.conf":
        "trains end-to-end on the checked-in data_bin_part in "
        "test_protodata.py (big-vocab sparse id regime; random dense "
        "batches for 1.45M-wide slots would be gigabytes)",
    "sample_trainer_config_qb_rnn.conf":
        "same big-vocab regime; cost parity vs the rnn conf is pinned by "
        "tests/test_network_compare.py (CompareTwoNets)",
    "sample_trainer_nest_rnn_gen.conf":
        "generation-mode config: its exact beam outputs reproduce from the "
        "reference's shipped model in tests/test_generation_golden.py",
}


def _fix_nest_layer_group(parsed, batch):
    # the label carries ONE id per subsequence of 'word' (sequenceGen
    # process2); tie the random label's lengths to word's n_sub
    w = batch["word"]
    n_sub = w.lengths  # [B] number of subsequences
    s_max = w.data.shape[1]
    rng = np.random.RandomState(3)
    lab = parsed.topology.layers["label"]
    dim = max(lab.size, 3)
    batch = dict(batch)
    batch["label"] = SeqTensor(
        jnp.asarray(rng.randint(0, dim, size=(w.data.shape[0], s_max)),
                    jnp.int32),
        n_sub.astype(jnp.int32),
    )
    return batch


BATCH_FIX = {"sequence_nest_layer_group.conf": _fix_nest_layer_group}


@pytest.mark.parametrize(
    "path", FIXTURES, ids=lambda f: os.path.basename(f)[:-5]
)
def test_fixture_config_executes(path):
    name = os.path.basename(path)
    if name in SKIP:
        pytest.skip(SKIP[name])
    old = os.getcwd()
    os.chdir("/root/reference/paddle")  # fixtures open data files relatively
    try:
        parsed = parse_config(path)
    finally:
        os.chdir(old)
    net = CompiledNetwork(parsed.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = rand_batch_for(parsed.topology, batch_size=2, max_len=4)
    if name in BATCH_FIX:
        batch = BATCH_FIX[name](parsed, batch)
    if net.has_dynamic_widths:
        params, _ = net.resolve_dynamic_widths(params, batch)
    outs, _ = net.apply(
        params, batch, state=state, train=True, rng=jax.random.PRNGKey(1)
    )
    for oname in parsed.topology.output_names:
        v = outs[oname]
        arr = v.data if hasattr(v, "data") else v
        assert np.all(np.isfinite(np.asarray(arr, np.float32))), (
            f"{name}: output {oname} not finite"
        )
