"""Tests for nce/hsigmoid/selective_fc/lambda_cost and the misc layer batch
(reference: the corresponding cases in paddle/gserver/tests/test_LayerGrad.cpp)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor, non_seq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names

from layer_grad_util import check_layer_grad

L = paddle.layer
A = paddle.activation


@pytest.fixture(autouse=True)
def _reset_names():
    reset_auto_names()
    yield


def dense(dim=8, name="in0"):
    return L.data(name, paddle.data_type.dense_vector(dim))


def ids(vocab=10, name="lab"):
    return L.data(name, paddle.data_type.integer_value(vocab))


# -- nce / hsigmoid / selective_fc / lambda_cost ----------------------------


def test_nce_grad():
    check_layer_grad(L.nce(dense(), ids(), num_neg_samples=4))


def test_nce_with_dist_runs():
    x, lab = dense(6, "x"), ids(8)
    dist = [0.3, 0.1, 0.1, 0.1, 0.1, 0.1, 0.1, 0.1]
    out = L.nce(x, lab, num_neg_samples=3, noise_dist=dist)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {
        "x": non_seq(rng.randn(4, 6).astype(np.float32)),
        "lab": SeqTensor(jnp.asarray(rng.randint(0, 8, 4), jnp.int32)),
    }
    outs, _ = net.apply(params, batch, state=state, train=True,
                        rng=jax.random.PRNGKey(1))
    assert np.all(np.isfinite(np.asarray(outs[out.name].data)))


def test_hsigmoid_grad():
    check_layer_grad(L.hsigmoid(dense(), ids(vocab=7)))


def test_hsigmoid_probabilities_sum_to_one():
    """Sum over classes of exp(-cost(c)) must be 1 — the binary tree defines
    a normalized distribution (LinearChainCRF-style sanity used for
    HierarchicalSigmoidLayer in the reference tests)."""
    c = 6
    x, lab = dense(5, "x"), ids(c)
    out = L.hsigmoid(x, lab, num_classes=c)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(1))
    feat = np.random.RandomState(0).randn(1, 5).astype(np.float32)
    total = 0.0
    for cls in range(c):
        batch = {
            "x": non_seq(feat),
            "lab": SeqTensor(jnp.asarray([cls], jnp.int32)),
        }
        outs, _ = net.apply(params, batch, state=state)
        total += math.exp(-float(outs[out.name].data[0, 0]))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_selective_fc_grad():
    x = dense(6, "x")
    sel = L.data("sel", paddle.data_type.sparse_binary_vector(9))
    check_layer_grad(L.selective_fc(x, sel, size=9), check_inputs=False)


def test_selective_fc_masks_output():
    x = dense(4, "x")
    sel = L.data("sel", paddle.data_type.sparse_binary_vector(5))
    out = L.selective_fc(x, sel, size=5, act=A.Identity())
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    selv = np.zeros((2, 5), np.float32)
    selv[0, [1, 3]] = 1
    selv[1, [0]] = 1
    batch = {
        "x": non_seq(np.random.RandomState(0).randn(2, 4).astype(np.float32)),
        "sel": non_seq(selv),
    }
    outs, _ = net.apply(params, batch, state=state)
    got = np.asarray(outs[out.name].data)
    assert got[0, 0] == 0 and got[0, 2] == 0 and got[0, 4] == 0
    assert got[1, 1] == 0 and np.any(got[0, [1, 3]] != 0)


def test_lambda_cost_grad():
    s = L.data("s", paddle.data_type.dense_vector_sequence(1))
    y = L.data("y", paddle.data_type.dense_vector_sequence(1))
    out = L.lambda_cost(s, y)
    rng = np.random.RandomState(0)
    B, T = 3, 5
    lengths = np.array([5, 3, 4], np.int32)
    batch = {
        "s": SeqTensor(jnp.asarray(rng.randn(B, T, 1).astype(np.float32)),
                       jnp.asarray(lengths)),
        "y": SeqTensor(
            jnp.asarray(rng.randint(0, 3, (B, T, 1)).astype(np.float32)),
            jnp.asarray(lengths)),
    }
    check_layer_grad(out, batch=batch)


# -- misc batch --------------------------------------------------------------


def test_prelu_grad():
    check_layer_grad(L.prelu(dense()))


def test_prelu_partial_sum_grad():
    check_layer_grad(L.prelu(dense(8), partial_sum=4))


def test_power():
    w = L.data("w", paddle.data_type.dense_vector(1))
    x = L.data("x", paddle.data_type.dense_vector(5))
    out = L.power(x, w)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    xv = np.abs(np.random.RandomState(0).randn(3, 5)).astype(np.float32) + 0.5
    wv = np.array([[2.0], [0.5], [1.0]], np.float32)
    outs, _ = net.apply(params, {"w": non_seq(wv), "x": non_seq(xv)}, state=state)
    np.testing.assert_allclose(
        np.asarray(outs[out.name].data), xv ** wv, rtol=1e-5
    )


def test_data_norm():
    x = dense(4, "x")
    out = L.data_norm(x)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    state[out.name]["mean"] = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    state[out.name]["std"] = jnp.asarray([2.0, 2.0, 2.0, 2.0])
    xv = np.ones((2, 4), np.float32)
    outs, _ = net.apply(params, {"x": non_seq(xv)}, state=state)
    np.testing.assert_allclose(
        np.asarray(outs[out.name].data),
        (xv - np.array([1, 2, 3, 4])) / 2.0,
        rtol=1e-6,
    )


def test_block_expand():
    img = L.data("img", paddle.data_type.dense_vector(1 * 4 * 4), height=4, width=4)
    out = L.block_expand(img, block_x=2, block_y=2, stride_x=2, stride_y=2)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    xv = np.arange(16, dtype=np.float32).reshape(1, 16)
    outs, _ = net.apply(params, {"img": non_seq(xv)}, state=state)
    got = outs[out.name]
    assert got.is_seq and got.data.shape == (1, 4, 4)
    # first block = top-left 2x2 patch of the 4x4 image
    np.testing.assert_allclose(np.asarray(got.data)[0, 0], [0, 1, 4, 5])


def test_rotate():
    img = L.data("img", paddle.data_type.dense_vector(1 * 2 * 3), height=2, width=3)
    out = L.rotate(img)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    xv = np.arange(6, dtype=np.float32).reshape(1, 6)  # [[0 1 2],[3 4 5]]
    outs, _ = net.apply(params, {"img": non_seq(xv)}, state=state)
    got = np.asarray(outs[out.name].data)[0, :, :, 0]  # [3, 2] rotated CCW
    np.testing.assert_allclose(got, [[2, 5], [1, 4], [0, 3]])


def test_sub_seq():
    s = L.data("s", paddle.data_type.dense_vector_sequence(2))
    off = L.data("off", paddle.data_type.integer_value(10))
    sz = L.data("sz", paddle.data_type.integer_value(10))
    out = L.sub_seq(s, off, sz)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    data = np.arange(12, dtype=np.float32).reshape(1, 6, 2)
    batch = {
        "s": SeqTensor(jnp.asarray(data), jnp.asarray([6], jnp.int32)),
        "off": SeqTensor(jnp.asarray([2], jnp.int32)),
        "sz": SeqTensor(jnp.asarray([3], jnp.int32)),
    }
    outs, _ = net.apply(params, batch, state=state)
    got = outs[out.name]
    assert int(got.lengths[0]) == 3
    np.testing.assert_allclose(np.asarray(got.data)[0, :3], data[0, 2:5])


def test_linear_comb():
    w = L.data("w", paddle.data_type.dense_vector(3))
    x = L.data("x", paddle.data_type.dense_vector(12))
    out = L.linear_comb(w, x, size=4)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    wv = rng.randn(2, 3).astype(np.float32)
    xv = rng.randn(2, 12).astype(np.float32)
    outs, _ = net.apply(params, {"w": non_seq(wv), "x": non_seq(xv)}, state=state)
    expect = np.einsum("bm,bmd->bd", wv, xv.reshape(2, 3, 4))
    np.testing.assert_allclose(np.asarray(outs[out.name].data), expect, rtol=1e-5)


def test_cos_sim_vec_mat():
    v = L.data("v", paddle.data_type.dense_vector(4))
    m = L.data("m", paddle.data_type.dense_vector(12))
    out = L.cos_sim_vec_mat(v, m, size=3)
    check_layer_grad(out)


def test_scale_shift_grad():
    check_layer_grad(L.scale_shift(dense()))


def test_kmax_seq_score():
    s = L.data("s", paddle.data_type.dense_vector_sequence(1))
    out = L.kmax_seq_score(s, beam_size=2)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(0))
    data = np.array([[[0.1], [0.9], [0.5], [0.3]]], np.float32)
    batch = {"s": SeqTensor(jnp.asarray(data), jnp.asarray([3], jnp.int32))}
    outs, _ = net.apply(params, batch, state=state)
    np.testing.assert_array_equal(np.asarray(outs[out.name].data)[0], [1, 2])
