"""Decode raw speed (PR 17) — copy-on-write prefix sharing, speculative
decoding and paged beam serving (paddle_tpu/serving/).

The load-bearing guarantees pinned here:

* refcounted block sharing: a block frees only at refcount 0, double
  share/release of unowned blocks is REJECTED loudly, retained warm
  blocks evict LRU-first and fire ``on_evict``;
* prefill-once: a second request over a warmed full prompt admits with
  ZERO new prefill dispatches (trace counters asserted) and decodes
  BIT-IDENTICALLY to the one-shot path;
* the cache key is signature-guarded — a different engine signature
  (topology fingerprint / feed dtype / tokenizer ids) can never hit;
* copy-on-write: a writer gets private pool rows BEFORE mutation and the
  copied bytes match the originals exactly;
* speculative decoding is bit-identical to plain greedy (rejection falls
  back to the true argmax chain) and the accept-rate metric rides along;
* beam requests through the serving plane reproduce the one-shot
  ``Seq2SeqGenerator.generate`` best hypothesis exactly.

Slow open-loop/chaos drills live in tests/test_decode_speed_e2e.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
from paddle_tpu.reader.loadgen import PrefixMixer
from paddle_tpu.serving import Request, ServingEngine, ServingScheduler
from paddle_tpu.serving.pages import BlockPagedCache
from paddle_tpu.utils.timers import StatSet

V, E, H = 20, 8, 12
BOS, EOS = 0, 1
MAXLEN = 8


@pytest.fixture(scope="module")
def small_gen():
    reset_auto_names()
    cost, _ = seq2seq_cost(V, V, word_dim=E, hidden_dim=H)
    params = paddle.parameters.create(cost, seed=5)
    return Seq2SeqGenerator(
        params, V, V, word_dim=E, hidden_dim=H,
        bos_id=BOS, eos_id=EOS, max_length=MAXLEN,
    )


def make_engine(small_gen, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("hbm_budget_mb", 1)
    kw.setdefault("max_new_tokens", MAXLEN)
    kw.setdefault("stats", StatSet())
    return ServingEngine(small_gen, **kw)


def srcs_of(seed, lengths):
    rng = np.random.RandomState(seed)
    return [rng.randint(2, V, size=n).tolist() for n in lengths]


def run_all(eng, reqs, max_steps=400):
    done = []
    pending = list(reqs)
    for _ in range(max_steps):
        if pending:
            admitted = eng.admit(pending)
            pending = pending[len(admitted):]
        done += eng.step()
        if len(done) == len(reqs):
            return done
        if not (pending or eng.n_live or eng.n_prefilling):
            break
    raise AssertionError(f"only {len(done)}/{len(reqs)} finished")


# ---------------------------------------------------------------------------
# refcounted block cache (pages.py)
# ---------------------------------------------------------------------------


def test_pages_share_release_refcounts():
    c = BlockPagedCache(16, {"x": 1}, n_blocks=4, stats=StatSet())
    a = c.alloc(2)
    assert [c.refcount(p) for p in a] == [1, 1] and c.n_shared == 0
    c.share(a)
    assert [c.refcount(p) for p in a] == [2, 2]
    assert c.n_shared == 2 and c.n_used == 2  # shared blocks count ONCE
    c.release(a)
    assert [c.refcount(p) for p in a] == [1, 1] and c.n_shared == 0
    assert c.n_free == 2  # still held by the other table
    c.release(a)
    assert c.n_free == 4 and c.n_used == 0


def test_pages_double_release_and_bad_share_rejected():
    c = BlockPagedCache(16, {"x": 1}, n_blocks=4, stats=StatSet())
    a = c.alloc(1)
    c.free(a)
    with pytest.raises(ValueError, match="double free"):
        c.release(a)
    with pytest.raises(ValueError, match="sharing free block"):
        c.share(a)  # freed bytes are undefined — sharing them is a bug
    with pytest.raises(ValueError, match="foreign"):
        c.release([99])
    with pytest.raises(ValueError, match="foreign"):
        c.share([-1])


def test_pages_retain_lru_eviction_order():
    c = BlockPagedCache(16, {"x": 1}, n_blocks=4, stats=StatSet())
    evicted = []
    c.on_evict = evicted.append
    a = c.alloc(1)
    b = c.alloc(1)
    c.release(a, retain=True)  # oldest retained
    c.release(b, retain=True)
    assert c.n_retained == 2 and c.n_used == 0  # warm, not in use
    # revival: share takes a retained block back out of the LRU pool
    c.share(a)
    assert c.n_retained == 1 and c.refcount(a[0]) == 1
    c.release(a, retain=True)
    # alloc(4): 2 from the free list, then retained evict oldest-first
    got = c.alloc(4)
    assert got is not None and len(got) == 4
    assert evicted == [b[0], a[0]]  # b parked before a's re-park: b first
    assert c.n_retained == 0


def test_pages_cow_swaps_only_shared_blocks():
    c = BlockPagedCache(16, {"x": 1}, n_blocks=4, stats=StatSet())
    a = c.alloc(2)
    c.share([a[0]])  # a[0] shared with another table, a[1] exclusive
    new, copies = c.cow(a)
    assert copies and copies[0][0] == a[0]
    assert new[1] == a[1]  # exclusive block untouched
    assert new[0] != a[0] and c.refcount(new[0]) == 1
    assert c.refcount(a[0]) == 1  # the other reader keeps the original
    # refusal path: everything shared, no free blocks for the copies
    c2 = BlockPagedCache(16, {"x": 1}, n_blocks=2, stats=StatSet())
    d = c2.alloc(2)
    c2.share(d)
    assert c2.cow(d) == (None, [])


# ---------------------------------------------------------------------------
# prefill-once: COW prefix cache (engine)
# ---------------------------------------------------------------------------


def test_prefill_once_zero_dispatches_bit_identical(small_gen):
    eng = make_engine(small_gen, prefix_cache=True)
    src = srcs_of(40, (7,))[0]
    golden = eng.reference_decode(src, MAXLEN)

    (r1,) = run_all(eng, [Request(src)])
    assert r1.tokens == golden
    assert eng.prefix_misses == 1 and eng.prefix_hits == 0
    assert eng.prefix_cache_len == 1
    assert eng.pages.n_used == 0 and eng.pages.n_retained >= 1

    before = dict(eng.trace_counts)
    dispatches = []
    orig_exe = eng._prefill_exe
    eng._prefill_exe = lambda *a: (dispatches.append(1), orig_exe(*a))[1]
    (r2,) = run_all(eng, [Request(src)])
    assert r2.tokens == golden  # bit-identical through the shared blocks
    assert eng.prefix_hits == 1
    # ZERO prefill work for the warmed prompt: no new prefill traces AND
    # no prefill executable even dispatched
    assert eng.trace_counts["prefill"] == before["prefill"]
    assert eng.trace_counts["prefill_chunk"] == before["prefill_chunk"]
    assert dispatches == []
    assert eng.pages.n_used == 0  # gauge drains even with a warm cache


def test_prefix_sharing_concurrent_hits_share_blocks(small_gen):
    eng = make_engine(small_gen, prefix_cache=True)
    src = srcs_of(41, (9,))[0]
    run_all(eng, [Request(src)])  # warm the entry
    r_a, r_b = Request(src), Request(src)
    eng.admit([r_a, r_b])
    assert eng.prefix_hits == 2
    assert eng.pages.n_shared >= 1  # both tables map the SAME blocks
    done = []
    for _ in range(100):
        done += eng.step()
        if len(done) == 2:
            break
    golden = eng.reference_decode(src, MAXLEN)
    assert r_a.tokens == golden and r_b.tokens == golden
    assert eng.pages.n_used == 0 and eng.pages.n_shared == 0


def test_prefix_cache_signature_mismatch_misses(small_gen):
    """The ISSUE's bugfix guard: an engine whose signature (topology
    fingerprint / feed dtype / tokenizer ids) differs must MISS on the
    same token ids — mutated here by tampering the signature hash, which
    stands in for any component of the tuple changing."""
    eng = make_engine(small_gen, prefix_cache=True)
    src = srcs_of(42, (6,))[0]
    run_all(eng, [Request(src)])
    assert eng.prefix_cache_len == 1
    eng._cache_sig_hash ^= 0x5BD1E995  # any signature component changing
    (r2,) = run_all(eng, [Request(src)])
    assert eng.prefix_hits == 0 and eng.prefix_misses == 2
    assert r2.tokens == eng.reference_decode(src, MAXLEN)


def test_prefix_entry_dies_with_evicted_block(small_gen):
    """LRU pressure reclaims a retained block -> the owning entry drops
    WHOLE (a later hit can never map half-dead bytes), and the prompt
    simply re-prefills correctly."""
    eng = make_engine(small_gen, prefix_cache=True)
    src = srcs_of(43, (5,))[0]
    run_all(eng, [Request(src)])
    assert eng.prefix_cache_len == 1
    n = eng.pages.n_free + eng.pages.n_retained
    held = eng.pages.alloc(n)  # drain the pool: retained blocks evict
    assert held is not None
    assert eng.prefix_cache_len == 0
    eng.pages.free(held)
    (r2,) = run_all(eng, [Request(src)])
    assert eng.prefix_hits == 0  # entry was gone — honest miss
    assert r2.tokens == eng.reference_decode(src, MAXLEN)


def test_cow_copies_pool_rows_before_remap(small_gen):
    eng = make_engine(small_gen, prefix_cache=True)
    src = srcs_of(44, (8,))[0]
    run_all(eng, [Request(src)])
    r_a, r_b = Request(src), Request(src)
    eng.admit([r_a, r_b])
    sid_a = next(iter(eng._slots))
    s = eng._slots[sid_a]
    old_pages = list(s.pages)
    enc_before = np.asarray(eng._enc_pool)[old_pages]
    assert eng.ensure_private_pages(s) is True
    assert s.pages != old_pages
    assert all(eng.pages.refcount(p) == 1 for p in s.pages)
    # the copy half of copy-on-write: private rows hold the same bytes
    assert np.array_equal(np.asarray(eng._enc_pool)[s.pages], enc_before)
    # the OTHER reader still maps the originals, now exclusively
    other = eng._slots[[k for k in eng._slots if k != sid_a][0]]
    assert list(other.pages) == old_pages
    # already-private slots are a no-op
    again = list(s.pages)
    assert eng.ensure_private_pages(s) is True and s.pages == again


def test_chunked_fw_carry_reuse(small_gen):
    """Partial-prefix reuse on the chunked path: a long prompt sharing
    chunk-aligned forward chunks with an earlier prompt resumes its fw
    scan at the cached boundary (the bw pass always re-runs — it reads
    the suffix) and stays bit-identical."""
    eng = make_engine(
        small_gen, prefix_cache=True, prefill_chunk_tokens=16,
        hbm_budget_mb=4,
    )
    base = srcs_of(45, (40,))[0]
    (r1,) = run_all(eng, [Request(base)])
    assert r1.tokens == eng.reference_decode(base, MAXLEN)
    # same first 32 tokens (two full 16-token chunks), different tail
    src2 = base[:32] + srcs_of(46, (8,))[0]
    r2 = Request(src2)
    eng.admit([r2])
    p = next(iter(eng._prefilling.values()))
    assert p.cursor == 2  # fw scan resumes AFTER the two cached chunks
    assert eng._stats.count("serving/prefix_fw_reuse") == 2
    while eng.n_live or eng.n_prefilling:
        eng.step()
    assert r2.tokens == eng.reference_decode(src2, MAXLEN)


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------


def test_spec_decode_bit_identical_to_greedy(small_gen):
    srcs = srcs_of(50, (3, 7, 11, 2, 9))
    eng = make_engine(small_gen, spec_decode=True, hbm_budget_mb=2)
    done = run_all(eng, [Request(s) for s in srcs])
    assert len(done) == len(srcs)
    for r in done:
        assert r.tokens == eng.reference_decode(r.src_ids, MAXLEN), r.req_id
    assert eng.spec_proposed > 0
    assert 0.0 <= eng.spec_accept_rate() <= 1.0
    assert eng.trace_counts["verify"] >= 1
    assert eng.trace_counts["decode"] == 0  # spec path owns every step
    s = eng.summary()
    assert s["spec_decode"] is True
    assert s["spec_accept_rate"] == eng.spec_accept_rate()


def test_spec_decode_with_prefix_cache(small_gen):
    """The two tentpole halves composed: a warmed-prefix hit decoding
    speculatively over SHARED blocks is still bit-identical (verify only
    reads the encoder pools; rejection falls back to true greedy)."""
    src = srcs_of(51, (10,))[0]
    eng = make_engine(small_gen, spec_decode=True, prefix_cache=True)
    golden = eng.reference_decode(src, MAXLEN)
    (r1,) = run_all(eng, [Request(src)])
    (r2,) = run_all(eng, [Request(src)])
    assert eng.prefix_hits == 1
    assert r1.tokens == golden and r2.tokens == golden


def test_cancel_mid_speculation_releases_pages(small_gen):
    eng = make_engine(small_gen, spec_decode=True, prefix_cache=True)
    srcs = srcs_of(52, (6, 8))
    reqs = [Request(s) for s in srcs]
    eng.admit(reqs)
    eng.step()  # at least one verify dispatch in flight state
    for r in reqs:
        eng.cancel(r)
    assert eng.n_live == 0 and eng.pages.n_used == 0
    assert eng.n_free_slots == eng.max_slots


# ---------------------------------------------------------------------------
# beam search as a serving citizen
# ---------------------------------------------------------------------------


def one_shot_beam(eng, gen, src, k):
    batch = eng._feeder([(list(src),)])
    seqs, scores = gen.generate(batch, beam_size=k)
    best = []
    for t in np.asarray(seqs)[0, 0]:
        if int(t) == EOS:
            break
        best.append(int(t))
    return best[:MAXLEN], float(np.asarray(scores)[0, 0])


def test_beam_request_matches_one_shot(small_gen):
    eng = make_engine(small_gen, hbm_budget_mb=2)
    srcs = srcs_of(60, (4, 9, 6))
    reqs = [Request(s, beam_size=3) for s in srcs]
    done = run_all(eng, reqs)
    assert len(done) == len(reqs)
    for r in done:
        toks, score = one_shot_beam(eng, small_gen, r.src_ids, 3)
        assert r.tokens == toks, r.req_id
        assert r.beam_score == pytest.approx(score, rel=1e-5)
    assert eng.pages.n_used == 0
    assert eng._stats.count("serving/beam_requests") == len(reqs)


def test_beam_mixed_with_greedy_slots(small_gen):
    """Beam and greedy requests interleave in one engine: beam slots
    retire via their own whole-sequence dispatch, greedy slots keep the
    batched decode loop, and neither disturbs the other's output."""
    eng = make_engine(small_gen, hbm_budget_mb=2)
    g_src, b_src = srcs_of(61, (5, 7))
    rg, rb = Request(g_src), Request(b_src, beam_size=2)
    done = run_all(eng, [rg, rb])
    assert len(done) == 2
    assert rg.tokens == eng.reference_decode(g_src, MAXLEN)
    toks, _ = one_shot_beam(eng, small_gen, b_src, 2)
    assert rb.tokens == toks


def test_beam_size_one_is_greedy(small_gen):
    eng = make_engine(small_gen)
    src = srcs_of(62, (6,))[0]
    (r,) = run_all(eng, [Request(src, beam_size=1)])
    assert r.tokens == eng.reference_decode(src, MAXLEN)
    assert eng.trace_counts["beam"] == 0  # beam of one IS the greedy loop


def test_beam_size_validation_through_scheduler(small_gen):
    eng = make_engine(small_gen)
    with ServingScheduler(eng) as sched:
        bad = [
            sched.submit(Request([2, 3], beam_size=0)),
            sched.submit(Request([2, 3], beam_size="wide")),
            sched.submit(Request([2, 3], beam_size=V + 1)),
        ]
        good = sched.submit(Request(srcs_of(63, (5,))[0], beam_size=2))
        assert good.wait(60)
        for r in bad:
            assert r.wait(60) and r.status == "rejected", r.req_id
        assert "positive integer" in bad[0].error
        assert "positive integer" in bad[1].error
        assert "exceeds the target vocab" in bad[2].error
        assert good.status == "served" and good.beam_score is not None


# ---------------------------------------------------------------------------
# loadgen prefix mix + Prometheus gauges
# ---------------------------------------------------------------------------


def test_prefix_mixer_deterministic_and_shaped():
    m1 = PrefixMixer(V, pool_size=3, prefix_frac=0.6, seed=7)
    m2 = PrefixMixer(V, pool_size=3, prefix_frac=0.6, seed=7)
    srcs = [m1.source(i) for i in range(64)]
    assert srcs == [m2.source(i) for i in range(64)]  # replayable drill
    assert all(2 <= t < V for s in srcs for t in s)
    prefixed = [
        s for i, s in enumerate(srcs)
        if s[: len(m1.pool[i % 3])] == m1.pool[i % 3]
    ]
    assert prefixed  # the hit path gets offered load
    assert len(prefixed) < len(srcs)  # and the miss path too
    dups = [s for s in srcs if s in (list(p) for p in m1.pool)]
    assert dups  # exact full-prompt repeats exercise prefill-once
    with pytest.raises(ValueError, match="prefix_frac"):
        PrefixMixer(V, prefix_frac=1.5)
    with pytest.raises(ValueError, match="pool_size"):
        PrefixMixer(V, pool_size=0)


def test_serving_speed_gauges_render(small_gen):
    from paddle_tpu.obs.metrics import render_prometheus

    eng = make_engine(small_gen, prefix_cache=True, spec_decode=True)
    src = srcs_of(70, (6,))[0]
    with ServingScheduler(eng) as sched:
        for _ in range(2):
            r = sched.submit(Request(src))
            assert r.wait(60) and r.status == "served"
        text = render_prometheus()
        assert "paddle_tpu_serving_prefix_cache_hits 1.0" in text
        assert "paddle_tpu_serving_prefix_cache_misses 1.0" in text
        assert "paddle_tpu_serving_pages_shared 0.0" in text  # drained
        assert "paddle_tpu_serving_spec_accept_rate" in text
    # close() unregisters: a fresh render drops the serving gauges
    text = render_prometheus()
    assert "paddle_tpu_serving_prefix_cache_hits" not in text
