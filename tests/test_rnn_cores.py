"""The custom-VJP recurrence cores (ops/rnn.py _lstm_core/_gru_core/
_rnn_core) against naive autodiff scans, in float64: the hand-written
backwards (one chain GEMM per step, weight grads deferred to post-scan
einsums) must reproduce plain jax.grad-through-lax.scan to summation-order
noise.  Finite-diff checks exist in test_layer_grad; this pins the VJP
math itself across peepholes / bias / masking / reverse / boot states.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.rnn import gru_scan, lstm_scan, simple_rnn_scan


@pytest.fixture(autouse=True)
def _x64():
    """f64 for these comparisons only — restore the session default so
    other test modules keep f32 (the flag is process-global)."""
    old = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", old)


def _naive_lstm(gates, w_h, bias, w_ci, w_cf, w_co, lengths, reverse, h0, c0):
    b, t, g4 = gates.shape
    h = g4 // 4
    xs = jnp.swapaxes(gates, 0, 1)
    if reverse:
        xs = jnp.flip(xs, 0)
    tt = jnp.arange(t)[:, None]
    if lengths is None:
        mask = jnp.ones((t, b, 1), bool)
    elif reverse:
        mask = (tt >= t - lengths[None, :])[..., None]
    else:
        mask = (tt < lengths[None, :])[..., None]
    h_p = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)
    c_p = c0 if c0 is not None else jnp.zeros((b, h), gates.dtype)

    def step(carry, inp):
        h_p, c_p = carry
        x, m = inp
        a = x + h_p @ w_h
        if bias is not None:
            a = a + bias
        a_i, a_f, a_g, a_o = jnp.split(a, 4, -1)
        if w_ci is not None:
            a_i = a_i + w_ci * c_p
            a_f = a_f + w_cf * c_p
        i = jax.nn.sigmoid(a_i)
        f = jax.nn.sigmoid(a_f)
        c = f * c_p + i * jnp.tanh(a_g)
        o = jax.nn.sigmoid(a_o + (w_co * c if w_co is not None else 0.0))
        hh = o * jnp.tanh(c)
        hh = jnp.where(m, hh, h_p)
        c = jnp.where(m, c, c_p)
        return (hh, c), hh

    (hl, cl), hs = jax.lax.scan(step, (h_p, c_p), (xs, mask))
    if reverse:
        hs = jnp.flip(hs, 0)
    return jnp.swapaxes(hs, 0, 1), (hl, cl)


@pytest.mark.parametrize("peephole", [False, True])
@pytest.mark.parametrize("reverse", [False, True])
@pytest.mark.parametrize("ragged", [False, True])
def test_lstm_core_matches_autodiff(peephole, reverse, ragged):
    rng = np.random.RandomState(0)
    b, t, h = 3, 7, 5
    gates = jnp.asarray(rng.randn(b, t, 4 * h))
    w_h = jnp.asarray(rng.randn(h, 4 * h) * 0.3)
    bias = jnp.asarray(rng.randn(4 * h) * 0.1)
    peep = (
        tuple(jnp.asarray(rng.randn(h) * 0.2) for _ in range(3))
        if peephole
        else (None, None, None)
    )
    lengths = jnp.asarray([7, 4, 2]) if ragged else None
    h0 = jnp.asarray(rng.randn(b, h) * 0.5)
    c0 = jnp.asarray(rng.randn(b, h) * 0.5)

    def loss(fn, gates, w_h, bias, h0, c0):
        hs, (hl, cl) = fn(
            gates, w_h, bias, *peep, lengths,
            reverse=reverse, h0=h0, c0=c0,
        ) if fn is lstm_scan else fn(
            gates, w_h, bias, peep[0], peep[1], peep[2], lengths, reverse, h0, c0
        )
        return (
            jnp.sum(hs * jnp.cos(jnp.arange(hs.size).reshape(hs.shape)))
            + jnp.sum(hl * 1.7)
            + jnp.sum(cl * 0.6)
        )

    args = (gates, w_h, bias, h0, c0)
    v1, g1 = jax.value_and_grad(
        lambda *a: loss(lstm_scan, *a), argnums=(0, 1, 2, 3, 4)
    )(*args)
    v2, g2 = jax.value_and_grad(
        lambda *a: loss(_naive_lstm, *a), argnums=(0, 1, 2, 3, 4)
    )(*args)
    np.testing.assert_allclose(v1, v2, rtol=1e-10)
    for a, b_, name in zip(g1, g2, ("gates", "w_h", "bias", "h0", "c0")):
        np.testing.assert_allclose(a, b_, rtol=1e-8, atol=1e-10, err_msg=name)


def _naive_gru(gates, w_h, w_c, bias, lengths, reverse, h0):
    b, t, g3 = gates.shape
    h = g3 // 3
    xs = jnp.swapaxes(gates, 0, 1)
    if reverse:
        xs = jnp.flip(xs, 0)
    tt = jnp.arange(t)[:, None]
    if lengths is None:
        mask = jnp.ones((t, b, 1), bool)
    elif reverse:
        mask = (tt >= t - lengths[None, :])[..., None]
    else:
        mask = (tt < lengths[None, :])[..., None]
    h_p = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)

    def step(h_p, inp):
        x, m = inp
        if bias is not None:
            x = x + bias
        x_u, x_r, x_c = jnp.split(x, 3, -1)
        ur = h_p @ w_h
        u = jax.nn.sigmoid(x_u + ur[:, :h])
        r = jax.nn.sigmoid(x_r + ur[:, h:])
        c = jnp.tanh(x_c + (r * h_p) @ w_c)
        hh = (1.0 - u) * h_p + u * c
        hh = jnp.where(m, hh, h_p)
        return hh, hh

    hl, hs = jax.lax.scan(step, h_p, (xs, mask))
    if reverse:
        hs = jnp.flip(hs, 0)
    return jnp.swapaxes(hs, 0, 1), hl


@pytest.mark.parametrize("reverse", [False, True])
@pytest.mark.parametrize("ragged", [False, True])
def test_gru_core_matches_autodiff(reverse, ragged):
    rng = np.random.RandomState(1)
    b, t, h = 3, 6, 4
    gates = jnp.asarray(rng.randn(b, t, 3 * h))
    w_h = jnp.asarray(rng.randn(h, 2 * h) * 0.3)
    w_c = jnp.asarray(rng.randn(h, h) * 0.3)
    bias = jnp.asarray(rng.randn(3 * h) * 0.1)
    lengths = jnp.asarray([6, 3, 1]) if ragged else None
    h0 = jnp.asarray(rng.randn(b, h) * 0.5)

    def loss(fn, gates, w_h, w_c, bias, h0):
        if fn is gru_scan:
            hs, hl = fn(gates, w_h, w_c, bias, lengths,
                        reverse=reverse, h0=h0)
        else:
            hs, hl = fn(gates, w_h, w_c, bias, lengths, reverse, h0)
        return (
            jnp.sum(hs * jnp.sin(jnp.arange(hs.size).reshape(hs.shape)))
            + jnp.sum(hl * 1.3)
        )

    args = (gates, w_h, w_c, bias, h0)
    v1, g1 = jax.value_and_grad(
        lambda *a: loss(gru_scan, *a), argnums=(0, 1, 2, 3, 4)
    )(*args)
    v2, g2 = jax.value_and_grad(
        lambda *a: loss(_naive_gru, *a), argnums=(0, 1, 2, 3, 4)
    )(*args)
    np.testing.assert_allclose(v1, v2, rtol=1e-10)
    for a, b_, name in zip(g1, g2, ("gates", "w_h", "w_c", "bias", "h0")):
        np.testing.assert_allclose(a, b_, rtol=1e-8, atol=1e-10, err_msg=name)


@pytest.mark.parametrize("reverse", [False, True])
def test_simple_rnn_core_matches_autodiff(reverse):
    rng = np.random.RandomState(2)
    b, t, h = 2, 5, 4
    x = jnp.asarray(rng.randn(b, t, h))
    w_h = jnp.asarray(rng.randn(h, h) * 0.4)
    bias = jnp.asarray(rng.randn(h) * 0.1)
    lengths = jnp.asarray([5, 3])
    h0 = jnp.asarray(rng.randn(b, h) * 0.5)

    def naive(x, w_h, bias, h0):
        xs = jnp.swapaxes(x, 0, 1)
        if reverse:
            xs = jnp.flip(xs, 0)
        tt = jnp.arange(t)[:, None]
        if reverse:
            mask = (tt >= t - lengths[None, :])[..., None]
        else:
            mask = (tt < lengths[None, :])[..., None]

        def step(h_p, inp):
            xt, m = inp
            hh = jnp.tanh(xt + h_p @ w_h + bias)
            hh = jnp.where(m, hh, h_p)
            return hh, hh

        hl, hs = jax.lax.scan(step, h0, (xs, mask))
        if reverse:
            hs = jnp.flip(hs, 0)
        return jnp.swapaxes(hs, 0, 1), hl

    def loss(fn, x, w_h, bias, h0):
        if fn is simple_rnn_scan:
            hs, hl = fn(x, w_h, bias, lengths, reverse=reverse, h0=h0)
        else:
            hs, hl = fn(x, w_h, bias, h0)
        return jnp.sum(hs**2) + jnp.sum(hl * 0.7)

    args = (x, w_h, bias, h0)
    v1, g1 = jax.value_and_grad(
        lambda *a: loss(simple_rnn_scan, *a), argnums=(0, 1, 2, 3)
    )(*args)
    v2, g2 = jax.value_and_grad(
        lambda *a: loss(naive, *a), argnums=(0, 1, 2, 3)
    )(*args)
    np.testing.assert_allclose(v1, v2, rtol=1e-10)
    for a, b_, name in zip(g1, g2, ("x", "w_h", "bias", "h0")):
        np.testing.assert_allclose(a, b_, rtol=1e-8, atol=1e-10, err_msg=name)
