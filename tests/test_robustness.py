"""Fault-tolerance plane tests (paddle_tpu/robustness/): divergence
sentinel, auto-rollback with failure_max quarantine, preemption-safe
resume, chaos fault points, resilient checkpoint restore, download retry,
master-client transport retry.

Reference models: go/master/service.go:308 processFailedTask (failure_max),
go/pserver/service.go:244-303 (CRC checkpoint + restart-resume), and the
user-level checkpoint + non-blocking health signal story of TensorFlow
(arXiv:1605.08695 §4.4)."""

import math
import os
import signal

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import checkpoint as ckpt
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.parallel.mesh import shard_batch
from paddle_tpu.robustness import chaos
from paddle_tpu.robustness.preemption import (
    clear_marker,
    read_marker,
    write_marker,
)
from paddle_tpu.robustness.sentinel import DivergenceSentinel
from paddle_tpu.utils import flags
from paddle_tpu.utils.timers import StatSet, global_stats


@pytest.fixture(autouse=True)
def _clean_global_state():
    yield
    chaos.disarm()
    flags.reset_flags()


def _make_trainer(seed=0):
    reset_auto_names()
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1, act=paddle.activation.Linear())
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    params = paddle.parameters.create(cost, seed=seed)
    return paddle.trainer.SGD(
        cost=cost,
        parameters=params,
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9
        ),
    )


_W = np.array([1.0, -1.0, 2.0, 0.5], np.float32)


def _data_reader(n=48, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            xv = rng.randn(4).astype(np.float32)
            yield xv, np.array([float(xv @ _W)], np.float32)

    return reader


def _staged_batch(trainer, samples):
    feeder = trainer._make_feeder(None)
    return shard_batch(feeder(samples), trainer.mesh)


def _host_tree(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _trees_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


# ---------------------------------------------------------------------------
# chaos registry
# ---------------------------------------------------------------------------

def test_chaos_spec_parse_and_occurrence():
    chaos.arm("nan_batch@2,kill")
    assert not chaos.fire("nan_batch")  # consultation 1
    assert chaos.fire("nan_batch")      # consultation 2 == @2
    assert not chaos.fire("nan_batch")  # exact match, not >=
    assert chaos.fire("kill") and chaos.fire("kill")  # no @: every time
    assert not chaos.fire("torn_checkpoint")  # unarmed
    chaos.disarm()
    assert not chaos.fire("kill")


def test_chaos_unknown_point_raises():
    with pytest.raises(ValueError, match="unknown chaos point"):
        chaos.arm("nan_batch,typo_point@3")


def test_chaos_poison_batch_hits_first_float_slot():
    tr = _make_trainer()
    feeder = tr._make_feeder(None)
    batch = feeder([(np.ones(4, np.float32), np.ones(1, np.float32))])
    chaos.poison_batch(batch)
    x = np.asarray(batch["x"].data if hasattr(batch["x"], "data") else batch["x"])
    assert np.isnan(x.reshape(-1)[0])


# ---------------------------------------------------------------------------
# sentinel — host judge
# ---------------------------------------------------------------------------

def test_sentinel_skip_streak_diverges():
    s = DivergenceSentinel(skip_limit=3, stats=StatSet())
    assert s.observe(1.0, healthy=True) == "ok"
    assert s.observe(float("nan"), healthy=False) == "skip"
    assert s.observe(float("nan"), healthy=False) == "skip"
    assert not s.steady
    assert s.observe(float("nan"), healthy=False) == "diverged"
    assert s.total_skipped == 3
    # a healthy step breaks the streak
    s.reset()
    s.observe(float("nan"), healthy=False)
    s.observe(1.0, healthy=True)
    assert s.observe(float("nan"), healthy=False) == "skip"


def test_sentinel_ema_spike_diverges_after_patience():
    s = DivergenceSentinel(
        skip_limit=3, spike_factor=4.0, spike_patience=2,
        warmup_steps=0, stats=StatSet(),
    )
    for _ in range(5):
        assert s.observe(1.0, healthy=True) == "ok"
    ema_before = s.ema
    # finite but exploding loss: first spike tolerated, second diverges
    assert s.observe(50.0, healthy=True) == "ok"
    assert not s.steady
    # the spike must not drag the EMA toward itself
    assert s.ema == ema_before
    assert s.observe(80.0, healthy=True) == "diverged"


def test_sentinel_small_costs_never_spike():
    s = DivergenceSentinel(
        spike_factor=4.0, spike_patience=1, warmup_steps=0,
        min_spike_cost=1e-3, stats=StatSet(),
    )
    s.observe(1e-7, healthy=True)
    # 100x the EMA but under the absolute floor: convergence noise
    assert s.observe(1e-5, healthy=True) == "ok"


def test_sentinel_reset_clears_judgment_keeps_history():
    s = DivergenceSentinel(skip_limit=2, stats=StatSet())
    s.observe(float("nan"), healthy=False)
    s.reset()
    assert s.steady and s.ema is None
    assert s.total_skipped == 1  # lifetime counter survives


# ---------------------------------------------------------------------------
# sentinel — device half (the fused skip)
# ---------------------------------------------------------------------------

def test_skipped_step_keeps_state_bit_identical():
    """A NaN batch's step must be a no-op: params, optimizer state, and
    layer state bit-identical to before (the lax select in the jitted
    step), with the health flag down."""
    tr = _make_trainer()
    nan_x = np.full(4, np.nan, np.float32)
    bad = _staged_batch(tr, [(nan_x, np.ones(1, np.float32))])
    before_p = _host_tree(tr.parameters.params)
    before_o = _host_tree(tr._opt_state)
    rng = jax.random.PRNGKey(7)
    p2, s2, o2, m = tr._train_step(
        tr.parameters.params, tr.parameters.state, tr._opt_state, bad, rng
    )
    assert float(m["health"]) == 0.0
    assert not math.isfinite(float(m["cost"]))
    assert _trees_equal(p2, before_p)
    assert _trees_equal(o2, before_o)


def test_healthy_step_updates_and_flags_up():
    tr = _make_trainer()
    good = _staged_batch(
        tr, [(np.ones(4, np.float32), np.ones(1, np.float32))]
    )
    before_p = _host_tree(tr.parameters.params)
    p2, s2, o2, m = tr._train_step(
        tr.parameters.params, tr.parameters.state, tr._opt_state, good,
        jax.random.PRNGKey(7),
    )
    assert float(m["health"]) == 1.0
    assert math.isfinite(float(m["grad_norm"]))
    assert not _trees_equal(p2, before_p)


def test_sentinel_flag_off_omits_health():
    flags.set_flag("divergence_sentinel", False)
    tr = _make_trainer()
    good = _staged_batch(
        tr, [(np.ones(4, np.float32), np.ones(1, np.float32))]
    )
    _, _, _, m = tr._train_step(
        tr.parameters.params, tr.parameters.state, tr._opt_state, good,
        jax.random.PRNGKey(0),
    )
    assert "health" not in m and "grad_norm" not in m


def test_poisoned_batch_skipped_in_training_loop():
    """End to end through SGD.train: one NaN batch is skipped (counter),
    every later step is finite, and training still learns."""
    tr = _make_trainer()
    chaos.arm("nan_batch@2")
    base_skipped = global_stats.count("robustness.skipped_steps")
    costs = []
    tr.train(
        paddle.batch(_data_reader(96), 16),
        num_passes=2,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert global_stats.count("robustness.skipped_steps") == base_skipped + 1
    nans = [c for c in costs if not math.isfinite(c)]
    assert len(nans) == 1  # exactly the poisoned step
    finite = [c for c in costs if math.isfinite(c)]
    assert finite[-1] < finite[0]  # the run still converges


# ---------------------------------------------------------------------------
# rollback + quarantine
# ---------------------------------------------------------------------------

def test_rollback_restores_opt_state_rng_and_counters_exactly(tmp_path):
    tr = _make_trainer()
    reader = paddle.batch(_data_reader(), 16)
    tr.train(reader, num_passes=1)
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    tr.save_checkpoint(mgr, extra={"pass_id": 1, "batch_id": -1})
    snap_p = _host_tree(tr.parameters.params)
    snap_o = _host_tree(tr._opt_state)
    snap_rng = np.asarray(tr._rng).copy()
    snap_step = tr._step_count
    tr.train(reader, num_passes=1)  # move everything forward
    assert tr._step_count != snap_step
    extra = tr._restore_latest_full(mgr)
    assert extra is not None and extra["pass_id"] == 1
    assert tr._step_count == snap_step
    assert np.array_equal(np.asarray(tr._rng), snap_rng)
    assert _trees_equal(tr.parameters.params, snap_p)
    assert _trees_equal(tr._opt_state, snap_o)


def test_divergence_rolls_back_then_quarantines(tmp_path):
    """A persistently poisoned window: retry failure_max times, then
    quarantine it and finish the pass (the service.go:308 discipline)."""
    flags.set_flag("sentinel_skip_limit", 1)
    flags.set_flag("failure_max", 3)
    tr = _make_trainer()
    chaos.arm("nan_batch@1")
    base_rb = global_stats.count("robustness.rollbacks")
    base_q = global_stats.count("robustness.quarantined_batches")
    tr.train(
        paddle.batch(_data_reader(), 16),
        num_passes=1,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    assert global_stats.count("robustness.rollbacks") - base_rb == 3
    assert global_stats.count("robustness.quarantined_batches") - base_q == 1
    # the run survived: params are finite
    for n in tr.parameters.names():
        assert np.isfinite(np.asarray(tr.parameters.get(n))).all()


def test_divergence_without_checkpoint_dir_logs_and_continues():
    flags.set_flag("sentinel_skip_limit", 1)
    tr = _make_trainer()
    chaos.arm("nan_batch@1")
    # no checkpoint_dir: nothing to roll back to, but the run must finish
    tr.train(paddle.batch(_data_reader(), 16), num_passes=1)
    for n in tr.parameters.names():
        assert np.isfinite(np.asarray(tr.parameters.get(n))).all()


def test_lost_anchor_quarantines_instead_of_gapped_retry():
    """If restore_latest falls back PAST the checkpoint that opened the
    window (torn newest), the retained batches are not contiguous with the
    restored state — they must be quarantined, never replayed over a gap."""
    from paddle_tpu.robustness.recovery import RecoveryCoordinator

    saved = {}
    restore_step = {"v": 100}

    rc = RecoveryCoordinator(
        save_fn=lambda step, extra: saved.update({step: extra}),
        restore_fn=lambda: {"step_count": restore_step["v"]},
        failure_max=3, stats=StatSet(),
    )
    rc.checkpoint(100, {"step_count": 100})
    rc.record(0, 5, "b5")
    rc.record(0, 6, "b6")
    # anchor intact: first divergence retries
    action, window = rc.on_divergence()
    assert action == "retry" and [w[2] for w in window] == ["b5", "b6"]
    rc.replay_done()
    # now the anchor is gone: restore lands on an OLDER checkpoint
    restore_step["v"] = 50
    action, window = rc.on_divergence()
    assert action == "quarantine" and window == []
    assert rc.quarantined == 2


def test_unreplayable_window_quarantine_counts_all_batches():
    from paddle_tpu.robustness.recovery import RecoveryCoordinator

    stats = StatSet()
    rc = RecoveryCoordinator(
        save_fn=lambda step, extra: None,
        restore_fn=lambda: {"step_count": 0},
        failure_max=3, max_window_batches=4, stats=stats,
    )
    rc.checkpoint(0, {"step_count": 0})
    for i in range(9):  # blows the 4-batch replay cap
        rc.record(0, i, f"b{i}")
    action, window = rc.on_divergence()
    assert action == "quarantine" and window == []
    # every recorded batch counts, not just the capped buffer
    assert stats.count("robustness.quarantined_batches") == 9


# ---------------------------------------------------------------------------
# preemption + resume
# ---------------------------------------------------------------------------

def test_marker_roundtrip(tmp_path):
    d = str(tmp_path)
    assert read_marker(d) is None
    write_marker(d, {"pass_id": 1, "batch_id": 7})
    assert read_marker(d)["batch_id"] == 7
    clear_marker(d)
    assert read_marker(d) is None
    clear_marker(d)  # idempotent


def test_resume_requires_checkpoint_dir():
    tr = _make_trainer()
    with pytest.raises(ValueError, match="resume=True requires"):
        tr.train(paddle.batch(_data_reader(), 16), resume=True)


def test_sigterm_checkpoints_marker_and_resume_is_bitwise(tmp_path):
    """SIGTERM mid-pass → synchronous checkpoint + PREEMPTED marker; a
    fresh trainer with resume=True reproduces the uninterrupted run's
    final parameters bit-for-bit (same reader, same RNG restoration)."""
    ckdir = str(tmp_path / "ck")
    reader = paddle.batch(_data_reader(96, seed=3), 16)

    # uninterrupted reference
    ref = _make_trainer(seed=1)
    ref.train(reader, num_passes=2)

    # interrupted run: SIGTERM after the 4th step of pass 0
    tr = _make_trainer(seed=1)
    steps = [0]

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            steps[0] += 1
            if steps[0] == 4:
                os.kill(os.getpid(), signal.SIGTERM)

    tr.train(
        reader, num_passes=2, event_handler=handler,
        checkpoint_dir=ckdir, checkpoint_period_batches=2,
    )
    assert tr.preempted
    marker = read_marker(ckdir)
    assert marker is not None and marker["preempted"] is True

    # resume into a DIFFERENTLY seeded trainer: restored state must win
    tr2 = _make_trainer(seed=99)
    tr2.train(reader, num_passes=2, checkpoint_dir=ckdir, resume=True)
    assert read_marker(ckdir) is None  # marker consumed
    for n in ref.parameters.names():
        assert np.array_equal(
            np.asarray(tr2.parameters.get(n)),
            np.asarray(ref.parameters.get(n)),
        ), n


def test_resume_with_empty_dir_starts_fresh(tmp_path):
    tr = _make_trainer()
    tr.train(
        paddle.batch(_data_reader(), 16), num_passes=1,
        checkpoint_dir=str(tmp_path / "empty"), resume=True,
    )
    assert not tr.preempted


# ---------------------------------------------------------------------------
# checkpoint restore resilience (satellite)
# ---------------------------------------------------------------------------

def test_restore_latest_falls_back_past_torn_write(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    tree = {"a": np.arange(64, dtype=np.float32)}
    mgr.save(1, tree, extra={"tag": "good"})
    mgr.save(2, tree, extra={"tag": "torn"})
    # tear the newest checkpoint's data file (crash mid-write)
    chaos.tear_file(
        os.path.join(str(tmp_path / "ck"), "ckpt-00000002", "state.npz")
    )
    step, restored, extra = mgr.restore_latest(tree)
    assert step == 1 and extra["tag"] == "good"
    np.testing.assert_array_equal(restored["a"], tree["a"])


def test_restore_latest_falls_back_past_missing_meta(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    tree = {"a": np.zeros(4)}
    mgr.save(1, tree)
    mgr.save(2, tree)
    os.remove(os.path.join(str(tmp_path / "ck"), "ckpt-00000002", "meta.json"))
    step, _, _ = mgr.restore_latest(tree)
    assert step == 1


def test_restore_latest_none_when_all_unusable(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    tree = {"a": np.zeros(4)}
    mgr.save(1, tree)
    chaos.tear_file(
        os.path.join(str(tmp_path / "ck"), "ckpt-00000001", "state.npz")
    )
    assert mgr.restore_latest(tree) is None


def test_named_restore_stays_strict(tmp_path):
    """restore(step) keeps raising — a caller naming a step deserves the
    corruption error (only restore_latest walks back)."""
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    tree = {"a": np.arange(8, dtype=np.float32)}
    mgr.save(3, tree)
    data = os.path.join(str(tmp_path / "ck"), "ckpt-00000003", "state.npz")
    with open(data, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff")
    with pytest.raises(IOError):
        mgr.restore(3, tree)


def test_torn_checkpoint_chaos_point(tmp_path):
    chaos.arm("torn_checkpoint@2")
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    tree = {"a": np.arange(256, dtype=np.float32)}
    mgr.save(1, tree)
    mgr.save(2, tree)  # this write gets torn
    step, _, _ = mgr.restore_latest(tree)
    assert step == 1


# ---------------------------------------------------------------------------
# dataset download retry (satellite)
# ---------------------------------------------------------------------------

def _flaky_fetcher(fail_times, payload=b"DATA", partial=b"PAR"):
    calls = {"n": 0}

    def fetch(url, dest):
        calls["n"] += 1
        if calls["n"] <= fail_times:
            with open(dest, "wb") as f:
                f.write(partial)  # torn partial write, then the error
            raise IOError(f"flaky fetch #{calls['n']}")
        with open(dest, "wb") as f:
            f.write(payload)

    fetch.calls = calls
    return fetch


def test_download_retries_flaky_fetch(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    sleeps = []
    fetch = _flaky_fetcher(fail_times=2)
    path = common.download(
        "http://example.invalid/file.bin", "t", fetch_fn=fetch,
        max_retries=5, backoff=0.01, sleep=sleeps.append,
    )
    assert open(path, "rb").read() == b"DATA"
    assert fetch.calls["n"] == 3
    assert len(sleeps) == 2
    assert sleeps[1] > sleeps[0]  # exponential backoff
    assert not os.path.exists(path + ".part")  # partials cleaned


def test_download_exhausted_raises_and_leaves_no_partial(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    fetch = _flaky_fetcher(fail_times=99)
    with pytest.raises(IOError, match="after 3 attempt"):
        common.download(
            "http://example.invalid/f.bin", "t", fetch_fn=fetch,
            max_retries=3, backoff=0.0, sleep=lambda s: None,
        )
    d = os.path.join(str(tmp_path), "t")
    assert not any(n.endswith(".part") for n in os.listdir(d))


def test_download_md5_mismatch_counts_as_failure(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    import hashlib

    good_md5 = hashlib.md5(b"DATA").hexdigest()
    # first fetch "succeeds" but returns a truncated body; retry gets it
    calls = {"n": 0}

    def fetch(url, dest):
        calls["n"] += 1
        with open(dest, "wb") as f:
            f.write(b"DAT" if calls["n"] == 1 else b"DATA")

    path = common.download(
        "http://example.invalid/f.bin", "t", md5sum=good_md5,
        fetch_fn=fetch, max_retries=3, backoff=0.0, sleep=lambda s: None,
    )
    assert calls["n"] == 2 and common.md5file(path) == good_md5


def test_download_cached_file_short_circuits(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    fetch = _flaky_fetcher(fail_times=0)
    p1 = common.download("http://x.invalid/a.bin", "t", fetch_fn=fetch)
    p2 = common.download("http://x.invalid/a.bin", "t", fetch_fn=fetch)
    assert p1 == p2 and fetch.calls["n"] == 1


# ---------------------------------------------------------------------------
# master client transport retry (satellite)
# ---------------------------------------------------------------------------

def test_client_survives_server_bounce(tmp_path):
    """A Server bounced mid-stream: the client's reconnect-retry bridges
    the gap; records keep flowing; no failure event is burned."""
    import pickle

    from paddle_tpu.io import recordio
    from paddle_tpu.master import Client, Server, Service

    shard = str(tmp_path / "data-00000")
    recordio.write_records(
        shard, (pickle.dumps(i) for i in range(8))
    )
    svc = Service(chunks_per_task=1)
    srv = Server(svc, address=("127.0.0.1", 0))
    addr = srv.address
    c = Client(addr, reconnect_tries=8, reconnect_backoff=0.05)
    try:
        c.set_dataset([shard])
        first = c.next_record()
        assert first is not None
        # bounce: close the server, restart on the SAME address+service
        # (rebinding can race the old listener's teardown — retry briefly,
        # which is also the realistic restart timeline the client rides out)
        srv.close()
        import time as _time

        for _ in range(50):
            try:
                srv = Server(svc, address=addr)
                break
            except OSError:
                _time.sleep(0.05)
        got = [first]
        while True:
            r = c.next_record()
            if r is None:
                break
            got.append(r)
        assert sorted(pickle.loads(r) for r in got) == list(range(8))
    finally:
        c.close()
        srv.close()


def test_rpc_app_error_is_not_retried(tmp_path):
    from paddle_tpu.master import (
        Client,
        MasterRPCError,
        Server,
        Service,
    )

    svc = Service()
    srv = Server(svc, address=("127.0.0.1", 0))
    c = Client(srv.address, reconnect_tries=2, reconnect_backoff=0.01)
    try:
        with pytest.raises(MasterRPCError):
            c._call("no_such_method")
    finally:
        c.close()
        srv.close()


def test_transport_error_surfaces_distinctly():
    from paddle_tpu.master import Client, MasterTransportError

    with pytest.raises((MasterTransportError, OSError)):
        # nothing listens here; constructor or first call must fail with a
        # transport-class error, never MasterRPCError
        c = Client(("127.0.0.1", 1), reconnect_tries=1)
        c.n_tasks = lambda: c._call("n_tasks")
        c.n_tasks()


# ---------------------------------------------------------------------------
# stale HA lease chaos (satellite)
# ---------------------------------------------------------------------------

def test_stale_lease_chaos_allows_takeover(tmp_path):
    from paddle_tpu.master_ha import LeaseFile

    leader = LeaseFile(str(tmp_path), "leader", lease_timeout=0.2)
    standby = LeaseFile(str(tmp_path), "standby", lease_timeout=0.2)
    assert leader.try_acquire()
    assert leader.renew() and leader.held_by_me()
    chaos.arm("stale_lease")
    # the leader BELIEVES its renewals land, but the heartbeat never
    # reaches storage — the lease goes stale underneath it
    import time as _time

    _time.sleep(0.25)
    assert leader.renew() is True  # lies (chaos)
    assert leader.is_stale()
    assert standby.try_acquire()  # takeover
    chaos.disarm()
    assert not leader.renew()  # deposed side detects the usurper


def test_chaos_consult_report_accounts_fired_and_unfired(tmp_path):
    """The arming audit: every armed point accounts for consultations
    and fires; an armed-never-consulted point shows up as exactly that
    (the silent skew that made green drills meaningless)."""
    import json

    chaos.arm("nan_batch@2,kill_worker@5")
    try:
        assert not chaos.fire("nan_batch")   # consultation 1
        assert chaos.fire("nan_batch")       # occurrence 2 fires
        rep = chaos.consult_report()
        assert rep["nan_batch"] == {
            "occurrence": 2, "consultations": 2, "fired": 1,
        }
        # armed but the faulted code path never ran
        assert rep["kill_worker"] == {
            "occurrence": 5, "consultations": 0, "fired": 0,
        }
        out = tmp_path / "chaos-report.json"
        written = chaos.write_report(str(out))
        assert json.loads(out.read_text()) == written == rep
    finally:
        chaos.disarm()


def test_chaos_rearm_clears_audit_counters():
    chaos.arm("nan_batch")
    assert chaos.fire("nan_batch")
    chaos.arm("nan_batch@3")  # re-arm: fresh audit, fresh occurrences
    try:
        rep = chaos.consult_report()
        assert rep["nan_batch"]["consultations"] == 0
        assert rep["nan_batch"]["fired"] == 0
    finally:
        chaos.disarm()


def test_chaos_exit_report_counts_and_writes(tmp_path, monkeypatch):
    """The atexit leg of the audit: fired/unfired StatSet counters and
    the PADDLE_TPU_CHAOS_REPORT file a drill parent reads after the
    child exits (a SIGKILL'd child leaves NO file — that absence is the
    expected signature of a successful kill)."""
    import json

    from paddle_tpu.utils.timers import global_stats

    report_path = tmp_path / "exit-report.json"
    monkeypatch.setenv("PADDLE_TPU_CHAOS_REPORT", str(report_path))
    chaos.arm("nan_batch,stale_lease@9")
    try:
        assert chaos.fire("nan_batch")

        def count(name):
            return global_stats.summary().get(name, {}).get("count", 0)

        before_fired = count("chaos/fired/nan_batch")
        before_unfired = count("chaos/unfired/stale_lease")
        chaos._exit_report()
        assert count("chaos/fired/nan_batch") == before_fired + 1
        assert count("chaos/unfired/stale_lease") == before_unfired + 1
        rep = json.loads(report_path.read_text())
        assert rep["nan_batch"]["fired"] == 1
        assert rep["stale_lease"]["consultations"] == 0
    finally:
        chaos.disarm()
