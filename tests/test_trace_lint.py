"""Trace-hygiene analyzer (analysis/trace_lint.py): jaxpr-level hazard rules
fire on deliberate mutations, stay silent on the real train/generation
steps, and the recompile audit enforces the shape-ladder contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import (
    format_diagnostics,
    lint_jaxpr,
    lint_step,
    recompile_audit,
    trace_step,
)
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import Topology, reset_auto_names


def rules(diags):
    return [d.rule for d in diags]


# ---------------------------------------------------------------------------
# mutations: each hazard fires with its exact rule id
# ---------------------------------------------------------------------------


def test_t101_f64_leak_detected():
    from jax.experimental import enable_x64

    with enable_x64():
        def leaky(x):
            return x * np.float64(2.0)

        d = lint_step(leaky, jnp.ones((4,), jnp.float64))
    assert "T101" in rules(d)


def test_t101_silent_in_f32():
    def clean(x):
        return x * 2.0

    d = lint_step(clean, jnp.ones((4,), jnp.float32))
    assert "T101" not in rules(d)


def test_t102_closure_captured_weights():
    w = np.ones((256, 256), np.float32)  # 64k elements, over threshold

    def step(x):
        return x @ w

    d = lint_step(step, np.ones((4, 256), np.float32))
    assert "T102" in rules(d)
    # as an ARGUMENT the same array is fine
    d2 = lint_step(lambda wt, x: x @ wt, w, np.ones((4, 256), np.float32))
    assert "T102" not in rules(d2)


def test_t102_threshold_respected():
    small = np.ones((8, 8), np.float32)

    def step(x):
        return x @ small

    assert "T102" not in rules(lint_step(step, np.ones((4, 8), np.float32)))


def test_t103_debug_print_in_hot_path():
    def step(x):
        jax.debug.print("sum={s}", s=x.sum())
        return x * 2

    d = lint_step(step, np.ones((4,), np.float32))
    assert "T103" in rules(d)


def test_t103_detects_inside_scan_body():
    def step(x):
        def body(c, xt):
            jax.debug.print("c={c}", c=c)
            return c + xt, c

        out, _ = jax.lax.scan(body, x[0], x)
        return out

    d = lint_step(step, np.ones((4,), np.float32))
    assert "T103" in rules(d)


# ---------------------------------------------------------------------------
# recompile audit (T104/T105)
# ---------------------------------------------------------------------------


def test_t104_off_ladder_shapes():
    keys = [
        (("x", (32, 17, 8), "float32"),),   # 17 is no rung
        (("x", (32, 32, 8), "float32"),),   # 32 is
    ]
    d = recompile_audit(keys)
    assert rules(d) == ["T104"]
    assert "x axis 1: [17]" in d[0].message


def test_t104_silent_on_ladder():
    keys = [(("x", (32, r, 8), "float32"),) for r in (16, 32, 64, 128)]
    assert recompile_audit(keys) == []


def test_t105_shape_explosion():
    keys = [(("x", (b, 32, 8), "float32"),) for b in range(1, 40)]
    d = recompile_audit(keys, max_shapes=10)
    assert "T105" in rules(d)


def test_audit_accepts_compile_shape_cache():
    from paddle_tpu.core.compiler import CompileShapeCache
    from paddle_tpu.utils.timers import StatSet

    cache = CompileShapeCache("t", stats=StatSet())
    for t in (17, 33):  # unladdered VARYING lengths: one compile per batch
        cache.observe({"x": SeqTensor(np.zeros((4, t, 3), np.float32),
                                      np.full((4,), t, np.int32))})
    d = recompile_audit(cache)
    assert "T104" in rules(d)


def test_audit_accepts_feeder_batches():
    batches = [
        {"x": SeqTensor(np.zeros((4, 16, 3), np.float32),
                        np.full((4,), 9, np.int32))},
        {"x": SeqTensor(np.zeros((4, 64, 3), np.float32),
                        np.full((4,), 40, np.int32))},
    ]
    assert recompile_audit(batches) == []


# ---------------------------------------------------------------------------
# the real steps stay clean (and the satellite regression: params-as-arg)
# ---------------------------------------------------------------------------


def _lenet_step():
    import paddle_tpu.optimizer as O
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.models.lenet import lenet_cost
    from paddle_tpu.trainer.step import _train_step_body

    reset_auto_names()
    cost, _ = lenet_cost()
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    opt = O.Adam(learning_rate=1e-3)
    step = _train_step_body(net, opt)
    batch = {
        "pixel": SeqTensor(np.random.rand(8, 784).astype(np.float32)),
        "label": SeqTensor(np.random.randint(0, 10, (8,)).astype(np.int32)),
    }
    return step, (params, state, opt.init(params), batch, jax.random.PRNGKey(1))


def test_train_step_is_hazard_free():
    step, args = _lenet_step()
    d = lint_step(step, *args)
    assert d == [], format_diagnostics(d)


def test_train_step_with_debug_print_flagged():
    """Control for the clean-step test: the same step with a debug print
    spliced in is caught — the linter sees through value_and_grad/jit."""
    step, args = _lenet_step()

    def noisy(params, state, opt_state, batch, rng):
        jax.debug.print("step")
        return step(params, state, opt_state, batch, rng)

    assert "T103" in rules(lint_step(noisy, *args))


@pytest.mark.slow
def test_generator_params_as_argument_no_t102():
    """Satellite regression (bench_nmt_generate fix): jitting the generator
    with weights passed as an ARGUMENT keeps them out of the jaxpr consts;
    the old closure form bakes in every weight (T102)."""
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost

    reset_auto_names()
    cost, _ = seq2seq_cost(40, 45, word_dim=16, hidden_dim=16)
    params = paddle.parameters.create(cost, seed=0)
    gen = Seq2SeqGenerator(
        params, 40, 45, word_dim=16, hidden_dim=16, max_length=5, beam_size=2,
    )
    rng = np.random.RandomState(0)
    batch = {
        "src_word": SeqTensor(
            rng.randint(2, 40, size=(2, 6)).astype(np.int32),
            np.full((2,), 6, np.int32),
        )
    }
    # the fixed form: params ride as an argument
    good = lint_jaxpr(trace_step(
        lambda p, bt: gen.generate(bt, params=p), params.params, batch,
    ), const_elem_threshold=256)
    assert "T102" not in rules(good), format_diagnostics(good)
    # the old closure form is exactly what T102 exists to catch
    bad = lint_jaxpr(
        trace_step(lambda bt: gen.generate(bt), batch),
        const_elem_threshold=256,
    )
    assert "T102" in rules(bad)


# ---------------------------------------------------------------------------
# T106: buffer-donation audit
# ---------------------------------------------------------------------------


def _mlp_step_parts():
    import paddle_tpu.optimizer as O
    from paddle_tpu.core.compiler import CompiledNetwork

    reset_auto_names()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(64))
    h = paddle.layer.fc(x, size=256, act=paddle.activation.Relu())
    pred = paddle.layer.fc(h, size=10, act=paddle.activation.Softmax())
    y = paddle.layer.data("y", paddle.data_type.integer_value(10))
    cost = paddle.layer.classification_cost(input=pred, label=y)
    net = CompiledNetwork(Topology([cost]))
    opt = O.Adam(learning_rate=1e-3)
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {
        "x": SeqTensor(jnp.zeros((8, 64), jnp.float32)),
        "y": SeqTensor(jnp.zeros((8,), jnp.int32)),
    }
    return net, opt, (params, state, opt.init(params), batch,
                      jax.random.PRNGKey(1))


def test_t106_undonated_carry_fires():
    """A jitted train step WITHOUT donate_argnums double-buffers params and
    Adam slots — T106 names the copied argnums."""
    from paddle_tpu.analysis import donation_audit
    from paddle_tpu.trainer.step import _train_step_body

    net, opt, args = _mlp_step_parts()
    undonated = jax.jit(_train_step_body(net, opt))
    d = donation_audit(undonated, *args)
    assert "T106" in rules(d)
    # params (argnum 0) and opt slots (argnum 2) both carry large buffers
    assert any("argument 0" in x.message for x in d), format_diagnostics(d)
    assert any("argument 2" in x.message for x in d), format_diagnostics(d)


def test_t106_explicit_donate_argnums_on_plain_fn():
    """For an un-jitted fn the audit takes the donation the builder intends
    as an argument — same rule, no pjit eqn to introspect."""
    from paddle_tpu.analysis import donation_audit
    from paddle_tpu.trainer.step import _train_step_body

    net, opt, args = _mlp_step_parts()
    body = _train_step_body(net, opt)
    assert "T106" in rules(donation_audit(body, *args))
    d = donation_audit(body, *args, donate_argnums=(0, 1, 2))
    assert d == [], format_diagnostics(d)


def test_t106_shipped_builders_are_clean():
    """The shipped step builders donate their carried state: make_train_step
    (params/state/opt-state), make_multi_train_step, and the whole-pass
    epoch program (the carry pytree) all audit clean — the `make lint`
    --donation gate."""
    from paddle_tpu.analysis import donation_audit
    from paddle_tpu.trainer.step import (
        make_epoch_program,
        make_multi_train_step,
        make_train_carry,
        make_train_step,
    )

    net, opt, args = _mlp_step_parts()
    params, state, opt_state, batch, rng = args
    d = donation_audit(make_train_step(net, opt, mesh=None), *args)
    assert d == [], format_diagnostics(d)
    k = 4
    stacked = jax.tree_util.tree_map(lambda v: jnp.stack([v] * k), batch)
    d = donation_audit(
        make_multi_train_step(net, opt, k, mesh=None),
        params, state, opt_state, stacked, rng,
    )
    assert d == [], format_diagnostics(d)
    carry = make_train_carry(params, state, opt_state, rng)
    d = donation_audit(
        make_epoch_program(net, opt, mesh=None),
        carry, stacked, jnp.arange(k),
    )
    assert d == [], format_diagnostics(d)


def test_t106_read_only_inputs_never_flag():
    """A large input that is NOT returned (batch data) has no copy to save
    — the audit must not demand donating the feed."""
    from paddle_tpu.analysis import donation_audit

    def fn(w, big_batch):
        return w + big_batch.sum()

    d = donation_audit(
        fn, jnp.zeros((256, 256)), jnp.zeros((512, 512)), donate_argnums=()
    )
    # w IS returned updated (matching aval) -> flagged; batch is not
    assert all("argument 1" not in x.message for x in d)
