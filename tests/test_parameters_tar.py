"""Reference-parity tests for the Parameters tar surface:

- ``Parameters.from_tar(f)`` on the CLASS is a static constructor returning
  a topology-free bag with SHAPED float32 values (reference
  python/paddle/v2/parameters.py:286 — shapes come from the
  ``<name>.protobuf`` ParameterConfig members the tar carries).
- ``init_from_tar(self, f)`` merges a tar into existing parameters
  (reference :314), ignoring unknown names.
- SGD and Inference accept the detached bag anywhere a Parameters goes.
"""
import io

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.parameters import (
    DetachedParameters,
    _encode_param_conf,
    _parse_param_conf,
)


def _small_net():
    paddle.init(seed=3)
    L = paddle.layer
    x = L.data("x", paddle.data_type.dense_vector(8))
    h = L.fc(x, size=6, act=paddle.activation.Tanh(), name="h")
    y = L.fc(h, size=3, act=paddle.activation.Softmax(), name="y")
    lab = L.data("lab", paddle.data_type.integer_value(3))
    return L.classification_cost(input=y, label=lab), y


def test_proto_conf_roundtrip():
    buf = _encode_param_conf("h.w0", (8, 6))
    name, dims = _parse_param_conf(buf)
    assert name == "h.w0"
    assert dims == [8, 6]


def test_static_from_tar_restores_shapes():
    cost, _ = _small_net()
    params = paddle.parameters.create(cost)
    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    bag = paddle.parameters.Parameters.from_tar(buf)
    assert isinstance(bag, DetachedParameters)
    assert set(bag.names()) == set(params.names())
    for name in params.names():
        got = bag.get(name)
        want = params.get(name)
        assert got.shape == want.shape, name
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_init_from_tar_merges_known_names_only():
    cost, _ = _small_net()
    params = paddle.parameters.create(cost)
    buf = io.BytesIO()
    params.to_tar(buf)

    cost2, _ = _small_net()
    other = paddle.parameters.create(cost2, seed=9)
    buf.seek(0)
    other.init_from_tar(buf)
    for name in params.names():
        np.testing.assert_allclose(
            other.get(name), params.get(name), rtol=1e-6
        )
    # instance .from_tar stays an alias of init_from_tar
    fresh = paddle.parameters.create(cost2, seed=11)
    buf.seek(0)
    fresh.from_tar(buf)
    np.testing.assert_allclose(
        fresh.get(params.names()[0]), params.get(params.names()[0]), rtol=1e-6
    )


def test_trainer_and_inference_accept_detached_bag():
    cost, y = _small_net()
    params = paddle.parameters.create(cost)
    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    bag = paddle.parameters.Parameters.from_tar(buf)

    inf = paddle.inference.Inference(output_layer=y, parameters=bag)
    out = inf.infer(input=[(np.arange(8, dtype=np.float32) / 8.0,)])
    assert out.shape == (1, 3)
    np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-3)

    trainer = paddle.trainer.SGD(
        cost=cost,
        parameters=bag,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.01),
    )
    for name in params.names():
        np.testing.assert_allclose(
            trainer.parameters.get(name), params.get(name), rtol=1e-6
        )


def test_reference_tar_without_protobuf_members_still_loads():
    # pre-round-5 tars (data members only) keep loading, flat
    import struct
    import tarfile

    arr = np.arange(12, dtype=np.float32)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        payload = struct.pack("<iIQ", 0, 4, arr.size) + arr.tobytes()
        info = tarfile.TarInfo(name="w")
        info.size = len(payload)
        tar.addfile(info, io.BytesIO(payload))
    buf.seek(0)
    bag = paddle.parameters.Parameters.from_tar(buf)
    np.testing.assert_allclose(bag.get("w"), arr)


def test_detached_bag_tar_roundtrip_keeps_shapes():
    cost, _ = _small_net()
    params = paddle.parameters.create(cost)
    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    bag = paddle.parameters.Parameters.from_tar(buf)
    buf2 = io.BytesIO()
    bag.to_tar(buf2)
    buf2.seek(0)
    bag2 = paddle.parameters.Parameters.from_tar(buf2)
    for name in params.names():
        assert bag2.get(name).shape == params.get(name).shape, name


def test_partial_merge_warns():
    cost, y = _small_net()
    params = paddle.parameters.create(cost)
    # a tar holding only ONE of the parameters
    full = io.BytesIO()
    params.to_tar(full)
    full.seek(0)
    bag = paddle.parameters.Parameters.from_tar(full)
    one = DetachedParameters({params.names()[0]: params.get(params.names()[0])})
    with pytest.warns(UserWarning, match="keep their random"):
        one.merge_into(paddle.parameters.create(cost, seed=5))
    # corrupt protobuf member fails with a named error, not IndexError
    from paddle_tpu.parameters import _parse_param_conf

    with pytest.raises(ValueError, match="corrupt ParameterConfig"):
        _parse_param_conf(b"\x0a\xff", "h.w0.protobuf")
