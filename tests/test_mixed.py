"""Mixed layer + projection family (reference: MixedLayer.cpp and the
projection tests inside paddle/gserver/tests/test_LayerGrad.cpp testProjection
cases)."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import non_seq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names

from layer_grad_util import check_layer_grad

L = paddle.layer
A = paddle.activation


@pytest.fixture(autouse=True)
def _reset_names():
    reset_auto_names()
    yield


def dense(dim=8, name="in0"):
    return L.data(name, paddle.data_type.dense_vector(dim))


def ids(vocab=12, name="ids0"):
    return L.data(name, paddle.data_type.integer_value(vocab))


def test_mixed_full_matrix_grad():
    check_layer_grad(
        L.mixed(size=6, input=L.full_matrix_projection(dense()), act=A.Tanh(),
                bias_attr=True)
    )


def test_mixed_trans_full_matrix_grad():
    check_layer_grad(
        L.mixed(size=6, input=L.trans_full_matrix_projection(dense()))
    )


def test_mixed_multiple_projections_grad():
    a, b = dense(8, "a"), dense(6, "b")
    check_layer_grad(
        L.mixed(
            size=6,
            input=[
                L.full_matrix_projection(a),
                L.identity_projection(b),
                L.dotmul_projection(b),
                L.scaling_projection(b),
            ],
            act=A.Sigmoid(),
        )
    )


def test_mixed_table_projection_grad():
    check_layer_grad(L.mixed(size=5, input=L.table_projection(ids())))


def test_mixed_identity_offset():
    x = dense(8)
    out = L.mixed(size=3, input=L.identity_projection(x, offset=2, size=3))
    topo = Topology([out])
    net = CompiledNetwork(topo)
    import jax

    params, state = net.init(jax.random.PRNGKey(0))
    data = jnp.asarray(np.arange(32, dtype=np.float32).reshape(4, 8))
    outs, _ = net.apply(
        params, {"in0": non_seq(data)}, state=state
    )
    np.testing.assert_allclose(outs[out.name].data, data[:, 2:5])


def test_mixed_slice_projection():
    x = dense(8)
    out = L.mixed(size=4, input=L.slice_projection(x, [(0, 2), (6, 8)]))
    topo = Topology([out])
    net = CompiledNetwork(topo)
    import jax

    params, state = net.init(jax.random.PRNGKey(0))
    data = jnp.asarray(np.arange(16, dtype=np.float32).reshape(2, 8))
    outs, _ = net.apply(
        params, {"in0": non_seq(data)}, state=state
    )
    expect = np.concatenate([data[:, 0:2], data[:, 6:8]], axis=1)
    np.testing.assert_allclose(outs[out.name].data, expect)


def test_mixed_matches_fc():
    """A single full_matrix projection + bias must equal an fc layer with the
    same weights (the reference asserts this equivalence in
    test_NetworkCompare-style configs)."""
    import jax

    x = dense(8)
    out = L.mixed(size=6, input=L.full_matrix_projection(x), bias_attr=True)
    topo = Topology([out])
    net = CompiledNetwork(topo)
    params, state = net.init(jax.random.PRNGKey(3))

    data = jnp.asarray(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    outs, _ = net.apply(params, {"in0": non_seq(data)}, state=state)
    w = params[out.name]["p0_w"]
    b = params[out.name]["b"]
    np.testing.assert_allclose(
        np.asarray(outs[out.name].data), np.asarray(data @ w + b), rtol=1e-5
    )


def test_conv_operator():
    img = L.data("img", paddle.data_type.dense_vector(3 * 8 * 8), height=8, width=8)
    filt = L.fc(dense(4, "z"), size=2 * 3 * 3 * 3, act=A.Identity())
    out = L.conv_operator(img, filt, filter_size=3, num_filters=2, num_channels=3)
    check_layer_grad(out, batch_size=2)


def test_mixed_seq_input_grad():
    seq = L.data("s", paddle.data_type.dense_vector_sequence(5))
    check_layer_grad(L.mixed(size=4, input=L.full_matrix_projection(seq)))


def test_table_projection_id_sequence_keeps_time_axis():
    """A [B, T] integer id sequence through mixed/table_projection must
    produce per-timestep embeddings [B, T, D] — the sparse-id bag-sum path
    (big-vocab padded rows [B, T, nnz]) must NOT trigger on plain id
    sequences whose T happens to differ from the vocab."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import layers as L
    from paddle_tpu.core.batch import seq
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names

    reset_auto_names()
    ids = L.data("ids", paddle.data_type.integer_value_sequence(100))
    out = L.mixed(
        size=8, input=[L.table_projection(ids)], bias_attr=False
    )
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {"ids": seq(np.array([[1, 2, 3, 0, 0], [4, 5, 0, 0, 0]], np.int32), [3, 2])}
    o, _ = net.apply(params, batch, state=state, train=False)
    assert o[out.name].data.shape == (2, 5, 8), o[out.name].data.shape
    # row 0, t=0 must equal the table row of id 1
    w = next(v for v in jax.tree_util.tree_leaves(params) if v.shape == (100, 8))
    np.testing.assert_allclose(
        np.asarray(o[out.name].data)[0, 0], np.asarray(w)[1], rtol=1e-5
    )
