"""Expert parallelism (MoE layer, layers/moe.py) and pipeline parallelism
(parallel/pipeline.py) — numerics vs dense/sequential references, and
sharded execution on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor, seq as mkseq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu import layers as L


def _dense_moe_reference(x, p):
    """top-1 MoE with no capacity drops: y_n = gate_n * FFN_{e(n)}(x_n)."""
    gates = jax.nn.softmax(x @ np.asarray(p["router"]), axis=-1)
    idx = np.argmax(gates, axis=-1)
    top = np.max(gates, axis=-1)
    out = np.zeros((x.shape[0], p["w2"].shape[-1]), np.float32)
    for n in range(x.shape[0]):
        e = int(idx[n])
        h = np.maximum(x[n] @ np.asarray(p["w1"][e]) + np.asarray(p["b1"][e]), 0)
        out[n] = top[n] * (h @ np.asarray(p["w2"][e]) + np.asarray(p["b2"][e]))
    return out


def test_moe_matches_dense_reference_when_capacity_ample():
    reset_auto_names()
    d, e, hid = 4, 3, 5
    x_in = paddle.layer.data("x", paddle.data_type.dense_vector(d))
    m = L.moe_layer(x_in, expert_hidden=hid, num_experts=e,
                    capacity_factor=float(e) * 2)  # nothing can drop
    net = CompiledNetwork(Topology([m]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    x = rng.randn(12, d).astype(np.float32)
    outs, _ = net.apply(params, {"x": SeqTensor(x)}, state=state, train=False)
    got = np.asarray(outs[m.name].data)
    want = _dense_moe_reference(x, params[m.name])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    aux = np.asarray(outs[m.name + "@aux_loss"].data)
    # every row carries the scalar Switch aux (>= 1 by Cauchy-Schwarz when
    # every token routes); sum_cost reduces per row and the trainer takes the
    # batch mean, so this form is batch-size invariant as-is
    assert aux.shape == (12, 1) and np.isfinite(aux).all()
    assert aux.min() >= 1.0 - 1e-5
    np.testing.assert_allclose(aux, aux[0, 0], rtol=1e-6)


def test_moe_capacity_drops_tokens_and_masks_padding():
    reset_auto_names()
    d, e = 4, 2
    x_in = paddle.layer.data(
        "x", paddle.data_type.dense_vector_sequence(d)
    )
    m = L.moe_layer(x_in, expert_hidden=3, num_experts=e, capacity_factor=0.26)
    net = CompiledNetwork(Topology([m]))
    params, state = net.init(jax.random.PRNGKey(1))
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, d).astype(np.float32)
    lens = np.asarray([4, 2], np.int32)
    outs, _ = net.apply(
        params, {"x": mkseq(x, lens)}, state=state, train=False
    )
    got = np.asarray(outs[m.name].data)
    # capacity 0.26 * 8 / 2 -> 1 slot per expert: at most 2 tokens survive
    nonzero_tokens = np.sum(np.any(got != 0, axis=-1))
    assert nonzero_tokens <= e
    # padded positions are exactly zero
    np.testing.assert_array_equal(got[1, 2:], 0.0)


@pytest.mark.parametrize("model_par", [2, 4])
def test_moe_expert_parallel_matches_unsharded(model_par):
    """The expert-sharded MoE (shard_axis='model', experts split over the
    model axis, XLA all-to-all dispatch) computes the same function."""
    from paddle_tpu.parallel.mesh import make_mesh, set_default_mesh
    from paddle_tpu.parallel.sharding import shard_params

    if len(jax.devices()) < model_par:
        pytest.skip("needs the virtual multi-device mesh")
    reset_auto_names()
    d, e, hid = 4, 4, 6
    x_in = paddle.layer.data("x", paddle.data_type.dense_vector(d))
    m = L.moe_layer(
        x_in, expert_hidden=hid, num_experts=e, capacity_factor=8.0,
        layer_attr=paddle.attr.ExtraAttr(shard_axis="model"),
    )
    net = CompiledNetwork(Topology([m]))
    params, state = net.init(jax.random.PRNGKey(2))
    rng = np.random.RandomState(2)
    x = rng.randn(16, d).astype(np.float32)
    ref, _ = net.apply(params, {"x": SeqTensor(x)}, state=state, train=False)
    ref = np.asarray(ref[m.name].data)

    mesh = make_mesh(data=len(jax.devices()) // model_par, model=model_par)
    net2 = CompiledNetwork(Topology([m]))
    net2.mesh = mesh
    sharded = shard_params(net2, params, mesh)
    set_default_mesh(mesh)
    try:
        outs, _ = net2.apply(
            sharded, {"x": SeqTensor(x)}, state=state, train=False
        )
    finally:
        set_default_mesh(None)
    np.testing.assert_allclose(
        np.asarray(outs[m.name].data), ref, rtol=1e-4, atol=1e-5
    )


def test_moe_trains_on_mesh():
    """dp x ep training step: cost decreases with sharded experts."""
    from paddle_tpu.parallel.mesh import make_mesh, shard_batch

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    reset_auto_names()
    d, nclass = 8, 4
    x_in = paddle.layer.data("x", paddle.data_type.dense_vector(d))
    m = L.moe_layer(
        x_in, expert_hidden=16, num_experts=2, capacity_factor=4.0,
        layer_attr=paddle.attr.ExtraAttr(shard_axis="model"),
    )
    pred = L.fc(m, size=nclass, act=paddle.activation.Softmax())
    lbl = paddle.layer.data("y", paddle.data_type.integer_value(nclass))
    cost = L.classification_cost(input=pred, label=lbl)

    mesh = make_mesh(data=len(jax.devices()) // 2, model=2)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params, mesh=mesh,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-2),
    )
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(64):
            y = rng.randint(nclass)
            v = rng.randn(d).astype(np.float32) * 0.1
            v[y] += 2.0
            yield v, y

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 16), num_passes=6,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
    )
    assert np.isfinite(costs).all()
    assert np.mean(costs[-4:]) < 0.5 * np.mean(costs[:4])


# ---------------------------------------------------------------------------
# pipeline parallelism
# ---------------------------------------------------------------------------


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _make_stages(s, d, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "w": jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.5),
            "b": jnp.asarray(rng.randn(d).astype(np.float32) * 0.1),
        }
        for _ in range(s)
    ]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize("s,m", [(2, 4), (4, 8)])
def test_pipeline_matches_sequential(s, m):
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline import (
        pipeline_apply, split_microbatches, stack_stage_params,
    )

    if len(jax.devices()) < s:
        pytest.skip("needs the virtual multi-device mesh")
    d, b = 6, 16
    stages = _make_stages(s, d)
    rng = np.random.RandomState(3)
    x = rng.randn(b, d).astype(np.float32)

    mesh = make_mesh(data=len(jax.devices()) // s, model=s)
    mbs = split_microbatches(jnp.asarray(x), m)
    got = pipeline_apply(
        _stage_fn, stack_stage_params(stages), mbs, mesh
    ).reshape(b, d)
    want = np.asarray(_sequential(stages, jnp.asarray(x)))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline import (
        pipeline_apply, split_microbatches, stack_stage_params,
    )

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    s, d, b, m = 4, 4, 8, 4
    stages = _make_stages(s, d, seed=4)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(b, d).astype(np.float32))
    mesh = make_mesh(data=len(jax.devices()) // s, model=s)
    stacked = stack_stage_params(stages)

    def loss_pipe(sp):
        y = pipeline_apply(_stage_fn, sp, split_microbatches(x, m), mesh)
        return jnp.sum(jnp.square(y))

    def loss_seq(sp):
        z = x
        for i in range(s):
            z = _stage_fn(jax.tree_util.tree_map(lambda v: v[i], sp), z)
        return jnp.sum(jnp.square(z))

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def test_moe_init_std_uses_fan_in():
    """Expert-major [E, D, H] weights must init at 1/sqrt(fan_in), not
    1/sqrt(num_experts) (the shape[0] heuristic would be wrong)."""
    reset_auto_names()
    d, e, hid = 256, 4, 512
    x_in = paddle.layer.data("xx", paddle.data_type.dense_vector(d))
    m = L.moe_layer(x_in, expert_hidden=hid, num_experts=e)
    net = CompiledNetwork(Topology([m]))
    params, _ = net.init(jax.random.PRNGKey(0))
    p = params[m.name]
    assert abs(float(jnp.std(p["w1"])) - d ** -0.5) < 0.2 * d ** -0.5
    assert abs(float(jnp.std(p["w2"])) - hid ** -0.5) < 0.2 * hid ** -0.5


def test_sink_restored_after_malformed_raw_group():
    """The error-path unwind must restore the PRE-PARSE layer sink, not the
    dead parse's (ordering of reset_raw_state vs set_layer_sink)."""
    from paddle_tpu.core import topology as T
    from paddle_tpu.v1_compat import config_helpers as H, parse_config

    assert T._layer_sink is None

    def bad():
        H.Layer(name="in", type="data", size=4)
        H.RecurrentLayerGroupBegin("gg_layer_group", in_links=["in"],
                                   out_links=["gg"])
        H.Layer(name="gg", type="no_such_type", size=4)

    with pytest.raises(KeyError):
        parse_config(bad)
    assert T._layer_sink is None  # not the dead parse's capture lambda
