"""SSD detection suite: priorbox / multibox_loss / detection_output layers
and the detection_map evaluator (reference gserver/layers/PriorBox.cpp,
MultiBoxLossLayer.cpp, DetectionOutputLayer.cpp, DetectionUtil.cpp,
evaluators/DetectionMAPEvaluator.cpp:306)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layers
from paddle_tpu.core.batch import SeqTensor, seq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu.ops import detection as D


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------


def test_make_priors_geometry():
    # 2x2 feature map over a 100x100 image, one min_size, ratio-1 only
    pri = D.make_priors(2, 2, [20.0], [], [1.0], 100, 100)
    assert pri.shape == (4, 4)
    # first cell center (25, 25), box 20x20 normalized
    np.testing.assert_allclose(pri[0], [0.15, 0.15, 0.35, 0.35], atol=1e-6)
    # last cell center (75, 75)
    np.testing.assert_allclose(pri[3], [0.65, 0.65, 0.85, 0.85], atol=1e-6)


def test_make_priors_variants_count():
    pri = D.make_priors(3, 3, [20.0], [40.0], [1.0, 2.0], 90, 90)
    # per cell: min + sqrt(min*max) + (2, 1/2) = 4
    assert D.priors_per_cell(1, 1, [1.0, 2.0]) == 4
    assert pri.shape == (3 * 3 * 4, 4)
    # aspect-2 box: w = 20*sqrt(2), h = 20/sqrt(2) around center (15,15)
    w, h = 20 * np.sqrt(2), 20 / np.sqrt(2)
    np.testing.assert_allclose(
        pri[2],
        [(15 - w / 2) / 90, (15 - h / 2) / 90, (15 + w / 2) / 90, (15 + h / 2) / 90],
        atol=1e-6,
    )


def test_iou_matrix():
    a = jnp.asarray([[0.0, 0.0, 1.0, 1.0], [0.0, 0.0, 0.5, 0.5]])
    b = jnp.asarray([[0.0, 0.0, 1.0, 1.0], [0.5, 0.5, 1.0, 1.0]])
    got = np.asarray(D.iou_matrix(a, b))
    np.testing.assert_allclose(got, [[1.0, 0.25], [0.25, 0.0]], atol=1e-6)


def test_encode_decode_roundtrip():
    rng = np.random.RandomState(0)
    priors = jnp.asarray(rng.uniform(0.1, 0.6, size=(7, 2)).repeat(2, 1))
    priors = priors.at[:, 2:].add(0.3)
    gt = jnp.asarray([[0.2, 0.2, 0.7, 0.8]] * 7)
    var = (0.1, 0.1, 0.2, 0.2)
    enc = D.encode_boxes(gt, priors, var)
    dec = D.decode_boxes(enc, priors, var)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(gt), atol=1e-5)


def test_match_priors_bipartite():
    priors = jnp.asarray([
        [0.0, 0.0, 0.1, 0.1],   # far from gt, low IoU
        [0.2, 0.2, 0.6, 0.6],   # good match for gt0
        [0.65, 0.65, 0.95, 0.95],  # good match for gt1
    ])
    gt = jnp.asarray([[0.25, 0.25, 0.6, 0.6], [0.7, 0.7, 0.9, 0.9], [0.0, 0.0, 0.0, 0.0]])
    valid = jnp.asarray([True, True, False])
    matched, pos, _ = D.match_priors(priors, gt, valid, 0.5)
    assert bool(pos[1]) and int(matched[1]) == 0
    assert bool(pos[2]) and int(matched[2]) == 1
    assert not bool(pos[0])
    # bipartite: even with an impossible threshold every valid gt is claimed
    matched2, pos2, _ = D.match_priors(priors, gt, valid, 0.99)
    assert int(jnp.sum(pos2)) == 2


def test_nms_suppresses_overlaps():
    boxes = jnp.asarray([
        [0.0, 0.0, 0.5, 0.5],
        [0.02, 0.02, 0.52, 0.52],  # heavy overlap with #0
        [0.6, 0.6, 0.9, 0.9],
    ])
    scores = jnp.asarray([0.9, 0.8, 0.7])
    idx, kept = D.nms(boxes, scores, 0.5, 3)
    got = [(int(i), round(float(s), 3)) for i, s in zip(idx, kept) if s > 0]
    assert got == [(0, 0.9), (2, 0.7)]


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------

N_CLS = 3  # background + 2 object classes


def _ssd_net(img_hw=8, cell=4):
    """Tiny SSD: image -> conv feature map -> loc/conf heads + priorbox."""
    k = D.priors_per_cell(1, 0, [1.0])  # 1 prior per cell
    img = layers.data(
        "image",
        paddle.data_type.dense_vector(3 * img_hw * img_hw),
        height=img_hw,
        width=img_hw,
    )
    gt = layers.data("gt", paddle.data_type.dense_vector_sequence(6))
    feat = layers.img_conv(
        img, filter_size=3, num_filters=8, stride=img_hw // cell, padding=1,
        act=paddle.activation.Relu(), name="feat",
    )
    loc = layers.img_conv(
        feat, filter_size=3, num_filters=k * 4, padding=1,
        act=paddle.activation.Identity(), name="loc",
    )
    cnf = layers.img_conv(
        feat, filter_size=3, num_filters=k * N_CLS, padding=1,
        act=paddle.activation.Identity(), name="cnf",
    )
    pb = layers.priorbox(
        feat, img, aspect_ratio=[1.0], variance=[0.1, 0.1, 0.2, 0.2],
        min_size=[3.0], name="pb",
    )
    cost = layers.multibox_loss(
        input_loc=loc, input_conf=cnf, priorbox=pb, label=gt,
        num_classes=N_CLS, name="mbl",
    )
    det = layers.detection_output(
        input_loc=loc, input_conf=cnf, priorbox=pb, num_classes=N_CLS,
        keep_top_k=8, nms_top_k=8, confidence_threshold=0.3, name="det",
    )
    return img, gt, cost, det


def _gt_batch(boxes_per_img):
    """list of [ (label,x1,y1,x2,y2,difficult) ] per image -> SeqTensor."""
    b = len(boxes_per_img)
    g = max(len(x) for x in boxes_per_img)
    arr = np.zeros((b, g, 6), np.float32)
    lens = np.zeros((b,), np.int32)
    for i, rows in enumerate(boxes_per_img):
        lens[i] = len(rows)
        for j, r in enumerate(rows):
            arr[i, j] = r
    return seq(arr, lens)


def test_multibox_loss_runs_and_matches():
    reset_auto_names()
    img, gt, cost, det = _ssd_net()
    net = CompiledNetwork(Topology([cost, det]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {
        "image": SeqTensor(jnp.asarray(rng.rand(2, 3 * 8 * 8), jnp.float32)),
        "gt": _gt_batch([
            [(1, 0.1, 0.1, 0.4, 0.4, 0)],
            [(2, 0.5, 0.5, 0.9, 0.9, 0), (1, 0.0, 0.0, 0.3, 0.3, 0)],
        ]),
    }
    outs, _ = net.apply(params, batch, state=state, train=False)
    loss = np.asarray(outs["mbl"].data)
    assert loss.shape == (2, 1) and np.isfinite(loss).all() and (loss > 0).all()
    dets = np.asarray(outs["det"].data)
    assert dets.shape == (2, 8, 6)


def test_ssd_trains():
    """Loss decreases on a fixed single-box task."""
    reset_auto_names()
    img, gt, cost, det = _ssd_net()
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    image = jnp.asarray(rng.rand(4, 3 * 8 * 8), jnp.float32)
    batch = {
        "image": SeqTensor(image),
        "gt": _gt_batch([[(1, 0.05, 0.05, 0.45, 0.45, 0)]] * 4),
    }
    import optax  # baked-in; fine for a test-only loop

    opt = optax.adam(1e-2)

    def loss_fn(p):
        outs, _ = net.apply(p, batch, state=state, train=False)
        return jnp.mean(outs[cost.name].data)

    opt_state = opt.init(params)

    @jax.jit
    def step(p, os):
        l, g = jax.value_and_grad(loss_fn)(p)
        up, os = opt.update(g, os)
        return optax.apply_updates(p, up), os, l

    losses = []
    for _ in range(30):
        params, opt_state, l = step(params, opt_state)
        losses.append(float(l))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


def test_detection_output_decodes_known_boxes():
    """Bypass the network: feed loc preds that decode exactly onto a known
    box and conf preds that put class 1 on one prior."""
    reset_auto_names()
    k = 1
    h = w = 2
    priors = D.make_priors(h, w, [40.0], [], [1.0], 100, 100)
    var = (0.1, 0.1, 0.2, 0.2)
    target = np.array([0.1, 0.1, 0.45, 0.45], np.float32)
    enc = np.asarray(D.encode_boxes(jnp.asarray(target), jnp.asarray(priors[0]), var))
    loc = np.zeros((1, h, w, 4), np.float32)
    loc[0, 0, 0] = enc
    cnf = np.full((1, h, w, N_CLS), -5.0, np.float32)
    cnf[0, 0, 0, 1] = 5.0  # prior 0 -> class 1

    img = layers.data(
        "image", paddle.data_type.dense_vector(3 * 100 * 100), height=100, width=100
    )
    locd = layers.data("locd", paddle.data_type.dense_vector(h * w * 4))
    locd.conf.attrs.update(out_h=h, out_w=w, channels=4)
    cnfd = layers.data("cnfd", paddle.data_type.dense_vector(h * w * N_CLS))
    cnfd.conf.attrs.update(out_h=h, out_w=w, channels=N_CLS)
    feat = layers.data("feat", paddle.data_type.dense_vector(h * w))
    feat.conf.attrs.update(out_h=h, out_w=w, channels=1)
    pb = layers.priorbox(
        feat, img, aspect_ratio=[1.0], variance=list(var), min_size=[40.0]
    )
    det = layers.detection_output(
        input_loc=locd, input_conf=cnfd, priorbox=pb, num_classes=N_CLS,
        keep_top_k=4, nms_top_k=4, confidence_threshold=0.5, name="det",
    )
    net = CompiledNetwork(Topology([det]))
    params, state = net.init(jax.random.PRNGKey(0))
    outs, _ = net.apply(
        params,
        {
            "image": SeqTensor(jnp.zeros((1, 3 * 100 * 100))),
            "locd": SeqTensor(jnp.asarray(loc)),
            "cnfd": SeqTensor(jnp.asarray(cnf)),
            "feat": SeqTensor(jnp.zeros((1, h, w, 1))),
        },
        state=state,
        train=False,
    )
    d = np.asarray(outs["det"].data)[0]
    live = d[d[:, 0] >= 0]
    assert live.shape[0] == 1
    assert int(live[0, 0]) == 1  # class
    assert live[0, 1] > 0.9  # confidence
    np.testing.assert_allclose(live[0, 2:6], target, atol=1e-3)


# ---------------------------------------------------------------------------
# detection_map evaluator
# ---------------------------------------------------------------------------


def _map_of(dets, gts, ap_type="11point"):
    """dets [B,K,6] (label,score,x1,y1,x2,y2); gts list-of-lists."""
    from paddle_tpu.evaluator import detection_map_evaluator

    reset_auto_names()
    det_l = layers.data("det", paddle.data_type.dense_vector(6))
    gt_l = layers.data("gtv", paddle.data_type.dense_vector_sequence(6))
    ev = detection_map_evaluator(
        det_l, gt_l, num_classes=N_CLS, ap_type=ap_type, name="map"
    )
    acc = ev.update({
        "det": SeqTensor(jnp.asarray(dets, jnp.float32)),
        "gtv": _gt_batch(gts),
    })
    return ev.finalize({k: np.asarray(v) for k, v in acc.items()})["map"]


def test_detection_map_perfect():
    dets = np.zeros((1, 2, 6), np.float32)
    dets[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
    dets[0, 1] = [2, 0.8, 0.5, 0.5, 0.9, 0.9]
    gts = [[(1, 0.1, 0.1, 0.4, 0.4, 0), (2, 0.5, 0.5, 0.9, 0.9, 0)]]
    assert _map_of(dets, gts) == pytest.approx(1.0, abs=1e-3)


def test_detection_map_half():
    """Class 1: one TP at score .9 and one FP at .8 over one gt -> AP = 1.0
    (11point: precision at the single recall point is 1.0 before the FP).
    Class 2: pure miss -> AP 0.  mAP = 0.5."""
    dets = np.zeros((1, 2, 6), np.float32)
    dets[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]       # TP
    dets[0, 1] = [2, 0.8, 0.0, 0.0, 0.05, 0.05]     # FP (gt 2 elsewhere)
    gts = [[(1, 0.1, 0.1, 0.4, 0.4, 0), (2, 0.5, 0.5, 0.9, 0.9, 0)]]
    assert _map_of(dets, gts) == pytest.approx(0.5, abs=1e-3)


def test_detection_map_duplicate_detection_is_fp():
    """Two detections on the same gt: second is FP (gt used once)."""
    dets = np.zeros((1, 2, 6), np.float32)
    dets[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
    dets[0, 1] = [1, 0.8, 0.11, 0.11, 0.41, 0.41]
    gts = [[(1, 0.1, 0.1, 0.4, 0.4, 0)]]
    # integral AP: recall jumps to 1 at precision 1, then FP doesn't add area
    assert _map_of(dets, gts, ap_type="Integral") == pytest.approx(1.0, abs=1e-2)


def test_detection_map_difficult_ignored():
    dets = np.zeros((1, 1, 6), np.float32)
    dets[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]  # matches a difficult gt
    gts = [[(1, 0.1, 0.1, 0.4, 0.4, 1), (1, 0.6, 0.6, 0.9, 0.9, 0)]]
    # difficult gt not counted; its detection neither TP nor FP; the one
    # counted gt is missed -> AP 0
    assert _map_of(dets, gts) == pytest.approx(0.0, abs=1e-3)


def test_match_priors_two_gts_share_best_prior():
    """Two valid gts whose best prior coincides must still BOTH match
    (exclusive bipartite — reference matchBBox claims distinct priors)."""
    priors = jnp.asarray([
        [0.0, 0.0, 0.4, 0.4],
        [0.1, 0.1, 0.5, 0.5],
        [0.6, 0.6, 0.9, 0.9],
    ])
    # both gts overlap prior 1 best, prior 0 second-best
    gt = jnp.asarray([[0.1, 0.1, 0.5, 0.5], [0.12, 0.12, 0.48, 0.48]])
    valid = jnp.asarray([True, True])
    matched, pos, _ = D.match_priors(priors, gt, valid, 0.99)
    claimed = {int(matched[i]) for i in range(3) if bool(pos[i])}
    assert claimed == {0, 1}  # each gt holds its own prior
