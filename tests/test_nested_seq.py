"""Nested sub-sequence engine tests.

Reference behavior being matched: two-level CSR sequences
(paddle/parameter/Argument.h:84-93 subSequenceStartPositions), sequence
layers operating at either nesting level (SequencePoolLayer.cpp with
trans_type, SequenceLastInstanceLayer.cpp, ExpandLayer.cpp), and
recurrent_group over subsequences (RecurrentGradientMachine.cpp:428-528
hasSubseq / SubsequenceInput).  TPU-native form: doubly padded [B, S, T, ...]
blocks + n_sub[B] + sub_lengths[B, S]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layers
from paddle_tpu.core.batch import SeqTensor, nested_seq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.data_types import (
    dense_vector_sub_sequence,
    integer_value,
    integer_value_sub_sequence,
)
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu.layers import (
    AggregateLevel,
    ExpandLevel,
    StaticInput,
    SubsequenceInput,
)
from paddle_tpu.reader.feeder import DataFeeder

from tests.layer_grad_util import check_layer_grad


# ---------------------------------------------------------------------------
# feeder
# ---------------------------------------------------------------------------


def test_feeder_integer_sub_sequence():
    feeder = DataFeeder(
        [("words", integer_value_sub_sequence(100)), ("label", integer_value(3))],
        seq_multiple=4,
        min_seq_len=4,
    )
    batch = feeder(
        [
            ([[1, 2, 3], [4, 5]], 0),
            ([[6]], 2),
        ]
    )
    w = batch["words"]
    assert w.is_nested
    assert w.data.shape == (2, 4, 4)  # S bucketed to 4, T bucketed to 4
    np.testing.assert_array_equal(np.asarray(w.lengths), [2, 1])
    np.testing.assert_array_equal(
        np.asarray(w.sub_lengths), [[3, 2, 0, 0], [1, 0, 0, 0]]
    )
    np.testing.assert_array_equal(np.asarray(w.data[0, 0, :3]), [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(w.data[0, 1, :2]), [4, 5])
    np.testing.assert_array_equal(np.asarray(w.data[1, 0, :1]), [6])
    assert not batch["label"].is_seq


def test_feeder_dense_sub_sequence():
    feeder = DataFeeder(
        [("x", dense_vector_sub_sequence(2))], seq_multiple=2, min_seq_len=2
    )
    batch = feeder([([[[1.0, 2.0], [3.0, 4.0]], [[5.0, 6.0]]],)])
    x = batch["x"]
    assert x.data.shape == (1, 4, 2, 2)
    np.testing.assert_allclose(np.asarray(x.data[0, 0]), [[1, 2], [3, 4]])
    np.testing.assert_allclose(np.asarray(x.data[0, 1, 0]), [5, 6])
    np.testing.assert_array_equal(np.asarray(x.sub_lengths[0, :2]), [2, 1])


# ---------------------------------------------------------------------------
# level-aware sequence layers
# ---------------------------------------------------------------------------


def _nested_batch():
    # B=2, S=3, T=4, D=2; sample 0 has 2 subseqs (len 3, 2), sample 1 has 1 (len 4)
    rng = np.random.RandomState(7)
    data = rng.randn(2, 3, 4, 2).astype(np.float32)
    n_sub = np.array([2, 1], np.int32)
    sub_len = np.array([[3, 2, 0], [4, 0, 0]], np.int32)
    return nested_seq(data, n_sub, sub_len), data, n_sub, sub_len


def _run_layer(out_layer, batch):
    net = CompiledNetwork(Topology([out_layer]))
    params, state = net.init(jax.random.PRNGKey(0))
    outs, _ = net.apply(params, batch, state=state, train=False)
    return outs[out_layer.name]


def test_seqpool_nested_to_sequence():
    reset_auto_names()
    x, data, n_sub, sub_len = _nested_batch()
    inp = layers.data("x", dense_vector_sub_sequence(2))
    out = layers.pooling(
        inp, pooling_type="sum", agg_level=AggregateLevel.TO_SEQUENCE
    )
    o = _run_layer(out, {"x": x})
    assert o.is_seq and not o.is_nested
    assert o.data.shape == (2, 3, 2)
    np.testing.assert_allclose(
        np.asarray(o.data[0, 0]), data[0, 0, :3].sum(0), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(o.data[0, 1]), data[0, 1, :2].sum(0), rtol=1e-5
    )
    np.testing.assert_allclose(np.asarray(o.data[1, 1]), [0, 0], atol=1e-6)


def test_seqpool_nested_to_no_sequence():
    reset_auto_names()
    x, data, n_sub, sub_len = _nested_batch()
    inp = layers.data("x", dense_vector_sub_sequence(2))
    out = layers.pooling(
        inp, pooling_type="average", agg_level=AggregateLevel.TO_NO_SEQUENCE
    )
    o = _run_layer(out, {"x": x})
    assert not o.is_seq
    want0 = np.concatenate([data[0, 0, :3], data[0, 1, :2]]).mean(0)
    want1 = data[1, 0, :4].mean(0)
    np.testing.assert_allclose(np.asarray(o.data[0]), want0, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(o.data[1]), want1, rtol=1e-5)


def test_last_first_seq_nested():
    reset_auto_names()
    x, data, n_sub, sub_len = _nested_batch()
    inp = layers.data("x", dense_vector_sub_sequence(2))
    per_sub = layers.last_seq(inp, agg_level=AggregateLevel.TO_SEQUENCE)
    o = _run_layer(per_sub, {"x": x})
    assert o.is_seq and o.data.shape == (2, 3, 2)
    np.testing.assert_allclose(np.asarray(o.data[0, 0]), data[0, 0, 2], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(o.data[0, 1]), data[0, 1, 1], rtol=1e-5)

    reset_auto_names()
    inp = layers.data("x", dense_vector_sub_sequence(2))
    whole = layers.last_seq(inp)  # last timestep of the whole nested sample
    o2 = _run_layer(whole, {"x": x})
    assert not o2.is_seq
    np.testing.assert_allclose(np.asarray(o2.data[0]), data[0, 1, 1], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(o2.data[1]), data[1, 0, 3], rtol=1e-5)

    reset_auto_names()
    inp = layers.data("x", dense_vector_sub_sequence(2))
    first = layers.first_seq(inp)
    o3 = _run_layer(first, {"x": x})
    np.testing.assert_allclose(np.asarray(o3.data[0]), data[0, 0, 0], rtol=1e-5)


def test_expand_to_nested():
    reset_auto_names()
    x, data, n_sub, sub_len = _nested_batch()
    vec = SeqTensor(jnp.asarray(np.arange(4, dtype=np.float32).reshape(2, 2)))
    pat = layers.data("pat", dense_vector_sub_sequence(2))
    v = layers.data("v", paddle.data_type.dense_vector(2))
    out = layers.expand(v, pat, expand_level=ExpandLevel.FROM_NO_SEQUENCE)
    o = _run_layer(out, {"pat": x, "v": vec})
    assert o.is_nested and o.data.shape == (2, 3, 4, 2)
    np.testing.assert_allclose(np.asarray(o.data[0, 1, 1]), [0, 1], rtol=1e-5)

    # FROM_SEQUENCE: a per-subsequence vector repeated across its timesteps
    reset_auto_names()
    pat = layers.data("pat", dense_vector_sub_sequence(2))
    sv = layers.data("sv", paddle.data_type.dense_vector_sequence(2))
    out2 = layers.expand(sv, pat, expand_level=ExpandLevel.FROM_SEQUENCE)
    seq_vec = SeqTensor(
        jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 3, 2)),
        jnp.asarray(n_sub),
    )
    o2 = _run_layer(out2, {"pat": x, "sv": seq_vec})
    assert o2.is_nested
    np.testing.assert_allclose(np.asarray(o2.data[0, 1, 3]), [2, 3], rtol=1e-5)


# ---------------------------------------------------------------------------
# gradients through nested layers
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("agg", [AggregateLevel.TO_SEQUENCE, AggregateLevel.TO_NO_SEQUENCE])
def test_nested_seqpool_grad(agg):
    reset_auto_names()
    inp = layers.data("x", dense_vector_sub_sequence(3))
    out = layers.pooling(inp, pooling_type="sum", agg_level=agg)
    check_layer_grad(out)


def test_nested_group_grad():
    reset_auto_names()
    inp = layers.data("x", dense_vector_sub_sequence(3))

    def step(sub):  # sub: [B, T, 3] plain sequence inside the group
        h = layers.fc(sub, size=4, act=paddle.activation.Tanh())
        return layers.last_seq(h)

    out = layers.recurrent_group(step=step, input=SubsequenceInput(inp))
    check_layer_grad(out, atol=8e-2, rtol=8e-2)


# ---------------------------------------------------------------------------
# hierarchical RNN end-to-end (sequence_nest_rnn-style)
# ---------------------------------------------------------------------------


def _hier_model(vocab=30, emb=8, hidden=8, n_cls=3):
    words = layers.data("words", integer_value_sub_sequence(vocab))
    label = layers.data("label", integer_value(n_cls))
    embd = layers.embedding(words, size=emb)

    def outer_step(sent):  # sent: [B, T, emb] — one subsequence per scan step
        # inner recurrence over the words of this sentence
        h = layers.recurrent(
            layers.fc(sent, size=hidden, act=paddle.activation.Linear()),
            act=paddle.activation.Tanh(),
        )
        sent_vec = layers.last_seq(h)
        prev = layers.memory(name="sent_acc", size=hidden)
        acc = layers.fc(
            layers.concat([sent_vec, prev]),
            size=hidden,
            act=paddle.activation.Tanh(),
            name="sent_acc",
        )
        return acc

    doc = layers.recurrent_group(step=outer_step, input=SubsequenceInput(embd))
    doc_vec = layers.last_seq(doc)
    pred = layers.fc(doc_vec, size=n_cls, act=paddle.activation.Softmax())
    cost = layers.classification_cost(input=pred, label=label)
    return cost, pred


def _hier_reader(n=40, vocab=30, n_cls=3, seed=3):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            n_sent = rng.randint(1, 4)
            label = rng.randint(n_cls)
            sents = [
                list(rng.randint(label * 10, label * 10 + 9, size=rng.randint(2, 6)))
                for _ in range(n_sent)
            ]
            yield sents, label

    return reader


def test_hierarchical_rnn_trains():
    reset_auto_names()
    paddle.init(use_gpu=False, trainer_count=1)
    cost, pred = _hier_model()
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost,
        parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-2),
    )
    losses = []
    trainer.train(
        reader=paddle.batch(_hier_reader(), batch_size=8),
        num_passes=4,
        event_handler=lambda e: losses.append(e.cost)
        if isinstance(e, paddle.event.EndIteration)
        else None,
    )
    assert np.mean(losses[-3:]) < np.mean(losses[:3]) * 0.8, losses


def test_nested_group_output_is_sequence():
    """A step that returns a sequence produces a NESTED group output."""
    reset_auto_names()
    inp = layers.data("x", dense_vector_sub_sequence(3))

    def step(sub):
        return layers.fc(sub, size=4, act=paddle.activation.Tanh())

    out = layers.recurrent_group(step=step, input=SubsequenceInput(inp))
    x, data, n_sub, sub_len = _nested_batch()
    x = SeqTensor(
        jnp.asarray(np.random.RandomState(0).randn(2, 3, 4, 3), jnp.float32),
        x.lengths,
        x.sub_lengths,
    )
    o = _run_layer(out, {"x": x})
    assert o.is_nested
    assert o.data.shape == (2, 3, 4, 4)
    np.testing.assert_array_equal(np.asarray(o.lengths), [2, 1])
    np.testing.assert_array_equal(np.asarray(o.sub_lengths), np.asarray(x.sub_lengths))
    # padding subsequences are zeroed
    np.testing.assert_allclose(np.asarray(o.data[1, 1]), 0.0, atol=1e-6)


# ---------------------------------------------------------------------------
# sequence-valued memories (reference sequence-memory frames,
# RecurrentGradientMachine.cpp:530-608; memory(is_seq=True) in layers.py)
# ---------------------------------------------------------------------------


def _masked_nested(seed=11, B=2, S=3, T=4, D=2):
    """Nested batch whose padding positions are ZERO (as the feeder
    produces), so running-sum goldens are exact."""
    rng = np.random.RandomState(seed)
    data = rng.randn(B, S, T, D).astype(np.float32)
    n_sub = np.array([3, 2], np.int32)[:B]
    sub_len = np.array([[3, 2, 4], [4, 1, 0]], np.int32)[:B, :S]
    for b in range(B):
        for s in range(S):
            lim = sub_len[b, s] if s < n_sub[b] else 0
            data[b, s, lim:] = 0.0
    return nested_seq(data, n_sub, sub_len), data, n_sub, sub_len


def test_sequence_memory_running_sum():
    """memory(is_seq=True): each outer step sees the previous step's WHOLE
    output sequence.  Step = addto(subsequence, prev) -> running elementwise
    sum of subsequences, verifiable in numpy exactly."""
    reset_auto_names()
    x, data, n_sub, sub_len = _masked_nested()
    inp = layers.data("x", dense_vector_sub_sequence(2))

    def step(sub):
        prev = layers.memory(name="acc", size=2, is_seq=True)
        return layers.addto([sub, prev], name="acc")

    out = layers.recurrent_group(step=step, input=SubsequenceInput(inp))
    o = _run_layer(out, {"x": x})
    assert o.is_nested and o.data.shape == (2, 3, 4, 2)

    B, S = data.shape[:2]
    want = np.zeros_like(data)
    for b in range(B):
        carry = np.zeros(data.shape[2:], np.float32)
        for s in range(S):
            if s < n_sub[b]:
                carry = carry + data[b, s]
                # the emitted step output is a sequence of the addto layer's
                # declared length (= the subsequence's); padding is masked
                w = carry.copy()
                w[sub_len[b, s]:] = 0.0
                want[b, s] = w
    np.testing.assert_allclose(np.asarray(o.data), want, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(o.lengths), n_sub)


def test_sequence_memory_boot_from_sequence_layer():
    """Booted seq memory: the t=0 carry is an OUTER sequence layer's value
    (reference memory boot frames)."""
    reset_auto_names()
    x, data, n_sub, sub_len = _masked_nested(seed=12)
    rng = np.random.RandomState(13)
    boot_np = rng.randn(2, 4, 2).astype(np.float32)
    boot_len = np.array([4, 2], np.int32)
    boot_np[1, 2:] = 0.0
    from paddle_tpu.core.data_types import dense_vector_sequence

    inp = layers.data("x", dense_vector_sub_sequence(2))
    boot = layers.data("boot", dense_vector_sequence(2))

    def step(sub):
        prev = layers.memory(name="acc2", size=2, is_seq=True, boot_layer=boot)
        return layers.addto([sub, prev], name="acc2")

    out = layers.recurrent_group(step=step, input=SubsequenceInput(inp))
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(0))
    outs, _ = net.apply(
        params,
        {"x": x, "boot": SeqTensor(jnp.asarray(boot_np), jnp.asarray(boot_len))},
        state=state,
        train=False,
    )
    o = outs[out.name]
    B, S = data.shape[:2]
    want = np.zeros_like(data)
    for b in range(B):
        carry = boot_np[b].copy()
        for s in range(S):
            if s < n_sub[b]:
                carry = carry + data[b, s]
                w = carry.copy()
                w[sub_len[b, s]:] = 0.0
                want[b, s] = w
    np.testing.assert_allclose(np.asarray(o.data), want, rtol=1e-5, atol=1e-5)


def test_sequence_memory_non_seq_link_raises():
    """A seq memory whose link resolves to a NON-sequence layer must raise
    (silent mis-training is the round-2/3 bug this replaces)."""
    reset_auto_names()
    x, *_ = _masked_nested(seed=14)
    inp = layers.data("x", dense_vector_sub_sequence(2))

    def step(sub):
        prev = layers.memory(name="pooled", size=2, is_seq=True)
        pooled = layers.pooling(
            sub, pooling_type="sum", name="pooled"
        )  # NOT a sequence
        return pooled

    out = layers.recurrent_group(step=step, input=SubsequenceInput(inp))
    net = CompiledNetwork(Topology([out]))
    with pytest.raises(ValueError, match="not a sequence"):
        params, state = net.init(jax.random.PRNGKey(0))
        net.apply(params, {"x": x}, state=state, train=False)


def test_sequence_memory_grad():
    """Gradients flow through the sequence carry."""
    reset_auto_names()
    inp = layers.data("x", dense_vector_sub_sequence(3))

    def step(sub):
        prev = layers.memory(name="accg", size=4, is_seq=True)
        h = layers.fc(sub, size=4, act=paddle.activation.Tanh())
        return layers.addto([h, prev], name="accg")

    grp = layers.recurrent_group(step=step, input=SubsequenceInput(inp))
    out = layers.last_seq(layers.pooling(
        grp, pooling_type="sum", agg_level=AggregateLevel.TO_SEQUENCE
    ))
    check_layer_grad(out, atol=8e-2, rtol=8e-2)
