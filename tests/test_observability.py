"""Observability plane: flags (gflags equivalent), plot, image utils,
profiler wiring, nan trap."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import flags


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    flags.reset_flags()


def test_flags_layers_of_override(monkeypatch):
    assert flags.get_flag("log_period") == 100  # default
    monkeypatch.setenv("PADDLE_TPU_LOG_PERIOD", "7")
    assert flags.get_flag("log_period") == 7  # env override
    flags.set_flag("log_period", 3)
    assert flags.get_flag("log_period") == 3  # explicit wins
    with pytest.raises(KeyError):
        flags.get_flag("no_such_flag")
    with pytest.raises(KeyError):
        flags.set_flag("no_such_flag", 1)


def test_flags_bool_coercion(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CHECK_NANS", "true")
    assert flags.get_flag("check_nans") is True
    monkeypatch.setenv("PADDLE_TPU_CHECK_NANS", "0")
    assert flags.get_flag("check_nans") is False


def test_init_sets_flags_and_ignores_gpu_era_names():
    paddle.init(trainer_count=4, log_period=9, use_gpu=False, gpu_id=2)
    assert flags.get_flag("trainer_count") == 4
    assert flags.get_flag("log_period") == 9


def test_ploter_records_and_renders(tmp_path):
    p = paddle.plot.Ploter("train", "test")
    for i in range(5):
        p.append("train", i, 1.0 / (i + 1))
    p.append("test", 4, 0.3)
    assert p.data("train").step == [0, 1, 2, 3, 4]
    out = tmp_path / "curve.png"
    p.plot(str(out))
    # rendered when matplotlib exists; silent otherwise — both acceptable
    if p._plt is not None:
        assert out.exists() and out.stat().st_size > 0
    p.reset()
    assert p.data("train").step == []


def test_image_transforms():
    from paddle_tpu import image as I

    rng = np.random.RandomState(0)
    im = (rng.rand(40, 60, 3) * 255).astype(np.uint8)
    r = I.resize_short(im, 20)
    assert r.shape == (20, 30, 3)  # short edge 20, aspect kept
    c = I.center_crop(r, 16)
    assert c.shape == (16, 16, 3)
    rc = I.random_crop(r, 16, rng=np.random.RandomState(1))
    assert rc.shape == (16, 16, 3)
    f = I.left_right_flip(c)
    np.testing.assert_array_equal(f[:, 0], c[:, -1])
    chw = I.to_chw(c)
    assert chw.shape == (3, 16, 16)
    t = I.simple_transform(im, 24, 16, is_train=False, mean=np.zeros(3))
    assert t.shape == (3, 16, 16) and t.dtype == np.float32
    t2 = I.simple_transform(
        im, 24, 16, is_train=True, rng=np.random.RandomState(2)
    )
    assert t2.shape == (3, 16, 16)


def test_image_resize_values():
    from paddle_tpu import image as I

    # constant image stays constant under bilinear resize
    im = np.full((10, 10, 3), 7, np.uint8)
    assert (I.resize_short(im, 5) == 7).all()


def test_profiler_trace_writes(tmp_path):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.utils import profiler

    with profiler.profile(str(tmp_path)):
        jax.jit(lambda x: x * 2)(jnp.ones((8, 8))).block_until_ready()
    files = [
        os.path.join(r, f) for r, _, fs in os.walk(tmp_path) for f in fs
    ]
    assert files, "profiler trace produced no files"


def test_nan_trap():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.utils import profiler

    profiler.enable_nan_checks(True)
    try:
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.zeros(3) - 1.0).block_until_ready()
    finally:
        profiler.enable_nan_checks(False)


def test_make_diagram_and_merge_model(tmp_path):
    import jax
    from paddle_tpu import layers
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.utils.model_tools import (
        load_merged_model, make_diagram, merge_model,
    )

    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(4))
    y = layers.data("y", paddle.data_type.integer_value(3))
    pred = layers.fc(x, size=3, act=paddle.activation.Softmax(), name="pred")
    cost = layers.classification_cost(input=pred, label=y, name="cost")
    params = paddle.parameters.create(cost)

    dot = make_diagram(params.network.topology, str(tmp_path / "m.dot"))
    assert '"x" [shape=box' in dot and '"x" -> "pred";' in dot
    assert (tmp_path / "m.dot").exists()

    bundle = str(tmp_path / "model.tgz")
    merge_model(params, bundle)
    # a freshly-initialized copy loads the bundled weights
    reset_auto_names()
    x2 = layers.data("x", paddle.data_type.dense_vector(4))
    y2 = layers.data("y", paddle.data_type.integer_value(3))
    pred2 = layers.fc(x2, size=3, act=paddle.activation.Softmax(), name="pred")
    cost2 = layers.classification_cost(input=pred2, label=y2, name="cost")
    params2 = paddle.parameters.create(cost2, seed=99)
    manifest = load_merged_model(bundle, params2)
    assert manifest["outputs"] == ["cost"]
    np.testing.assert_allclose(params2.get("pred.w0"), params.get("pred.w0"))


def test_merge_model_rejects_mismatched_topology(tmp_path):
    from paddle_tpu import layers
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.utils.model_tools import load_merged_model, merge_model

    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(4))
    p1 = paddle.parameters.create(layers.fc(x, size=3, name="a"))
    bundle = str(tmp_path / "m.tgz")
    merge_model(p1, bundle)
    reset_auto_names()
    x2 = layers.data("x", paddle.data_type.dense_vector(4))
    p2 = paddle.parameters.create(layers.fc(x2, size=5, name="a"))
    with pytest.raises(ValueError):
        load_merged_model(bundle, p2)


def test_dump_config():
    import os as _os

    if not _os.path.isdir("/root/reference/v1_api_demo"):
        pytest.skip("reference not mounted")
    from paddle_tpu.utils.model_tools import dump_config

    text = dump_config("/root/reference/v1_api_demo/mnist/light_mnist.py")
    assert "conv" in text and "outputs=" in text


def test_seq_text_printer_and_gradient_stats(tmp_path, capsys):
    import jax
    from paddle_tpu import layers
    from paddle_tpu.core.batch import seq
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.evaluator import (
        gradient_printer_evaluator, seq_text_printer_evaluator,
    )
    from paddle_tpu.utils.debug import gradient_stats

    reset_auto_names()
    ids_l = layers.data("ids", paddle.data_type.integer_value_sequence(10))
    out_file = str(tmp_path / "gen.txt")
    ev = seq_text_printer_evaluator(
        ids_l, id_to_word=[f"w{i}" for i in range(10)], result_file=out_file
    )
    batch = {"ids": seq(np.asarray([[1, 2, 3, 0]], np.int32), [3])}
    ev.update(batch)
    jax.effects_barrier()
    assert open(out_file).read().strip() == "w1 w2 w3"

    # gradient stats over a tiny net
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(4))
    y = layers.data("y", paddle.data_type.integer_value(3))
    pred = layers.fc(x, size=3, act=paddle.activation.Softmax(), name="p")
    cost = layers.classification_cost(input=pred, label=y)
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    from paddle_tpu.core.batch import SeqTensor
    g = gradient_stats(net, params, {
        "x": SeqTensor(np.random.rand(2, 4).astype(np.float32)),
        "y": SeqTensor(np.asarray([0, 2], np.int32)),
    }, state=state)
    assert "p.w0" in g and g["p.w0"] > 0
    # gradient_printer still runs (prints forward norm)
    gp = gradient_printer_evaluator(pred)
    outs, _ = net.apply(params, {
        "x": SeqTensor(np.random.rand(2, 4).astype(np.float32)),
        "y": SeqTensor(np.asarray([0, 2], np.int32)),
    }, state=state)
    gp.update(outs)
