"""Minimum end-to-end slice (SURVEY.md §7 stage 2): LeNet on MNIST via the
v2 API — build topology, train passes, evaluator improves, checkpoint
round-trips.  Mirrors the reference's test_TrainerOnePass.cpp (train one pass
on mnist and check cost) but through the paddle.v2-compatible surface."""

import io

import numpy as np
import pytest

import paddle_tpu as paddle


def lenet(img):
    conv1 = paddle.layer.img_conv(
        img, filter_size=5, num_filters=8, num_channels=1, padding=2,
        act=paddle.activation.Relu(),
    )
    pool1 = paddle.layer.img_pool(conv1, pool_size=2, stride=2)
    conv2 = paddle.layer.img_conv(
        pool1, filter_size=5, num_filters=16, padding=2,
        act=paddle.activation.Relu(),
    )
    pool2 = paddle.layer.img_pool(conv2, pool_size=2, stride=2)
    fc1 = paddle.layer.fc(pool2, size=64, act=paddle.activation.Relu())
    return paddle.layer.fc(fc1, size=10, act=paddle.activation.Softmax())


@pytest.fixture(scope="module")
def trained():
    paddle.init(seed=0)
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    label = paddle.layer.data("label", paddle.data_type.integer_value(10))
    predict = lenet(img)
    cost = paddle.layer.classification_cost(input=predict, label=label)

    params = paddle.parameters.create(cost, seed=0)
    opt = paddle.optimizer.Momentum(
        learning_rate=0.05, momentum=0.9,
        regularization=paddle.optimizer.L2Regularization(1e-4),
    )
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params, update_equation=opt, extra_layers=[predict]
    )

    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    train_reader = paddle.batch(paddle.dataset.mnist.train(), batch_size=128)
    trainer.train(train_reader, num_passes=2, event_handler=handler)
    return trainer, costs


def test_cost_decreases(trained):
    trainer, costs = trained
    assert len(costs) >= 32
    head = np.mean(costs[:4])
    tail = np.mean(costs[-4:])
    assert tail < head * 0.5, f"cost did not improve: {head} -> {tail}"


def test_classification_error_drops(trained):
    trainer, _ = trained
    result = trainer.test(paddle.batch(paddle.dataset.mnist.test(), 128))
    assert result.metrics["classification_error"] < 0.2


def test_checkpoint_roundtrip(trained):
    trainer, _ = trained
    buf = io.BytesIO()
    trainer.save_parameter_to_tar(buf)
    buf.seek(0)

    name = trainer.parameters.names()[0]
    before = trainer.parameters.get(name).copy()
    trainer.parameters.set(name, np.zeros_like(before))
    assert not np.allclose(trainer.parameters.get(name), before)

    trainer.parameters.from_tar(buf)
    after = trainer.parameters.get(name)
    np.testing.assert_allclose(after, before, rtol=1e-6)


def test_topology_serialize_stable(trained):
    trainer, _ = trained
    text = trainer.topology.serialize()
    assert "conv" in text and "cross_entropy" in text
