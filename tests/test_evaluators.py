"""Evaluator framework tests (reference: the evaluator checks embedded in
paddle/gserver/tests and trainer integration in test_TrainerOnePass.cpp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor, non_seq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu.evaluator import (
    _chunk_bounds,
    _ctc_best_path,
    _edit_distance,
    auc_evaluator,
    chunk_evaluator,
    classification_error_evaluator,
    combined_update,
    finalize_all,
    pnpair_evaluator,
    precision_recall_evaluator,
    sum_evaluator,
)

L = paddle.layer


@pytest.fixture(autouse=True)
def _reset_names():
    reset_auto_names()
    yield


def _run_ev(evs, outs):
    acc = combined_update(evs)(outs)
    return finalize_all(evs, {k: np.asarray(v) for k, v in acc.items()})


def test_classification_error():
    x = L.data("x", paddle.data_type.dense_vector(3))
    y = L.data("y", paddle.data_type.integer_value(3))
    ev = classification_error_evaluator(x, y, name="err")
    outs = {
        "x": non_seq(jnp.asarray([[0.9, 0.1, 0.0], [0.1, 0.8, 0.1],
                                  [0.3, 0.3, 0.4], [1.0, 0.0, 0.0]])),
        "y": SeqTensor(jnp.asarray([0, 1, 0, 2], jnp.int32)),
    }
    res = _run_ev([ev], outs)
    assert res["err"] == 0.5


def test_sum_evaluator():
    x = L.data("x", paddle.data_type.dense_vector(2))
    ev = sum_evaluator(x, name="s")
    outs = {"x": non_seq(jnp.asarray([[1.0, 2.0], [3.0, 4.0]]))}
    assert _run_ev([ev], outs)["s"] == 10.0


def test_auc_perfect_separation():
    x = L.data("x", paddle.data_type.dense_vector(2))
    y = L.data("y", paddle.data_type.integer_value(2))
    ev = auc_evaluator(x, y, name="auc")
    # scores: positives all above negatives → AUC 1
    score = np.array([[0.1, 0.9], [0.2, 0.8], [0.9, 0.1], [0.7, 0.3]], np.float32)
    label = np.array([1, 1, 0, 0], np.int32)
    res = _run_ev([ev], {"x": non_seq(score), "y": SeqTensor(jnp.asarray(label))})
    np.testing.assert_allclose(res["auc"], 1.0, atol=1e-3)


def test_auc_random_is_half():
    x = L.data("x", paddle.data_type.dense_vector(2))
    y = L.data("y", paddle.data_type.integer_value(2))
    ev = auc_evaluator(x, y, name="auc")
    rng = np.random.RandomState(0)
    n = 4000
    p1 = rng.rand(n).astype(np.float32)
    score = np.stack([1 - p1, p1], axis=1)
    label = rng.randint(0, 2, n).astype(np.int32)
    res = _run_ev([ev], {"x": non_seq(score), "y": SeqTensor(jnp.asarray(label))})
    assert abs(res["auc"] - 0.5) < 0.05


def test_precision_recall():
    x = L.data("x", paddle.data_type.dense_vector(2))
    y = L.data("y", paddle.data_type.integer_value(2))
    ev = precision_recall_evaluator(x, y, positive_label=1, name="pr")
    score = np.array([[0.1, 0.9], [0.2, 0.8], [0.9, 0.1], [0.4, 0.6]], np.float32)
    label = np.array([1, 0, 0, 1], np.int32)
    res = _run_ev([ev], {"x": non_seq(score), "y": SeqTensor(jnp.asarray(label))})
    # predictions: 1,1,0,1 → tp=2 fp=1 fn=0
    np.testing.assert_allclose(res["pr.precision"], 2 / 3, rtol=1e-6)
    np.testing.assert_allclose(res["pr.recall"], 1.0, rtol=1e-6)


def test_pnpair():
    s = L.data("s", paddle.data_type.dense_vector(1))
    y = L.data("y", paddle.data_type.integer_value(3))
    q = L.data("q", paddle.data_type.integer_value(10))
    ev = pnpair_evaluator(s, y, q, name="pn")
    outs = {
        "s": non_seq(jnp.asarray([[0.9], [0.1], [0.5], [0.6]])),
        "y": SeqTensor(jnp.asarray([1, 0, 1, 0], jnp.int32)),
        "q": SeqTensor(jnp.asarray([0, 0, 1, 1], jnp.int32)),
    }
    # q0: pair (0>1): score 0.9>0.1 pos.  q1: pair (2>3): 0.5<0.6 neg.
    res = _run_ev([ev], outs)
    np.testing.assert_allclose(res["pn"], 1.0, rtol=1e-6)


def test_edit_distance():
    a = jnp.asarray([[1, 2, 3, 0], [1, 1, 0, 0]], jnp.int32)
    alen = jnp.asarray([3, 2], jnp.int32)
    b = jnp.asarray([[1, 3, 0], [2, 2, 2]], jnp.int32)
    blen = jnp.asarray([2, 3], jnp.int32)
    d = np.asarray(_edit_distance(a, alen, b, blen))
    # "123" vs "13" → 1 deletion; "11" vs "222" → 3 (2 sub + 1 ins)
    np.testing.assert_allclose(d, [1.0, 3.0])


def test_ctc_best_path_collapse():
    # argmax path: [1, 1, 0, 2, 2] (blank=0) → collapse → [1, 2]
    logits = np.full((1, 5, 3), -5.0, np.float32)
    for t, c in enumerate([1, 1, 0, 2, 2]):
        logits[0, t, c] = 5.0
    dec, dlen = _ctc_best_path(jnp.asarray(logits), jnp.asarray([5], jnp.int32), 0)
    assert int(dlen[0]) == 2
    np.testing.assert_array_equal(np.asarray(dec)[0, :2], [1, 2])


def test_chunk_bounds_iob():
    # types: B-PER I-PER O B-LOC → ids with 2 types (PER=0, LOC=1), tag_num=2
    # B-PER=0, I-PER=1, B-LOC=2, I-LOC=3, O=4
    ids = jnp.asarray([[0, 1, 4, 2]], jnp.int32)
    start, end, typ = _chunk_bounds(ids, jnp.asarray([4], jnp.int32), "IOB", 2)
    np.testing.assert_array_equal(np.asarray(start)[0], [True, False, False, True])
    np.testing.assert_array_equal(np.asarray(end)[0], [False, True, False, True])


def test_chunk_evaluator_f1():
    p = L.data("p", paddle.data_type.integer_value_sequence(5))
    g = L.data("g", paddle.data_type.integer_value_sequence(5))
    ev = chunk_evaluator(p, g, chunk_scheme="IOB", num_chunk_types=2, name="ch")
    gold = jnp.asarray([[0, 1, 4, 2]], jnp.int32)  # chunks: PER[0,1], LOC[3]
    pred = jnp.asarray([[0, 1, 4, 4]], jnp.int32)  # chunks: PER[0,1]
    lengths = jnp.asarray([4], jnp.int32)
    outs = {"p": SeqTensor(pred, lengths), "g": SeqTensor(gold, lengths)}
    res = _run_ev([ev], outs)
    np.testing.assert_allclose(res["ch.precision"], 1.0)
    np.testing.assert_allclose(res["ch.recall"], 0.5)


def test_trainer_with_evaluator():
    """End-to-end: evaluator flows through SGD.train events."""
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(4))
    y = L.data("y", paddle.data_type.integer_value(2))
    fc = L.fc(x, size=2, act=paddle.activation.Softmax())
    cost = L.classification_cost(fc, y)
    ev = classification_error_evaluator(fc, y, name="clserr")

    trainer = paddle.trainer.SGD(
        cost,
        update_equation=paddle.optimizer.SGD(learning_rate=0.1),
        evaluators=[ev],
    )
    rng = np.random.RandomState(0)
    data = [(rng.randn(4).astype(np.float32), int(i % 2)) for i in range(16)]

    seen = {}

    def handler(e):
        if isinstance(e, paddle.event.EndPass):
            seen.update(e.evaluator)

    trainer.train(paddle.batch(lambda: iter(data), 8), num_passes=1,
                  event_handler=handler)
    assert "clserr" in seen and 0.0 <= seen["clserr"] <= 1.0


def test_multi_binary_ce_multi_id_labels_multi_hot():
    """_label_as_dense with padded multi-id rows (the feeder's sparse_ids
    form): multi-hot with sentinel rows contributing nothing and duplicates
    clamped — never a silently mis-shaped [B, nnz, width] broadcast."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.layers.cost import _label_as_dense

    ids = jnp.asarray([[1, 3, 3, 5], [0, 5, 5, 5]], jnp.int32)  # 5 = sentinel
    t = np.asarray(_label_as_dense(SeqTensor(ids, sparse_ids=True), 5))
    assert t.shape == (2, 5)
    np.testing.assert_allclose(t[0], [0, 1, 0, 1, 0])
    np.testing.assert_allclose(t[1], [1, 0, 0, 0, 0])


# ---------------------------------------------------------------------------
# the two round-6 printers (reference Evaluator.cpp:1061 MaxFramePrinter,
# :1337 ClassificationErrorPrinter) — DSL surface + v1 raw-face wiring
# ---------------------------------------------------------------------------


def test_maxframe_printer_prints_max_frame(capfd):
    from paddle_tpu.evaluator import maxframe_printer_evaluator

    x = L.data("x", paddle.data_type.dense_vector_sequence(2))
    ev = maxframe_printer_evaluator(x, name="mf")
    data = jnp.asarray(
        [
            [[0.0, 1.0], [5.0, 0.0], [0.0, 9.0]],  # max at frame 2
            [[7.0, 0.0], [0.0, 1.0], [8.0, 8.0]],  # len 2 -> max at frame 0
        ]
    )
    outs = {"x": SeqTensor(data, jnp.asarray([3, 2], jnp.int32))}
    assert _run_ev([ev], outs) == {}
    jax.effects_barrier()
    out = capfd.readouterr().out
    assert "sample 0: frame 2 value 9" in out
    assert "sample 1: frame 0 value 7" in out


def test_maxframe_printer_non_seq(capfd):
    from paddle_tpu.evaluator import maxframe_printer_evaluator

    x = L.data("x", paddle.data_type.dense_vector(3))
    ev = maxframe_printer_evaluator(x, name="mf2")
    outs = {"x": non_seq(jnp.asarray([[1.0, 4.0, 2.0]]))}
    _run_ev([ev], outs)
    jax.effects_barrier()
    assert "sample 0: frame 1 value 4" in capfd.readouterr().out


def test_classification_error_printer_per_instance(capfd):
    from paddle_tpu.evaluator import classification_error_printer_evaluator

    x = L.data("x", paddle.data_type.dense_vector(3))
    y = L.data("y", paddle.data_type.integer_value(3))
    ev = classification_error_printer_evaluator(x, y, name="cep")
    outs = {
        "x": non_seq(jnp.asarray([[0.9, 0.1, 0.0], [0.1, 0.8, 0.1],
                                  [0.3, 0.3, 0.4]])),
        "y": SeqTensor(jnp.asarray([0, 0, 2], jnp.int32)),
    }
    assert _run_ev([ev], outs) == {}
    jax.effects_barrier()
    assert "cep: [0 1 0]" in capfd.readouterr().out


def test_classification_error_printer_masks_padding(capfd):
    from paddle_tpu.evaluator import classification_error_printer_evaluator

    x = L.data("x", paddle.data_type.dense_vector_sequence(2))
    y = L.data("y", paddle.data_type.integer_value_sequence(2))
    ev = classification_error_printer_evaluator(x, y, name="cepseq")
    pred = jnp.asarray([[[0.9, 0.1], [0.2, 0.8], [0.5, 0.5]]])
    lens = jnp.asarray([2], jnp.int32)
    outs = {
        "x": SeqTensor(pred, lens),
        "y": SeqTensor(jnp.asarray([[0, 0, 1]], jnp.int32), lens),
    }
    _run_ev([ev], outs)
    jax.effects_barrier()
    out = capfd.readouterr().out
    assert "cepseq: [0 1]" in out  # padding step 2 excluded


def test_printer_evaluators_via_raw_face():
    """The reference raw-config Evaluator() face wires both new printer
    types (plus the existing value/maxid printers)."""
    from paddle_tpu.v1_compat import raw_face

    cfg = """
Layer(name="x", type="data", size=3)
Layer(name="y", type="data", size=3)
Layer(name="fc", type="fc", size=3, active_type="softmax",
      inputs=[Input("x", parameter_name="w")], bias=Bias())
Evaluator(name="ev_mf", type="max_frame_printer", inputs=["fc"])
Evaluator(name="ev_cep", type="classification_error_printer",
          inputs=["fc", "y"])
Evaluator(name="ev_vp", type="value_printer", inputs=["fc"])
Outputs("fc")
"""
    import tempfile, os

    from paddle_tpu.v1_compat import parse_config

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "conf.py")
        with open(p, "w") as f:
            f.write(cfg)
        parsed = parse_config(p, "")
    names = sorted(ev.name for ev in parsed.evaluators)
    assert names == ["ev_cep", "ev_mf", "ev_vp"]
