"""Ring attention (sequence/context parallelism): exactness vs dense
attention on the virtual 8-device mesh, including key-padding, causal
masking, and gradients through the ring."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel.mesh import MODEL_AXIS, make_mesh
from paddle_tpu.parallel.ring_attention import (
    ring_attention,
    sequence_parallel_attention,
)


def _dense_attention(q, k, v, lengths=None, causal=False):
    b, t, h, dh = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(dh)
    if lengths is not None:
        mask = jnp.arange(t)[None, :] < lengths[:, None]
        s = jnp.where(mask[:, None, None, :], s, -1e9)
    if causal:
        cm = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(cm[None, None], s, -1e9)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _rand_qkv(b=2, t=32, h=2, dh=4, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, dh), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    mesh = make_mesh(data=1, model=8)
    q, k, v = _rand_qkv()
    got = sequence_parallel_attention(q, k, v, mesh, MODEL_AXIS, causal=causal)
    want = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_respects_key_padding():
    mesh = make_mesh(data=1, model=8)
    q, k, v = _rand_qkv(t=32)
    lengths = jnp.asarray([17, 32], jnp.int32)  # first sample padded
    got = sequence_parallel_attention(q, k, v, mesh, MODEL_AXIS, lengths=lengths)
    want = _dense_attention(q, k, v, lengths=lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    # growing the padded region must not change the output
    k2 = k.at[0, 17:].set(99.0)
    v2 = v.at[0, 17:].set(-99.0)
    got2 = sequence_parallel_attention(q, k2, v2, mesh, MODEL_AXIS, lengths=lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(got2), atol=2e-5)


def test_ring_gradients_match_dense():
    mesh = make_mesh(data=1, model=8)
    q, k, v = _rand_qkv(t=16)

    def loss_ring(q_, k_, v_):
        o = sequence_parallel_attention(q_, k_, v_, mesh, MODEL_AXIS, causal=True)
        return jnp.sum(o**2)

    def loss_dense(q_, k_, v_):
        return jnp.sum(_dense_attention(q_, k_, v_, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_ring_under_jit_keeps_sequence_sharded():
    mesh = make_mesh(data=1, model=8)
    q, k, v = _rand_qkv()

    @jax.jit
    def f(q_, k_, v_):
        return sequence_parallel_attention(q_, k_, v_, mesh, MODEL_AXIS)

    out = f(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense_attention(q, k, v)), atol=2e-5
    )


def test_ring_attention_uneven_ring_rejected():
    mesh = make_mesh(data=1, model=8)
    q, k, v = _rand_qkv(t=20)  # 20 % 8 != 0
    with pytest.raises(AssertionError):
        sequence_parallel_attention(q, k, v, mesh, MODEL_AXIS)


def test_transformer_with_sequence_parallel_matches_dense():
    """transformer_cost(seq_parallel_axis=...) computes the same loss as the
    dense model with identical parameters — the long-context path is a
    drop-in."""
    import paddle_tpu as paddle
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.core.batch import seq
    from paddle_tpu.models.transformer import transformer_cost
    from paddle_tpu.parallel.mesh import set_default_mesh

    V, T, B = 12, 16, 2
    mesh = make_mesh(data=1, model=8)

    def build(sp):
        reset_auto_names()
        cost, _ = transformer_cost(
            V, V, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            seq_parallel_axis=MODEL_AXIS if sp else None,
        )
        return CompiledNetwork(Topology([cost])), cost

    rng = np.random.RandomState(0)
    ids = lambda: rng.randint(1, V, size=(B, T)).astype(np.int32)
    lens = np.asarray([16, 11], np.int32)
    batch = {
        "src_word": seq(ids(), lens),
        "trg_word": seq(ids(), lens),
        "trg_next": seq(ids(), lens),
    }

    net_d, cost_d = build(False)
    params, state = net_d.init(jax.random.PRNGKey(0))
    dense, _ = net_d.apply(params, batch, state=state, train=False)

    net_s, cost_s = build(True)
    set_default_mesh(mesh)
    try:
        sp, _ = net_s.apply(params, batch, state=state, train=False)
    finally:
        set_default_mesh(None)
    np.testing.assert_allclose(
        np.asarray(sp[cost_s.name].data),
        np.asarray(dense[cost_d.name].data),
        rtol=2e-4, atol=2e-4,
    )
