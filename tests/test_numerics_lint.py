"""Precision-flow lint (analysis/numerics_lint.py): every N-rule fires on
a deliberate mutation and stays silent on the guarded idiom, pragmas
suppress with a justification, certify_precision_plan gates dtype plans
on the real train step, and the satellite guards (StatSet non-finite
bucket, bench non-finite regression) hold."""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.analysis.diagnostics import format_diagnostics
from paddle_tpu.analysis.numerics_lint import (
    certify_precision_plan,
    lint_numerics_config,
    lint_numerics_jaxpr,
    lint_numerics_step,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = os.path.join(REPO, "tests", "configs")


def rules(diags):
    return [d.rule for d in diags]


def lint_fn(fn, *args, **kw):
    return lint_numerics_jaxpr(
        jax.make_jaxpr(fn)(*args), apply_pragmas=False, **kw
    )


# ---------------------------------------------------------------------------
# N401 low-precision accumulation
# ---------------------------------------------------------------------------


def test_n401_bf16_dot_without_f32_accumulator_fires():
    x = jnp.ones((4, 128), jnp.bfloat16)
    w = jnp.ones((128, 8), jnp.bfloat16)
    d = lint_fn(lambda a, b: a @ b, x, w)
    assert "N401" in rules(d), format_diagnostics(d)


def test_n401_silent_with_preferred_f32():
    x = jnp.ones((4, 128), jnp.bfloat16)
    w = jnp.ones((128, 8), jnp.bfloat16)
    d = lint_fn(
        lambda a, b: jnp.matmul(a, b, preferred_element_type=jnp.float32),
        x, w,
    )
    assert "N401" not in rules(d), format_diagnostics(d)
    # ...and at f32 the plain matmul is clean by construction
    d32 = lint_fn(lambda a, b: a @ b, x.astype(jnp.float32),
                  w.astype(jnp.float32))
    assert "N401" not in rules(d32)


def test_n401_long_bf16_reduce_fires_short_and_f32_do_not():
    # jnp.sum's default promotion accumulates bf16 sums in f32, so the
    # firing mutation is a LOW-dtype running reduction (cumsum keeps the
    # operand dtype — the pattern the softmax backward emits)
    big = jnp.ones((4, 256), jnp.bfloat16)
    d = lint_fn(lambda a: jnp.cumsum(a, axis=-1), big)
    assert "N401" in rules(d), format_diagnostics(d)
    small = jnp.ones((4, 8), jnp.bfloat16)
    assert "N401" not in rules(lint_fn(lambda a: jnp.cumsum(a, axis=-1),
                                       small))
    # the default (f32-accumulating) sum is the clean idiom
    assert "N401" not in rules(lint_fn(lambda a: a.sum(axis=-1), big))


def test_n401_scan_carry_accumulator_fires_state_carry_does_not():
    xs = jnp.ones((64, 8), jnp.bfloat16)

    def accumulating(xs):
        def body(c, x):
            return c + x, x  # running sum: quantizes every step

        return jax.lax.scan(body, jnp.zeros((8,), jnp.bfloat16), xs)

    d = lint_fn(accumulating, xs)
    assert "N401" in rules(d), format_diagnostics(d)
    assert any("carry" in x.message for x in d if x.rule == "N401")

    def overwriting(xs):
        def body(c, x):
            return jnp.tanh(x) * 0.5 + 0.5 * jnp.tanh(c), c

        return jax.lax.scan(body, jnp.zeros((8,), jnp.bfloat16), xs)

    d2 = lint_fn(overwriting, xs)
    assert not any("carry" in x.message for x in d2 if x.rule == "N401"), (
        format_diagnostics(d2)
    )


# ---------------------------------------------------------------------------
# N402 master-precision escape (via the step-level entry point)
# ---------------------------------------------------------------------------


def _fake_step(update_in_bf16):
    def step(params, state, opt_state, batch, rng):
        g = batch["x"].sum(axis=0) * 1e-3
        if update_in_bf16:
            p16 = params["w"].astype(jnp.bfloat16) - g.astype(jnp.bfloat16)
            new_w = p16.astype(jnp.float32)  # upcast AFTER the math
        else:
            new_w = params["w"] - g
        return ({"w": new_w}, state, opt_state, {"cost": g.sum()})

    params = {"w": jnp.zeros((8,), jnp.float32)}
    batch = {"x": jnp.ones((4, 8), jnp.float32)}
    return step, (params, {}, {}, batch, jax.random.PRNGKey(0))


def test_n402_update_math_below_master_precision_fires():
    step, args = _fake_step(update_in_bf16=True)
    d = lint_numerics_step(step, *args, master_argnums=(0,),
                           apply_pragmas=False)
    assert "N402" in rules(d), format_diagnostics(d)


def test_n402_silent_on_f32_update_math():
    step, args = _fake_step(update_in_bf16=False)
    d = lint_numerics_step(step, *args, master_argnums=(0,),
                           apply_pragmas=False)
    assert "N402" not in rules(d), format_diagnostics(d)


def test_n402_master_leaf_left_at_bf16_fires():
    def step(params, state, opt_state, batch, rng):
        return (
            {"w": params["w"] - batch["x"].sum(axis=0)},
            state, opt_state, {"cost": batch["x"].sum()},
        )

    params = {"w": jnp.zeros((8,), jnp.bfloat16)}
    batch = {"x": jnp.ones((4, 8), jnp.bfloat16)}
    d = lint_numerics_step(step, params, {}, {}, batch,
                           jax.random.PRNGKey(0), master_argnums=(0,),
                           apply_pragmas=False)
    assert "N402" in rules(d), format_diagnostics(d)


# ---------------------------------------------------------------------------
# N403 unguarded domain hazards
# ---------------------------------------------------------------------------


def test_n403_unguarded_exp_fires_max_subtracted_does_not():
    x = jnp.ones((4, 16), jnp.float32)
    d = lint_fn(lambda a: jnp.exp(a), x)
    assert "N403" in rules(d)

    def softmaxish(a):
        return jnp.exp(a - jax.lax.stop_gradient(a.max(-1, keepdims=True)))

    assert "N403" not in rules(lint_fn(softmaxish, x))


def test_n403_att_softmax_is_the_positive_pattern():
    """ops/rnn.py:_att_softmax — masked fill + softmax — must lint clean:
    the max-subtraction inside jax.nn.softmax guards the exp and the
    guaranteed exp(0)=1 term guards the normalizing division."""
    from paddle_tpu.ops.rnn import _att_softmax

    score = jnp.ones((4, 16), jnp.float32)
    emask = jnp.ones((4, 16), bool)
    d = lint_fn(_att_softmax, score, emask)
    assert "N403" not in rules(d), format_diagnostics(d)


def test_n403_unguarded_log_and_div_fire_epsilon_idiom_does_not():
    x = jnp.ones((4, 16), jnp.float32)
    assert "N403" in rules(lint_fn(lambda a: jnp.log(a), x))
    assert "N403" not in rules(lint_fn(lambda a: jnp.log(a + 1e-6), x))
    y = jnp.ones((4, 16), jnp.float32)
    assert "N403" in rules(lint_fn(lambda a, b: a / b, x, y))
    assert "N403" not in rules(
        lint_fn(lambda a, b: a / jnp.maximum(b, 1e-6), x, y)
    )
    assert "N403" in rules(lint_fn(lambda a: jax.lax.rsqrt(a), x))
    assert "N403" not in rules(lint_fn(lambda a: jax.lax.rsqrt(a + 1e-8), x))


# ---------------------------------------------------------------------------
# N404 sentinel literal overflow
# ---------------------------------------------------------------------------


def test_n404_1e9_mask_under_f16_fires():
    score = jnp.ones((4, 16), jnp.float16)
    mask = jnp.ones((4, 16), bool)
    d = lint_fn(lambda s, m: jnp.where(m, s, -1e9), score, mask)
    assert "N404" in rules(d), format_diagnostics(d)


def test_n404_silent_under_bf16_and_with_dtype_aware_fill():
    score16 = jnp.ones((4, 16), jnp.bfloat16)
    mask = jnp.ones((4, 16), bool)
    # bf16 has f32 range: -1e9 is representable
    d = lint_fn(lambda s, m: jnp.where(m, s, -1e9), score16, mask)
    assert "N404" not in rules(d)

    def dtype_aware(s, m):
        fill = jnp.asarray(jnp.finfo(s.dtype).min, s.dtype)
        return jnp.where(m, s, fill)

    d2 = lint_fn(dtype_aware, jnp.ones((4, 16), jnp.float16), mask)
    assert "N404" not in rules(d2), format_diagnostics(d2)


# ---------------------------------------------------------------------------
# N405 sub-f32 psum without block-scale structure
# ---------------------------------------------------------------------------


def _lint_psum(fn, *args):
    closed = jax.make_jaxpr(fn, axis_env=[("dp", 2)])(*args)
    return lint_numerics_jaxpr(closed, apply_pragmas=False)


def test_n405_lone_bf16_psum_fires():
    g = jnp.ones((8,), jnp.bfloat16)
    d = _lint_psum(lambda x: jax.lax.psum(x, "dp"), g)
    assert "N405" in rules(d), format_diagnostics(d)


def test_n405_block_scaled_psum_passes():
    g = jnp.ones((8,), jnp.bfloat16)
    s = jnp.ones((1,), jnp.float32)

    def block_scaled(x, scale):
        blocks = jax.lax.psum(x, "dp")
        scales = jax.lax.psum(scale, "dp")  # scales ride at f32
        return blocks.astype(jnp.float32) * scales

    assert "N405" not in rules(_lint_psum(block_scaled, g, s))
    # and a plain f32 psum never fires
    assert "N405" not in rules(
        _lint_psum(lambda x: jax.lax.psum(x, "dp"), g.astype(jnp.float32))
    )


def test_n405_quantized_psum_helper_lints_zero():
    """ACCEPT-path mutation check: the shipped ops.quantize.quantized_psum
    emits the payload psum + f32 scale psum pair, and the WHOLE jaxpr
    lints to zero diagnostics — not merely 'no N405' (a guard regression
    in the helper would surface as N403 here)."""
    from paddle_tpu.ops.quantize import quantized_psum

    g = {"w": jnp.ones((300,), jnp.float32), "b": jnp.ones((7,), jnp.float32)}
    for payload in (jnp.int8, jnp.bfloat16):
        d = _lint_psum(
            lambda t: quantized_psum(t, "dp", payload_dtype=payload), g
        )
        assert d == [], (str(payload), format_diagnostics(d))
    # stochastic rounding keeps the same psum structure
    d = _lint_psum(
        lambda t, k: quantized_psum(t, "dp", stochastic=True, rng=k),
        g, jax.random.PRNGKey(0),
    )
    assert d == [], format_diagnostics(d)


def test_n405_mutated_quantized_psum_fires_and_hint_names_helpers():
    """Strip the scale psum off the block-scaled pair (quantize against a
    purely LOCAL scale, psum only the int8 payload) — the exact mutation
    N405 exists to catch — and the fix hint must point at the ops
    quantize helpers."""

    def local_scale_only(x):
        amax = jnp.max(jnp.abs(x))
        scale = jnp.where(amax == 0.0, jnp.float32(1.0), amax / 127.0)
        q = jnp.round(x / scale).astype(jnp.int8)
        summed = jax.lax.psum(q, "dp")  # no f32 psum beside it
        return summed.astype(jnp.float32) * scale

    d = _lint_psum(local_scale_only, jnp.ones((64,), jnp.float32))
    n405 = [x for x in d if x.rule == "N405"]
    assert n405, format_diagnostics(d)
    assert "ops.quantize.quantized_psum" in (n405[0].hint or "")
    assert "quantize_block_scaled" in (n405[0].hint or "")


def test_n405_sees_through_shard_map():
    """The walker descends into shard_map bodies (where the quantized
    allreduce actually lives): a naked int8 psum inside one fires, the
    correctly paired one stays silent."""
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.ops.quantize import quantized_psum
    from paddle_tpu.parallel.mesh import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    n = len(jax.devices())

    def naked(g):
        def body(t):
            q = t.astype(jnp.int8)
            return jax.lax.psum(q, "dp").astype(jnp.float32)

        return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                         out_specs=P("dp"), check_vma=False)(g)

    closed = jax.make_jaxpr(naked)(jnp.zeros((n, 32), jnp.float32))
    assert "N405" in rules(lint_numerics_jaxpr(closed, apply_pragmas=False))

    def paired(g):
        return shard_map(
            lambda t: quantized_psum(t, "dp", mean=True), mesh=mesh,
            in_specs=(P("dp"),), out_specs=P("dp"), check_vma=False,
        )(g)

    closed = jax.make_jaxpr(paired)(jnp.zeros((n, 300), jnp.float32))
    d = lint_numerics_jaxpr(closed, apply_pragmas=False)
    assert d == [], format_diagnostics(d)


# ---------------------------------------------------------------------------
# N406 dtype round-trip churn
# ---------------------------------------------------------------------------


def test_n406_f32_bf16_f32_roundtrip_fires():
    x = jnp.ones((4, 16), jnp.float32)
    d = lint_fn(
        lambda a: a.astype(jnp.bfloat16).astype(jnp.float32) * 2.0, x
    )
    assert "N406" in rules(d), format_diagnostics(d)


def test_n406_one_way_casts_do_not_fire():
    x = jnp.ones((4, 16), jnp.float32)
    assert "N406" not in rules(
        lint_fn(lambda a: a.astype(jnp.bfloat16) * jnp.bfloat16(2), x)
    )
    # widening round trip (bf16 -> f32 -> bf16 loses nothing on the way up)
    y = jnp.ones((4, 16), jnp.bfloat16)
    assert "N406" not in rules(
        lint_fn(lambda a: a.astype(jnp.float32).astype(jnp.bfloat16), y)
    )


# ---------------------------------------------------------------------------
# pragma plane
# ---------------------------------------------------------------------------


def _write_module(tmp_path, name, body):
    p = tmp_path / f"{name}.py"
    p.write_text(textwrap.dedent(body))
    import importlib.util

    spec = importlib.util.spec_from_file_location(name, p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_num_pragma_suppresses_with_justification(tmp_path):
    mod = _write_module(tmp_path, "praggood", """
        import jax.numpy as jnp

        def f(x):
            return jnp.exp(x)  # num: allow[N403] scores are clipped by the caller
    """)
    x = jnp.ones((4, 16), jnp.float32)
    d = lint_numerics_jaxpr(jax.make_jaxpr(mod.f)(x))
    assert "N403" not in rules(d), format_diagnostics(d)
    # without pragma filtering the same jaxpr fires — the pragma did it
    d_raw = lint_numerics_jaxpr(jax.make_jaxpr(mod.f)(x),
                                apply_pragmas=False)
    assert "N403" in rules(d_raw)


def test_num_pragma_without_justification_is_rejected(tmp_path):
    mod = _write_module(tmp_path, "pragbad", """
        import jax.numpy as jnp

        def f(x):
            return jnp.exp(x)  # num: allow[N403]
    """)
    from paddle_tpu.analysis.numerics_lint import _PragmaFilter

    x = jnp.ones((4, 16), jnp.float32)
    f = _PragmaFilter()
    d = lint_numerics_jaxpr(jax.make_jaxpr(mod.f)(x), _filter=f)
    # the finding is NOT suppressed and the malformed pragma reports N400
    assert "N403" in rules(d)
    assert "N400" in rules(f.pragma_diags)


# ---------------------------------------------------------------------------
# certify_precision_plan — the ROADMAP item 2 gate
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_certify_rejects_bf16_master_accepts_bf16_compute_f32_master():
    """The documented gate: updating params IN bf16 is statically rejected
    (N402); the master-f32/compute-bf16 split passes on the LSTM
    flagship."""
    from paddle_tpu.v1_compat import parse_config

    topo = parse_config(
        os.path.join(CONFIGS, "demo_text_lstm.py"), ""
    ).topology

    good = certify_precision_plan(topo, {"compute_dtype": "bfloat16"})
    assert good.ok, good.format()
    assert good.master_dtype == "float32"
    # the certificate names the layers and shows f32 accumulators
    text = good.format()
    assert "ACCEPT" in text and "__lstmemory_0__" in text

    bad = certify_precision_plan(
        topo, {"compute_dtype": "bfloat16", "master_dtype": "bfloat16"}
    )
    assert not bad.ok, bad.format()
    assert "N402" in {d.rule for d in bad.diagnostics}
    assert "REJECT" in bad.format()


def test_certify_int8_weight_only_accepts_int8_master_rejects():
    """The quantization-plane split: declaring weight-ONLY int8 (the
    serving decode bundle) leaves the train plane untouched and ACCEPTs;
    asking for int8 master params or optimizer state REJECTs outright,
    without even tracing."""
    import paddle_tpu as paddle
    from paddle_tpu.core.topology import Topology, reset_auto_names

    reset_auto_names()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
    h = paddle.layer.fc(x, size=16, act=paddle.activation.Relu())
    pred = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax())
    y = paddle.layer.data("y", paddle.data_type.integer_value(4))
    topo = Topology([paddle.layer.classification_cost(input=pred, label=y)])

    ok = certify_precision_plan(
        topo, {"compute_dtype": "bfloat16", "quantized_weights": True}
    )
    assert ok.ok, ok.format()

    for plan in (
        {"master_dtype": "int8"},
        {"compute_dtype": "int8"},
        {"compute_dtype": "bfloat16", "master_dtype": "int8",
         "quantized_weights": True},
    ):
        bad = certify_precision_plan(topo, plan)
        assert not bad.ok, (plan, bad.format())
        assert "N402" in {d.rule for d in bad.diagnostics}
        assert "weight-only" in bad.diagnostics[0].message
        assert "quantized_weights" in (bad.diagnostics[0].hint or "")


# ---------------------------------------------------------------------------
# the shipped corpus + package stay zero-diagnostic (make lint's contract)
# ---------------------------------------------------------------------------


def test_mnist_demo_config_zero_diagnostic_at_f32_and_bf16():
    cfg = os.path.join(CONFIGS, "demo_mnist_mlp.py")
    assert lint_numerics_config(cfg) == []
    d = lint_numerics_config(cfg, compute_dtype="bfloat16")
    assert d == [], format_diagnostics(d)


@pytest.mark.slow
def test_flagship_corpus_zero_diagnostic_both_dtypes():
    from paddle_tpu.analysis.numerics_lint import lint_numerics_package

    for cfg in sorted(os.listdir(CONFIGS)):
        if not cfg.endswith(".py"):
            continue
        for dt in (None, "bfloat16"):
            d = lint_numerics_config(
                os.path.join(CONFIGS, cfg), compute_dtype=dt
            )
            assert d == [], (cfg, dt, format_diagnostics(d))
    for dt in (None, "bfloat16"):
        d = lint_numerics_package(compute_dtype=dt)
        assert d == [], (dt, format_diagnostics(d))


# ---------------------------------------------------------------------------
# satellites: StatSet non-finite bucket + bench non-finite guard
# ---------------------------------------------------------------------------


def test_statset_observe_nonfinite_goes_to_own_bucket():
    from paddle_tpu.utils.timers import StatSet

    s = StatSet()
    s.observe("num/x", 2.0)
    s.observe("num/x", float("nan"))
    s.observe("num/x", float("inf"))
    s.observe("num/x", 4.0)
    row = s.summary()["num/x"]
    assert row["count"] == 2 and row["nonfinite"] == 2
    assert row["avg"] == 3.0 and row["max"] == 4.0  # unpoisoned
    assert np.isfinite(row["total"])


def test_bench_nonfinite_metric_is_hard_regression():
    import bench

    prior = {"m": [("r01", 10.0)]}
    f = bench.regression_fields("m", float("nan"), "tok/s", prior)
    assert f["regressed_vs_best"] is True and f["non_finite"] is True
    # a NaN with NO history still hard-fails (the silent-pass case)
    f2 = bench.regression_fields("fresh", float("inf"), "ms", {})
    assert f2["regressed_vs_best"] is True
    # finite values keep the old behavior
    f3 = bench.regression_fields("m", 10.0, "tok/s", prior)
    assert not f3.get("non_finite") and f3["regressed_vs_best"] is False


def test_bench_guard_line_reports_non_finite_separately():
    import bench

    results = [
        {"metric": "ok", "value": 1.0, "regressed_vs_best": False},
        {"metric": "bad", "value": float("nan"), "regressed_vs_best": True,
         "non_finite": True},
        {"metric": "slow", "value": 1.0, "regressed_vs_best": True,
         "best_prior": 2.0},
    ]
    guard = bench.build_guard(results)
    assert [g["metric"] for g in guard["non_finite"]] == ["bad"]
    assert [g["metric"] for g in guard["regressed"]] == ["slow"]
