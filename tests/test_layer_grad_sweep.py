"""Registry-sweep gradient checks — every registered layer type is either
finite-diff-checked here or named on the asserted skip list.

The reference's test_LayerGrad.cpp (~2.3k LoC) runs testLayerGrad over
essentially every layer type; the targeted files (test_layer_grad.py and
friends) mirror its depth, while THIS file mirrors its breadth discipline:
``test_every_registered_type_is_swept`` fails the moment someone registers a
new layer type without adding a builder (grad check) or a skip entry
(non-differentiable/structural types only, with the reason stated).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import LayerConf, LayerOutput, reset_auto_names
from paddle_tpu.layers.base import registered_layer_types

from layer_grad_util import check_layer_grad, rand_batch_for

L = paddle.layer
A = paddle.activation
dt = paddle.data_type


@pytest.fixture(autouse=True)
def _reset_names():
    reset_auto_names()
    yield


def dense(dim=8, name="in0"):
    return L.data(name, dt.dense_vector(dim))


def dense_seq(dim=8, name="seq0"):
    return L.data(name, dt.dense_vector_sequence(dim))


def ids(vocab=10, name="ids0"):
    return L.data(name, dt.integer_value(vocab))


def ids_seq(vocab=12, name="idseq0"):
    return L.data(name, dt.integer_value_sequence(vocab))


def img(c=2, s=6, name="img0"):
    return L.data(name, dt.dense_vector(c * s * s), height=s, width=s)


# ---------------------------------------------------------------------------
# types with no gradient to check: integer/decode outputs, constant outputs,
# and structural wiring that never computes anything of its own
# ---------------------------------------------------------------------------

SKIP = {
    "data": "input placeholder, no computation",
    "memory": "scan carry placeholder inside recurrent_group",
    "step_input": "scan slice placeholder inside recurrent_group",
    "agent": "subnet wiring alias, no computation",
    "gather_agent": "generation-time id gather, integer plumbing",
    "scatter_agent": "generation-time id scatter, integer plumbing",
    "print": "identity pass-through with a host-side print",
    "get_output": "aux-output selector, no computation of its own",
    "maxid": "emits integer argmax ids",
    "sampling_id": "emits sampled integer ids",
    "eos_id": "emits end-of-sequence flags (integers)",
    "beam_search": "decode-time search emitting token ids",
    "crf_decoding": "viterbi argmax decode emitting label ids",
    "detection_output": "NMS decode emitting selected boxes",
    "priorbox": "constant prior-box geometry from static shapes",
    "kmax_seq_score": "top-k index selection; output ids feed beam pruning",
}


# ---------------------------------------------------------------------------
# builders: one micro-net per differentiable type.  Value = a callable
# returning either a LayerOutput or (LayerOutput, check_kwargs).
# ---------------------------------------------------------------------------


def _slice_time_out():
    # internal wiring type (memory boot / attention) with no DSL face:
    # build its conf directly
    x = dense_seq(4)
    conf = LayerConf(
        name="st", type="slice_time", size=4, inputs=(x.name,),
        act="identity", bias=False, attrs={"offset": 1},
    )
    return LayerOutput(conf, [x])


def _recurrent_group_out():
    x = dense_seq(5)

    def step(x_t):
        mem = L.memory("h", 5)
        hm = L.fc(mem, 5, act=A.Identity(), bias_attr=False, name="hproj")
        return L.addto([x_t, hm], act=A.Tanh(), bias_attr=True, name="h")

    return L.recurrent_group(step, x, name="grp")


def _gru_step_out():
    x = dense_seq(12)

    def step(x_t):
        mem = L.memory("g", 4)
        return L.gru_step(input=x_t, output_mem=mem, size=4, name="g")

    return L.recurrent_group(step, x, name="ggrp")


def _lstm_step_out():
    x = dense_seq(16)

    def step(x_t):
        om = L.memory("o", 4)
        cm = L.memory("o@cell", 4)
        return L.lstm_step(
            input=x_t, output_mem=om, state_mem=cm, size=4, name="o"
        )

    return L.recurrent_group(step, x, name="lgrp")


def _soft_bce_out():
    x = dense(6)
    t = L.data("t", dt.dense_vector(6))
    pred = L.fc(x, size=6, act=A.Sigmoid())
    topo_probe = paddle.Topology(
        [L.soft_binary_class_cross_entropy_cost(pred, t)]
    )
    batch = rand_batch_for(topo_probe)
    batch["t"] = SeqTensor(jax.nn.sigmoid(batch["t"].data))
    reset_auto_names()
    out = L.soft_binary_class_cross_entropy_cost(
        L.fc(dense(6), size=6, act=A.Sigmoid()), L.data("t", dt.dense_vector(6))
    )
    return out, {"batch": batch}


def _multi_binary_out():
    # sigmoid predictions vs {0,1} multi-label targets
    x = dense(6)
    t = L.data("t", dt.dense_vector(5))
    pred = L.fc(x, size=5, act=A.Sigmoid())
    out = L.multi_binary_label_cross_entropy_cost(pred, t)
    topo = paddle.Topology([out])
    batch = rand_batch_for(topo)
    batch["t"] = SeqTensor((batch["t"].data > 0).astype(jnp.float32))
    return out, {"batch": batch}


def _multibox_out():
    from tests.test_detection import _gt_batch, _ssd_net

    img_l, gt, cost, _ = _ssd_net()
    rng = np.random.RandomState(0)
    b = _gt_batch([[(1, 0.1, 0.1, 0.5, 0.6, 0)], [(2, 0.3, 0.2, 0.9, 0.8, 0)]])
    batch = {
        "image": SeqTensor(
            jnp.asarray(rng.randn(2, 3 * 8 * 8), jnp.float32)
        ),
        "gt": b,
    }
    return cost, {"batch": batch, "check_inputs": False,
                  "atol": 8e-2, "rtol": 8e-2}


def _ctc_out():
    # valid CTC batch (labels avoid the blank, input len >= label length):
    # random labels from rand_batch_for can include the blank id, which has
    # no gradient-consistent alignment — reuse the structured-test helper
    from tests.test_structured import _ctc_batch

    B, T, C, Lmax = 3, 8, 5, 3
    logits, in_len, labels, lab_len = _ctc_batch(B, T, C, Lmax)
    probs = L.data("probs", dt.dense_vector_sequence(C))
    lab = ids_seq(vocab=C, name="lab")
    out = L.warp_ctc(probs, lab, size=C, blank=0)
    batch = {
        "probs": SeqTensor(jnp.asarray(logits), jnp.asarray(in_len)),
        "lab": SeqTensor(jnp.asarray(labels), jnp.asarray(lab_len)),
    }
    return out, {"batch": batch, "atol": 8e-2, "rtol": 8e-2}


def _softmax_with_cost_out():
    # the fused logits->CE kernel has no direct DSL face (classification_cost
    # emits cross_entropy and the compiler fuses through the @logits aux):
    # build its conf directly to exercise the registered impl
    logits = L.fc(dense(), size=5, act=A.Identity())
    lbl = ids(5, "lbl")
    conf = LayerConf(
        name="swc", type="softmax_with_cost", size=1,
        inputs=(logits.name, lbl.name), bias=False,
    )
    return LayerOutput(conf, [logits, lbl])


def _multi_nn_out():
    # the multi_nn ensemble joint cost (built by v1_compat's multi_nn
    # assembly): sum of the sub-networks' mean costs
    a = L.classification_cost(
        L.fc(dense(6, "xa"), size=3, act=A.Softmax()), ids(3, "la")
    )
    b = L.square_error_cost(
        L.fc(dense(4, "xb"), size=2, act=A.Identity()), dense(2, "lb")
    )
    conf = LayerConf(
        name="__multi_nn_cost__", type="multi_nn_cost", size=1,
        inputs=(a.name, b.name), bias=False,
    )
    return LayerOutput(conf, [a, b])


BUILDERS = {
    "fc": lambda: L.fc(dense(), size=6, act=A.Tanh()),
    "embedding": lambda: L.embedding(ids_seq(), size=6),
    "addto": lambda: L.addto(
        [dense(8, "a"), dense(8, "b")], act=A.Tanh(), bias_attr=True
    ),
    "concat": lambda: L.concat([dense(8, "a"), dense(4, "b")]),
    "scaling": lambda: L.scaling(dense(1, "w"), dense(8, "x")),
    "slope_intercept": lambda: L.slope_intercept(
        dense(), slope=2.0, intercept=0.5
    ),
    "interpolation": lambda: L.interpolation(
        dense(1, "w"), dense(8, "a"), dense(8, "b")
    ),
    "sum_to_one_norm": lambda: L.sum_to_one_norm(dense()),
    "row_l2_norm": lambda: L.row_l2_norm(dense()),
    "cos": lambda: L.cos_sim(dense(8, "a"), dense(8, "b"), scale=5.0),
    "cos_vm": lambda: L.cos_sim_vec_mat(dense(3, "v"), dense(12, "m"), size=4),
    "out_prod": lambda: L.out_prod(dense(4, "a"), dense(3, "b")),
    "tensor": lambda: L.tensor(dense(4, "a"), dense(3, "b"), size=5,
                               act=A.Tanh()),
    "trans": lambda: L.trans(dense(12), height=3),
    "resize": lambda: L.resize(dense(12), size=6),
    "rotate": lambda: L.rotate(dense(12, "r"), height=3, width=4),
    "multiplex": lambda: L.multiplex(
        [L.data("sel", dt.integer_value(2)), dense(6, "a"), dense(6, "b")]
    ),
    "clip": lambda: L.clip(dense(), min=-0.4, max=0.4),
    "power": lambda: L.power(dense(1, "w"), dense(8, "x")),
    "dotmul": lambda: L.dotmul_operator(dense(8, "a"), dense(8, "b")),
    "mixed": lambda: L.mixed(
        size=5, input=[
            L.full_matrix_projection(dense(8, "a")),
            L.full_matrix_projection(dense(4, "b")),
        ],
    ),
    "conv_op": lambda: L.conv_operator(
        img(2, 6, "x"),
        L.fc(dense(4, "z"), size=2 * 3 * 3 * 2, act=A.Identity()),
        filter_size=3, num_filters=2, num_channels=2,
    ),
    "context_projection": lambda: L.mixed(
        size=12, input=L.context_projection(
            dense_seq(4), context_len=3, context_start=-1
        ),
    ),
    "linear_comb": lambda: L.linear_comb(dense(3, "w"), dense(12, "x"),
                                         size=4),
    "conv_shift": lambda: L.conv_shift(dense(8, "a"), dense(3, "b")),
    "scale_shift": lambda: L.scale_shift(dense()),
    "prelu": lambda: L.prelu(dense()),
    "layer_norm": lambda: L.layer_norm(dense()),
    "pos_encoding": lambda: L.pos_encoding(dense_seq(6)),
    "data_norm": lambda: L.data_norm(dense()),
    "featmap_expand": lambda: L.featmap_expand(dense(6), num_filters=3),
    "repeat": lambda: L.repeat(dense(6), num_repeats=2),
    "expand": lambda: L.expand(dense(4, "v"), dense_seq(3, "s")),
    "conv": lambda: L.img_conv(img(), filter_size=3, num_filters=3,
                               padding=1, act=A.Relu()),
    "convt": lambda: L.img_conv(img(), filter_size=3, num_filters=3,
                                padding=1, act=A.Relu(), trans=True),
    "pool": lambda: L.img_pool(img(), pool_size=2, stride=2),
    "batch_norm": lambda: (
        L.batch_norm(L.fc(dense(), size=6, act=A.Identity()), act=A.Relu()),
        {"atol": 8e-2, "rtol": 8e-2},
    ),
    "maxout": lambda: L.maxout(img(4, 4), groups=2, num_channels=4),
    "pad": lambda: L.img_pad(img(2, 4), pad_c=[0, 0], pad_h=[1, 1],
                             pad_w=[1, 1]),
    "bilinear_interp": lambda: L.bilinear_interp(img(2, 4), out_size_x=8,
                                                 out_size_y=8),
    "spp": lambda: L.spp(img(2, 6), pyramid_height=2, num_channels=2),
    "norm": lambda: L.img_cmrnorm(img(3, 4), size=3),
    "crop": lambda: L.crop(img(2, 6), axis=2, shape=[4, 4]),
    "block_expand": lambda: L.block_expand(
        img(2, 6), num_channels=2, block_x=2, block_y=2, stride_x=2,
        stride_y=2,
    ),
    "row_conv": lambda: L.row_conv(dense_seq(4), context_len=3),
    "seqpool": lambda: L.pooling(dense_seq(), pooling_type=None),
    "seqlastins": lambda: L.last_seq(dense_seq()),
    "seqconcat": lambda: L.seq_concat(dense_seq(4, "a"), dense_seq(4, "b")),
    "seqreshape": lambda: L.seq_reshape(dense_seq(4), reshape_size=8),
    "sub_seq": lambda: (
        L.sub_seq(
            dense_seq(3, "s"),
            L.data("off", dt.integer_value(2)),
            L.data("sz", dt.integer_value(2)),
        ),
        {"check_inputs": False},
    ),
    "slice_time": _slice_time_out,
    "lstmemory": lambda: L.lstmemory(
        L.fc(dense_seq(4), size=16, act=A.Identity())
    ),
    "gru": lambda: L.grumemory(
        L.fc(dense_seq(4), size=12, act=A.Identity())
    ),
    "recurrent": lambda: L.recurrent(dense_seq(6), act=A.Tanh()),
    # input pre-projected to 5*size gate channels (i, f_row, f_col, o, g)
    "mdlstmemory": lambda: (
        L.mdlstmemory(img(15, 4), size=3),
        {"batch_size": 2, "atol": 8e-2, "rtol": 8e-2},
    ),
    "recurrent_group": _recurrent_group_out,
    "gru_step": _gru_step_out,
    "lstm_step": _lstm_step_out,
    # tiny eps keeps the finite difference inside one top-k routing cell —
    # at the default 1e-3 a perturbation can flip an expert assignment and
    # the fd estimate jumps across the (piecewise) routing boundary
    "moe": lambda: (
        L.moe_layer(dense_seq(6), expert_hidden=4, num_experts=2),
        {"atol": 8e-2, "rtol": 8e-2, "eps": 2e-4},
    ),
    "multi_head_attention": lambda: L.multi_head_attention(
        dense_seq(8), n_heads=2
    ),
    "selective_fc": lambda: (
        L.selective_fc(dense(8, "x"), ids(9, "sel"), size=9),
        {"check_inputs": False},
    ),
    "nce": lambda: (
        L.nce(dense(), ids(), num_neg_samples=4),
        {"check_inputs": False},
    ),
    "hsigmoid": lambda: (
        L.hsigmoid(dense(), ids(vocab=7)),
        {"check_inputs": False},
    ),
    "crf": lambda: (
        L.crf(
            L.fc(dense_seq(6), size=4, act=A.Identity()),
            ids_seq(vocab=4, name="lab"), size=4,
        ),
        {"check_inputs": False, "atol": 8e-2, "rtol": 8e-2},
    ),
    "ctc": _ctc_out,
    # -- costs ---------------------------------------------------------
    "square_error": lambda: L.square_error_cost(
        L.fc(dense(), size=3, act=A.Identity()), dense(3, "lbl")
    ),
    "smooth_l1": lambda: L.smooth_l1_cost(
        L.fc(dense(), size=3, act=A.Identity()), dense(3, "lbl")
    ),
    "huber_regression": lambda: L.huber_regression_cost(
        L.fc(dense(), size=3, act=A.Identity()), dense(3, "lbl")
    ),
    "huber_classification": lambda: L.huber_classification_cost(
        L.fc(dense(), size=1, act=A.Identity()), ids(2, "lbl")
    ),
    "rank_cost": lambda: L.rank_cost(
        L.fc(dense(4, "a"), size=1, act=A.Identity()),
        L.fc(dense(4, "b"), size=1, act=A.Identity()),
        ids(2, "lbl"),
    ),
    "lambda_cost": lambda: (
        L.lambda_cost(
            L.fc(dense_seq(4), size=1, act=A.Identity()),
            L.data("y", dt.dense_vector_sequence(1)),
        ),
        {"check_inputs": False, "atol": 8e-2, "rtol": 8e-2},
    ),
    "sum_cost": lambda: L.sum_cost(L.fc(dense(), size=4, act=A.Tanh())),
    "cross_entropy": lambda: L.cross_entropy_cost(
        L.fc(dense(), size=5, act=A.Softmax()), ids(5, "lbl")
    ),
    "cross_entropy_with_selfnorm": lambda: L.cross_entropy_with_selfnorm_cost(
        L.fc(dense(), size=5, act=A.Softmax()), ids(5, "lbl")
    ),
    "softmax_with_cost": _softmax_with_cost_out,
    "soft_binary_class_cross_entropy": _soft_bce_out,
    "multi_binary_label_cross_entropy": _multi_binary_out,
    "multi_nn_cost": _multi_nn_out,
    "multibox_loss": _multibox_out,
}


def test_every_registered_type_is_swept():
    """THE registry gate: a new layer type must land with a grad-check
    builder here or an explicit skip reason."""
    types = set(registered_layer_types())
    handled = set(SKIP) | set(BUILDERS)
    missing = sorted(types - handled)
    assert not missing, (
        f"registered layer types with neither a grad-check builder nor a "
        f"skip entry in test_layer_grad_sweep.py: {missing}"
    )
    stale = sorted(handled - types)
    assert not stale, f"sweep entries for unregistered types: {stale}"
    overlap = sorted(set(SKIP) & set(BUILDERS))
    assert not overlap, f"types both skipped and built: {overlap}"


@pytest.mark.parametrize("ltype", sorted(BUILDERS))
def test_registry_grad(ltype):
    built = BUILDERS[ltype]()
    out, kwargs = built if isinstance(built, tuple) else (built, {})
    # the builder must actually CONTAIN the type it claims to exercise —
    # without this a stale builder silently turns a type's check into a
    # check of something else
    topo = paddle.Topology([out])
    types_in = {c.type for c in topo.layers.values()}
    for c in topo.layers.values():
        sub = c.attrs.get("_sub_topology")
        if sub is not None:
            types_in |= {s.type for s in sub.layers.values()}
    assert ltype in types_in, (
        f"builder for {ltype!r} built a net without any {ltype!r} layer "
        f"(types present: {sorted(types_in)})"
    )
    reset_auto_names()
    built = BUILDERS[ltype]()
    out, kwargs = built if isinstance(built, tuple) else (built, {})
    check_layer_grad(out, **kwargs)
