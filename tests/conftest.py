"""Test configuration: force an 8-device virtual CPU mesh so all sharding
paths (data/model parallel) are exercised without TPU hardware — the loopback
"fake cluster" strategy of the reference's distributed tests (reference:
paddle/trainer/tests/test_CompareSparse.cpp spawning localhost pservers)."""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)
