"""Test configuration: force an 8-device virtual CPU mesh so all sharding
paths (data/model parallel) are exercised without TPU hardware — the loopback
"fake cluster" strategy of the reference's distributed tests (reference:
paddle/trainer/tests/test_CompareSparse.cpp spawning localhost pservers).

The ambient sitecustomize registers the single-chip `axon` TPU backend at
interpreter start, so switching platforms requires a re-exec (see
paddle_tpu.testing.ensure_cpu_mesh).  The re-exec happens in pytest_configure
— after suspending pytest's fd capture, otherwise the new process inherits
redirected fds and all output vanishes."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.testing import REEXEC_SENTINEL, ensure_cpu_mesh  # noqa: E402


def pytest_configure(config):
    # test tiers (reference CI splits fast unit tests from the long
    # trainer/integration binaries, paddle/scripts/travis/): `make test`
    # runs `-m "not slow"` in under 5 minutes; `make verify` runs everything
    config.addinivalue_line(
        "markers", "slow: long-running E2E/training test (excluded from `make test`)"
    )
    if not os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get(REEXEC_SENTINEL):
        ensure_cpu_mesh()  # just sets env defaults; no exec
        import jax

        jax.config.update("jax_enable_x64", False)
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.suspend_global_capture(in_=True)
    ensure_cpu_mesh(argv=["-m", "pytest", *config.invocation_params.args])


# Long-running tests (>= ~4s wall on the virtual CPU mesh, measured via
# `pytest --durations=0`): excluded from the `make test` fast tier and run
# by `make verify`.  Regenerate after large suite changes with
#   pytest --durations=0 | awk '$1+0>=4' ...
_SLOW_TESTS = {
    # demo run-sweep heavyweights
    "test_quick_start_configs_execute[db-lstm]",
    "test_quick_start_configs_execute[lstm]",
    "test_quick_start_configs_execute[bidi-lstm]",
    "test_quick_start_configs_execute[resnet-lstm]",
    "test_sequence_tagging_configs_execute[rnn_crf]",
    "test_sequence_tagging_configs_execute[linear_crf]",
    # DSL run-sweep heavyweights (conv-stack configs compile ~30s each)
    "test_dsl_config_executes[img_trans_layers]",
    "test_dsl_config_executes[img_layers]",
    "test_dsl_config_executes[test_cost_layers]",
    "test_dsl_config_executes[test_cost_layers_with_weight]",
    "test_dsl_config_executes[simple_rnn_layers]",
    # registry-sweep grad checks >= ~2s each (the sweep's completeness GATE,
    # test_every_registered_type_is_swept, always runs in the fast tier)
    "test_registry_grad[multibox_loss]",
    "test_registry_grad[lstmemory]",
    "test_registry_grad[gru]",
    "test_registry_grad[moe]",
    "test_registry_grad[mdlstmemory]",
    "test_registry_grad[multi_head_attention]",
    "test_registry_grad[crf]",
    "test_registry_grad[ctc]",
    "test_registry_grad[recurrent]",
    "test_registry_grad[nce]",
    "test_registry_grad[recurrent_group]",
    "test_registry_grad[lstm_step]",
    "test_registry_grad[multi_nn_cost]",
    "test_registry_grad[lambda_cost]",
    "test_registry_grad[hsigmoid]",
    "test_registry_grad[gru_step]",
    "test_registry_grad[seqconcat]",
    "test_registry_grad[selective_fc]",
    "test_registry_grad[cross_entropy]",
    "test_registry_grad[norm]",
    "test_beam_hooks_through_dsl_layer",
    "test_beam_search_generation",
    "test_beam_search_layer_through_infer",
    "test_column_parallel_fc_matches",
    "test_conv_operator",
    "test_cos_sim_vec_mat",
    "test_cost_decreases",
    "test_crf_grad",
    "test_ctc_grad",
    "test_ctc_matches_torch",
    "test_detection_output_decodes_known_boxes",
    "test_flash_gradients_match_dense_interpret",
    "test_gan_learns_gaussian",
    "test_gan_losses_are_finite_and_adversarial",
    "test_greedy_generation_copies",
    "test_gru_grad",
    "test_hierarchical_rnn_trains",
    "test_hsigmoid_grad",
    "test_hsigmoid_probabilities_sum_to_one",
    "test_infer_field_id_and_multiple_outputs",
    "test_infer_mnist_lenet",
    "test_lambda_cost_grad",
    "test_lstmemory_grad",
    "test_lstmemory_reverse_grad",
    "test_masters_stay_f32_grads_f32",
    "test_mdlstm_shape_and_grad",
    "test_mha_self_attention_grad",
    "test_mixed_seq_input_grad",
    "test_moe_capacity_drops_tokens_and_masks_padding",
    "test_moe_expert_parallel_matches_unsharded",
    "test_moe_init_std_uses_fan_in",
    "test_moe_matches_dense_reference_when_capacity_ample",
    "test_moe_trains_on_mesh",
    "test_multibox_loss_runs_and_matches",
    "test_nce_grad",
    "test_nce_with_dist_runs",
    "test_ner_crf_trains_locally",
    "test_ner_crf_trains_sparse_sharded_on_mesh",
    "test_ner_tagging_accuracy_via_decoding",
    "test_nested_group_grad",
    "test_nmt_cost_decreases",
    "test_param_init_stable_across_processes",
    "test_pipeline_gradients_match_sequential",
    "test_profiler_trace_writes",
    "test_pipeline_matches_sequential",
    "test_prelu_grad",
    "test_rank_cost_grad",
    "test_raw_face_chunking_crf_forward",
    "test_recurrent_grad",
    "test_recurrent_group_bf16_carry",
    "test_reference_nested_rnn_equals_flat_rnn",
    "test_ring_gradients_match_dense",
    "test_ring_matches_dense",
    "test_ring_respects_key_padding",
    "test_selective_fc_grad",
    "test_sequence_memory_grad",
    "test_shared_fc_and_groups_share_storage",
    "test_soft_bce_grad",
    "test_sparse_sharded_matches_dense_numerics",
    "test_trainer_one_pass_mnist_opt_a",
    "test_training_survives_failover",
    "test_transformer_trains_on_copy_task",
    "test_transformer_with_sequence_parallel_matches_dense",
    "test_vae_config_builds_and_trains",
    "test_vae_reconstructs_and_samples",
}


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest

    for item in items:
        # match the base name (marks every param case) or one exact
        # parametrized id like "test_registry_grad[moe]"
        if item.name.split("[")[0] in _SLOW_TESTS or item.name in _SLOW_TESTS:
            item.add_marker(_pytest.mark.slow)
