"""Test configuration: force an 8-device virtual CPU mesh so all sharding
paths (data/model parallel) are exercised without TPU hardware — the loopback
"fake cluster" strategy of the reference's distributed tests (reference:
paddle/trainer/tests/test_CompareSparse.cpp spawning localhost pservers).

The ambient sitecustomize registers the single-chip `axon` TPU backend at
interpreter start, so switching platforms requires a re-exec (see
paddle_tpu.testing.ensure_cpu_mesh).  The re-exec happens in pytest_configure
— after suspending pytest's fd capture, otherwise the new process inherits
redirected fds and all output vanishes."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.testing import REEXEC_SENTINEL, ensure_cpu_mesh  # noqa: E402


def pytest_configure(config):
    if not os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get(REEXEC_SENTINEL):
        ensure_cpu_mesh()  # just sets env defaults; no exec
        import jax

        jax.config.update("jax_enable_x64", False)
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.suspend_global_capture(in_=True)
    ensure_cpu_mesh(argv=["-m", "pytest", *config.invocation_params.args])
