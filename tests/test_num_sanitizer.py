"""Numerics sanitizer (analysis/num_sanitizer.py): the jaxpr interpreter
localizes the first non-finite-producing eqn (through scans, with layer
provenance), the trainer postmortem rides the flight recorder on a
``nan_batch`` drill, and the unarmed path is untouched (zero captures,
byte-identical params)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis.num_sanitizer import (
    find_first_nonfinite,
    num_sanitizer_armed,
)
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.robustness import chaos
from paddle_tpu.utils import flags
from paddle_tpu.utils.timers import global_stats


@pytest.fixture(autouse=True)
def _clean():
    yield
    chaos.disarm()
    flags.reset_flags()


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


def test_first_nonfinite_names_eqn_and_poisoned_input():
    def fn(x, w):
        with jax.named_scope("fc:h1"):
            y = x @ w
        return jnp.tanh(y)

    x = np.ones((4, 8), np.float32)
    x[0, 0] = np.nan
    rec = find_first_nonfinite(fn, (x, np.ones((8, 8), np.float32)))
    assert rec is not None
    assert rec["primitive"] == "dot_general"
    assert rec["layer"] == "h1"
    assert rec["poisoned_inputs"] and "arg0" in rec["poisoned_inputs"][0]["input"]
    # the offending eqn's input stats show the poison
    assert any(s.get("n_nonfinite") for s in rec["inputs"])


def test_first_nonfinite_is_the_producer_not_a_consumer():
    """A finite input that OVERFLOWS mid-graph: the record names the op
    that produced the first inf, not the op that consumed it."""
    def fn(x):
        big = jnp.exp(x)          # overflows to inf at x=200
        return big - big          # the consumer turns it into nan

    rec = find_first_nonfinite(fn, (np.full((4,), 200.0, np.float32),))
    assert rec["primitive"] == "exp"
    assert rec["poisoned_inputs"] == []


def test_first_nonfinite_localizes_inside_scan_step():
    def fn(xs):
        def body(c, x):
            c = c * x             # blows up at the poisoned step
            return c, jnp.log(c)

        return jax.lax.scan(body, jnp.ones((), jnp.float32), xs)

    xs = np.ones((6,), np.float32)
    xs[3] = np.inf
    rec = find_first_nonfinite(fn, (xs,))
    assert rec["scan_step"] == 3
    assert rec["primitive"] == "mul"
    assert "step3" in rec["eqn"]


def test_all_finite_returns_none():
    assert find_first_nonfinite(
        lambda x: jnp.tanh(x).sum(), (np.ones((4,), np.float32),)
    ) is None


def test_saturating_quantization_scale_underflow_named_as_div():
    """The quantization chaos drill (ops/quantize.py's LOUD-failure
    contract): tiny-magnitude blocks with a narrow ``scale_dtype`` make
    the stored block scale underflow to 0 — amax is NOT exactly zero, so
    the zero-guard stays out of the way, the quantize division produces
    inf, and the sanitizer names that div eqn instead of the config
    silently zeroing every block."""
    from paddle_tpu.ops.quantize import quantize_block_scaled

    def quant(x):
        payload, scale = quantize_block_scaled(
            x, block=64, scale_dtype=jnp.float16
        )
        return payload.astype(jnp.float32).sum() + scale.sum()

    # amax ~1e-8: amax/127 ~ 7.9e-11 is below the smallest f16 subnormal
    # (~6e-8), so the f16-stored scale reads back 0.0
    x = np.full((64,), 1e-8, np.float32)
    rec = find_first_nonfinite(quant, (x,))
    assert rec is not None
    assert rec["primitive"] == "div"
    assert rec["poisoned_inputs"] == []  # the div PRODUCES the first inf
    # the healthy config (f32 scales) on the same data is finite
    def quant_ok(x):
        payload, scale = quantize_block_scaled(x, block=64)
        return payload.astype(jnp.float32).sum() + scale.sum()

    assert find_first_nonfinite(quant_ok, (x,)) is None


def test_armed_flag_reads_env(monkeypatch):
    flags.reset_flags()
    monkeypatch.delenv("PADDLE_TPU_NUM_SANITIZER", raising=False)
    assert not num_sanitizer_armed()
    monkeypatch.setenv("PADDLE_TPU_NUM_SANITIZER", "1")
    assert num_sanitizer_armed()


# ---------------------------------------------------------------------------
# trainer e2e: nan_batch drill -> flight-recorder postmortem
# ---------------------------------------------------------------------------


def _small_trainer(seed=0):
    reset_auto_names()
    paddle.init(seed=seed)
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(32))
    label = paddle.layer.data("label", paddle.data_type.integer_value(4))
    h = paddle.layer.fc(img, size=16, act=paddle.activation.Relu(), name="h1")
    pred = paddle.layer.fc(h, size=4, act=paddle.activation.Softmax(),
                           name="out")
    cost = paddle.layer.classification_cost(input=pred, label=label)
    return paddle.trainer.SGD(
        cost=cost,
        parameters=paddle.parameters.create(cost, seed=seed),
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9
        ),
    )


def _reader(n_batches=6, rows=8):
    rng = np.random.RandomState(7)
    data = [
        (rng.randn(32).astype("float32"), int(rng.randint(4)))
        for _ in range(n_batches * rows)
    ]

    def read():
        for v, y in data:
            yield v, y

    return paddle.batch(read, rows)


def _final_params(trainer):
    return {
        k: np.asarray(v)
        for k, v in jax.tree_util.tree_leaves_with_path(
            jax.device_get(trainer.parameters.params)
        )
    }


def test_nan_batch_postmortem_names_poisoned_eqn(tmp_path):
    """The acceptance drill: with the sanitizer armed, the nan_batch
    chaos point's skipped step produces a flight-recorder postmortem
    naming the first non-finite-producing eqn, its layer, and the
    poisoned feed slot — instead of just 'a step was skipped'."""
    flags.set_flag("num_sanitizer", True)
    flags.set_flag("trace_dir", str(tmp_path))
    trainer = _small_trainer()
    chaos.arm("nan_batch@3")
    base = global_stats.count("num_sanitizer/captures")
    trainer.train(_reader(), num_passes=1)
    assert global_stats.count("num_sanitizer/captures") > base

    fl = tmp_path / f"flight-{os.getpid()}.json"
    obj = json.loads(fl.read_text())
    assert obj["otherData"]["reason"].startswith("num-sanitizer: skip")
    num = obj["otherData"]["numerics"]
    # the first op to consume the poisoned 'pixel' slot, with provenance
    assert num["primitive"] == "dot_general"
    assert num["layer"] == "h1"
    assert any("pixel" in p["input"] for p in num["poisoned_inputs"])
    assert num["source"] and num["line"]
    # input max-abs range stats landed in the StatSet num/<eqn> rows
    summ = global_stats.summary()
    num_rows = {k: v for k, v in summ.items() if k.startswith("num/")}
    assert num_rows, sorted(summ)
    # the poisoned input's NaN observation went to the nonfinite bucket
    assert any(v["nonfinite"] for v in num_rows.values())


def test_unarmed_is_untouched_and_armed_changes_nothing(tmp_path):
    """Zero-overhead contract: unarmed, the capture counter never moves;
    and arming the sanitizer (observe-only) leaves the trained params
    byte-identical to the unarmed run."""
    base = global_stats.count("num_sanitizer/captures")
    flags.set_flag("divergence_sentinel", True)
    # explicit False beats a PADDLE_TPU_NUM_SANITIZER=1 environment (the
    # `make chaos` target arms it globally) — this leg tests UNARMED
    flags.set_flag("num_sanitizer", False)
    t1 = _small_trainer(seed=3)
    t1.train(_reader(), num_passes=1)
    assert global_stats.count("num_sanitizer/captures") == base  # unarmed
    p1 = _final_params(t1)

    flags.set_flag("num_sanitizer", True)
    flags.set_flag("trace_dir", str(tmp_path))
    t2 = _small_trainer(seed=3)
    t2.train(_reader(), num_passes=1)
    assert global_stats.count("num_sanitizer/captures") > base  # armed
    p2 = _final_params(t2)

    assert p1.keys() == p2.keys()
    for k in p1:
        assert np.array_equal(p1[k], p2[k]), k
