"""Dynamic-width consumers (fc / matrix projections over a whole-minibatch
trans) — reference TransLayer.cpp + FullyConnectedLayer.cpp.

The reference keeps the STATIC declared size for the fc weight (protostr
test_fc dims 100x100) and can therefore only run the graph when batch ==
that size.  Here the trainer resolves the true width from its first batch
(CompiledNetwork.resolve_dynamic_widths), so the reference's own test_fc
config builds warning-free AND trains at any batch size.
"""

import warnings

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu.v1_compat import parse_config

L = paddle.layer
A = paddle.activation

TEST_FC = (
    "/root/reference/python/paddle/trainer_config_helpers/tests/configs/"
    "test_fc.py"
)


@pytest.fixture(autouse=True)
def _reset_names():
    reset_auto_names()
    yield


def test_reference_test_fc_builds_warning_free():
    """The r4 VERDICT regression: parsing + compiling the reference's
    test_fc.py (trans -> fc) must not emit the dynamic-width warning."""
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        p = parse_config(TEST_FC)
        CompiledNetwork(p.topology)
    fc_conf = next(
        c for c in p.topology.layers.values() if c.type == "fc"
    )
    assert fc_conf.attr("dynamic_width_in") == (0,)


@pytest.mark.parametrize("batch", [7, 100, 160])
def test_trans_fc_trains_at_any_batch(batch):
    """trans -> fc -> sum cost trains at batch sizes below, equal to, and
    above the static width: the first batch resolves the fc weight to
    [batch, size] and cost decreases."""
    x = L.data("x", paddle.data_type.dense_vector(12))
    h = L.fc(L.trans(x), size=4, act=A.Tanh(), name="dynfc")
    cost = L.sum_cost(h)
    params = paddle.parameters.create(cost)
    # init builds the static shape (the reference's parameter dims)
    assert params.params["dynfc"]["w0"].shape == (12, 4)

    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.05),
    )
    rng = np.random.RandomState(0)
    rows = [(rng.randn(12).astype(np.float32),) for _ in range(batch * 4)]
    costs = []
    trainer.train(
        reader=paddle.batch(lambda: iter(rows), batch, drop_last=True),
        num_passes=3,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
        async_load_data=False,
    )
    # the weight was re-shaped to the runtime width...
    assert trainer.parameters.params["dynfc"]["w0"].shape == (batch, 4)
    # ...and gradients flow through it (sum cost is driven down)
    assert all(np.isfinite(costs))
    assert costs[-1] < costs[0] - 0.1, costs


def test_matrix_projection_resolves_too():
    """The mixed/full_matrix_projection analogue of trans -> fc."""
    x = L.data("x", paddle.data_type.dense_vector(10))
    m = L.mixed(
        size=3, input=L.full_matrix_projection(L.trans(x)), name="dynmix"
    )
    topo = Topology([m])
    net = CompiledNetwork(topo)
    assert net.has_dynamic_widths
    params, state = net.init(jax.random.PRNGKey(0))
    assert params["dynmix"]["p0_w"].shape == (10, 3)
    from paddle_tpu.core.batch import SeqTensor

    b = 6
    batch = {"x": SeqTensor(np.random.randn(b, 10).astype(np.float32))}
    params, changed = net.resolve_dynamic_widths(params, batch)
    assert changed
    assert params["dynmix"]["p0_w"].shape == (b, 3)
    outs, _ = net.apply(params, batch, state=state, train=False)
    assert outs["dynmix"].data.shape == (10, 3)  # [D rows, size]


def test_static_batch_still_uses_init_weights():
    """batch == static size: nothing to resolve, weights untouched."""
    x = L.data("x", paddle.data_type.dense_vector(8))
    h = L.fc(L.trans(x), size=2, act=A.Identity(), name="f")
    net = CompiledNetwork(Topology([h]))
    params, _ = net.init(jax.random.PRNGKey(0))
    from paddle_tpu.core.batch import SeqTensor

    batch = {"x": SeqTensor(np.zeros((8, 8), np.float32))}
    p2, changed = net.resolve_dynamic_widths(params, batch)
    assert not changed
    assert p2["f"]["w0"] is params["f"]["w0"]


def test_restored_other_batch_weights_raise_not_redraw():
    """Weights trained/restored at a different batch size must raise, not
    be silently replaced with fresh random values (r5 review finding)."""
    x = L.data("x", paddle.data_type.dense_vector(8))
    h = L.fc(L.trans(x), size=2, act=A.Identity(), name="f")
    net = CompiledNetwork(Topology([h]))
    params, _ = net.init(jax.random.PRNGKey(0))
    # simulate a checkpoint trained at batch 20 (static size is 8)
    params["f"]["w0"] = np.zeros((20, 2), np.float32)
    from paddle_tpu.core.batch import SeqTensor

    batch = {"x": SeqTensor(np.zeros((6, 8), np.float32))}
    with pytest.raises(ValueError, match="different batch size"):
        net.resolve_dynamic_widths(params, batch)
