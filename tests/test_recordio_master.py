"""recordio + elastic master tests (reference models: go recordio usage in
go/master/service_test.go, master/client_test.go's kill-and-recover flows)."""

import os
import pickle
import threading
import time

import numpy as np
import pytest

from paddle_tpu import master as master_mod
from paddle_tpu.io import recordio


def _write(path, n, chunk=50, tag=""):
    recordio.write_records(
        path, (f"{tag}{i}".encode() for i in range(n)), max_chunk_records=chunk
    )


# ---------------------------------------------------------------------------
# recordio format
# ---------------------------------------------------------------------------

def test_roundtrip_native(tmp_path):
    p = str(tmp_path / "a.rio")
    _write(p, 1234, chunk=100)
    with recordio.Reader(p) as r:
        recs = list(r)
    assert len(recs) == 1234
    assert recs[0] == b"0" and recs[-1] == b"1233"


def test_python_fallback_reads_native_file(tmp_path, monkeypatch):
    p = str(tmp_path / "a.rio")
    _write(p, 300, chunk=64)  # whichever backend is active
    # force the pure-Python path
    monkeypatch.setattr(recordio, "_load_native", lambda: None)
    with recordio.Reader(p) as r:
        recs = list(r)
    assert len(recs) == 300
    chunks = recordio.scan_chunks(p)
    assert sum(c.n_records for c in chunks) == 300
    # and python-written files read back fine too
    p2 = str(tmp_path / "b.rio")
    recordio.write_records(p2, [b"x", b"y"], max_chunk_records=1)
    assert list(recordio.Reader(p2)) == [b"x", b"y"]


def test_native_reads_python_file(tmp_path, monkeypatch):
    if not recordio.native_available():
        pytest.skip("no native toolchain")
    p = str(tmp_path / "a.rio")
    orig = recordio._load_native
    monkeypatch.setattr(recordio, "_load_native", lambda: None)
    recordio.write_records(p, [f"r{i}".encode() for i in range(97)], max_chunk_records=10)
    monkeypatch.setattr(recordio, "_load_native", orig)
    with recordio.Reader(p) as r:
        assert len(list(r)) == 97


def test_chunk_seek(tmp_path):
    p = str(tmp_path / "a.rio")
    _write(p, 500, chunk=100)
    chunks = recordio.scan_chunks(p)
    assert len(chunks) == 5
    with recordio.Reader(p, offset=chunks[2].offset) as r:
        assert r.next() == b"200"


def test_corruption_detected(tmp_path):
    p = str(tmp_path / "a.rio")
    _write(p, 100, chunk=100)
    with open(p, "r+b") as f:
        f.seek(40)
        f.write(b"\xde\xad")
    with pytest.raises(IOError):
        list(recordio.Reader(p))


def test_prefetcher_surfaces_corruption(tmp_path):
    p = str(tmp_path / "bad.rio")
    _write(p, 100, chunk=100)
    with open(p, "r+b") as f:
        f.seek(40)
        f.write(b"\xde\xad")
    with pytest.raises(IOError):
        with recordio.Prefetcher([p]) as pf:
            list(pf)


def test_prefetcher(tmp_path):
    paths = []
    for k in range(4):
        p = str(tmp_path / f"f{k}.rio")
        _write(p, 250, tag=f"{k}:")
        paths.append(p)
    with recordio.Prefetcher(paths, n_threads=4, capacity=32) as pf:
        got = list(pf)
    assert len(got) == 1000
    assert sorted(got) == sorted(
        f"{k}:{i}".encode() for k in range(4) for i in range(250)
    )


# ---------------------------------------------------------------------------
# master service
# ---------------------------------------------------------------------------

class _FakeClock:
    """Deterministic clock injected into Service so lease-expiry tests don't
    depend on wall time (suite load made 0.05s leases double-expire)."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _make_service(tmp_path, n_files=2, n_records=200, **kw):
    for k in range(n_files):
        _write(str(tmp_path / f"d{k}.rio"), n_records, chunk=25, tag=f"{k}:")
    kw.setdefault("snapshot_min_interval_s", 0.0)
    svc = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"),
        chunks_per_task=2,
        **kw,
    )
    svc.set_dataset([str(tmp_path / "d*.rio")])
    return svc


def test_master_full_pass(tmp_path):
    svc = _make_service(tmp_path)
    client = master_mod.Client(svc)
    recs = []
    while True:
        r = client.next_record()
        if r is None:
            break
        recs.append(r)
    assert len(recs) == 400
    assert svc.pass_id == 1
    # second pass serves everything again
    recs2 = [r for r in iter(client.next_record, None)]
    assert sorted(recs2) == sorted(recs)


def test_master_timeout_requeue(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, timeout_s=5.0, clock=clk)
    t1 = svc.get_task()
    assert t1 is not None
    clk.advance(10.0)  # lease expires; no real waiting
    # expired lease goes back to todo with epoch+1
    tasks = []
    while True:
        t = svc.get_task()
        if not isinstance(t, dict):
            break
        tasks.append(t)
    ids = [t["task"]["task_id"] for t in tasks]
    assert t1["task"]["task_id"] in ids  # requeued
    requeued = next(t for t in tasks if t["task"]["task_id"] == t1["task"]["task_id"])
    assert requeued["epoch"] == 1


def test_master_failure_discard(tmp_path):
    svc = _make_service(tmp_path, failure_max=2)
    total = svc.n_tasks()
    t = svc.get_task()
    tid, ep = t["task"]["task_id"], t["epoch"]
    assert svc.task_failed(tid, ep)
    # second failure discards
    t2 = None
    while True:
        cand = svc.get_task()
        assert isinstance(cand, dict)
        if cand["task"]["task_id"] == tid:
            t2 = cand
            break
    assert svc.task_failed(tid, t2["epoch"])
    assert len(svc.discarded) == 1
    assert svc.n_tasks() == total - 1
    # stale epoch is rejected
    assert not svc.task_failed(tid, 0)


def test_master_snapshot_recover(tmp_path):
    svc = _make_service(tmp_path)
    total = svc.n_tasks()
    got = svc.get_task()
    svc.task_finished(got["task"]["task_id"])
    got2 = svc.get_task()  # left pending — lease must not survive restart
    # "crash": new service from the same snapshot
    svc2 = master_mod.Service(snapshot_path=str(tmp_path / "snap.json"))
    assert svc2.n_tasks() == total
    assert len(svc2.done) == 1
    assert not svc2.pending  # pending requeued into todo
    ids = {t.task_id for t in svc2.todo}
    assert got2["task"]["task_id"] in ids


def test_master_snapshot_trailing_flush(tmp_path):
    """A debounced transition must still reach disk via the flush timer."""
    import json

    svc = _make_service(tmp_path, snapshot_min_interval_s=0.2)
    svc.get_task()  # debounced (set_dataset just wrote)
    time.sleep(0.5)  # timer fires
    with open(str(tmp_path / "snap.json")) as f:
        state = json.load(f)
    assert len(state["pending"]) == 1


def test_master_save_arbitration(tmp_path):
    svc = _make_service(tmp_path)
    a = master_mod.Client(svc, trainer_id="a")
    b = master_mod.Client(svc, trainer_id="b")
    assert a.request_save_model(block_secs=5.0)
    assert not b.request_save_model(block_secs=5.0)
    assert a.request_save_model(block_secs=5.0)  # holder keeps the grant


def test_master_over_rpc(tmp_path):
    svc = _make_service(tmp_path, n_files=1, n_records=100)
    server = master_mod.Server(svc, address=("127.0.0.1", 0))
    try:
        client = master_mod.Client(server.address)
        n = 0
        while client.next_record() is not None:
            n += 1
        assert n == 100
        assert client.request_save_model(1.0)
        client.close()
    finally:
        server.close()


def test_master_concurrent_workers(tmp_path):
    """Several worker threads drain one pass exactly once under the
    synchronized-pass barrier (auto_rotate=False), then a released barrier
    serves the next pass."""
    svc = _make_service(tmp_path, n_files=3, n_records=120, auto_rotate=False)
    expected = sorted(f"{k}:{i}".encode() for k in range(3) for i in range(120))

    def drain():
        out, lock = [], threading.Lock()

        def work():
            c = master_mod.Client(svc)
            while True:
                r = c.next_record()
                if r is None:
                    return
                with lock:
                    out.append(r)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sorted(out)

    assert drain() == expected  # pass 0: exactly once
    assert svc.pass_id == 0
    svc.start_new_pass()
    assert svc.pass_id == 1
    assert drain() == expected  # pass 1 serves everything again


def test_prefetcher_close_unblocks_workers(tmp_path, monkeypatch):
    """Early consumer exit must not leak blocked fallback workers."""
    import threading as _threading

    monkeypatch.setattr(recordio, "_load_native", lambda: None)
    p = str(tmp_path / "big.rio")
    _write(p, 500)
    before = _threading.active_count()
    pf = recordio.Prefetcher([p], n_threads=1, capacity=4)
    assert pf.next() is not None  # worker is now blocked on the full queue
    pf.close()
    time.sleep(0.3)
    assert _threading.active_count() <= before + 1  # worker exited


def test_numpy_payloads_end_to_end(tmp_path):
    """Typical use: pickled numpy samples through recordio + master reader."""
    p = str(tmp_path / "data.rio")
    rng = np.random.RandomState(0)
    samples = [(rng.randn(4).astype(np.float32), int(rng.randint(3))) for _ in range(50)]
    recordio.write_records(p, (pickle.dumps(s) for s in samples), max_chunk_records=10)
    svc = master_mod.Service(chunks_per_task=2)
    svc.set_dataset([p])
    client = master_mod.Client(svc)
    got = [pickle.loads(r) for r in iter(client.next_record, None)]
    assert len(got) == 50
    np.testing.assert_allclose(got[0][0], samples[0][0])


def _craft_bad_header(path, n_records=None, first_len=None):
    """Write one valid chunk, then rewrite header fields the CRC does not
    cover (crc32 spans the body only) to simulate a crafted/corrupted header."""
    import struct
    import zlib

    recs = [b"abc", b"defg"]
    body = b"".join([struct.pack("<I", len(r)) for r in recs] + recs)
    n = n_records if n_records is not None else len(recs)
    if first_len is not None:
        body = struct.pack("<I", first_len) + body[4:]
    head = struct.pack("<IIII", 0x7061646C, zlib.crc32(body), len(body), n)
    with open(path, "wb") as f:
        f.write(head + body)


@pytest.mark.parametrize("force_py", [False, True])
def test_crafted_header_n_records(tmp_path, monkeypatch, force_py):
    """n_records claiming a length table bigger than the body must surface as
    a corrupt chunk, not an out-of-bounds read (ADVICE r1, paddle_tpu/native/recordio.cc
    load_chunk)."""
    if force_py:
        monkeypatch.setattr(recordio, "_load_native", lambda: None)
    elif not recordio.native_available():
        pytest.skip("no native toolchain")
    p = str(tmp_path / "bad_n.rio")
    _craft_bad_header(p, n_records=1 << 30)
    with pytest.raises(IOError):
        list(recordio.Reader(p))
    with pytest.raises(IOError):
        recordio.scan_chunks(p)


@pytest.mark.parametrize("force_py", [False, True])
def test_crafted_record_length(tmp_path, monkeypatch, force_py):
    """A record length overrunning the body must be treated as corruption.
    The length table is CRC-covered, so the CRC is recomputed to match."""
    if force_py:
        monkeypatch.setattr(recordio, "_load_native", lambda: None)
    elif not recordio.native_available():
        pytest.skip("no native toolchain")
    p = str(tmp_path / "bad_len.rio")
    _craft_bad_header(p, first_len=1 << 20)
    with pytest.raises(IOError):
        list(recordio.Reader(p))


@pytest.mark.parametrize("force_py", [False, True])
def test_reader_seek_bad_offset(tmp_path, monkeypatch, force_py):
    """A failing seek (negative offset) must raise at construction on both
    backends — not silently serve records from offset 0 (ADVICE r1)."""
    if force_py:
        monkeypatch.setattr(recordio, "_load_native", lambda: None)
    elif not recordio.native_available():
        pytest.skip("no native toolchain")
    p = str(tmp_path / "a.rio")
    _write(p, 10, chunk=5)
    # (fseek beyond EOF succeeds on POSIX; the first read then reports clean
    # EOF or corruption — both acceptable and covered elsewhere.)
    with pytest.raises((IOError, OverflowError, ValueError)):
        recordio.Reader(p, offset=-1)


def test_master_client_acks_on_drain(tmp_path):
    """Consume-then-ack: the task lease is released only after every record
    was handed out (ADVICE r1; reference go/master client NextRecord)."""
    p = str(tmp_path / "a.rio")
    _write(p, 10, chunk=10)  # one chunk -> one task
    svc = master_mod.Service(timeout_s=60, chunks_per_task=1, auto_rotate=False)
    client = master_mod.Client(svc)
    client.set_dataset([p])
    first = client.next_record()
    assert first is not None
    # records are buffered but not fully consumed: the task must still be
    # leased (pending), not done
    assert len(svc.pending) == 1 and not svc.done
    got = [first] + [client.next_record() for _ in range(9)]
    assert all(r is not None for r in got)
    # pass boundary drains + acks
    assert client.next_record() is None
    assert not svc.pending and len(svc.done) == 1


def test_master_lease_renewal(tmp_path):
    """A consumer slower than the lease timeout renews instead of expiring
    into the failure/discard path."""
    p = str(tmp_path / "a.rio")
    _write(p, 4, chunk=4)
    svc = master_mod.Service(timeout_s=0.8, chunks_per_task=1, auto_rotate=False)
    client = master_mod.Client(svc)
    client.lease_renew_secs = 0.05
    client.set_dataset([p])
    got = []
    for _ in range(4):
        got.append(client.next_record())
        time.sleep(0.25)  # total consumption time (1s) > timeout_s (0.8s)
    assert all(r is not None for r in got)
    assert client.next_record() is None
    assert not svc.pending and len(svc.done) == 1 and not svc.discarded

def test_master_stale_ack_rejected(tmp_path):
    """An expired holder must not ack a task re-served at a higher epoch."""
    p = str(tmp_path / "a.rio")
    _write(p, 8, chunk=4)
    clk = _FakeClock()
    svc = master_mod.Service(
        timeout_s=5.0, chunks_per_task=1, auto_rotate=False, clock=clk
    )
    svc.set_dataset([p])
    t = svc.get_task()
    tid, ep = t["task"]["task_id"], t["epoch"]
    clk.advance(10.0)  # lease expires; clock then freezes — exactly one expiry
    # re-served at epoch+1 (possibly after draining the other task first)
    while True:
        t2 = svc.get_task()
        assert isinstance(t2, dict), "task was not re-served"
        if t2["task"]["task_id"] == tid:
            break
        svc.task_finished(t2["task"]["task_id"], t2["epoch"])
    assert t2["epoch"] == ep + 1
    assert not svc.task_finished(tid, ep)  # stale holder rejected
    assert svc.task_finished(tid, t2["epoch"])  # live holder acks fine


def test_client_close_returns_unconsumed_task(tmp_path):
    """Graceful close with buffered records hands the task back (no failure
    event, no progress toward failure_max discard) and a later client still
    sees every record of the pass."""
    svc = _make_service(tmp_path, failure_max=2)
    client = master_mod.Client(svc)
    first = client.next_record()
    assert first is not None
    assert client._pending_task is not None
    client.close()
    assert client._pending_task is None
    assert svc.fail_events == 0
    # every record (including the returned task's) is served to a new client
    client2 = master_mod.Client(svc)
    recs = [r for r in iter(client2.next_record, None)]
    assert len(recs) == 400 and first in recs


def test_client_close_acks_drained_task(tmp_path):
    svc = _make_service(tmp_path, n_files=1, n_records=50)
    n_task_records = 50  # 2 chunks/task x 25 records/chunk
    client = master_mod.Client(svc)
    # drain the first task's buffer completely, but don't fetch the next
    for _ in range(n_task_records):
        assert client.next_record() is not None
    assert client._pending_task is not None and not client._records
    client.close()
    assert len(svc.done) == 1 and not svc.pending


def test_task_failed_stale_epoch_keeps_lease(tmp_path):
    """A stale holder's failure report must not evict the current holder's
    pending entry (epoch guard checks BEFORE removal)."""
    clk = _FakeClock()
    svc = _make_service(tmp_path, timeout_s=5.0, clock=clk)
    t1 = svc.get_task()
    tid, epoch = t1["task"]["task_id"], t1["epoch"]
    clk.advance(10.0)  # lease expires; clock then freezes — exactly one expiry
    t2 = None
    while True:
        t = svc.get_task()
        if not isinstance(t, dict):
            break
        if t["task"]["task_id"] == tid:
            t2 = t
    assert t2 is not None and t2["epoch"] == epoch + 1
    # stale holder reports failure with the old epoch: rejected, lease intact
    assert not svc.task_failed(tid, epoch)
    assert tid in svc.pending
    # current holder can still ack
    assert svc.task_finished(tid, t2["epoch"])


def test_dataset_convert_writes_shards(tmp_path):
    """dataset.common.convert: any reader -> pickled recordio shards
    (reference v2/dataset/common.py:187), line_count samples per shard."""
    from paddle_tpu.dataset import common as ds_common

    samples = [(np.full(3, i, np.float32), i % 2) for i in range(25)]
    paths = ds_common.convert(
        str(tmp_path / "out"), lambda: iter(samples), 10, "toy"
    )
    assert [os.path.basename(p) for p in paths] == [
        "toy-00000", "toy-00001", "toy-00002"
    ]
    got = []
    for p in paths:
        with recordio.Reader(p) as r:
            for rec in iter(r.next, None):
                got.append(pickle.loads(rec))
    assert len(got) == 25
    # shard-local shuffle only: the sample SET is preserved
    assert sorted(float(s[0][0]) for s in got) == [float(i) for i in range(25)]


def test_convert_master_train_round_trip(tmp_path):
    """The full reader->master pipeline the VERDICT asked to wire: convert
    mnist shards -> Service.set_dataset -> cloud_reader leases/acks ->
    one v2 training pass runs and the cost is finite (reference
    v2/dataset/common.py convert + go/master/service.go:105 + the v2
    cloud_reader recipe in reader/creator.py:87)."""
    import paddle_tpu as paddle
    from paddle_tpu.dataset import common as ds_common, mnist
    from paddle_tpu.reader import creator

    out = str(tmp_path / "mnist_rio")
    # small synthetic slice: convert the first 300 samples of the mnist
    # reader (synthetic fallback when the real idx files are absent)
    from paddle_tpu.reader.decorator import firstn

    ds_common.convert(out, firstn(mnist.train(), 300), 100, "mnist_train")

    svc = master_mod.Service(chunks_per_task=1)
    reader = creator.cloud_reader([out + "/mnist_train-*"], svc)

    img = paddle.layer.data(name="pixel", type=paddle.data_type.dense_vector(784))
    lbl = paddle.layer.data(name="label", type=paddle.data_type.integer_value(10))
    fc = paddle.layer.fc(input=img, size=10, act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=fc, label=lbl)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.05),
    )
    costs = []
    trainer.train(
        reader=paddle.batch(reader, 50),
        num_passes=2,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    # 300 samples / batch 50 = 6 updates per pass, 2 passes through the
    # master's pass-rotation (start_new_pass via auto_rotate)
    assert len(costs) == 12, len(costs)
    assert all(np.isfinite(costs))
    assert np.mean(costs[-3:]) < np.mean(costs[:3])
