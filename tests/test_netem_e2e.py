"""Hostile-network e2e drills — real process fleets under partition and
split-brain (ISSUE 15 acceptance; `make chaos`).

Two drills:

* **worker partitioned mid-pass** — one of 4 worker processes loses its
  link (``net_partition``, egress dropped for seconds): its registry
  lease expires, the master prunes it (requeueing any held shard lease),
  the surviving fleet fences and completes WITHOUT it, and when the link
  heals the worker rejoins late, catches up from retained result maps,
  and exits clean — final params bit-for-bit vs an unfaulted run.

* **leader <-> standby asymmetric partition during a campaign** — the
  leader and its standby communicate ONLY through shared storage (lease
  mtime, snapshot, journal), so the ``stale_lease`` chaos point IS the
  asymmetric partition of that link: the leader's heartbeat WRITES stop
  reaching storage (it believes every renewal succeeded) while its READS
  — and its whole RPC plane — keep working.  The standby sees the stale
  lease, campaigns, and promotes WARM while the deposed leader is still
  alive and serving: a genuine dual-leader window.  The fencing layers
  (lease-owner detection on the next renew, journal generation ownership,
  the idempotent epoch/pass-guarded ack plane) must collapse it to
  exactly ONE fenced leader with zero tasks lost, params bit-for-bit,
  and a clean surviving journal.

All tests spawn multiple python processes => marked slow (tier-1 runs
`-m "not slow"`; `make chaos` runs this file directly)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.checkpoint import CheckpointManager
from paddle_tpu.io import recordio
from paddle_tpu.master_ha import HAMaster, discover_endpoint
from paddle_tpu.trainer.elastic import NumpyLinearModel

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8
TASKS_PER_PASS = 12  # 96 records / 4 per chunk = 24 chunks at 2/task
PASSES = 2

# shorter worker lease than the failover drill: the partitioned worker
# must be PRUNED well inside its partition window; the task lease stays
# wider so an ordinary slow ack never burns a failure event
MASTER_KW = dict(chunks_per_task=2, timeout_s=8.0, worker_timeout_s=3.0,
                 auto_rotate=False, lease_timeout=6.0)


def _write_dataset(path, n=96, seed=0):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(DIM).astype(np.float32)
    recs = []
    for _ in range(n):
        x = rng.randn(DIM).astype(np.float32)
        recs.append(
            np.concatenate([x, [np.float32(x @ w_true)]])
            .astype(np.float32).tobytes()
        )
    recordio.write_records(path, iter(recs), max_chunk_records=4)


def _env(extra=None):
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", OMP_NUM_THREADS="1",
        OPENBLAS_NUM_THREADS="1", MKL_NUM_THREADS="1",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    if extra:
        env.update(extra)
    return env


def _spawn_workers(d, n, passes=PASSES, chaos_env=None):
    procs = []
    for i in range(n):
        extra = chaos_env.get(i) if chaos_env else None
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.trainer.elastic",
             "--dir", os.path.join(d, "ha"), "--worker-id", f"w{i}",
             "--num-passes", str(passes), "--model", "numpy",
             "--model-arg", f"dim={DIM}", "--model-arg", "lr=0.2",
             "--min-workers", str(n),
             "--rpc-retry-window-s", "40",
             "--checkpoint-dir", os.path.join(d, "ck"),
             "--stats-out", os.path.join(d, "stats-{worker}.json")],
            env=_env(extra), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        ))
    return procs


def _collect(d, n, procs, timeout=240):
    errs = {}
    rcs = []
    for i, p in enumerate(procs):
        _out, err = p.communicate(timeout=timeout)
        rcs.append(p.returncode)
        errs[i] = err.decode()[-2000:]
    stats = {}
    for i in range(n):
        p = os.path.join(d, f"stats-w{i}.json")
        if os.path.exists(p):
            with open(p) as f:
                stats[i] = json.load(f)
    restored = CheckpointManager(os.path.join(d, "ck")).restore_latest(
        NumpyLinearModel(DIM).state()
    )
    return rcs, errs, stats, restored


def _run_clean(d, n, passes=PASSES):
    os.makedirs(d, exist_ok=True)
    data = os.path.join(d, "data.rio")
    _write_dataset(data)
    ha = HAMaster(os.path.join(d, "ha"), [data], owner_id="ref", **MASTER_KW)
    ha.start()
    assert ha.wait_leader(30)
    try:
        rcs, errs, stats, restored = _collect(
            d, n, _spawn_workers(d, n, passes)
        )
        master_stats = ha.service.stats() if ha.service else None
    finally:
        ha.stop()
    assert rcs == [0] * n, errs
    return stats, restored, master_stats


def _journal_path(service):
    snap = json.load(open(service.snapshot_path))
    return os.path.join(
        os.path.dirname(service.snapshot_path), snap["journal_file"]
    )


def test_worker_partitioned_mid_pass_rejoins_bit_identical(tmp_path):
    """Drill 1: worker w1's link dies for 6s mid-run (egress dropped —
    heartbeats, acks, everything).  The master prunes it after the 3s
    registry lease; any held shard lease requeues to survivors; the pass
    fences release WITHOUT the dead member.  On heal the worker rejoins,
    catches up the passes it slept through, and every process exits 0
    with final params bit-for-bit vs the unfaulted reference."""
    _stats_ref, res_ref, mst_ref = _run_clean(str(tmp_path / "clean"), 4)
    assert res_ref is not None

    d = str(tmp_path / "partitioned")
    os.makedirs(d)
    data = os.path.join(d, "data.rio")
    _write_dataset(data)
    ha = HAMaster(os.path.join(d, "ha"), [data], owner_id="drill",
                  **MASTER_KW)
    ha.start()
    assert ha.wait_leader(30)
    chaos_env = {1: {
        "PADDLE_TPU_CHAOS": "net_partition@6",
        "PADDLE_TPU_NETEM_PARTITION_SECS": "6",
        "PADDLE_TPU_NETEM_DIRECTION": "send",
    }}
    try:
        rcs, errs, stats, restored = _collect(
            d, 4, _spawn_workers(d, 4, chaos_env=chaos_env), timeout=300,
        )
        master_stats = ha.service.stats()
        jpath = _journal_path(ha.service)
        jlint_rc = None
        from paddle_tpu.cli import cmd_lint

        jlint_rc = cmd_lint(["--journal", jpath])
    finally:
        ha.stop()

    # everyone — including the partitioned worker — exited clean
    assert rcs == [0, 0, 0, 0], errs
    # nothing lost: both passes fully acked, nothing discarded, the
    # queue state matches the unfaulted run's
    assert master_stats["n_done"] == TASKS_PER_PASS
    assert master_stats["n_todo"] == 0 and master_stats["n_pending"] == 0
    assert master_stats["n_discarded"] == 0
    assert master_stats["pass_id"] == mst_ref["pass_id"]
    # the fleet genuinely rode a membership change: the victim was pruned
    # (journaled leave) and/or its held lease requeued (fail event) —
    # read it from the durable record, not a guess
    from paddle_tpu import master_journal as mj

    records = []
    hadir = os.path.join(d, "ha")
    for fn in sorted(os.listdir(hadir)):
        if fn.startswith("master_journal-"):
            recs, _info = mj.read_records(os.path.join(hadir, fn))
            records.extend(r for _s, r in recs)
    pruned = [r for r in records if r.get("t") == "leave" and r.get("pruned")]
    rejoined = sum(1 for r in records if r.get("t") == "join"
                   and r.get("worker") == "w1")
    assert pruned or master_stats["fail_events"] >= 1 or rejoined >= 2, (
        "the partition left no membership trace — did it fire?"
    )
    # bit-for-bit final parameters vs the unfaulted fleet
    assert restored is not None
    assert np.array_equal(restored[1]["w"], res_ref[1]["w"])
    assert np.array_equal(restored[1]["b"], res_ref[1]["b"])
    # and the surviving journal lints clean
    assert jlint_rc == 0


def test_split_brain_asymmetric_partition_exactly_one_fenced_leader(tmp_path):
    """Drill 2 (the ISSUE 15 kill drill): asymmetric leader<->standby
    partition during an active pass.  The subprocess leader's lease
    renewals silently stop reaching shared storage (``stale_lease`` —
    writes partitioned, reads fine, RPC plane fully alive), the
    in-process standby campaigns and promotes WARM mid-run, and for up to
    one renew interval BOTH leaders serve.  Fencing must hold: exactly
    one leader at the end, zero tasks lost, final params bit-for-bit vs
    the unfaulted run, surviving journal clean."""
    for attempt in range(2):
        out = _split_brain_once(
            str(tmp_path / f"attempt{attempt}"), passes=8 + 4 * attempt
        )
        if out is not None:
            return  # drill proved itself
    pytest.fail("takeover never landed while the fleet was still running")


def _journal_ack_count(hadir):
    """Acked 'finish' records in the generation the published snapshot
    references — how deep into the pass the (doomed) leader is."""
    from paddle_tpu import master_journal as mj

    try:
        snap = json.load(open(os.path.join(hadir, "master_state.json")))
        jf = snap.get("journal_file")
        if not jf:
            return 0
        recs, _info = mj.read_records(os.path.join(hadir, jf))
    except (OSError, ValueError):
        return 0
    return sum(1 for _s, r in recs if r.get("t") == "finish")


def _split_brain_once(d, passes):
    _stats_ref, res_ref, mst_ref = _run_clean(
        os.path.join(d, "clean"), 4, passes=passes
    )
    drill = os.path.join(d, "drill")
    os.makedirs(drill)
    data = os.path.join(drill, "data.rio")
    _write_dataset(data)
    hadir = os.path.join(drill, "ha")
    # the doomed leader: every lease renewal silently no-ops (the
    # storage-side write partition), while it keeps serving RPC
    leader = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "master",
         "--dir", hadir, "--patterns", data,
         "--chunks-per-task", "2", "--timeout-s", "8",
         "--worker-timeout-s", "3", "--lease-timeout", "3",
         "--chaos", "stale_lease"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    standby = HAMaster(hadir, [data], owner_id="standby",
                       **{**MASTER_KW, "lease_timeout": 3.0})
    procs = []
    try:
        deadline = time.time() + 60
        while discover_endpoint(hadir) is None:
            assert leader.poll() is None, leader.stdout.read()[-2000:]
            assert time.time() < deadline, "no leader endpoint appeared"
            time.sleep(0.1)

        # every worker rides a 40ms-per-message net_delay: the hostile
        # network paces the fleet to REAL multi-second passes (a 2-core
        # box's numpy tasks are otherwise sub-millisecond and the whole
        # job outruns any second-scale campaign)
        delay_env = {
            i: {"PADDLE_TPU_CHAOS": "net_delay",
                "PADDLE_TPU_NETEM_DELAY_MS": "40"}
            for i in range(4)
        }
        procs = _spawn_workers(drill, 4, passes=passes,
                               chaos_env=delay_env)
        # hold the standby back until the fleet is genuinely MID-PASS
        # (acks landing in the leader's journal) AND the lease has gone
        # stale underneath the write-partitioned leader — then the
        # standby's first campaign tick wins and the takeover lands
        # while tasks are in flight, not in the boot window.
        deadline = time.time() + 120
        while (_journal_ack_count(hadir) < 6
               or not standby.lease.is_stale()):
            assert time.time() < deadline, "fleet never started acking"
            assert leader.poll() is None, "leader died early"
            time.sleep(0.05)
        # tail the (still-appending) journal into a live replica FIRST, so
        # the immediate campaign win promotes WARM instead of recovering
        # cold — the takeover must carry the in-flight leases
        standby._standby_tick()
        assert standby._replica is not None
        standby.start()
        rcs, errs, stats, restored = _collect(drill, 4, procs, timeout=300)
        t_fleet_done = time.time()
        took_over = standby.is_leader.is_set()
        takeover = dict(standby.last_takeover or {})
        master_stats = (
            standby.service.stats() if standby.service else None
        )
        jpath = (
            _journal_path(standby.service) if standby.service else None
        )
        lease_owner = standby.lease.current_owner()
        leader_alive = leader.poll() is None
        from paddle_tpu.cli import cmd_lint

        jlint_rc = cmd_lint(["--journal", jpath]) if jpath else None
    finally:
        standby.stop()
        if leader.poll() is None:
            leader.send_signal(signal.SIGTERM)
        try:
            leader_out, _ = leader.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            leader.kill()
            leader_out, _ = leader.communicate()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    assert rcs == [0] * 4, errs
    if not took_over or takeover.get("t_leader", 0) > t_fleet_done:
        return None  # fleet outran the campaign: retry with more passes
    # the takeover was warm (journal-tailed replica, not a cold restart)
    assert takeover["warm"] is True
    # EXACTLY ONE fenced leader: the standby owns the lease, the deposed
    # leader survived (stepped down to candidate, exited 0 on SIGTERM)
    assert lease_owner == "standby"
    assert leader_alive, leader_out[-2000:]
    assert leader.returncode == 0, leader_out[-2000:]
    # zero tasks LOST: every pass fully acked on the surviving leader,
    # nothing discarded (the dual-window may legitimately recompute a
    # task whose ack landed only in the zombie's generation — at-least-
    # once — but nothing may vanish)
    assert master_stats["n_done"] == TASKS_PER_PASS
    assert master_stats["n_todo"] == 0 and master_stats["n_pending"] == 0
    assert master_stats["n_discarded"] == 0
    assert master_stats["pass_id"] == mst_ref["pass_id"]
    total_acks = sum(s["tasks_done"] for s in stats.values())
    assert total_acks >= TASKS_PER_PASS * passes
    # bit-for-bit params vs the unfaulted fleet: the dual-leader window
    # corrupted NOTHING (deterministic recompute + epoch/pass guards)
    assert restored is not None
    assert np.array_equal(restored[1]["w"], res_ref[1]["w"])
    assert np.array_equal(restored[1]["b"], res_ref[1]["b"])
    # the surviving (standby-owned) journal generation lints clean
    assert jlint_rc == 0
    return True
