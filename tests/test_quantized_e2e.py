"""Quantized-collectives e2e A/B (slow tier; ISSUE 16 acceptance drills).

Four contracts of the ``quantized_allreduce`` tentpole, each against the
REAL training loop (paddle.trainer.SGD on the 8-device virtual mesh):

* OFF is bit-identical — the flag unset and explicitly False produce
  byte-equal trained params (no graph change whatsoever on the default
  path);
* ON converges — an MLP classifier and an LSTM text classifier both
  train to within tolerance of their f32 arms (round-to-nearest AND
  stochastic-rounding int8, plus the bf16 payload arm);
* serving int8 weight-only decode keeps the dequantization drift inside
  the ``serving_int8_drift_budget`` flag while shrinking resident weight
  bytes ~4x and raising slots-per-GB.

Every arm trains a real fleet of passes, so the module is slow-marked
(scripts/tier1_failset.py --slow-guard pins the whole file out of tier 1).
"""

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.utils import flags

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _clean():
    yield
    flags.reset_flags()


# ---------------------------------------------------------------------------
# arms
# ---------------------------------------------------------------------------

DIM, CLASSES = 32, 4


def _mlp_trainer(seed=0):
    reset_auto_names()
    paddle.init(seed=seed)
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(DIM))
    label = paddle.layer.data(
        "label", paddle.data_type.integer_value(CLASSES)
    )
    h = paddle.layer.fc(img, size=24, act=paddle.activation.Relu(),
                        name="h1")
    pred = paddle.layer.fc(h, size=CLASSES,
                           act=paddle.activation.Softmax(), name="out")
    cost = paddle.layer.classification_cost(input=pred, label=label)
    return paddle.trainer.SGD(
        cost=cost,
        parameters=paddle.parameters.create(cost, seed=seed),
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9
        ),
        mesh=make_mesh(data=-1, model=1),  # the data-parallel mesh the
        # quantized collective replaces the implicit psum on
    )


def _mlp_reader(n_batches=10, rows=16):
    """Learnable synthetic task: the label is the argmax of a fixed random
    projection, so the cost has real signal to descend."""
    rng = np.random.RandomState(11)
    w_true = rng.randn(DIM, CLASSES).astype(np.float32)
    xs = rng.randn(n_batches * rows, DIM).astype(np.float32)
    ys = np.argmax(xs @ w_true, axis=1)

    def read():
        for v, y in zip(xs, ys):
            yield v, int(y)

    return paddle.batch(read, rows)


def _train_costs(trainer, reader, num_passes=3):
    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(float(e.cost))

    trainer.train(reader, num_passes=num_passes, event_handler=handler)
    return costs


def _final_params(trainer):
    return {
        k: np.asarray(v)
        for k, v in jax.tree_util.tree_leaves_with_path(
            jax.device_get(trainer.parameters.params)
        )
    }


def _run_mlp_arm(num_passes=3, seed=0):
    t = _mlp_trainer(seed=seed)
    costs = _train_costs(t, _mlp_reader(), num_passes)
    return costs, _final_params(t)


# ---------------------------------------------------------------------------
# OFF bit-identity
# ---------------------------------------------------------------------------


def test_quantized_off_is_bit_identical():
    """Flag unset (the historical default) and explicitly False trace the
    SAME graph: trained params are byte-equal."""
    costs_default, p_default = _run_mlp_arm(num_passes=2)
    flags.set_flag("quantized_allreduce", False)
    costs_off, p_off = _run_mlp_arm(num_passes=2)
    assert costs_default == costs_off
    assert p_default.keys() == p_off.keys()
    for k in p_default:
        assert np.array_equal(p_default[k], p_off[k]), k


# ---------------------------------------------------------------------------
# ON: convergence A/B
# ---------------------------------------------------------------------------


def _assert_converged_close(costs_f32, costs_q):
    head_f, tail_f = np.mean(costs_f32[:4]), np.mean(costs_f32[-4:])
    head_q, tail_q = np.mean(costs_q[:4]), np.mean(costs_q[-4:])
    assert tail_f < head_f * 0.7, (head_f, tail_f)
    assert tail_q < head_q * 0.7, (head_q, tail_q)  # quantized arm learns
    # A/B tolerance: the quantized trajectory lands in the same cost
    # neighborhood (block-scaled int8 error is ~amax/254 per element)
    assert abs(tail_q - tail_f) < 0.25 * max(head_f - tail_f, 1e-6), (
        tail_f, tail_q,
    )


@pytest.mark.parametrize(
    "payload,stochastic",
    [("int8", False), ("int8", True), ("bfloat16", False)],
    ids=["int8", "int8-stochastic", "bf16"],
)
def test_mlp_convergence_ab(payload, stochastic):
    costs_f32, p_f32 = _run_mlp_arm()
    flags.set_flag("quantized_allreduce", True)
    flags.set_flag("quantize_payload_dtype", payload)
    flags.set_flag("quantize_stochastic_rounding", stochastic)
    costs_q, p_q = _run_mlp_arm()
    _assert_converged_close(costs_f32, costs_q)
    # the flag really switched the collective: trajectories differ
    assert any(
        not np.array_equal(p_f32[k], p_q[k]) for k in p_f32
    )


def _lstm_trainer(vocab, seed=0):
    reset_auto_names()
    paddle.init(seed=seed)
    words = paddle.layer.data(
        "word", paddle.data_type.integer_value_sequence(vocab)
    )
    emb = paddle.layer.embedding(input=words, size=8)
    lstm = paddle.layer.networks.simple_lstm(input=emb, size=12)
    last = paddle.layer.last_seq(input=lstm)
    pred = paddle.layer.fc(last, size=2, act=paddle.activation.Softmax())
    label = paddle.layer.data("label", paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=pred, label=label)
    return paddle.trainer.SGD(
        cost=cost,
        parameters=paddle.parameters.create(cost, seed=seed),
        update_equation=paddle.optimizer.Adam(learning_rate=2e-2),
        mesh=make_mesh(data=-1, model=1),
    )


def _lstm_reader(vocab, n_batches=8, rows=16, seq_len=10):
    """Label = whether the LAST token is in the top half of the vocab —
    exactly what last_seq over an LSTM can learn quickly."""
    rng = np.random.RandomState(13)
    data = []
    for _ in range(n_batches * rows):
        seq = rng.randint(2, vocab, size=seq_len).tolist()
        data.append((seq, int(seq[-1] >= vocab // 2)))

    def read():
        for row in data:
            yield row

    return paddle.batch(read, rows)


def test_lstm_convergence_ab():
    vocab = 50

    def arm():
        t = _lstm_trainer(vocab)
        return _train_costs(t, _lstm_reader(vocab), num_passes=8)

    costs_f32 = arm()
    flags.set_flag("quantized_allreduce", True)
    costs_q = arm()
    _assert_converged_close(costs_f32, costs_q)


# ---------------------------------------------------------------------------
# serving int8 weight-only decode
# ---------------------------------------------------------------------------


def test_serving_int8_drift_and_capacity():
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
    from paddle_tpu.serving import ServingEngine

    V, E, H, MAXLEN = 96, 24, 32, 12

    def build(int8):
        reset_auto_names()
        cost, _ = seq2seq_cost(V, V, word_dim=E, hidden_dim=H)
        params = paddle.parameters.create(cost, seed=7)
        gen = Seq2SeqGenerator(
            params, V, V, word_dim=E, hidden_dim=H,
            bos_id=0, eos_id=1, max_length=MAXLEN,
        )
        return ServingEngine(gen, max_slots=8, hbm_budget_mb=2,
                             max_new_tokens=MAXLEN, int8_weights=int8)

    f32 = build(False)
    q8 = build(True)
    assert not f32.int8_weights and q8.int8_weights

    budget = float(flags.get_flag("serving_int8_drift_budget"))
    drift = q8.weight_drift()
    assert 0.0 < drift < budget, (drift, budget)
    assert f32.weight_drift() == 0.0

    # resident weight bytes shrink ~4x; decode slots per GB go UP
    assert f32.weight_bytes > 2.5 * q8.weight_bytes
    assert q8.slots_per_gb(16) > f32.slots_per_gb(16)

    # the quantized engine still decodes: every request completes, and
    # most outputs match the f32 argmax (ties may legitimately flip)
    rng = np.random.RandomState(3)
    srcs = [rng.randint(2, V, size=6).tolist() for _ in range(6)]
    outs_f = [f32.reference_decode(s, MAXLEN) for s in srcs]
    outs_q = [q8.reference_decode(s, MAXLEN) for s in srcs]
    assert all(len(o) > 0 for o in outs_q)
    same = sum(a == b for a, b in zip(outs_f, outs_q))
    assert same >= len(srcs) // 2, (same, len(srcs))

    summ = q8.summary()
    assert summ["int8_weights"] is True
    assert summ["weight_bytes"] == q8.weight_bytes
    assert summ["slots_per_gb"] > 0
