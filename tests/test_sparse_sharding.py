"""Sparse/embedding sharding over the model mesh axis (reference model:
paddle/trainer/tests/test_CompareSparse.cpp — sparse-remote training must
converge identically to local dense training)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.parallel.mesh import MODEL_AXIS, make_mesh
from paddle_tpu.parallel.sharding import has_model_sharding, param_shardings

VOCAB = 64
EMB = 16
CLASSES = 4


def _topology(sparse: bool, shard_fc: bool = False):
    reset_auto_names()
    word = paddle.layer.data(
        name="word", type=paddle.data_type.integer_value_sequence(VOCAB)
    )
    label = paddle.layer.data(name="label", type=paddle.data_type.integer_value(CLASSES))
    emb = paddle.layer.embedding(
        input=word,
        size=EMB,
        param_attr=paddle.attr.ParamAttr(sparse_update=sparse),
    )
    pooled = paddle.layer.pooling(
        input=emb, pooling_type=paddle.pooling.Avg()
    )
    fc_attr = (
        paddle.attr.ExtraAttr(shard_axis=MODEL_AXIS) if shard_fc else None
    )
    hidden = paddle.layer.fc(
        input=pooled, size=32, act=paddle.activation.Relu(), layer_attr=fc_attr
    )
    pred = paddle.layer.fc(input=hidden, size=CLASSES, act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)
    return cost


def _reader(n=96, seed=0):
    """Sequences whose label depends on which vocab half dominates."""
    rng_w = np.random.RandomState(42)
    cls_words = [rng_w.randint(0, VOCAB, size=8) for _ in range(CLASSES)]

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(CLASSES))
            length = int(rng.randint(4, 12))
            words = [int(cls_words[label][rng.randint(8)]) for _ in range(length)]
            yield words, label

    return reader


def _train(mesh, sparse, shard_fc=False, passes=3, seed=5):
    cost = _topology(sparse, shard_fc)
    params = paddle.parameters.create(cost, seed=seed)
    trainer = paddle.trainer.SGD(
        cost=cost,
        parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05),
        mesh=mesh,
    )
    costs = []
    trainer.train(
        reader=paddle.batch(_reader(), 16),
        num_passes=passes,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    return trainer, costs


def test_sharding_specs_derived_from_attrs():
    cost = _topology(sparse=True, shard_fc=True)
    params = paddle.parameters.create(cost, seed=0)
    mesh = make_mesh(data=2, model=4)
    net = params.network
    assert has_model_sharding(net, params.params, mesh)
    specs = param_shardings(net, params.params, mesh)
    emb_name = next(n for n in specs if "embedding" in n)
    emb_spec = specs[emb_name]["w"].spec
    assert emb_spec[0] == MODEL_AXIS  # rows sharded
    fc_name = next(n for n in specs if "fc_layer" in n)
    assert tuple(specs[fc_name]["w0"].spec) == (None, MODEL_AXIS)


def test_dense_has_no_model_sharding():
    cost = _topology(sparse=False)
    params = paddle.parameters.create(cost, seed=0)
    mesh = make_mesh(data=8, model=1)
    assert not has_model_sharding(params.network, params.params, mesh)


def test_sharded_table_is_actually_distributed():
    mesh = make_mesh(data=2, model=4)
    trainer, _ = _train(mesh, sparse=True, passes=1)
    emb_name = next(
        n for n in trainer.parameters.params if "embedding" in n
    )
    table = trainer.parameters.params[emb_name]["w"]
    # each model-axis shard holds VOCAB/4 rows
    shard_shape = table.sharding.shard_shape(table.shape)
    assert shard_shape[0] == VOCAB // 4
    assert shard_shape[1] == EMB


def test_sparse_sharded_matches_dense_numerics():
    """The CompareSparse golden: row-sharded training == replicated training."""
    mesh_dense = make_mesh(data=2, model=4)
    t_dense, c_dense = _train(mesh_dense, sparse=False, passes=2)
    t_sparse, c_sparse = _train(mesh_dense, sparse=True, passes=2)
    np.testing.assert_allclose(c_dense, c_sparse, rtol=2e-4, atol=2e-5)
    for name in t_dense.parameters.names():
        np.testing.assert_allclose(
            np.asarray(t_dense.parameters.get(name)),
            np.asarray(t_sparse.parameters.get(name)),
            rtol=2e-4,
            atol=2e-5,
        )


def test_column_parallel_fc_matches():
    mesh = make_mesh(data=2, model=4)
    _, c_plain = _train(mesh, sparse=False, shard_fc=False, passes=2)
    _, c_shard = _train(mesh, sparse=True, shard_fc=True, passes=2)
    np.testing.assert_allclose(c_plain, c_shard, rtol=2e-4, atol=2e-5)


def test_sharded_training_learns():
    mesh = make_mesh(data=2, model=4)
    _, costs = _train(mesh, sparse=True, shard_fc=True, passes=6)
    assert costs[-1] < 0.5 * costs[0], (costs[0], costs[-1])
