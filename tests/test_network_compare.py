"""Network-compare tests (reference test strategy: gserver/tests/
test_NetworkCompare.cpp + test_RecurrentLayer.cpp — two equivalent
configurations must produce identical outputs).  Here: the recurrent_group
compositions (gru_group / lstmemory_group) vs the fused single-scan layers
(grumemory / lstmemory) with tied parameters, on variable-length batches."""

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import activation as A
from paddle_tpu.core.batch import SeqTensor, seq as mkseq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu.layers import networks
import paddle_tpu.layers as L

H = 6
B, T = 3, 5


LENS = np.asarray([T, 3, 1], np.int32)


def _var_len_batch(dim, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(B, T, dim).astype(np.float32)
    for i, n in enumerate(LENS):
        x[i, n:] = 0.0
    return mkseq(x, LENS)


def _assert_valid_close(a, b):
    """Compare only the VALID timesteps — the two forms differ in what they
    leave in padding (zeros vs carried state), which no downstream masked
    layer ever reads."""
    mask = (np.arange(T)[None, :] < LENS[:, None])[..., None]
    np.testing.assert_allclose(
        np.asarray(a) * mask, np.asarray(b) * mask, rtol=1e-5, atol=1e-6
    )


def _single_subparam(params, group_name):
    """The one param-bearing inner layer of a group's sub-topology."""
    sub = params[group_name]
    assert len(sub) == 1, f"expected one inner param layer, got {list(sub)}"
    return next(iter(sub.values()))


@pytest.mark.parametrize("reverse", [False, True])
def test_gru_group_matches_fused_grumemory(reverse):
    reset_auto_names()
    din = L.data("x", paddle.data_type.dense_vector_sequence(3 * H))
    fused = L.grumemory(din, size=H, reverse=reverse, name="fused")
    group = networks.gru_group(din, size=H, reverse=reverse, name="group")
    net = CompiledNetwork(Topology([fused, group]))
    params, state = net.init(jax.random.PRNGKey(0))

    # tie the group's step params (w_h [H,2H], w_c [H,H], b [3H]) to the
    # fused layer's — identical layout by design
    inner = _single_subparam(params, "group")
    for k in ("w_h", "w_c", "b"):
        inner[k] = params["fused"][k]

    batch = {"x": _var_len_batch(3 * H)}
    outs, _ = net.apply(params, batch, state=state, train=False)
    _assert_valid_close(outs["group"].data, outs["fused"].data)


@pytest.mark.parametrize("reverse", [False, True])
def test_lstm_group_matches_fused_lstmemory_without_peepholes(reverse):
    """lstmemory_group (mixed recurrence + weightless lstm_step) equals the
    fused lstmemory when the fused peepholes are zeroed (the reference
    lstm_step form has no peepholes — lstm_step_layer docs)."""
    reset_auto_names()
    din = L.data("x", paddle.data_type.dense_vector_sequence(4 * H))
    fused = L.lstmemory(din, size=H, reverse=reverse, name="fused")
    group = networks.lstmemory_group(din, size=H, reverse=reverse, name="group")
    net = CompiledNetwork(Topology([fused, group]))
    params, state = net.init(jax.random.PRNGKey(0))

    for k in ("w_ci", "w_cf", "w_co"):
        params["fused"][k] = np.zeros_like(params["fused"][k])
    # group inner layers: the mixed input_recurrent (p1_w = W_h) and the
    # lstm_step (b)
    sub = params["group"]
    mixed_name = [n for n in sub if "input_recurrent" in n][0]
    step_name = [n for n in sub if n != mixed_name][0]
    sub[mixed_name]["p1_w"] = params["fused"]["w_h"]
    sub[step_name]["b"] = params["fused"]["b"]

    batch = {"x": _var_len_batch(4 * H, seed=1)}
    outs, _ = net.apply(params, batch, state=state, train=False)
    _assert_valid_close(outs["group"].data, outs["fused"].data)


def test_simple_gru_matches_simple_gru2():
    """simple_gru (recurrent_group form) and simple_gru2 (fused form) are
    the same function of the same parameters (reference networks.py doc:
    'gru_memory ... does same calculation with gru_group')."""
    reset_auto_names()
    din = L.data("x", paddle.data_type.dense_vector_sequence(4))
    g1 = networks.simple_gru(din, size=H, name="a")
    g2 = networks.simple_gru2(din, size=H, name="b")
    net = CompiledNetwork(Topology([g1, g2]))
    params, state = net.init(jax.random.PRNGKey(0))

    params["b_transform"]["w0"] = params["a_transform"]["w0"]
    inner = _single_subparam(params, "a")
    for k in ("w_h", "w_c", "b"):
        params["b"][k] = inner[k]

    batch = {"x": _var_len_batch(4, seed=2)}
    outs, _ = net.apply(params, batch, state=state, train=False)
    _assert_valid_close(outs["a"].data, outs["b"].data)


def test_mixed_sharing_registries_cannot_cross():
    """A parameter name used both whole-layer (embedding) and per-key
    (fc/projection) must fail loudly at build, not silently diverge."""
    reset_auto_names()
    from paddle_tpu.attr import ParamAttr

    shared = ParamAttr(name="tied")
    ids = L.data("ids", paddle.data_type.integer_value_sequence(7))
    emb = L.embedding(ids, size=4, param_attr=shared)
    vec = L.data("v", paddle.data_type.dense_vector(7))
    fcw = L.fc(vec, size=4, param_attr=shared, bias_attr=False)
    with pytest.raises(ValueError, match="whole-layer"):
        CompiledNetwork(Topology([emb, fcw]))
