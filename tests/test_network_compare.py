"""Network-compare tests (reference test strategy: gserver/tests/
test_NetworkCompare.cpp + test_RecurrentLayer.cpp — two equivalent
configurations must produce identical outputs).  Here: the recurrent_group
compositions (gru_group / lstmemory_group) vs the fused single-scan layers
(grumemory / lstmemory) with tied parameters, on variable-length batches."""

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import activation as A
from paddle_tpu.core.batch import SeqTensor, seq as mkseq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names
from paddle_tpu.layers import networks
import paddle_tpu.layers as L

H = 6
B, T = 3, 5


LENS = np.asarray([T, 3, 1], np.int32)


def _var_len_batch(dim, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(B, T, dim).astype(np.float32)
    for i, n in enumerate(LENS):
        x[i, n:] = 0.0
    return mkseq(x, LENS)


def _assert_valid_close(a, b):
    """Compare only the VALID timesteps — the two forms differ in what they
    leave in padding (zeros vs carried state), which no downstream masked
    layer ever reads."""
    mask = (np.arange(T)[None, :] < LENS[:, None])[..., None]
    np.testing.assert_allclose(
        np.asarray(a) * mask, np.asarray(b) * mask, rtol=1e-5, atol=1e-6
    )


def _single_subparam(params, group_name):
    """The one param-bearing inner layer of a group's sub-topology."""
    sub = params[group_name]
    assert len(sub) == 1, f"expected one inner param layer, got {list(sub)}"
    return next(iter(sub.values()))


@pytest.mark.parametrize("reverse", [False, True])
def test_gru_group_matches_fused_grumemory(reverse):
    reset_auto_names()
    din = L.data("x", paddle.data_type.dense_vector_sequence(3 * H))
    fused = L.grumemory(din, size=H, reverse=reverse, name="fused")
    group = networks.gru_group(din, size=H, reverse=reverse, name="group")
    net = CompiledNetwork(Topology([fused, group]))
    params, state = net.init(jax.random.PRNGKey(0))

    # tie the group's step params (w_h [H,2H], w_c [H,H], b [3H]) to the
    # fused layer's — identical layout by design
    inner = _single_subparam(params, "group")
    for k in ("w_h", "w_c", "b"):
        inner[k] = params["fused"][k]

    batch = {"x": _var_len_batch(3 * H)}
    outs, _ = net.apply(params, batch, state=state, train=False)
    _assert_valid_close(outs["group"].data, outs["fused"].data)


@pytest.mark.parametrize("reverse", [False, True])
def test_lstm_group_matches_fused_lstmemory_without_peepholes(reverse):
    """lstmemory_group (mixed recurrence + weightless lstm_step) equals the
    fused lstmemory when the fused peepholes are zeroed (the reference
    lstm_step form has no peepholes — lstm_step_layer docs)."""
    reset_auto_names()
    din = L.data("x", paddle.data_type.dense_vector_sequence(4 * H))
    fused = L.lstmemory(din, size=H, reverse=reverse, name="fused")
    group = networks.lstmemory_group(din, size=H, reverse=reverse, name="group")
    net = CompiledNetwork(Topology([fused, group]))
    params, state = net.init(jax.random.PRNGKey(0))

    for k in ("w_ci", "w_cf", "w_co"):
        params["fused"][k] = np.zeros_like(params["fused"][k])
    # group inner layers: the mixed input_recurrent (p1_w = W_h) and the
    # lstm_step (b)
    sub = params["group"]
    mixed_name = [n for n in sub if "input_recurrent" in n][0]
    step_name = [n for n in sub if n != mixed_name][0]
    sub[mixed_name]["p1_w"] = params["fused"]["w_h"]
    sub[step_name]["b"] = params["fused"]["b"]

    batch = {"x": _var_len_batch(4 * H, seed=1)}
    outs, _ = net.apply(params, batch, state=state, train=False)
    _assert_valid_close(outs["group"].data, outs["fused"].data)


def test_simple_gru_matches_simple_gru2():
    """simple_gru (recurrent_group form) and simple_gru2 (fused form) are
    the same function of the same parameters (reference networks.py doc:
    'gru_memory ... does same calculation with gru_group')."""
    reset_auto_names()
    din = L.data("x", paddle.data_type.dense_vector_sequence(4))
    g1 = networks.simple_gru(din, size=H, name="a")
    g2 = networks.simple_gru2(din, size=H, name="b")
    net = CompiledNetwork(Topology([g1, g2]))
    params, state = net.init(jax.random.PRNGKey(0))

    params["b_transform"]["w0"] = params["a_transform"]["w0"]
    inner = _single_subparam(params, "a")
    for k in ("w_h", "w_c", "b"):
        params["b"][k] = inner[k]

    batch = {"x": _var_len_batch(4, seed=2)}
    outs, _ = net.apply(params, batch, state=state, train=False)
    _assert_valid_close(outs["a"].data, outs["b"].data)


def test_mixed_sharing_registries_cannot_cross():
    """A parameter name used both whole-layer (embedding) and per-key
    (fc/projection) must fail loudly at build, not silently diverge."""
    reset_auto_names()
    from paddle_tpu.attr import ParamAttr

    shared = ParamAttr(name="tied")
    ids = L.data("ids", paddle.data_type.integer_value_sequence(7))
    emb = L.embedding(ids, size=4, param_attr=shared)
    vec = L.data("v", paddle.data_type.dense_vector(7))
    fcw = L.fc(vec, size=4, param_attr=shared, bias_attr=False)
    with pytest.raises(ValueError, match="whole-layer"):
        CompiledNetwork(Topology([emb, fcw]))


# ---------------------------------------------------------------------------
# The reference's OWN NetworkCompare fixtures (gserver/tests/*.conf pairs,
# driver: test_NetworkCompare.cpp) — two config files that must compute the
# same function.  We parse both unmodified, tie parameters by signature,
# and require numerically equal outputs.
# ---------------------------------------------------------------------------

GSERVER = "/root/reference/paddle/gserver/tests"


def _param_dicts(tree):
    """Innermost param dicts (those holding arrays) in deterministic
    traversal order."""
    out = []

    def walk(d):
        if not isinstance(d, dict):
            return
        if any(not isinstance(v, dict) for v in d.values()):
            out.append(d)
        for v in d.values():
            walk(v)

    walk(tree)
    return out


def _tie_by_signature(src_tree, dst_tree):
    """Copy src param values into dst, pairing innermost param dicts by
    their shape multiset in traversal order (key NAMES differ across
    equivalent forms: fc 'w0' vs mixed 'p0_w')."""
    src = _param_dicts(src_tree)
    dst = _param_dicts(dst_tree)

    def sig(d):
        return tuple(sorted(np.shape(v) for v in d.values()))

    def ordered_keys(d):
        return [k for _, k in sorted((np.shape(d[k]), k) for k in d)]

    unused = list(src)
    for d in dst:
        i = next(j for j, s in enumerate(unused) if sig(s) == sig(d))
        s = unused.pop(i)
        for dk, sk in zip(ordered_keys(d), ordered_keys(s)):
            d[dk] = s[sk]


def _build(conf_path, config_args=""):
    import os

    from paddle_tpu.v1_compat import parse_config

    old = os.getcwd()
    os.chdir("/root/reference/paddle")  # configs open data files relatively
    try:
        p = parse_config(conf_path, config_args)
    finally:
        os.chdir(old)
    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    return p, net, params, state


@pytest.mark.parametrize(
    "pair",
    ["concat_dotmul", "concat_fullmatrix", "concat_slice", "concat_table",
     "img_pool"],
)
def test_reference_network_compare_pairs(pair):
    reset_auto_names()
    pa, neta, params_a, state_a = _build(f"{GSERVER}/{pair}_a.conf")
    reset_auto_names()
    pb, netb, params_b, state_b = _build(f"{GSERVER}/{pair}_b.conf")
    _tie_by_signature(params_a, params_b)

    rng = np.random.RandomState(0)
    size = next(iter(pa.topology.data_layers().values())).size
    name = next(iter(pa.topology.data_layers()))
    if pair == "concat_table":
        x = rng.randint(0, size, size=(4, 1)).astype(np.int32)
    else:
        x = rng.randn(4, size).astype(np.float32)
    batch = {name: SeqTensor(x)}
    outs_a, _ = neta.apply(params_a, batch, state=state_a, train=False)
    outs_b, _ = netb.apply(params_b, batch, state=state_b, train=False)
    for oa, ob in zip(pa.output_layers, pb.output_layers):
        np.testing.assert_allclose(
            np.asarray(outs_a[oa].data),
            np.asarray(outs_b[ob].data),
            rtol=1e-5,
            atol=1e-6,
        )


def test_reference_nested_rnn_equals_flat_rnn():
    """sequence_nest_rnn.conf vs sequence_rnn.conf (reference
    test_RecurrentGradientMachine): the hierarchical RNN whose inner memory
    boots from the previous subsequence's last state computes exactly the
    flat RNN over the concatenated tokens."""
    from paddle_tpu.reader.feeder import DataFeeder

    reset_auto_names()
    pn, netn, params_n, state_n = _build(f"{GSERVER}/sequence_nest_rnn.conf")
    reset_auto_names()
    pf, netf, params_f, state_f = _build(f"{GSERVER}/sequence_rnn.conf")
    _tie_by_signature(params_f, params_n)

    nested_rows = [
        ([[1, 3, 2], [4, 5, 2]], 0),
        ([[0, 2], [2, 5], [0, 1, 2]], 1),
    ]
    flat_rows = [
        ([t for sub in row for t in sub], lab) for row, lab in nested_rows
    ]
    fn = DataFeeder(pn.topology.data_types())
    ff = DataFeeder(pf.topology.data_types())
    outs_n, _ = netn.apply(params_n, fn(nested_rows), state=state_n, train=False)
    outs_f, _ = netf.apply(params_f, ff(flat_rows), state=state_f, train=False)
    cost_n = np.asarray(outs_n[pn.output_layers[0]].data)
    cost_f = np.asarray(outs_f[pf.output_layers[0]].data)
    np.testing.assert_allclose(cost_n, cost_f, rtol=1e-5, atol=1e-6)


def test_gru_group_partial_sharing_named_weight_unnamed_bias():
    """ADVICE r2 (medium): a named recurrent param + unnamed default bias
    must share the WEIGHTS across groups (per-key, like the reference's
    global parameter table) while each group keeps its own bias."""
    reset_auto_names()
    pa = paddle.attr.ParamAttr(name="shared_gru_w")
    din = L.data("x", paddle.data_type.dense_vector_sequence(3 * H))
    g1 = networks.gru_group(din, size=H, name="g1", gru_param_attr=pa)
    g2 = networks.gru_group(din, size=H, name="g2", gru_param_attr=pa)
    net = CompiledNetwork(Topology([g1, g2]))
    params, state = net.init(jax.random.PRNGKey(0))

    # g1 owns the named weights; g2's subtree keeps ONLY its own bias
    p1 = params["g1"]["g1_unit"]
    p2 = params["g2"]["g2_unit"]
    assert "w_h" in p1 and "w_c" in p1 and "b" in p1
    assert "w_h" not in p2 and "w_c" not in p2 and "b" in p2

    # with equal biases the two groups compute identically (same weights)
    params["g2"]["g2_unit"]["b"] = params["g1"]["g1_unit"]["b"]
    batch = {"x": _var_len_batch(3 * H)}
    outs, _ = net.apply(params, batch, state=state, train=False)
    _assert_valid_close(outs["g1"].data, outs["g2"].data)

    # ...and with different biases they diverge (biases are NOT shared)
    params["g2"]["g2_unit"]["b"] = params["g1"]["g1_unit"]["b"] + 1.0
    outs2, _ = net.apply(params, batch, state=state, train=False)
    a = np.asarray(outs2["g1"].data)
    b = np.asarray(outs2["g2"].data)
    assert not np.allclose(a[:, :1], b[:, :1], rtol=1e-5, atol=1e-6)


def test_inner_group_param_shares_with_outer_layer():
    """Per-key sharing crosses the group boundary in both directions: an fc
    OUTSIDE a group and the gru_step INSIDE one can't collide, but a named
    bias ties an outer fc bias to the in-group step bias (global table)."""
    reset_auto_names()
    bname = paddle.attr.ParamAttr(name="tied_bias")
    din = L.data("x", paddle.data_type.dense_vector_sequence(3 * H))
    outer = L.fc(
        L.first_seq(din), size=3 * H, bias_attr=bname, act=A.Identity(),
        name="outer_fc",
    )
    g = networks.gru_group(din, size=H, name="g", gru_bias_attr=bname)
    net = CompiledNetwork(Topology([outer, g]))
    params, _ = net.init(jax.random.PRNGKey(0))
    # owner: outer_fc (earlier in order); the group's step bias is grafted
    assert "b" in params["outer_fc"]
    assert "b" not in params.get("g", {}).get("g_unit", {})


def test_gru_fused_and_naive_share_reference_recurrence():
    """GruStepLayer.cpp and gru_step_naive_layer lower to the SAME GruCompute
    recurrence in the reference (hl_gru_ops.cuh gru_resetOutput/
    gru_finalOutput, hl_cpu_gru.cuh:238-253): c = act(x_c + (r⊙h₋)·W_c),
    h = (1-u)⊙h₋ + u⊙c.  With identical params both paths must produce
    identical outputs, and both must match a numpy transcription of the
    reference formula."""
    reset_auto_names()
    din = L.data("x", paddle.data_type.dense_vector_sequence(3 * H))
    fused = networks.gru_group(din, size=H, name="fused")
    naive = networks.gru_group(din, size=H, name="naive", naive=True)
    net = CompiledNetwork(Topology([fused, naive]))
    params, state = net.init(jax.random.PRNGKey(2))
    params["naive"]["naive_unit"] = jax.tree_util.tree_map(
        lambda x: x, params["fused"]["fused_unit"]
    )
    batch = {"x": _var_len_batch(3 * H, seed=3)}
    outs, _ = net.apply(params, batch, state=state, train=False)

    # same params, SAME math (reference checkpoints produce identical
    # outputs whichever layer type a config uses)
    _assert_valid_close(outs["fused"].data, np.asarray(outs["naive"].data))

    # numpy transcription of the reference GruCompute formula
    p = jax.tree_util.tree_map(np.asarray, params["naive"]["naive_unit"])
    x = np.asarray(batch["x"].data)
    h_prev = np.zeros((B, H), np.float32)
    want = np.zeros((B, T, H), np.float32)
    for t in range(T):
        xt = x[:, t] + p["b"]
        x_u, x_r, x_c = np.split(xt, 3, axis=-1)
        ur = h_prev @ p["w_h"]
        u = 1.0 / (1.0 + np.exp(-(x_u + ur[:, :H])))
        r = 1.0 / (1.0 + np.exp(-(x_r + ur[:, H:])))
        c = np.tanh(x_c + (r * h_prev) @ p["w_c"])
        h_t = (1.0 - u) * h_prev + u * c
        alive = (t < LENS)[:, None]
        h_prev = np.where(alive, h_t, h_prev)
        want[:, t] = h_prev
    _assert_valid_close(outs["naive"].data, want)
    _assert_valid_close(outs["fused"].data, want)


def test_gru_naive_named_param_ties_three_blocks():
    """Reference gru_step_naive_layer with a NAMED param_attr hands the same
    name to all three full_matrix_projections — one shared H×H recurrent
    matrix.  naive=True + ParamAttr(name=...) must build a single tied `w`
    and match the formula with U_u = U_r = W_c = w."""
    from paddle_tpu.layers.recurrent_group import memory, recurrent_group

    reset_auto_names()
    din = L.data("x", paddle.data_type.dense_vector_sequence(3 * H))

    def step(ipt):
        mem = memory(name="tied_out", size=H)
        return L.gru_step(
            input=ipt,
            output_mem=mem,
            size=H,
            naive=True,
            param_attr=paddle.attr.ParamAttr(name="shared_w"),
            name="tied_out",
        )

    out = recurrent_group(step=step, input=din, name="tied_grp")
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(4))
    leaves, _ = jax.tree_util.tree_flatten(params)
    # one H×H recurrent weight + one 3H bias — no w_h/w_c pair
    shapes = sorted(tuple(l.shape) for l in leaves)
    assert (H, H) in shapes and (H, 2 * H) not in shapes, shapes

    batch = {"x": _var_len_batch(3 * H, seed=5)}
    outs, _ = net.apply(params, batch, state=state, train=False)
    flat = {
        "/".join(map(str, path)): np.asarray(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            params, is_leaf=lambda x: hasattr(x, "shape")
        )[0]
    }
    w = next(v for v in flat.values() if v.shape == (H, H))
    b = next((v for v in flat.values() if v.shape == (3 * H,)), None)
    x = np.asarray(batch["x"].data)
    h_prev = np.zeros((B, H), np.float32)
    want = np.zeros((B, T, H), np.float32)
    for t in range(T):
        xt = x[:, t] + (b if b is not None else 0.0)
        x_u, x_r, x_c = np.split(xt, 3, axis=-1)
        hw = h_prev @ w
        u = 1.0 / (1.0 + np.exp(-(x_u + hw)))
        r = 1.0 / (1.0 + np.exp(-(x_r + hw)))
        c = np.tanh(x_c + (r * h_prev) @ w)
        h_t = (1.0 - u) * h_prev + u * c
        alive = (t < LENS)[:, None]
        h_prev = np.where(alive, h_t, h_prev)
        want[:, t] = h_prev
    _assert_valid_close(outs["tied_grp"].data, want)


def test_two_inner_declarers_chain_to_outer_owner():
    """Two in-group layers declaring the SAME global name while the owner is
    an outer layer: the group's sub-network chains the second to the first,
    the first grafts from the outer owner — no KeyError, one storage."""
    from paddle_tpu.layers.recurrent_group import memory, recurrent_group

    reset_auto_names()
    bname = paddle.attr.ParamAttr(name="tri_bias")
    din = L.data("x", paddle.data_type.dense_vector_sequence(3 * H))
    outer = L.fc(
        L.first_seq(din), size=3 * H, bias_attr=bname, act=A.Identity(),
        name="owner_fc",
    )

    def step(x):
        m1 = memory(name="s1", size=H)
        m2 = memory(name="s2", size=H)
        s1 = L.gru_step(x, output_mem=m1, size=H, bias_attr=bname, name="s1")
        s2 = L.gru_step(x, output_mem=m2, size=H, bias_attr=bname, name="s2")
        return L.addto([s1, s2], act=A.Identity(), name="both")

    g = recurrent_group(step=step, input=din, name="g")
    net = CompiledNetwork(Topology([outer, g]))
    params, state = net.init(jax.random.PRNGKey(0))
    assert "b" in params["owner_fc"]
    assert "b" not in params.get("g", {}).get("s1", {})
    assert "b" not in params.get("g", {}).get("s2", {})
    outs, _ = net.apply(
        params, {"x": _var_len_batch(3 * H)}, state=state, train=False
    )
    assert np.isfinite(np.asarray(outs["g"].data)).all()
