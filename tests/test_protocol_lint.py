"""Protocol lint (analysis/protocol_lint.py): the package gate — the
shipped distributed planes produce zero P-findings — plus one firing
mutation per P-rule (the test_concurrency_lint.py discipline: take the
REAL sources, seed exactly one protocol drift, assert exactly that rule
fires).  Mutations run through ``lint_protocol_sources`` so the real
files on disk are never touched."""

import os

from paddle_tpu.analysis import format_diagnostics
from paddle_tpu.analysis.protocol_lint import (
    PROTOCOL_FILES,
    lint_protocol_package,
    lint_protocol_sources,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddle_tpu")


def rules(diags):
    return sorted({d.rule for d in diags})


def _sources():
    out = {}
    for rel in PROTOCOL_FILES:
        with open(os.path.join(PKG, rel), encoding="utf-8") as fh:
            out[rel] = fh.read()
    return out


def _mutated(rel, old, new):
    """Real package sources with exactly one edit applied to ``rel``."""
    srcs = _sources()
    before = srcs[rel]
    srcs[rel] = before.replace(old, new, 1)
    assert srcs[rel] != before, (
        f"mutation anchor drifted: {old!r} not found in {rel}"
    )
    return lint_protocol_sources(srcs)


# ---------------------------------------------------------------------------
# the repo gate: the shipped package is clean
# ---------------------------------------------------------------------------


def test_package_protocol_lint_is_clean():
    diags = lint_protocol_package()
    assert diags == [], format_diagnostics(diags)


def test_baseline_sources_are_clean():
    # the mutation harness below only proves anything if the UNMUTATED
    # sources lint clean through the same entry point
    diags = lint_protocol_sources(_sources())
    assert diags == [], format_diagnostics(diags)


def test_cli_protocol_leg_exits_zero(capsys):
    from paddle_tpu.cli import cmd_lint

    assert cmd_lint(["--protocol"]) == 0
    assert "no diagnostics" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# P501 — RPC surface: whitelist <-> handler <-> wire universe
# ---------------------------------------------------------------------------


def test_p501_whitelisted_method_without_handler():
    # rename the Service handler out from under the _METHODS whitelist
    d = _mutated("master.py", "def get_task(", "def get_task_unbound(")
    assert "P501" in rules(d)
    assert any("get_task" in x.message for x in d if x.rule == "P501")


def test_p501_unwireable_reply_type():
    # a handler whose reply is a set literal can never cross the wire
    d = _mutated(
        "serving/router.py",
        "def ping(self) -> str:",
        "def ping(self):\n"
        "        return {1, 2}\n"
        "\n"
        "    def _unused_ping(self) -> str:",
    )
    assert "P501" in rules(d)
    assert any("ping" in x.message for x in d if x.rule == "P501")


# ---------------------------------------------------------------------------
# P502 — journal emission <-> registered record type <-> replay handler
# ---------------------------------------------------------------------------


def test_p502_emitted_type_not_registered():
    d = _mutated(
        "master.py",
        '{"t": "rotate", "from": from_pass}',
        '{"t": "rotateX", "from": from_pass}',
    )
    assert "P502" in rules(d)
    assert any("rotateX" in x.message for x in d if x.rule == "P502")


def test_p502_registered_type_without_apply_handler():
    d = _mutated("master.py", "def _apply_lease(", "def _apply_leaseXX(")
    assert "P502" in rules(d)
    assert any("lease" in x.message for x in d if x.rule == "P502")


def test_p502_dead_registered_type():
    # register a type nobody ever journals: a recovery path that can
    # never be exercised (usually a leftover from a removed transition)
    d = _mutated("master_journal.py", '"lease",', '"zzz_dead",\n    "lease",')
    assert "P502" in rules(d)
    assert any("zzz_dead" in x.message for x in d if x.rule == "P502")


# ---------------------------------------------------------------------------
# P503 — status-ledger exhaustiveness
# ---------------------------------------------------------------------------


def test_p503_rogue_status_literal():
    d = _mutated(
        "serving/router.py", 'status = "rejected"', 'status = "exploded"'
    )
    assert "P503" in rules(d)
    assert any("exploded" in x.message for x in d if x.rule == "P503")


# ---------------------------------------------------------------------------
# P504 — lease/fence monotonicity
# ---------------------------------------------------------------------------


def test_p504_epoch_fence_uses_ordering_not_equality():
    # epoch fences compare for identity; an ordering comparison silently
    # accepts stale holders (or rejects live ones) after wrap/reset
    d = _mutated(
        "master.py", "ent[0].epoch != epoch", "ent[0].epoch <= epoch"
    )
    assert "P504" in rules(d)


def test_p504_seq_dedupe_uses_equality_not_ordering():
    # journal seq dedupe must be an ordering (<=) — equality lets a
    # reordered/duplicated record slip past the monotonicity fence
    d = _mutated("master.py", "if seq <= self._seq:", "if seq == self._seq:")
    assert "P504" in rules(d)


# ---------------------------------------------------------------------------
# P505 — timeout completeness
# ---------------------------------------------------------------------------


def test_p505_unbounded_poll():
    d = _mutated(
        "master.py", "self._conn.poll(remaining)", "self._conn.poll()"
    )
    assert "P505" in rules(d)


# ---------------------------------------------------------------------------
# pragma plane: `# proto: allow[P50x] why` suppression + staleness
# ---------------------------------------------------------------------------


def test_proto_pragma_suppresses_finding():
    d = _mutated(
        "master.py",
        "if ent is None or ent[0].epoch != epoch:",
        "if ent is None or ent[0].epoch <= epoch:"
        "  # proto: allow[P504] mutation-fixture suppression",
    )
    assert d == [], format_diagnostics(d)


def test_stale_proto_pragma_is_flagged():
    d = _mutated(
        "master.py",
        "if ent is None or ent[0].epoch != epoch:",
        "if ent is None or ent[0].epoch != epoch:"
        "  # proto: allow[P504] nothing wrong here",
    )
    # the compare is already correct: the pragma suppresses nothing and
    # must be flagged as stale, not silently tolerated
    assert "P500" in rules(d)
