"""Production-gate e2e drills (slow; `make chaos` runs them
SANITIZER-ARMED): chaos faults injected under LIVE mixed train+serve
traffic, and the `paddle-tpu serve` SIGTERM graceful-drain contract.

The headline (ISSUE 12 acceptance): kill -9 one elastic worker AND bounce
the leader master — each under a live fleet that is training while the
parent process serves open-loop deadline traffic — and assert recovery,
ZERO training divergence (final params bit-identical to the unfaulted
reference), zero recomputed tasks for the master bounce, and that every
serving request lands in the disjoint served/shed/timeout ledger (nothing
fails any other way).

These spawn real process fleets => the whole module is slow-marked
(scripts/tier1_failset.py --slow-guard pins that)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from paddle_tpu.robustness import scenarios

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fleet_chaos_kill_worker_and_master_under_live_traffic(tmp_path):
    """One unfaulted reference fleet, then both fleet faults — sharing the
    reference and the prewarmed serving engine (the drills' serve plane
    must pay dispatch under contention, never XLA under contention)."""
    ref = scenarios.fleet_reference(str(tmp_path / "reference"))
    engine = scenarios.make_serving_engine(seed=0)

    worker = scenarios.run_fleet_chaos(
        str(tmp_path), kill="kill_worker", reference=ref, engine=engine,
    )
    assert worker["train_params_bit_identical"], worker
    assert worker["only_shed_or_timeout_failed"], worker
    assert worker["master_fail_events"] >= 1  # the lease requeue happened
    assert worker["recovery_after_fault_s"] < 120.0
    assert worker["passed"], worker

    master = scenarios.run_fleet_chaos(
        str(tmp_path), kill="kill_master", reference=ref, engine=engine,
    )
    assert master["train_params_bit_identical"], master
    assert master["only_shed_or_timeout_failed"], master
    # warm takeover from the journal: zero recomputed tasks, bounded span
    assert master["zero_recomputed_tasks"], master
    assert master["master_fail_events"] == 0
    assert master["takeover"]["warm"] is True
    assert master["takeover"]["replayed_records"] > 0
    assert master["recovery_after_fault_s"] < 30.0
    assert master["passed"], master


def _spawn_serve(extra, n=400, rate=3.0):
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "serve",
         "--src-vocab", "50", "--trg-vocab", "50", "--word-dim", "8",
         "--hidden-dim", "12", "--max-length", "8",
         "--synthetic", str(n), "--rate", str(rate), *extra],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def test_serve_sigterm_drains_clean_and_exits_zero():
    """The graceful-drain acceptance: SIGTERM mid-traffic -> stop
    admitting, finish every in-flight request, exit 0 — with the summary
    ledger showing zero 'unfinished' and drained_clean=true."""
    p = _spawn_serve(["--deadline-s", "30"])
    lines = []
    deadline = time.time() + 180
    try:
        while time.time() < deadline:
            line = p.stdout.readline()
            if not line:
                break
            lines.append(line)
            if sum(1 for ln in lines if '"req"' in ln) >= 3:
                break
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
        lines += out.splitlines()
    finally:
        if p.poll() is None:
            p.kill()
            p.communicate()
    assert p.returncode == 0, "".join(lines)[-2000:]
    summary = json.loads(
        [ln for ln in lines if '"drained_clean"' in ln][-1]
    )
    assert summary["drained_clean"] is True
    assert summary["unfinished"] == 0
    assert summary["served"] >= 3
    # every per-request line the drain emitted is a FINISHED request
    for ln in lines:
        if '"req"' in ln:
            rec = json.loads(ln)
            assert rec["status"] in ("served", "shed", "rejected",
                                     "timeout"), rec


def test_serve_second_sigterm_still_kills():
    """The PreemptionGuard contract: the FIRST signal drains, a SECOND
    falls through to the default handler — a wedged drain can always be
    killed."""
    p = _spawn_serve([], n=10_000, rate=2.0)
    try:
        deadline = time.time() + 180
        got = False
        while time.time() < deadline:
            line = p.stdout.readline()
            if not line:
                break
            if '"req"' in line:
                got = True
                break
        assert got, "server never served a request"
        p.send_signal(signal.SIGTERM)
        p.send_signal(signal.SIGTERM)
        p.communicate(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
            p.communicate()
    # killed by the chained default handler (or exited during the race):
    # it must be GONE promptly either way, never wedged
    assert p.returncode is not None
