"""Chaos e2e — real processes, real signals, real torn state.

The contract under test (ISSUE 5 acceptance): a trainer subprocess killed
with SIGKILL mid-pass and restarted with ``--resume`` finishes with a loss
trajectory identical to an uninterrupted run (bit-for-bit on the logged
costs and on the final parameters), and an injected NaN batch is skipped
while training converges regardless.  This is the paddle-tpu equivalent of
the reference's process-killing master/pserver failover tests
(go/master/service_internal_test.go; paddle/trainer survives pserver
restarts via go/pserver/service.go checkpoints)."""

import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.robustness import chaos
from paddle_tpu.robustness.preemption import read_marker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    yield
    chaos.disarm()
    from paddle_tpu.utils import flags

    flags.reset_flags()


def _run_cli(args, cwd=None, timeout=600, extra_env=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu", *args],
        capture_output=True, text=True, cwd=cwd, env=env, timeout=timeout,
    )


def _write_fixture(tmp_path):
    """A self-contained v1 config + deterministic provider: 4-class
    Gaussian blobs, order-stable (should_shuffle=False, provider-local
    RNG), so two processes see bit-identical batch streams."""
    (tmp_path / "conf.py").write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=16, learning_rate=0.05,\n"
        "         learning_method=MomentumOptimizer())\n"
        "define_py_data_sources2(train_list='train.list', test_list=None,\n"
        "                        module='chaos_provider', obj='process')\n"
        "x = data_layer(name='x', size=8)\n"
        "h = fc_layer(input=x, size=16, act=TanhActivation())\n"
        "pred = fc_layer(input=h, size=4, act=SoftmaxActivation())\n"
        "label = data_layer(name='label', size=4)\n"
        "outputs(classification_cost(input=pred, label=label))\n"
    )
    (tmp_path / "chaos_provider.py").write_text(
        "import numpy as np\n"
        "from paddle.trainer.PyDataProvider2 import *\n"
        "@provider(input_types=[dense_vector(8), integer_value(4)],\n"
        "          should_shuffle=False)\n"
        "def process(settings, f):\n"
        "    rng = np.random.RandomState(7)\n"
        "    centers = rng.randn(4, 8).astype('float32') * 2.0\n"
        "    for i in range(192):\n"
        "        lbl = int(i % 4)\n"
        "        v = centers[lbl] + 0.3 * rng.randn(8)\n"
        "        yield v.astype('float32').tolist(), lbl\n"
    )
    (tmp_path / "train.list").write_text("unused\n")


_COST_LINE = re.compile(r"pass (\d+) batch (\d+) cost (\S+)")


def _cost_lines(text):
    """{(pass, batch): cost-string} from the trainer's per-batch log lines
    (string compare = bit-for-bit on the %.6f rendering)."""
    out = {}
    for m in _COST_LINE.finditer(text):
        out[(int(m.group(1)), int(m.group(2)))] = m.group(3)
    return out


def _load_pass_params(pass_dir):
    import struct

    out = {}
    for name in sorted(os.listdir(pass_dir)):
        if name == "params.tar":
            continue
        with open(os.path.join(pass_dir, name), "rb") as f:
            _, _, count = struct.unpack("<iIQ", f.read(16))
            out[name] = np.frombuffer(f.read(count * 4), dtype=np.float32)
    return out


def test_kill9_resume_matches_uninterrupted_run(tmp_path):
    """kill -9 at step 8 (checkpoint every 3 batches), restart with
    --resume: the resumed per-batch cost lines must equal the
    uninterrupted run's for the same (pass, batch), and the final pass
    parameters must be byte-identical."""
    _write_fixture(tmp_path)
    common = [
        "train", "--config=conf.py", "--num_passes=2", "--seed=5",
        "--log_period=1", "--dot_period=0",
    ]

    ref_save = str(tmp_path / "ref_save")
    r = _run_cli([*common, f"--save_dir={ref_save}"], cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr[-2000:]
    ref_costs = _cost_lines(r.stderr)
    assert len(ref_costs) == 24  # 192/16 batches x 2 passes

    ck = str(tmp_path / "ck")
    save2 = str(tmp_path / "resume_save")
    r_kill = _run_cli(
        [*common, f"--save_dir={save2}", f"--checkpoint_dir={ck}",
         "--checkpoint_period_batches=3", "--chaos=kill@8"],
        cwd=str(tmp_path),
    )
    assert r_kill.returncode == -signal.SIGKILL  # died hard, no cleanup
    assert os.path.isdir(ck) and any(
        n.startswith("ckpt-") for n in os.listdir(ck)
    )

    r_res = _run_cli(
        [*common, f"--save_dir={save2}", f"--checkpoint_dir={ck}",
         "--resume"],
        cwd=str(tmp_path),
    )
    assert r_res.returncode == 0, r_res.stderr[-2000:]
    res_costs = _cost_lines(r_res.stderr)
    # the resumed run re-trains from the last checkpoint (step 6 = pass 0
    # batch 5 done) — every step it logs must be bit-for-bit the reference
    assert res_costs, "resumed run logged no steps"
    assert min(res_costs) == (0, 6)
    for key, cost in res_costs.items():
        assert cost == ref_costs[key], (key, cost, ref_costs[key])

    ref_p = _load_pass_params(os.path.join(ref_save, "pass-00001"))
    res_p = _load_pass_params(os.path.join(save2, "pass-00001"))
    assert ref_p.keys() == res_p.keys()
    for name in ref_p:
        assert np.array_equal(ref_p[name], res_p[name]), name


def test_sigterm_preempts_marker_and_resume_completes(tmp_path):
    """SIGTERM mid-run: graceful final checkpoint + PREEMPTED marker +
    exit 75; --resume clears the marker and finishes the job."""
    _write_fixture(tmp_path)
    ck = str(tmp_path / "ck")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "train", "--config=conf.py",
         "--num_passes=50", "--seed=5", "--log_period=1", "--dot_period=0",
         f"--checkpoint_dir={ck}", "--checkpoint_period_batches=2"],
        cwd=str(tmp_path), env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        # wait for training to actually start (first checkpoint lands)
        deadline = time.time() + 300
        while time.time() < deadline:
            if os.path.isdir(ck) and any(
                n.startswith("ckpt-") for n in os.listdir(ck)
            ):
                break
            if proc.poll() is not None:
                out, err = proc.communicate()
                pytest.fail(f"trainer exited early: {err[-2000:]}")
            time.sleep(0.2)
        else:
            pytest.fail("no checkpoint appeared before the deadline")
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 75, (proc.returncode, err[-2000:])
    assert "PREEMPTED" in out
    marker = read_marker(ck)
    assert marker is not None and marker["preempted"] is True

    r = _run_cli(
        ["train", "--config=conf.py", "--num_passes=2", "--seed=5",
         "--dot_period=0", f"--checkpoint_dir={ck}", "--resume"],
        cwd=str(tmp_path),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert read_marker(ck) is None


def test_nan_inject_skips_and_mnist_converges():
    """A NaN-poisoned batch mid-training is skipped on device and MNIST
    training converges regardless (the acceptance bar: robustness must not
    cost learning)."""
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.utils.timers import global_stats

    reset_auto_names()
    paddle.init(seed=0)
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    label = paddle.layer.data("label", paddle.data_type.integer_value(10))
    h = paddle.layer.fc(img, size=32, act=paddle.activation.Relu())
    pred = paddle.layer.fc(h, size=10, act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)
    trainer = paddle.trainer.SGD(
        cost=cost,
        parameters=paddle.parameters.create(cost, seed=0),
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9
        ),
    )
    chaos.arm("nan_batch@5")
    base_skip = global_stats.count("robustness.skipped_steps")
    costs = []
    trainer.train(
        paddle.batch(paddle.dataset.mnist.train(), 64),
        num_passes=1,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert global_stats.count("robustness.skipped_steps") == base_skip + 1
    finite = [c for c in costs if np.isfinite(c)]
    assert len(costs) - len(finite) == 1  # exactly the poisoned step
    assert np.mean(finite[-8:]) < 0.5 * np.mean(finite[:8])
