"""The fused attention-GRU decoder core (ops/rnn.py _attgru_core /
attention_gru_scan) against the naive unfused lowering, plus the
recurrent_group pattern-match dispatch that routes v1 attention-decoder
configs onto it with no config edits.

Three layers of pinning:
  * f64 VJP parity — the hand-written backward (transposed chain GEMMs in
    the scan, every weight grad a post-scan einsum) must reproduce plain
    jax.grad through the naive step-by-step composition;
  * finite-diff — jax.test_util.check_grads against central differences;
  * end-to-end A/B — the seq2seq training graph with the fused dispatch ON
    vs OFF produces the same outputs, gradients, and training trajectory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.rnn import attention_gru_scan

B, T, S, H, P, E = 3, 5, 4, 6, 7, 8


@pytest.fixture(autouse=True)
def _x64():
    old = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", old)


def _naive_attgru(
    gates, enc, enc_proj, w1, v, w_ctx, w_c, enc_lengths, lengths,
    h0=None, reverse=False,
):
    """The unfused v1 lowering, step by step (expand -> state-proj add ->
    tanh -> score -> sequence_softmax -> scaling -> sum-pool -> input fc ->
    gru_step), as plain autodiff-able jax."""
    b, t, _ = gates.shape
    h = w_c.shape[0]
    p_dim = enc_proj.shape[-1]
    w_sp, w_h = w1[:, :p_dim], w1[:, p_dim:]
    xs = jnp.swapaxes(gates, 0, 1)
    if reverse:
        xs = jnp.flip(xs, 0)
    tt = jnp.arange(t)[:, None]
    if lengths is None:
        mask = jnp.ones((t, b, 1), bool)
    elif reverse:
        mask = (tt >= t - lengths[None, :])[..., None]
    else:
        mask = (tt < lengths[None, :])[..., None]
    if enc_lengths is None:
        emask = jnp.ones(enc.shape[:2], bool)
    else:
        emask = jnp.arange(enc.shape[1])[None, :] < enc_lengths[:, None]
    h_p0 = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)

    def step(h_p, inp):
        x_t, m = inp
        sp = h_p @ w_sp  # the expand+fc state projection, per row
        hidden = jnp.tanh(enc_proj + sp[:, None, :])
        score = jnp.einsum("bsp,p->bs", hidden, v)
        score = jnp.where(emask, score, -1e9)
        alpha = jax.nn.softmax(score, axis=-1) * emask.astype(score.dtype)
        ctx = jnp.einsum("bs,bse->be", alpha, enc)
        x = x_t + ctx @ w_ctx
        x_u, x_r, x_c = jnp.split(x, 3, -1)
        ur = h_p @ w_h
        u = jax.nn.sigmoid(x_u + ur[:, :h])
        r = jax.nn.sigmoid(x_r + ur[:, h:])
        c = jnp.tanh(x_c + (r * h_p) @ w_c)
        h_t = (1.0 - u) * h_p + u * c
        h_t = jnp.where(m, h_t, h_p)
        return h_t, h_t

    h_last, hs = jax.lax.scan(step, h_p0, (xs, mask))
    if reverse:
        hs = jnp.flip(hs, 0)
    return jnp.swapaxes(hs, 0, 1), h_last


def _rand_args(seed=0, ragged=True):
    rng = np.random.RandomState(seed)
    args = dict(
        gates=jnp.asarray(rng.randn(B, T, 3 * H)),
        enc=jnp.asarray(rng.randn(B, S, E)),
        enc_proj=jnp.asarray(rng.randn(B, S, P)),
        w1=jnp.asarray(rng.randn(H, P + 2 * H) * 0.3),
        v=jnp.asarray(rng.randn(P) * 0.5),
        w_ctx=jnp.asarray(rng.randn(E, 3 * H) * 0.3),
        w_c=jnp.asarray(rng.randn(H, H) * 0.3),
    )
    lens = dict(
        enc_lengths=jnp.asarray(rng.randint(1, S + 1, B), jnp.int32)
        if ragged else None,
        lengths=jnp.asarray(rng.randint(2, T + 1, B), jnp.int32)
        if ragged else None,
    )
    h0 = jnp.asarray(rng.randn(B, H) * 0.5)
    return args, lens, h0


@pytest.mark.parametrize("ragged", [False, True])
@pytest.mark.parametrize("reverse", [False, True])
@pytest.mark.parametrize("early_exit", [False, True])
def test_fused_core_matches_autodiff(ragged, reverse, early_exit):
    args, lens, h0 = _rand_args(0, ragged)
    diff_keys = list(args)

    def loss_fused(a):
        hs, h_last = attention_gru_scan(
            **a, **lens, h0=h0, reverse=reverse, early_exit=early_exit
        )
        return jnp.sum(hs * jnp.cos(jnp.arange(hs.size).reshape(hs.shape))) \
            + jnp.sum(h_last)

    def loss_naive(a):
        hs, h_last = _naive_attgru(
            a["gates"], a["enc"], a["enc_proj"], a["w1"], a["v"],
            a["w_ctx"], a["w_c"], lens["enc_lengths"], lens["lengths"],
            h0=h0, reverse=reverse,
        )
        return jnp.sum(hs * jnp.cos(jnp.arange(hs.size).reshape(hs.shape))) \
            + jnp.sum(h_last)

    vf, gf = jax.value_and_grad(loss_fused)(args)
    vn, gn = jax.value_and_grad(loss_naive)(args)
    assert np.allclose(vf, vn, rtol=1e-10, atol=1e-10)
    for k in diff_keys:
        np.testing.assert_allclose(
            np.asarray(gf[k]), np.asarray(gn[k]), rtol=1e-8, atol=1e-8,
            err_msg=f"grad mismatch for {k}",
        )


def test_fused_core_h0_grad_and_masked_tail():
    """Gradient wrt the boot state flows; fully-masked tails are exact
    pass-throughs (the early-exit contract)."""
    args, lens, h0 = _rand_args(3, ragged=True)
    short = jnp.minimum(lens["lengths"], 2)  # every row dead past step 2

    def f(h0_, early):
        hs, h_last = attention_gru_scan(
            **args, enc_lengths=lens["enc_lengths"], lengths=short,
            h0=h0_, early_exit=early,
        )
        return jnp.sum(hs**2) + jnp.sum(h_last**2)

    v0, g0 = jax.value_and_grad(lambda h_: f(h_, False))(h0)
    v1, g1 = jax.value_and_grad(lambda h_: f(h_, True))(h0)
    assert np.asarray(jnp.abs(g0)).max() > 0
    np.testing.assert_allclose(np.asarray(v0), np.asarray(v1), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(g0), np.asarray(g1), rtol=1e-10, atol=1e-12
    )


def test_fused_core_finite_diff():
    from jax.test_util import check_grads

    args, lens, h0 = _rand_args(1, ragged=True)

    def f(w1, v, w_ctx, w_c, gates):
        hs, _ = attention_gru_scan(
            gates, args["enc"], args["enc_proj"], w1, v, w_ctx, w_c,
            **lens, h0=h0,
        )
        return jnp.mean(hs**2)

    check_grads(
        f, (args["w1"], args["v"], args["w_ctx"], args["w_c"],
            args["gates"]),
        order=1, modes=["rev"], atol=1e-5, rtol=1e-5,
    )


# ---------------------------------------------------------------------------
# end-to-end: the recurrent_group dispatch through the real seq2seq graph
# ---------------------------------------------------------------------------

VOCAB = 13


def _nmt_net_and_batch(seed=0):
    import paddle_tpu as paddle  # noqa: F401  (registers layers)
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.models.seq2seq import seq2seq_cost

    reset_auto_names()
    cost, dec = seq2seq_cost(VOCAB, VOCAB, word_dim=5, hidden_dim=4)
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(seed))
    rng = np.random.RandomState(seed)
    b, t = 4, 6
    batch = {
        "src_word": SeqTensor(
            jnp.asarray(rng.randint(2, VOCAB, (b, t)), jnp.int32),
            jnp.asarray(rng.randint(2, t + 1, b), jnp.int32),
        ),
        "trg_word": SeqTensor(
            jnp.asarray(rng.randint(2, VOCAB, (b, t)), jnp.int32),
            jnp.asarray(rng.randint(2, t + 1, b), jnp.int32),
        ),
        "trg_next": SeqTensor(
            jnp.asarray(rng.randint(2, VOCAB, (b, t)), jnp.int32),
            jnp.asarray(rng.randint(2, t + 1, b), jnp.int32),
        ),
    }
    batch["trg_next"] = SeqTensor(
        batch["trg_next"].data, batch["trg_word"].lengths
    )
    return net, params, state, batch, dec


def _flag(name, value):
    from paddle_tpu.utils.flags import set_flag

    set_flag(name, value)


@pytest.fixture()
def _flag_guard():
    from paddle_tpu.utils.flags import get_flag, set_flag

    old = get_flag("fused_attention_gru")
    yield
    set_flag("fused_attention_gru", old)


def test_seq2seq_decoder_matches_pattern():
    from paddle_tpu.layers.attention import match_attention_gru_step

    net, params, state, batch, dec = _nmt_net_and_batch()
    dec_conf = net.topology.get("decoder")
    sub = dec_conf.attrs["_sub_topology"]
    mems = dec_conf.attrs["_memories"]
    assert len(mems) == 1
    statics = {p for p, is_seq in dec_conf.attrs["_static_placeholders"] if is_seq}
    m = match_attention_gru_step(
        sub.layers, mems[0], set(dec_conf.attrs["_scan_placeholders"]), statics
    )
    assert m is not None
    assert m.gru == "dec_state"
    assert m.in_proj == "dec_in_proj"
    assert m.enc_name != m.ep_name


def test_seq2seq_fused_vs_generic_forward_and_grad(_flag_guard):
    """The whole training graph — cost value, every layer output reachable
    from the group, and the full parameter gradient — agrees between the
    fused dispatch and the generic scan."""
    net, params, state, batch, dec = _nmt_net_and_batch()

    def cost_fn(p):
        c, (o, _s) = net.cost(p, batch, state=state, train=False)
        return c, o

    outs = {}
    grads = {}
    for fused in (False, True):
        _flag("fused_attention_gru", fused)
        (c, o), g = jax.value_and_grad(cost_fn, has_aux=True)(params)
        outs[fused] = (float(c), o)
        grads[fused] = g
    c0, o0 = outs[False]
    c1, o1 = outs[True]
    np.testing.assert_allclose(c0, c1, rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(o0["decoder"].data), np.asarray(o1["decoder"].data),
        rtol=2e-4, atol=2e-6,
    )
    flat0, tree0 = jax.tree_util.tree_flatten(grads[False])
    flat1, tree1 = jax.tree_util.tree_flatten(grads[True])
    assert tree0 == tree1
    for a, b_, k in zip(flat0, flat1, range(len(flat0))):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-6,
            err_msg=f"grad leaf {k} ({jax.tree_util.tree_structure(grads[False])})",
        )


def test_seq2seq_fused_vs_generic_training_trajectory(_flag_guard):
    """A/B: a few SGD steps with the fused path produce the same cost
    trajectory as the generic path (numerics-pinned training)."""
    import paddle_tpu as paddle
    from paddle_tpu.trainer.step import make_train_step

    traj = {}
    for fused in (False, True):
        _flag("fused_attention_gru", fused)
        net, params, state, batch, _ = _nmt_net_and_batch(seed=5)
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        opt_state = opt.init(params)
        step = make_train_step(net, opt, mesh=None)
        costs = []
        for i in range(4):
            params, state, opt_state, m = step(
                params, state, opt_state, batch, jax.random.PRNGKey(i)
            )
            costs.append(float(m["cost"]))
        traj[fused] = costs
    np.testing.assert_allclose(traj[False], traj[True], rtol=1e-4)
    assert traj[True][-1] < traj[True][0]  # it actually trains


def test_generation_fused_vs_generic_step(_flag_guard):
    """Seq2SeqGenerator: beam/greedy decode agrees between the fused step
    and the generic sub-network interpretation."""
    import paddle_tpu as paddle
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator
    from paddle_tpu.core.topology import reset_auto_names

    reset_auto_names()
    cost, _ = __import__(
        "paddle_tpu.models.seq2seq", fromlist=["seq2seq_cost"]
    ).seq2seq_cost(VOCAB, VOCAB, word_dim=5, hidden_dim=4)
    params = paddle.parameters.create(cost, seed=1)
    rng = np.random.RandomState(2)
    samples = [
        (
            [int(x) for x in rng.randint(2, VOCAB, rng.randint(2, 6))],
            [0, 2, 3],
            [2, 3, 1],
        )
        for _ in range(6)
    ]
    feeder = paddle.reader.DataFeeder(
        params.network.topology.data_types(),
        {"src_word": 0, "trg_word": 1, "trg_next": 2},
    )
    batch = feeder(samples)
    results = {}
    for fused in (False, True):
        _flag("fused_attention_gru", fused)
        gen = Seq2SeqGenerator(
            params, VOCAB, VOCAB, word_dim=5, hidden_dim=4,
            bos_id=0, eos_id=1, max_length=7, beam_size=3,
        )
        assert (gen._match is not None) == True  # topology always matches
        seqs, scores = gen.generate(batch)
        toks, lens = gen.generate_greedy(batch)
        results[fused] = (
            np.asarray(seqs), np.asarray(scores), np.asarray(toks),
            np.asarray(lens),
        )
    np.testing.assert_array_equal(results[False][0], results[True][0])
    np.testing.assert_allclose(
        results[False][1], results[True][1], rtol=1e-5, atol=1e-6
    )
    np.testing.assert_array_equal(results[False][2], results[True][2])
    np.testing.assert_array_equal(results[False][3], results[True][3])


def test_non_elementwise_att_act_rejected():
    """A softmax (non-elementwise) act on the attention hidden layer must
    NOT match: the fused backward's jvp-with-ones derivative is only exact
    for elementwise activations."""
    import dataclasses

    from paddle_tpu.layers.attention import match_attention_gru_step

    net, params, state, batch, dec = _nmt_net_and_batch()
    dec_conf = net.topology.get("decoder")
    sub = dec_conf.attrs["_sub_topology"]
    mems = dec_conf.attrs["_memories"]
    statics = {
        p for p, is_seq in dec_conf.attrs["_static_placeholders"] if is_seq
    }
    scans = set(dec_conf.attrs["_scan_placeholders"])
    base = match_attention_gru_step(sub.layers, mems[0], scans, statics)
    assert base is not None
    layers = dict(sub.layers)
    layers[base.hidden] = dataclasses.replace(
        layers[base.hidden], act="softmax"
    )
    assert match_attention_gru_step(layers, mems[0], scans, statics) is None


def test_fused_group_finite_diff_layer_grad(_flag_guard):
    """LayerGradUtil-style numeric-vs-analytic check through the WHOLE
    jitted graph with the fused dispatch on: the custom VJP must agree
    with central differences for every parameter and dense input."""
    from layer_grad_util import check_layer_grad

    _flag("fused_attention_gru", True)
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.models.seq2seq import seq2seq_cost

    reset_auto_names()
    _cost, dec = seq2seq_cost(VOCAB, VOCAB, word_dim=4, hidden_dim=3)
    check_layer_grad(dec, batch_size=3, max_len=5, seed=2)


def test_non_matching_step_falls_back(_flag_guard):
    """A decoder step that is NOT the attention idiom (extra transform on
    the gru output inside the loop) still runs — via the generic scan —
    and the flag has no effect on it."""
    import paddle_tpu as paddle
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names

    L = paddle.layer
    A = paddle.activation
    outs = {}
    for fused in (False, True):
        reset_auto_names()
        src = L.data(
            "src", paddle.data_type.integer_value_sequence(VOCAB)
        )
        emb = L.embedding(src, size=6, name="emb")

        def step(x_t):
            mem = L.memory("st", 4)
            gates = L.fc(x_t, size=12, act=A.Identity(), bias_attr=False,
                         name="gates")
            g = L.gru_step(gates, mem, size=4, name="gru_raw")
            # the memory links a TRANSFORM of the gru output — no match
            out = L.fc(g, size=4, act=A.Tanh(), name="st")
            return out

        grp = L.recurrent_group(step, emb, name="grp")
        net = CompiledNetwork(Topology([grp]))
        params, state = net.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        batch = {
            "src": SeqTensor(
                jnp.asarray(rng.randint(0, VOCAB, (3, 5)), jnp.int32),
                jnp.asarray([5, 3, 2], jnp.int32),
            )
        }
        _flag("fused_attention_gru", fused)
        o, _ = net.apply(params, batch, state=state, train=False)
        outs[fused] = np.asarray(o["grp"].data)
    np.testing.assert_allclose(outs[False], outs[True], rtol=0, atol=0)
