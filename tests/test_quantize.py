"""Block-scaled quantization plane (ops/quantize.py) unit drills.

Covers all three surfaces of the format: the numpy wire half (elastic
contributions ride master_wire as int8 blocks + f32 scales, with the
compact ``q``/``Q`` array tags and the wire-byte counters), the in-graph
jax half (quantized_psum's psum-of-amax shared scale is overflow-free by
construction and its error stays within the block-scale bound), and the
serving weight bundle (weight-only int8, ~4x resident-byte reduction,
drift bounded).
"""

import multiprocessing.connection as mpc

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import master_wire as wire
from paddle_tpu.ops import quantize as bsq


# ---------------------------------------------------------------------------
# numpy wire half
# ---------------------------------------------------------------------------


def test_quantize_array_roundtrip_within_block_scale_bound():
    rng = np.random.RandomState(0)
    a = (rng.randn(40, 25) * rng.uniform(0.1, 10)).astype(np.float32)
    d = bsq.quantize_array(a, block=64)
    assert bsq.is_quantized_array(d)
    assert d["q"].dtype == np.int8 and d["s"].dtype == np.float32
    back = bsq.dequantize_array(d)
    assert back.shape == a.shape and back.dtype == a.dtype
    # round-half-even against scale amax/127: error <= scale/2 per block
    bound = np.repeat(d["s"], 64)[: a.size].reshape(a.shape) / 2 + 1e-7
    assert np.all(np.abs(back - a) <= bound)


def test_quantize_array_zero_block_and_scalar_edge():
    d = bsq.quantize_array(np.zeros((130,), np.float32), block=64)
    assert np.all(d["s"] == 0.0) and np.all(d["q"] == 0)
    assert np.all(bsq.dequantize_array(d) == 0.0)
    one = bsq.quantize_array(np.asarray([3.5], np.float32))
    assert bsq.dequantize_array(one).shape == (1,)


def test_quantize_tree_mixed_and_wire_bytes():
    rng = np.random.RandomState(1)
    tree = {
        "layer": {"w": rng.randn(64, 32).astype(np.float32)},
        "rows": 17,  # non-array leaf passes through
        "ids": np.arange(5, dtype=np.int32),  # non-float array untouched
    }
    qt = bsq.quantize_tree(tree, block=128)
    assert bsq.is_quantized_array(qt["layer"]["w"])
    assert qt["rows"] == 17 and qt["ids"].dtype == np.int32
    back = bsq.dequantize_tree(qt)
    assert back["layer"]["w"].shape == (64, 32)
    # mixed map (one producer quantized, one not) dequantizes only marked
    mixed = bsq.dequantize_tree({"a": qt["layer"]["w"], "b": tree["ids"]})
    assert mixed["a"].dtype == np.float32 and mixed["b"] is tree["ids"]
    # the >= 3x wire-byte reduction the elastic bench gates on
    f32_bytes = bsq.tree_wire_bytes({"w": tree["layer"]["w"]})
    q_bytes = bsq.tree_wire_bytes({"w": qt["layer"]["w"]})
    assert f32_bytes >= 3 * q_bytes, (f32_bytes, q_bytes)


def test_wire_codec_compact_int8_tags_and_counters():
    """int8/uint8 arrays ride the dedicated ``q``/``Q`` tags (no dtype
    string) and send/recv tally wire_bytes counters, per endpoint label."""
    a8 = np.arange(-5, 5, dtype=np.int8).reshape(2, 5)
    u8 = np.arange(10, dtype=np.uint8)
    payload = wire.encode_payload((a8, u8))
    back_a, back_u = wire.decode_payload(payload)
    assert np.array_equal(back_a, a8) and back_a.dtype == np.int8
    assert np.array_equal(back_u, u8) and back_u.dtype == np.uint8
    # compact framing: the generic 'a' tag spends 5 extra bytes on the
    # "|i1" dtype string; the compact tag must not
    generic = wire.encode_payload(a8.astype(np.int16))
    assert len(wire.encode_payload(a8)) < len(generic)

    wire.counters.reset()
    left, right = mpc.Pipe()
    try:
        wire.send_msg(left, {"g": a8}, label="test")
        got = wire.recv_msg(right, label="test")
        assert np.array_equal(got["g"], a8)
        snap = wire.counters.snapshot()
        assert snap["wire_bytes_sent"] == snap["wire_bytes_recv"] > 0
        assert snap["wire_bytes_sent[test]"] == snap["wire_bytes_sent"]
    finally:
        left.close()
        right.close()
        wire.counters.reset()


def test_reduce_results_dequantizes_then_reduces_deterministically():
    """A quantized contribution reduces to the SAME mean no matter which
    worker reduces it (everyone dequantizes the producer's bytes), and a
    mixed map (fleet mid-flag-flip) still reduces."""
    from paddle_tpu.trainer.elastic import reduce_results

    rng = np.random.RandomState(2)
    g0 = {"w": rng.randn(30, 10).astype(np.float32)}
    g1 = {"w": rng.randn(30, 10).astype(np.float32)}
    q1 = bsq.quantize_tree(g1)
    results = {
        0: {"grads": g0, "cost": 1.0, "rows": 10},
        1: {"grads": q1, "cost": 3.0, "rows": 30},
    }
    mean_a, cost_a, rows_a = reduce_results(results)
    mean_b, cost_b, rows_b = reduce_results(dict(reversed(results.items())))
    assert np.array_equal(mean_a["w"], mean_b["w"])  # sorted-order contract
    assert rows_a == rows_b == 40 and cost_a == cost_b == 0.1
    expect = (g0["w"] * 10 + bsq.dequantize_tree(q1)["w"] * 30) / 40
    assert np.allclose(mean_a["w"], expect)


# ---------------------------------------------------------------------------
# in-graph jax half
# ---------------------------------------------------------------------------


def _psum_ab(tree_parts, **kw):
    """Run quantized_psum over the devices axis via shard_map; returns the
    per-shard outputs (all identical) next to the exact f32 psum."""
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.parallel.mesh import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(t):
        return bsq.quantized_psum(t, "dp", **kw)

    out = shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
        check_vma=False,
    )(tree_parts)
    return out


def test_quantized_psum_matches_exact_sum_within_bound():
    n_dev = len(jax.devices())
    rng = np.random.RandomState(3)
    parts = rng.randn(n_dev, 500).astype(np.float32)
    out = np.asarray(_psum_ab(jnp.asarray(parts), block=128))
    exact = parts.sum(axis=0)
    # shared bound S = sum_i amax_i; per-element error <= S/127 per shard
    # rounding, n_dev shards -> loose bound n_dev * S / (2 * 127)
    s = np.abs(parts).max(axis=1).sum()
    bound = n_dev * s / (2 * 127) + 1e-5
    for d in range(n_dev):
        assert np.all(np.abs(out[d] - exact) <= bound)
    # every shard sees the SAME reduced value (it is an allreduce)
    for d in range(1, n_dev):
        assert np.array_equal(out[d], out[0])


def test_quantized_psum_bf16_payload_and_mean():
    n_dev = len(jax.devices())
    rng = np.random.RandomState(4)
    parts = rng.randn(n_dev, 300).astype(np.float32)
    out = np.asarray(_psum_ab(
        jnp.asarray(parts), payload_dtype=jnp.bfloat16, mean=True,
    ))
    exact = parts.mean(axis=0)
    assert np.max(np.abs(out[0] - exact)) < 0.05
    assert out.dtype == np.float32


def test_quantized_psum_stochastic_rounding_unbiased_runs():
    n_dev = len(jax.devices())
    rng = np.random.RandomState(5)
    parts = rng.randn(n_dev, 256).astype(np.float32)

    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.parallel.mesh import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    out = shard_map(
        lambda t, k: bsq.quantized_psum(t, "dp", stochastic=True, rng=k),
        mesh=mesh, in_specs=(P("dp"), P()), out_specs=P("dp"),
        check_vma=False,
    )(jnp.asarray(parts), jax.random.PRNGKey(0))
    exact = parts.sum(axis=0)
    s = np.abs(parts).max(axis=1).sum()
    assert np.max(np.abs(np.asarray(out)[0] - exact)) <= n_dev * s / 127


def test_quantize_block_scaled_roundtrip_and_zero_guard():
    x = jnp.asarray(np.random.RandomState(6).randn(17, 13), jnp.float32)
    p, s = bsq.quantize_block_scaled(x, block=64)
    assert p.dtype == jnp.int8 and s.dtype == jnp.float32
    back = bsq.dequantize_block_scaled(p, s, x.shape, x.dtype)
    assert float(jnp.max(jnp.abs(back - x))) <= float(jnp.max(s)) / 2 + 1e-6
    # exact-zero input: guard pins scale path, output is exactly zero
    pz, sz = bsq.quantize_block_scaled(jnp.zeros((70,), jnp.float32))
    assert float(jnp.max(jnp.abs(pz))) == 0.0


# ---------------------------------------------------------------------------
# serving weight bundles
# ---------------------------------------------------------------------------


def test_weight_bundle_quantize_shrinks_and_bounds_drift():
    rng = np.random.RandomState(7)
    w = {
        "head_w": jnp.asarray(rng.randn(48, 40), jnp.float32),
        "w_ctx": jnp.asarray(rng.randn(96, 144), jnp.float32),
        "v": jnp.asarray(rng.randn(48), jnp.float32),  # 1-D: untouched
        "head_b": None,  # None leaves ride through
        "sp_b": jnp.asarray(rng.randn(48), jnp.float32),
    }
    wq, meta = bsq.quantize_weight_bundle(w, block=128)
    assert set(meta) == {"head_w", "w_ctx"}
    assert wq["v"] is w["v"] and wq["head_b"] is None
    f32_bytes = bsq.weight_bundle_bytes(w)
    q_bytes = bsq.weight_bundle_bytes(wq)
    assert q_bytes < f32_bytes / 2.5, (q_bytes, f32_bytes)
    deq = bsq.dequantize_weight_bundle(wq, meta)
    for k in meta:
        a = np.asarray(w[k])
        drift = np.max(np.abs(np.asarray(deq[k]) - a)) / np.max(np.abs(a))
        assert drift < 0.01, (k, drift)
    assert deq["v"] is wq["v"]
