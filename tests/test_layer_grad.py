"""Per-layer gradient checks — the test_LayerGrad.cpp equivalent (reference:
paddle/gserver/tests/test_LayerGrad.cpp, ~2.3k LoC over ~80 layer types):
every layer type gets numeric-vs-analytic gradients through the jitted net."""

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names

from layer_grad_util import check_layer_grad, rand_batch_for

L = paddle.layer
A = paddle.activation


@pytest.fixture(autouse=True)
def _reset_names():
    reset_auto_names()
    yield


def dense(dim=8, name="in0"):
    return L.data(name, paddle.data_type.dense_vector(dim))


def dense_seq(dim=8, name="in0"):
    return L.data(name, paddle.data_type.dense_vector_sequence(dim))


def ids_seq(vocab=12, name="ids0"):
    return L.data(name, paddle.data_type.integer_value_sequence(vocab))


# ---------------------------------------------------------------------------


def test_fc_grad():
    check_layer_grad(L.fc(dense(), size=6, act=A.Tanh()))


def test_fc_multi_input_grad():
    a, b = dense(8, "a"), dense(4, "b")
    check_layer_grad(L.fc([a, b], size=5, act=A.Sigmoid()))


def test_fc_seq_grad():
    check_layer_grad(L.fc(dense_seq(), size=6, act=A.Relu()))


def test_embedding_grad():
    check_layer_grad(L.embedding(ids_seq(), size=6))


def test_addto_grad():
    a, b = dense(8, "a"), dense(8, "b")
    check_layer_grad(L.addto([a, b], act=A.Tanh(), bias_attr=True))


def test_concat_grad():
    a, b = dense(8, "a"), dense(4, "b")
    check_layer_grad(L.concat([a, b]))


def test_scaling_grad():
    w, x = dense(1, "w"), dense(8, "x")
    check_layer_grad(L.scaling(w, x))


def test_slope_intercept_grad():
    check_layer_grad(L.slope_intercept(dense(), slope=2.0, intercept=0.5))


def test_interpolation_grad():
    w, x1, x2 = dense(1, "w"), dense(8, "a"), dense(8, "b")
    check_layer_grad(L.interpolation(w, x1, x2))


def test_sum_to_one_norm_grad():
    check_layer_grad(L.sum_to_one_norm(dense()))


def test_row_l2_norm_grad():
    check_layer_grad(L.row_l2_norm(dense()))


def test_cos_sim_grad():
    a, b = dense(8, "a"), dense(8, "b")
    check_layer_grad(L.cos_sim(a, b, scale=5.0))


def test_out_prod_grad():
    a, b = dense(4, "a"), dense(3, "b")
    check_layer_grad(L.out_prod(a, b))


def test_tensor_grad():
    a, b = dense(4, "a"), dense(3, "b")
    check_layer_grad(L.tensor(a, b, size=5, act=A.Tanh()))


def test_trans_grad():
    check_layer_grad(L.trans(dense(12), height=3))


def test_resize_grad():
    check_layer_grad(L.resize(dense(12), size=6))


def test_multiplex_grad():
    sel = L.data("sel", paddle.data_type.integer_value(2))
    a, b = dense(6, "a"), dense(6, "b")
    check_layer_grad(L.multiplex([sel, a, b]))


# -- image layers -----------------------------------------------------------


def img_data(c=2, s=6, name="img"):
    return L.data(name, paddle.data_type.dense_vector(c * s * s))


def test_conv_grad():
    x = img_data()
    check_layer_grad(
        L.img_conv(x, filter_size=3, num_filters=4, num_channels=2, padding=1,
                   act=A.Tanh()),
    )


def test_conv_stride_grad():
    x = img_data()
    check_layer_grad(
        L.img_conv(x, filter_size=3, num_filters=3, num_channels=2, stride=2,
                   padding=1, act=A.Identity()),
    )


def test_conv_groups_grad():
    x = img_data(c=4)
    check_layer_grad(
        L.img_conv(x, filter_size=3, num_filters=4, num_channels=4, padding=1,
                   groups=2, act=A.Identity()),
    )


def test_convt_grad():
    x = img_data()
    check_layer_grad(
        L.img_conv(x, filter_size=3, num_filters=3, num_channels=2, stride=2,
                   padding=1, trans=True, act=A.Identity()),
    )


def test_pool_max_grad():
    x = img_data()
    conv = L.img_conv(x, filter_size=3, num_filters=3, num_channels=2,
                      padding=1, act=A.Identity())
    check_layer_grad(L.img_pool(conv, pool_size=2, stride=2))


def test_pool_avg_grad():
    x = img_data()
    conv = L.img_conv(x, filter_size=3, num_filters=3, num_channels=2,
                      padding=1, act=A.Identity())
    check_layer_grad(
        L.img_pool(conv, pool_size=3, stride=2, pool_type=paddle.pooling.Avg())
    )


def test_batch_norm_img_grad():
    x = img_data()
    conv = L.img_conv(x, filter_size=3, num_filters=3, num_channels=2,
                      padding=1, act=A.Identity())
    check_layer_grad(L.batch_norm(conv, act=A.Relu()))


def test_batch_norm_fc_grad():
    check_layer_grad(L.batch_norm(L.fc(dense(), size=6, act=A.Identity())))


def test_maxout_grad():
    x = img_data(c=4)
    check_layer_grad(L.maxout(x, groups=2, num_channels=4))


def test_pad_grad():
    x = img_data()
    check_layer_grad(L.img_pad(x, pad_c=(1, 1), pad_h=(1, 0), pad_w=(0, 1),
                               num_channels=2))


def test_bilinear_grad():
    x = img_data()
    check_layer_grad(L.bilinear_interp(x, out_size_x=9, out_size_y=9,
                                       num_channels=2))


def test_spp_grad():
    x = img_data()
    check_layer_grad(L.spp(x, pyramid_height=2, num_channels=2))


# -- sequence layers --------------------------------------------------------


def test_seqpool_grads():
    for ptype in (paddle.pooling.Max(), paddle.pooling.Avg(), paddle.pooling.Sum(),
                  paddle.pooling.SquareRootN()):
        reset_auto_names()
        check_layer_grad(L.pooling(dense_seq(), ptype))


def test_last_first_seq_grad():
    check_layer_grad(L.last_seq(dense_seq()))
    reset_auto_names()
    check_layer_grad(L.first_seq(dense_seq()))


def test_expand_grad():
    x = dense(8, "x")
    pat = dense_seq(4, "pat")
    check_layer_grad(L.expand(x, pat))


def test_seq_reshape_grad():
    check_layer_grad(L.seq_reshape(dense_seq(8), reshape_size=4))


def test_seq_concat_grad():
    a, b = dense_seq(6, "a"), dense_seq(6, "b")
    check_layer_grad(L.seq_concat(a, b))


def test_lstmemory_grad():
    proj = L.fc(dense_seq(), size=20, act=A.Identity(), bias_attr=False)
    check_layer_grad(L.lstmemory(proj), atol=8e-2, rtol=8e-2)


def test_lstmemory_reverse_grad():
    proj = L.fc(dense_seq(), size=20, act=A.Identity(), bias_attr=False)
    check_layer_grad(L.lstmemory(proj, reverse=True), atol=8e-2, rtol=8e-2)


def test_gru_grad():
    proj = L.fc(dense_seq(), size=15, act=A.Identity(), bias_attr=False)
    check_layer_grad(L.grumemory(proj), atol=8e-2, rtol=8e-2)


def test_recurrent_grad():
    proj = L.fc(dense_seq(), size=6, act=A.Identity())
    check_layer_grad(L.recurrent(proj), atol=8e-2, rtol=8e-2)


# -- cost layers ------------------------------------------------------------


def test_classification_cost_grad():
    x = dense()
    lbl = L.data("lbl", paddle.data_type.integer_value(5))
    pred = L.fc(x, size=5, act=A.Softmax())
    check_layer_grad(L.classification_cost(pred, lbl))


def test_square_error_grad():
    x, y = dense(6, "x"), dense(6, "y")
    pred = L.fc(x, size=6, act=A.Identity())
    check_layer_grad(L.square_error_cost(pred, y))


def test_smooth_l1_grad():
    x, y = dense(6, "x"), dense(6, "y")
    pred = L.fc(x, size=6, act=A.Identity())
    check_layer_grad(L.smooth_l1_cost(pred, y), eps=1e-4)


def test_huber_regression_grad():
    x, y = dense(6, "x"), dense(6, "y")
    pred = L.fc(x, size=6, act=A.Identity())
    check_layer_grad(L.huber_regression_cost(pred, y), eps=1e-4)


def test_rank_cost_grad():
    a, b = dense(6, "a"), dense(6, "b")
    lbl = L.data("lbl", paddle.data_type.dense_vector(1))
    left = L.fc(a, size=1, act=A.Identity())
    right = L.fc(b, size=1, act=A.Identity())
    check_layer_grad(L.rank_cost(left, right, lbl))


def test_soft_bce_grad():
    x = dense(6, "x")
    t = L.data("t", paddle.data_type.dense_vector(6))
    pred = L.fc(x, size=6, act=A.Sigmoid())
    # targets must be in (0,1): feed sigmoid-squashed random targets
    topo_probe = paddle.Topology([L.soft_binary_class_cross_entropy_cost(pred, t)])
    batch = rand_batch_for(topo_probe)
    import jax.nn as jnn
    from paddle_tpu.core.batch import SeqTensor

    batch["t"] = SeqTensor(jnn.sigmoid(batch["t"].data))
    reset_auto_names()
    check_layer_grad(
        L.soft_binary_class_cross_entropy_cost(pred, t), batch=batch
    )
