# Shipped demo config: attention NMT decoder in the v1 dialect (the
# demo/seqToseq shape: bi-GRU encoder + simple_attention + gru_step inside a
# recurrent_group).  Corpus member proving the graph linter stays silent on
# the exact idiom the PR-2 fused attention-GRU matcher targets — and the
# base for the G010 mutation (dropout inside the pattern defeats the fused
# lowering).
from paddle.trainer_config_helpers import *  # noqa: F401,F403

src_vocab = 40
trg_vocab = 45
word_dim = 16
hidden_dim = 16

settings(batch_size=8, learning_rate=5e-4, learning_method=AdamOptimizer())

src = data_layer(name="src_word", size=src_vocab)
src_emb = embedding_layer(input=src, size=word_dim, name="src_emb")
enc_fw = simple_gru(input=src_emb, size=hidden_dim, name="enc_fw")
enc_bw = simple_gru(input=src_emb, size=hidden_dim, reverse=True, name="enc_bw")
enc = concat_layer(input=[enc_fw, enc_bw], name="enc")
enc_proj = fc_layer(
    input=enc, size=hidden_dim, act=IdentityActivation(), bias_attr=False,
    name="enc_proj",
)
boot = fc_layer(
    input=first_seq(input=enc, name="enc_first"), size=hidden_dim,
    act=TanhActivation(), name="dec_boot",
)

trg = data_layer(name="trg_word", size=trg_vocab)
trg_emb = embedding_layer(input=trg, size=word_dim, name="trg_emb")


def decoder_step(trg_emb_t, enc_seq, enc_p):
    state = memory(name="dec_state", size=hidden_dim, boot_layer=boot)
    context = simple_attention(
        encoded_sequence=enc_seq, encoded_proj=enc_p, decoder_state=state,
        name="att",
    )
    gate_in = fc_layer(
        input=[context, trg_emb_t], size=hidden_dim * 3,
        act=IdentityActivation(), bias_attr=False, name="dec_in_proj",
    )
    gru = gru_step_layer(
        input=gate_in, output_mem=state, size=hidden_dim, name="dec_state",
    )
    return fc_layer(
        input=gru, size=trg_vocab, act=SoftmaxActivation(), name="dec_out",
    )


dec = recurrent_group(
    step=decoder_step,
    input=[
        trg_emb,
        StaticInput(input=enc, is_seq=True),
        StaticInput(input=enc_proj, is_seq=True),
    ],
    name="decoder",
)
label = data_layer(name="trg_next", size=trg_vocab)
outputs(classification_cost(input=dec, label=label, name="nmt_cost"))
