# Shipped demo config: MNIST MLP in the reference v1 trainer-config dialect
# (the v1_api_demo/mnist shape) — part of the graph-lint zero-false-positive
# corpus (tests/test_graph_lint.py, `make lint`); feed it through an
# explicit DataFeeder (or add define_py_data_sources2) to train.
from paddle.trainer_config_helpers import *  # noqa: F401,F403

settings(batch_size=32, learning_rate=1e-3, learning_method=AdamOptimizer())

img = data_layer(name="pixel", size=784)
hidden1 = fc_layer(input=img, size=128, act=ReluActivation())
hidden2 = fc_layer(input=hidden1, size=64, act=ReluActivation())
predict = fc_layer(input=hidden2, size=10, act=SoftmaxActivation())

if get_config_arg("is_predict", bool, False):
    outputs(predict)
else:
    label = data_layer(name="label", size=10)
    cls = classification_cost(input=predict, label=label)
    outputs(cls)
