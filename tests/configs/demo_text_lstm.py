# Shipped demo config: quick_start-style LSTM text classification in the v1
# dialect (embedding -> lstm -> pooling -> softmax) — graph-lint corpus
# member exercising sequence layers and dropout placement.
from paddle.trainer_config_helpers import *  # noqa: F401,F403

dict_dim = 100
settings(batch_size=16, learning_rate=2e-3, learning_method=AdamOptimizer())

words = data_layer(name="word", size=dict_dim)
emb = embedding_layer(input=words, size=32)
lstm = simple_lstm(input=emb, size=32)
pooled = pooling_layer(input=lstm, pooling_type=MaxPooling())
hidden = fc_layer(
    input=pooled, size=32, act=TanhActivation(),
    layer_attr=ExtraAttr(drop_rate=0.1),
)
predict = fc_layer(input=hidden, size=2, act=SoftmaxActivation())
label = data_layer(name="label", size=2)
outputs(classification_cost(input=predict, label=label))
