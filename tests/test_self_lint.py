"""AST self-lint (analysis/ast_rules.py): the package gate — paddle_tpu's
own source plus bench.py must produce zero findings — and per-rule mutation
fixtures proving each rule fires.  Also covers the flags satellite: the
define_flag re-registration guard (runtime twin of rule A204)."""

import os
import textwrap

import pytest

from paddle_tpu.analysis import format_diagnostics, lint_file, lint_package

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules(diags):
    return [d.rule for d in diags]


def _lint_src(tmp_path, src, relname="reader/mod.py"):
    p = tmp_path / relname
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return lint_file(str(p), root=str(tmp_path))


# ---------------------------------------------------------------------------
# the repo gate: our own source is clean
# ---------------------------------------------------------------------------


def test_package_self_lint_is_clean():
    diags = lint_package(
        extra_paths=[os.path.join(REPO, "bench.py")]
    )
    assert diags == [], format_diagnostics(diags)


# ---------------------------------------------------------------------------
# mutation fixtures
# ---------------------------------------------------------------------------


def test_a201_time_in_jitted_function(tmp_path):
    d = _lint_src(tmp_path, """
        import time
        import jax

        @jax.jit
        def step(x):
            return x * time.time()
    """, "mod.py")
    assert rules(d) == ["A201"]
    assert d[0].line == 7 and d[0].hint


def test_a201_via_jit_call_by_name(tmp_path):
    d = _lint_src(tmp_path, """
        import time
        import jax

        def make_step():
            def step(x):
                return x + time.perf_counter()
            return jax.jit(step, donate_argnums=(0,))
    """, "mod.py")
    assert rules(d) == ["A201"]


def test_a201_partial_jit_decorator(tmp_path):
    d = _lint_src(tmp_path, """
        import functools
        import time
        import jax

        @functools.partial(jax.jit, static_argnames=("n",))
        def step(x, n):
            return x * time.monotonic()
    """, "mod.py")
    assert rules(d) == ["A201"]


def test_a202_host_rng_in_jitted_function(tmp_path):
    d = _lint_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + np.random.rand()
    """, "mod.py")
    assert rules(d) == ["A202"]


def test_a202_jitted_lambda(tmp_path):
    d = _lint_src(tmp_path, """
        import jax
        import random

        fn = jax.jit(lambda x: x * random.random())
    """, "mod.py")
    assert rules(d) == ["A202"]


def test_unjitted_time_and_rng_are_fine(tmp_path):
    d = _lint_src(tmp_path, """
        import time
        import numpy as np

        def host_loop(x):
            t0 = time.time()
            return x + np.random.rand(), time.time() - t0
    """, "mod.py")
    assert d == []


def test_a203_global_rng_in_reader_module(tmp_path):
    d = _lint_src(tmp_path, """
        import random

        def reader():
            data = list(range(10))
            random.shuffle(data)
            yield from data
    """, "reader/creator2.py")
    assert rules(d) == ["A203"]


def test_a203_seeded_rng_is_fine(tmp_path):
    d = _lint_src(tmp_path, """
        import random
        import numpy as np

        def reader(seed=0):
            rng = random.Random(seed)
            nrng = np.random.RandomState(seed)
            data = list(range(10))
            rng.shuffle(data)
            yield from (data + [nrng.rand()])
    """, "dataset/gen.py")
    assert d == []


def test_a203_not_applied_outside_reader_modules(tmp_path):
    d = _lint_src(tmp_path, """
        import random

        def sample():
            return random.random()
    """, "models/gen.py")
    assert d == []


def test_a206_pickle_loads_flagged(tmp_path):
    d = _lint_src(tmp_path, """
        import pickle

        def decode(blob):
            return pickle.loads(blob)
    """, "mod.py")
    assert rules(d) == ["A206"]
    assert "master_wire" in d[0].message and d[0].hint


def test_a206_alias_and_from_import(tmp_path):
    d = _lint_src(tmp_path, """
        import pickle as pkl
        from pickle import loads as unmarshal

        def a(b):
            return pkl.load(b), unmarshal(b), pkl.Unpickler(b)
    """, "mod.py")
    assert rules(d) == ["A206", "A206", "A206"]


def test_a206_bare_conn_recv_flagged_socket_recv_fine(tmp_path):
    d = _lint_src(tmp_path, """
        def pump(conn, sock):
            msg = conn.recv()          # Connection-style: implicit unpickle
            raw = sock.recv(4096)      # socket-style bytes read: fine
            return msg, raw
    """, "mod.py")
    assert rules(d) == ["A206"]
    assert d[0].line == 3


def test_a206_dumps_and_master_wire_exempt(tmp_path):
    # serializing is legal everywhere; deserializing is legal in the codec
    d = _lint_src(tmp_path, """
        import pickle

        def save(obj, f):
            pickle.dump(obj, f)
            return pickle.dumps(obj)
    """, "mod.py")
    assert d == []
    d = _lint_src(tmp_path, """
        import pickle

        def decode(blob):
            return pickle.loads(blob)
    """, "paddle_tpu/master_wire.py")
    assert d == []


def test_a206_pragma_suppresses_with_justification(tmp_path):
    d = _lint_src(tmp_path, """
        import pickle

        def decode(blob):
            return pickle.loads(blob)  # wire: allow[A206] local md5-verified dataset file
    """, "mod.py")
    assert d == []


def test_a206_empty_pragma_justification_rejected(tmp_path):
    d = _lint_src(tmp_path, """
        import pickle

        def decode(blob):
            return pickle.loads(blob)  # wire: allow[A206]
    """, "mod.py")
    # the malformed pragma reports (and the hazard is NOT double-reported)
    assert rules(d) == ["A206"]
    assert "justification" in d[0].message


def test_a206_stale_pragma_flagged(tmp_path):
    d = _lint_src(tmp_path, """
        def harmless():  # wire: allow[A206] nothing here needs this anymore
            return 1
    """, "mod.py")
    assert rules(d) == ["A206"]
    assert "unused" in d[0].message


def test_a204_duplicate_flag_definition(tmp_path):
    a = tmp_path / "pkg" / "flags_a.py"
    b = tmp_path / "pkg" / "flags_b.py"
    a.parent.mkdir(parents=True)
    a.write_text('define_flag("seed", 0, "x")\n')
    b.write_text('define_flag("seed", 1, "y")\n')
    defs = {}
    d = lint_file(str(a), root=str(tmp_path), _flag_defs=defs)
    d += lint_file(str(b), root=str(tmp_path), _flag_defs=defs)
    assert rules(d) == ["A204"]
    assert "flags_a.py" in d[0].message  # provenance of the first definition


# ---------------------------------------------------------------------------
# flags satellite: runtime re-registration guard
# ---------------------------------------------------------------------------


def test_define_flag_identical_reregistration_is_noop():
    from paddle_tpu.utils import flags

    flags.define_flag("_test_lint_flag", 7, "probe")
    try:
        flags.define_flag("_test_lint_flag", 7, "probe again")  # no raise
        assert flags.get_flag("_test_lint_flag") == 7
    finally:
        flags._DEFS.pop("_test_lint_flag", None)


def test_define_flag_conflicting_reregistration_raises():
    from paddle_tpu.utils import flags

    flags.define_flag("_test_lint_flag2", 7, "probe")
    try:
        with pytest.raises(ValueError, match="already defined"):
            flags.define_flag("_test_lint_flag2", 8, "conflicting default")
        with pytest.raises(ValueError, match="already defined"):
            flags.define_flag("_test_lint_flag2", "7", "conflicting type")
        # the original definition survives the failed re-registration
        assert flags.get_flag("_test_lint_flag2") == 7
    finally:
        flags._DEFS.pop("_test_lint_flag2", None)


# ---------------------------------------------------------------------------
# CLI face
# ---------------------------------------------------------------------------


def test_cli_lint_self_clean():
    from paddle_tpu.cli import main

    assert main(["lint"]) == 0


def test_cli_lint_reports_bad_config(tmp_path, capsys):
    cfg = tmp_path / "bad_conf.py"
    cfg.write_text(textwrap.dedent("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=8, learning_rate=1e-3)
        x = data_layer(name="x", size=8)
        a = fc_layer(input=x, size=8, name="a")
        b = fc_layer(input=x, size=12, name="b")
        s = addto_layer(input=[a, b], name="sum")
        outputs(s)
    """))
    from paddle_tpu.cli import main

    rc = main(["lint", f"--config={cfg}"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "G004" in out and "'sum'" in out and "fix:" in out


# ---------------------------------------------------------------------------
# tier-1 failure-set snapshot tooling
# ---------------------------------------------------------------------------


def test_tier1_failset_parses_summary_lines():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tier1_failset", os.path.join(REPO, "scripts", "tier1_failset.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    log = textwrap.dedent("""
        ....F..E
        =========================== short test summary info ====================
        FAILED tests/test_a.py::test_one - AssertionError: boom
        FAILED tests/test_a.py::test_two[case - with - dashes]
        ERROR tests/test_b.py::test_three
        1 failed, 1 passed in 0.1s
    """)
    got = mod.parse_failures(log)
    assert got == {
        "tests/test_a.py::test_one",
        "tests/test_a.py::test_two[case - with - dashes]",
        "tests/test_b.py::test_three",
    }
    # the committed baseline matches the parser's id format
    baseline = mod.load_baseline()
    assert baseline and all("::" in t for t in baseline)


def test_a202_jax_random_from_import_not_flagged(tmp_path):
    """Review regression: `from jax import random` is the jit-SAFE jax
    namespace; only the stdlib `import random` binding may flag."""
    d = _lint_src(tmp_path, """
        import jax
        from jax import random

        @jax.jit
        def step(key, x):
            return x + random.normal(key, x.shape)
    """, "mod.py")
    assert d == []


def test_cli_lint_multiple_configs_one_process(tmp_path, capsys):
    good = tmp_path / "good.py"
    good.write_text(textwrap.dedent("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=8, learning_rate=1e-3)
        x = data_layer(name="x", size=8)
        outputs(fc_layer(input=x, size=4, name="out"))
    """))
    dup = tmp_path / "dup.py"
    dup.write_text(textwrap.dedent("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=8, learning_rate=1e-3)
        x = data_layer(name="x", size=8)
        a = fc_layer(input=x, size=4, name="twin")
        b = fc_layer(input=a, size=8, name="twin")
        outputs(b)
    """))
    from paddle_tpu.cli import main

    assert main(["lint", f"--config={good}"]) == 0
    # a config whose BUILD raises reports formatted diagnostics, not a
    # traceback, and rides alongside other configs in one process
    rc = main(["lint", f"--config={good}", f"--config={dup}"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "G016" in out and "'twin'" in out and "fix:" in out


def test_a201_jit_by_name_is_scope_aware(tmp_path):
    """Review regression: two factories each define a local `step`; only one
    is jitted.  The host-side step's time call must NOT flag."""
    d = _lint_src(tmp_path, """
        import time
        import jax

        def jitted_factory():
            def step(x):
                return x * 2
            return jax.jit(step)

        def host_factory():
            def step(x):
                return x, time.perf_counter()
            return step
    """, "mod.py")
    assert d == []


def test_tier1_failset_ignores_captured_log_errors():
    """Review regression: 'ERROR ...' log records captured in test output
    must not be parsed as failing node ids — only the short-summary
    section counts."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tier1_failset2", os.path.join(REPO, "scripts", "tier1_failset.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    log = textwrap.dedent("""
        ------------------------------ Captured log call ----------------------
        ERROR    root:provider.py:12 could not fetch dataset
        FAILED to connect to pserver (retrying)
        =========================== short test summary info ====================
        FAILED tests/test_a.py::test_one - RuntimeError
        1 failed in 0.1s
    """)
    assert mod.parse_failures(log) == {"tests/test_a.py::test_one"}
