"""Obs plane (ISSUE 13): span tracer, trace merge, flight recorder,
Prometheus metrics export, the shared --stats-out writer, and the A205
monotonic-clock self-lint rule.

The cross-process acceptance drill (a traced scenario producing ONE
merged timeline from >= 2 processes / >= 3 planes) lives in
tests/test_obs_e2e.py (slow, `make trace-demo`)."""

import json
import os

import pytest

from paddle_tpu import obs
from paddle_tpu.obs import merge as obs_merge
from paddle_tpu.obs.tracer import Tracer
from paddle_tpu.utils import flags


@pytest.fixture(autouse=True)
def _clean_obs():
    """Every test sees a recording, export-less singleton and default
    flags; nothing leaks between tests."""
    obs.tracer.reset()
    obs.tracer.set_recording(True)
    obs.tracer._export_dir = None
    obs.tracer.set_annotation_factory(None)
    yield
    obs.tracer.reset()
    obs.tracer.set_recording(True)
    obs.tracer._export_dir = None
    obs.tracer.set_annotation_factory(None)
    flags.reset_flags()


class FakeClock:
    def __init__(self, t0=100.0):
        self.t = t0

    def __call__(self):
        self.t += 0.001
        return self.t


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------

def test_trace_event_schema_roundtrip(tmp_path):
    t = Tracer(clock=FakeClock(), ring_events=128)
    with t.span("train_step", cat="trainer", p=0, b=3):
        t.instant("serving/submit", cat="serving", req="r1", deadline_s=0.5)
        with t.span("rpc_call:get_task", cat="rpc", rpc="a-1"):
            pass
    path = t.dump(str(tmp_path / "trace.json"))
    with open(path) as f:
        obj = json.load(f)
    assert obs_merge.validate_trace(obj) == []
    evs = [e for e in obj["traceEvents"] if e["ph"] != "M"]
    # required keys on every event
    for ev in evs:
        for k in ("ph", "ts", "pid", "tid", "name"):
            assert k in ev, ev
    # begin/end pairing, args well-formed, correlation ids intact
    assert [e["ph"] for e in evs] == ["B", "i", "B", "E", "E"]
    sub = next(e for e in evs if e["name"] == "serving/submit")
    assert sub["args"] == {"req": "r1", "deadline_s": 0.5}
    assert sub["cat"] == "serving"
    rpc_b = next(e for e in evs if e["name"] == "rpc_call:get_task")
    assert rpc_b["args"]["rpc"] == "a-1"
    # timestamps are strictly increasing with the injected monotonic clock
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts) and ts[0] < ts[-1]
    # trace context rides otherData
    other = obj["otherData"]
    assert other["pid"] == os.getpid()
    assert other["role"] == "proc"
    assert other["trace_id"]
    assert "mono_us" in other["clock_anchor"]


def test_ring_buffer_wraps_to_last_n():
    t = Tracer(clock=FakeClock(), ring_events=8)
    for i in range(50):
        t.instant(f"ev{i}")
    evs = [e for e in t.events() if e["ph"] != "M"]
    assert len(evs) == 8  # bounded memory: capacity holds
    assert [e["name"] for e in evs] == [f"ev{i}" for i in range(42, 50)]


def test_disarmed_recorder_emits_nothing():
    t = Tracer(clock=FakeClock(), ring_events=8)
    t.set_recording(False)
    with t.span("x"):
        t.instant("y")
    assert [e for e in t.events() if e["ph"] != "M"] == []
    t.set_recording(True)
    t.instant("z")
    assert len([e for e in t.events() if e["ph"] != "M"]) == 1


def test_annotation_factory_nests_spans():
    entered = []

    class Ann:
        def __init__(self, name):
            self.name = name

        def __enter__(self):
            entered.append(("in", self.name))

        def __exit__(self, *a):
            entered.append(("out", self.name))

    t = Tracer(clock=FakeClock())
    t.set_annotation_factory(Ann)
    with t.span("step"):
        pass
    assert entered == [("in", "step"), ("out", "step")]
    # disarmed recording skips the annotation too (zero-cost contract)
    t.set_recording(False)
    with t.span("step2"):
        pass
    assert len(entered) == 2


def test_validate_catches_mispairing_and_missing_keys():
    bad = {"traceEvents": [
        {"ph": "B", "ts": 1, "pid": 1, "tid": 1, "name": "a"},
        {"ph": "E", "ts": 2, "pid": 1, "tid": 1, "name": "b"},
        {"ph": "i", "ts": 3, "pid": 1, "name": "c"},  # no tid
        {"ph": "i", "ts": 4, "pid": 1, "tid": 1, "name": "d", "args": 7},
    ]}
    problems = obs_merge.validate_trace(bad)
    assert any("closes B" in p for p in problems)
    assert any("missing key 'tid'" in p for p in problems)
    assert any("args is not an object" in p for p in problems)
    assert obs_merge.validate_trace({"traceEvents": []}) == []


def test_validate_tolerates_ring_wrap_and_mid_span_dump():
    """The two EXPECTED pairing artifacts must not fail validation:
    leading orphan Es (the ring dropped their Bs at wrap) and trailing
    unclosed Bs (a flight dump fired mid-span)."""
    t = Tracer(clock=FakeClock(), ring_events=3)
    with t.span("outer"):
        with t.span("inner"):
            pass
    # ring of 3 kept [E inner, ...]: B outer evicted -> leading orphan E
    assert obs_merge.validate_trace(t.trace_object()) == []
    t2 = Tracer(clock=FakeClock(), ring_events=64)
    with t2.span("outer"):
        with t2.span("inner"):
            obj = t2.trace_object()  # dump mid-span: two unclosed Bs
    assert obs_merge.validate_trace(obj) == []


# ---------------------------------------------------------------------------
# merge: clock-skew alignment
# ---------------------------------------------------------------------------

def _synthetic_process(pid, role, skew_us, rpc_ids, client, extra=()):
    """A trace whose clock runs ``skew_us`` ahead of process 1's."""
    base = 1_000_000.0 + skew_us
    evs = []
    for i, rid in enumerate(rpc_ids):
        t0 = base + 1000 * i
        if client:
            evs.append({"ph": "B", "ts": t0, "pid": pid, "tid": 1,
                        "name": "rpc_call:get_task", "cat": "rpc",
                        "args": {"rpc": rid}})
            evs.append({"ph": "E", "ts": t0 + 40, "pid": pid, "tid": 1,
                        "name": "rpc_call:get_task", "cat": "rpc"})
        else:
            evs.append({"ph": "B", "ts": t0 + 15, "pid": pid, "tid": 1,
                        "name": "rpc:get_task", "cat": "master",
                        "args": {"rpc": rid}})
            evs.append({"ph": "E", "ts": t0 + 25, "pid": pid, "tid": 1,
                        "name": "rpc:get_task", "cat": "master"})
    evs.extend(extra)
    return {
        "traceEvents": evs,
        "otherData": {
            "pid": pid, "role": role, "trace_id": "t0",
            # wall anchors deliberately COARSE (500us off) so the test
            # proves the rpc pairs refine past them
            "clock_anchor": {"mono_us": base, "wall_us": 2_000_000.0 + 500},
        },
    }


def test_merge_aligns_known_skew_via_rpc_pairs():
    rpc_ids = [f"1-{i}" for i in range(9)]
    skew = 123_456.0
    a = _synthetic_process(1, "worker", 0.0, rpc_ids, client=True)
    b = _synthetic_process(2, "master", skew, rpc_ids, client=False)
    merged = obs_merge.merge_traces([a, b], reference_pid=1)
    off = merged["otherData"]["offsets_us"]
    assert off["1"] == 0.0
    # recovered within a fraction of the (symmetric) exchange window
    assert abs(off["2"] + skew) < 25.0
    # after alignment every server-handling span sits INSIDE its client
    # exchange span on the unified clock
    evs = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    by_rpc = {}
    for e in evs:
        rid = (e.get("args") or {}).get("rpc")
        if rid is not None:
            by_rpc.setdefault(rid, {})[e["name"]] = e["ts"]
    for rid, d in by_rpc.items():
        assert d["rpc_call:get_task"] < d["rpc:get_task"]
    assert merged["otherData"]["rpc_pair_edges"] == {"1->2": 9}
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)


def test_merge_wall_anchor_fallback_without_rpc_pairs():
    # two processes that never talked: only the wall anchors align them
    a = _synthetic_process(1, "serve", 0.0, [], client=True,
                           extra=[{"ph": "i", "ts": 1_000_100.0, "pid": 1,
                                   "tid": 1, "name": "x", "cat": "serving"}])
    b = _synthetic_process(2, "trainer", 50_000.0, [], client=False,
                           extra=[{"ph": "i", "ts": 1_050_100.0, "pid": 2,
                                   "tid": 1, "name": "y", "cat": "trainer"}])
    merged = obs_merge.merge_traces([a, b], reference_pid=1)
    off = merged["otherData"]["offsets_us"]
    # anchor math: dw_a = 2e6+500 - 1e6; dw_b = 2e6+500 - 1.05e6
    assert abs(off["2"] + 50_000.0) < 1.0
    evs = {e["name"]: e["ts"] for e in merged["traceEvents"]
           if e["ph"] != "M"}
    assert abs(evs["x"] - evs["y"]) < 1.0  # simultaneous events align


def test_merge_dir_and_cli(tmp_path):
    t1 = Tracer(clock=FakeClock(10.0), ring_events=64)
    t1.role = "serve"
    t1.instant("serving/submit", cat="serving", req="r1")
    t1.dump(str(tmp_path / "trace-serve-1.json"))
    t2 = Tracer(clock=FakeClock(20.0), ring_events=64)
    t2.role = "worker"
    t2.pid = t1.pid + 1  # distinct synthetic process
    t2.instant("elastic/lease", cat="trainer", task=0)
    t2.dump(str(tmp_path / "trace-worker-2.json"))
    merged, out = obs_merge.merge_dir(str(tmp_path))
    assert os.path.exists(out)
    assert len(merged["otherData"]["merged_pids"]) == 2
    # the CLI face over the same files
    from paddle_tpu.cli import main as cli_main

    rc = cli_main(["trace", "validate", out])
    assert rc == 0
    rc = cli_main(["trace", "merge", "--dir", str(tmp_path),
                   "--out", str(tmp_path / "m2.json")])
    assert rc == 0 and os.path.exists(tmp_path / "m2.json")


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_dump_on_scheduler_crash_guard(tmp_path):
    from paddle_tpu.serving import Request, ServingScheduler

    flags.set_flag("trace_dir", str(tmp_path))

    class BrokenEngine:
        max_slots = 2
        n_prefilling = 0
        n_free_slots = 2
        src_vocab = 50
        default_max_new_tokens = 4
        trace_counts = {}

        def __init__(self):
            self._reqs = []

        @property
        def n_live(self):
            return len(self._reqs)

        def max_src_tokens(self):
            return 64

        def admit(self, waiting):
            self._reqs.extend(waiting)
            return list(waiting)

        def step(self):
            raise RuntimeError("boom: engine corrupted")

        def outstanding_requests(self):
            return list(self._reqs)

        def preempt(self):
            return self._reqs.pop() if self._reqs else None

        def cancel(self, r):
            if r in self._reqs:
                self._reqs.remove(r)
                return True
            return False

        def cancel_by_id(self, rid):
            return None

    sched = ServingScheduler(BrokenEngine(), queue_limit=0,
                             default_deadline_s=0.0)
    r = sched.submit(Request([1, 2, 3]))
    assert r.wait(20.0), "crash guard must finalize the stranded request"
    assert r.status == "closed" and "crashed" in (r.error or "")
    sched.close()
    flight = tmp_path / f"flight-{os.getpid()}.json"
    assert flight.exists(), "crash guard must leave a postmortem"
    obj = json.loads(flight.read_text())
    assert "serving-crash-guard" in obj["otherData"]["reason"]
    names = [e["name"] for e in obj["traceEvents"]]
    assert "serving/submit" in names  # the last events show the lead-in


def test_flight_dump_on_chaos_fire(tmp_path):
    """A firing chaos point dumps the postmortem once per arming (the
    kill -9 SIGKILL variant — the dump must land BEFORE the process dies
    — is drilled in tests/test_obs_e2e.py with a real subprocess)."""
    from paddle_tpu.robustness import chaos

    flags.set_flag("trace_dir", str(tmp_path))
    obs.instant("train_step", cat="trainer", b=1)
    chaos.arm("nan_batch")
    try:
        assert chaos.fire("nan_batch")
        assert chaos.fire("nan_batch")  # fires again, dumps only once
    finally:
        chaos.disarm()
    flight = tmp_path / f"flight-{os.getpid()}.json"
    assert flight.exists()
    obj = json.loads(flight.read_text())
    assert obj["otherData"]["reason"] == "chaos:nan_batch@1"
    assert any(e["name"] == "train_step" for e in obj["traceEvents"])


def test_flight_dump_on_sentinel_divergence(tmp_path):
    from paddle_tpu.robustness.sentinel import DivergenceSentinel

    flags.set_flag("trace_dir", str(tmp_path))
    obs.instant("train_step", cat="trainer", b=0)
    s = DivergenceSentinel(skip_limit=2)
    assert s.observe(1.0, healthy=False) == "skip"
    assert s.observe(1.0, healthy=False) == "diverged"
    flight = tmp_path / f"flight-{os.getpid()}.json"
    assert flight.exists()
    obj = json.loads(flight.read_text())
    assert "sentinel-divergence" in obj["otherData"]["reason"]


# ---------------------------------------------------------------------------
# RPC correlation (client + server halves in one process)
# ---------------------------------------------------------------------------

def test_rpc_spans_share_correlation_id(tmp_path):
    from paddle_tpu import master

    d = str(tmp_path / "rio")
    os.makedirs(d)
    from paddle_tpu.io import recordio

    recordio.write_records(
        os.path.join(d, "a.rio"), iter([b"x"] * 4), max_chunk_records=2
    )
    svc = master.Service(chunks_per_task=2, snapshot_path=None)
    srv = master.Server(svc)
    try:
        cli = master.Client(srv.address)
        cli.set_dataset([os.path.join(d, "*.rio")])
        assert cli._call("stats")["n_todo"] >= 1
        cli.close()
    finally:
        srv.close()
    evs = [e for e in obs.tracer.events() if e["ph"] == "B"]
    calls = {
        (e["args"] or {}).get("rpc")
        for e in evs if e["name"].startswith("rpc_call:")
    }
    handles = {
        (e["args"] or {}).get("rpc")
        for e in evs if e["name"].startswith("rpc:") and e["args"]
    }
    shared = (calls & handles) - {None}
    assert shared, (calls, handles)  # both halves carry the same rpc id


# ---------------------------------------------------------------------------
# metrics export
# ---------------------------------------------------------------------------

_PROM_LINE = __import__("re").compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (-?(?:[0-9.eE+-]+|inf|nan))$"
)


def _parse_prometheus(text):
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        samples[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return samples


def test_prometheus_exposition_parses(tmp_path):
    from paddle_tpu.obs.metrics import (
        register_gauge, render_prometheus, unregister_gauge,
    )
    from paddle_tpu.utils.timers import StatSet

    stats = StatSet()
    stats.incr("serving/completed", 5)
    stats.incr("serving/shed", 2)
    stats.observe('lock_held/master.Service._lock "x"', 0.25)
    register_gauge("paddle_tpu_serving_queue_depth", lambda: 3,
                   "queued requests")
    register_gauge("paddle_tpu_dead_gauge", lambda: 1 / 0, "must be skipped")
    try:
        text = render_prometheus(stats)
    finally:
        unregister_gauge("paddle_tpu_serving_queue_depth")
        unregister_gauge("paddle_tpu_dead_gauge")
    samples = _parse_prometheus(text)
    assert samples["paddle_tpu_serving_queue_depth"] == 3.0
    assert not any("dead_gauge" in k for k in samples)
    assert samples[
        'paddle_tpu_serving_requests_total{status="served"}'] == 5.0
    assert samples[
        'paddle_tpu_serving_requests_total{status="shed"}'] == 2.0
    assert samples[
        'paddle_tpu_serving_requests_total{status="timeout"}'] == 0.0
    # label escaping: the quoted stat name survives
    assert any("lock_held" in k and '\\"x\\"' in k for k in samples)
    assert "# HELP paddle_tpu_serving_queue_depth queued requests" in text
    assert "# TYPE paddle_tpu_serving_requests_total counter" in text


def test_metrics_exporter_file_and_http(tmp_path):
    import urllib.request

    from paddle_tpu.obs.metrics import MetricsExporter
    from paddle_tpu.utils.timers import StatSet

    stats = StatSet()
    stats.incr("serving/completed", 7)
    out = tmp_path / "metrics.prom"
    with MetricsExporter(path=str(out), port=0, period_s=30.0,
                         stats=stats) as exp:
        assert exp.write_once()
        samples = _parse_prometheus(out.read_text())
        assert samples[
            'paddle_tpu_serving_requests_total{status="served"}'] == 7.0
        assert exp.port and exp.port > 0
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/metrics", timeout=10
        ) as resp:
            assert resp.status == 200
            body = resp.read().decode()
        assert _parse_prometheus(body)[
            'paddle_tpu_serving_requests_total{status="served"}'] == 7.0
    # closed: the endpoint is gone
    with pytest.raises(Exception):
        urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/metrics", timeout=2
        )


def test_scheduler_registers_slo_gauges(tmp_path):
    """The PR-12 SLO variables are live gauges while a scheduler exists,
    and unregister on close."""
    from paddle_tpu.obs.metrics import render_prometheus
    from paddle_tpu.serving import ServingScheduler

    class IdleEngine:
        max_slots = 2
        n_live = 0
        n_prefilling = 0
        n_free_slots = 2
        src_vocab = 50
        default_max_new_tokens = 4
        trace_counts = {}

        class pages:
            n_used = 3

        def max_src_tokens(self):
            return 64

        def admit(self, waiting):
            return []

        def step(self):
            return []

        def outstanding_requests(self):
            return []

        def cancel_by_id(self, rid):
            return None

    sched = ServingScheduler(IdleEngine(), queue_limit=0,
                             default_deadline_s=0.0)
    try:
        samples = _parse_prometheus(render_prometheus())
        assert samples["paddle_tpu_serving_queue_depth"] == 0.0
        assert samples["paddle_tpu_serving_pages_in_use"] == 3.0
        assert "paddle_tpu_serving_predicted_wait_seconds" in samples
        # a SECOND scheduler takes the names over; closing the OLD one
        # must not tear the new one's gauges down (ownership check)
        eng2 = IdleEngine()
        eng2.pages = type("P", (), {"n_used": 9})
        sched2 = ServingScheduler(eng2, queue_limit=0,
                                  default_deadline_s=0.0)
        try:
            assert _parse_prometheus(render_prometheus())[
                "paddle_tpu_serving_pages_in_use"] == 9.0
            sched.close()
            assert _parse_prometheus(render_prometheus())[
                "paddle_tpu_serving_pages_in_use"] == 9.0
        finally:
            sched2.close()
    finally:
        sched.close()
    samples = _parse_prometheus(render_prometheus())
    assert "paddle_tpu_serving_queue_depth" not in samples


# ---------------------------------------------------------------------------
# the shared --stats-out writer
# ---------------------------------------------------------------------------

def test_write_stats_json_atomic_append_and_unwritable(tmp_path, capsys):
    p = tmp_path / "stats.json"
    assert obs.write_stats_json(str(p), {"a": 1})
    assert json.loads(p.read_text()) == {"a": 1}
    assert obs.write_stats_json(str(p), {"a": 2})  # replace, not append
    assert json.loads(p.read_text()) == {"a": 2}
    ap = tmp_path / "log.jsonl"
    obs.write_stats_json(str(ap), {"n": 1}, append=True)
    obs.write_stats_json(str(ap), {"n": 2}, append=True)
    assert [json.loads(l) for l in ap.read_text().splitlines()] == [
        {"n": 1}, {"n": 2},
    ]
    # uniform unwritable-path behavior: warn + False, never raise
    bad = str(tmp_path / "no" / "such" / "dir" / "s.json")
    assert obs.write_stats_json(bad, {"a": 1}) is False
    assert obs.write_stats_json(bad, {"a": 1}, append=True) is False
    assert "unwritable" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# satellites: StatSet column alignment + A205 self-lint rule
# ---------------------------------------------------------------------------

def test_statset_print_aligns_long_names(capsys):
    from paddle_tpu.utils.timers import StatSet

    s = StatSet()
    s.incr("feed")
    s.observe("lock_held/master.Server._conns_lock-and-then-some", 0.5)
    out = s.print_all_status()
    capsys.readouterr()
    lines = out.splitlines()
    # every row (header included) lays the same columns: equal lengths
    assert len({len(ln) for ln in lines}) == 1
    assert lines[0].rstrip().endswith("max_ms")
    # numeric columns still right-aligned after the longest name
    for ln in lines[1:]:
        assert not ln.startswith(" ")


def _lint_obs_source(tmp_path, src):
    from paddle_tpu.analysis.ast_rules import lint_file

    d = tmp_path / "paddle_tpu" / "obs"
    d.mkdir(parents=True, exist_ok=True)
    p = d / "mod.py"
    p.write_text(src)
    return lint_file(str(p), root=str(tmp_path))


def test_a205_flags_wall_clock_in_obs(tmp_path):
    diags = _lint_obs_source(
        tmp_path, "import time\nts = time.time()\n"
    )
    assert [d.rule for d in diags] == ["A205"]
    diags = _lint_obs_source(
        tmp_path, "import time\nts = time.time_ns()\n"
    )
    assert [d.rule for d in diags] == ["A205"]


def test_a205_sees_through_aliases(tmp_path):
    # `from time import time` and `import time as t` must not slip past
    # the ban; `from time import monotonic` stays legal
    diags = _lint_obs_source(
        tmp_path, "from time import time\nts = time()\n"
    )
    assert [d.rule for d in diags] == ["A205"]
    diags = _lint_obs_source(
        tmp_path, "import time as t\nts = t.time()\n"
    )
    assert [d.rule for d in diags] == ["A205"]
    assert _lint_obs_source(
        tmp_path, "from time import monotonic\nts = monotonic()\n"
    ) == []


def test_a205_pragma_requires_justification(tmp_path):
    ok = (
        "import time\n"
        "anchor = time.time()  # obs: allow-wall-clock merge anchor only\n"
        "mono = time.monotonic()\n"
    )
    assert _lint_obs_source(tmp_path, ok) == []
    empty = (
        "import time\n"
        "anchor = time.time()  # obs: allow-wall-clock\n"
    )
    diags = _lint_obs_source(tmp_path, empty)
    assert [d.rule for d in diags] == ["A205"]
    assert "justification" in diags[0].message


def test_a205_does_not_fire_outside_obs(tmp_path):
    from paddle_tpu.analysis.ast_rules import lint_file

    d = tmp_path / "paddle_tpu" / "reader"
    d.mkdir(parents=True)
    p = d / "mod.py"
    p.write_text("import time\nts = time.time()\n")
    assert [x.rule for x in lint_file(str(p), root=str(tmp_path))] == []


def test_obs_package_lints_clean():
    """The new plane passes its own rules: A-rules (incl. A205) over
    paddle_tpu/obs/ report nothing."""
    import paddle_tpu
    from paddle_tpu.analysis.ast_rules import lint_file

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(paddle_tpu.__file__)
    ))
    obs_dir = os.path.join(root, "paddle_tpu", "obs")
    diags = []
    for fn in sorted(os.listdir(obs_dir)):
        if fn.endswith(".py"):
            diags.extend(lint_file(os.path.join(obs_dir, fn), root=root))
    assert diags == [], [str(d) for d in diags]


def test_prometheus_per_class_ledger_series():
    """The class-labeled requests_total series: the scheduler's
    serving/class/<class>/<status> counters render as labeled series of
    the same family, all statuses included (served too)."""
    from paddle_tpu.obs.metrics import render_prometheus
    from paddle_tpu.utils.timers import StatSet

    stats = StatSet()
    stats.incr("serving/class/p0/served", 3)
    stats.incr("serving/class/p2/shed", 2)
    stats.incr("serving/class/p2/served", 1)
    samples = _parse_prometheus(render_prometheus(stats))
    assert samples[
        'paddle_tpu_serving_requests_total{class="p0",status="served"}'
    ] == 3.0
    assert samples[
        'paddle_tpu_serving_requests_total{class="p2",status="shed"}'
    ] == 2.0
    assert samples[
        'paddle_tpu_serving_requests_total{class="p2",status="served"}'
    ] == 1.0
