"""Remaining layer/optimizer inventory (VERDICT missing #8): grouped
conv-transpose, mdlstmemory, get_output, agent family, SparseMomentum,
static pruning hook."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layers
from paddle_tpu.core.batch import SeqTensor, seq
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names

from tests.layer_grad_util import check_layer_grad


def _run(out_layer, batch, seed=0):
    net = CompiledNetwork(Topology([out_layer]))
    params, state = net.init(jax.random.PRNGKey(seed))
    outs, _ = net.apply(params, batch, state=state, train=False)
    return outs, params


# ---------------------------------------------------------------------------
# grouped conv-transpose
# ---------------------------------------------------------------------------


def test_grouped_conv_transpose_shapes_and_grad():
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(4 * 5 * 5), height=5, width=5)
    up = layers.img_conv(
        x, filter_size=2, num_filters=4, stride=2, groups=2, trans=True,
        act=paddle.activation.Identity(), name="up",
    )
    assert up.conf.attrs["out_h"] == 10 and up.conf.attrs["out_w"] == 10
    outs, params = _run(up, {"x": SeqTensor(np.random.rand(2, 100).astype(np.float32))})
    assert outs["up"].data.shape == (2, 10, 10, 4)
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(4 * 5 * 5), height=5, width=5)
    up = layers.img_conv(
        x, filter_size=2, num_filters=4, stride=2, groups=2, trans=True,
        act=paddle.activation.Identity(),
    )
    check_layer_grad(up, batch_size=2)


def test_grouped_conv_transpose_group_independence():
    """Group 0's output channels must not depend on group 1's input
    channels."""
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(2 * 4 * 4), height=4, width=4)
    up = layers.img_conv(
        x, filter_size=2, num_filters=2, stride=2, groups=2, trans=True,
        act=paddle.activation.Identity(), bias_attr=False, name="up",
    )
    net = CompiledNetwork(Topology([up]))
    params, state = net.init(jax.random.PRNGKey(1))
    rng = np.random.RandomState(0)
    base = rng.rand(1, 2, 4, 4).astype(np.float32)  # CHW flat
    pert = base.copy()
    pert[0, 1] += 5.0  # perturb channel 1 (group 1) only
    o1, _ = net.apply(params, {"x": SeqTensor(base.reshape(1, -1))}, state=state)
    o2, _ = net.apply(params, {"x": SeqTensor(pert.reshape(1, -1))}, state=state)
    a, b = np.asarray(o1["up"].data), np.asarray(o2["up"].data)
    np.testing.assert_allclose(a[..., 0], b[..., 0], atol=1e-6)  # group 0 ch
    assert np.abs(a[..., 1] - b[..., 1]).max() > 1e-3  # group 1 ch changed


# ---------------------------------------------------------------------------
# mdlstmemory
# ---------------------------------------------------------------------------


def _md_net(n=3, hw=4):
    x = layers.data(
        "x", paddle.data_type.dense_vector(5 * n * hw * hw), height=hw, width=hw
    )
    return x, layers.mdlstmemory(x, size=n, name="md")


def test_mdlstm_shape_and_grad():
    reset_auto_names()
    x, md = _md_net()
    outs, _ = _run(md, {"x": SeqTensor(np.random.rand(2, 5 * 3 * 16).astype(np.float32))})
    assert outs["md"].data.shape == (2, 4, 4, 3)
    reset_auto_names()
    x, md = _md_net()
    check_layer_grad(md, batch_size=2, atol=8e-2, rtol=8e-2)


def test_mdlstm_causality():
    """Output at (0,0) must not depend on input at (2,2); with both
    reverses, the dependency flips."""
    reset_auto_names()
    n, hw = 2, 3
    x, md = _md_net(n, hw)
    net = CompiledNetwork(Topology([md]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    base = rng.rand(1, 5 * n, hw, hw).astype(np.float32)
    pert = base.copy()
    pert[0, :, 2, 2] += 3.0
    o1, _ = net.apply(params, {"x": SeqTensor(base.reshape(1, -1))}, state=state)
    o2, _ = net.apply(params, {"x": SeqTensor(pert.reshape(1, -1))}, state=state)
    a, b = np.asarray(o1["md"].data), np.asarray(o2["md"].data)
    np.testing.assert_allclose(a[0, 0, 0], b[0, 0, 0], atol=1e-6)
    assert np.abs(a[0, 2, 2] - b[0, 2, 2]).max() > 1e-4


def test_mdlstm_reverse_direction():
    reset_auto_names()
    n, hw = 2, 3
    x = layers.data(
        "x", paddle.data_type.dense_vector(5 * n * hw * hw), height=hw, width=hw
    )
    md = layers.mdlstmemory(x, size=n, reverse_h=True, reverse_w=True, name="md")
    net = CompiledNetwork(Topology([md]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    base = rng.rand(1, 5 * n, hw, hw).astype(np.float32)
    pert = base.copy()
    pert[0, :, 0, 0] += 3.0  # perturb the (0,0) corner
    o1, _ = net.apply(params, {"x": SeqTensor(base.reshape(1, -1))}, state=state)
    o2, _ = net.apply(params, {"x": SeqTensor(pert.reshape(1, -1))}, state=state)
    a, b = np.asarray(o1["md"].data), np.asarray(o2["md"].data)
    # reversed scan: (2,2) is now upstream of (0,0) -> unaffected
    np.testing.assert_allclose(a[0, 2, 2], b[0, 2, 2], atol=1e-6)
    assert np.abs(a[0, 0, 0] - b[0, 0, 0]).max() > 1e-4


# ---------------------------------------------------------------------------
# get_output / agents
# ---------------------------------------------------------------------------


def test_get_output_reads_aux_logits():
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(4))
    sm = layers.fc(x, size=3, act=paddle.activation.Softmax(), name="sm")
    logits = layers.get_output(sm, "logits")
    cost = layers.cross_entropy_cost(
        input=sm, label=layers.data("y", paddle.data_type.integer_value(3))
    )
    net = CompiledNetwork(Topology([cost, logits]))
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {
        "x": SeqTensor(np.random.rand(2, 4).astype(np.float32)),
        "y": SeqTensor(np.asarray([0, 2], np.int32)),
    }
    outs, _ = net.apply(params, batch, state=state)
    lg = np.asarray(outs[logits.name].data)
    probs = np.asarray(outs["sm"].data)
    np.testing.assert_allclose(
        np.exp(lg) / np.exp(lg).sum(-1, keepdims=True), probs, rtol=1e-5
    )


def test_get_output_unknown_arg_errors():
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(4))
    h = layers.fc(x, size=3, act=paddle.activation.Tanh(), name="h")
    bad = layers.get_output(h, "nope")
    net = CompiledNetwork(Topology([bad]))
    params, state = net.init(jax.random.PRNGKey(0))
    with pytest.raises(KeyError):
        net.apply(params, {"x": SeqTensor(np.zeros((1, 4), np.float32))}, state=state)


def test_agent_and_scatter_gather():
    reset_auto_names()
    src = layers.data("src", paddle.data_type.dense_vector_sequence(2))
    ids = layers.data("ids", paddle.data_type.integer_value(4))
    ag = layers.agent(src, name="view")
    sc = layers.scatter_agent(src, ids, name="sc")
    ga = layers.gather_agent([src, src], name="ga")
    net = CompiledNetwork(Topology([ag, sc, ga]))
    params, state = net.init(jax.random.PRNGKey(0))
    data = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
    batch = {
        "src": seq(data, [3, 2]),
        "ids": SeqTensor(np.asarray([1, 1, 0], np.int32)),
    }
    outs, _ = net.apply(params, batch, state=state)
    np.testing.assert_allclose(np.asarray(outs["view"].data), data)
    got = outs["sc"]
    np.testing.assert_allclose(np.asarray(got.data), data[[1, 1, 0]])
    np.testing.assert_array_equal(np.asarray(got.lengths), [2, 2, 3])
    ga_out = outs["ga"]
    np.testing.assert_array_equal(np.asarray(ga_out.lengths), [6, 4])
    # sample 1 (len 2): gathered = its 2 rows twice back-to-back
    np.testing.assert_allclose(np.asarray(ga_out.data[1, :4]),
                               np.concatenate([data[1, :2], data[1, :2]]))


# ---------------------------------------------------------------------------
# SparseMomentum + pruning hook
# ---------------------------------------------------------------------------


def _toy_trainer(update_eq, param_attr=None):
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(6))
    y = layers.data("y", paddle.data_type.integer_value(3))
    h = layers.fc(x, size=12, act=paddle.activation.Tanh(),
                  param_attr=param_attr, name="h")
    pred = layers.fc(h, size=3, act=paddle.activation.Softmax())
    cost = layers.classification_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost=cost, parameters=params, update_equation=update_eq)
    rng = np.random.RandomState(0)
    centers = rng.randn(3, 6) * 2

    def reader():
        for _ in range(90):
            c = rng.randint(3)
            yield centers[c] + rng.randn(6) * 0.3, c

    costs = []
    trainer.train(reader=paddle.batch(reader, 15), num_passes=5,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    return trainer, costs


def test_sparse_momentum_trains():
    trainer, costs = _toy_trainer(
        paddle.optimizer.SparseMomentum(momentum=0.9, learning_rate=0.1)
    )
    assert np.mean(costs[-3:]) < 0.5 * np.mean(costs[:3])


def test_static_pruning_hook():
    hook = paddle.attr.HookAttribute(type="pruning", sparsity_ratio=0.5)
    trainer, costs = _toy_trainer(
        paddle.optimizer.Adam(learning_rate=5e-2),
        param_attr=paddle.attr.ParamAttr(update_hooks=hook),
    )
    w = np.asarray(trainer.parameters.params["h"]["w0"])
    sparsity = float((w == 0).mean())
    assert sparsity >= 0.45, sparsity  # ~half the weights pinned to zero
    # and the model still learned
    assert np.mean(costs[-3:]) < 0.6 * np.mean(costs[:3])
    # bias was NOT pruned
    b = np.asarray(trainer.parameters.params["h"]["b"])
    assert (b != 0).mean() > 0.5


def test_img_cmrnorm_matches_reference_formula():
    """out = x * (1 + scale * window_sum(x^2))^(-power), window across
    channels centered per CrossMapNormalOp.cpp."""
    reset_auto_names()
    c, hw, size, scale, power = 6, 3, 4, 0.01, 0.75  # EVEN size: window
    # start -((size-1)//2), extends one further right (CrossMapNormalOp)
    x_l = layers.data("x", paddle.data_type.dense_vector(c * hw * hw),
                      height=hw, width=hw)
    out = layers.img_cmrnorm(x_l, size=size, scale=scale, power=power, name="n")
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    chw = rng.randn(2, c, hw, hw).astype(np.float32)
    outs, _ = net.apply(params, {"x": SeqTensor(chw.reshape(2, -1))}, state=state)
    got = np.asarray(outs["n"].data)  # NHWC
    x = chw.transpose(0, 2, 3, 1)
    want = np.zeros_like(x)
    half = (size - 1) // 2
    for ch in range(c):
        lo, hi = max(0, ch - half), min(c, ch + size - half)
        denom = 1.0 + scale * (x[..., lo:hi] ** 2).sum(-1)
        want[..., ch] = x[..., ch] * denom ** (-power)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    reset_auto_names()
    x_l = layers.data("x", paddle.data_type.dense_vector(c * hw * hw),
                      height=hw, width=hw)
    check_layer_grad(layers.img_cmrnorm(x_l, size=3), batch_size=2)


def test_crop_offsets_align_to_axis():
    """crop_layer offsets align to the cropped axes starting at `axis`
    (reference crop_layer: axis=2, offset=[h, w])."""
    import jax
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu import layers as L
    import paddle_tpu as paddle

    reset_auto_names()
    d = L.data("img", paddle.data_type.dense_vector(4 * 5), height=4, width=5)
    c = L.crop_layer(input=d, axis=2, offset=[1, 2], shape=[2, 2])
    assert c.size == 2 * 2
    net = CompiledNetwork(Topology([c]))
    params, state = net.init(jax.random.PRNGKey(0))
    x = np.arange(20, dtype=np.float32).reshape(1, 20)
    outs, _ = net.apply(params, {"img": SeqTensor(x)}, state=state, train=False)
    img = x.reshape(4, 5)
    expect = img[1:3, 2:4].reshape(-1)
    np.testing.assert_allclose(
        np.asarray(outs[c.name].data).reshape(-1), expect
    )


def test_error_clipping_threshold_clips_gradient():
    """ExtraAttr(error_clipping_threshold=t) clips the cotangent flowing
    into the layer output (reference Layer.cpp backwardActivation)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.attr import ExtraAttr
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu import layers as L
    from paddle_tpu import activation as A
    import paddle_tpu as paddle

    reset_auto_names()
    t = 0.01
    d = L.data("x", paddle.data_type.dense_vector(3))
    h = L.fc(
        d, size=3, act=A.Identity(), bias_attr=False,
        layer_attr=ExtraAttr(error_clipping_threshold=t),
    )
    # cost = 100 * sum(h): dcost/dh = 100 per element -> clipped to t
    scaled = L.slope_intercept(h, slope=100.0)
    cost = L.sum_cost(scaled)
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {"x": SeqTensor(np.ones((1, 3), np.float32))}

    def loss(p):
        outs, _ = net.apply(p, batch, state=state, train=True)
        return jnp.sum(outs[cost.name].data)

    g = jax.grad(loss)(params)[h.name]["w0"]
    # dL/dW = x^T @ clip(100, t) -> every entry == t
    np.testing.assert_allclose(np.asarray(g), t, rtol=1e-5)
    # train=False leaves gradients untouched
    def loss_eval(p):
        outs, _ = net.apply(p, batch, state=state, train=False)
        return jnp.sum(outs[cost.name].data)

    g2 = jax.grad(loss_eval)(params)[h.name]["w0"]
    np.testing.assert_allclose(np.asarray(g2), 100.0, rtol=1e-5)


def test_stride_pooling_rejects_nested():
    import jax
    import pytest
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu import layers as L
    from paddle_tpu import pooling as P
    import paddle_tpu as paddle

    reset_auto_names()
    d = L.data(
        "seq", paddle.data_type.dense_vector_sub_sequence(2)
    )
    pooled = L.pooling(d, P.Sum(), stride=2)
    net = CompiledNetwork(Topology([pooled]))
    params, state = net.init(jax.random.PRNGKey(0))
    nested = SeqTensor(
        np.zeros((1, 2, 3, 2), np.float32),
        np.asarray([2], np.int32),
        np.asarray([[3, 2]], np.int32),
    )
    with pytest.raises(AssertionError, match="nested"):
        net.apply(params, {"seq": nested}, state=state, train=False)


def test_embedding_out_of_range_ids_contribute_zero():
    """Reference table kernels SKIP ids outside [0, tableSize)
    (hl_table_apply.cu KeMatrixAddRows bounds check): providers emit
    0xffffffff == -1 for OOV-ignored tokens (sequence_tagging
    dataprovider.py OOV_POLICY_IGNORE).  The lookup must yield a zero row
    — jnp's default clamp would silently read the edge row — and the
    backward must scatter nothing into the table for those positions."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology

    reset_auto_names()
    vocab, dim = 5, 3
    ids = layers.data("ids", paddle.data_type.integer_value_sequence(vocab))
    emb = layers.embedding(ids, size=dim, name="emb")
    net = CompiledNetwork(Topology([emb]))
    params, state = net.init(jax.random.PRNGKey(0))
    w = np.asarray(params["emb"]["w"])

    idx = np.array([[0, vocab - 1, -1, vocab]], np.int32)  # last two invalid
    batch = {"ids": SeqTensor(jnp.asarray(idx), jnp.asarray([4], jnp.int32))}
    outs, _ = net.apply(params, batch, state=state, train=False)
    got = np.asarray(outs["emb"].data)[0]
    np.testing.assert_allclose(got[0], w[0], rtol=1e-6)
    np.testing.assert_allclose(got[1], w[vocab - 1], rtol=1e-6)
    np.testing.assert_allclose(got[2], 0.0, atol=0)
    np.testing.assert_allclose(got[3], 0.0, atol=0)

    # backward: only valid rows receive gradient
    def loss(p):
        o, _ = net.apply(p, batch, state=state, train=False)
        return o["emb"].data.sum()

    g = np.asarray(jax.grad(loss)(params)["emb"]["w"])
    assert g[0].sum() != 0 and g[vocab - 1].sum() != 0
    rows_touched = {i for i in range(vocab) if np.abs(g[i]).sum() > 0}
    assert rows_touched == {0, vocab - 1}, rows_touched
