"""Checkpoint/resume tests (reference model: Parameter.cpp save/load round
trips, go/pserver checkpoint CRC, v2 trainer save cadence)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import checkpoint as ckpt
from paddle_tpu.core.topology import reset_auto_names


def _make_trainer(seed=0):
    reset_auto_names()
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1, act=paddle.activation.Linear())
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    params = paddle.parameters.create(cost, seed=seed)
    return (
        paddle.trainer.SGD(
            cost=cost,
            parameters=params,
            update_equation=paddle.optimizer.Momentum(
                learning_rate=0.05, momentum=0.9
            ),
        ),
        cost,
    )


def _data_reader(n=64, seed=0):
    w = np.array([1.0, -1.0, 2.0, 0.5], np.float32)

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            xv = rng.randn(4).astype(np.float32)
            yield xv, np.array([float(xv @ w)], np.float32)

    return reader


def test_v1_parameter_dir_roundtrip(tmp_path):
    trainer, _ = _make_trainer()
    d = str(tmp_path / "pdir")
    before = {n: np.array(trainer.parameters.get(n)) for n in trainer.parameters.names()}
    ckpt.save_parameter_dir(trainer.parameters, d)
    # perturb, then reload
    for n in trainer.parameters.names():
        trainer.parameters.set(n, np.zeros_like(before[n]))
    ckpt.load_parameter_dir(trainer.parameters, d)
    for n, v in before.items():
        np.testing.assert_allclose(np.array(trainer.parameters.get(n)), v)


def test_v1_header_layout(tmp_path):
    trainer, _ = _make_trainer()
    d = str(tmp_path / "pdir")
    ckpt.save_parameter_dir(trainer.parameters, d)
    fname = sorted(os.listdir(d))[0]
    raw = open(os.path.join(d, fname), "rb").read()
    import struct

    version, value_size, count = struct.unpack("<iIQ", raw[:16])
    assert version == 0 and value_size == 4
    assert len(raw) == 16 + 4 * count


def test_manager_save_restore_and_crc(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3), "b": {"c": np.ones(4)}}
    mgr.save(10, tree)
    restored, extra = mgr.restore(10, tree)
    np.testing.assert_allclose(restored["a"], tree["a"])
    np.testing.assert_allclose(restored["b"]["c"], tree["b"]["c"])
    # corruption is detected
    data = os.path.join(str(tmp_path / "ck"), "ckpt-00000010", "state.npz")
    with open(data, "r+b") as f:
        f.seek(30)
        f.write(b"\xff\xff")
    with pytest.raises(IOError):
        mgr.restore(10, tree)


def test_manager_retention_and_latest(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    tree = {"a": np.zeros(2)}
    for s in (1, 2, 3, 4):
        mgr.save(s, tree)
    assert mgr.all_steps() == [3, 4]
    assert mgr.latest_step() == 4


def test_manager_async(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    tree = {"a": np.full(8, 3.0)}
    mgr.save(5, tree, async_=True)
    mgr.wait()
    step, restored, _ = mgr.restore_latest(tree)
    assert step == 5
    np.testing.assert_allclose(restored["a"], tree["a"])


def test_trainer_pass_saving_and_resume(tmp_path):
    trainer, _ = _make_trainer(seed=1)
    save_dir = str(tmp_path / "out")
    trainer.train(
        reader=paddle.batch(_data_reader(), 16),
        num_passes=2,
        save_dir=save_dir,
        saving_period=1,
    )
    assert os.path.isdir(os.path.join(save_dir, "pass-00000"))
    assert os.path.isdir(os.path.join(save_dir, "pass-00001"))
    assert os.path.exists(os.path.join(save_dir, "pass-00001", "params.tar"))

    # resume into a freshly-initialized trainer: values must match pass 1
    trainer2, _ = _make_trainer(seed=9)
    trainer2.load_pass(save_dir, 1)
    for n in trainer.parameters.names():
        np.testing.assert_allclose(
            np.array(trainer2.parameters.get(n)),
            np.array(trainer.parameters.get(n)),
            rtol=1e-6,
        )


def test_full_checkpoint_resume_is_bitwise(tmp_path):
    """Training from a restored full checkpoint (incl. momentum) must match
    uninterrupted training — the reference's test_CompareTwoNets-style golden."""
    reader = paddle.batch(_data_reader(n=96, seed=3), 16)

    # run A: 4 passes straight
    ta, _ = _make_trainer(seed=2)
    ta.train(reader=reader, num_passes=4)

    # run B: 2 passes, full checkpoint, restore into new trainer, 2 more
    tb, _ = _make_trainer(seed=2)
    tb.train(reader=reader, num_passes=2)
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    tb.save_checkpoint(mgr)
    tc, _ = _make_trainer(seed=99)  # different init — must be overwritten
    assert tc.restore_checkpoint(mgr)
    tc.train(reader=reader, num_passes=2)

    for n in ta.parameters.names():
        np.testing.assert_allclose(
            np.array(tc.parameters.get(n)),
            np.array(ta.parameters.get(n)),
            rtol=1e-5,
            atol=1e-6,
        )


def test_saving_period_by_batches(tmp_path):
    trainer, _ = _make_trainer()
    save_dir = str(tmp_path / "out")
    trainer.train(
        reader=paddle.batch(_data_reader(n=64), 16),
        num_passes=1,
        save_dir=save_dir,
        saving_period_by_batches=2,
    )
    assert os.path.isdir(os.path.join(save_dir, "pass-00000-batch-2"))
    assert os.path.isdir(os.path.join(save_dir, "pass-00000-batch-4"))


def test_meta_json(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    mgr.save(7, {"a": np.zeros(3)}, extra={"pass_id": 2})
    meta = mgr.meta(7)
    assert meta["step"] == 7 and meta["extra"]["pass_id"] == 2
    assert "crc32" in meta and meta["n_leaves"] == 1


# ---------------------------------------------------------------------------
# sharded multi-writer checkpoints (elastic scale-out plane)
# ---------------------------------------------------------------------------

def _tree():
    return {
        "a": np.arange(6, dtype=np.float32),
        "b": {"w": np.ones((2, 3), np.float32), "v": np.zeros(4, np.float32)},
        "c": np.float32(7),
    }


def test_sharded_save_commit_restore_roundtrip(tmp_path):
    """Two writers, one manifest commit: the merged restore equals the
    source tree, and the manifest carries the step/extra."""
    d = str(tmp_path / "ck")
    t = _tree()
    w0, w1 = ckpt.CheckpointManager(d), ckpt.CheckpointManager(d)
    w0.save_shard(1, 0, 2, t)
    w1.save_shard(1, 1, 2, t, async_=True)
    w1.wait()
    assert w0.commit(1, 2, extra={"pass_id": 0})
    assert w0.commit(1, 2)  # idempotent from any worker
    step, restored, extra = w0.restore_latest(t)
    assert step == 1 and extra == {"pass_id": 0}
    np.testing.assert_array_equal(restored["a"], t["a"])
    np.testing.assert_array_equal(restored["b"]["w"], t["b"]["w"])
    np.testing.assert_array_equal(restored["b"]["v"], t["b"]["v"])
    assert w0.meta(1)["num_shards"] == 2


def test_commit_refuses_while_a_shard_is_missing(tmp_path):
    """A writer died before its shard landed: the step must stay
    unrestorable (no manifest) rather than commit a partial state."""
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    mgr.save_shard(1, 0, 3, _tree())
    mgr.save_shard(1, 2, 3, _tree())
    assert mgr.commit(1, 3) is False
    assert mgr.restore_latest(_tree()) is None


def test_torn_shard_falls_back_to_previous_complete_manifest(tmp_path):
    """Save under load across two steps, tear ONE shard of the newest
    committed step: restore_latest must fall back to the previous complete
    manifest (the acceptance bullet)."""
    from paddle_tpu.robustness import chaos

    d = str(tmp_path / "ck")
    mgr = ckpt.CheckpointManager(d)
    t1, t2 = _tree(), _tree()
    t2["a"] = t2["a"] * 2
    for step, t in ((1, t1), (2, t2)):
        mgr.save_shard(step, 0, 2, t)
        mgr.save_shard(step, 1, 2, t)
        assert mgr.commit(step, 2, extra={"pass_id": step - 1})
    chaos.tear_file(
        os.path.join(d, "ckpt-00000002", "shard-00000-of-00002.npz")
    )
    step, restored, extra = mgr.restore_latest(t1)
    assert step == 1 and extra["pass_id"] == 0
    np.testing.assert_array_equal(restored["a"], t1["a"])


def test_uncommitted_shard_set_is_walked_past(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    t = _tree()
    mgr.save_shard(1, 0, 1, t)
    assert mgr.commit(1, 1)
    mgr.save_shard(2, 0, 2, t)  # second writer never arrives, no commit
    step, _, _ = mgr.restore_latest(t)
    assert step == 1


def test_shard_leaf_partition_is_disjoint_and_total(tmp_path):
    """Every flattened leaf lands in exactly one shard."""
    d = str(tmp_path / "ck")
    mgr = ckpt.CheckpointManager(d)
    t = _tree()
    for i in range(3):
        mgr.save_shard(5, i, 3, t)
    seen = []
    for i in range(3):
        with np.load(
            os.path.join(d, "ckpt-00000005", f"shard-{i:05d}-of-00003.npz")
        ) as z:
            seen.extend(z.files)
    assert sorted(seen) == sorted(set(seen))  # disjoint
    assert len(seen) == 4  # a, b.w, b.v, c — total


def test_retention_never_reaps_last_committed_manifest(tmp_path):
    """Uncommitted/stranded shard sets must not count toward max_to_keep:
    steps 6/7 stranded (writers died, no manifest), step 8 committed but
    torn post-commit — the retention pass that 8's commit triggers must
    keep the old committed step 5, and restore_latest must land on it."""
    from paddle_tpu.robustness import chaos

    d = str(tmp_path / "ck")
    mgr = ckpt.CheckpointManager(d, max_to_keep=3)
    t = _tree()
    mgr.save_shard(5, 0, 1, t)
    assert mgr.commit(5, 1, extra={"pass_id": 4})
    for step in (6, 7):  # writers died: shards landed, no manifest
        mgr.save_shard(step, 0, 2, t)
        assert not mgr.commit(step, 2)
    mgr.save_shard(8, 0, 1, t)
    assert mgr.commit(8, 1)  # triggers retention
    chaos.tear_file(
        os.path.join(d, "ckpt-00000008", "shard-00000-of-00001.npz")
    )
    assert 5 in mgr.all_steps()  # retention kept the restorable step
    step, _, extra = mgr.restore_latest(t)
    assert step == 5 and extra["pass_id"] == 4


# satellite: a background-thread write failure must never vanish — it
# re-raises from wait() AND from the next save
def test_async_write_error_reraises_from_wait(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))

    def boom(*a, **k):
        raise IOError("disk full")

    mgr._write = boom
    mgr.save(1, _tree(), async_=True)
    with pytest.raises(IOError, match="disk full"):
        mgr.wait()
    mgr.wait()  # the error is consumed exactly once


def test_async_write_error_reraises_from_next_save(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
    orig = mgr._write

    def boom(*a, **k):
        raise IOError("disk full")

    mgr._write = boom
    mgr.save(1, _tree(), async_=True)
    mgr._pending.join()  # let the failure land without consuming it
    mgr._write = orig
    with pytest.raises(IOError, match="disk full"):
        mgr.save(2, _tree())
    # and the failed step never became restorable
    assert mgr.restore_latest(_tree()) is None


def test_async_shard_write_error_reraises(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))

    def boom(*a, **k):
        raise IOError("enospc")

    mgr._write_shard = boom
    mgr.save_shard(1, 0, 2, _tree(), async_=True)
    with pytest.raises(IOError, match="enospc"):
        mgr.wait()


def test_v2_model_save_load_roundtrip(tmp_path):
    """paddle.model.save_model/load_model (reference v2/model.py): plain tar
    without a master; master arbitration grants exactly one trainer."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core.topology import reset_auto_names

    reset_auto_names()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    fc = paddle.layer.fc(x, size=3, act=paddle.activation.Softmax())
    params = paddle.parameters.create(fc)
    p = str(tmp_path / "model.tar")
    out = paddle.model.save_model(params, p)
    assert out == p

    params2 = paddle.parameters.create(fc, seed=99)  # different init
    before = np.asarray(params2.params["__fc_layer_0__"]["w0"]).copy()
    paddle.model.load_model(params2, p)
    after = np.asarray(params2.params["__fc_layer_0__"]["w0"])
    want = np.asarray(params.params["__fc_layer_0__"]["w0"])
    np.testing.assert_allclose(after, want)
    assert not np.allclose(before, want)  # it actually changed something

    # master arbitration: only one of two "trainers" gets the grant
    from paddle_tpu.master import Client, Service

    svc = Service()
    a = Client(svc, trainer_id="a")
    b = Client(svc, trainer_id="b")
    got_a = paddle.model.save_model(params, str(tmp_path / "dist"), master=a)
    got_b = paddle.model.save_model(params, str(tmp_path / "dist"), master=b)
    assert (got_a is None) != (got_b is None)  # exactly one saved
    saved = got_a or got_b
    assert saved.endswith("model.tar")
    import os

    assert os.path.exists(saved)


def test_plotcurve_parses_cli_and_reference_logs(tmp_path):
    """utils.plotcurve reads both this CLI's 'Pass N: mean cost X' lines and
    reference-style 'AvgCost=X' lines (reference utils/plotcurve.py)."""
    from paddle_tpu.utils.plotcurve import main, parse_log

    log = tmp_path / "train.log"
    log.write_text(
        "Pass 0: mean cost 2.500000 (1.0s elapsed)\n"
        "I some noise\n"
        "Pass 1: mean cost 1.250000 (2.0s elapsed)\n"
        ".....\n"
        "Batch=200 samples=25600 AvgCost=0.625 Eval: err=0.2\n"
    )
    curves = parse_log(log.read_text().splitlines())
    assert curves["cost"] == [2.5, 1.25]
    assert curves["AvgCost"] == [0.625]

    out = tmp_path / "plot.png"
    rc = main(["-i", str(log), "-o", str(out)])
    assert rc == 0
