"""recurrent_group tests — the test_RecurrentGradientMachine/
test_RecurrentLayer equivalents (reference: paddle/gserver/tests/
test_RecurrentLayer.cpp compares recurrent_group output against the fused
recurrent layer with identical weights)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names

from layer_grad_util import check_layer_grad, rand_batch_for

L = paddle.layer
A = paddle.activation


@pytest.fixture(autouse=True)
def _reset_names():
    reset_auto_names()
    yield


H = 6


def make_batch(seed=0, b=4, t=7, d=H):
    rng = np.random.RandomState(seed)
    lengths = np.array([7, 3, 5, 1], dtype=np.int32)[:b]
    data = rng.randn(b, t, d).astype(np.float32)
    mask = (np.arange(t)[None, :] < lengths[:, None]).astype(np.float32)
    data = data * mask[..., None]
    return SeqTensor(jnp.asarray(data), jnp.asarray(lengths))


def test_group_matches_fused_recurrent():
    """A simple-RNN built from fc+addto inside recurrent_group must equal the
    fused `recurrent` layer given the same weights."""
    x = L.data("x", paddle.data_type.dense_vector_sequence(H))

    def step(x_t):
        mem = L.memory("h", H)
        hm = L.fc(mem, H, act=A.Identity(), bias_attr=False, name="hproj")
        h = L.addto([x_t, hm], act=A.Tanh(), bias_attr=True, name="h")
        return h

    grp = L.recurrent_group(step, x, name="grp")
    fused = L.recurrent(x, act=A.Tanh(), name="fused")

    topo = Topology([grp, fused])
    net = CompiledNetwork(topo)
    params = net.init_params(jax.random.PRNGKey(0))

    # tie weights: fused.w_h <- hproj.w0, fused.b <- h.b
    params["fused"]["w_h"] = params["grp"]["hproj"]["w0"]
    params["fused"]["b"] = params["grp"]["h"]["b"]

    batch = {"x": make_batch()}
    outs, _ = net.apply(params, batch, train=False)
    g = np.asarray(outs["grp"].masked_data())
    f = np.asarray(outs["fused"].masked_data())
    np.testing.assert_allclose(g, f, rtol=1e-5, atol=1e-5)


def test_group_reverse_matches_fused():
    x = L.data("x", paddle.data_type.dense_vector_sequence(H))

    def step(x_t):
        mem = L.memory("h", H)
        hm = L.fc(mem, H, act=A.Identity(), bias_attr=False, name="hproj")
        return L.addto([x_t, hm], act=A.Tanh(), bias_attr=True, name="h")

    grp = L.recurrent_group(step, x, reverse=True, name="grp")
    fused = L.recurrent(x, act=A.Tanh(), reverse=True, name="fused")
    topo = Topology([grp, fused])
    net = CompiledNetwork(topo)
    params = net.init_params(jax.random.PRNGKey(0))
    params["fused"]["w_h"] = params["grp"]["hproj"]["w0"]
    params["fused"]["b"] = params["grp"]["h"]["b"]
    batch = {"x": make_batch()}
    outs, _ = net.apply(params, batch, train=False)
    np.testing.assert_allclose(
        np.asarray(outs["grp"].masked_data()),
        np.asarray(outs["fused"].masked_data()),
        rtol=1e-5,
        atol=1e-5,
    )


def test_group_with_boot_memory():
    x = L.data("x", paddle.data_type.dense_vector_sequence(H))
    boot_src = L.data("bootsrc", paddle.data_type.dense_vector(4))
    boot = L.fc(boot_src, H, act=A.Tanh(), name="boot")

    def step(x_t):
        mem = L.memory("h", H, boot_layer=boot)
        hm = L.fc(mem, H, act=A.Identity(), bias_attr=False, name="hproj")
        return L.addto([x_t, hm], act=A.Tanh(), name="h")

    grp = L.recurrent_group(step, x, name="grp")
    topo = Topology([grp])
    net = CompiledNetwork(topo)
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    batch = {
        "x": make_batch(),
        "bootsrc": SeqTensor(jnp.asarray(rng.randn(4, 4), jnp.float32)),
    }
    outs, _ = net.apply(params, batch, train=False)
    out = np.asarray(outs["grp"].data)
    assert out.shape == (4, 7, H)
    assert np.isfinite(out).all()
    # boot must influence t=0 output: zero the boot input and compare
    batch2 = dict(batch)
    batch2["bootsrc"] = SeqTensor(jnp.zeros((4, 4), jnp.float32))
    outs2, _ = net.apply(params, batch2, train=False)
    assert not np.allclose(out[:, 0], np.asarray(outs2["grp"].data)[:, 0])


def test_group_gradients():
    x = L.data("in0", paddle.data_type.dense_vector_sequence(H))

    def step(x_t):
        mem = L.memory("h", H)
        hm = L.fc(mem, H, act=A.Identity(), bias_attr=False, name="hproj")
        return L.addto([x_t, hm], act=A.Tanh(), name="h")

    grp = L.recurrent_group(step, x, name="grp")
    check_layer_grad(grp, atol=8e-2, rtol=8e-2)


def test_group_static_input_attention():
    """Attention decoder pattern: static encoder sequence + memory decoder
    state; checks shapes, masking and that attention weights vary by step."""
    src = L.data("src", paddle.data_type.dense_vector_sequence(5))
    trg = L.data("trg", paddle.data_type.dense_vector_sequence(3))
    enc = paddle.networks.simple_gru(src, size=H, name="enc")
    enc_proj = L.fc(enc, size=H, act=A.Identity(), bias_attr=False, name="encproj")

    def step(trg_t, enc_seq, enc_p):
        state = L.memory("dec", H)
        context = paddle.networks.simple_attention(
            encoded_sequence=enc_seq,
            encoded_proj=enc_p,
            decoder_state=state,
            name="att",
        )
        return L.fc(
            [context, trg_t, state], size=H, act=A.Tanh(), name="dec"
        )

    grp = L.recurrent_group(
        step,
        [trg, L.StaticInput(enc, is_seq=True), L.StaticInput(enc_proj, is_seq=True)],
        name="decoder",
    )
    topo = Topology([grp])
    net = CompiledNetwork(topo)
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    src_lens = np.array([6, 2, 4, 6], np.int32)
    trg_lens = np.array([5, 3, 1, 4], np.int32)
    src_data = rng.randn(4, 6, 5).astype(np.float32)
    trg_data = rng.randn(4, 5, 3).astype(np.float32)
    batch = {
        "src": SeqTensor(jnp.asarray(src_data), jnp.asarray(src_lens)),
        "trg": SeqTensor(jnp.asarray(trg_data), jnp.asarray(trg_lens)),
    }
    outs, _ = net.apply(params, batch, train=False)
    out = np.asarray(outs["decoder"].data)
    assert out.shape == (4, 5, H)
    assert np.isfinite(out).all()
    # masking: steps beyond trg length are zero
    assert np.allclose(out[2, 1:], 0.0)
    assert np.allclose(out[1, 3:], 0.0)


def test_seq_memory_rejects_const_id_boot():
    """memory(is_seq=True, boot_with_const_id=...) is contradictory (a
    sequence cannot boot from a scalar id) and must raise, not silently
    boot empty."""
    import pytest as _pytest

    from paddle_tpu import layers as L
    from paddle_tpu.core.data_types import dense_vector_sub_sequence
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.layers import SubsequenceInput

    reset_auto_names()
    inp = L.data("x", dense_vector_sub_sequence(3))

    def step(sub):
        with _pytest.raises(ValueError, match="constant id"):
            L.memory(name="m", size=3, is_seq=True, boot_with_const_id=0)
        m = L.memory(name="m", size=3, is_seq=True)
        return L.addto([sub, m], name="m")

    L.recurrent_group(step=step, input=SubsequenceInput(inp))


def test_named_parameter_table_whole_layer_resolves_to_leaf():
    """Legacy whole-layer parameter names (embedding param_attr name) must
    resolve to the single array leaf through Parameters.get/set, never hand
    back a dict."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import layers as L
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names

    reset_auto_names()
    d = L.data("ids", paddle.data_type.integer_value_sequence(20))
    e = L.embedding(d, size=8, param_attr=paddle.attr.ParamAttr(name="emb.w"))
    out = L.fc(L.pooling(e, pooling_type="sum"), size=2)
    net = CompiledNetwork(Topology([out]))
    ps = paddle.parameters.Parameters(net, *net.init(jax.random.PRNGKey(0)))
    v = ps.get("emb.w")
    assert v.shape == (20, 8) and v.dtype == np.float32
    ps.set("emb.w", np.zeros((20, 8), np.float32))
    assert np.all(ps.get("emb.w") == 0)
