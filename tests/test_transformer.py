"""Transformer-base MT (BASELINE.json configs #5) — attention building
blocks + end-to-end training."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layers
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models.transformer import transformer_cost

from tests.layer_grad_util import check_layer_grad


def test_layer_norm_grad_and_stats():
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector_sequence(6))
    out = layers.layer_norm(x)
    check_layer_grad(out)


def test_layer_norm_normalizes():
    import jax
    from paddle_tpu.core.batch import seq
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology

    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector_sequence(8))
    out = layers.layer_norm(x)
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(0))
    data = np.random.RandomState(0).randn(2, 3, 8).astype(np.float32) * 5 + 3
    outs, _ = net.apply(params, {"x": seq(data, [3, 2])}, state=state)
    o = np.asarray(outs[out.name].data)
    np.testing.assert_allclose(o.mean(-1), 0.0, atol=1e-4)
    np.testing.assert_allclose(o.std(-1), 1.0, atol=1e-2)


def test_mha_self_attention_grad():
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector_sequence(8))
    out = layers.multi_head_attention(x, n_heads=2)
    check_layer_grad(out, atol=8e-2, rtol=8e-2)


def test_mha_respects_key_padding():
    """Attention weights over padded keys must be ~0: growing the key
    padding must not change the output."""
    import jax
    from paddle_tpu.core.batch import seq
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology

    reset_auto_names()
    q = layers.data("q", paddle.data_type.dense_vector_sequence(8))
    kv = layers.data("kv", paddle.data_type.dense_vector_sequence(8))
    out = layers.multi_head_attention(q, key_value=kv, n_heads=2)
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    qd = rng.randn(1, 3, 8).astype(np.float32)
    kd = rng.randn(1, 4, 8).astype(np.float32)
    kd_padded = np.concatenate([kd, rng.randn(1, 3, 8).astype(np.float32)], 1)
    o1, _ = net.apply(params, {"q": seq(qd, [3]), "kv": seq(kd, [2])}, state=state)
    o2, _ = net.apply(
        params, {"q": seq(qd, [3]), "kv": seq(kd_padded, [2])}, state=state
    )
    np.testing.assert_allclose(
        np.asarray(o1[out.name].data), np.asarray(o2[out.name].data),
        rtol=1e-4, atol=1e-5,
    )


def test_mha_causal_masks_future():
    """With causal=True, output at position t must not depend on inputs
    after t."""
    import jax
    from paddle_tpu.core.batch import seq
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology

    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector_sequence(8))
    out = layers.multi_head_attention(x, n_heads=2, causal=True)
    net = CompiledNetwork(Topology([out]))
    params, state = net.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(2)
    d1 = rng.randn(1, 4, 8).astype(np.float32)
    d2 = d1.copy()
    d2[0, 3] += 10.0  # perturb the LAST position only
    o1, _ = net.apply(params, {"x": seq(d1, [4])}, state=state)
    o2, _ = net.apply(params, {"x": seq(d2, [4])}, state=state)
    a, b = np.asarray(o1[out.name].data), np.asarray(o2[out.name].data)
    np.testing.assert_allclose(a[0, :3], b[0, :3], rtol=1e-4, atol=1e-5)
    assert np.abs(a[0, 3] - b[0, 3]).max() > 1e-3  # last position did change


# Readers yield (src, trg, trg_next); DFS feeding order visits the decoder
# subtree (trg_word) first — map explicitly (reference v2 feeding= contract).
_FEEDING = {"src_word": 0, "trg_word": 1, "trg_next": 2}


def test_transformer_trains_on_copy_task():
    reset_auto_names()
    V, BOS, EOS = 14, 0, 1
    cost, logits = transformer_cost(
        V, V, d_model=32, n_heads=4, n_layers=2, d_ff=64
    )
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=3e-3),
    )
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(160):
            s = list(rng.randint(2, V, size=rng.randint(2, 6)))
            yield s, [BOS] + s, s + [EOS]

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 16),
        num_passes=10,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
        feeding=_FEEDING,
    )
    assert np.mean(costs[-5:]) < 0.6 * np.mean(costs[:5]), (
        costs[:5], costs[-5:],
    )


def test_transformer_infer():
    """Forward through paddle.infer: per-timestep distributions, unpadded."""
    reset_auto_names()
    V = 10
    cost, logits = transformer_cost(V, V, d_model=16, n_heads=2, n_layers=1, d_ff=32)
    params = paddle.parameters.create(cost)
    samples = [([2, 3, 4], [0, 2, 3, 4], [2, 3, 4, 1]), ([5, 6], [0, 5, 6], [5, 6, 1])]
    probs = paddle.infer(
        output_layer=logits, parameters=params, input=samples, feeding=_FEEDING
    )
    assert probs.shape == (7, V)  # 4 + 3 decoder timesteps
    np.testing.assert_allclose(probs.sum(1), 1.0, rtol=1e-3)
