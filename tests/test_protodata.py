"""DataFormat.proto binary data plane — TrainerOnePass parity.

Reference: proto/DataFormat.proto, ProtoDataProvider.cpp:31 /
ProtoReader.h:53 (varint-framed proto2 stream), exercised by
paddle/trainer/tests/test_TrainerOnePass.cpp on the CHECKED-IN binary
datasets mnist_bin_part / data_bin_part — the reference's own training
fixtures must feed and train here unmodified."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io.protodata import (
    INDEX,
    VECTOR_DENSE,
    VECTOR_SPARSE_NON_VALUE,
    VECTOR_SPARSE_VALUE,
    SlotDef,
    make_reader,
    read_proto_data,
    read_proto_header,
    slot_input_types,
    write_proto_data,
)
from paddle_tpu.v1_compat import make_data_reader, make_optimizer, parse_config

REF_TESTS = "/root/reference/paddle/trainer/tests"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF_TESTS), reason="reference tree not present"
)


def test_mnist_bin_part_header_and_samples():
    """The checked-in mnist binary: dense 784 image + 10-class index label
    (the DataHeader is the authoritative slot-type source,
    ProtoDataProvider.cpp:84 checkDataHeader)."""
    defs, samples = read_proto_data(f"{REF_TESTS}/mnist_bin_part")
    assert defs == [SlotDef(VECTOR_DENSE, 784), SlotDef(INDEX, 10)]
    assert len(samples) == 1227
    for s in samples[:20]:
        assert len(s.vector_slots[0].values) == 784
        assert 0 <= s.id_slots[0] < 10
    labels = {s.id_slots[0] for s in samples}
    assert len(labels) == 10  # all classes present


def test_data_bin_part_reads():
    """The chunking binary: 8 sparse-non-value feature slots + binary
    label."""
    defs, samples = read_proto_data(f"{REF_TESTS}/data_bin_part")
    assert len(defs) == 9
    assert all(d.type == VECTOR_SPARSE_NON_VALUE for d in defs[:8])
    assert defs[8].type == INDEX and defs[8].dim == 2
    assert len(samples) == 1000
    s0 = samples[0]
    assert all(
        i < defs[k].dim for k in range(8) for i in s0.vector_slots[k].ids
    )


def test_trainer_one_pass_mnist_opt_a():
    """test_TrainerOnePass.cpp parity: the reference's OWN config
    (sample_trainer_config_opt_a.conf) + OWN binary data (mnist_bin_part via
    mnist.list) parse, feed, and train — cost must decrease over one pass."""
    p = parse_config(f"{REF_TESTS}/sample_trainer_config_opt_a.conf")
    types = dict(p.topology.data_types())
    assert types["input"].dim == 784
    reader = make_data_reader(p, REF_TESTS)

    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology,
        parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    costs = []
    trainer.train(
        # the conf says batch_size=1000; use 100 so one pass has 12 updates
        reader=paddle.batch(reader, 100),
        num_passes=1,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert len(costs) >= 10
    assert all(np.isfinite(costs))
    # the conf's own hyperparams are conservative (lr 1e-3, momentum 0.5 —
    # 12 updates of a sigmoid MLP): one pass reliably lands ~0.93x; demand
    # a real decrease with noise margin
    assert np.mean(costs[-3:]) < 0.98 * np.mean(costs[:3]), costs


def test_trainer_one_pass_mnist_opt_b():
    """The second OnePass optimizer config (opt_b) on the same data."""
    p = parse_config(f"{REF_TESTS}/sample_trainer_config_opt_b.conf")
    reader = make_data_reader(p, REF_TESTS)
    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology,
        parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    costs = []
    trainer.train(
        reader=paddle.batch(reader, 100),
        num_passes=1,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert all(np.isfinite(costs))
    assert np.mean(costs[-3:]) < 0.98 * np.mean(costs[:3]), costs


def test_proto_roundtrip_all_slot_kinds(tmp_path):
    """write_proto_data -> read_proto_data round-trips dense, sparse-binary,
    sparse-value and index slots, including gzip."""
    defs = [
        SlotDef(VECTOR_DENSE, 4),
        SlotDef(VECTOR_SPARSE_NON_VALUE, 100),
        SlotDef(VECTOR_SPARSE_VALUE, 50),
        SlotDef(INDEX, 3),
    ]
    rng = np.random.RandomState(0)
    rows = [
        (
            rng.randn(4).astype(np.float32),
            [1, 7, 42],
            [(3, 0.5), (9, -1.25)],
            2,
        ),
        (
            rng.randn(4).astype(np.float32),
            [],
            [(0, 1.0)],
            0,
        ),
    ]
    for name in ["t.bin", "t.bin.gz"]:
        path = str(tmp_path / name)
        write_proto_data(path, defs, rows)
        rdefs, _ = read_proto_data(path)
        assert rdefs == defs
        got = list(make_reader([path])())
        assert len(got) == 2
        np.testing.assert_allclose(got[0][0], rows[0][0], rtol=1e-6)
        assert got[0][1] == [1, 7, 42]
        assert got[0][2] == [(3, 0.5), (9, -1.25)]
        assert got[0][3] == 2
        assert got[1][1] == []


def test_proto_sequence_grouping(tmp_path):
    """is_beginning groups samples into sequences (proto_sequence
    semantics, ProtoDataProvider.cpp:528)."""
    defs = [SlotDef(VECTOR_DENSE, 2), SlotDef(INDEX, 5)]
    rows = [
        (np.asarray([i, i], np.float32), i % 5) for i in range(5)
    ]
    path = str(tmp_path / "seq.bin")
    # two sequences: [0,1,2] and [3,4]
    write_proto_data(
        path, defs, rows, is_beginning=[True, False, False, True, False]
    )
    seqs = list(make_reader([path], sequence=True)())
    assert len(seqs) == 2
    dense0, ids0 = seqs[0]
    assert len(dense0) == 3 and ids0 == [0, 1, 2]
    dense1, ids1 = seqs[1]
    assert len(dense1) == 2 and ids1 == [3, 4]
    t = slot_input_types(defs, sequence=True)
    assert t[0].seq.name == "SEQ" and t[1].seq.name == "SEQ"


def test_proto_index_before_vector_slots(tmp_path):
    """Headers whose kinds interleave (index slot FIRST) must read back
    correctly — per-kind offsets, not a shared vector offset."""
    defs = [
        SlotDef(INDEX, 7),
        SlotDef(VECTOR_DENSE, 3),
        SlotDef(INDEX, 4),
        SlotDef(VECTOR_SPARSE_NON_VALUE, 20),
    ]
    rows = [
        (5, np.asarray([1.0, 2.0, 3.0], np.float32), 2, [4, 9]),
        (1, np.asarray([0.5, 0.25, 0.125], np.float32), 0, []),
    ]
    path = str(tmp_path / "mixed.bin")
    write_proto_data(path, defs, rows)
    got = list(make_reader([path])())
    assert got[0][0] == 5 and got[0][2] == 2
    np.testing.assert_allclose(got[0][1], rows[0][1])
    assert got[0][3] == [4, 9]
    assert got[1][0] == 1 and got[1][3] == []


def test_compare_sparse_conf_mismatched_dims_is_a_hard_error():
    """sample_trainer_config_compare_sparse.conf declares word_dim=999 but
    data_bin_part's slots carry ids up to 1.45M — feeding that into a
    999-row table would be out-of-bounds.  The binding must refuse loudly
    at the feed boundary, never gather garbage rows."""
    p = parse_config(f"{REF_TESTS}/sample_trainer_config_compare_sparse.conf")
    with pytest.raises(ValueError, match="dim-consistent|slot types unknown"):
        p.topology.data_types()


@pytest.mark.slow
@pytest.mark.parametrize("conf", ["sample_trainer_config_rnn.conf"])
def test_trainer_big_vocab_ltr_configs_train_on_data_bin_part(conf):
    """The reference's learning-to-rank fixtures (test_CompareTwoNets /
    test_CompareSparse data): raw-face recurrent groups over eight
    1.45M-vocab sparse_binary sequence slots, fed from the checked-in
    data_bin_part via proto_sequence.  Big-vocab sparse slots feed as
    padded id lists (gather-sum of touched embedding rows — the
    SparseRowMatrix regime), never as multi-hot."""
    import jax

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.reader.feeder import DataFeeder
    from paddle_tpu.trainer.step import make_train_step

    p = parse_config(f"{REF_TESTS}/{conf}")
    types = dict(p.topology.data_types())
    assert sum(t.kind.name == "SPARSE_BINARY" for t in types.values()) == 8
    r = make_data_reader(p, REF_TESTS)
    it = iter(r())
    rows = [next(it) for _ in range(8)]

    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    opt = make_optimizer(p.settings)
    opt_state = opt.init(params)
    step = make_train_step(net, opt, mesh=None)
    feeder = DataFeeder(p.topology.data_types())
    batch = feeder(rows[:4])
    # the id-form batch must be tiny compared to a multi-hot (4 samples x
    # T x 1.45M floats would be gigabytes)
    qb = batch[next(iter(types))]
    assert qb.data.dtype == np.int32 and qb.data.shape[-1] <= 64
    costs = []
    for i in range(2):
        params, state, opt_state, m = step(
            params, state, opt_state, feeder(rows[i * 4:(i + 1) * 4]),
            jax.random.PRNGKey(i),
        )
        costs.append(float(m["cost"]))
    assert all(np.isfinite(costs)), costs


def test_sparse_ids_flag_and_nested_form():
    """The feeder TAGS id-form batches (SeqTensor.sparse_ids) — consumers
    dispatch on the tag, not shape heuristics — and the nested
    (sub-sequence) variant also feeds as padded ids, never multi-hot."""
    from paddle_tpu.core.data_types import (
        sparse_binary_vector_sequence,
        sparse_binary_vector_sub_sequence,
    )
    from paddle_tpu.reader.feeder import DataFeeder

    V = 1_000_000
    f = DataFeeder([("s", sparse_binary_vector_sequence(V))])
    b = f([([[1, 5], [7]],), ([[9]],)])["s"]
    assert b.sparse_ids and b.data.dtype == np.int32
    assert b.data.shape[-1] <= 64 and b.data.ndim == 3

    f2 = DataFeeder([("n", sparse_binary_vector_sub_sequence(V))])
    b2 = f2([([[[1], [2, 3]], [[4]]],)])["n"]
    assert b2.sparse_ids and b2.is_nested
    assert b2.data.ndim == 4 and b2.data.shape[-1] <= 64

    # pytree round-trip preserves the tag
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(b)
    again = jax.tree_util.tree_unflatten(treedef, leaves)
    assert again.sparse_ids


@pytest.mark.slow
def test_compare_two_nets_rnn_vs_qb_rnn():
    """test_CompareTwoNets.cpp parity: sample_trainer_config_rnn.conf (raw
    recurrent layer groups) and sample_trainer_config_qb_rnn.conf (fused
    `recurrent` layers) describe the same network; with parameters tied
    through the GLOBAL parameter name table (embedding.w0, rnn1.w0, ...)
    both must produce the SAME cost on the same reference data."""
    import jax

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.reader.feeder import DataFeeder

    pa = parse_config(f"{REF_TESTS}/sample_trainer_config_rnn.conf")
    pb = parse_config(f"{REF_TESTS}/sample_trainer_config_qb_rnn.conf")
    na, nb = CompiledNetwork(pa.topology), CompiledNetwork(pb.topology)
    pla = paddle.parameters.Parameters(na, *na.init(jax.random.PRNGKey(0)))
    plb = paddle.parameters.Parameters(nb, *nb.init(jax.random.PRNGKey(1)))
    common = sorted(set(na.named_parameters()) & set(nb.named_parameters()))
    # the whole model is named-parameter-shared in both configs
    assert {"embedding.w0", "rnn1.w0", "rnn1.bias"} <= set(common)
    for n in common:
        plb.set(n, pla.get(n))
    r = make_data_reader(pa, REF_TESTS, shuffle=False)
    rows = [x for _, x in zip(range(6), r())]
    fa = DataFeeder(pa.topology.data_types())
    fb = DataFeeder(pb.topology.data_types())
    ca, _ = na.cost(pla.params, fa(rows), state=pla.state, train=False)
    cb, _ = nb.cost(plb.params, fb(rows), state=plb.state, train=False)
    np.testing.assert_allclose(float(ca), float(cb), rtol=1e-6)


def test_native_decoder_matches_python():
    """The C++ fast-path decoder (native/protodata.cc) must agree with the
    pure-Python wire decoder byte for byte on the dense/index mnist file,
    and decline (None) on the sparse chunking file so the Python path
    serves it."""
    from paddle_tpu.io.protodata import native_decode_dense_index

    nat = native_decode_dense_index(f"{REF_TESTS}/mnist_bin_part")
    if nat is None:
        pytest.skip("native toolchain unavailable")
    defs, arrs = nat
    assert [d.type for d in defs] == [VECTOR_DENSE, INDEX]
    d2, samples = read_proto_data(f"{REF_TESTS}/mnist_bin_part")
    assert d2 == defs and arrs[0].shape == (len(samples), 784)
    for i in (0, 1, 613, len(samples) - 1):
        np.testing.assert_array_equal(
            arrs[0][i], np.asarray(samples[i].vector_slots[0].values, np.float32)
        )
        assert int(arrs[1][i]) == samples[i].id_slots[0]
    # sparse slots are NOT the fast path
    assert native_decode_dense_index(f"{REF_TESTS}/data_bin_part") is None
    # the reader uses the fast path transparently
    rows = list(make_reader([f"{REF_TESTS}/mnist_bin_part"])())
    assert len(rows) == len(samples) and rows[0][0].shape == (784,)


def test_trainer_one_pass_simple_data():
    """test_TrainerOnePass.cpp's PRIMARY config (sample_trainer_config.conf,
    configFile1) trains on the checked-in sample_data.txt through the
    SimpleData text provider (DataProvider.cpp SimpleDataProvider:
    'label feat_1 .. feat_sampleDim' per line)."""
    from paddle_tpu.v1_compat import make_config_reader

    p = parse_config(f"{REF_TESTS}/sample_trainer_config.conf")
    types = dict(p.topology.data_types())
    assert types["input"].dim == 3
    from paddle_tpu.core.data_types import SlotKind

    assert types["label"].kind == SlotKind.INDEX
    reader = make_config_reader(p, REF_TESTS)
    rows = list(reader())
    assert len(rows) == 10  # the checked-in sample_data.txt
    assert rows[0][0].shape == (3,)

    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology, parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    costs = []
    trainer.train(
        reader=paddle.batch(reader, 10), num_passes=40,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
        async_load_data=False,
    )
    assert all(np.isfinite(costs))
    assert costs[-1] < 0.7 * costs[0], (costs[0], costs[-1])


def test_trainer_one_pass_hsigmoid_simple_data():
    """The hsigmoid OnePass fixture (sample_trainer_config_hsigmoid.conf)
    trains on the same SimpleData text file."""
    from paddle_tpu.v1_compat import make_config_reader

    p = parse_config(f"{REF_TESTS}/sample_trainer_config_hsigmoid.conf")
    reader = make_config_reader(p, REF_TESTS)
    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology, parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    costs = []
    trainer.train(
        reader=paddle.batch(reader, 10), num_passes=80,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
        async_load_data=False,
    )
    assert all(np.isfinite(costs))
    # the conf's own hyperparams are conservative; demand a real decrease
    assert costs[-1] < 0.8 * costs[0], (costs[0], costs[-1])


def test_trainer_one_pass_parallel_conf_simple_data():
    """sample_trainer_config_parallel.conf (the reference's parallel_nn
    OnePass fixture — per-layer device attrs are placement hints the XLA
    plane absorbs) trains on the same SimpleData text file."""
    from paddle_tpu.v1_compat import make_config_reader

    p = parse_config(f"{REF_TESTS}/sample_trainer_config_parallel.conf")
    reader = make_config_reader(p, REF_TESTS)
    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology, parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    costs = []
    trainer.train(
        reader=paddle.batch(reader, 10), num_passes=40,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
        async_load_data=False,
    )
    assert all(np.isfinite(costs))
    assert costs[-1] < 0.98 * costs[0], (costs[0], costs[-1])
