"""Graph linter (analysis/graph_lint.py): the zero-false-positive corpus —
every shipped demo config and model-zoo topology lints clean — plus a
mutation suite proving each rule fires with its exact rule id (the
config_assert contract: provenance + fix hint on every finding)."""

import dataclasses
import os

import jax
import pytest

import paddle_tpu as paddle
import paddle_tpu.activation as A
import paddle_tpu.layers as L
from paddle_tpu.analysis import (
    Diagnostic,
    DiagnosticError,
    Severity,
    format_diagnostics,
    lint_parsed,
    lint_topology,
)
from paddle_tpu.core.data_types import integer_value
from paddle_tpu.core.topology import (
    LayerConf,
    LayerOutput,
    Topology,
    reset_auto_names,
)

HERE = os.path.dirname(__file__)
CONFIGS = os.path.join(HERE, "configs")


def rules(diags):
    return [d.rule for d in diags]


def _lint(outs, **kw):
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return lint_topology(Topology(list(outs)), **kw)


# ---------------------------------------------------------------------------
# corpus: every shipped demo config and model-zoo builder must be silent
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "cfg", sorted(f for f in os.listdir(CONFIGS) if f.endswith(".py"))
)
def test_demo_config_corpus_lints_clean(cfg):
    from paddle_tpu.v1_compat import parse_config

    parsed = parse_config(os.path.join(CONFIGS, cfg))
    diags = lint_parsed(parsed)
    assert not diags, format_diagnostics(diags)


def _zoo():
    from paddle_tpu.models.lenet import lenet_cost
    from paddle_tpu.models.resnet import resnet_cost
    from paddle_tpu.models.seq2seq import seq2seq_cost
    from paddle_tpu.models.sequence_tagging import ner_crf_cost
    from paddle_tpu.models.transformer import transformer_cost

    return {
        "lenet": lambda: list(lenet_cost()),
        "resnet18": lambda: [resnet_cost(depth=18, class_num=10, img_size=32)[0]],
        "seq2seq": lambda: [seq2seq_cost(40, 45, word_dim=16, hidden_dim=16)[0]],
        "ner_crf": lambda: list(ner_crf_cost(60, 5)),
        "transformer": lambda: [
            transformer_cost(
                src_vocab=50, trg_vocab=50, n_layers=1, d_model=32,
                n_heads=4, d_ff=64,
            )
        ],
        "transformer_moe": lambda: [
            transformer_cost(
                src_vocab=50, trg_vocab=50, n_layers=1, d_model=32,
                n_heads=4, d_ff=64, moe_experts=4,
            )
        ],
    }


@pytest.mark.parametrize("name", sorted(_zoo()))
def test_model_zoo_lints_clean(name):
    reset_auto_names()
    outs = _zoo()[name]()
    diags = _lint([o for o in outs if isinstance(o, LayerOutput)])
    assert not diags, f"{name}:\n" + format_diagnostics(diags)


# ---------------------------------------------------------------------------
# mutation suite: one deliberately-broken graph per rule, exact id asserted
# ---------------------------------------------------------------------------


def _mlp():
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(8))
    h = L.fc(x, size=8, act=A.Tanh(), name="hidden")
    return x, h


def test_g001_unknown_layer_type():
    x, h = _mlp()
    bad = LayerOutput(
        LayerConf(name="mystery", type="warp_drive", size=8, inputs=("hidden",)),
        [h],
    )
    d = _lint(bad)
    assert "G001" in rules(d)
    (g1,) = [x for x in d if x.rule == "G001"]
    assert g1.layer == "mystery" and g1.hint


def test_g002_dangling_input():
    x, h = _mlp()
    bad = LayerOutput(
        LayerConf(name="sum", type="addto", size=8,
                  inputs=("hidden", "ghost_layer")),
        [h],
    )
    d = _lint(bad)
    assert "G002" in rules(d)


def test_g003_arity_mismatch():
    x, h = _mlp()
    bad = LayerOutput(
        LayerConf(name="gru", type="gru_step", size=8, inputs=("hidden",)),
        [h],
    )
    d = _lint(bad)
    assert "G003" in rules(d)


def test_g004_width_mismatch_addto():
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(8))
    a = L.fc(x, size=8, name="a")
    b = L.fc(x, size=12, name="b")
    bad = LayerOutput(
        LayerConf(name="sum", type="addto", size=8, inputs=("a", "b")),
        [a, b],
    )
    d = _lint(bad)
    assert "G004" in rules(d)


def test_g004_width_mismatch_gru_gates():
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(8))
    gates = L.fc(x, size=16, name="gates")  # needs 3*size = 24
    state = L.fc(x, size=8, name="state")
    bad = LayerOutput(
        LayerConf(name="gru", type="gru_step", size=8,
                  inputs=("gates", "state")),
        [gates, state],
    )
    d = _lint(bad)
    assert "G004" in rules(d)


def test_g005_dead_layer():
    x, h = _mlp()
    dead = L.fc(x, size=4, name="orphan")  # built, reaches no output
    d = _lint(h, created=["x", "hidden", "orphan"])
    assert "G005" in rules(d)
    (g5,) = [y for y in d if y.rule == "G005"]
    assert "orphan" in g5.message
    # evaluator-rooted layers are NOT dead
    d2 = _lint(h, created=["x", "hidden", "orphan"],
               evaluator_layers=["orphan"])
    assert "G005" not in rules(d2)


def test_g006_param_share_shape_conflict():
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(8))
    y = L.data("y", paddle.data_type.dense_vector(12))
    a = L.fc(x, size=8, name="a", param_attr=paddle.attr.ParamAttr(name="shared"))
    b = L.fc(y, size=8, name="b", param_attr=paddle.attr.ParamAttr(name="shared"))
    cat = L.concat([a, b], name="cat")
    d = _lint(cat)
    assert "G006" in rules(d)


def test_g007_unknown_attr_key():
    x, h = _mlp()
    bad = LayerOutput(
        LayerConf(name="opt", type="fc", size=8, inputs=("hidden",),
                  attrs={"kernel_sz": 3}),
        [h],
    )
    d = _lint(bad)
    assert "G007" in rules(d)
    (g7,) = [y for y in d if y.rule == "G007"]
    assert "kernel_sz" in g7.message and g7.severity == Severity.WARNING


def test_g008_unknown_shard_axis():
    x, h = _mlp()
    bad = LayerOutput(
        LayerConf(name="wide", type="fc", size=8, inputs=("hidden",),
                  shard_axis="tensor"),
        [h],
    )
    d = _lint(bad)
    assert "G008" in rules(d)


def test_g009_dynamic_width_with_bucketing():
    x, h = _mlp()
    bad = LayerOutput(
        LayerConf(name="dyn", type="fc", size=8, inputs=("hidden",),
                  attrs={"dynamic_width_in": (0,)}),
        [h],
    )
    d = _lint(bad, bucketing=True)
    assert "G009" in rules(d)
    # without bucketing the construct is legal
    d2 = _lint(bad, bucketing=False)
    assert "G009" not in rules(d2)


def _attention_decoder(drop_in_pattern: float = 0.0):
    from paddle_tpu.models.seq2seq import _encoder_and_boot

    reset_auto_names()
    enc, enc_proj, boot = _encoder_and_boot(30, 8, 8)
    trg = L.data("trg_word", paddle.data_type.integer_value_sequence(30))
    trg_emb = L.embedding(trg, size=8, name="trg_emb")

    def step(trg_emb_t, enc_seq, enc_p):
        state = L.memory("dec_state", 8, boot_layer=boot)
        context = paddle.networks.simple_attention(
            encoded_sequence=enc_seq, encoded_proj=enc_p,
            decoder_state=state, name="att",
        )
        extra = (
            {"layer_attr": paddle.attr.ExtraAttr(drop_rate=drop_in_pattern)}
            if drop_in_pattern else {}
        )
        inputs = L.fc(
            [context, trg_emb_t], size=24, act=A.Identity(),
            bias_attr=False, name="dec_in_proj", **extra,
        )
        gru = L.gru_step(inputs, state, size=8, name="dec_state")
        return L.fc(gru, size=30, act=A.Softmax(), name="dec_out")

    return L.recurrent_group(
        step,
        [trg_emb, L.StaticInput(enc, is_seq=True),
         L.StaticInput(enc_proj, is_seq=True)],
        name="decoder",
    )


def test_g010_dropout_defeats_fused_matcher():
    dec = _attention_decoder(drop_in_pattern=0.3)
    d = _lint(dec)
    assert rules(d) == ["G010"], format_diagnostics(d)
    (g10,) = d
    assert "dec_in_proj" in g10.message and g10.severity == Severity.WARNING


def test_g010_silent_when_pattern_fuses():
    dec = _attention_decoder(drop_in_pattern=0.0)
    d = _lint(dec)
    assert "G010" not in rules(d), format_diagnostics(d)


def test_g011_unresolved_data_slot():
    reset_auto_names()
    conf = LayerConf(
        name="w", type="data", size=10,
        input_type=paddle.data_type.dense_vector(10),
        attrs={"_v1_unresolved": "provider module not importable"},
    )
    lo = LayerOutput(conf)
    out = LayerOutput(
        LayerConf(name="fc", type="fc", size=4, inputs=("w",)), [lo]
    )
    d = _lint(out)
    assert "G011" in rules(d)
    # the feed boundary raises the same rule as a hard error
    with pytest.raises(DiagnosticError) as ei:
        Topology([out]).data_types()
    assert ei.value.rules == ["G011"]


def test_g013_unknown_activation():
    x, h = _mlp()
    bad = LayerOutput(
        LayerConf(name="act", type="fc", size=8, inputs=("hidden",),
                  act="quantum"),
        [h],
    )
    d = _lint(bad)
    assert "G013" in rules(d)


def test_g014_drop_rate_out_of_range():
    x, h = _mlp()
    bad = LayerOutput(
        LayerConf(name="drp", type="fc", size=8, inputs=("hidden",),
                  drop_rate=1.5),
        [h],
    )
    d = _lint(bad)
    assert "G014" in rules(d)


def test_g015_data_size_vs_input_type_dim():
    reset_auto_names()
    conf = LayerConf(
        name="pix", type="data", size=784,
        input_type=paddle.data_type.dense_vector(100),
    )
    d = _lint(LayerOutput(conf))
    assert "G015" in rules(d)


def test_g016_duplicate_layer_name_raises_diagnostic():
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(4))
    a = LayerOutput(LayerConf(name="twin", type="fc", size=4, inputs=("x",)), [x])
    b = LayerOutput(LayerConf(name="twin", type="fc", size=8, inputs=("x",)), [x])
    cat = LayerOutput(
        LayerConf(name="cat", type="concat", size=12, inputs=("twin", "twin")),
        [a, b],
    )
    with pytest.raises(DiagnosticError) as ei:
        Topology([cat])
    assert ei.value.rules == ["G016"]
    assert "twin" in str(ei.value)


def test_g017_label_dim_mismatch():
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(8))
    pred = L.fc(x, size=10, act=A.Softmax(), name="pred")
    lbl = LayerOutput(
        LayerConf(name="lbl", type="data", size=7, input_type=integer_value(7))
    )
    cost = LayerOutput(
        LayerConf(name="ce", type="cross_entropy", size=1,
                  inputs=("pred", "lbl"), bias=False),
        [pred, lbl],
    )
    d = _lint(cost)
    assert "G017" in rules(d)


# ---------------------------------------------------------------------------
# diagnostic model / formatter
# ---------------------------------------------------------------------------


def test_diagnostic_format_carries_provenance_and_hint():
    d = Diagnostic(
        rule="G004", severity=Severity.ERROR, message="widths differ",
        layer="sum", source="conf.py", line=12, hint="align the sizes",
    )
    s = d.format()
    assert "error[G004]" in s and "'sum'" in s
    assert "conf.py:12" in s and "fix: align the sizes" in s


def test_format_diagnostics_orders_errors_first():
    ds = [
        Diagnostic(rule="G007", severity=Severity.WARNING, message="w"),
        Diagnostic(rule="G004", severity=Severity.ERROR, message="e"),
    ]
    text = format_diagnostics(ds)
    assert text.index("G004") < text.index("G007")
    assert "1 error(s), 1 warning(s)" in text


def test_compiler_share_conflict_is_diagnostic_formatted():
    """Satellite: core.compiler's parameter-sharing errors carry the shared
    diagnostic format (rule G006 + layer + hint) while staying ValueError."""
    from paddle_tpu.core.compiler import CompiledNetwork

    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(8))
    y = L.data("y", paddle.data_type.dense_vector(12))
    a = L.fc(x, size=8, name="a", param_attr=paddle.attr.ParamAttr(name="shared"))
    b = L.fc(y, size=8, name="b", param_attr=paddle.attr.ParamAttr(name="shared"))
    net = CompiledNetwork(Topology([L.concat([a, b], name="cat")]))
    with pytest.raises(ValueError) as ei:
        net.init_params(jax.random.PRNGKey(0))
    assert isinstance(ei.value, DiagnosticError)
    assert ei.value.rules == ["G006"]
    assert "error[G006]" in str(ei.value) and "fix:" in str(ei.value)


def test_g016_duplicate_name_on_ancestor_path():
    """Review regression: a duplicate met while its descendant's conf is
    seen but not yet stored must still raise — the old check compared
    against the incomplete layers dict and silently dropped the ancestor."""
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(4))
    inner = LayerOutput(
        LayerConf(name="twin", type="fc", size=4, inputs=("x",)), [x]
    )
    outer = LayerOutput(
        LayerConf(name="twin", type="fc", size=8, inputs=("twin",)), [inner]
    )
    with pytest.raises(DiagnosticError) as ei:
        Topology([outer])
    assert ei.value.rules == ["G016"]


def test_g009_fires_inside_recurrent_group():
    """Review regression: a dynamic-width layer nested in a sub-topology is
    caught at config time, not just by the runtime trainer guard."""
    reset_auto_names()
    x = L.data("x", paddle.data_type.dense_vector(8))
    inner = LayerOutput(
        LayerConf(name="dyn", type="fc", size=8, inputs=(),
                  attrs={"dynamic_width_in": (0,)})
    )
    group = LayerOutput(
        LayerConf(name="grp", type="recurrent_group", size=8, inputs=("x",),
                  attrs={"_sub_topology": Topology([inner])}),
        [x],
    )
    d = _lint(group, bucketing=True)
    assert "G009" in rules(d)
    (g9,) = [y for y in d if y.rule == "G009"]
    assert "grp.dyn" in g9.message
