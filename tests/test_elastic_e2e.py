"""Elastic scale-out e2e — real worker PROCESSES, real SIGKILL, real torn
state (ISSUE 6 acceptance).

The contract: N worker processes lease data-shard tasks from the HA master;
kill -9 one of them MID-PASS (holding a shard lease) and the job does not
even hiccup — the dead worker's registry lease expires, its shard leases
requeue to survivors, the pass fence releases over the live membership, and
because every per-task contribution is deterministic and the reduction is
task-id-ordered, the final parameters are BIT-FOR-BIT identical to an
uninterrupted N-worker run (and to an N=1 run).  This is the Go master's
lease-based fault-tolerance model (go/master/service.go; arXiv:1605.08695
§4.4) completed end-to-end at the process level.

All tests here spawn multiple python processes => marked slow (tier-1 runs
`-m "not slow"`; `make chaos` runs this file directly)."""

import json
import os
import signal
import sys

import numpy as np
import pytest

from paddle_tpu import launcher
from paddle_tpu.checkpoint import CheckpointManager
from paddle_tpu.io import recordio
from paddle_tpu.master_ha import HAMaster
from paddle_tpu.trainer.elastic import NumpyLinearModel

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8


def _write_dataset(path, n=96, seed=0):
    """Deterministic regression records [x..., y] — 24 chunks at 4
    records/chunk => 12 tasks at chunks_per_task=2."""
    rng = np.random.RandomState(seed)
    w_true = rng.randn(DIM).astype(np.float32)
    recs = []
    for _ in range(n):
        x = rng.randn(DIM).astype(np.float32)
        recs.append(
            np.concatenate([x, [np.float32(x @ w_true)]])
            .astype(np.float32).tobytes()
        )
    recordio.write_records(path, iter(recs), max_chunk_records=4)


def _start_master(d, data, **kw):
    kw.setdefault("lease_timeout", 2.0)
    kw.setdefault("chunks_per_task", 2)
    kw.setdefault("timeout_s", 30.0)
    # wide enough that a scheduling stall on a loaded 2-core box never
    # spuriously prunes a healthy worker (the clean run asserts
    # fail_events == 0), small enough that a real death costs seconds
    kw.setdefault("worker_timeout_s", 3.0)
    kw.setdefault("snapshot_min_interval_s", 0.0)
    ha = HAMaster(
        os.path.join(d, "ha"), [data], owner_id="test-driver",
        auto_rotate=False, **kw,
    )
    ha.start()
    assert ha.wait_leader(30)
    return ha


def _worker_args(d, num_passes, n, extra=()):
    """One argv serves the whole fleet: the worker id comes from the
    launcher's PADDLE_TPU_PROCESS_ID env and the stats path expands
    {worker}.  --min-workers=n gang-starts the fleet: python boot skew on
    a loaded box must not let the first worker race through whole (tiny)
    passes alone before its peers register."""
    return [
        "paddle_tpu.trainer.elastic",
        "--dir", os.path.join(d, "ha"),
        "--num-passes", str(num_passes), "--model", "numpy",
        "--model-arg", f"dim={DIM}", "--model-arg", "lr=0.2",
        "--min-workers", str(n),
        "--checkpoint-dir", os.path.join(d, "ck"),
        "--stats-out", os.path.join(d, "stats-{worker}.json"),
        *extra,
    ]


def _run_fleet(d, n, num_passes=3, chaos=None, master_kw=None, extra=()):
    """Launch n elastic worker processes through launcher.launch(elastic=
    True) — "python -m paddle_tpu.trainer.elastic" per local host entry;
    returns (rc, exit_codes, master stats, restored params, worker
    stats)."""
    os.makedirs(d, exist_ok=True)
    data = os.path.join(d, "data.rio")
    if not os.path.exists(data):
        _write_dataset(data)
    ha = _start_master(d, data, **(master_kw or {}))
    try:
        base_env = {
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        }
        extra_env = {i: dict(base_env) for i in range(n)}
        for i, spec in (chaos or {}).items():
            extra_env[i].update(spec)
        codes: list = []
        # the "-m module" spelling rides the launcher's [python, script,
        # *args] command shape unchanged
        rc = launcher.launch(
            ["localhost"] * n, "127.0.0.1:0", "-m",
            _worker_args(d, num_passes, n, extra=extra),
            elastic=True, extra_env=extra_env, exit_codes=codes,
        )
        stats = ha.service.stats() if ha.service else None
    finally:
        ha.stop()
    worker_stats = {}
    for i in range(n):
        p = os.path.join(d, f"stats-w{i}.json")
        if os.path.exists(p):
            with open(p) as f:
                worker_stats[i] = json.load(f)
    mgr = CheckpointManager(os.path.join(d, "ck"))
    restored = mgr.restore_latest(NumpyLinearModel(DIM).state())
    return rc, codes, stats, restored, worker_stats


def test_kill_one_of_four_mid_pass_matches_uninterrupted_bitwise(tmp_path):
    """The headline acceptance: N=4, kill -9 worker 2 as it takes its 1st
    task (HOLDING the shard lease, mid-pass — @1 so the drill fires even
    when scheduling skew makes the victim a straggler that never reaches a
    2nd lease).  Its leases requeue to survivors after one lease timeout
    (fail_events >= 1), the pass completes, and the final committed params
    equal the uninterrupted N=4 run's bit-for-bit."""
    rc1, codes1, st1, res1, ws1 = _run_fleet(str(tmp_path / "clean"), 4)
    assert rc1 == 0 and codes1 == [0, 0, 0, 0]
    assert st1["fail_events"] == 0
    assert res1 is not None

    rc2, codes2, st2, res2, ws2 = _run_fleet(
        str(tmp_path / "killed"), 4,
        chaos={2: {"PADDLE_TPU_CHAOS": "kill_worker@1"}},
    )
    assert rc2 == 0  # elastic: the job survives the kill
    assert codes2[2] == -signal.SIGKILL  # died hard, no cleanup
    assert sum(1 for c in codes2 if c == 0) == 3  # every survivor finished
    assert 2 not in ws2  # the dead worker never wrote its summary
    assert st2["fail_events"] >= 1  # the lease-timeout requeue happened
    assert st2["n_discarded"] == 0  # requeue, not discard
    assert st2["pass_id"] == st1["pass_id"]  # the pass(es) completed

    step1, tree1, _ = res1
    step2, tree2, _ = res2
    assert step1 == step2 == 3
    assert np.array_equal(tree1["w"], tree2["w"])
    assert np.array_equal(tree1["b"], tree2["b"])
    # and the cost trajectories agree wherever both logged them
    costs1 = ws1[0]["pass_costs"]
    for i, ws in ws2.items():
        tail = ws["pass_costs"]
        assert tail == costs1[len(costs1) - len(tail):], f"worker {i}"


def test_single_worker_matches_fleet_bitwise(tmp_path):
    """N-invariance: the task-ordered reduction makes N=1 and N=4 runs
    bit-identical — the property that lets membership change freely."""
    _, codes1, _, res1, _ = _run_fleet(str(tmp_path / "n1"), 1)
    _, codes4, _, res4, _ = _run_fleet(str(tmp_path / "n4"), 4)
    assert codes1 == [0] and codes4 == [0, 0, 0, 0]
    assert np.array_equal(res1[1]["w"], res4[1]["w"])
    assert np.array_equal(res1[1]["b"], res4[1]["b"])


def test_worker_hang_is_pruned_then_rejoins(tmp_path):
    """A stalled-but-alive worker (full-process freeze, heartbeats
    included): its leases expire and the fleet finishes without it; on
    waking, its stale acks are rejected by epoch and it catches the fleet
    back up (retained results / committed manifest) instead of forking the
    trajectory."""
    rc, codes, st, res, ws = _run_fleet(
        str(tmp_path / "hang"), 3, num_passes=2,
        chaos={1: {"PADDLE_TPU_CHAOS": "worker_hang@1",
                   "PADDLE_TPU_CHAOS_HANG_SECS": "6"}},
    )
    assert codes == [0, 0, 0], codes  # the hung worker still exits clean
    assert st["fail_events"] >= 1  # its held lease walked the requeue path
    _, codes_ref, _, res_ref, _ = _run_fleet(
        str(tmp_path / "ref"), 3, num_passes=2
    )
    assert codes_ref == [0, 0, 0]
    assert np.array_equal(res[1]["w"], res_ref[1]["w"])
    # the hung worker observed its zombie ack being rejected OR returned
    # its stale lease gracefully — either way it reports rejoining
    assert 1 in ws


def test_sharded_resume_reproduces_uninterrupted_trajectory(tmp_path):
    """Stop the whole cluster at a pass boundary, restart with --resume:
    the master recovers its queues from the snapshot, workers restore the
    latest committed manifest, and the remaining passes' costs and final
    params are bit-for-bit the uninterrupted run's."""
    # uninterrupted reference: 4 passes in one go
    _, codes_a, _, res_a, ws_a = _run_fleet(
        str(tmp_path / "ref"), 2, num_passes=4
    )
    assert codes_a == [0, 0]
    ref_costs = ws_a[0]["pass_costs"]
    assert len(ref_costs) == 4

    # phase 1: 2 passes, clean stop
    d = str(tmp_path / "resumed")
    _, codes_b, _, _, _ = _run_fleet(d, 2, num_passes=2)
    assert codes_b == [0, 0]

    # phase 2: same dirs, --resume, 2 more passes (master recovers its
    # snapshot; workers restore the manifest and rotate past pass 1)
    _, codes_c, _, _, ws_c = _run_fleet(
        d, 2, num_passes=4, extra=("--resume",)
    )
    assert codes_c == [0, 0]
    resumed = ws_c[0]
    # the resumed phase logged exactly the tail passes, bit-for-bit
    assert resumed["pass_costs"] == ref_costs[2:]
    mgr = CheckpointManager(os.path.join(d, "ck"))
    step, tree, _ = mgr.restore_latest(NumpyLinearModel(DIM).state())
    assert step == 4
    assert np.array_equal(tree["w"], res_a[1]["w"])
    assert np.array_equal(tree["b"], res_a[1]["b"])


def test_cli_master_candidate_serves_and_stops(tmp_path):
    """`paddle-tpu master` runs an HA candidate: it wins the lease, prints
    LEADER with its endpoint, serves an elastic worker, and exits 0 on
    SIGTERM."""
    import subprocess
    import time

    d = str(tmp_path)
    data = os.path.join(d, "data.rio")
    _write_dataset(data)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "master",
         "--dir", os.path.join(d, "ha"), "--patterns", data,
         "--chunks-per-task", "2", "--worker-timeout-s", "5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        from paddle_tpu.master_ha import discover_endpoint

        deadline = time.time() + 60
        while time.time() < deadline:
            if discover_endpoint(os.path.join(d, "ha")) is not None:
                break
            assert proc.poll() is None, proc.communicate()[1][-2000:]
            time.sleep(0.2)
        else:
            pytest.fail("no leader endpoint appeared")
        # a worker trains one pass against the CLI-served master
        rc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.trainer.elastic",
             "--dir", os.path.join(d, "ha"), "--num-passes", "1",
             "--model", "numpy", "--model-arg", f"dim={DIM}"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert rc.returncode == 0, rc.stderr[-2000:]
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, err[-2000:]
    assert "LEADER" in out


def test_jax_fleet_two_workers_matches_single(tmp_path):
    """The REAL training stack across processes: the jitted
    make_grad_step + Momentum optimizer (SGD.elastic_model) on a paddle
    MLP, 2 worker processes vs 1 — final params and per-pass costs must be
    bit-identical (pass-synchronous reduction is membership-invariant)."""
    import subprocess

    def fleet(tag, n):
        d = str(tmp_path / tag)
        os.makedirs(d)
        data = os.path.join(d, "data.rio")
        rng = np.random.RandomState(7)
        centers = rng.randn(4, DIM).astype(np.float32) * 2.0
        recs = []
        for i in range(64):
            v = (centers[i % 4] + 0.3 * rng.randn(DIM)).astype(np.float32)
            recs.append(
                np.concatenate([v, [np.float32(i % 4)]])
                .astype(np.float32).tobytes()
            )
        recordio.write_records(data, iter(recs), max_chunk_records=4)
        ha = _start_master(d, data, timeout_s=120.0, worker_timeout_s=20.0)
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PYTHONPATH=REPO + os.pathsep
                       + os.environ.get("PYTHONPATH", ""))
            procs = [
                subprocess.Popen(
                    [sys.executable, "-m", "paddle_tpu.trainer.elastic",
                     "--dir", os.path.join(d, "ha"),
                     "--worker-id", f"w{i}", "--num-passes", "2",
                     "--model", "mlp", "--seed", "5",
                     "--model-arg", f"dim={DIM}", "--model-arg", "classes=4",
                     "--model-arg", "hidden=16", "--model-arg", "lr=0.1",
                     "--min-workers", str(n),
                     "--stats-out", os.path.join(d, f"stats{i}.json")],
                    env=env,
                )
                for i in range(n)
            ]
            assert [p.wait() for p in procs] == [0] * n
        finally:
            ha.stop()
        with open(os.path.join(d, "stats0.json")) as f:
            return json.load(f)["pass_costs"]

    costs1 = fleet("n1", 1)
    costs2 = fleet("n2", 2)
    assert costs1 == costs2
    assert costs1[-1] < costs1[0]  # and it actually learns
