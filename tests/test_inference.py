"""paddle.infer / Inference surface (reference python/paddle/v2/inference.py)
and the beam_search generation layer (reference trainer_config_helpers
layers.py beam_search/GeneratedInput; RecurrentGradientMachine.cpp:964)."""

import io

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layers
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models import seq2seq as s2s


def _train_classifier(n_cls=3, dim=4, n=120):
    x = layers.data("x", paddle.data_type.dense_vector(dim))
    y = layers.data("y", paddle.data_type.integer_value(n_cls))
    hidden = layers.fc(x, size=16, act=paddle.activation.Tanh())
    pred = layers.fc(hidden, size=n_cls, act=paddle.activation.Softmax())
    cost = layers.classification_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    rng = np.random.RandomState(0)
    centers = rng.randn(n_cls, dim) * 3

    def reader():
        for _ in range(n):
            c = rng.randint(n_cls)
            yield centers[c] + rng.randn(dim) * 0.3, c

    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-2),
    )
    trainer.train(reader=paddle.batch(reader, 20), num_passes=6)
    samples = [(centers[c] + rng.randn(dim) * 0.3,) for c in [0, 1, 2, 1, 0, 2, 2]]
    wanted = [0, 1, 2, 1, 0, 2, 2]
    return pred, params, samples, wanted


def test_infer_classification():
    reset_auto_names()
    pred, params, samples, wanted = _train_classifier()
    probs = paddle.infer(output_layer=pred, parameters=params, input=samples)
    assert probs.shape == (7, 3)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-3)
    assert list(np.argmax(probs, axis=1)) == wanted


def test_infer_batched_matches_single():
    reset_auto_names()
    pred, params, samples, _ = _train_classifier()
    whole = paddle.infer(output_layer=pred, parameters=params, input=samples)
    chunked = paddle.infer(
        output_layer=pred, parameters=params, input=samples, batch_size=3
    )
    np.testing.assert_allclose(whole, chunked, rtol=1e-4, atol=1e-5)


def test_infer_field_id_and_multiple_outputs():
    reset_auto_names()
    pred, params, samples, wanted = _train_classifier()
    ids_layer = layers.maxid(pred)
    # maxid has no params; reuse the trained ones for the shared prefix
    inferer = paddle.Inference(
        output_layer=[pred, ids_layer], parameters=params
    )
    probs, ids = inferer.infer(input=samples, field="value")
    assert probs.shape == (7, 3)
    assert list(np.asarray(ids).reshape(-1).astype(int)) == wanted
    ids2 = paddle.infer(
        output_layer=ids_layer, parameters=params, input=samples, field="id"
    )
    assert ids2.dtype == np.int64


def test_infer_unpads_sequence_output():
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector_sequence(2))
    proj = layers.fc(x, size=5, act=paddle.activation.Tanh())
    params = paddle.parameters.create(proj)
    samples = [([[0.1, 0.2], [0.3, 0.4], [0.5, 0.6]],), ([[1.0, 1.0]],)]
    vals = paddle.infer(output_layer=proj, parameters=params, input=samples)
    # CSR-rows convention: 3 + 1 valid timesteps concatenated
    assert vals.shape == (4, 5)


def test_infer_mnist_lenet():
    """LeNet forward through paddle.infer (mnist demo shape)."""
    reset_auto_names()
    from paddle_tpu.models.lenet import lenet_cost

    cost, pred = lenet_cost()
    params = paddle.parameters.create(cost)
    rng = np.random.RandomState(1)
    samples = [(rng.rand(784).astype(np.float32),) for _ in range(5)]
    probs = paddle.infer(output_layer=pred, parameters=params, input=samples)
    assert probs.shape == (5, 10)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-3)


def _untrained_classifier(n_cls=3, dim=4):
    x = layers.data("x", paddle.data_type.dense_vector(dim))
    hidden = layers.fc(x, size=16, act=paddle.activation.Tanh())
    pred = layers.fc(hidden, size=n_cls, act=paddle.activation.Softmax())
    return pred, paddle.parameters.create(pred)


def test_infer_ragged_batch_sizes_hit_jit_cache():
    """Repeated infer() with varying batch sizes must NOT retrace per size:
    the batch axis pads to a DEFAULT_BATCH_LADDER rung (compile-count
    regression for the pre-serving behavior, where every distinct B was a
    fresh XLA compile)."""
    reset_auto_names()
    pred, params = _untrained_classifier()
    inferer = paddle.Inference(output_layer=pred, parameters=params)
    rng = np.random.RandomState(0)
    samples = [(rng.rand(4).astype(np.float32),) for _ in range(8)]
    outs = {}
    for bs in (5, 6, 7, 8, 5):  # all land on the B=8 rung
        outs[bs] = inferer.infer(input=samples[:bs])
        assert outs[bs].shape == (bs, 3)
    assert inferer.trace_count == 1
    inferer.infer(input=samples[:3])  # B=4 rung: exactly one more trace
    inferer.infer(input=samples[:4])
    assert inferer.trace_count == 2
    # dead padding rows don't perturb the live rows
    np.testing.assert_array_equal(outs[8][:5], outs[5])
    # and the chunked path reuses the same rungs
    inferer.infer(input=samples, batch_size=4)  # chunks of 4, 4
    assert inferer.trace_count == 2


def test_infer_ragged_seq_lengths_hit_jit_cache():
    """Sequence inputs additionally round T onto the canonical shape
    ladder, so ragged lengths share compiled variants too."""
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector_sequence(2))
    proj = layers.fc(x, size=5, act=paddle.activation.Tanh())
    params = paddle.parameters.create(proj)
    inferer = paddle.Inference(output_layer=proj, parameters=params)
    rng = np.random.RandomState(1)

    def sample(n):
        return (rng.rand(n, 2).astype(np.float32).tolist(),)

    for lens in ((3, 5), (9, 2), (16, 1)):  # all pad to T=16, B=2
        vals = inferer.infer(input=[sample(n) for n in lens])
        assert vals.shape == (sum(lens), 5)  # unpadded CSR rows intact
    assert inferer.trace_count == 1
    inferer.infer(input=[sample(20), sample(4)])  # T=32 rung
    assert inferer.trace_count == 2


# ---------------------------------------------------------------------------
# generation through paddle.infer
# ---------------------------------------------------------------------------


V, E, H = 12, 6, 8
BOS, EOS = 0, 1


def _copy_reader(rng, n=60):
    """Tiny copy task: target repeats the source (bos/eos framed)."""

    def reader():
        for _ in range(n):
            seq = list(rng.randint(2, V, size=rng.randint(2, 5)))
            yield seq, [BOS] + seq, seq + [EOS]

    return reader


def test_beam_search_layer_through_infer():
    reset_auto_names()
    cost, dec = s2s.seq2seq_cost(V, V, word_dim=E, hidden_dim=H)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-2),
    )
    rng = np.random.RandomState(4)
    trainer.train(reader=paddle.batch(_copy_reader(rng), 10), num_passes=3)

    reset_auto_names()
    beam = s2s.seq2seq_generation(
        V, V, word_dim=E, hidden_dim=H,
        bos_id=BOS, eos_id=EOS, beam_size=3, max_length=6,
    )
    gen_params = paddle.parameters.create(beam)
    # weight transfer: shared names via tar round-trip + the gen embedding
    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    gen_params.from_tar(buf)
    gen_params.set("decoder.@gen_emb.w", params.get("trg_emb.w"))

    samples = [([3, 4, 5],), ([7, 8],)]
    ids = paddle.infer(
        output_layer=beam, parameters=gen_params, input=samples, field="id"
    )
    assert ids.shape == (2, 3, 6)  # [B, beam, max_length]
    assert ids.min() >= 0 and ids.max() < V
    # after eos, beams emit only eos (finished-beam propagation)
    for b in range(2):
        for k in range(3):
            seq = list(ids[b, k])
            if EOS in seq:
                at = seq.index(EOS)
                assert all(t == EOS for t in seq[at:])
    # scores exposed as auxiliary output, sorted best-first
    inferer = paddle.Inference(output_layer=beam, parameters=gen_params)
    out = next(inferer.iter_infer(input=samples))
    scores = np.asarray(out["decoder@scores"].data)
    assert scores.shape == (2, 3)
    assert (np.diff(scores, axis=1) <= 1e-5).all()  # best-first ordering


def test_gen_params_align_with_training():
    """The beam layer's sub-params must be name-compatible with the training
    recurrent_group so the tar round-trip actually transfers weights."""
    reset_auto_names()
    cost, _ = s2s.seq2seq_cost(V, V, word_dim=E, hidden_dim=H)
    train_p = paddle.parameters.create(cost)
    reset_auto_names()
    beam = s2s.seq2seq_generation(V, V, word_dim=E, hidden_dim=H)
    gen_p = paddle.parameters.create(beam)
    train_names = set(train_p.names())
    gen_names = set(gen_p.names())
    shared = {n for n in gen_names if not n.startswith("decoder.@gen_emb")}
    missing = shared - train_names
    assert not missing, f"gen-only params (name drift): {sorted(missing)}"
    # and the transfer changes values
    buf = io.BytesIO()
    train_p.to_tar(buf)
    buf.seek(0)
    gen_p.from_tar(buf)
    some = next(n for n in sorted(shared) if n.startswith("decoder."))
    np.testing.assert_allclose(gen_p.get(some), train_p.get(some))
