"""Obs-plane acceptance drills (slow; `make trace-demo` / `make chaos`).

The ISSUE-13 acceptance: `paddle-tpu scenario mixed_train_serve --trace`
must produce ONE merged Chrome-trace JSON correlating spans from >= 2
PROCESSES and >= 3 PLANES — the serving request lifecycle, the trainer
step plane, and the master RPC plane — clock-skew aligned via the RPC
request/response pairs.  Plus the kill -9 postmortem: a chaos ``kill``
SIGKILL leaves a flight-recorder timeline from the dead process.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(**extra):
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", OMP_NUM_THREADS="2",
        PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    env.update(extra)
    return env


def test_traced_scenario_merges_cross_process_timeline(tmp_path):
    trace_dir = str(tmp_path / "trace")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "scenario",
         "--name", "mixed_train_serve", "--trace", "--trace-dir", trace_dir],
        env=_env(), cwd=_REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stdout[-4000:]
    result = json.loads(
        [ln for ln in proc.stdout.splitlines()
         if ln.startswith("{")][-1]
    )
    assert result["passed"] is True
    assert result["traced_fleet"]["worker_rc"] == 0
    mpath = result["trace"]["merged"]
    assert os.path.exists(mpath)

    from paddle_tpu.obs.merge import load_trace, validate_trace

    merged = load_trace(mpath)
    assert validate_trace(merged) == [], validate_trace(merged)[:10]
    evs = [e for e in merged["traceEvents"] if e.get("ph") != "M"]

    # >= 2 processes contributed real events
    pids = {e["pid"] for e in evs}
    assert len(pids) >= 2, pids
    assert set(merged["otherData"]["merged_pids"]) == pids

    # >= 3 planes: serving request lifecycle, trainer step, master RPC
    cats = {e.get("cat") for e in evs}
    assert {"serving", "trainer", "master"} <= cats, cats

    # serving request lifecycle: one request id walks submit -> queued ->
    # admit -> done, in order, on the unified clock
    by_req = {}
    for e in evs:
        req = (e.get("args") or {}).get("req")
        if req is not None:
            by_req.setdefault(req, {}).setdefault(e["name"], e["ts"])
    walked = [
        d for d in by_req.values()
        if {"serving/submit", "serving/queued", "serving/admit",
            "serving/done"} <= set(d)
    ]
    assert walked, "no request completed a full traced lifecycle"
    for d in walked[:5]:
        assert (d["serving/submit"] <= d["serving/queued"]
                <= d["serving/admit"] <= d["serving/done"])

    # trainer plane: steps in the parent AND elastic task spans in the
    # worker subprocess
    assert any(e["name"] == "train_step" for e in evs)
    worker_pids = {
        e["pid"] for e in evs if e["name"].startswith("elastic/")
    }
    assert worker_pids and worker_pids < pids  # a strict subset: 2 procs

    # master RPC plane, CORRELATED across processes: the same rpc id on a
    # client span (worker) and a server span (parent)
    call_pids = {}
    handle_pids = {}
    for e in evs:
        rpc = (e.get("args") or {}).get("rpc")
        if rpc is None:
            continue
        if e["name"].startswith("rpc_call:"):
            call_pids[rpc] = e["pid"]
        elif e["name"].startswith("rpc:"):
            handle_pids[rpc] = e["pid"]
    cross = [
        r for r in set(call_pids) & set(handle_pids)
        if call_pids[r] != handle_pids[r]
    ]
    assert cross, "no cross-process rpc correlation pairs in the timeline"
    # and the merger used them for skew alignment
    assert merged["otherData"]["rpc_pair_edges"], merged["otherData"]

    # after alignment, each cross-process handling span's begin sits no
    # earlier than its client span's begin (server handles AFTER dial)
    b_ts = {}
    for e in evs:
        rpc = (e.get("args") or {}).get("rpc")
        if rpc in cross and e["ph"] == "B":
            b_ts.setdefault(rpc, {})[e["name"].split(":")[0]] = e["ts"]
    aligned_ok = sum(
        1 for d in b_ts.values()
        if "rpc_call" in d and "rpc" in d and d["rpc"] >= d["rpc_call"] - 5e3
    )
    assert aligned_ok >= len(b_ts) * 0.8, b_ts


def test_chaos_kill_sigkill_leaves_flight_postmortem(tmp_path):
    """The kill -9 drill's postmortem: arming ``kill@1`` in a subprocess
    dumps flight-<pid>.json at the firing consultation, BEFORE SIGKILL
    lands — the dead process's only record."""
    code = textwrap.dedent("""
        from paddle_tpu import obs
        from paddle_tpu.robustness import chaos
        obs.instant("train_step", cat="trainer", b=12)
        chaos.arm("kill@1")
        if chaos.fire("kill"):
            chaos.kill_self()
        raise SystemExit("kill point did not fire")
    """)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=_env(PADDLE_TPU_TRACE_DIR=str(tmp_path)), cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=300,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stdout.decode()[-2000:]
    flights = list(tmp_path.glob("flight-*.json"))
    assert len(flights) == 1
    obj = json.loads(flights[0].read_text())
    assert obj["otherData"]["reason"].startswith("chaos:kill@")
    assert any(e["name"] == "train_step" for e in obj["traceEvents"])
