"""Interleaving explorer (analysis/interleave.py): seeded determinism,
the planted double-serve canary (detect -> shrink <=6 events -> replay),
clean batches over every model, and the shrunk-spec regression for the
router re-route livelock the explorer found (see Router._dispatch)."""

import logging

import pytest

from paddle_tpu.analysis.interleave import (
    dfs_explore,
    explore_schedules,
    make_model,
    replay_spec,
    run_schedule,
    shrink_events,
)


@pytest.fixture(autouse=True)
def _quiet_router_logs():
    # fault injection makes the router narrate every simulated transport
    # failure; hundreds of schedules would drown the test output
    logger = logging.getLogger("paddle_tpu")
    prev = logger.level
    logger.setLevel(logging.ERROR)
    yield
    logger.setLevel(prev)


@pytest.fixture()
def router_model(tmp_path):
    m = make_model("router", str(tmp_path))
    yield m
    m.close()


# the acceptance canary, computed once per module: plant the journal bug,
# let the batch find it, shrink, and keep the spec for the replay tests
@pytest.fixture(scope="module")
def canary(tmp_path_factory):
    logging.getLogger("paddle_tpu").setLevel(logging.ERROR)
    m = make_model("router", str(tmp_path_factory.mktemp("canary")),
                   planted="double_serve")
    res = explore_schedules(m, schedules=200, seed=7, max_events=12)
    m.close()
    return res


# ---------------------------------------------------------------------------
# determinism: same seed, same trajectory, same shrunk spec
# ---------------------------------------------------------------------------


def test_seeded_exploration_is_deterministic(tmp_path):
    outs = []
    for run in ("a", "b"):
        m = make_model("router", str(tmp_path / run),
                       planted="double_serve")
        outs.append(explore_schedules(m, schedules=200, seed=7,
                                      max_events=12))
        m.close()
    a, b = outs
    assert a["violation_found"] and b["violation_found"]
    assert a["schedules_run"] == b["schedules_run"]
    assert a["spec"]["events"] == b["spec"]["events"]
    assert a["spec"]["violations"] == b["spec"]["violations"]


# ---------------------------------------------------------------------------
# the canary: detect, shrink to a handful of events, replay
# ---------------------------------------------------------------------------


def test_planted_double_serve_is_caught_and_shrunk(canary):
    assert canary["violation_found"], (
        "planted journal bug escaped 200 schedules — the harness is blind"
    )
    spec = canary["spec"]
    assert len(spec["events"]) <= 6, spec["events"]
    assert any("double-serve" in v for v in spec["violations"])
    # the shrunk schedule must still exercise the failure ingredients:
    # a settle, a router bounce, and a client retry
    ops = [e["op"] for e in spec["events"]]
    assert "crash_router" in ops and "retry" in ops


def test_replay_of_shrunk_spec_reproduces(canary):
    out = replay_spec(canary["spec"])
    assert out["reproduced"], out
    assert any("double-serve" in v for v in out["violations"])


def test_replay_of_clean_spec_reports_no_reproduction(tmp_path):
    spec = {
        "version": 1, "model": "router", "planted": None, "seed": 0,
        "events": [{"op": "submit", "req": "q1"}],
        "violations": ["(none expected)"],
    }
    out = replay_spec(spec, workdir=str(tmp_path))
    assert not out["reproduced"]
    assert out["violations"] == []


def test_shrink_events_drops_irrelevant_noise(tmp_path):
    # pad the violating core with no-op churn; ddmin must strip it
    m = make_model("router", str(tmp_path), planted="double_serve")
    noisy = [
        {"op": "advance", "dt": 3.0},
        {"op": "submit", "req": "q2"},
        {"op": "heartbeat", "engine": "e1"},
        {"op": "advance", "dt": 3.0},
        {"op": "crash_router"},
        {"op": "restart_router"},
        {"op": "heartbeat", "engine": "e2"},
        {"op": "retry", "req": "q2"},
    ]
    assert run_schedule(m, noisy)["violations"]
    small = shrink_events(m, noisy)
    assert len(small) <= 4
    assert run_schedule(m, small)["violations"]
    m.close()


# ---------------------------------------------------------------------------
# clean batches: the real (unplanted) planes survive exploration
# ---------------------------------------------------------------------------


def test_router_random_batch_is_clean(router_model):
    res = explore_schedules(router_model, schedules=40, seed=1,
                            max_events=12)
    assert not res["violation_found"], res["spec"]


def test_router_dfs_sweep_is_clean(router_model):
    res = dfs_explore(router_model, depth=3, branch_limit=5, max_paths=200)
    assert not res["violation_found"], res["spec"]
    assert res["paths_run"] > 50


def test_master_random_batch_is_clean(tmp_path):
    m = make_model("master", str(tmp_path))
    res = explore_schedules(m, schedules=25, seed=11, max_events=12)
    m.close()
    assert not res["violation_found"], res["spec"]


def test_ha_random_and_dfs_are_clean(tmp_path):
    m = make_model("ha", str(tmp_path))
    res = explore_schedules(m, schedules=40, seed=5, max_events=10)
    assert not res["violation_found"], res["spec"]
    res = dfs_explore(m, depth=4, branch_limit=5, max_paths=400)
    assert not res["violation_found"], res["spec"]
    m.close()


# ---------------------------------------------------------------------------
# targeted schedules: protocol facts the models must hold
# ---------------------------------------------------------------------------


def test_master_duplicate_ack_is_idempotent(tmp_path):
    # the reply-lost retry: a duplicate (task, epoch) ack is accepted-
    # and-deduped — queue state frozen, first result payload wins
    m = make_model("master", str(tmp_path))
    out = run_schedule(m, [
        {"op": "get", "worker": "w0"},
        {"op": "finish", "worker": "w0"},
        {"op": "stale_ack"},
    ])
    m.close()
    assert out["violations"] == []


def test_router_terminates_when_every_engine_is_unreachable(tmp_path):
    # regression for the re-route livelock the explorer found: with all
    # live engines partitioned (heartbeats fine, data plane dead) and no
    # request deadline, _dispatch used to reset its tried-set and spin
    # forever with zero delay — no terminal status, no timeout path.
    # The fix bounds the sweeps and settles the request as rejected.
    m = make_model("router", str(tmp_path))
    out = run_schedule(m, [
        {"op": "partition", "engine": "e1"},
        {"op": "partition", "engine": "e2"},
        {"op": "submit", "req": "q1"},
    ])
    assert out["violations"] == []
    assert out["applied"] == 3  # the submit RETURNED — no livelock
    assert m.results[-1]["status"] == "rejected"
    assert "sweeps" in (m.results[-1].get("error") or "")
    m.close()
