"""master_wire codec tests: typed roundtrip (numpy bit-exactness), the
structured error taxonomy (type / oversize / version / corrupt), the
allocation bounds a hostile frame must hit, the send+recv
``rpc_max_message_mb`` enforcement through a real Server/Client pair, and
the journal's PTJ2 payload migration (+ PTJ1 legacy read)."""

import os
import pickle
import struct
import zlib

import numpy as np
import pytest

from paddle_tpu import master_journal as mj
from paddle_tpu import master_wire as w


def _deep_eq(a, b):
    if isinstance(a, np.ndarray):
        return (isinstance(b, np.ndarray) and a.dtype == b.dtype
                and a.shape == b.shape
                and np.array_equal(a, b, equal_nan=True))
    if isinstance(a, np.generic):
        return type(a) is type(b) and (a == b or a != a)
    if isinstance(a, dict):
        return (isinstance(b, dict) and set(a) == set(b)
                and all(_deep_eq(a[k], b[k]) for k in a))
    if isinstance(a, (list, tuple)):
        return (type(a) is type(b) and len(a) == len(b)
                and all(_deep_eq(x, y) for x, y in zip(a, b)))
    return type(a) is type(b) and (a == b or (a != a and b != b))


# ---------------------------------------------------------------------------
# payload roundtrip
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("obj", [
    None, True, False, 0, -1, 2**62, 2**100, -(2**200), 1.5, float("nan"),
    "", "日本語 text", b"", b"\x00\xff" * 7,
    [1, [2, [3, None]]], (1, (2,), "x"), {},
    {"a": 1, 2: "b", b"k": None, 1.5: True},
    {"grads": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
               "b": np.float32(-0.25)},
     "cost": 0.125, "rows": 8},
])
def test_payload_roundtrip(obj):
    assert _deep_eq(obj, w.decode_payload(w.encode_payload(obj)))


@pytest.mark.parametrize("dtype", [
    np.bool_, np.int8, np.uint16, np.int32, np.int64, np.float16,
    np.float32, np.float64, np.complex64,
])
def test_ndarray_roundtrip_bit_exact(dtype):
    rng = np.random.RandomState(3)
    arr = (rng.randn(5, 3) * 100).astype(dtype)
    out = w.decode_payload(w.encode_payload(arr))
    assert out.dtype == arr.dtype and out.shape == arr.shape
    assert out.tobytes() == arr.tobytes()  # BIT exact, not just equal


def test_ndarray_empty_zero_dim_and_noncontiguous():
    for arr in (np.zeros((0,), np.float64), np.zeros((2, 0, 3), np.int8),
                np.float64(7.0), np.arange(12).reshape(3, 4).T):
        out = w.decode_payload(w.encode_payload(arr))
        assert np.array_equal(np.asarray(out), np.asarray(arr))


def test_numpy_scalar_preserves_type():
    out = w.decode_payload(w.encode_payload(np.float32(1.5)))
    assert type(out) is np.float32 and out == np.float32(1.5)


# ---------------------------------------------------------------------------
# the restricted set: refusals are structured and deterministic
# ---------------------------------------------------------------------------


def test_unencodable_type_is_wire_type_error():
    class Evil:
        pass

    with pytest.raises(w.WireTypeError, match="Evil"):
        w.encode_payload({"x": Evil()})
    with pytest.raises(w.WireTypeError, match="restricted wire set"):
        w.encode_payload({1, 2})  # sets are not on the wire


def test_object_dtype_rejected_both_sides():
    with pytest.raises(w.WireTypeError, match="dtype"):
        w.encode_payload(np.array([object()], dtype=object))


def test_non_primitive_dict_key_rejected():
    with pytest.raises(w.WireTypeError, match="hashable primitives"):
        w.encode_payload({(1, 2): "x"})  # tuple key is not a primitive


def test_nesting_bomb_rejected_on_encode():
    obj = []
    for _ in range(w.MAX_DEPTH + 2):
        obj = [obj]
    with pytest.raises(w.WireTypeError, match="MAX_DEPTH"):
        w.encode_payload(obj)


def test_decode_never_overallocates():
    # a crafted count far beyond the buffer must refuse BEFORE allocating
    bomb = b"l" + struct.pack(">I", 2**31 - 1) + b"N"
    with pytest.raises(w.WireCorruptError, match="refusing to preallocate"):
        w.decode_payload(bomb)
    # ndarray claiming gigabytes it doesn't carry
    bomb = b"a" + bytes([3]) + b"<f8" + bytes([1]) + struct.pack(">I", 2**30)
    with pytest.raises(w.WireCorruptError, match="refusing to allocate"):
        w.decode_payload(bomb)


def test_decode_rejects_trailing_and_truncated():
    enc = w.encode_payload([1, 2])
    with pytest.raises(w.WireCorruptError, match="trailing"):
        w.decode_payload(enc + b"\x00")
    with pytest.raises(w.WireCorruptError, match="truncated"):
        w.decode_payload(enc[:-1])
    # "Q" stopped being unknown when the compact uint8 tag landed — probe
    # with a byte outside the whole tag vocabulary
    with pytest.raises(w.WireCorruptError, match="unknown payload type tag"):
        w.decode_payload(b"~")
    # a TRUNCATED compact-tag array is a corruption error, not a crash
    with pytest.raises(w.WireCorruptError, match="truncated"):
        w.decode_payload(b"Q")


def test_decode_rejects_object_dtype_string():
    blob = b"z" + bytes([3]) + b"|O8" + b"\x00" * 8
    with pytest.raises(w.WireCorruptError):
        w.decode_payload(blob)


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def test_frame_roundtrip_and_overhead():
    payload = w.encode_payload({"m": "get_task", "a": (1,)})
    frame = w.encode_frame(payload)
    assert len(frame) == len(payload) + w.FRAME_OVERHEAD
    assert w.decode_frame(frame) == payload


def test_frame_oversize_send_and_recv():
    with pytest.raises(w.WireOversizeError, match="refusing to send"):
        w.encode_frame(b"x" * 100, max_bytes=64)
    frame = w.encode_frame(b"x" * 100)
    with pytest.raises(w.WireOversizeError):
        w.decode_frame(frame, max_bytes=64)


def test_frame_corruption_every_byte_detected():
    """Flip EVERY byte position once: each must surface as a structured
    MasterWireError — never a misparse, never an unhandled exception."""
    frame = bytearray(w.encode_frame(w.encode_payload(
        {"grads": np.arange(3, dtype=np.float32), "rows": 3}
    )))
    for i in range(len(frame)):
        bad = bytearray(frame)
        bad[i] ^= 0xFF
        with pytest.raises(w.MasterWireError):
            w.decode_payload(w.decode_frame(bytes(bad)))


def test_frame_unknown_version():
    frame = bytearray(w.encode_frame(w.encode_payload(1)))
    frame[3] = w.VERSION + 7
    with pytest.raises(w.WireVersionError, match="version skew"):
        w.decode_frame(bytes(frame))


def test_frame_truncated_header():
    with pytest.raises(w.WireCorruptError, match="shorter than"):
        w.decode_frame(b"PTW")
    with pytest.raises(w.WireCorruptError, match="bad frame magic"):
        w.decode_frame(b"NOPE" + b"\x00" * 20)


def test_frame_length_field_mismatch():
    payload = w.encode_payload([1, 2, 3])
    frame = bytearray(w.encode_frame(payload))
    struct.pack_into(">I", frame, 4, len(payload) + 1)
    with pytest.raises(w.MasterWireError):
        w.decode_frame(bytes(frame))


# ---------------------------------------------------------------------------
# rpc_max_message_mb through a real Server/Client pair
# ---------------------------------------------------------------------------


def test_rpc_oversize_send_is_structured(tmp_path):
    from paddle_tpu.master import Client, Server, Service

    svc = Service(auto_rotate=False)
    srv = Server(svc)
    c = Client(srv.address, call_timeout_s=5.0,
               max_message_bytes=64 * 1024)
    try:
        big = {"grads": {"w": np.zeros(1 << 16, np.float64)}, "cost": 0.0,
               "rows": 1}
        with pytest.raises(w.WireOversizeError, match="rpc_max_message_mb"):
            c.task_finished(0, 0, big, 0)
        # the structured refusal did not poison the connection
        assert c.n_tasks() == 0
    finally:
        c.close()
        srv.close()


def test_rpc_oversize_recv_refused_before_allocation():
    """An over-budget INBOUND frame is refused by the server before any
    allocation (the connection drops; the accept loop survives): the
    storm satellite's 'oversized inbound frame used to allocate
    unbounded' hole, closed."""
    from paddle_tpu.master import Client, MasterTransportError, Server, Service

    w.counters.reset()
    svc = Service(auto_rotate=False)
    srv = Server(svc, max_message_bytes=16 * 1024)
    c = Client(srv.address, call_timeout_s=5.0, reconnect_tries=2,
               reconnect_backoff=0.01)
    try:
        big = {"grads": {"w": np.zeros(1 << 15, np.float64)}, "cost": 0.0,
               "rows": 1}
        with pytest.raises(MasterTransportError):
            c.task_finished(0, 0, big, 0)  # 256 KB frame vs a 16 KB server
        snap = w.counters.snapshot()
        assert snap.get("server_oversize_frames", 0) >= 1
        assert snap.get("server_rejected_frames", 0) >= 1
        # the accept loop survived: a fresh client is served normally
        c2 = Client(srv.address, call_timeout_s=5.0)
        assert c2.n_tasks() == 0
        c2.close()
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# journal payloads ride the codec (PTJ2), legacy PTJ1 stays readable
# ---------------------------------------------------------------------------


def test_journal_frames_are_wire_encoded_not_pickled(tmp_path):
    rec = {"t": "finish", "task": 1, "epoch": 0, "pass": 0,
           "result": {"grads": {"w": np.ones(4, np.float32)}, "cost": 1.0,
                      "rows": 4}}
    frame = mj.encode_frame(7, rec)
    assert frame[:4] == mj.MAGIC == b"PTJ2"
    payload = frame[20:]  # MAGIC(4) + seq/len(12) + crc(4)
    got = w.decode_payload(payload)  # decodes via the codec...
    assert got["t"] == "finish"
    with pytest.raises(Exception):  # noqa: B017 — any unpickle failure
        pickle.loads(payload)  # ...and is NOT pickle
    p = str(tmp_path / "j.log")
    with open(p, "wb") as f:
        f.write(frame)
    records, info = mj.read_records(p)
    assert not info["corrupt"] and not info["torn"]
    assert records[0][0] == 7
    assert np.array_equal(records[0][1]["result"]["grads"]["w"],
                          np.ones(4, np.float32))


def test_journal_legacy_ptj1_pickled_frames_still_replay(tmp_path):
    """An upgrade boot must replay a pre-wire-codec journal: PTJ1 frames
    (pickled payload) decode on the read path; everything newly written
    is PTJ2."""
    rec = {"t": "lease", "task": 3, "epoch": 0, "worker": "w1"}
    payload = pickle.dumps(rec, protocol=4)
    header = struct.pack(">QI", 5, len(payload))
    crc = zlib.crc32(header + payload) & 0xFFFFFFFF
    legacy = mj.MAGIC_V1 + header + struct.pack(">I", crc) + payload
    p = str(tmp_path / "j.log")
    with open(p, "wb") as f:
        f.write(legacy)          # old build's frame...
        f.write(mj.encode_frame(6, {"t": "fail", "task": 3, "epoch": 0}))
    records, info = mj.read_records(p)
    assert not info["corrupt"]
    assert [(s, r["t"]) for s, r in records] == [(5, "lease"), (6, "fail")]
    assert mj.verify_journal(p) == []


def test_journal_unpicklable_ptj2_payload_flags_corrupt(tmp_path):
    frame = bytearray(mj.encode_frame(1, {"t": "rotate", "from": 0}))
    # wreck the payload's type tag AND refresh the CRC: a crc-INTACT
    # frame whose payload fails the TYPED decode must still flag as
    # corrupt (never crash, never half-decode)
    frame[20] = ord("Q")  # unknown wire tag
    crc = zlib.crc32(bytes(frame[4:16]) + bytes(frame[20:])) & 0xFFFFFFFF
    struct.pack_into(">I", frame, 16, crc)
    p = str(tmp_path / "j.log")
    with open(p, "wb") as f:
        f.write(bytes(frame))
    records, info = mj.read_records(p)
    assert records == [] and info["corrupt"]
    assert "undecodable payload" in info["error"]
