"""Master HA: file-lease leader election + hot-standby failover with
snapshot recovery (the etcd campaign/lease/state/discovery roles of
go/master/etcd_client.go over a shared directory)."""

import os
import time

import pytest

from paddle_tpu.io import recordio
from paddle_tpu import master_ha
from paddle_tpu.master_ha import HAClient, HAMaster, LeaseFile, discover_endpoint


def _write_data(tmp_path, n=120):
    p = str(tmp_path / "data.rio")
    recordio.write_records(p, (f"r{i}".encode() for i in range(n)), max_chunk_records=10)
    return p


def test_lease_single_winner(tmp_path):
    a = LeaseFile(str(tmp_path), "a", lease_timeout=5.0)
    b = LeaseFile(str(tmp_path), "b", lease_timeout=5.0)
    assert a.try_acquire()
    assert not b.try_acquire()  # fresh lease held by a
    assert a.held_by_me() and not b.held_by_me()
    assert a.renew()
    a.release()
    assert b.try_acquire()


def test_lease_stale_takeover(tmp_path):
    a = LeaseFile(str(tmp_path), "a", lease_timeout=0.2)
    b = LeaseFile(str(tmp_path), "b", lease_timeout=0.2)
    assert a.try_acquire()
    time.sleep(0.3)  # a stops renewing -> stale
    assert b.try_acquire()
    assert not a.renew()  # usurped: a must step down


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _lease(tmp_path, owner, clk, timeout=5.0):
    return LeaseFile(
        str(tmp_path), owner, lease_timeout=timeout,
        clock=clk, sleep=lambda s: None,
    )


def test_lease_injected_clock_staleness_no_real_sleeps(tmp_path):
    """is_stale/renew are judged entirely against the injected clock: the
    whole expiry lifecycle runs without a single wall-clock sleep."""
    clk = _FakeClock()
    a = _lease(tmp_path, "a", clk)
    assert a.try_acquire()
    assert not a.is_stale()
    clk.advance(4.9)
    assert not a.is_stale()
    assert a.renew()  # heartbeat re-stamps mtime from the same clock
    clk.advance(4.9)
    assert not a.is_stale()  # renewal actually moved the deadline
    clk.advance(0.2)
    assert a.is_stale()


def test_lease_renew_fails_after_steal(tmp_path):
    """The renew-after-steal race: a stalls past its lease timeout, b
    claims the stale lease, and a's next renew MUST fail (it would
    otherwise heartbeat b's lease and both sides would believe they
    lead)."""
    clk = _FakeClock()
    a = _lease(tmp_path, "a", clk)
    b = _lease(tmp_path, "b", clk)
    assert a.try_acquire()
    clk.advance(6.0)  # a stalls: the lease goes stale under it
    assert b.try_acquire()
    assert not a.renew()  # usurped — a steps down
    assert b.renew()  # the new owner's heartbeat still works
    assert b.held_by_me() and not a.held_by_me()


def test_lease_claim_races_have_one_winner_fake_clock(tmp_path):
    clk = _FakeClock()
    a = _lease(tmp_path, "a", clk)
    b = _lease(tmp_path, "b", clk)
    assert a.try_acquire()
    clk.advance(6.0)
    # both see the lease stale and race; last-writer-wins leaves exactly
    # one of them owning
    ra, rb = a.try_acquire(), b.try_acquire()
    assert (ra, rb) in ((True, False), (False, True))
    winner = a if ra else b
    assert winner.held_by_me()


def test_leader_serves_and_publishes_endpoint(tmp_path):
    data = _write_data(tmp_path)
    ha = HAMaster(str(tmp_path / "ha"), [data], owner_id="m0",
                  lease_timeout=2.0, snapshot_min_interval_s=0.0)
    ha.start()
    try:
        assert ha.wait_leader(10)
        ep = discover_endpoint(str(tmp_path / "ha"))
        assert ep is not None
        client = HAClient(str(tmp_path / "ha"))
        recs = [r for r in iter(client.next_record, None)]
        assert len(recs) == 120
        client.close()
    finally:
        ha.stop()


def test_failover_preserves_pass_records(tmp_path):
    """Leader dies mid-pass; the standby takes over from the shared
    snapshot; the client re-resolves and still sees every record
    (duplicates allowed — at-least-once — but no loss)."""
    data = _write_data(tmp_path)
    hadir = str(tmp_path / "ha")
    m0 = HAMaster(hadir, [data], owner_id="m0", lease_timeout=1.0,
                  snapshot_min_interval_s=0.0)
    m1 = HAMaster(hadir, [data], owner_id="m1", lease_timeout=1.0,
                  snapshot_min_interval_s=0.0)
    m0.start()
    assert m0.wait_leader(10)
    m1.start()
    time.sleep(0.3)
    assert not m1.is_leader.is_set()  # hot standby

    client = HAClient(hadir, timeout=30.0)
    got = []
    for _ in range(30):  # consume a few tasks from the first leader
        r = client.next_record()
        assert r is not None
        got.append(r)

    m0.freeze()  # crash: no release, no renewals, server gone
    # standby must take over within a few lease timeouts
    assert m1.wait_leader(15)
    assert discover_endpoint(hadir) is not None

    while True:  # finish the pass against the new leader
        r = client.next_record()
        if r is None:
            break
        got.append(r)
    client.close()
    want = {f"r{i}".encode() for i in range(120)}
    assert want.issubset(set(got)), sorted(want - set(got))[:5]
    m1.stop()


def test_client_times_out_without_any_leader(tmp_path):
    client = HAClient(str(tmp_path / "nothing"), timeout=0.5)
    with pytest.raises(TimeoutError):
        client.next_record()


def test_training_survives_failover(tmp_path):
    """A real training loop fed by HAClient keeps running across a leader
    crash: the trainer's reader re-resolves to the standby mid-pass, the
    whole pass is consumed, and the loss still improves."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import layers
    from paddle_tpu.core.topology import reset_auto_names

    rng = np.random.RandomState(0)
    data = str(tmp_path / "train.rio")
    recs = []
    for i in range(240):
        c = i % 3
        x = np.concatenate(
            [np.full(4, c, np.float32), rng.rand(2).astype(np.float32), [c]]
        )
        recs.append(x.astype(np.float32).tobytes())
    recordio.write_records(data, iter(recs), max_chunk_records=20)

    hadir = str(tmp_path / "ha")
    m0 = HAMaster(hadir, [data], owner_id="m0", lease_timeout=1.0,
                  snapshot_min_interval_s=0.0)
    m1 = HAMaster(hadir, [data], owner_id="m1", lease_timeout=1.0,
                  snapshot_min_interval_s=0.0)
    m0.start()
    assert m0.wait_leader(10)
    m1.start()

    client = HAClient(hadir, timeout=30.0)
    reset_auto_names()
    x = layers.data("x", paddle.data_type.dense_vector(6))
    y = layers.data("y", paddle.data_type.integer_value(3))
    pred = layers.fc(layers.fc(x, 16), size=3, act=paddle.activation.Softmax())
    cost = layers.classification_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-2),
    )

    state = {"n": 0, "killed": False}

    def record_reader():
        while True:
            r = client.next_record()
            if r is None:
                return
            state["n"] += 1
            if state["n"] == 60:
                state["killed"] = True
                m0.freeze()  # leader crash mid-pass
            a = np.frombuffer(r, np.float32)
            yield a[:6], int(a[6])

    costs = []
    try:
        trainer.train(
            reader=paddle.batch(record_reader, 20),
            num_passes=3,
            event_handler=lambda e: costs.append(e.cost)
            if isinstance(e, paddle.event.EndIteration) else None,
        )
        assert state["killed"] and m1.is_leader.is_set()
        # 3 passes x 240 records (+ at-least-once duplicates) / 20 per batch
        assert len(costs) >= 36
        # failover must not corrupt optimization: loss improves end to end
        assert np.mean(costs[-4:]) < np.mean(costs[:4])
    finally:
        client.close()
        m0.stop()
        m1.stop()


def test_standby_tails_journal_and_takes_over_warm(tmp_path):
    """ISSUE 7 tentpole at the HA layer: the standby tails the leader's
    snapshot + journal into a live replica, so winning the campaign is a
    bounded replay + promote — leases stay warm, result payloads survive,
    requeue_unresulted finds ZERO tasks to recompute, and the in-flight
    worker's retried ack is absorbed."""
    import numpy as np

    from paddle_tpu.master import Client

    data = _write_data(tmp_path)
    hadir = str(tmp_path / "ha")
    kw = dict(lease_timeout=1.0, chunks_per_task=2, auto_rotate=False,
              timeout_s=60.0, worker_timeout_s=60.0)
    m0 = HAMaster(hadir, [data], owner_id="m0", **kw)
    m0.start()
    assert m0.wait_leader(10)
    m1 = HAMaster(hadir, [data], owner_id="m1", **kw)
    m1.start()

    # mid-pass workload on the first leader: two finished tasks with
    # result payloads, one in-flight lease whose reply we'll "lose"
    c = Client(m0.server.address)
    c.register_worker("w0")
    c.register_worker("w1")
    done = {}
    for _ in range(2):
        got = c.get_task("w0")
        payload = {"g": np.full(4, got["task"]["task_id"], np.float32),
                   "rows": 5}
        assert c.task_finished(got["task"]["task_id"], got["epoch"], payload)
        done[got["task"]["task_id"]] = payload
    inflight = c.get_task("w1")
    live_seq = m0.service._seq

    # the standby replica must catch up to the leader's journal tip
    deadline = time.time() + 10
    while time.time() < deadline:
        rep = m1._replica
        if rep is not None and rep._seq >= live_seq:
            break
        time.sleep(0.05)
    else:
        pytest.fail("standby never tailed the journal to the live seq")

    m0.freeze()  # kill -9 equivalent: no release, no renewals
    assert m1.wait_leader(15)
    assert m1.last_takeover is not None
    assert m1.last_takeover["warm"] is True
    assert m1.last_takeover["replayed_records"] > 0

    svc = m1.service
    assert svc.requeue_unresulted() == 0  # zero recomputed tasks
    res = svc.pass_results(0)["results"]
    assert res.keys() == done.keys()
    for tid, payload in done.items():
        np.testing.assert_array_equal(res[tid]["g"], payload["g"])
    # the in-flight lease survived WARM with its owner...
    tid, epoch = inflight["task"]["task_id"], inflight["epoch"]
    assert tid in svc.pending and svc.pending[tid][2] == "w1"
    # ...so the worker's retried ack against the new leader just lands
    c2 = Client(m1.server.address)
    assert c2.task_finished(tid, epoch, {"g": np.zeros(4, np.float32)})
    c2.close()
    m1.stop()


def test_takeover_survives_legacy_snapshot_dropping_replica(tmp_path):
    """Mixed-config fleet edge: a journaled candidate tails a journaled
    leader into a replica, but a deposed --no-journal leader publishes a
    LEGACY snapshot (no journal_file) before the candidate wins the
    campaign.  The final catch-up tick inside _become_leader then DROPS
    the replica it was about to promote — takeover must fall through to
    cold recovery, not crash promoting None (which would release the
    lease and extend the leaderless window by a full backoff)."""
    import json

    from paddle_tpu.master import Client

    data = _write_data(tmp_path)
    hadir = str(tmp_path / "ha")
    kw = dict(lease_timeout=1.0, chunks_per_task=2, auto_rotate=False,
              timeout_s=60.0, worker_timeout_s=60.0)
    m0 = HAMaster(hadir, [data], owner_id="m0", **kw)
    m0.start()
    assert m0.wait_leader(10)
    c = Client(m0.server.address)
    got = c.get_task("w0")
    assert c.task_finished(got["task"]["task_id"], got["epoch"], {"r": 1})
    c.close()
    live_seq = m0.service._seq
    snap_path = m0.service.snapshot_path

    m1 = HAMaster(hadir, [data], owner_id="m1", **kw)  # never start()ed
    deadline = time.time() + 10
    while m1._replica is None or m1._replica._seq < live_seq:
        m1._standby_tick()
        assert time.time() < deadline, "standby never built a live replica"
        time.sleep(0.02)
    m0.stop()

    # the deposed --no-journal leader's last word: a journal-less snapshot
    with open(snap_path) as f:
        state = json.load(f)
    state.pop("journal_file", None)
    with open(snap_path, "w") as f:
        json.dump(state, f)

    m1._become_leader()
    try:
        assert m1.is_leader.is_set()
        assert m1.service is not None
        assert m1.last_takeover["warm"] is False  # cold, but ALIVE
        # the cold service actually serves the legacy snapshot's queue
        assert m1.service.get_task("w1") not in (None, "wait")
    finally:
        m1._step_down()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_poisoned_journal_is_fatal_for_candidate(tmp_path):
    """An unknown journal record type (version skew / corruption) must
    kill the whole CANDIDATE loudly — ``fatal`` set, campaign thread dead,
    the CLI loop exits nonzero — never lurk as a zombie standby that can
    neither take over nor warn anyone."""
    import json as _json

    from paddle_tpu import master_journal as mj
    from paddle_tpu.master import Client

    data = _write_data(tmp_path)
    hadir = str(tmp_path / "ha")
    kw = dict(lease_timeout=1.0, chunks_per_task=2, auto_rotate=False,
              timeout_s=60.0, worker_timeout_s=60.0)
    m0 = HAMaster(hadir, [data], owner_id="m0", **kw)
    m0.start()
    assert m0.wait_leader(10)
    snap_path = m0.service.snapshot_path
    c = Client(m0.server.address)
    c.register_worker("w0")  # journal at least one real record
    c.close()
    m0.freeze()  # crashed leader: journal and snapshot stay as-is

    snap = _json.load(open(snap_path))
    jpath = os.path.join(os.path.dirname(snap_path), snap["journal_file"])
    w = mj.JournalWriter(jpath, fsync=False, fresh=False)
    w.append(10 ** 6, {"t": "frobnicate"})  # version-skewed append
    w.close()

    m1 = HAMaster(hadir, [data], owner_id="m1", **kw)
    m1.start()
    deadline = time.time() + 15
    while m1.fatal is None and time.time() < deadline:
        time.sleep(0.05)
    assert m1.fatal is not None and "frobnicate" in m1.fatal
    m1._thread.join(timeout=10)
    assert not m1._thread.is_alive()  # crashed loudly, not a zombie
    assert not m1.is_leader.is_set()
