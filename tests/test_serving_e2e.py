"""Serving-plane e2e drills (slow; `make chaos` runs them SANITIZER-ARMED).

Three scenarios over the real threaded scheduler:

* open-loop load — the reader/loadgen arrival clock drives the continuous-
  batching scheduler; every request completes bit-identical to the
  one-shot path and the batch sustains more than sequential decode could;
* ``nan_request`` chaos — a poisoned submission is REJECTED at admission
  (error result) without stalling the sequences already in flight;
* ``serve_slow_client`` chaos — a frozen client callback stalls only the
  delivery thread: ``Request.wait()`` and the decode loop keep running.

These spawn real threads and decode under wall-clock load, so the whole
module is slow-marked (scripts/tier1_failset.py --slow-guard pins that).
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
from paddle_tpu.reader.loadgen import OpenLoopLoadGen
from paddle_tpu.robustness import chaos
from paddle_tpu.serving import Request, ServingEngine, ServingScheduler

pytestmark = pytest.mark.slow

V, E, H = 40, 12, 16
BOS, EOS = 0, 1
MAXLEN = 12


@pytest.fixture()
def engine():
    reset_auto_names()
    cost, _ = seq2seq_cost(V, V, word_dim=E, hidden_dim=H)
    params = paddle.parameters.create(cost, seed=7)
    gen = Seq2SeqGenerator(
        params, V, V, word_dim=E, hidden_dim=H,
        bos_id=BOS, eos_id=EOS, max_length=MAXLEN,
    )
    eng = ServingEngine(gen, max_slots=8, hbm_budget_mb=2,
                        max_new_tokens=MAXLEN)
    yield eng


@pytest.fixture(autouse=True)
def _disarm():
    yield
    chaos.disarm()


def _no_leaked_serve_threads():
    return not [
        t for t in threading.enumerate() if t.name.startswith("paddle-serve")
    ]


def test_serving_under_open_loop_load(engine):
    """Continuous batching under a Poisson arrival clock: all requests
    complete, outputs bit-identical per request, and the sustained rate
    beats what sequential one-shot decode achieves on the same requests."""
    rng = np.random.RandomState(3)
    srcs = [rng.randint(2, V, size=rng.randint(3, 30)).tolist()
            for _ in range(24)]

    # sequential JITTED one-shot baseline (and the bit-identity
    # references); warm both source rungs so it pays dispatch, not XLA
    for s in (srcs[0], max(srcs, key=len)):
        engine.reference_decode(s, MAXLEN)
    t0 = time.perf_counter()
    refs = [engine.reference_decode(s, MAXLEN) for s in srcs]
    oneshot_rps = len(srcs) / (time.perf_counter() - t0)

    # prewarm the serving ladder (the bench's cache-warm discipline) so
    # the measured window compares dispatch against dispatch
    for gsz in (1, 2, 4, 8):
        for src_len in (5, 20):
            engine.admit([Request([2] * src_len) for _ in range(gsz)])
            while engine.n_live:
                engine.step()

    reqs = [Request(s) for s in srcs]
    with ServingScheduler(engine) as sched:
        gen = OpenLoopLoadGen(
            max(2.0 * oneshot_rps, 4.0), len(reqs), lambda i: reqs[i], seed=3
        )
        t1 = time.perf_counter()
        gen.run(sched.submit)
        for r in reqs:
            assert r.wait(120), r
        wall = time.perf_counter() - t1
    assert _no_leaked_serve_threads()
    for r, ref in zip(reqs, refs):
        assert r.error is None, r
        assert r.result() == ref, r.req_id
    served_rps = len(reqs) / wall
    # loose e2e floor (the calibrated 2x-vs-the-pre-serving-path gate
    # lives in bench_serving): under load at ~2x the B=1 JIT baseline's
    # rate, in-flight batching must stay within the same order — on the
    # shared-CI 2-core box both arms are compute-bound, so only gross
    # stalls (a wedged scheduler, a recompile storm) can break this
    assert served_rps > 0.3 * oneshot_rps, (served_rps, oneshot_rps)


def test_chaos_nan_request_rejected_without_stalling(engine):
    """The 3rd submission is poisoned in flight (chaos nan_request): it is
    rejected with an error result; every other request completes
    bit-identical and promptly — the shared batch never stalls."""
    chaos.arm("nan_request@3")
    rng = np.random.RandomState(5)
    srcs = [rng.randint(2, V, size=6).tolist() for _ in range(8)]
    t0 = time.perf_counter()
    with ServingScheduler(engine) as sched:
        reqs = [sched.submit(Request(s)) for s in srcs]
        for r in reqs:
            assert r.wait(60), r
    wall = time.perf_counter() - t0
    assert _no_leaked_serve_threads()
    poisoned = [r for r in reqs if r.error is not None]
    assert len(poisoned) == 1
    assert poisoned[0] is reqs[2]  # the 3rd submission
    assert "non-integral" in poisoned[0].error
    for r in reqs:
        if r.error is None:
            assert r.result() == engine.reference_decode(r.src_ids, MAXLEN)
    # "without stalling": the whole batch (7 live + 1 reject) finished in
    # interactive time, nowhere near any timeout/backoff path
    assert wall < 30.0, wall


def test_chaos_slow_client_stalls_only_delivery(engine, monkeypatch):
    """A client callback frozen for 2s (chaos serve_slow_client) must not
    block the decode loop or other clients' wait(): only callback
    delivery serializes behind it."""
    monkeypatch.setenv("PADDLE_TPU_CHAOS_HANG_SECS", "2")
    chaos.arm("serve_slow_client@1")
    rng = np.random.RandomState(6)
    delivered = []
    srcs = [rng.randint(2, V, size=5).tolist() for _ in range(6)]
    with ServingScheduler(engine) as sched:
        reqs = [
            sched.submit(Request(s, callback=lambda r: delivered.append(r)))
            for s in srcs
        ]
        t0 = time.perf_counter()
        for r in reqs:
            assert r.wait(60), r
        wait_wall = time.perf_counter() - t0
        # every wait() returned while the FIRST delivery was still hung:
        # decoding and finalization never waited on the slow client
        assert wait_wall < 2.0, wait_wall
        # the hung callback drains eventually (close() joins delivery)
    assert _no_leaked_serve_threads()
    assert len(delivered) == 6
    for r in reqs:
        assert r.error is None
        assert r.result() == engine.reference_decode(r.src_ids, MAXLEN)
