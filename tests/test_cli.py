"""The ``paddle train`` CLI face (paddle_tpu/cli.py) — subprocess tests.

Reference parity: paddle/trainer/TrainerMain.cpp:32-65 (the paddle_trainer
binary and its --job dispatch), paddle/scripts/submit_local.sh.in (the
``paddle`` wrapper's subcommands), TrainerBenchmark.cpp:71 (--job=time).
The fast tests drive the reference's own self-contained OnePass fixture
(sample_trainer_config_opt_a.conf + the checked-in mnist_bin_part); the
slow tests run the reference's real demo dirs (v1_api_demo/mnist,
quick_start) from a shell, unmodified, with synthesized data files.
"""

import json
import os
import shutil
import struct
import subprocess
import sys

import numpy as np
import pytest

REF = "/root/reference"
REF_TESTS = f"{REF}/paddle/trainer/tests"
OPT_A = f"{REF_TESTS}/sample_trainer_config_opt_a.conf"


def run_cli(args, cwd=None, timeout=900):
    """Run `python -m paddle_tpu <args>` like a user would from a shell."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # the package runs from the source tree in CI; a user would have it
    # pip-installed and need no PYTHONPATH
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu", *args],
        capture_output=True, text=True, cwd=cwd, env=env, timeout=timeout,
    )


def test_help_lists_commands():
    r = run_cli(["--help"])
    assert r.returncode == 0
    for cmd in ("train", "version", "dump_config", "merge_model"):
        assert cmd in r.stdout


def test_unknown_command_fails():
    r = run_cli(["frobnicate"])
    assert r.returncode == 1
    assert "unknown command" in r.stderr


@pytest.mark.slow
def test_train_job_writes_pass_checkpoints(tmp_path):
    """`paddle-tpu train --config=... --save_dir=... --num_passes=...` on the
    reference's own OnePass config + binary data: two passes, pass-%05d dirs
    with params.tar + v1 per-parameter binaries (TrainerMain.cpp + the
    Trainer.cpp checkpoint cadence)."""
    save = tmp_path / "model"
    r = run_cli([
        "train", f"--config={OPT_A}", f"--save_dir={save}",
        "--num_passes=2", "--batch_size=200", "--dot_period=2",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Pass 0" in r.stdout and "Pass 1" in r.stdout
    for p in ("pass-00000", "pass-00001"):
        d = save / p
        assert (d / "params.tar").exists()
        assert (d / "__fc_layer_0__.w0").exists()  # v1 binary plane


@pytest.mark.slow
def test_test_job_evaluates_saved_model(tmp_path):
    """--job=test loads --init_model_path and reports cost + evaluator
    metrics (Tester.cpp)."""
    save = tmp_path / "model"
    r = run_cli([
        "train", f"--config={OPT_A}", f"--save_dir={save}",
        "--num_passes=1", "--batch_size=400",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    r = run_cli([
        "train", f"--config={OPT_A}", "--job=test",
        f"--init_model_path={save / 'pass-00000'}", "--batch_size=400",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Test cost" in r.stdout
    assert "classification_error" in r.stdout


@pytest.mark.slow
def test_time_job_prints_stat_table():
    """--job=time: burn-in + timed loop + the StatSet table
    (TrainerBenchmark.cpp:30-90)."""
    r = run_cli([
        "train", f"--config={OPT_A}", "--job=time",
        "--test_period=5", "--batch_size=100",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Burning time" in r.stdout
    assert "FwdBwd" in r.stdout
    assert "ms/batch" in r.stdout


@pytest.mark.slow
def test_checkgrad_job_passes():
    """--job=checkgrad: float64 directional finite differences vs the VJP
    (Trainer::checkGradient; fd accuracy from x64 like the reference's
    WITH_DOUBLE build)."""
    r = run_cli([
        "train", f"--config={OPT_A}", "--job=checkgrad", "--batch_size=8",
    ])
    assert r.returncode == 0, r.stdout + r.stderr[-2000:]
    assert "checkgrad PASSED" in r.stdout


def test_dump_config_prints_topology():
    r = run_cli(["dump_config", f"{REF}/v1_api_demo/mnist/light_mnist.py"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "conv" in r.stdout and "pixel" in r.stdout


# ---------------------------------------------------------------------------
# the reference demo dirs, run from a shell the way their train.sh does
# ---------------------------------------------------------------------------

def _write_idx_mnist(prefix, n):
    """Raw MNIST idx files the demo's mnist_util.read_from_mnist expects:
    <prefix>-images-idx3-ubyte (16-byte header) and -labels-idx1-ubyte
    (8-byte header)."""
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 10, size=n).astype(np.uint8)
    # class-dependent pixels so one pass actually learns something
    images = (labels[:, None] * 20 + rng.randint(0, 40, size=(n, 784))).astype(
        np.uint8
    )
    with open(f"{prefix}-images-idx3-ubyte", "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with open(f"{prefix}-labels-idx1-ubyte", "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())


@pytest.mark.slow
def test_v1_api_demo_mnist_runs_from_shell(tmp_path):
    """The README path: copy the reference's v1_api_demo/mnist dir verbatim,
    synthesize the raw MNIST files its provider reads, and run
    `paddle-tpu train --config=light_mnist.py` from the demo dir exactly like
    its train.sh runs `paddle train` — checkpoints land in pass-%05d/.

    NB the test name must not contain 'train': pytest puts it in tmp_path,
    and the demo's mnist_util.read_from_mnist keys its sample count on
    `"train" in filename` (60000 vs 10000)."""
    demo = tmp_path / "mnist_demo"
    shutil.copytree(f"{REF}/v1_api_demo/mnist", demo)
    raw = demo / "data" / "raw_data"
    raw.mkdir(parents=True)
    _write_idx_mnist(str(raw / "t10k"), 10000)  # 't10k' => n=10000 branch
    (demo / "data" / "train.list").write_text("data/raw_data/t10k\n")
    (demo / "data" / "test.list").write_text("data/raw_data/t10k\n")
    save = demo / "mnist_model"
    r = run_cli(
        [
            "train", "--config=light_mnist.py", f"--save_dir={save}",
            "--num_passes=1", "--batch_size=1000", "--use_gpu=0",
            "--trainer_count=1", "--dot_period=10", "--log_period=100",
        ],
        cwd=str(demo),
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-3000:])
    assert "Pass 0" in r.stdout
    assert (save / "pass-00000" / "params.tar").exists()


@pytest.mark.slow
def test_quick_start_trains_from_shell(tmp_path):
    """quick_start parity: the reference's trainer_config.lr.py + its own
    dataprovider_bow provider, run from the shell with synthesized
    '<label>\\t<text>' data (demo/quick_start/train.sh shape)."""
    demo = tmp_path / "qs_demo"
    shutil.copytree(f"{REF}/v1_api_demo/quick_start", demo, dirs_exist_ok=True)
    data = demo / "data"
    data.mkdir(exist_ok=True)
    rng = np.random.RandomState(0)
    words = [f"w{i}" for i in range(100)]
    (data / "dict.txt").write_text(
        "\n".join(f"{w}\t{i}" for i, w in enumerate(words))
    )
    lines = []
    for _ in range(400):
        label = rng.randint(2)
        base = 10 if label else 60
        toks = [words[base + rng.randint(20)] for _ in range(rng.randint(3, 8))]
        lines.append(f"{label}\t{' '.join(toks)}")
    (data / "train.txt").write_text("\n".join(lines))
    (data / "train.list").write_text("data/train.txt\n")
    (data / "test.list").write_text("data/train.txt\n")
    save = demo / "output"
    r = run_cli(
        [
            "train", "--config=trainer_config.lr.py",
            "--config_args=dict_file=data/dict.txt",
            f"--save_dir={save}", "--num_passes=1", "--batch_size=100",
        ],
        cwd=str(demo),
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-3000:])
    assert (save / "pass-00000" / "params.tar").exists()


@pytest.mark.slow
def test_merge_model_roundtrip(tmp_path):
    """merge_model bundles a pass dir + config into one file the inference
    face can load (submit_local.sh.in merge_model / paddle_merge_model)."""
    save = tmp_path / "model"
    r = run_cli([
        "train", f"--config={OPT_A}", f"--save_dir={save}",
        "--num_passes=1", "--batch_size=400",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    bundle = tmp_path / "merged.paddle"
    r = run_cli([
        "merge_model", f"--model_dir={save / 'pass-00000'}",
        f"--config_file={OPT_A}", f"--model_file={bundle}",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    assert bundle.exists() and bundle.stat().st_size > 1000
    # the bundle round-trips through --init_model_path (detected as a
    # merged bundle, not a bare params.tar)
    r = run_cli([
        "train", f"--config={OPT_A}", "--job=test",
        f"--init_model_path={bundle}", "--batch_size=400",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Test cost" in r.stdout


def test_reference_train_sh_flag_lines_accepted():
    """A reference train.sh command line (mnist/train.sh passes
    --test_all_data_in_one_period and friends) must run — unknown gflags
    are warned about, never fatal."""
    r = run_cli([
        "train", f"--config={OPT_A}", "--num_passes=0", "--batch_size=400",
        "--test_all_data_in_one_period=1", "--num_gradient_servers=1",
        "--nics=eth0", "--ports_num=1",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ignoring reference trainer flags" in r.stderr

    # typos of SUPPORTED flags and stray tokens stay fatal — a multi-hour
    # run must not silently drop --save_dir because of a typo
    r = run_cli([
        "train", f"--config={OPT_A}", "--num_passes=0", "--save_dri=/tmp/x",
    ])
    assert r.returncode == 2
    assert "unrecognized arguments" in r.stderr
    r = run_cli(["train", f"--config={OPT_A}", "num_passes=5"])
    assert r.returncode == 2

    # gflags separate-value and --no<flag> boolean-negation spellings of
    # ignored reference flags must also pass, including negative values
    r = run_cli([
        "train", f"--config={OPT_A}", "--num_passes=0",
        "--nics", "eth0", "--gpu_id", "-1", "--nolocal", "--notest_wait",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ignoring reference trainer flags" in r.stderr

    # a stray key=value token after a BOOLEAN ignored flag is NOT its
    # value — it stays a hard error (would otherwise silently drop a
    # mistyped option)
    r = run_cli([
        "train", f"--config={OPT_A}", "--nolocal", "batch_size=32",
    ])
    assert r.returncode == 2
    assert "unrecognized arguments" in r.stderr


@pytest.mark.slow
def test_start_pass_resumes_from_save_dir(tmp_path):
    """--start_pass=N without --init_model_path resumes from
    save_dir/pass-%05d (reference ParamUtil loadParametersWithPath)."""
    save = tmp_path / "model"
    r = run_cli([
        "train", f"--config={OPT_A}", f"--save_dir={save}",
        "--num_passes=1", "--batch_size=400",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    r = run_cli([
        "train", f"--config={OPT_A}", f"--save_dir={save}",
        "--num_passes=1", "--start_pass=1", "--batch_size=400",
    ])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Pass 1" in r.stdout
    assert (save / "pass-00001" / "params.tar").exists()


def test_make_diagram_writes_dot(tmp_path):
    """make_diagram renders a config to Graphviz dot
    (submit_local.sh.in make_diagram -> python -m paddle.utils.make_model_diagram)."""
    out = tmp_path / "net.dot"
    r = run_cli(["make_diagram", OPT_A, str(out)])
    assert r.returncode == 0, r.stderr[-2000:]
    text = out.read_text()
    assert text.startswith("digraph")
    assert "__fc_layer_0__" in text
