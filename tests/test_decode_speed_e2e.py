"""Decode raw speed e2e drills (slow; `make chaos` runs them
SANITIZER-ARMED) — the PR-17 tentpole under real threaded load.

Three scenarios over the real scheduler:

* shared-prefix open-loop load — PrefixMixer traffic (the workload the
  COW prefix cache exists for) through the arrival clock: every request
  bit-identical to the one-shot path, the cache takes real hits, and
  ``pages_in_use`` drains to 0 with the warm entries still resident;
* speculative decoding under load — the verify-K path serves an open-loop
  burst bit-identically to plain greedy, accept-rate metric live;
* cancel mid-speculation — a timed-out ``generate()`` cancels its
  in-flight speculative request: ``pages_in_use`` returns to 0 (shared
  blocks refcount down, never double-free) and the survivors finish.

Real threads + wall-clock load: the whole module is slow-marked
(scripts/tier1_failset.py --slow-guard pins that).
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer
from paddle_tpu.robustness import chaos
from paddle_tpu.serving import Request, ServingEngine, ServingScheduler

pytestmark = pytest.mark.slow

V, E, H = 40, 12, 16
BOS, EOS = 0, 1
MAXLEN = 12


@pytest.fixture()
def small_gen():
    reset_auto_names()
    cost, _ = seq2seq_cost(V, V, word_dim=E, hidden_dim=H)
    params = paddle.parameters.create(cost, seed=7)
    return Seq2SeqGenerator(
        params, V, V, word_dim=E, hidden_dim=H,
        bos_id=BOS, eos_id=EOS, max_length=MAXLEN,
    )


@pytest.fixture(autouse=True)
def _disarm():
    yield
    chaos.disarm()


def _no_leaked_serve_threads():
    return not [
        t for t in threading.enumerate() if t.name.startswith("paddle-serve")
    ]


def test_prefix_sharing_under_open_loop_load(small_gen):
    """PrefixMixer traffic (pooled prefixes, exact duplicates, fresh
    prompts) over the threaded scheduler with the COW cache armed: all
    bit-identical, real cache hits, pages drained."""
    eng = ServingEngine(small_gen, max_slots=8, hbm_budget_mb=2,
                        max_new_tokens=MAXLEN, prefix_cache=True)
    mixer = PrefixMixer(V, pool_size=3, prefix_frac=0.6, dup_frac=0.5,
                        seed=11)
    srcs = [mixer.source(i) for i in range(24)]
    refs = [eng.reference_decode(s, MAXLEN) for s in srcs]
    reqs = [Request(s) for s in srcs]
    with ServingScheduler(eng) as sched:
        gen = OpenLoopLoadGen(8.0, len(reqs), lambda i: reqs[i], seed=11)
        gen.run(sched.submit)
        for r in reqs:
            assert r.wait(120), r
    assert _no_leaked_serve_threads()
    for r, ref in zip(reqs, refs):
        assert r.error is None, r
        assert r.result() == ref, r.req_id
    # duplicate prompts in the mix MUST have mapped warmed blocks
    assert eng.prefix_hits > 0
    assert eng.prefix_misses + eng.prefix_hits == len(srcs)
    # the SLO gauge drains even though warm entries stay resident
    assert eng.pages.n_used == 0 and eng.pages.n_retained > 0


def test_spec_decode_under_open_loop_load(small_gen):
    eng = ServingEngine(small_gen, max_slots=8, hbm_budget_mb=2,
                        max_new_tokens=MAXLEN, spec_decode=True)
    rng = np.random.RandomState(13)
    srcs = [rng.randint(2, V, size=rng.randint(3, 30)).tolist()
            for _ in range(16)]
    refs = [eng.reference_decode(s, MAXLEN) for s in srcs]
    reqs = [Request(s) for s in srcs]
    with ServingScheduler(eng) as sched:
        gen = OpenLoopLoadGen(8.0, len(reqs), lambda i: reqs[i], seed=13)
        gen.run(sched.submit)
        for r in reqs:
            assert r.wait(120), r
    assert _no_leaked_serve_threads()
    for r, ref in zip(reqs, refs):
        assert r.error is None, r
        assert r.result() == ref, r.req_id
    assert eng.spec_proposed > 0
    assert 0.0 <= eng.spec_accept_rate() <= 1.0


def test_cancel_mid_speculation_drains_pages(small_gen):
    """The orphaned-slot drill on the speculative + shared path: a
    timed-out ``generate()`` cancels its request while verify dispatches
    are in flight over SHARED prefix blocks — refcounts step down cleanly
    (no double free, no leak) and pages_in_use returns to 0."""
    eng = ServingEngine(small_gen, max_slots=8, hbm_budget_mb=2,
                        max_new_tokens=MAXLEN, prefix_cache=True,
                        spec_decode=True)
    src = [2 + i % (V - 2) for i in range(9)]
    sched = ServingScheduler(eng)
    try:
        # warm the prefix entry so the canceled request decodes over a
        # SHARED mapping (refcount 2: entry + slot)
        assert sched.generate(src, timeout=60.0) == eng.reference_decode(
            src, MAXLEN
        )
        with pytest.raises(TimeoutError):
            sched.generate(src, timeout=0.0)
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline:
            if eng.pages.n_used == 0 and eng.n_live == 0:
                break
            time.sleep(0.01)
        assert eng.pages.n_used == 0, eng.pages.summary()
        assert eng.n_live == 0 and eng.n_prefilling == 0
        # the warm entry survived the cancel — a follow-up request still
        # hits and stays bit-identical
        hits = eng.prefix_hits
        assert sched.generate(src, timeout=60.0) == eng.reference_decode(
            src, MAXLEN
        )
        assert eng.prefix_hits > hits
    finally:
        sched.close()
    assert _no_leaked_serve_threads()
