"""The master's elastic cluster plane: worker registry heartbeat leases,
dead-worker shard-lease requeue, pass fences with elastic membership, the
per-task result plane, and the lease-expiry/zombie-epoch discipline
(reference go/master/service.go's failure_max model completed fleet-wide,
arXiv:1605.08695 §4.4).  Everything runs on an injected clock — no real
sleeps on the lease paths."""

import os

import numpy as np
import pytest

from paddle_tpu import master as master_mod
from paddle_tpu.io import recordio


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _write(path, n, chunk, tag=""):
    recordio.write_records(
        path, (f"{tag}{i}".encode() for i in range(n)),
        max_chunk_records=chunk,
    )


def _make_service(tmp_path, clock, **kw):
    _write(str(tmp_path / "d.rio"), 80, chunk=10)
    kw.setdefault("snapshot_min_interval_s", 0.0)
    kw.setdefault("chunks_per_task", 2)
    kw.setdefault("auto_rotate", False)
    svc = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), clock=clock, **kw
    )
    svc.set_dataset([str(tmp_path / "d.rio")])
    return svc  # 4 tasks


# ---------------------------------------------------------------------------
# satellite: _requeue_expired — expired mid-pass lease re-serves EXACTLY
# once, and the zombie owner's epoch-guarded ack is rejected
# ---------------------------------------------------------------------------

def test_expired_lease_hands_task_to_second_client_exactly_once(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, timeout_s=5.0)
    got_a = svc.get_task("A")
    tid, epoch = got_a["task"]["task_id"], got_a["epoch"]
    clk.advance(6.0)  # A's task lease expires mid-pass
    servings = {}
    while True:
        got = svc.get_task("B")
        if got is None:
            break
        assert got != "wait"
        t = got["task"]["task_id"]
        servings[t] = servings.get(t, 0) + 1
        assert svc.task_finished(t, got["epoch"])
        if t == tid:
            assert got["epoch"] == epoch + 1  # walked the failure path
    assert servings[tid] == 1  # re-served exactly once
    assert svc.fail_events == 1


def test_zombie_task_finished_rejected_by_epoch(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, timeout_s=5.0)
    got_a = svc.get_task("A")
    tid, epoch = got_a["task"]["task_id"], got_a["epoch"]
    clk.advance(6.0)
    got_b = None
    while got_b is None or got_b["task"]["task_id"] != tid:
        got_b = svc.get_task("B")
        assert got_b not in (None, "wait")
        if got_b["task"]["task_id"] != tid:
            assert svc.task_finished(
                got_b["task"]["task_id"], got_b["epoch"]
            )
            got_b = None
    # the original (zombie) owner's ack — with its result — must bounce
    assert svc.task_finished(tid, epoch, {"g": "zombie"}) is False
    # the new holder's ack (and result) wins
    assert svc.task_finished(tid, got_b["epoch"], {"g": "survivor"})
    assert svc.pass_results(0)["results"][tid] == {"g": "survivor"}


# ---------------------------------------------------------------------------
# worker registry: heartbeat leases, prune -> immediate lease requeue
# ---------------------------------------------------------------------------

def test_dead_worker_leases_requeue_on_registry_expiry(tmp_path):
    clk = _FakeClock()
    # task leases far longer than the registry lease: the requeue must ride
    # the REGISTRY expiry, not the per-task timeout
    svc = _make_service(tmp_path, clk, timeout_s=600.0, worker_timeout_s=5.0)
    svc.register_worker("A")
    svc.register_worker("B")
    got = svc.get_task("A")
    tid = got["task"]["task_id"]
    clk.advance(3.0)
    svc.heartbeat("B")
    clk.advance(3.0)  # A silent for 6s > 5s; B heartbeated at 3s
    assert svc.live_workers() == ["B"]
    assert svc.fail_events == 1  # A's lease walked the failure path
    served = set()
    while True:
        g = svc.get_task("B")
        if g is None:
            break
        served.add(g["task"]["task_id"])
        svc.task_finished(g["task"]["task_id"], g["epoch"])
    assert tid in served  # the dead worker's shard reached the survivor


def test_heartbeat_false_after_expiry_then_reregister(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, worker_timeout_s=5.0)
    svc.register_worker("A")
    clk.advance(6.0)
    assert svc.heartbeat("A") is False  # expired: must re-register
    info = svc.register_worker("A")
    assert info["workers"] == ["A"]
    assert svc.heartbeat("A") is True


def test_deregister_returns_leases_without_failure_event(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, timeout_s=600.0)
    svc.register_worker("A")
    got = svc.get_task("A")
    svc.deregister_worker("A")
    assert svc.fail_events == 0  # graceful leave is not a crash
    got2 = svc.get_task("B")
    # the returned task re-serves at the SAME epoch
    assert got2["task"]["task_id"] in {got["task"]["task_id"], 1, 2, 3}


# ---------------------------------------------------------------------------
# pass fence: elastic membership
# ---------------------------------------------------------------------------

def _drain(svc, worker):
    while True:
        g = svc.get_task(worker)
        if g is None:
            return
        if g == "wait":
            continue
        svc.task_finished(g["task"]["task_id"], g["epoch"], {"rows": 1})


def test_fence_releases_when_all_live_arrived(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, worker_timeout_s=50.0)
    svc.register_worker("A")
    svc.register_worker("B")
    _drain(svc, "A")
    st = svc.fence_arrive("pass-0", "A")
    assert not st["released"]
    st = svc.fence_arrive("pass-0", "B")
    assert st["released"]
    assert st["workers"] == ["A", "B"]
    assert st["n_done"] == 4


def test_fence_does_not_wedge_on_dead_worker(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, worker_timeout_s=5.0)
    svc.register_worker("A")
    svc.register_worker("B")
    _drain(svc, "A")
    assert not svc.fence_arrive("pass-0", "A")["released"]
    clk.advance(6.0)  # B dies silently; prune runs on the next poll
    st = svc.fence_status("pass-0")
    assert st["released"] is True
    assert st["workers"] == ["A"]  # membership froze without the dead B


def test_late_arrival_sees_frozen_membership(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, worker_timeout_s=5.0)
    svc.register_worker("A")
    _drain(svc, "A")
    assert svc.fence_arrive("pass-0", "A")["released"]
    svc.register_worker("C")  # joins after release
    st = svc.fence_arrive("pass-0", "C")
    assert st["released"] and "C" not in st["workers"]


def test_fence_negotiates_writer_roster(tmp_path):
    """The shard-writer set is the checkpoint-enabled subset of the
    membership: one checkpoint-less worker must not doom every manifest
    commit to a missing shard."""
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, worker_timeout_s=50.0)
    svc.register_worker("A")
    svc.register_worker("B")
    svc.register_worker("C")
    _drain(svc, "A")
    svc.fence_arrive("pass-0", "A", {"ckpt": True})
    svc.fence_arrive("pass-0", "B", {"ckpt": False})
    st = svc.fence_arrive("pass-0", "C", {"ckpt": True})
    assert st["released"]
    assert st["workers"] == ["A", "B", "C"]
    assert st["writers"] == ["A", "C"]


def test_mixed_fleet_checkpoint_commits_without_ckptless_worker(tmp_path):
    """In-process mixed fleet: a worker WITHOUT --checkpoint-dir rides
    along and the checkpointing workers' manifest still commits (writer
    roster excludes it)."""
    import threading

    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.trainer.elastic import ElasticWorker, NumpyLinearModel

    rng = np.random.RandomState(0)
    w_true = rng.randn(4).astype(np.float32)
    recordio.write_records(
        str(tmp_path / "d.rio"),
        (np.concatenate([x := rng.randn(4).astype(np.float32),
                         [np.float32(x @ w_true)]])
         .astype(np.float32).tobytes() for _ in range(48)),
        max_chunk_records=4,
    )
    svc = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), chunks_per_task=2,
        auto_rotate=False, snapshot_min_interval_s=0.0,
        worker_timeout_s=30.0,
    )
    svc.set_dataset([str(tmp_path / "d.rio")])
    ck = str(tmp_path / "ck")
    workers = [
        ElasticWorker(master_mod.Client(svc), "w0", NumpyLinearModel(4),
                      manager=CheckpointManager(ck)),
        ElasticWorker(master_mod.Client(svc), "w1", NumpyLinearModel(4),
                      manager=None),  # no checkpoint dir
    ]
    results = {}
    threads = [
        threading.Thread(target=lambda w=w: results.update(
            {w.worker_id: w.run(2)}
        ))
        for w in workers
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert set(results) == {"w0", "w1"}
    restored = CheckpointManager(ck).restore_latest(
        NumpyLinearModel(4).state()
    )
    assert restored is not None and restored[0] == 2  # committed
    assert results["w0"]["pass_costs"] == results["w1"]["pass_costs"]


def test_fence_arrive_renews_registry_lease(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, worker_timeout_s=5.0)
    svc.register_worker("A")
    svc.register_worker("B")
    for _ in range(4):  # A parked at the barrier, polling by re-arrival
        clk.advance(3.0)
        svc.fence_arrive("pass-0", "A")
    assert "A" in svc.live_workers()  # never pruned mid-wait


# ---------------------------------------------------------------------------
# pass accounting: guarded rotation, retained results, requeue_unresulted
# ---------------------------------------------------------------------------

def test_start_new_pass_target_guard_is_idempotent(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk)
    _drain(svc, "A")
    assert svc.start_new_pass(1) == 1
    assert svc.start_new_pass(1) == 1  # straggler cannot double-rotate
    _drain(svc, "A")
    assert svc.start_new_pass(1) == 1  # target already reached: held
    assert svc.start_new_pass(2) == 2


def test_pass_results_retained_with_done_count_across_rotation(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk)
    _drain(svc, "A")
    svc.start_new_pass(1)
    pr = svc.pass_results(0)
    assert pr["n_done"] == 4 and len(pr["results"]) == 4
    # current (un-rotated) pass has no frozen count yet
    assert svc.pass_results(1)["n_done"] is None


def test_requeue_unresulted_recomputes_orphaned_done_tasks(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk)
    g1 = svc.get_task("A")
    svc.task_finished(g1["task"]["task_id"], g1["epoch"], {"g": 1})
    g2 = svc.get_task("A")
    svc.task_finished(g2["task"]["task_id"], g2["epoch"])  # result lost
    assert svc.requeue_unresulted() == 1
    assert len(svc.done) == 1 and len(svc.todo) == 3


# ---------------------------------------------------------------------------
# the full surface over RPC (Server/Client process boundary)
# ---------------------------------------------------------------------------

def test_elastic_surface_over_rpc(tmp_path):
    svc = _make_service(tmp_path, _FakeClock(), worker_timeout_s=60.0)
    server = master_mod.Server(svc)
    try:
        c = master_mod.Client(tuple(server.address))
        info = c.register_worker("w0")
        assert info["auto_rotate"] is False
        assert c.heartbeat("w0") is True
        done = 0
        while True:
            got = c.get_task("w0")
            if got is None:
                break
            payload = {
                "grads": {"w": np.ones(3, np.float32)},
                "cost": 1.0,
                "rows": 10,
            }
            assert c.task_finished(
                got["task"]["task_id"], got["epoch"], payload
            )
            done += 1
        assert done == 4
        st = c.fence_arrive("pass-0", "w0")
        assert st["released"] and st["n_done"] == 4
        results = c.pass_results(0)["results"]
        assert len(results) == 4
        np.testing.assert_array_equal(
            results[0]["grads"]["w"], np.ones(3, np.float32)
        )
        assert c.stats()["fail_events"] == 0
        assert c.start_new_pass(1) == 1
        c.deregister_worker("w0")
        c.close()
    finally:
        server.close()


def test_elastic_worker_inprocess_trains_and_commits(tmp_path):
    """Fast-tier end-to-end of the worker driver against an in-process
    Service (no subprocesses, numpy model): passes reduce + apply, cost
    decreases, and the sharded manifest commits with the pass position."""
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.trainer.elastic import ElasticWorker, NumpyLinearModel

    rng = np.random.RandomState(0)
    w_true = rng.randn(4).astype(np.float32)
    recs = []
    for _ in range(48):
        x = rng.randn(4).astype(np.float32)
        recs.append(
            np.concatenate([x, [np.float32(x @ w_true)]])
            .astype(np.float32).tobytes()
        )
    recordio.write_records(
        str(tmp_path / "d.rio"), iter(recs), max_chunk_records=4
    )
    svc = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), chunks_per_task=2,
        auto_rotate=False, snapshot_min_interval_s=0.0,
    )
    svc.set_dataset([str(tmp_path / "d.rio")])
    mgr = CheckpointManager(str(tmp_path / "ck"))
    worker = ElasticWorker(
        master_mod.Client(svc), "w0", NumpyLinearModel(4, lr=0.2),
        manager=mgr,
    )
    summary = worker.run(3)
    assert summary["pass_costs"][-1] < summary["pass_costs"][0]
    assert summary["tasks_done"] == 6 * 3
    restored = CheckpointManager(str(tmp_path / "ck")).restore_latest(
        NumpyLinearModel(4).state()
    )
    assert restored is not None
    step, _, extra = restored
    assert step == 3 and extra["pass_id"] == 2


def test_elastic_worker_requires_fenced_master(tmp_path):
    from paddle_tpu.trainer.elastic import ElasticWorker, NumpyLinearModel

    _write(str(tmp_path / "d.rio"), 8, chunk=4)
    svc = master_mod.Service(auto_rotate=True)  # free-running: refused
    svc.set_dataset([str(tmp_path / "d.rio")])
    worker = ElasticWorker(
        master_mod.Client(svc), "w0", NumpyLinearModel(4)
    )
    with pytest.raises(RuntimeError, match="auto_rotate"):
        worker.run(1)


def test_reduce_results_is_order_canonical():
    from paddle_tpu.trainer.elastic import reduce_results

    a = {"grads": {"w": np.full(3, 1.0, np.float32)}, "cost": 2.0, "rows": 2}
    b = {"grads": {"w": np.full(3, 4.0, np.float32)}, "cost": 4.0, "rows": 6}
    m1, c1, r1 = reduce_results({0: a, 1: b})
    m2, c2, r2 = reduce_results({1: b, 0: a})  # insertion order must not matter
    np.testing.assert_array_equal(m1["w"], m2["w"])
    assert c1 == c2 and r1 == r2 == 8
    np.testing.assert_allclose(m1["w"], (1.0 * 2 + 4.0 * 6) / 8)


def test_snapshot_roundtrip_with_owner_field(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk)
    svc.register_worker("A")
    svc.get_task("A")  # one pending lease with an owner
    # recover from the snapshot: pending requeues immediately
    svc2 = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"),
        chunks_per_task=2, auto_rotate=False, snapshot_min_interval_s=0.0,
    )
    assert len(svc2.todo) == 4 and not svc2.pending


# ---------------------------------------------------------------------------
# ISSUE 7 satellites: idempotent task_finished re-acks, at-least-once lease
# re-serve, and per-RPC deadlines against half-open/frozen masters
# ---------------------------------------------------------------------------

def test_duplicate_task_finished_reack_is_deduped(tmp_path):
    """A worker retrying across a master bounce re-sends the same (task,
    epoch[, result]): accepted-and-deduped, never double-counted — the
    regression the zombie-epoch tests above don't cover (same epoch, same
    owner, duplicate delivery)."""
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk)
    got = svc.get_task("A")
    tid, epoch = got["task"]["task_id"], got["epoch"]
    payload = {"g": np.arange(3, dtype=np.float32), "rows": 7}
    assert svc.task_finished(tid, epoch, payload)
    n_done = len(svc.done)
    # the retry (reply lost mid-bounce) and even a third delivery
    assert svc.task_finished(tid, epoch, payload)
    assert svc.task_finished(tid, epoch, payload)
    assert len(svc.done) == n_done  # not double-counted
    res = svc.pass_results(0)["results"]
    assert list(res) == [tid]
    np.testing.assert_array_equal(res[tid]["g"], payload["g"])
    # epoch-less legacy duplicates still report failure (no guard to dedupe
    # against — the legacy client never retries across bounces)
    assert svc.task_finished(tid) is False


def test_duplicate_reack_without_result_keeps_first_payload(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk)
    got = svc.get_task("A")
    tid, epoch = got["task"]["task_id"], got["epoch"]
    assert svc.task_finished(tid, epoch, {"g": "first"})
    assert svc.task_finished(tid, epoch, None)  # bare retry
    assert svc.pass_results(0)["results"][tid] == {"g": "first"}


def test_get_task_reserves_held_lease_to_owner(tmp_path):
    """At-least-once lease delivery: the old leader journaled the grant and
    died before replying, so the worker retries get_task against a master
    whose replica already holds its warm lease — it must get the SAME task
    back (fresh deadline), not a second one."""
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, timeout_s=10.0)
    got = svc.get_task("A")
    tid, epoch = got["task"]["task_id"], got["epoch"]
    clk.advance(6.0)  # more than half the lease burned waiting
    again = svc.get_task("A")
    assert again["task"]["task_id"] == tid and again["epoch"] == epoch
    assert len(svc.pending) == 1
    # and the deadline was refreshed: the original grant would now be 4s
    # from expiry, the re-serve gives the full window again
    assert svc.pending[tid][1] == clk() + 10.0
    # a DIFFERENT worker still gets a different task
    other = svc.get_task("B")
    assert other["task"]["task_id"] != tid


def test_client_call_deadline_fires_on_frozen_master(tmp_path):
    """A frozen leader (GC pause, dead NFS) that accepted the connection
    but never replies: the per-call deadline surfaces MasterTimeoutError
    instead of blocking the worker forever."""
    import time as _time

    class _Frozen(master_mod.Service):
        def stats(self):
            _time.sleep(5.0)
            return super().stats()

    srv = master_mod.Server(_Frozen())
    try:
        c = master_mod.Client(
            srv.address, call_timeout_s=0.3, reconnect_tries=1
        )
        t0 = _time.time()
        with pytest.raises(master_mod.MasterTimeoutError):
            c.stats()
        assert _time.time() - t0 < 3.0  # the deadline, not the freeze
        # timeout is a ConnectionError subclass: HA wrappers re-discover
        assert issubclass(
            master_mod.MasterTimeoutError, master_mod.MasterTransportError
        )
        assert issubclass(master_mod.MasterTimeoutError, ConnectionError)
    finally:
        srv.close()


def test_dial_deadline_against_half_open_listener():
    """A listener that accepts into its backlog and never completes the
    auth handshake — the exact socket state a bouncing master leaves
    behind.  The stock multiprocessing dial blocks FOREVER here; ours
    raises MasterTimeoutError at the deadline."""
    import socket
    import time as _time

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    s.listen(1)  # backlog accepts the TCP connect; nobody ever serves it
    try:
        t0 = _time.time()
        with pytest.raises(master_mod.MasterTimeoutError):
            master_mod.Client(
                s.getsockname(), call_timeout_s=0.3, reconnect_tries=1
            )
        assert _time.time() - t0 < 3.0
    finally:
        s.close()


def test_accept_loop_survives_client_rst_mid_handshake():
    """The server side of the bounce: a dialer that hangs up HARD (RST)
    during the auth handshake — exactly what an abandoned dial-deadline
    socket produces — surfaces in the accept loop as ConnectionResetError,
    an OSError.  It must cost that one connection, never the loop: a dead
    accept loop keeps the port bound (looking alive) while serving nobody,
    the one half-open state no client-side deadline can heal."""
    import socket
    import struct
    import time as _time

    srv = master_mod.Server(master_mod.Service())
    try:
        for _ in range(3):
            s = socket.socket()
            s.connect(srv.address)
            # SO_LINGER(on, 0): close() sends RST, not FIN
            s.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
            )
            s.close()
            _time.sleep(0.05)  # let the accept loop chew the dead socket
        c = master_mod.Client(
            srv.address, call_timeout_s=5.0, reconnect_tries=1
        )
        assert "n_todo" in c.stats()  # the server still serves
        c.close()
    finally:
        srv.close()


def test_elastic_worker_rpc_retry_rides_through_bounce(tmp_path):
    """ElasticWorker's bounded reconnect: a client that throws transport
    errors for a while (the master bounce window) then heals — the worker
    retries inside rpc_retry_window_s instead of dying; past the window it
    surfaces the error for its supervisor."""
    from paddle_tpu.trainer.elastic import ElasticWorker, NumpyLinearModel

    clk = _FakeClock()

    class _Bouncy:
        def __init__(self, fail_times):
            self.fail_times = fail_times
            self.calls = 0

        def stats(self):
            self.calls += 1
            if self.calls <= self.fail_times:
                raise master_mod.MasterTransportError("bounce")
            return {"pass_id": 3}

    w = ElasticWorker(
        _Bouncy(3), "w0", NumpyLinearModel(4),
        rpc_retry_window_s=60.0, clock=clk, sleep=lambda s: clk.advance(s),
    )
    assert w._rpc("stats") == {"pass_id": 3}  # rode through 3 failures

    w2 = ElasticWorker(
        _Bouncy(10 ** 6), "w0", NumpyLinearModel(4),
        rpc_retry_window_s=5.0, clock=clk, sleep=lambda s: clk.advance(s),
    )
    with pytest.raises(master_mod.MasterTransportError):
        w2._rpc("stats")  # bounded: gives up after the window


# ---------------------------------------------------------------------------
# failover-regression heal: unanimous attestation force-rotates a pass the
# whole fleet already applied on a deposed leader (ISSUE 15 split-brain)
# ---------------------------------------------------------------------------

def test_force_rotate_requires_unanimous_attestation(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk)
    svc.register_worker("w0")
    svc.register_worker("w1")
    # simulate the post-failover replica: pass 0 partially done, the
    # rest re-opened (their acks died with the deposed leader)
    got = svc.get_task("w0")
    svc.task_finished(got["task"]["task_id"], got["epoch"],
                      {"grads": 1.0, "cost": 1.0, "rows": 1}, 0)
    assert svc.pass_id == 0 and svc.todo  # undrained
    # one attestation proves nothing
    assert svc.start_new_pass(1, "w0") == 0
    assert svc.pass_id == 0
    # unanimity alone does not fire either: it must stay unanimous for a
    # full worker-timeout window (a briefly-pruned-but-alive worker gets
    # the chance to re-register and break it)
    assert svc.start_new_pass(1, "w1") == 0
    for _ in range(2):
        clk.advance(6.0)
        svc.heartbeat("w0")
        svc.heartbeat("w1")
    assert svc.start_new_pass(1, "w1") == 1
    assert svc.pass_id == 1
    # the whole queue recycled at epoch 0 for the next pass
    assert [t.task_id for t in svc.todo] == [0, 1, 2, 3]
    assert all(t.epoch == 0 for t in svc.todo)
    assert not svc.pending and not svc.done
    # the unfinishable pass's retained map is POISONED: a late joiner can
    # never replay it as complete (manifest fallback is its heal)
    pr = svc.pass_results(0)
    assert pr["results"] == {} and pr["n_done"] == -1


def test_force_rotate_never_fires_from_healthy_rotation_calls(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk)
    svc.register_worker("w0")
    svc.register_worker("w1")
    # drain pass 0 normally
    for w in ("w0", "w1"):
        while True:
            got = svc.get_task(w)
            if not isinstance(got, dict):
                break
            svc.task_finished(got["task"]["task_id"], got["epoch"],
                              {"grads": 1.0, "cost": 1.0, "rows": 1}, 0)
    # healthy release: the drained branch rotates, no attestation involved
    assert svc.start_new_pass(1, "w0") == 1
    # the straggler's idempotent call neither double-rotates nor votes
    assert svc.start_new_pass(1, "w1") == 1
    assert svc.pass_id == 1 and svc._repass_votes == {}
    # and pass 0's retained map stays REPLAYABLE (frozen-complete)
    pr = svc.pass_results(0)
    assert pr["n_done"] == 4 and len(pr["results"]) == 4


def test_force_rotate_replays_through_the_journal(tmp_path):
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, journal=True)
    svc.register_worker("w0")
    got = svc.get_task("w0")
    svc.task_finished(got["task"]["task_id"], got["epoch"],
                      {"grads": 1.0, "cost": 1.0, "rows": 1}, 0)
    assert svc.start_new_pass(2, "w0") == 0  # unanimity clock starts
    for _ in range(2):
        clk.advance(6.0)
        svc.heartbeat("w0")
    assert svc.start_new_pass(2, "w0") == 1  # stable: force-rotates
    # a replica recovering from snapshot+journal lands on the same state
    replica = master_mod.Service(
        snapshot_path=str(tmp_path / "snap.json"), clock=clk,
        auto_rotate=False, chunks_per_task=2,
    )
    assert replica.pass_id == 1
    assert [t.task_id for t in replica.todo] == [0, 1, 2, 3]
    assert replica.pass_results(0)["n_done"] == -1


def test_briefly_pruned_live_worker_breaks_attestation_unanimity(tmp_path):
    """The stability window's whole point: a worker silent just past the
    registry timeout (GC pause) is pruned — unanimity among the REST must
    not fire while it can still come back.  Its re-registration resets
    the unanimity clock."""
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, worker_timeout_s=4.0)
    svc.register_worker("w0")
    svc.register_worker("w1")
    got = svc.get_task("w1")  # w1 is mid-compute when the vote starts
    clk.advance(5.0)  # w1 goes silent past the registry lease: pruned
    assert svc.start_new_pass(1, "w0") == 0  # w0 alone IS unanimous now
    clk.advance(2.0)
    svc.heartbeat("w0")
    # w1 wakes up inside the stability window and re-registers
    svc.register_worker("w1")
    assert svc.start_new_pass(1, "w0") == 0
    clk.advance(3.0)  # past the original window — but unanimity was reset
    svc.heartbeat("w0")
    svc.heartbeat("w1")
    assert svc.start_new_pass(1, "w0") == 0  # w1 is live and not attesting
    assert svc.pass_id == 0 and svc._repass_unanimous_since is None
    # the prune walked w1's held lease through the failure path, so its
    # stale-epoch ack is a zombie — and the re-served task completes the
    # pass the LEGITIMATE way (normal lease discipline, no force)
    assert svc.task_finished(got["task"]["task_id"], got["epoch"],
                             {"grads": 1.0, "cost": 1.0, "rows": 1},
                             0) is False
    g2 = svc.get_task("w1")
    while g2["task"]["task_id"] != got["task"]["task_id"]:
        svc.task_finished(g2["task"]["task_id"], g2["epoch"],
                          {"grads": 1.0, "cost": 1.0, "rows": 1}, 0)
        g2 = svc.get_task("w1")
    assert g2["epoch"] == got["epoch"] + 1
    assert svc.task_finished(g2["task"]["task_id"], g2["epoch"],
                             {"grads": 1.0, "cost": 1.0, "rows": 1}, 0)


def test_restarted_worker_incarnation_drops_its_ghost_attestation(tmp_path):
    """Review regression: a worker that attested and then crashed must not
    leave a vote its RESTARTED incarnation (whose params may never have
    applied the attested pass) is bound by — the fresh registration drops
    the ghost vote and unanimity breaks."""
    clk = _FakeClock()
    svc = _make_service(tmp_path, clk, worker_timeout_s=4.0)
    svc.register_worker("w0")
    svc.register_worker("w1")
    assert svc.start_new_pass(1, "w0") == 0
    assert svc.start_new_pass(1, "w1") == 0  # unanimous; window starts
    clk.advance(5.0)  # w1 crashes (silent past the lease) mid-window
    svc.heartbeat("w0")
    svc.register_worker("w1")  # the supervisor's restart re-registers it
    clk.advance(2.0)
    svc.heartbeat("w0")
    svc.heartbeat("w1")
    # past the original stability window, still unanimous-looking ONLY if
    # the ghost vote survived — it must not have
    assert svc.start_new_pass(1, "w0") == 0
    assert svc.pass_id == 0
    assert "w1" not in svc._repass_votes
