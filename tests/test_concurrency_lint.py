"""Concurrency lint (analysis/concurrency_lint.py): the package gate —
paddle_tpu's own threaded planes produce zero C-findings after triage —
plus one firing mutation fixture per rule (the test_graph_lint.py
discipline: seed exactly the violation, assert exactly the rule)."""

import os
import textwrap

from paddle_tpu.analysis import format_diagnostics
from paddle_tpu.analysis.concurrency_lint import (
    lint_concurrency_file,
    lint_concurrency_package,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules(diags):
    return sorted(d.rule for d in diags)


def _lint_src(tmp_path, src, relname="mod.py"):
    p = tmp_path / relname
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return lint_concurrency_file(str(p), root=str(tmp_path))


# ---------------------------------------------------------------------------
# the repo gate: the shipped package is clean
# ---------------------------------------------------------------------------


def test_package_concurrency_lint_is_clean():
    diags = lint_concurrency_package()
    assert diags == [], format_diagnostics(diags)


# ---------------------------------------------------------------------------
# C301 mixed-guard write
# ---------------------------------------------------------------------------


def test_c301_write_outside_guarding_lock(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def push(self, x):
                with self._lock:
                    self.items.append(x)

            def reset(self):
                self.items = []          # C301: no lock
    """)
    assert rules(d) == ["C301"]
    assert "items" in d[0].message and d[0].line == 14


def test_c301_guarded_helper_via_call_site_propagation(tmp_path):
    # _drain is only called under the lock: analyzed as guarded, no C301
    d = _lint_src(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def push(self, x):
                with self._lock:
                    self.items.append(x)
                    self._drain()

            def _drain(self):
                self.items = []
    """)
    assert d == [], format_diagnostics(d)


def test_c301_init_writes_are_exempt(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []          # single-threaded by construction

            def push(self, x):
                with self._lock:
                    self.items.append(x)
    """)
    assert d == [], format_diagnostics(d)


def test_c301_module_global_written_without_module_lock(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        _lock = threading.Lock()
        _cache = None

        def load():
            global _cache
            with _lock:
                _cache = 1

        def clear():
            global _cache
            _cache = None            # C301: other writes hold _lock
    """)
    assert rules(d) == ["C301"]


# ---------------------------------------------------------------------------
# C302 unguarded read on a thread-entry path
# ---------------------------------------------------------------------------


def test_c302_thread_entry_reads_guarded_field_unlocked(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.jobs = []
                self._t = threading.Thread(target=self._run, daemon=True)

            def add(self, j):
                with self._lock:
                    self.jobs.append(j)

            def _run(self):
                while self.jobs:         # C302: unlocked read on the thread
                    pass
    """)
    assert rules(d) == ["C302"]
    assert "jobs" in d[0].message


def test_c302_locked_thread_read_is_clean(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.jobs = []
                self._t = threading.Thread(target=self._run, daemon=True)

            def add(self, j):
                with self._lock:
                    self.jobs.append(j)

            def _run(self):
                with self._lock:
                    n = len(self.jobs)   # locked: fine
                return n
    """)
    assert d == [], format_diagnostics(d)


def test_c302_nested_thread_body_closure(tmp_path):
    # the thread body is a nested def: it holds NOTHING even though the
    # spawning method might
    d = _lint_src(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = {}

            def set(self, k, v):
                with self._lock:
                    self.state[k] = v

            def snapshot_async(self):
                def run():
                    return dict(self.state)   # C302: fresh thread, no lock
                threading.Thread(target=run, daemon=True).start()
    """)
    assert rules(d) == ["C302"]


# ---------------------------------------------------------------------------
# C303 static lock-order inversion
# ---------------------------------------------------------------------------


def test_c303_abba_cycle_across_classes(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class A:
            def __init__(self, b):
                self._a_lock = threading.Lock()
                self.b = b

            def hit(self):
                with self._a_lock:
                    with self.b._b_lock:
                        pass

        class B:
            def __init__(self, a):
                self._b_lock = threading.Lock()
                self.a = a

            def hit(self):
                with self._b_lock:
                    with self.a._a_lock:
                        pass
    """)
    assert rules(d) == ["C303"]
    assert "_a_lock" in d[0].message and "_b_lock" in d[0].message


def test_c303_consistent_order_is_clean(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class A:
            def __init__(self, b):
                self._a_lock = threading.Lock()
                self.b = b

            def one(self):
                with self._a_lock:
                    with self.b._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self.b._b_lock:
                        pass

        class B:
            def __init__(self):
                self._b_lock = threading.Lock()
    """)
    assert d == [], format_diagnostics(d)


def test_c303_cycle_via_method_call_under_lock(tmp_path):
    # A holds its lock and CALLS into B, which locks then calls back into
    # a lock-acquiring A method — the interprocedural edge set closes
    d = _lint_src(tmp_path, """
        import threading

        class A:
            def __init__(self, b):
                self._a_lock = threading.Lock()
                self.b = b

            def outer(self):
                with self._a_lock:
                    self.b.poke()

            def reenter(self):
                with self._a_lock:
                    pass

        class B:
            def __init__(self, a):
                self._b_lock = threading.Lock()
                self.a = a

            def poke(self):
                with self._b_lock:
                    self.a.reenter()
    """)
    assert rules(d) == ["C303"]


def test_c303_reentrant_same_lock_is_not_a_cycle(tmp_path):
    # Service-style RLock: methods call each other, both take self._lock
    d = _lint_src(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.RLock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.incr()

            def incr(self):
                with self._lock:
                    self.n += 1
    """)
    assert d == [], format_diagnostics(d)


# ---------------------------------------------------------------------------
# C304 blocking call under a lock (+ the allowlist pragma)
# ---------------------------------------------------------------------------


def test_c304_fsync_under_lock(tmp_path):
    d = _lint_src(tmp_path, """
        import os
        import threading

        class J:
            def __init__(self):
                self._lock = threading.Lock()

            def append(self, f):
                with self._lock:
                    os.fsync(f.fileno())
    """)
    assert rules(d) == ["C304"]
    assert "os.fsync" in d[0].message


def test_c304_sleep_and_socket_under_lock(tmp_path):
    d = _lint_src(tmp_path, """
        import time
        import threading

        class C:
            def __init__(self, conn):
                self._lock = threading.Lock()
                self.conn = conn

            def call(self):
                with self._lock:
                    self.conn.send(b"x")
                    time.sleep(0.1)
                    return self.conn.recv()
    """)
    assert rules(d) == ["C304", "C304", "C304"]


def test_c304_pragma_with_justification_suppresses(tmp_path):
    d = _lint_src(tmp_path, """
        import os
        import threading

        class J:
            def __init__(self):
                self._lock = threading.Lock()

            def append(self, f):
                with self._lock:
                    os.fsync(f.fileno())  # lock: allow[C304] fsync-before-ack is the durability contract
    """)
    assert d == [], format_diagnostics(d)


def test_c300_pragma_without_justification_is_its_own_finding(tmp_path):
    d = _lint_src(tmp_path, """
        import os
        import threading

        class J:
            def __init__(self):
                self._lock = threading.Lock()

            def append(self, f):
                with self._lock:
                    os.fsync(f.fileno())  # lock: allow[C304]
    """)
    # the empty pragma is rejected AND does not suppress the hold
    assert rules(d) == ["C300", "C304"]


def test_c304_propagates_through_guarded_helper(tmp_path):
    # the blocking op sits in a private method ONLY called under the lock —
    # the entry-held propagation must still see the hold
    d = _lint_src(tmp_path, """
        import os
        import threading

        class J:
            def __init__(self):
                self._lock = threading.Lock()

            def publish(self, f):
                with self._lock:
                    self._write(f)

            def _write(self, f):
                os.fsync(f.fileno())
    """)
    assert rules(d) == ["C304"]


# ---------------------------------------------------------------------------
# C305 leaked thread / unbounded Event.wait loop
# ---------------------------------------------------------------------------


def test_c305_non_daemon_thread_without_join(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class R:
            def start(self):
                t = threading.Thread(target=self._run)
                t.start()

            def _run(self):
                pass
    """)
    assert rules(d) == ["C305"]


def test_c305_joined_or_daemon_threads_are_clean(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class R:
            def start(self):
                t = threading.Thread(target=self._run, daemon=True)
                t.start()
                u = threading.Thread(target=self._run)
                u.start()
                u.join()
                self._w = threading.Thread(target=self._run)
                self._w.start()

            def stop(self):
                self._w.join(timeout=5)

            def _run(self):
                pass
    """)
    assert d == [], format_diagnostics(d)


def test_c305_unbounded_event_wait_loop(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class R:
            def __init__(self):
                self._ev = threading.Event()
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while True:
                    self._ev.wait()      # C305: no timeout, stop can't land
    """)
    assert rules(d) == ["C305"]


def test_c305_timed_event_wait_loop_is_clean(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class R:
            def __init__(self):
                self._ev = threading.Event()
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while not self._ev.wait(0.5):
                    pass
    """)
    assert d == [], format_diagnostics(d)


# ---------------------------------------------------------------------------
# C306 time.sleep polling loop without an injectable clock
# ---------------------------------------------------------------------------


def test_c306_polling_loop_without_injectable_sleep(tmp_path):
    d = _lint_src(tmp_path, """
        import time

        class Poller:
            def __init__(self, path):
                self.path = path

            def wait_ready(self):
                while True:
                    time.sleep(0.1)      # C306
    """)
    assert rules(d) == ["C306"]


def test_c306_injectable_sleep_param_is_clean(tmp_path):
    # the LeaseFile discipline: sleep= in __init__ (or the function itself)
    d = _lint_src(tmp_path, """
        import time

        class Poller:
            def __init__(self, path, sleep=time.sleep):
                self.path = path
                self._sleep = sleep

            def wait_ready(self):
                while True:
                    self._sleep(0.1)

        def drive(deadline, sleep=time.sleep):
            while True:
                sleep(0.1)
    """)
    assert d == [], format_diagnostics(d)


def test_c306_single_sleep_outside_loop_is_clean(tmp_path):
    d = _lint_src(tmp_path, """
        import time

        def settle():
            time.sleep(0.2)   # one-shot settle, not a polling loop
    """)
    assert d == [], format_diagnostics(d)


# ---------------------------------------------------------------------------
# resolution details
# ---------------------------------------------------------------------------


def test_sanitizer_factory_locks_are_recognized(tmp_path):
    d = _lint_src(tmp_path, """
        from paddle_tpu.analysis.lock_sanitizer import make_lock

        class Q:
            def __init__(self):
                self._lock = make_lock("Q._lock")
                self.items = []

            def push(self, x):
                with self._lock:
                    self.items.append(x)

            def reset(self):
                self.items = []
    """)
    assert rules(d) == ["C301"]


def test_subscript_store_counts_as_field_write(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.table = {}

            def put(self, k, v):
                with self._lock:
                    self.table[k] = v

            def evict(self, k):
                del self.table[k]        # C301: unlocked delete
    """)
    assert rules(d) == ["C301"]


def test_c304_in_dynamic_dispatch_exempt_method_uses_lexical_held(tmp_path):
    # a no-visible-callsite private method is exempt from C301/C302 but its
    # LEXICAL holds still fire C304 — and must not crash the formatter
    d = _lint_src(tmp_path, """
        import os
        import threading

        class J:
            def __init__(self):
                self._lock = threading.Lock()

            def _apply_sync(self, f):
                with self._lock:
                    os.fsync(f.fileno())
    """)
    assert rules(d) == ["C304"]
    assert "_lock" in d[0].message


def test_c300_unused_pragma_is_reported(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1   # lock: allow[C304] nothing here blocks
    """)
    assert rules(d) == ["C300"]
    assert "unused" in d[0].message


def test_pragma_inside_string_literal_is_documentation(tmp_path):
    d = _lint_src(tmp_path, '''
        DOC = """annotate holds like this:
        os.fsync(f)  # lock: allow[C304] fsync-before-ack is the contract
        """
        HINT = "# lock: allow[C304] <why>"
    ''')
    assert d == [], format_diagnostics(d)


def test_lambda_body_is_not_analyzed_at_definition_site(tmp_path):
    # a deferred callback must not fire C302 where it is DEFINED
    d = _lint_src(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._run, daemon=True)

            def bump(self):
                with self._lock:
                    self.count += 1

            def _run(self):
                cb = lambda: self.count   # deferred: runs elsewhere
                return cb
    """)
    assert d == [], format_diagnostics(d)


def test_c305_in_nested_def_reports_once(tmp_path):
    d = _lint_src(tmp_path, """
        import threading

        class R:
            def kick(self):
                def go():
                    t = threading.Thread(target=print)
                    t.start()
                go()
    """)
    assert rules(d) == ["C305"]
